"""rpc_press — protobuf-less load generator
(reference tools/rpc_press/rpc_press_impl.cpp: sends sample requests from
JSON at a target qps, reports qps + latency percentiles).

Unary example:
  python -m brpc_tpu.tools.rpc_press --server 127.0.0.1:8000 \
      --service EchoService --method Echo --input '{"msg":"hi"}' \
      --qps 5000 --duration 10 --threads 8

Streaming mode (--streaming) drives a method that streams items back
over the credit-windowed stream layer (e.g. Serving.Generate): each
worker attaches a client stream per call, counts delivered items, and
reports items/s plus time-to-first-item percentiles — the serving-path
analog of unary qps/latency.

Prefix-skewed load (--shared-prefix-ratio R): each call's "prompt"
field is regenerated — with probability R it opens with ONE fixed
shared prefix (--prefix-tokens long) followed by a random suffix,
otherwise it is fully random.  R=0.9 models a shared-system-prompt
workload and drives the paged KV cache's radix hit-rate (watch
/kvcache while pressing); R=0 is the worst case for prefix reuse.
The schedule is seeded per worker, so runs replay.

Trace dumping (--dump-traces N): rpcz is enabled in the press process
and every call runs under a client root span, so each press call is
one trace; after the run the N SLOWEST traces print as tree-ordered
indented timelines (relative offsets, annotations).  Against an
in-process or rpcz-enabled server the timelines include the server-side
stage spans — the fastest way from "it's slow" to WHICH stage is slow.

Hotspot attribution (--hotspots N, ISSUE 6): while the press runs, the
SERVER's /hotspots console is asked for a stage-tagged burst profile
covering the press duration, and the top-N folded stacks print
alongside the latency report — load test and CPU attribution in one
command ("it's slow" -> "decode_step is 60% lock-wait" without a
second tool).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import brpc_tpu as brpc
from brpc_tpu import errors, rpcz
from brpc_tpu.bvar import LatencyRecorder


def dump_slowest_traces(n: int, trace_ids=None, out=sys.stderr) -> None:
    """Print the n slowest collected traces as indented timelines
    (--dump-traces).  ``trace_ids`` restricts ranking to THIS run's
    traces — the shared in-process span store may hold unrelated
    history (a co-located server's own traffic)."""
    spans = rpcz.recent_spans(limit=2048)
    if trace_ids is not None:
        spans = [s for s in spans if s.trace_id in trace_ids]
    groups = rpcz.slowest_traces(spans, n)
    if not groups:
        print("no traces collected (is rpcz enabled?)", file=out)
        return
    print(f"--- {len(groups)} slowest traces ---", file=out)
    for group in groups:
        print(rpcz.format_trace(group), end="", file=out)


class HotspotFetcher:
    """Background fetch of the target server's stage-tagged burst
    profile (``/hotspots?seconds=N&fmt=collapsed``) for the press
    window; ``report(top_n)`` prints the hottest folded stacks."""

    def __init__(self, server: str, seconds: float):
        self.server = server
        self.seconds = max(0.2, min(60.0, seconds))
        self.folded: str | None = None
        self.error: str | None = None
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> "HotspotFetcher":
        self._thread.start()
        return self

    def _run(self) -> None:
        import http.client
        host, _, port = self.server.rpartition(":")
        try:
            c = http.client.HTTPConnection(host or "127.0.0.1", int(port),
                                           timeout=self.seconds + 60)
            c.request("GET", f"/hotspots?seconds={self.seconds}"
                             f"&fmt=collapsed")
            r = c.getresponse()
            body = r.read().decode("utf-8", "replace")
            c.close()
            if r.status != 200:
                self.error = f"/hotspots returned {r.status}"
            else:
                self.folded = body
        except Exception as e:
            self.error = f"{type(e).__name__}: {e}"

    def report(self, top_n: int, out=sys.stderr) -> None:
        self._thread.join(self.seconds + 90)
        if self.folded is None:
            print(f"(no server hotspot profile: "
                  f"{self.error or 'fetch still pending'})", file=out)
            return
        rows = []
        for line in self.folded.splitlines():
            stack, _, n = line.rpartition(" ")
            if stack and n.isdigit():
                rows.append((int(n), stack))
        rows.sort(reverse=True)
        total = sum(n for n, _ in rows) or 1
        print(f"--- server hotspots during press "
              f"({self.seconds:g}s burst @100Hz, {total} samples; "
              f"top {min(top_n, len(rows))} stage-tagged stacks) ---",
              file=out)
        for n, stack in rows[:top_n]:
            print(f"  [{n:>5} samples {100.0 * n / total:>5.1f}%] "
                  f"{stack}", file=out)


def make_prefix_skew(request, ratio: float, prefix_tokens: int = 32,
                     suffix_tokens: int = 8, vocab: int = 1000,
                     seed: int = 0):
    """Per-worker request factory for prefix-skewed generate load: with
    probability `ratio` the "prompt" opens with one fixed shared prefix
    (the page-aligned unit the KV radix tree caches), else it is fully
    random.  ``make_prefix_skew(...)(k)`` returns worker k's factory —
    each worker gets its own seeded rng so the schedule replays."""
    import random as _random
    shared = [(seed * 1009 + i * 37) % vocab for i in range(prefix_tokens)]

    def for_worker(k: int):
        rng = _random.Random((seed << 16) ^ k)

        def next_request():
            req = dict(request)
            suffix = [rng.randrange(vocab) for _ in range(suffix_tokens)]
            if rng.random() < ratio:
                req["prompt"] = shared + suffix
            else:
                req["prompt"] = [rng.randrange(vocab) for _ in
                                 range(prefix_tokens)] + suffix
            return req

        return next_request

    return for_worker


def run_press(server: str, service: str, method: str, request,
              qps: int = 0, duration_s: float = 10.0, threads: int = 4,
              serializer: str = "json", timeout_ms: int = 1000,
              connection_type: str = "single", request_factory=None,
              dump_traces: int = 0, hotspots: int = 0,
              out=sys.stderr) -> dict:
    """Drives the load; returns a summary dict (also printable).
    ``request_factory(k)`` (e.g. ``make_prefix_skew(...)``), when
    given, builds worker k's per-call request generator.
    ``dump_traces=N`` enables rpcz for the run (each call becomes one
    trace rooted at a press client span) and prints the N slowest
    traces as indented timelines afterwards.  ``hotspots=N`` runs the
    server-side burst profiler for the press duration and prints the
    top-N stage-tagged folded stacks alongside the latency report."""
    traced = dump_traces > 0
    rpcz_state = (rpcz.enabled(), rpcz.sample_rate())
    if traced:
        rpcz.set_enabled(True)
    try:
        return _run_press_body(server, service, method, request, qps,
                               duration_s, threads, serializer,
                               timeout_ms, connection_type,
                               request_factory, dump_traces, traced,
                               hotspots, out)
    finally:
        # restore BOTH knobs, even on a mid-run exception: a press must
        # not leave a co-located server force-traced at rate 1.0
        if traced:
            rpcz.set_enabled(*rpcz_state)


def _run_press_body(server, service, method, request, qps, duration_s,
                    threads, serializer, timeout_ms, connection_type,
                    request_factory, dump_traces, traced, hotspots,
                    out) -> dict:
    ch = brpc.Channel(server, timeout_ms=timeout_ms,
                      connection_type=connection_type)
    fetcher = HotspotFetcher(server, duration_s).start() \
        if hotspots > 0 else None
    rec = LatencyRecorder("rpc_press")
    # python-side latency reservoir: the native recorder pool is 512
    # slots process-wide, and deep in a churn-heavy suite a freshly
    # created recorder can transiently miss a slot (GC lag holds
    # freed-but-uncollected recorders' slots) — its percentiles then
    # read 0 despite real traffic.  The press must report honest
    # latency regardless, so it keeps a bounded sample of its own.
    lats: list = []          # GIL-atomic appends; bounded below
    _LATS_CAP = 200_000
    nerr = [0]
    nok = [0]
    press_tids: list = []   # this run's trace ids (GIL-atomic appends)
    stop = threading.Event()
    # per-thread qps budget; qps<=0 = unthrottled
    per_thread_interval = threads / qps if qps > 0 else 0.0

    def worker(k: int):
        gen = request_factory(k) if request_factory is not None else None
        next_at = time.monotonic()
        while not stop.is_set():
            if per_thread_interval > 0:
                now = time.monotonic()
                if now < next_at:
                    time.sleep(min(next_at - now, 0.05))
                    continue
                next_at += per_thread_interval
            req = gen() if gen is not None else request
            span = rpcz.new_span("client", service, method) if traced \
                else rpcz.NULL_SPAN
            if span is not rpcz.NULL_SPAN:
                span.remote_side = server
                press_tids.append(span.trace_id)
                rpcz.set_current_span(span)
            t0 = time.monotonic()
            try:
                ch.call_sync(service, method, req,
                             serializer=serializer)
                dt_us = int((time.monotonic() - t0) * 1e6)
                rec.add(dt_us)
                if len(lats) < _LATS_CAP:
                    lats.append(dt_us)
                nok[0] += 1
            except Exception as e:
                nerr[0] += 1
                span.error_code = getattr(e, "code", -1) or -1
            finally:
                if span is not rpcz.NULL_SPAN:
                    rpcz.set_current_span(None)
                    rpcz.submit(span)

    ts = [threading.Thread(target=worker, args=(k,), daemon=True)
          for k in range(threads)]
    t_start = time.monotonic()
    [t.start() for t in ts]
    try:
        time.sleep(duration_s)
    finally:
        stop.set()
    [t.join(2) for t in ts]
    elapsed = time.monotonic() - t_start
    srt = sorted(lats)

    def pctl(p: float) -> float:
        v = rec.latency_percentile(p)
        if v <= 0 and srt:
            # native recorder never got a slot: serve the percentile
            # from the press's own reservoir
            v = float(srt[min(len(srt) - 1, int(p * len(srt)))])
        return v

    avg = rec.latency()
    if avg <= 0 and srt:
        avg = sum(srt) / len(srt)
    mx = rec.max_latency()
    if mx <= 0 and srt:
        mx = srt[-1]
    summary = {
        "sent_ok": nok[0],
        "errors": nerr[0],
        "qps": round(nok[0] / elapsed, 1),
        "avg_us": round(avg, 1),
        "p50_us": pctl(0.5),
        "p90_us": pctl(0.9),
        "p99_us": pctl(0.99),
        "p999_us": pctl(0.999),
        "max_us": mx,
        "elapsed_s": round(elapsed, 2),
    }
    print(json.dumps(summary), file=out)
    if fetcher is not None:
        fetcher.report(hotspots, out=out)
    if traced:
        dump_slowest_traces(dump_traces, trace_ids=set(press_tids),
                            out=out)
    return summary


class _PressStreamHandler(brpc.StreamHandler):
    """Counts delivered items, stamps the first one, latches close."""

    def __init__(self):
        self.items = 0
        self.first_at = None
        self.closed = threading.Event()

    def on_received_messages(self, stream, messages):
        if self.first_at is None:
            self.first_at = time.monotonic()
        self.items += len(messages)

    def on_closed(self, stream):
        self.closed.set()


def run_streaming_press(server: str, service: str, method: str, request,
                        duration_s: float = 10.0, threads: int = 4,
                        serializer: str = "json", timeout_ms: int = 5000,
                        connection_type: str = "single",
                        request_factory=None, dump_traces: int = 0,
                        hotspots: int = 0, out=sys.stderr) -> dict:
    """Streaming load: one client stream per call, looped per worker for
    `duration_s`.  Reports aggregate items/s and time-to-first-item
    (TTFI) percentiles; a stream that never closes within the timeout
    counts as an error.  ``dump_traces=N`` prints the N slowest traces
    afterwards (each stream call is one trace)."""
    traced = dump_traces > 0
    rpcz_state = (rpcz.enabled(), rpcz.sample_rate())
    if traced:
        rpcz.set_enabled(True)
    try:
        return _run_streaming_body(server, service, method, request,
                                   duration_s, threads, serializer,
                                   timeout_ms, connection_type,
                                   request_factory, dump_traces, traced,
                                   hotspots, out)
    finally:
        if traced:
            rpcz.set_enabled(*rpcz_state)


def _run_streaming_body(server, service, method, request, duration_s,
                        threads, serializer, timeout_ms, connection_type,
                        request_factory, dump_traces, traced, hotspots,
                        out) -> dict:
    ch = brpc.Channel(server, timeout_ms=timeout_ms,
                      connection_type=connection_type)
    fetcher = HotspotFetcher(server, duration_s).start() \
        if hotspots > 0 else None
    ttfi = LatencyRecorder("rpc_press_ttfi")
    items = [0]
    streams_ok = [0]
    nerr = [0]
    press_tids: list = []
    mu = threading.Lock()
    stop = threading.Event()

    def worker(k: int):
        gen = request_factory(k) if request_factory is not None else None
        while not stop.is_set():
            h = _PressStreamHandler()
            cntl = brpc.Controller()
            stream = brpc.stream_create(cntl, h)
            req = gen() if gen is not None else request
            span = rpcz.new_span("client", service, method) if traced \
                else rpcz.NULL_SPAN
            if span is not rpcz.NULL_SPAN:
                span.remote_side = server
                press_tids.append(span.trace_id)
                rpcz.set_current_span(span)
            t0 = time.monotonic()
            try:
                ch.call_sync(service, method, req,
                             serializer=serializer, cntl=cntl)
            except Exception as e:
                with mu:
                    nerr[0] += 1
                span.error_code = getattr(e, "code", -1) or -1
                if span is not rpcz.NULL_SPAN:
                    rpcz.set_current_span(None)
                    rpcz.submit(span)
                stream.close()
                continue
            finally:
                if span is not rpcz.NULL_SPAN:
                    rpcz.set_current_span(None)
            ok = h.closed.wait(timeout_ms / 1e3)
            if span is not rpcz.NULL_SPAN:
                span.annotate(f"stream closed: items={h.items} ok={ok}")
                rpcz.submit(span)
            with mu:
                if ok:
                    streams_ok[0] += 1
                    items[0] += h.items
                    if h.first_at is not None:
                        ttfi.add(int((h.first_at - t0) * 1e6))
                else:
                    nerr[0] += 1
            if not ok:
                stream.close()

    ts = [threading.Thread(target=worker, args=(k,), daemon=True)
          for k in range(threads)]
    t_start = time.monotonic()
    [t.start() for t in ts]
    try:
        time.sleep(duration_s)
    finally:
        stop.set()
    [t.join(timeout_ms / 1e3 + 2) for t in ts]
    elapsed = time.monotonic() - t_start
    summary = {
        "streams_ok": streams_ok[0],
        "errors": nerr[0],
        "items": items[0],
        "items_per_s": round(items[0] / elapsed, 1),
        "ttfi_avg_us": round(ttfi.latency(), 1),
        "ttfi_p50_us": ttfi.latency_percentile(0.5),
        "ttfi_p90_us": ttfi.latency_percentile(0.9),
        "ttfi_p99_us": ttfi.latency_percentile(0.99),
        "elapsed_s": round(elapsed, 2),
    }
    print(json.dumps(summary), file=out)
    if fetcher is not None:
        fetcher.report(hotspots, out=out)
    if traced:
        dump_slowest_traces(dump_traces, trace_ids=set(press_tids),
                            out=out)
    return summary


def run_disagg_press(prefill_addr: str, decode_addr: str, request,
                     duration_s: float = 10.0, threads: int = 4,
                     timeout_ms: int = 20_000, request_factory=None,
                     out=sys.stderr) -> dict:
    """``--disagg`` mode: drive full generations through the SPLIT
    topology — each call runs Prefill on the prefill process (whose
    finished pages stream to the decode store over the ``_kvmig``
    plane) and then streams tokens from the decode process — so heavy
    traffic exercises the page stream under load.  Reports
    generations/s, tokens/s, time-to-first-token percentiles, and how
    many prefills fell back to recompute (failed migrations)."""
    from brpc_tpu.migrate import DisaggCoordinator
    rec_ttft = LatencyRecorder("rpc_press_disagg_ttft")
    mu = threading.Lock()
    gens_ok = [0]
    nerr = [0]
    tokens = [0]
    fallbacks = [0]
    stop = threading.Event()

    def worker(k: int):
        # one coordinator (its own channel pair) per worker: the page
        # stream and the token stream both scale with concurrency
        co = DisaggCoordinator(prefill_addr, decode_addr,
                               timeout_ms=timeout_ms)
        gen = request_factory(k) if request_factory is not None else None
        while not stop.is_set():
            req = gen() if gen is not None else request
            prompt = req.get("prompt") or [1]
            n = int(req.get("max_new_tokens", 16))
            first = [None]

            def emit(tok, first=first):
                if first[0] is None:
                    first[0] = time.monotonic()

            t0 = time.monotonic()
            try:
                res = co.generate(prompt, n, emit=emit,
                                  timeout_s=timeout_ms / 1e3)
            except Exception:
                with mu:
                    nerr[0] += 1
                continue
            with mu:
                if res["error"]:
                    nerr[0] += 1
                    continue
                gens_ok[0] += 1
                tokens[0] += len(res["tokens"])
                if res["prefill"].get("recompute_fallback"):
                    fallbacks[0] += 1
            if first[0] is not None:
                rec_ttft.add(int((first[0] - t0) * 1e6))

    ts = [threading.Thread(target=worker, args=(k,), daemon=True)
          for k in range(threads)]
    t_start = time.monotonic()
    [t.start() for t in ts]
    try:
        time.sleep(duration_s)
    finally:
        stop.set()
    [t.join(timeout_ms / 1e3 + 2) for t in ts]
    elapsed = time.monotonic() - t_start
    summary = {
        "generations_ok": gens_ok[0],
        "errors": nerr[0],
        "tokens": tokens[0],
        "generations_per_s": round(gens_ok[0] / elapsed, 1),
        "tokens_per_s": round(tokens[0] / elapsed, 1),
        "recompute_fallbacks": fallbacks[0],
        "ttft_avg_us": round(rec_ttft.latency(), 1),
        "ttft_p50_us": rec_ttft.latency_percentile(0.5),
        "ttft_p99_us": rec_ttft.latency_percentile(0.99),
        "elapsed_s": round(elapsed, 2),
    }
    print(json.dumps(summary), file=out)
    return summary


def spin_up_cluster(n_replicas: int, *, page_tokens: int = 8,
                    step_delay_s: float = 0.0, num_slots: int = 8,
                    max_blocks: int = 64, page_bytes: int = 512,
                    max_pages_per_slot: int = 64,
                    name_prefix: str = "cluster",
                    commit_live_pages: bool = False,
                    replicate_sessions: bool = False,
                    max_sessions: int = 256,
                    timeout_ms: int = 20_000):
    """Build an in-process cluster: N serving replicas (paged KV store +
    decode engine + server with the Serving and ``_kvmig`` services)
    behind a :class:`~brpc_tpu.serving.ClusterRouter` exposed on its own
    router server.  The step function is plain numpy (CPU-valid), each
    step optionally sleeping ``step_delay_s`` so generations are
    decode-bound.  Shared by ``--cluster`` press mode and ``bench.py
    cluster`` (which differ only in knobs: the press turns on
    ``commit_live_pages``/``replicate_sessions`` to exercise resume
    under a replica kill; the bench leaves replication off so the
    router-overhead number isn't polluted by page shipping).

    Returns ``(replicas, router, rsrv, raddr)`` with ``replicas`` a
    list of ``(store, engine, server, addr)``; tear down with
    :func:`tear_down_cluster`."""
    from brpc_tpu.serving import (ClusterRouter, ReplicaHandle,
                                  register_router)

    replicas = spin_up_replicas(
        n_replicas, page_tokens=page_tokens, step_delay_s=step_delay_s,
        num_slots=num_slots, max_blocks=max_blocks,
        page_bytes=page_bytes, max_pages_per_slot=max_pages_per_slot,
        name_prefix=name_prefix, commit_live_pages=commit_live_pages)
    router = ClusterRouter(
        [ReplicaHandle(addr, name=f"{name_prefix}_{i}", engine=eng,
                       store=store, server=srv)
         for i, (store, eng, srv, addr) in enumerate(replicas)],
        page_tokens=page_tokens, replicate_sessions=replicate_sessions,
        max_sessions=max_sessions, name=f"{name_prefix}_router",
        timeout_ms=timeout_ms)
    rsrv = brpc.Server()
    register_router(rsrv, router)
    rsrv.start("127.0.0.1", 0)
    return replicas, router, rsrv, f"127.0.0.1:{rsrv.port}"


def spin_up_replicas(n_replicas: int, *, page_tokens: int = 8,
                     step_delay_s: float = 0.0, num_slots: int = 8,
                     max_blocks: int = 64, page_bytes: int = 512,
                     max_pages_per_slot: int = 64,
                     name_prefix: str = "cluster",
                     commit_live_pages: bool = False,
                     prefill_cost_per_token_s: float = 0.0):
    """The replica half of :func:`spin_up_cluster`: N serving replicas
    (paged KV store + decode engine) each exposing the Serving,
    ``_kvmig`` AND ``_cluster`` services — so they work behind an
    in-process router (ISSUE 8 shape) or a remote-only SUBPROCESS
    router (ISSUE 16: address-only handles, floor pushes over the
    wire, prefix pulls between replicas).

    ``prefill_cost_per_token_s`` adds a prefill stage whose cost
    scales with the (bucket-padded) UNCACHED suffix — the real-model
    cost shape where a prefix-cache hit buys skipped compute, so
    benches measuring warmth effects (``bench.py durable``) see them
    at true proportions instead of one flat-priced vectorized call.

    Returns a list of ``(store, engine, server, addr)``; tear down
    with :func:`tear_down_replicas`."""
    import numpy as np

    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.migrate import make_prefix_fetcher, register_migration
    from brpc_tpu.serving import (DecodeEngine, register_cluster_control,
                                  register_serving, register_telemetry)

    def step(tokens, positions, pages=None):
        if step_delay_s:
            time.sleep(step_delay_s)
        return (np.asarray(tokens) * 7 + np.asarray(positions)) % 997

    prefill_fn = None
    if prefill_cost_per_token_s:
        def prefill_fn(tokens, prefill_from):
            time.sleep(prefill_cost_per_token_s * int(np.size(tokens)))

    replicas = []
    for i in range(n_replicas):
        store = KVCacheStore(page_tokens=page_tokens,
                             page_bytes=page_bytes,
                             max_blocks=max_blocks,
                             name=f"{name_prefix}_{i}",
                             commit_live_pages=commit_live_pages)
        eng = DecodeEngine(step, num_slots=num_slots, store=store,
                           max_pages_per_slot=max_pages_per_slot,
                           prefill_fn=prefill_fn,
                           name=f"{name_prefix}_eng_{i}")
        srv = brpc.Server(enable_dcn=True)
        serving_svc = register_serving(srv, engine=eng)
        mig_svc = register_migration(srv, store)
        register_cluster_control(srv, engine=eng, store=store,
                                 name=f"{name_prefix}_{i}")
        register_telemetry(srv, name=f"{name_prefix}_{i}")
        srv.start("127.0.0.1", 0)
        addr = f"127.0.0.1:{srv.port}"
        # the fetcher needs the replica's own addr, known only now
        serving_svc.prefix_fetcher = make_prefix_fetcher(
            mig_svc.migrator, addr)
        replicas.append((store, eng, srv, addr))
    return replicas


def tear_down_replicas(replicas) -> None:
    """Close what :func:`spin_up_replicas` built (replicas already
    killed mid-run tear down quietly)."""
    for store, eng, srv, _addr in replicas:
        try:
            eng.close(timeout_s=2.0)
        except Exception:
            pass
        try:
            srv.stop()
            srv.join()
        except Exception:
            pass
        store.clear()
        store.close()


def tear_down_cluster(replicas, router, rsrv,
                      timeout_s: float = 3.0) -> None:
    """Close everything :func:`spin_up_cluster` built (replicas that
    were already killed mid-run tear down quietly)."""
    router.close(timeout_s=timeout_s)
    rsrv.stop()
    rsrv.join()
    tear_down_replicas(replicas)


# ---------------------------------------------------------------------------
# multi-model fleets (ISSUE 18)
# ---------------------------------------------------------------------------

# per-model step-function multipliers: model i's decode rule is
# (t * PRIME_i + pos) % 997, so every model's token stream is
# distinguishable from every other's — a generation that bit-matches
# the WRONG model's oracle is a mis-route, caught client-side
MODEL_STEP_PRIMES = (7, 11, 13, 17, 19, 23, 29)


def model_step_fn(mult: int, step_delay_s=0.0):
    """The numpy step function for one model deployment (CPU-valid).
    ``step_delay_s`` may be a float or a zero-arg callable evaluated
    per step — the knob the SLO rollback test turns mid-run to make
    ONE version's ITL burn while its tokens stay bit-exact."""
    import numpy as np

    def step(tokens, positions, pages=None):
        d = step_delay_s() if callable(step_delay_s) else step_delay_s
        if d:
            time.sleep(d)
        return (np.asarray(tokens) * int(mult)
                + np.asarray(positions)) % 997

    return step


def expected_model_tokens(prompt, n: int, mult: int = 7) -> list:
    """The bit-exact oracle for :func:`model_step_fn`: the n tokens a
    correct generation of ``prompt`` emits under multiplier ``mult``."""
    out = []
    last = int(prompt[-1])
    pos = len(prompt)
    for _ in range(int(n)):
        last = (last * int(mult) + pos) % 997
        out.append(last)
        pos += 1
    return out


def spin_up_multimodel_replicas(n_replicas: int, models, *, layout=None,
                                page_tokens: int = 8,
                                step_delay_s=0.0,
                                num_slots: int = 8, max_blocks: int = 64,
                                page_bytes: int = 512,
                                max_pages_per_slot: int = 64,
                                name_prefix: str = "mm",
                                commit_live_pages: bool = False,
                                warm: bool = True):
    """N serving replicas, each carrying one :class:`~brpc_tpu.serving.
    ReplicaDeployments` table over the given ``models`` (ISSUE 18):
    per-deployment store + engine (model i's step rule uses
    ``MODEL_STEP_PRIMES[i]``, so streams are model-attributable), the
    Serving service resolving the forwarded ``model`` field, the
    ``_cluster`` service publishing the catalog, and ``_kvmig`` bound
    to the FIRST deployment's store, model-tagged (a mismatched fetch
    is refused; other models fall back to recompute — fetch is an
    optimization, never a correctness dependency).

    ``layout[i]`` restricts replica i to a subset of ``models``
    (default: every replica serves all of them) — the knob chaos
    scenario 19 uses to build a fleet where exactly one replica is
    warm for model B.  ``warm=False`` starts deployments ``loading``
    (the first completed generation flips them warm).

    Returns ``(replicas, mults)``: ``replicas`` a list of dicts with
    keys ``deps``/``stores``/``engines``/``server``/``addr``/
    ``models``, ``mults`` the ``model -> multiplier`` oracle map.
    Tear down with :func:`tear_down_multimodel_replicas`."""
    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.migrate import make_prefix_fetcher, register_migration
    from brpc_tpu.serving import (DecodeEngine, ReplicaDeployments,
                                  register_cluster_control,
                                  register_serving, register_telemetry)
    from brpc_tpu.serving.modelplane import LOADING, WARM

    models = [str(m) for m in models]
    mults = {m: MODEL_STEP_PRIMES[i % len(MODEL_STEP_PRIMES)]
             for i, m in enumerate(models)}
    state0 = WARM if warm else LOADING
    replicas = []
    for i in range(n_replicas):
        served = models if layout is None \
            else [str(m) for m in layout[i]]
        deps = ReplicaDeployments(name=f"{name_prefix}_{i}")
        stores, engines = {}, {}
        srv = brpc.Server(enable_dcn=True)
        for m in served:
            store = KVCacheStore(page_tokens=page_tokens,
                                 page_bytes=page_bytes,
                                 max_blocks=max_blocks,
                                 name=f"{name_prefix}_{i}_{m}",
                                 commit_live_pages=commit_live_pages)
            # step_delay_s: scalar/callable for the whole fleet, or a
            # dict keyed by deployment key — per-VERSION latency
            # injection (the SLO rollback test slows only the canary)
            delay = step_delay_s.get(m, 0.0) \
                if isinstance(step_delay_s, dict) else step_delay_s
            eng = DecodeEngine(model_step_fn(mults[m], delay),
                               num_slots=num_slots, store=store,
                               max_pages_per_slot=max_pages_per_slot,
                               name=f"{name_prefix}_eng_{i}_{m}")
            stores[m], engines[m] = store, eng
            deps.deploy(m, engine=eng, store=store, state=state0)
        m0 = served[0] if served else None
        serving_svc = register_serving(
            srv, engine=engines.get(m0), deployments=deps)
        mig_svc = register_migration(srv, stores[m0], model=m0) \
            if m0 else None
        register_cluster_control(srv, engine=engines.get(m0),
                                 store=stores.get(m0),
                                 name=f"{name_prefix}_{i}",
                                 deployments=deps)
        register_telemetry(srv, name=f"{name_prefix}_{i}")
        srv.start("127.0.0.1", 0)
        addr = f"127.0.0.1:{srv.port}"
        if mig_svc is not None:
            # fetcher ONLY on the _kvmig-bound deployment: a shared
            # svc-level fetcher would splice other models' fetches
            # into m0's store
            deps.deploy(m0, prefix_fetcher=make_prefix_fetcher(
                mig_svc.migrator, addr, model=m0), state=state0)
        replicas.append({"deps": deps, "stores": stores,
                         "engines": engines, "server": srv,
                         "addr": addr, "models": list(served),
                         "serving": serving_svc})
    return replicas, mults


def tear_down_multimodel_replicas(replicas) -> None:
    for r in replicas:
        for eng in r["engines"].values():
            try:
                eng.close(timeout_s=2.0)
            except Exception:
                pass
        try:
            r["server"].stop()
            r["server"].join()
        except Exception:
            pass
        for store in r["stores"].values():
            store.clear()
            store.close()


def spin_up_multimodel_cluster(n_replicas: int, models, *, layout=None,
                               page_tokens: int = 8,
                               step_delay_s=0.0,
                               commit_live_pages: bool = False,
                               replicate_sessions: bool = False,
                               max_sessions: int = 256,
                               timeout_ms: int = 20_000,
                               name_prefix: str = "mm", warm: bool = True,
                               wal=None, router_kw=None, **replica_kw):
    """A multi-model fleet behind one :class:`~brpc_tpu.serving.
    ClusterRouter` front door: :func:`spin_up_multimodel_replicas` plus
    a router whose handles carry the deployment tables (the catalog
    seeds instantly; remote publication keeps it fresh).  Returns
    ``(replicas, mults, router, rsrv, raddr)``; tear down with
    :func:`tear_down_multimodel_cluster`."""
    from brpc_tpu.serving import (ClusterRouter, ReplicaHandle,
                                  register_router)

    replicas, mults = spin_up_multimodel_replicas(
        n_replicas, models, layout=layout, page_tokens=page_tokens,
        step_delay_s=step_delay_s, commit_live_pages=commit_live_pages,
        name_prefix=name_prefix, warm=warm, **replica_kw)
    handles = []
    for i, r in enumerate(replicas):
        m0 = r["models"][0] if r["models"] else None
        handles.append(ReplicaHandle(
            r["addr"], name=f"{name_prefix}_{i}",
            engine=r["engines"].get(m0), store=r["stores"].get(m0),
            server=r["server"], deployments=r["deps"]))
    kw = dict(router_kw or {})
    if wal is not None:
        kw["wal"] = wal
    router = ClusterRouter(
        handles, page_tokens=page_tokens,
        replicate_sessions=replicate_sessions,
        max_sessions=max_sessions, name=f"{name_prefix}_router",
        timeout_ms=timeout_ms, **kw)
    rsrv = brpc.Server()
    register_router(rsrv, router)
    rsrv.start("127.0.0.1", 0)
    return replicas, mults, router, rsrv, f"127.0.0.1:{rsrv.port}"


def tear_down_multimodel_cluster(replicas, router, rsrv,
                                 timeout_s: float = 3.0) -> None:
    router.close(timeout_s=timeout_s)
    rsrv.stop()
    rsrv.join()
    tear_down_multimodel_replicas(replicas)


def zipf_key_sampler(vocab: int, s: float, seed: int = 0):
    """Seeded zipf-skewed key sampler: key k's probability is
    proportional to 1/(rank+1)^s under a seeded permutation (so hot
    keys spread across shard ranges instead of piling on shard 0).
    s=0 is uniform; s~1 is classic web skew."""
    import numpy as np
    rng = np.random.default_rng(seed)
    ranks = rng.permutation(vocab)
    p = 1.0 / np.power(np.arange(vocab, dtype=np.float64) + 1.0,
                       max(float(s), 0.0))
    p /= p.sum()
    probs = np.empty(vocab)
    probs[ranks] = p

    def sample(n: int) -> np.ndarray:
        return rng.choice(vocab, size=n, p=probs).astype(np.int64)

    return sample


def spin_up_psserve(n_shards: int, *, vocab: int = 1024, dim: int = 32,
                    max_delay_us: int = 1000, name_prefix: str = "press"):
    """In-process sharded parameter-server fleet + a PartitionChannel
    over it (shared by --embedding mode and bench.py embedding)."""
    from brpc_tpu.psserve import EmbeddingShardServer, register_psserve
    from brpc_tpu.rpc.combo_channels import PartitionChannel
    from brpc_tpu.serving.telemetry import register_telemetry

    servers, svcs, shards = [], [], []
    pc = PartitionChannel(n_shards)
    for i in range(n_shards):
        sh = EmbeddingShardServer(i, n_shards, vocab, dim, seed=0,
                                  name=f"{name_prefix}_ps")
        shards.append(sh)
        s = brpc.Server()
        svcs.append(register_psserve(s, sh, max_delay_us=max_delay_us,
                                     name=f"{name_prefix}_{i}"))
        register_telemetry(s, name=f"{name_prefix}_ps_{i}")
        s.start("127.0.0.1", 0)
        servers.append(s)
        pc.add_partition(i, brpc.Channel(f"127.0.0.1:{s.port}",
                                         timeout_ms=10_000))
    return servers, svcs, shards, pc


def tear_down_psserve(servers, svcs, pc) -> None:
    from brpc_tpu.psserve import unregister_psserve
    for svc in svcs:
        unregister_psserve(svc)
    for s in servers:
        try:
            s.stop()
            s.join()
        except Exception:
            pass
    pc.close()


def run_embedding_press(n_shards: int, *, vocab: int = 1024,
                        dim: int = 32, zipf_s: float = 1.0,
                        update_ratio: float = 0.1,
                        key_counts=(4, 16, 64),
                        duration_s: float = 10.0, threads: int = 4,
                        serializer: str = "json",
                        out=sys.stderr) -> dict:
    """``--embedding N`` mode (ISSUE 12): zipf-skewed key load over an
    in-process N-shard parameter-server service through PSClient's
    PartitionChannel fan-out.  Reports lookups/s, updates/s, the
    update/lookup mix actually served, and latency p50/p99 BY KEY-COUNT
    BUCKET (small lookups shouldn't pay big lookups' padding), plus the
    shards' version/dup counters so exactly-once holds under load.

    ``--serializer json|tensorframe`` (ISSUE 13) picks the wire format
    and the report adds WIRE BYTES/REQUEST — request-direction bytes
    exact from the psserve_wire_bytes_* server counters, response bytes
    measured by re-encoding one received response per key-count bucket
    (byte-identical to what the server sent: both wires' encodes are
    deterministic) — so the binary-vs-JSON A/B is reproducible outside
    the bench."""
    import numpy as np

    from brpc_tpu.psserve import PSClient
    from brpc_tpu.psserve import service as ps_service
    from brpc_tpu.rpc.serialization import get_serializer

    if serializer not in ("json", "tensorframe"):
        raise ValueError("--serializer must be json|tensorframe")
    servers, svcs, shards, pc = spin_up_psserve(
        n_shards, vocab=vocab, dim=dim, name_prefix="press_ps")
    if serializer == "json":
        req0 = ps_service.REQUESTS_JSON.get_value()
        wb0 = ps_service.WIRE_BYTES_JSON.get_value()
    else:
        req0 = ps_service.REQUESTS_TENSORFRAME.get_value()
        wb0 = ps_service.WIRE_BYTES_TENSORFRAME.get_value()
    # one decoded response per (kind, key-count), re-encoded after the
    # run to measure exact response wire bytes
    resp_samples: dict = {}
    counts = {"lookups": 0, "updates": 0}
    lat_by_bucket: dict[int, list] = {k: [] for k in key_counts}
    mu = threading.Lock()
    stop_t = time.monotonic() + duration_s

    counts["errors"] = 0

    def worker(widx: int):
        rng = np.random.default_rng(1000 + widx)
        sample = zipf_key_sampler(vocab, zipf_s, seed=widx)
        cli = PSClient(pc, vocab=vocab, dim=dim,
                       serializer=serializer, ici="off",
                       name=f"press_cli_{widx}")
        ones = {k: np.ones((k, dim), np.float32) for k in key_counts}
        while time.monotonic() < stop_t:
            n = int(rng.choice(key_counts))
            keys = sample(n)
            t0 = time.monotonic()
            try:
                if rng.random() < update_ratio:
                    cli.update(keys, ones[n])
                    kind = "updates"
                else:
                    cli.lookup(keys)
                    kind = "lookups"
            except errors.RpcError:
                # an exhausted-retries failure under load is DATA, not
                # a reason to silently lose this worker for the rest
                # of the run (which would understate throughput with
                # no trace): count it and keep pressing
                with mu:
                    counts["errors"] += 1
                continue
            dt_us = (time.monotonic() - t0) * 1e6
            with mu:
                counts[kind] += 1
                lat_by_bucket[n].append(dt_us)
                if kind == "lookups" and n not in resp_samples:
                    # keep one keyset per bucket for the exact
                    # response-bytes re-encode after the run
                    resp_samples[n] = keys

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(threads)]
    t0 = time.monotonic()
    [t.start() for t in ts]
    [t.join(duration_s + 60) for t in ts]
    elapsed = time.monotonic() - t0
    try:
        by_bucket = {}
        for k, lats in lat_by_bucket.items():
            if not lats:
                continue
            a = np.asarray(lats)
            by_bucket[str(k)] = {
                "n": int(a.size),
                "p50_us": round(float(np.percentile(a, 50)), 1),
                "p99_us": round(float(np.percentile(a, 99)), 1),
            }
        # wire bytes/request (ISSUE 13): request direction exact from
        # the per-serializer server Adders; response direction measured
        # by re-encoding one REAL per-partition response per key-count
        # (both wires' encodes are deterministic, so these are the
        # bytes the server actually sent for that shape)
        if serializer == "json":
            req_d = ps_service.REQUESTS_JSON.get_value() - req0
            wb_d = ps_service.WIRE_BYTES_JSON.get_value() - wb0
        else:
            req_d = ps_service.REQUESTS_TENSORFRAME.get_value() - req0
            wb_d = ps_service.WIRE_BYTES_TENSORFRAME.get_value() - wb0
        ser_obj = get_serializer(serializer)
        from brpc_tpu.psserve.shard import owners_for, shard_bounds
        bounds = shard_bounds(vocab, n_shards)
        resp_bytes = {}
        for k, keys in sorted(resp_samples.items()):
            owner = owners_for(keys, bounds)
            total_b = 0
            for part in np.unique(owner):
                pos = np.flatnonzero(owner == part)
                sub = keys[pos]
                # rows straight off the table snapshot — NOT
                # shard.lookup, which would pollute the hot-key
                # histogram and lookup counters the summary reports
                # with synthetic probe traffic
                sh = shards[int(part)]
                rows = sh.snapshot_rows()[sub - sh.lo]
                # the shard's REAL version: JSON response size varies
                # with its digit count, and the probe's claim is
                # byte-identical re-encoding
                if serializer == "json":
                    obj = {"rows": rows.tolist(),
                           "version": int(sh.version)}
                else:
                    obj = {"rows": np.ascontiguousarray(rows),
                           "version": int(sh.version)}
                total_b += len(ser_obj.encode(obj)[0])
            resp_bytes[str(k)] = int(total_b)
        total = counts["lookups"] + counts["updates"]
        summary = {
            "mode": "embedding",
            "shards": n_shards, "vocab": vocab, "dim": dim,
            "zipf_s": zipf_s,
            "serializer": serializer,
            "wire": {
                "req_bytes_per_call": round(wb_d / req_d, 1)
                if req_d else 0.0,
                "requests": int(req_d),
                "lookup_resp_bytes_by_key_count": resp_bytes,
            },
            "lookups_per_s": round(counts["lookups"] / elapsed, 1),
            "updates_per_s": round(counts["updates"] / elapsed, 1),
            "update_mix": round(counts["updates"] / total, 3)
            if total else 0.0,
            "errors": counts["errors"],
            "latency_by_key_count": by_bucket,
            "shard_versions": [sh.version for sh in shards],
            "dup_updates": sum(sh.n_dup_updates for sh in shards),
            "hot_keys": shards[0].hot_keys(5),
            "elapsed_s": round(elapsed, 2),
        }
        print(json.dumps(summary), file=out)
        return summary
    finally:
        tear_down_psserve(servers, svcs, pc)


def run_mixed_press(shapes, *, weights=None, n_shards: int = 2,
                    vocab: int = 128, dim: int = 16,
                    gen_tokens: int = 16, train_steps: int = 8,
                    duration_s: float = 10.0, seed: int = 0,
                    out=sys.stderr) -> dict:
    """``--mixed lookup,generate,train`` (ISSUE 17): ONE in-process
    fleet serving every requested traffic shape SIMULTANEOUSLY — zipf
    PS lookups, streamed generations, trainer update waves — with the
    :class:`~brpc_tpu.train.TrafficArbiter` arbitrating across shapes.
    ``weights`` scales worker counts per shape (matching the shape
    list's order; default 1 each).  The report prints per-shape qps
    and latency percentiles plus the arbiter ladder's fire counters —
    escalations and first-fired ticks per named rung — so the
    cheapest-first ordering (trainer paced/shed BEFORE any serving
    rung) is visible from the command line."""
    from brpc_tpu.train.arbiter import MixedWorkloadHarness
    shapes = [s.strip() for s in shapes if s.strip()]
    known = ("lookup", "generate", "train")
    bad = [s for s in shapes if s not in known]
    if bad:
        raise ValueError(f"unknown shapes {bad}; pick from {known}")
    if not shapes:
        raise ValueError("--mixed needs at least one shape")
    w = {s: 1 for s in shapes}
    for s, n in zip(shapes, weights or []):
        w[s] = int(n)
    h = MixedWorkloadHarness(
        n_shards=n_shards, vocab=vocab, dim=dim,
        lookup_workers=w.get("lookup", 0),
        gen_workers=w.get("generate", 0), gen_tokens=gen_tokens,
        train_workers=w.get("train", 0),
        train_steps=train_steps if "train" in w else 0,
        min_duration_s=duration_s, seed=seed, name="mixed_press")
    try:
        rep = h.run()
    finally:
        h.close()

    def ms(v):
        return "-" if v is None else f"{v / 1000.0:.2f}ms"

    print(f"--- mixed press: {'+'.join(shapes)} over {n_shards} PS "
          f"shards, {rep['elapsed_s']:.1f}s ---", file=out)
    for name in ("lookup", "generate"):
        st = rep["shapes"][name]
        if not (st["ok"] or st["err"]):
            continue
        extra = ""
        if name == "generate":
            extra = (f"  bit_exact={st['bit_exact']}/"
                     f"{st['ok']}")
        print(f"{name:>9}: {st['qps']:8.1f} qps  "
              f"p50 {ms(st['p50_us'])}  p99 {ms(st['p99_us'])}  "
              f"errors {st['err']}{extra}", file=out)
    tr = rep["train"]
    if tr["waves"]:
        print(f"{'train':>9}: {tr['updates_per_s']:8.1f} waves/s  "
              f"waves {tr['waves']}  retries {tr['wave_retries']}  "
              f"paced {tr['paced_waves']}  "
              f"loss {tr['loss_first']:.4f} -> {tr['loss_final']:.4f}",
              file=out)
    lad = rep["arbiter"]["ladder"]
    print("ladder fire counts (cheapest first):", file=out)
    for i, name in enumerate(lad["level_names"]):
        print(f"  L{i + 1} {name:<18} escalations "
              f"{lad['escalations'][i]:<4} first_fired "
              f"{lad['first_fired'][i]}", file=out)
    print(f"arbiter: admitted {rep['arbiter']['admitted_waves']}  "
          f"paced {rep['arbiter']['paced_waves']}  "
          f"shed {rep['arbiter']['shed_waves']}", file=out)
    print(f"invariants: exactly_once={all(rep['exactly_once'])}  "
          f"stale_reads={rep['stale_reads']}  "
          f"queues_drained={rep['queues_drained']}  "
          f"pools_at_baseline={rep['pools_at_baseline']}", file=out)
    return rep


def run_cluster_press(n_replicas: int, request,
                      duration_s: float = 10.0, threads: int = 4,
                      timeout_ms: int = 20_000, request_factory=None,
                      kill_replica_after: float | None = None,
                      slo: bool = False,
                      out=sys.stderr) -> dict:
    """``--cluster N`` mode: spin up N in-process serving replicas
    behind a :class:`~brpc_tpu.serving.ClusterRouter` and press full
    generations through the front door — ROADMAP item 3's "heavy
    traffic" scenario driver.  Reports generations/s, tokens/s,
    time-to-first-token percentiles, the RESUME count (replica
    failovers ridden by sessions), and the overload gradient's
    per-level shed counts.  ``kill_replica_after=S`` kills one replica
    mid-run so the resume path runs under load.  CPU-valid: the step
    function is plain numpy."""
    from brpc_tpu.serving import RouterClient

    replicas, router, rsrv, raddr = spin_up_cluster(
        n_replicas, page_tokens=8, commit_live_pages=True,
        replicate_sessions=True, max_sessions=max(64, 8 * threads),
        name_prefix="press_cl", timeout_ms=timeout_ms)
    if slo:
        # --slo (ISSUE 20): observe-only burn-rate evaluation riding
        # the collector ticks — a single-model press has no canary
        # pair to re-weight, so verdicts report, never act
        from brpc_tpu.serving import Objective, SLOEngine
        from brpc_tpu.serving.modelplane import DEFAULT_MODEL
        router.attach_slo(SLOEngine(
            DEFAULT_MODEL, DEFAULT_MODEL, DEFAULT_MODEL,
            [Objective("ttft_p99_ms", 500.0),
             Objective("itl_p99_ms", 50.0),
             Objective("error_rate", 0.05)],
            short_window_s=1.0, long_window_s=3.0, act=False))

    rec_ttft = LatencyRecorder("rpc_press_cluster_ttft")
    mu = threading.Lock()
    gens_ok = [0]
    nerr = [0]
    nshed = [0]
    tokens = [0]
    stop = threading.Event()

    def worker(k: int):
        cli = RouterClient(raddr, timeout_ms=timeout_ms)
        gen = request_factory(k) if request_factory is not None else None
        while not stop.is_set():
            req = gen() if gen is not None else request
            prompt = req.get("prompt") or [1]
            n = int(req.get("max_new_tokens", 16))
            first = [None]

            def emit(tok, first=first):
                if first[0] is None:
                    first[0] = time.monotonic()

            t0 = time.monotonic()
            try:
                res = cli.generate(prompt, n, emit=emit,
                                   timeout_s=timeout_ms / 1e3)
            except brpc.RpcError as e:
                with mu:
                    if e.code == brpc.errors.ELIMIT:
                        nshed[0] += 1   # shed-at-router, by design
                    else:
                        nerr[0] += 1
                continue
            except Exception:
                with mu:
                    nerr[0] += 1
                continue
            with mu:
                if res["error"]:
                    nerr[0] += 1
                    continue
                gens_ok[0] += 1
                tokens[0] += len(res["tokens"])
            if first[0] is not None:
                rec_ttft.add(int((first[0] - t0) * 1e6))

    ts = [threading.Thread(target=worker, args=(k,), daemon=True)
          for k in range(threads)]
    t_start = time.monotonic()
    [t.start() for t in ts]
    try:
        if kill_replica_after is not None and \
                kill_replica_after < duration_s:
            time.sleep(kill_replica_after)
            _store, keng, ksrv, kaddr = replicas[0]
            print(f"cluster press: killing replica {kaddr}",
                  file=sys.stderr)
            ksrv.stop()
            ksrv.join()
            keng.close(timeout_s=2.0)
            time.sleep(max(0.0, duration_s - kill_replica_after))
        else:
            time.sleep(duration_s)
    finally:
        stop.set()
    [t.join(timeout_ms / 1e3 + 2) for t in ts]
    elapsed = time.monotonic() - t_start
    rstats = router.stats()
    summary = {
        "replicas": n_replicas,
        "generations_ok": gens_ok[0],
        "errors": nerr[0],
        "client_sheds": nshed[0],
        "tokens": tokens[0],
        "generations_per_s": round(gens_ok[0] / elapsed, 1),
        "tokens_per_s": round(tokens[0] / elapsed, 1),
        "ttft_avg_us": round(rec_ttft.latency(), 1),
        "ttft_p50_us": rec_ttft.latency_percentile(0.5),
        "ttft_p90_us": rec_ttft.latency_percentile(0.9),
        "ttft_p99_us": rec_ttft.latency_percentile(0.99),
        "resumes": rstats["resumes"],
        "shed_counts": rstats["gradient_fired"],
        "router_level": rstats["ladder"]["level"],
        "elapsed_s": round(elapsed, 2),
    }
    tel = rstats.get("telemetry") or {}
    summary["telemetry"] = {k: tel.get(k, 0) for k in
                            ("pulls", "pull_bytes", "pull_errors",
                             "tombstones")}
    if slo and rstats.get("slo"):
        s = rstats["slo"]
        can = (s.get("last_eval") or {}).get("canary") or {}
        summary["slo"] = {
            "verdict": can.get("verdict"),
            "burns": can.get("burns"),
            "floor": s.get("floor"),
            "evaluations": s.get("evaluations"),
        }
        print("--- slo (observe-only burn rates) ---", file=sys.stderr)
        print(f"verdict={can.get('verdict')} floor={s.get('floor')} "
              f"evaluations={s.get('evaluations')}", file=sys.stderr)
        for met, b in sorted((can.get("burns") or {}).items()):
            print(f"  {met}: target={b.get('target')} "
                  f"burn_short={b.get('short')} "
                  f"burn_long={b.get('long')}"
                  + (" BURNING" if b.get("burning") else ""),
                  file=sys.stderr)
    print(json.dumps(summary), file=out)
    tear_down_cluster(replicas, router, rsrv)
    return summary


def run_multimodel_press(n_replicas: int, models,
                         duration_s: float = 10.0, threads: int = 4,
                         max_new_tokens: int = 12,
                         timeout_ms: int = 20_000,
                         out=sys.stderr) -> dict:
    """``--cluster N --models a,b[,c]`` mode (ISSUE 18): a multi-model
    fleet behind one router front door, workers alternating models per
    request.  Every finished stream is checked against ITS model's
    bit-exact oracle; a stream matching a DIFFERENT model's oracle is
    a wrong-model route.  The report carries per-model generations/s +
    TTFT percentiles and the wrong-model-route count — which must be 0
    (three independent witnesses: client oracles, the router's
    ``wrong_model_routes`` counter, the replicas' ``n_model_misroutes``
    counters)."""
    import random

    from brpc_tpu.serving import RouterClient

    models = [str(m) for m in models]
    replicas, mults, router, rsrv, raddr = spin_up_multimodel_cluster(
        n_replicas, models, commit_live_pages=True,
        replicate_sessions=True, max_sessions=max(64, 8 * threads),
        name_prefix="press_mm", timeout_ms=timeout_ms)

    mu = threading.Lock()
    per = {m: {"ok": 0, "err": 0, "sheds": 0, "tokens": 0,
               "mismatches": 0,
               "rec": LatencyRecorder(f"rpc_press_mm_ttft_{i}")}
           for i, m in enumerate(models)}
    wrong_route = [0]
    stop = threading.Event()

    def worker(k: int):
        cli = RouterClient(raddr, timeout_ms=timeout_ms)
        rng = random.Random(1000 + k)
        j = 0
        while not stop.is_set():
            m = models[(k + j) % len(models)]
            j += 1
            st = per[m]
            prompt = [rng.randrange(1, 97)]
            first = [None]

            def emit(tok, first=first):
                if first[0] is None:
                    first[0] = time.monotonic()

            t0 = time.monotonic()
            try:
                res = cli.generate(prompt, max_new_tokens, emit=emit,
                                   timeout_s=timeout_ms / 1e3, model=m)
            except brpc.RpcError as e:
                with mu:
                    if e.code == brpc.errors.ELIMIT:
                        st["sheds"] += 1
                    else:
                        st["err"] += 1
                continue
            except Exception:
                with mu:
                    st["err"] += 1
                continue
            with mu:
                if res["error"]:
                    st["err"] += 1
                    continue
                st["ok"] += 1
                st["tokens"] += len(res["tokens"])
                exp = expected_model_tokens(prompt, len(res["tokens"]),
                                            mults[m])
                if res["tokens"] != exp:
                    st["mismatches"] += 1
                    if any(res["tokens"] == expected_model_tokens(
                            prompt, len(res["tokens"]), mm)
                           for mo, mm in mults.items() if mo != m):
                        wrong_route[0] += 1
            if first[0] is not None:
                st["rec"].add(int((first[0] - t0) * 1e6))

    ts = [threading.Thread(target=worker, args=(k,), daemon=True)
          for k in range(threads)]
    t_start = time.monotonic()
    [t.start() for t in ts]
    try:
        time.sleep(duration_s)
    finally:
        stop.set()
    [t.join(timeout_ms / 1e3 + 2) for t in ts]
    elapsed = time.monotonic() - t_start
    rstats = router.stats()
    misroutes = sum(r["serving"].n_model_misroutes for r in replicas)
    summary = {
        "replicas": n_replicas,
        "models": {},
        "wrong_model_routes": (wrong_route[0]
                               + int(rstats["wrong_model_routes"])
                               + misroutes),
        "elapsed_s": round(elapsed, 2),
    }
    for m in models:
        st = per[m]
        rec = st["rec"]
        summary["models"][m] = {
            "generations_ok": st["ok"],
            "errors": st["err"],
            "client_sheds": st["sheds"],
            "mismatches": st["mismatches"],
            "generations_per_s": round(st["ok"] / elapsed, 1),
            "tokens_per_s": round(st["tokens"] / elapsed, 1),
            "ttft_p50_us": rec.latency_percentile(0.5),
            "ttft_p90_us": rec.latency_percentile(0.9),
            "ttft_p99_us": rec.latency_percentile(0.99),
        }
    print(json.dumps(summary), file=out)
    tear_down_multimodel_cluster(replicas, router, rsrv)
    return summary


def run_router_kill_press(n_replicas: int, request,
                          duration_s: float = 10.0, threads: int = 4,
                          kill_router_after: float = 3.0,
                          timeout_ms: int = 20_000,
                          request_factory=None,
                          out=sys.stderr) -> dict:
    """``--cluster N --kill-router-after S`` mode (ISSUE 16): the
    replicas stay in-process but the ROUTER runs as its own OS process
    over a session WAL.  S seconds in, the harness SIGKILLs it — no
    goodbye, no flush beyond the WAL's write-ahead discipline — and
    spawns a successor over the same WAL file.  Every generation that
    was mid-flight resumes against the successor from its client-held
    cursor; the report adds the resume count and resume-latency
    percentiles (client resume call -> generation complete) next to
    the usual press numbers."""
    import os
    import tempfile

    from brpc_tpu.serving import RouterClient
    from brpc_tpu.serving.router_proc import spawn_router

    replicas = spin_up_replicas(
        n_replicas, page_tokens=8, commit_live_pages=True,
        step_delay_s=0.002, name_prefix="press_kr")
    addrs = [addr for _, _, _, addr in replicas]
    wal_dir = tempfile.mkdtemp(prefix="rpc_press_wal_")
    wal_path = os.path.join(wal_dir, "sessions.wal")
    proc, raddr = spawn_router(
        wal_path, addrs, replicate_sessions=True, replication_factor=2,
        page_tokens=8, max_sessions=max(64, 8 * threads),
        timeout_ms=timeout_ms)

    rec_ttft = LatencyRecorder("rpc_press_krouter_ttft")
    rec_resume = LatencyRecorder("rpc_press_krouter_resume")
    mu = threading.Lock()
    gens_ok = [0]
    nerr = [0]
    nshed = [0]
    tokens = [0]
    resumes = [0]
    stop = threading.Event()
    router_up = threading.Event()
    router_up.set()
    cur_addr = [raddr]

    def worker(k: int):
        gen_req = request_factory(k) if request_factory is not None \
            else None
        while not stop.is_set():
            router_up.wait(1.0)
            if stop.is_set():
                return
            addr = cur_addr[0]
            cli = RouterClient(addr, timeout_ms=timeout_ms,
                               shed_retries=0)
            req = gen_req() if gen_req is not None else request
            prompt = req.get("prompt") or [1]
            n = int(req.get("max_new_tokens", 16))
            first = [None]

            def emit(tok, first=first):
                if first[0] is None:
                    first[0] = time.monotonic()

            t0 = time.monotonic()
            try:
                live = cli.start(prompt, n, emit=emit)
            except brpc.RpcError as e:
                with mu:
                    if e.code == brpc.errors.ELIMIT:
                        nshed[0] += 1
                    else:
                        nerr[0] += 1
                continue
            except Exception:
                with mu:
                    nerr[0] += 1
                continue
            done = live.wait(timeout_ms / 1e3)
            if done and live.error is None:
                with mu:
                    gens_ok[0] += 1
                    tokens[0] += len(live.tokens)
                if first[0] is not None:
                    rec_ttft.add(int((first[0] - t0) * 1e6))
                continue
            # mid-flight router death (or wedge): resume the SESSION on
            # whatever router holds the WAL now, from the client-held
            # cursor — the durable-control-plane acceptance path
            sid, cursor = live.session_id, live.cursor
            try:
                live.drop()
            except Exception:
                pass
            if not sid or stop.is_set():
                with mu:
                    nerr[0] += 1
                continue
            router_up.wait(timeout_ms / 1e3)
            r0 = time.monotonic()
            try:
                res = RouterClient(cur_addr[0], timeout_ms=timeout_ms,
                                   shed_retries=0).resume_wait(
                    sid, cursor, timeout_s=timeout_ms / 1e3)
            except Exception:
                with mu:
                    nerr[0] += 1
                continue
            rec_resume.add(int((time.monotonic() - r0) * 1e6))
            with mu:
                resumes[0] += 1
                if res["error"]:
                    nerr[0] += 1
                else:
                    gens_ok[0] += 1
                    tokens[0] += len(res["tokens"]) + cursor

    ts = [threading.Thread(target=worker, args=(k,), daemon=True)
          for k in range(threads)]
    t_start = time.monotonic()
    [t.start() for t in ts]
    adoption_ms = None
    replay = None
    try:
        time.sleep(min(kill_router_after, duration_s))
        print(f"cluster press: SIGKILL router pid={proc.pid}",
              file=sys.stderr)
        router_up.clear()
        k0 = time.monotonic()
        proc.kill()
        proc.wait()
        proc2, raddr2 = spawn_router(
            wal_path, addrs, replicate_sessions=True,
            replication_factor=2, page_tokens=8,
            max_sessions=max(64, 8 * threads), timeout_ms=timeout_ms)
        adoption_ms = round((time.monotonic() - k0) * 1e3, 1)
        cur_addr[0] = raddr2
        proc = proc2
        router_up.set()
        time.sleep(max(0.0, duration_s - kill_router_after))
    finally:
        stop.set()
        router_up.set()
    [t.join(timeout_ms / 1e3 + 2) for t in ts]
    elapsed = time.monotonic() - t_start
    try:
        from brpc_tpu.rpc.channel import Channel
        replay = Channel(cur_addr[0], timeout_ms=5000).call_sync(
            "Router", "Stats", {}, serializer="json",
            response_serializer="json").get("wal_replay")
    except Exception:
        replay = None
    summary = {
        "replicas": n_replicas,
        "generations_ok": gens_ok[0],
        "errors": nerr[0],
        "client_sheds": nshed[0],
        "tokens": tokens[0],
        "generations_per_s": round(gens_ok[0] / elapsed, 1),
        "tokens_per_s": round(tokens[0] / elapsed, 1),
        "ttft_avg_us": round(rec_ttft.latency(), 1),
        "ttft_p50_us": rec_ttft.latency_percentile(0.5),
        "ttft_p99_us": rec_ttft.latency_percentile(0.99),
        "router_resumes": resumes[0],
        "resume_p50_us": rec_resume.latency_percentile(0.5),
        "resume_p90_us": rec_resume.latency_percentile(0.9),
        "resume_p99_us": rec_resume.latency_percentile(0.99),
        "router_adoption_ms": adoption_ms,
        "wal_replay": replay,
        "elapsed_s": round(elapsed, 2),
    }
    print(json.dumps(summary), file=out)
    try:
        proc.kill()
        proc.wait()
    except Exception:
        pass
    tear_down_replicas(replicas)
    try:
        os.unlink(wal_path)
        os.rmdir(wal_dir)
    except OSError:
        pass
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--server", help="host:port (unary/streaming modes)")
    ap.add_argument("--service")
    ap.add_argument("--method")
    ap.add_argument("--cluster", type=int, default=0, metavar="N",
                    help="spin up N in-process serving replicas behind "
                         "a ClusterRouter and press generations "
                         "through the front door (generations/s, TTFT "
                         "percentiles, resume count, per-level shed "
                         "counts)")
    ap.add_argument("--models", metavar="A,B[,C]",
                    help="with --cluster: serve a comma list of named "
                         "model deployments on every replica and press "
                         "them through one router front door; reports "
                         "per-model generations/s + TTFT percentiles "
                         "and the wrong-model-route count (must be 0)")
    ap.add_argument("--kill-replica-after", type=float, default=None,
                    metavar="S",
                    help="with --cluster: kill one replica S seconds "
                         "into the run so session resume runs under "
                         "load")
    ap.add_argument("--slo", action="store_true",
                    help="with --cluster: attach an observe-only SLO "
                         "burn-rate engine to the router and print its "
                         "verdict/burn summary block (ISSUE 20)")
    ap.add_argument("--kill-router-after", type=float, default=None,
                    metavar="S",
                    help="with --cluster: run the router as its own OS "
                         "process over a session WAL, SIGKILL it S "
                         "seconds in, spawn a successor over the same "
                         "WAL, and resume every mid-flight session "
                         "(reports resume count + resume-latency "
                         "percentiles)")
    ap.add_argument("--embedding", type=int, default=0, metavar="N",
                    help="spin up N in-process parameter-server shards "
                         "and press zipf-skewed Lookup/Update key load "
                         "through PSClient's PartitionChannel fan-out "
                         "(lookups/s, update mix, p99 by key-count "
                         "bucket)")
    ap.add_argument("--zipf", type=float, default=1.0, metavar="S",
                    help="with --embedding: zipf skew exponent for the "
                         "key distribution (0 = uniform)")
    ap.add_argument("--update-ratio", type=float, default=0.1,
                    help="with --embedding: fraction of requests that "
                         "are sparse Updates instead of Lookups")
    ap.add_argument("--vocab", type=int, default=1024,
                    help="with --embedding: embedding table rows")
    ap.add_argument("--dim", type=int, default=32,
                    help="with --embedding: embedding row width")
    ap.add_argument("--mixed", metavar="SHAPES",
                    help="comma list from lookup,generate,train: one "
                         "in-process fleet serving every shape at "
                         "once, TrafficArbiter arbitrating; reports "
                         "per-shape qps/p99 + ladder fire counts "
                         "(ISSUE 17)")
    ap.add_argument("--mixed-weights", metavar="W",
                    help="comma worker weights matching --mixed order "
                         "(default 1 each)")
    ap.add_argument("--shards", type=int, default=2,
                    help="--mixed: PS shard count")
    ap.add_argument("--train-steps", type=int, default=8,
                    help="--mixed: trainer steps per worker")
    ap.add_argument("--disagg", metavar="PREFILL_ADDR,DECODE_ADDR",
                    help="drive a disaggregated prefill/decode split: "
                         "each call runs DisaggPrefill.Prefill on the "
                         "first address (pages stream to the decode "
                         "store) then streams Serving.Generate tokens "
                         "from the second; reports generations/s, "
                         "tokens/s and TTFT percentiles")
    ap.add_argument("--input", default="{}",
                    help="JSON request body, or @file.json")
    ap.add_argument("--qps", type=int, default=0,
                    help="0 = unthrottled (unary mode only)")
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--timeout-ms", type=int, default=1000)
    ap.add_argument("--serializer", default="json",
                    help="request serializer; with --embedding: "
                         "json|tensorframe picks the PS wire format "
                         "and the report adds wire bytes/request")
    ap.add_argument("--connection-type", default="single",
                    choices=["single", "pooled", "short"])
    ap.add_argument("--streaming", action="store_true",
                    help="drive a streaming method: attach a client "
                         "stream per call, report items/s and "
                         "time-to-first-item percentiles")
    ap.add_argument("--shared-prefix-ratio", type=float, default=0.0,
                    help="regenerate each call's \"prompt\" field: with "
                         "this probability it opens with one fixed "
                         "shared prefix (prefix-skewed KV-cache load); "
                         "0 disables")
    ap.add_argument("--prefix-tokens", type=int, default=32,
                    help="shared-prefix length for --shared-prefix-ratio")
    ap.add_argument("--prefix-seed", type=int, default=0,
                    help="seed for the prefix-skew schedule")
    ap.add_argument("--dump-traces", type=int, default=0,
                    help="enable rpcz for the run and print the N "
                         "slowest traces as indented timelines after "
                         "the summary; 0 disables")
    ap.add_argument("--hotspots", type=int, default=0,
                    help="burst-profile the SERVER for the press "
                         "duration (/hotspots?seconds=) and print its "
                         "top-N stage-tagged folded stacks alongside "
                         "the latency report; 0 disables")
    a = ap.parse_args(argv)
    if a.mixed:
        weights = [int(x) for x in a.mixed_weights.split(",")] \
            if a.mixed_weights else None
        run_mixed_press(a.mixed.split(","), weights=weights,
                        n_shards=a.shards, vocab=a.vocab, dim=a.dim,
                        train_steps=a.train_steps,
                        duration_s=a.duration, out=sys.stdout)
        return
    if a.embedding:
        run_embedding_press(a.embedding, vocab=a.vocab, dim=a.dim,
                            serializer=a.serializer,
                            zipf_s=a.zipf, update_ratio=a.update_ratio,
                            duration_s=a.duration, threads=a.threads,
                            out=sys.stdout)
        return
    if a.disagg is None and not a.cluster:
        missing = [n for n, v in (("--server", a.server),
                                  ("--service", a.service),
                                  ("--method", a.method)) if not v]
        if missing:
            ap.error(f"{', '.join(missing)} required "
                     f"(unless --disagg or --cluster is used)")
    text = a.input
    if text.startswith("@"):
        with open(text[1:]) as f:
            text = f.read()
    req = json.loads(text)
    factory = None
    if a.shared_prefix_ratio > 0:
        factory = make_prefix_skew(req, a.shared_prefix_ratio,
                                   prefix_tokens=a.prefix_tokens,
                                   seed=a.prefix_seed)
    if a.cluster and a.models:
        run_multimodel_press(
            a.cluster, [m for m in a.models.split(",") if m],
            duration_s=a.duration, threads=a.threads,
            timeout_ms=max(a.timeout_ms, 5000), out=sys.stdout)
    elif a.cluster and a.kill_router_after is not None:
        run_router_kill_press(a.cluster, req, duration_s=a.duration,
                              threads=a.threads,
                              kill_router_after=a.kill_router_after,
                              timeout_ms=max(a.timeout_ms, 5000),
                              request_factory=factory, out=sys.stdout)
    elif a.cluster:
        run_cluster_press(a.cluster, req, duration_s=a.duration,
                          threads=a.threads,
                          timeout_ms=max(a.timeout_ms, 5000),
                          request_factory=factory,
                          kill_replica_after=a.kill_replica_after,
                          slo=a.slo,
                          out=sys.stdout)
    elif a.disagg:
        try:
            prefill_addr, decode_addr = a.disagg.split(",", 1)
        except ValueError:
            ap.error("--disagg needs PREFILL_ADDR,DECODE_ADDR")
        run_disagg_press(prefill_addr.strip(), decode_addr.strip(), req,
                         duration_s=a.duration, threads=a.threads,
                         timeout_ms=max(a.timeout_ms, 5000),
                         request_factory=factory, out=sys.stdout)
    elif a.streaming:
        run_streaming_press(a.server, a.service, a.method, req,
                            duration_s=a.duration, threads=a.threads,
                            serializer=a.serializer,
                            timeout_ms=a.timeout_ms,
                            connection_type=a.connection_type,
                            request_factory=factory,
                            dump_traces=a.dump_traces,
                            hotspots=a.hotspots,
                            out=sys.stdout)
    else:
        run_press(a.server, a.service, a.method, req, qps=a.qps,
                  duration_s=a.duration, threads=a.threads,
                  serializer=a.serializer, timeout_ms=a.timeout_ms,
                  connection_type=a.connection_type,
                  request_factory=factory, dump_traces=a.dump_traces,
                  hotspots=a.hotspots, out=sys.stdout)


if __name__ == "__main__":
    main()
