"""rpc_replay — re-send traffic captured by rpc_dump
(reference tools/rpc_replay/rpc_replay.cpp; capture side rpc_dump.{h,cpp}).

Reads .rdump recordio files (meta = wire RpcMeta bytes, body = payload as
received) and re-issues each request byte-for-byte against a target server.

Example:
  python -m brpc_tpu.tools.rpc_replay --server 127.0.0.1:8000 \
      --dir ./rpc_dump --qps 1000 --times 1
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

from brpc_tpu import errors
from brpc_tpu.butil.endpoint import str2endpoint
from brpc_tpu.butil.recordio import RecordReader
from brpc_tpu.bvar import LatencyRecorder
from brpc_tpu.rpc import meta as M
from brpc_tpu.rpc.channel import CallManager, SocketMap, _CallState
from brpc_tpu.rpc.controller import Controller, OneShotEvent
from brpc_tpu.rpc.transport import Transport


def load_records(path_or_dir: str) -> list[tuple[bytes, bytes]]:
    paths = ([path_or_dir] if os.path.isfile(path_or_dir)
             else sorted(glob.glob(os.path.join(path_or_dir, "*.rdump"))))
    records: list[tuple[bytes, bytes]] = []
    for p in paths:
        with open(p, "rb") as f:
            records.extend(RecordReader(f))
    return records


def replay_one(ep, meta_bytes: bytes, body: bytes,
               timeout_ms: int = 1000) -> Controller:
    """Re-issues one captured request with a fresh correlation id; returns
    the controller (join()ed by the caller)."""
    meta = M.RpcMeta.decode(meta_bytes)
    cntl = Controller()
    cntl.timeout_ms = timeout_ms
    cntl.max_retry = 0
    from brpc_tpu.rpc.channel import _cid_counter
    cntl.correlation_id = next(_cid_counter)
    cntl._start_us = int(time.monotonic() * 1e6)
    cntl._done_event = OneShotEvent()
    meta.correlation_id = cntl.correlation_id
    meta.attempt = 0
    mgr = CallManager.instance()
    st = _CallState(cntl, _NullChannel(), meta, body, None)
    mgr.register(st)
    t = Transport.instance()
    cid = cntl.correlation_id
    st.deadline_timer = t.schedule(timeout_ms / 1e3,
                                   lambda: mgr.on_deadline(cid))
    try:
        conn = SocketMap.instance().get_connection(ep)
    except (ConnectionError, OSError):
        cntl.set_failed(errors.ECONNREFUSED, f"cannot connect {ep}")
        mgr._finish(st)
        return cntl
    mgr.bind_socket(cid, conn.sid)
    rc = t.write_frame(conn.sid, meta.encode(), body)
    if rc != 0:
        cntl.set_failed(errors.EFAILEDSOCKET, "write failed")
        mgr._finish(st)
    return cntl


class _NullChannel:
    """Replay has no retry/LB policy — a minimal channel stand-in."""
    def _should_retry(self, st, owner_attempt=None):
        return False

    def _on_call_end(self, st):
        pass


def run_replay(server: str, path: str, qps: int = 0, times: int = 1,
               timeout_ms: int = 1000, out=sys.stderr) -> dict:
    ep = str2endpoint(server)
    records = load_records(path)
    if not records:
        print(json.dumps({"error": "no records found", "path": path}),
              file=out)
        return {"replayed": 0, "errors": 0}
    rec = LatencyRecorder("rpc_replay")
    nerr = 0
    nok = 0
    interval = 1.0 / qps if qps > 0 else 0.0
    t_start = time.monotonic()
    next_at = t_start
    inflight: list[Controller] = []
    for _ in range(times):
        for meta_bytes, body in records:
            if interval > 0:
                now = time.monotonic()
                if now < next_at:
                    time.sleep(next_at - now)
                next_at += interval
            cntl = replay_one(ep, meta_bytes, body, timeout_ms)
            inflight.append(cntl)
            if len(inflight) >= 128:  # bounded pipeline window
                done = inflight.pop(0)
                done.join()
                nok, nerr = _account(done, rec, nok, nerr)
    for cntl in inflight:
        cntl.join()
        nok, nerr = _account(cntl, rec, nok, nerr)
    elapsed = time.monotonic() - t_start
    summary = {
        "replayed": nok,
        "errors": nerr,
        "qps": round(nok / elapsed, 1) if elapsed > 0 else 0,
        "p50_us": rec.latency_percentile(0.5),
        "p99_us": rec.latency_percentile(0.99),
        "elapsed_s": round(elapsed, 2),
    }
    print(json.dumps(summary), file=out)
    return summary


def _account(cntl, rec, nok, nerr):
    if cntl.error_code == 0:
        rec.add(cntl.latency_us)
        return nok + 1, nerr
    return nok, nerr + 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--server", required=True, help="host:port")
    ap.add_argument("--dir", dest="path", required=True,
                    help=".rdump file or directory of them")
    ap.add_argument("--qps", type=int, default=0, help="0 = unthrottled")
    ap.add_argument("--times", type=int, default=1,
                    help="replay the capture N times")
    ap.add_argument("--timeout-ms", type=int, default=1000)
    a = ap.parse_args(argv)
    run_replay(a.server, a.path, qps=a.qps, times=a.times,
               timeout_ms=a.timeout_ms, out=sys.stdout)


if __name__ == "__main__":
    main()
