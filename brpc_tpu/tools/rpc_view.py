"""rpc_view — view another server's builtin console pages
(reference tools/rpc_view: a proxy that renders a remote server's builtin
pages; here a fetch-and-print CLI plus an optional local proxy port).

Examples:
  python -m brpc_tpu.tools.rpc_view --target 127.0.0.1:8000 --path /status
  python -m brpc_tpu.tools.rpc_view --target 127.0.0.1:8000 --serve 8888
"""
from __future__ import annotations

import argparse
import sys
import urllib.request


def fetch(target: str, path: str = "/index", timeout: float = 5.0) -> str:
    if not path.startswith("/"):
        path = "/" + path
    with urllib.request.urlopen(f"http://{target}{path}",
                                timeout=timeout) as r:
        return r.read().decode(errors="replace")


def serve_proxy(target: str, port: int) -> None:
    """Local proxy: browse http://127.0.0.1:<port>/<any builtin path>."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            try:
                body = fetch(target, self.path).encode()
                self.send_response(200)
            except Exception as e:
                body = f"proxy error: {e}".encode()
                self.send_response(502)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    print(f"proxying {target} on http://127.0.0.1:{httpd.server_port}/",
          file=sys.stderr)
    httpd.serve_forever()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--target", required=True, help="host:port of a server")
    ap.add_argument("--path", default="/index")
    ap.add_argument("--serve", type=int, default=0,
                    help="run a local proxy on this port instead")
    a = ap.parse_args(argv)
    if a.serve:
        serve_proxy(a.target, a.serve)
    else:
        print(fetch(a.target, a.path))


if __name__ == "__main__":
    main()
