"""brpc_tpu.train — the training plane (ISSUE 17).

The fourth traffic shape: a real data-parallel trainer driving the
sharded parameter server end to end over the same RPC core that
serves lookups and generations —

  * ``optimizer.py`` — :class:`OptimizerSpec` + the fused
    scatter-and-slot-update math.  ``PS.Update`` with an optimizer
    spec runs the gradient scatter AND the momentum/Adam slot step as
    ONE jitted program per key-count bucket, with the slot rows living
    WITH the shard ("RPC Considered Harmful"'s fix done natively:
    momentum never crosses the wire);
  * ``trainer.py`` — :class:`DataParallelTrainer`: N worker threads
    pulling minibatches, Lookup through PSClient (batched, tensorframe
    wire), local grads, PS.Update waves under bounded-staleness
    gating, periodic Pull-based eval proving loss decreases THROUGH
    the service;
  * ``arbiter.py`` — :class:`TrafficArbiter` (the OverloadLadder's
    background-tier rungs: pace/shed trainer waves BEFORE serving
    traffic is touched) + :class:`MixedWorkloadHarness` (one fleet
    carrying zipf lookups, streamed generations and update waves
    simultaneously — the paper's north-star mixed-shape claim).

``trainer``/``arbiter`` import lazily (PEP 562) so the wire layers can
import :class:`OptimizerSpec` without dragging the harness in.
"""
from __future__ import annotations

from brpc_tpu.train.optimizer import OptimizerSpec, oracle_apply

__all__ = [
    "OptimizerSpec", "oracle_apply",
    "DataParallelTrainer", "TrafficArbiter", "MixedWorkloadHarness",
]

_LAZY = {
    "DataParallelTrainer": "brpc_tpu.train.trainer",
    "TrafficArbiter": "brpc_tpu.train.arbiter",
    "MixedWorkloadHarness": "brpc_tpu.train.arbiter",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib
    return getattr(importlib.import_module(mod), name)
