"""TrafficArbiter + MixedWorkloadHarness — one fleet, every traffic
shape (ISSUE 17).

The paper's north-star claim is ONE RPC core carrying every traffic
shape at once.  The harness here is that claim made runnable: a single
in-process fleet serving

  * zipf ``PS.Lookup`` reads (the online serving shape),
  * streamed ``Serving.Generate`` decodes (bit-exact token streams),
  * trainer ``PS.Update`` waves (the background shape),

simultaneously, with the :class:`TrafficArbiter` arbitrating ACROSS
shapes on one OverloadLadder.  The arbiter's contribution is the
background tier: its two cheapest rungs act on the TRAINER —

  level 1  ``pace_trainer``     inject delay before each update wave
  level 2  ``shed_trainer``     hold waves entirely until calm
  level 3  ``brownout_batcher`` first rung that touches SERVING
  level 4  ``clamp_engine``     clamp new generations' budgets

so under a pressure ramp the gradient provably degrades cheapest-first:
the ladder's ``first_fired`` ticks show pace_trainer firing strictly
before any serving-touching rung, and its ``escalations`` counters
show trainer waves absorbing overload while serving traffic still runs
untouched.  Trainer waves are throughput work — delaying one costs
nothing a user can see; a browned-out batcher sheds real requests.

CLUSTER FLOOR TIER (ISSUE 18, closing ROADMAP 5c).  PR 16 gave the
fleet a wire-level overload floor: the router pushes its gradient
level to every replica's ``_cluster`` service each tick.  The arbiter
now consumes that floor as an EXTERNAL level source
(:meth:`add_cluster_floor_source` / :meth:`bind_cluster_service`): any
router-pushed floor >= 1 raises the arbiter's EFFECTIVE level to
shed_trainer, holding update waves FLEET-WIDE before any
serving-touching rung fires anywhere — the cluster's cheapest-first
extension of the local ordering.  ``n_cluster_held_waves`` counts the
waves held by the floor alone (local ladder calm), which is the
cheapest-first proof: trainer paused, zero local brownouts/clamps.

The harness also carries the chaos story (scenario 18): ``kill_shard``
mid-update-wave + ``restart_shard`` (same shard STATE, fresh server —
the PartitionChannel's replica rotation heals the fan-out), with the
update_token replay discipline guaranteeing momentum steps exactly
once through the whole mess.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Optional

import numpy as np

from brpc_tpu import errors
from brpc_tpu.bvar import Adder
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.serving.ladder import OverloadLadder

ARBITER_LEVEL_NAMES = ("pace_trainer", "shed_trainer",
                       "brownout_batcher", "clamp_engine")

# metric names match ReplicaHandle.pressures() so the same pressure
# dicts drive either ladder.  Calibrated so a saturated-but-serving
# closed loop sits at pace_trainer at most; shed and the serving rungs
# need real queue growth (tests drive ordering with synthetic ramps)
DEFAULT_ARBITER_THRESHOLDS = (
    {"queue_delay_us": 10_000.0, "queue_depth": 8.0},     # pace_trainer
    {"queue_delay_us": 50_000.0, "queue_depth": 32.0},    # shed_trainer
    {"queue_delay_us": 150_000.0, "queue_depth": 128.0,   # brownout
     "pool_ratio": 0.92},
    {"queue_delay_us": 500_000.0, "pool_ratio": 0.98},    # clamp
)

PACED_WAVES = Adder("train_arbiter_paced_waves")
SHED_WAVES = Adder("train_arbiter_shed_waves")
ADMITTED_WAVES = Adder("train_arbiter_admitted_waves")


class TrafficArbiter:
    """The mixed-shape overload policy: an OverloadLadder whose two
    cheapest rungs pace/shed TRAINER waves before any serving
    component is touched (see module docstring).

    The trainer calls :meth:`admit_wave` before each update wave; a
    background tick thread (:meth:`start`) — or an explicit driver
    calling :meth:`tick` — advances the ladder from ``pressure_fn``'s
    readings and drives the serving-tier actions (batcher brownout,
    engine clamp) exactly like
    :func:`~brpc_tpu.serving.ladder.apply_level_to_components`.
    """

    def __init__(self, *, thresholds=DEFAULT_ARBITER_THRESHOLDS,
                 hysteresis_ticks: int = 3,
                 tick_interval_s: float = 0.02,
                 pace_delay_s: float = 0.005,
                 shed_poll_s: float = 0.01,
                 shed_timeout_s: float = 30.0,
                 batchers=(), engines=(), pressure_fn=None,
                 clamp_new_tokens: int = 32, name: str = "arbiter",
                 cluster_floor_sources=()):
        self.ladder = OverloadLadder(thresholds,
                                     hysteresis_ticks=hysteresis_ticks,
                                     level_names=ARBITER_LEVEL_NAMES[
                                         :len(thresholds)])
        self.tick_interval_s = float(tick_interval_s)
        self.pace_delay_s = float(pace_delay_s)
        self.shed_poll_s = float(shed_poll_s)
        self.shed_timeout_s = float(shed_timeout_s)
        self.batchers = list(batchers)
        self.engines = list(engines)
        self.pressure_fn = pressure_fn
        self.clamp_new_tokens = int(clamp_new_tokens)
        self.name = str(name)
        self._mu = InstrumentedLock("train.arbiter")
        self._browned = False
        self._clamped = False
        self._thread = None
        self._stop = threading.Event()
        self.n_paced_waves = 0
        self.n_shed_waves = 0
        self.n_admitted_waves = 0
        self.n_brownouts = 0
        self.n_clamps = 0
        # cluster floor tier (ISSUE 18): external level sources — the
        # router-pushed ``_cluster`` floor this process has latched
        self._floor_sources = list(cluster_floor_sources)
        self.n_cluster_held_waves = 0

    # ---- the cluster floor tier (ISSUE 18) ----

    def add_cluster_floor_source(self, fn) -> "TrafficArbiter":
        """Register a zero-arg callable returning the cluster overload
        floor this process currently sees (a failing source reads as
        0 — a dead floor never wedges the trainer)."""
        self._floor_sources.append(fn)
        return self

    def bind_cluster_service(self, svc) -> "TrafficArbiter":
        """Consume a replica-side
        :class:`~brpc_tpu.serving.cluster_control.ClusterControlService`
        as a floor source: the router pushes its gradient level there
        every tick, so the trainer co-located with this replica yields
        fleet-wide within one tick."""
        return self.add_cluster_floor_source(lambda: svc.level)

    def cluster_floor(self) -> int:
        """The highest router-pushed floor across sources."""
        floor = 0
        for fn in self._floor_sources:
            try:
                floor = max(floor, int(fn() or 0))
            except Exception:
                pass
        return floor

    def effective_level(self) -> int:
        """The level :meth:`admit_wave` gates on: the local ladder,
        raised to shed_trainer (2) whenever ANY cluster floor >= 1 — a
        router already shaping serving traffic means background waves
        must hold everywhere, the cheapest relief the fleet has."""
        lvl = self.ladder.level
        if self._floor_sources and self.cluster_floor() >= 1:
            lvl = max(lvl, 2)
        return lvl

    # ---- the ladder tick ----

    def pressures(self) -> dict:
        if self.pressure_fn is not None:
            try:
                return dict(self.pressure_fn() or {})
            except Exception:
                return {}
        return {}

    def tick(self, pressures: Optional[dict] = None) -> int:
        """One ladder tick: escalate/de-escalate from ``pressures``
        (default: ``pressure_fn()``) and drive the serving-tier
        actions.  The trainer tier needs no push — waves consult
        :meth:`admit_wave` themselves."""
        p = self.pressures() if pressures is None else pressures
        with self._mu:
            lvl = self.ladder.update(p)
            if lvl >= 3 and not self._browned:
                self._browned = True
                self.n_brownouts += 1
                for b in self.batchers:
                    b.brownout = max(getattr(b, "brownout", 0), 1)
            elif lvl < 3 and self._browned:
                self._browned = False
                for b in self.batchers:
                    b.brownout = 0
            if lvl >= 4 and not self._clamped:
                self._clamped = True
                self.n_clamps += 1
                for e in self.engines:
                    e.degraded_clamp = self.clamp_new_tokens
            elif lvl < 4 and self._clamped:
                self._clamped = False
                for e in self.engines:
                    e.degraded_clamp = None
        return lvl

    def start(self) -> "TrafficArbiter":
        if self._thread is None:
            self._stop.clear()

            def loop():
                while not self._stop.wait(self.tick_interval_s):
                    self.tick()

            self._thread = threading.Thread(
                target=loop, name=f"{self.name}_tick", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    # ---- the trainer's gate ----

    def admit_wave(self) -> bool:
        """Called by the trainer before each update wave.  Blocks
        while the EFFECTIVE level sheds trainer waves (>= 2 — local
        ladder, or any cluster floor >= 1), sleeps one pace delay
        while it paces them (>= 1); returns True when the wave was
        delayed at all.  Raises ELIMIT only after ``shed_timeout_s``
        of continuous shed — background work waits, it doesn't fail
        fast."""
        delayed = False
        shed_counted = False
        cluster_counted = False
        deadline = time.monotonic() + self.shed_timeout_s
        while self.effective_level() >= 2:
            if not shed_counted:
                shed_counted = True
                with self._mu:
                    self.n_shed_waves += 1
                SHED_WAVES.add(1)
            if not cluster_counted and self.ladder.level < 2:
                # held by the ROUTER'S floor alone — the fleet-wide
                # cheapest-first proof the tests pin
                cluster_counted = True
                with self._mu:
                    self.n_cluster_held_waves += 1
            delayed = True
            if time.monotonic() > deadline:
                raise errors.RpcError(
                    errors.ELIMIT,
                    f"trainer waves shed for {self.shed_timeout_s}s "
                    f"(effective level {self.effective_level()}, "
                    f"cluster floor {self.cluster_floor()})")
            time.sleep(self.shed_poll_s)
        if self.effective_level() >= 1:
            with self._mu:
                self.n_paced_waves += 1
            PACED_WAVES.add(1)
            time.sleep(self.pace_delay_s)
            delayed = True
        with self._mu:
            self.n_admitted_waves += 1
        ADMITTED_WAVES.add(1)
        return delayed

    def stats(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "ladder": self.ladder.stats(),
                "paced_waves": self.n_paced_waves,
                "shed_waves": self.n_shed_waves,
                "admitted_waves": self.n_admitted_waves,
                "brownouts": self.n_brownouts,
                "clamps": self.n_clamps,
                "cluster_floor": self.cluster_floor(),
                "cluster_held_waves": self.n_cluster_held_waves,
            }


# ---------------------------------------------------------------------------
# the mixed-shape harness
# ---------------------------------------------------------------------------

class MixedWorkloadHarness:
    """One in-process fleet carrying zipf lookups + streamed
    generations + trainer update waves simultaneously, arbitrated by a
    :class:`TrafficArbiter` (see module docstring).  ``run()`` returns
    the full report; ``kill_shard``/``restart_shard`` are the chaos
    hooks scenario 18 drives mid-wave."""

    def __init__(self, *, n_shards: int = 2, vocab: int = 128,
                 dim: int = 16, n_replicas: int = 1,
                 lookup_workers: int = 2, lookup_keys: int = 16,
                 zipf_s: float = 1.0, gen_workers: int = 1,
                 gen_tokens: int = 16, train_workers: int = 2,
                 train_steps: int = 6, optimizer=None,
                 trainer_mode: str = "wire", max_lag: int = 1,
                 min_duration_s: float = 0.0, seed: int = 0,
                 arbiter: Optional[TrafficArbiter] = None,
                 pressure_fn=None, timeout_ms: int = 10_000,
                 name: str = "mixed"):
        from brpc_tpu.models.parameter_server import PSConfig
        from brpc_tpu.train.optimizer import OptimizerSpec
        from brpc_tpu.train.trainer import DataParallelTrainer
        self.n_shards = int(n_shards)
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.n_replicas = int(n_replicas)
        self.lookup_workers = int(lookup_workers)
        self.lookup_keys = int(lookup_keys)
        self.zipf_s = float(zipf_s)
        self.gen_workers = int(gen_workers)
        self.gen_tokens = int(gen_tokens)
        self.min_duration_s = float(min_duration_s)
        self.seed = int(seed)
        self.timeout_ms = int(timeout_ms)
        self.name = str(name)
        self.cfg = PSConfig(vocab=self.vocab, d_model=self.dim,
                            d_ff=2 * self.dim, n_layers=2, seq=8,
                            batch=4)
        self._spin_up()
        self.arbiter = arbiter or TrafficArbiter(
            engines=[eng for _, eng, _, _ in self.replicas],
            pressure_fn=pressure_fn or self._pressures,
            name=f"{self.name}_arbiter")
        if not self.arbiter.batchers:
            # brownout tier: the PS lookup batchers (serving reads)
            self.arbiter.batchers = [
                svc._lookup_b for svc in self.ps_svcs
                if svc._lookup_b is not None]
        self.trainer = DataParallelTrainer(
            self.client, self.cfg, n_workers=int(train_workers),
            steps=int(train_steps),
            optimizer=optimizer or OptimizerSpec("sgdm", lr=0.5,
                                                 momentum=0.5),
            mode=trainer_mode, max_lag=int(max_lag),
            arbiter=self.arbiter, seed=self.seed,
            name=f"{self.name}_trainer")
        self.trainer.seed_dense(self._dense0)
        self._closed = False

    # ---- fleet construction / teardown ----

    def _spin_up(self) -> None:
        import brpc_tpu as brpc
        from brpc_tpu.psserve import (EmbeddingShardServer, PSClient,
                                      register_psserve)
        from brpc_tpu.rpc.combo_channels import PartitionChannel
        from brpc_tpu.tools.rpc_press import spin_up_replicas
        from brpc_tpu.train.trainer import DataParallelTrainer
        self._brpc = brpc
        embed0, dense0 = DataParallelTrainer.model_init(
            self.cfg, seed=self.seed)
        self._dense0 = dense0
        self.shards, self.ps_servers, self.ps_svcs = [], [], []
        self.pc = PartitionChannel(self.n_shards)
        for i in range(self.n_shards):
            sh = EmbeddingShardServer(i, self.n_shards, self.vocab,
                                      self.dim, table=embed0,
                                      name=f"{self.name}_ps")
            self.shards.append(sh)
            s = brpc.Server()
            self.ps_svcs.append(register_psserve(
                s, sh, name=f"{self.name}_{i}"))
            # every serving process joins the fleet telemetry plane
            # (ISSUE 20) — a trainer-harness PS shard is pullable like
            # any replica
            from brpc_tpu.serving.telemetry import register_telemetry
            register_telemetry(s, name=f"{self.name}_ps_{i}")
            s.start("127.0.0.1", 0)
            self.ps_servers.append(s)
            self.pc.add_partition(i, brpc.Channel(
                f"127.0.0.1:{s.port}", timeout_ms=self.timeout_ms))
        self.client = PSClient(self.pc, vocab=self.vocab, dim=self.dim,
                               name=f"{self.name}_trainer_cli")
        # every shape gets its OWN client so per-shape RYW counters
        # stay attributable
        self.lookup_client = PSClient(
            self.pc, vocab=self.vocab, dim=self.dim,
            name=f"{self.name}_lookup_cli")
        self.replicas = spin_up_replicas(
            self.n_replicas, name_prefix=f"{self.name}_srv")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        from brpc_tpu.psserve import unregister_psserve
        from brpc_tpu.tools.rpc_press import tear_down_replicas
        self.arbiter.stop()
        for svc in self.ps_svcs:
            unregister_psserve(svc)
        for s in self.ps_servers:
            try:
                s.stop()
                s.join()
            except Exception:
                pass
        self.pc.close()
        tear_down_replicas(self.replicas)

    # ---- chaos hooks (scenario 18) ----

    def kill_shard(self, i: int) -> None:
        """Kill partition ``i``'s SERVER mid-flight.  The shard's
        STATE (rows, slots, version, applied ids) survives in
        process — exactly a crashed frontend over durable state."""
        s = self.ps_servers[i]
        s.stop()
        s.join()

    def restart_shard(self, i: int) -> None:
        """Bring partition ``i`` back: same shard object, fresh
        server + channel.  add_partition promotes the partition to a
        SelectiveChannel, so fan-out retries rotate off the dead
        endpoint and the trainer's update_token replay dedups anything
        the killed server already applied."""
        from brpc_tpu.psserve import register_psserve
        from brpc_tpu.serving.telemetry import register_telemetry
        brpc = self._brpc
        s = brpc.Server()
        self.ps_svcs.append(register_psserve(
            s, self.shards[i], name=f"{self.name}_r{i}"))
        register_telemetry(s, name=f"{self.name}_ps_r{i}")
        s.start("127.0.0.1", 0)
        self.ps_servers[i] = s
        self.pc.add_partition(i, brpc.Channel(
            f"127.0.0.1:{s.port}", timeout_ms=self.timeout_ms))

    # ---- pressures (real readings; tests may inject a synthetic
    # ramp via pressure_fn) ----

    def _pressures(self) -> dict:
        out = {"queue_depth": 0.0}
        for svc in self.ps_svcs:
            b = svc._lookup_b
            if b is None:
                continue
            try:
                st = b.stats()
                out["queue_depth"] = max(out["queue_depth"],
                                         float(st["queued"]))
                out["queue_delay_us"] = max(
                    out.get("queue_delay_us", 0.0),
                    float(b.queue_delay_rec.latency_percentile(0.99)))
            except Exception:
                pass
        for store, _eng, _srv, _addr in self.replicas:
            try:
                s = store.pagepool.stats()
                cap = s["max_blocks"] * s["pages_per_block"]
                if cap:
                    out["pool_ratio"] = max(
                        out.get("pool_ratio", 0.0),
                        s["pages_in_use"] / cap)
            except Exception:
                pass
        return out

    # ---- the generation shape ----

    class _StreamCollector:
        def __init__(self, brpc):
            base = brpc.StreamHandler
            outer = self

            class _H(base):
                def on_received_messages(self, stream, messages):
                    for m in messages:
                        d = json.loads(m)
                        outer.msgs.append(d)
                        if d.get("done"):
                            outer.done.set()

                def on_closed(self, stream):
                    outer.done.set()

            self.msgs: list = []
            self.done = threading.Event()
            self.handler = _H()

    def _generate(self, ch, prompt) -> Optional[list]:
        brpc = self._brpc
        col = self._StreamCollector(brpc)
        cntl = brpc.Controller(timeout_ms=self.timeout_ms)
        brpc.stream_create(cntl, col.handler)
        resp = ch.call_sync("Serving", "Generate",
                            {"prompt": list(prompt),
                             "max_new_tokens": self.gen_tokens},
                            serializer="json", cntl=cntl)
        if not resp.get("accepted"):
            return None
        if not col.done.wait(30):
            return None
        return [m["token"] for m in col.msgs if "token" in m]

    # ---- run ----

    def run(self) -> dict:
        """Drive all three shapes until the trainer completes (and at
        least ``min_duration_s`` elapsed); returns the report."""
        from brpc_tpu.tools.rpc_press import zipf_key_sampler
        brpc = self._brpc
        stop = threading.Event()
        mu = threading.Lock()
        shape: dict = {
            "lookup": {"ok": 0, "err": 0, "lat_us": []},
            "generate": {"ok": 0, "err": 0, "bit_exact": 0,
                         "mismatch": 0, "lat_us": []},
        }

        # reference streams FIRST (quiesced fleet): later generations
        # of the same prompt must be bit-exact under full mixed load
        gen_chs = [brpc.Channel(self.replicas[g % self.n_replicas][3],
                                timeout_ms=self.timeout_ms)
                   for g in range(self.gen_workers)]
        prompts = [[(self.seed + 3 * g + 1) % 97]
                   for g in range(self.gen_workers)]
        refs = [self._generate(gen_chs[g], prompts[g])
                for g in range(self.gen_workers)]
        # pool baseline AFTER the reference runs: the radix prefix
        # cache legitimately retains those chains' pages; repeating the
        # same prompts under load must not grow occupancy past this
        for _store, eng, _srv, _addr in self.replicas:
            eng.join_idle(10)
        self._pool_base = [
            store.pagepool.stats()["pages_in_use"]
            for store, _, _, _ in self.replicas]

        def lookup_loop(w):
            sample = zipf_key_sampler(self.vocab, self.zipf_s,
                                      seed=self.seed * 31 + w)
            st = shape["lookup"]
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    self.lookup_client.lookup(sample(self.lookup_keys))
                    with mu:
                        st["ok"] += 1
                        st["lat_us"].append(
                            (time.monotonic() - t0) * 1e6)
                except errors.RpcError:
                    with mu:
                        st["err"] += 1

        def gen_loop(g):
            st = shape["generate"]
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    toks = self._generate(gen_chs[g], prompts[g])
                except errors.RpcError:
                    toks = None
                if toks is None:
                    with mu:
                        st["err"] += 1
                    continue
                with mu:
                    st["ok"] += 1
                    st["lat_us"].append((time.monotonic() - t0) * 1e6)
                    if refs[g] is not None and toks == refs[g]:
                        st["bit_exact"] += 1
                    else:
                        st["mismatch"] += 1

        self.arbiter.start()
        threads = [threading.Thread(target=lookup_loop, args=(w,),
                                    daemon=True,
                                    name=f"{self.name}_lookup{w}")
                   for w in range(self.lookup_workers)]
        threads += [threading.Thread(target=gen_loop, args=(g,),
                                     daemon=True,
                                     name=f"{self.name}_gen{g}")
                    for g in range(self.gen_workers)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        try:
            train_report = self.trainer.run()
        finally:
            remain = self.min_duration_s - (time.monotonic() - t0)
            if remain > 0:
                time.sleep(remain)
            stop.set()
            for t in threads:
                t.join(timeout=10)
            self.arbiter.stop()
        elapsed = time.monotonic() - t0

        def lat(st):
            xs = st.pop("lat_us")
            st["p50_us"] = float(np.percentile(xs, 50)) if xs else None
            st["p99_us"] = float(np.percentile(xs, 99)) if xs else None
            st["qps"] = st["ok"] / max(elapsed, 1e-9)

        with mu:
            lat(shape["lookup"])
            lat(shape["generate"])

        # invariants: exactly-once applies (each shard's version
        # counter == its distinct applies), RYW clean, queues drained,
        # pools at baseline
        drained = all(
            b is None or b.stats()["queued"] == 0
            for svc in self.ps_svcs
            for b in (svc._lookup_b, svc._update_b, svc._update_tb))
        pools_ok = True
        for i, (store, eng, _srv, _addr) in enumerate(self.replicas):
            eng.join_idle(10)
            now = store.pagepool.stats()["pages_in_use"]
            pools_ok = pools_ok and now == self._pool_base[i]
        return {
            "elapsed_s": elapsed,
            "shapes": shape,
            "train": train_report,
            "arbiter": self.arbiter.stats(),
            "shards": [sh.stats() for sh in self.shards],
            "exactly_once": [
                sh.version == sh.n_updates + sh.n_pushes
                for sh in self.shards],
            "stale_reads": (self.trainer.stale_reads()
                            + self.lookup_client.n_stale_reads),
            "queues_drained": drained,
            "pools_at_baseline": pools_ok,
        }
