"""OptimizerSpec + the fused scatter-and-slot-update math (ISSUE 17).

"RPC Considered Harmful" (PAPERS.md) argues distributed training dies
on per-update round trips unless updates are batched, co-located with
state, and fused into one device program.  This module is that fix on
our own wire: optimizer slot rows (momentum; Adam m/v/step) live WITH
the embedding shard that owns the parameter rows, and ``PS.Update``
carrying an optimizer spec runs

    gradient scatter  +  slot step  +  row step

as ONE jitted program per key-count bucket.  The slots never cross the
wire — the client sends RAW gradients, not deltas.

The math lives here ONCE (``sgdm_step`` / ``adam_step`` are pure
``jnp`` elementwise functions) and is shared by all three executors:

  * the RPC shard's fused apply (:meth:`EmbeddingShardServer.update_opt`),
  * the lowered ``shard_map`` apply under the ownership mask
    (:meth:`ShardedEmbeddingTable.update`),
  * the dense single-host oracle (:func:`oracle_apply`) the bit-identity
    tests compare both against.

One source of the formulas is what makes bit-identity across partition
counts provable rather than approximate: the scatter accumulates every
duplicate of a key on its one owner in request order (the dense
scatter's order), and everything after the scatter is elementwise.

Semantics per touched row r (rows with no key in the update keep ALL
state bit-for-bit, including Adam step counts):

    sgdm:  m_r    <- momentum * m_r + g_r
           row_r  <- row_r - lr * m_r
    adam:  t_r    <- t_r + 1
           m_r    <- beta1 * m_r + (1 - beta1) * g_r
           v_r    <- beta2 * v_r + (1 - beta2) * g_r^2
           row_r  <- row_r - lr * (m_r / (1 - beta1^t_r))
                              / (sqrt(v_r / (1 - beta2^t_r)) + eps)

where g_r is the SUM of that row's gradient contributions in the
update (duplicate keys accumulate first, then the slot steps once —
exactly what a dense ``.at[].add`` + host optimizer would do).
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

VALID_KINDS = ("sgdm", "adam")

# the flattened tensorframe field names (the binary wire has no nested
# dicts: the spec rides as inline scalar fields next to keys/grads)
_FRAME_FIELDS = ("opt_kind", "opt_lr", "opt_momentum", "opt_beta1",
                 "opt_beta2", "opt_eps")


class OptimizerSpec:
    """One wire-parseable optimizer description.

    ``kind`` is ``"sgdm"`` (momentum SGD; uses ``lr``/``momentum``) or
    ``"adam"`` (uses ``lr``/``beta1``/``beta2``/``eps``).  Hyper-
    parameters ride the wire as plain floats and reach the fused
    program as TRACED scalars, so the compile count stays one per
    (kind, key bucket) no matter how a schedule sweeps them.
    """

    __slots__ = ("kind", "lr", "momentum", "beta1", "beta2", "eps")

    def __init__(self, kind: str, *, lr: float = 0.1,
                 momentum: float = 0.9, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8):
        if kind not in VALID_KINDS:
            raise ValueError(f"optimizer kind must be one of "
                             f"{VALID_KINDS}, got {kind!r}")
        for fname, val in (("lr", lr), ("momentum", momentum),
                           ("beta1", beta1), ("beta2", beta2),
                           ("eps", eps)):
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise ValueError(f"optimizer {fname} must be a number")
            if not np.isfinite(float(val)):
                raise ValueError(f"optimizer {fname} must be finite")
        self.kind = kind
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.beta1 = float(beta1)
        self.beta2 = float(beta2)
        self.eps = float(eps)

    # ---- wire forms ----

    def to_wire(self) -> dict:
        """The JSON form (``PS.Update``'s ``"optimizer"`` field)."""
        if self.kind == "sgdm":
            return {"kind": "sgdm", "lr": self.lr,
                    "momentum": self.momentum}
        return {"kind": "adam", "lr": self.lr, "beta1": self.beta1,
                "beta2": self.beta2, "eps": self.eps}

    @classmethod
    def from_wire(cls, obj) -> "OptimizerSpec":
        """Parse the JSON form (or pass through a spec).  Raises
        ValueError on anything malformed — the service maps that to
        EREQUEST, never EINTERNAL."""
        if isinstance(obj, cls):
            return obj
        if not isinstance(obj, dict):
            raise ValueError('"optimizer" must be an object')
        kind = obj.get("kind")
        if kind not in VALID_KINDS:
            raise ValueError(f'optimizer "kind" must be one of '
                             f"{VALID_KINDS}")
        kw = {}
        for fname in ("lr", "momentum", "beta1", "beta2", "eps"):
            if fname in obj:
                kw[fname] = obj[fname]
        return cls(kind, **kw)

    def to_frame_fields(self) -> dict:
        """The FLATTENED tensorframe form: inline scalar fields
        (``opt_kind`` + floats) merged next to keys/grads — the binary
        wire carries no nested dicts."""
        return {"opt_kind": self.kind, "opt_lr": self.lr,
                "opt_momentum": self.momentum, "opt_beta1": self.beta1,
                "opt_beta2": self.beta2, "opt_eps": self.eps}

    @classmethod
    def from_frame_fields(cls, req: dict) -> Optional["OptimizerSpec"]:
        """Reassemble from a decoded frame; None when the request
        carries no optimizer (no ``opt_kind`` field)."""
        kind = (req or {}).get("opt_kind")
        if kind is None:
            return None
        if not isinstance(kind, str):
            raise ValueError('"opt_kind" must be a string')
        kw = {}
        for fname in ("lr", "momentum", "beta1", "beta2", "eps"):
            v = req.get(f"opt_{fname}")
            if v is not None:
                kw[fname] = v
        return cls(kind, **kw)

    def slot_names(self) -> tuple:
        return ("m",) if self.kind == "sgdm" else ("m", "v", "t")

    def __repr__(self) -> str:
        return f"OptimizerSpec({self.to_wire()})"

    def __eq__(self, other) -> bool:
        return isinstance(other, OptimizerSpec) and \
            self.to_wire() == other.to_wire()


# ---------------------------------------------------------------------------
# the ONE slot-step math (pure jnp elementwise; jax passed in so this
# module imports without touching jax)
# ---------------------------------------------------------------------------

def sgdm_step(jnp, rows, m, g_acc, touched, lr, mu):
    """Momentum-SGD step over pre-accumulated per-row gradients.
    Untouched rows keep rows AND m bit-for-bit."""
    tmask = touched[:, None]
    m_new = jnp.where(tmask, mu * m + g_acc, m)
    rows_new = jnp.where(tmask, rows - lr * m_new, rows)
    return rows_new, m_new


def adam_step(jnp, rows, m, v, t, g_acc, touched, lr, b1, b2, eps):
    """Adam step with PER-ROW step counts (a row's bias correction
    depends on how many updates touched THAT row, not a global clock —
    sparse training's rows advance at wildly different rates)."""
    tmask = touched[:, None]
    t_new = t + touched.astype(t.dtype)
    m_new = jnp.where(tmask, b1 * m + (1.0 - b1) * g_acc, m)
    v_new = jnp.where(tmask, b2 * v + (1.0 - b2) * g_acc * g_acc, v)
    # untouched rows may still have t == 0; clamp so their (discarded)
    # branch never divides by zero
    ts = jnp.maximum(t_new, 1.0)
    bc1 = 1.0 - b1 ** ts
    bc2 = 1.0 - b2 ** ts
    step = lr * (m_new / bc1[:, None]) \
        / (jnp.sqrt(v_new / bc2[:, None]) + eps)
    rows_new = jnp.where(tmask, rows - step, rows)
    return rows_new, m_new, v_new, t_new


# ---------------------------------------------------------------------------
# the fused scatter+step programs (jitted once per kind; the bucket
# padding discipline bounds compiles per kind to the bucket count)
# ---------------------------------------------------------------------------

_fns_mu = threading.Lock()
_FUSED: dict = {}


def fused_apply(kind: str):
    """The jitted fused program for ``kind`` — built once per process
    (never per call: the shard's hot path must not construct jits).

    Signature (sgdm):  (rows, m, keys, grads, valid, lr, mu)
                       -> (rows', m')
    Signature (adam):  (rows, m, v, t, keys, grads, valid,
                        lr, b1, b2, eps) -> (rows', m', v', t')

    ``keys`` are LOCAL row indices padded to a bucket; ``valid`` is a
    float32 mask (0.0 on padding) so pad entries neither contribute
    gradient NOR mark row 0 touched.  Duplicate keys accumulate into
    ``g_acc`` first, then the slot steps once per touched row.

    The state arrays (rows + slots) are DONATED: the program writes
    them in place instead of materialising four table-sized outputs
    per wave, so the wave cost is the gradient scatter plus
    O(bucket) slot math, not O(vocab) copies.  Callers must treat the
    inputs as consumed and keep every other reader of those buffers
    behind the owner's lock (the shard does; ``oracle_apply`` passes
    throwaway copies).  The step math itself runs on the GATHERED
    bucket rows — bit-identical to the dense elementwise form because
    untouched rows are untouched either way, and duplicate key
    positions all compute the same post-accumulation value.
    """
    if kind not in VALID_KINDS:
        raise ValueError(f"optimizer kind must be one of {VALID_KINDS}")
    fn = _FUSED.get(kind)
    if fn is not None:
        return fn
    with _fns_mu:
        fn = _FUSED.get(kind)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        if kind == "sgdm":
            def _sgdm(rows, m, keys, grads, valid, lr, mu):
                g_acc = jnp.zeros_like(rows).at[keys].add(
                    grads * valid[:, None])
                cnt = jnp.zeros((rows.shape[0],), jnp.float32
                                ).at[keys].add(valid)
                rk, mk = sgdm_step(jnp, rows[keys], m[keys],
                                   g_acc[keys], cnt[keys] > 0.0,
                                   lr, mu)
                return rows.at[keys].set(rk), m.at[keys].set(mk)
            # built ONCE per process under _fns_mu and cached in
            # _FUSED; the early return above keeps the hot path
            # construction-free
            # brpc-check: allow(jit-hot-path)
            fn = jax.jit(_sgdm, donate_argnums=(0, 1))
        else:
            def _adam(rows, m, v, t, keys, grads, valid,
                      lr, b1, b2, eps):
                g_acc = jnp.zeros_like(rows).at[keys].add(
                    grads * valid[:, None])
                cnt = jnp.zeros((rows.shape[0],), jnp.float32
                                ).at[keys].add(valid)
                rk, mk, vk, tk = adam_step(
                    jnp, rows[keys], m[keys], v[keys], t[keys],
                    g_acc[keys], cnt[keys] > 0.0, lr, b1, b2, eps)
                return (rows.at[keys].set(rk), m.at[keys].set(mk),
                        v.at[keys].set(vk), t.at[keys].set(tk))
            # once per process, cached in _FUSED (see _sgdm above)
            # brpc-check: allow(jit-hot-path)
            fn = jax.jit(_adam, donate_argnums=(0, 1, 2, 3))
        _FUSED[kind] = fn
        return fn


# ---------------------------------------------------------------------------
# the dense single-host oracle (tests; trainer's pull-compute-push mode)
# ---------------------------------------------------------------------------

def zero_slots(spec: OptimizerSpec, vocab: int, dim: int) -> dict:
    """Fresh host-side slot state matching what a shard lazily
    allocates (all zeros)."""
    slots = {"m": np.zeros((vocab, dim), np.float32)}
    if spec.kind == "adam":
        slots["v"] = np.zeros((vocab, dim), np.float32)
        slots["t"] = np.zeros((vocab,), np.float32)
    return slots


def oracle_apply(table: np.ndarray, slots: dict, keys, grads,
                 spec: OptimizerSpec) -> tuple:
    """ONE fused update applied to the DENSE single-host table: the
    bit-identity oracle.  Runs the exact fused program the shards run
    (same scatter, same elementwise step, GLOBAL keys, no padding),
    so any divergence on a sharded path is the sharding's fault, not
    a reimplementation's.  Returns (table', slots') as numpy; inputs
    are not mutated."""
    keys = np.asarray(keys, np.int64)
    grads = np.asarray(grads, np.float32)
    if grads.shape != (keys.shape[0], table.shape[1]):
        raise ValueError(f"grads shape {grads.shape} != "
                         f"({keys.shape[0]}, {table.shape[1]})")
    valid = np.ones((keys.shape[0],), np.float32)
    fn = fused_apply(spec.kind)
    # the fused program DONATES its state inputs — hand it fresh device
    # copies so the caller's arrays stay intact ("inputs are not
    # mutated" above is a promise)
    import jax.numpy as jnp
    tbl = jnp.array(np.asarray(table, np.float32))
    sl = {k: jnp.array(np.asarray(v, np.float32))
          for k, v in slots.items()}
    if spec.kind == "sgdm":
        rows, m = fn(tbl, sl["m"], keys, grads, valid,
                     spec.lr, spec.momentum)
        return np.asarray(rows), {"m": np.asarray(m)}
    rows, m, v, t = fn(tbl, sl["m"], sl["v"], sl["t"],
                       keys, grads, valid, spec.lr, spec.beta1,
                       spec.beta2, spec.eps)
    return np.asarray(rows), {"m": np.asarray(m), "v": np.asarray(v),
                              "t": np.asarray(t)}
