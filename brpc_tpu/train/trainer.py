"""DataParallelTrainer — N workers training THROUGH the PS wire
(ISSUE 17).

The training loop is the seed example (examples/embedding_server.py)
grown into a real multi-worker trainer: every gather rides
``PS.Lookup`` (batched, tensorframe wire), every sparse gradient rides
``PS.Update`` carrying an :class:`~brpc_tpu.train.OptimizerSpec` so
the scatter AND the momentum/Adam slot step run fused ON the shard
(mode="wire"), dense parameters live in the service (``Pull``/``Push``
per step), and a periodic Pull-based eval proves loss decreases
through the service — the model the trainer ever sees is the one the
shards hold.

Worker coordination is BOUNDED STALENESS: worker w may start step s
only while ``s - min(steps completed by any worker) <= max_lag`` —
``max_lag=0`` is synchronous lockstep (a barrier per step), larger
lags trade gradient staleness for stall immunity.  The gate is a
condition variable over the progress vector, so a dead worker is
excused (marked complete) rather than wedging the fleet.

Update waves heal like any PS client: a failed wave re-issues with its
``update_token``, so partitions that already applied DEDUP — the fused
optimizer's applied-id discipline means a retried wave can never
double-step momentum.  Fault site ``train.update_wave`` injects wave
failures (chaos scenario 18 kills a live shard instead).

``mode="pull_compute_push"`` is the bench baseline the fused path is
measured against: optimizer slots live AT THE TRAINER (host numpy),
each wave computes the slot step host-side and ships the resulting
row DELTAS as a plain scatter-add — the classic parameter-server
shape "RPC Considered Harmful" argues against.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from brpc_tpu import errors, fault
from brpc_tpu.bvar import Adder
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.train.optimizer import OptimizerSpec

WAVES = Adder("train_waves")
WAVE_RETRIES = Adder("train_wave_retries")
EVALS = Adder("train_evals")

MODES = ("wire", "pull_compute_push")


class DataParallelTrainer:
    """N worker threads pulling minibatches, computing grads locally,
    and streaming PS.Update waves under bounded-staleness gating."""

    def __init__(self, client, cfg=None, *, n_workers: int = 2,
                 steps: int = 8,
                 optimizer: Optional[OptimizerSpec] = None,
                 mode: str = "wire", max_lag: int = 1,
                 sync: bool = False, lr_dense: float = 0.5,
                 eval_every: int = 0, wave_max_retry: int = 4,
                 retry_backoff_s: float = 0.05, arbiter=None,
                 seed: int = 0, name: str = "trainer"):
        import jax
        import jax.numpy as jnp
        from brpc_tpu.models.parameter_server import (PSConfig, _block,
                                                      make_example_batch)
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        self.client = client
        self.cfg = cfg or PSConfig(
            vocab=client.vocab, d_model=client.dim,
            d_ff=2 * client.dim, n_layers=2, seq=8, batch=4)
        if self.cfg.vocab != client.vocab or \
                self.cfg.d_model != client.dim:
            raise ValueError(
                f"cfg ({self.cfg.vocab}x{self.cfg.d_model}) does not "
                f"match the client's table "
                f"({client.vocab}x{client.dim})")
        self.n_workers = int(n_workers)
        self.steps = int(steps)
        self.optimizer = optimizer or OptimizerSpec(
            "sgdm", lr=0.5, momentum=0.5)
        self.mode = mode
        self.max_lag = 0 if sync else int(max_lag)
        self.sync = bool(sync) or self.max_lag == 0
        self.lr_dense = float(lr_dense)
        self.eval_every = int(eval_every)
        self.wave_max_retry = int(wave_max_retry)
        self.retry_backoff_s = float(retry_backoff_s)
        self.arbiter = arbiter
        self.seed = int(seed)
        self.name = str(name)
        self._jax, self._jnp = jax, jnp
        self._make_batch = make_example_batch

        # bounded-staleness gate state
        self._cv = threading.Condition()
        self._progress = [0] * self.n_workers
        self._stop = False
        self._errors: list = []
        self._mu = InstrumentedLock("train.trainer")
        self.n_waves = 0
        self.n_wave_retries = 0
        self.n_io_retries = 0
        self.n_paced = 0
        self.loss_history: list = []
        self.step_losses: list = []

        # pull-compute-push mode's HOST-side slots (the baseline the
        # fused co-located path is benched against)
        self._host_slots: dict = {}

        # the seed model's loss over gathered rows + dense params —
        # jitted ONCE here (never per call)
        def loss_from_rows(rows, dense, targets):
            x = rows.astype(jnp.bfloat16)

            def body(x, layer):
                wqk, wup, wdown = layer
                return _block(x, wqk, wup, wdown), None

            d = {k: v.astype(jnp.bfloat16) for k, v in dense.items()}
            x, _ = jax.lax.scan(body, x,
                                (d["w_qk"], d["w_up"], d["w_down"]))
            logits = (x @ d["w_out"]).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
            return -jnp.mean(ll)

        self._loss_fn = jax.jit(loss_from_rows)
        self._grad_fn = jax.jit(
            jax.value_and_grad(loss_from_rows, argnums=(0, 1)))
        self._dense_names: list = []
        # fixed held-out eval batch (its own key, never trained on)
        self._eval_tokens, self._eval_targets = make_example_batch(
            self.cfg, key=jax.random.PRNGKey(self.seed + 104729))

    # ---- model seeding (the fleet holds the model; seed it first) ----

    @staticmethod
    def model_init(cfg, seed: int = 0) -> tuple:
        """(embed0, dense0) for seeding the shard fleet: build shards
        with ``table=embed0`` and let :meth:`seed_dense` push dense."""
        import jax
        from brpc_tpu.models.parameter_server import init_params
        params = init_params(cfg, key=jax.random.PRNGKey(seed))
        embed = np.asarray(params["embed"], np.float32)
        dense = {k: np.asarray(v, np.float32)
                 for k, v in params.items() if k != "embed"}
        return embed, dense

    def seed_dense(self, dense: dict) -> None:
        """Push the dense (non-embedding) params into the service —
        after this the trainer has NO local copy of the model."""
        for k, v in dense.items():
            self.client.push(k, np.asarray(v, np.float32))
        self._dense_names = sorted(dense)

    def _clone_client(self, w: int):
        """One PSClient per worker: read-your-writes is a PER-CLIENT
        contract (a lookup must observe every update THIS client got
        acked), so workers sharing one client would count each other's
        in-flight writes as stale reads.  update_ids come from a
        module-global sequence, so clones never collide."""
        c = self.client
        if getattr(c, "_pc", None) is None:
            return c        # lowered/ICI backend: no wire, no clone
        from brpc_tpu.psserve import PSClient
        return PSClient(c._pc, vocab=c.vocab, dim=c.dim,
                        n_shards=c.n_shards, timeout_ms=c.timeout_ms,
                        max_retry=c.max_retry, serializer=c.serializer,
                        ici=c._ici_mode, table_name=c.table_name,
                        name=f"{c.name}_w{w}")

    # ---- bounded-staleness gate ----

    def _gate(self, w: int, s: int) -> None:
        with self._cv:
            while not self._stop and \
                    s - min(self._progress) > self.max_lag:
                self._cv.wait(0.2)

    def _advance(self, w: int) -> None:
        with self._cv:
            self._progress[w] += 1
            self._cv.notify_all()

    def _excuse(self, w: int) -> None:
        """A dead worker must not wedge the gate: mark it complete."""
        with self._cv:
            self._progress[w] = self.steps
            self._cv.notify_all()

    # ---- the update wave ----

    def _io_retry(self, fn):
        """Bounded retry with backoff for the worker's NON-wave I/O
        (pull/lookup/push).  The wave already heals itself via
        update_token replay; the read path needs the same patience so a
        shard restart mid-run (chaos scenario 18) costs a few retries,
        not a dead worker.  Reads are idempotent and pushes carry their
        own update_id through the partition channel's retry, so a
        replay here never double-applies."""
        for attempt in range(self.wave_max_retry + 1):
            try:
                return fn()
            except errors.RpcError:
                with self._mu:
                    self.n_io_retries += 1
                if attempt >= self.wave_max_retry:
                    raise
                time.sleep(self.retry_backoff_s * (attempt + 1))

    def _send_wave(self, cli, w: int, s: int, keys: np.ndarray,
                   grads: np.ndarray) -> None:
        """One PS.Update wave with partition-retry healing: a failed
        fan-out replays the SAME logical update via its update_token,
        so partitions that already applied dedup instead of
        double-stepping momentum."""
        tok = None
        for attempt in range(self.wave_max_retry + 1):
            if self.arbiter is not None:
                paced = self.arbiter.admit_wave()
                if paced:
                    with self._mu:
                        self.n_paced += 1
            try:
                if fault.ENABLED and fault.hit(
                        "train.update_wave", worker=w, step=s,
                        attempt=attempt) is not None:
                    raise errors.RpcError(
                        errors.EINTERNAL,
                        "injected train.update_wave fault")
                if self.mode == "wire":
                    cli.update(keys, grads, update_token=tok,
                               optimizer=self.optimizer)
                else:
                    self._pull_compute_push(cli, keys, grads, tok)
                with self._mu:
                    self.n_waves += 1
                WAVES.add(1)
                return
            except errors.RpcError as e:
                # keep (or adopt) the token: partitions that acked the
                # failed attempt will dedup the replay
                tok = getattr(e, "update_token", tok)
                with self._mu:
                    self.n_wave_retries += 1
                WAVE_RETRIES.add(1)
                if attempt >= self.wave_max_retry:
                    raise
                time.sleep(self.retry_backoff_s * (attempt + 1))

    def _pull_compute_push(self, cli, keys, grads, tok) -> None:
        """The baseline wave: slot math at the HOST, deltas on the
        wire.  Duplicate keys accumulate first (what the fused path's
        scatter does), then one plain scatter-add update ships the
        stepped rows' deltas."""
        spec = self.optimizer
        uniq, inv = np.unique(keys, return_inverse=True)
        g_acc = np.zeros((uniq.shape[0], self.client.dim), np.float32)
        np.add.at(g_acc, inv, grads)
        with self._mu:
            hs = self._host_slots
            if "m" not in hs:
                hs["m"] = np.zeros((self.client.vocab, self.client.dim),
                                   np.float32)
                if spec.kind == "adam":
                    hs["v"] = np.zeros_like(hs["m"])
                    hs["t"] = np.zeros((self.client.vocab,), np.float32)
            if spec.kind == "sgdm":
                m = spec.momentum * hs["m"][uniq] + g_acc
                hs["m"][uniq] = m
                delta = -spec.lr * m
            else:
                t = hs["t"][uniq] + 1.0
                m = spec.beta1 * hs["m"][uniq] + \
                    (1.0 - spec.beta1) * g_acc
                v = spec.beta2 * hs["v"][uniq] + \
                    (1.0 - spec.beta2) * g_acc * g_acc
                hs["t"][uniq], hs["m"][uniq], hs["v"][uniq] = t, m, v
                delta = -spec.lr * (m / (1.0 - spec.beta1 ** t[:, None])) \
                    / (np.sqrt(v / (1.0 - spec.beta2 ** t[:, None]))
                       + spec.eps)
        cli.update(uniq, delta.astype(np.float32), update_token=tok)

    # ---- eval (Pull-based: the model scored is the SERVICE's) ----

    def eval_loss(self) -> float:
        jnp = self._jnp
        dense = {k: jnp.asarray(self.client.pull(k))
                 for k in self._dense_names}
        keys = np.asarray(self._eval_tokens).reshape(-1).astype(np.int64)
        rows = self.client.lookup(keys).reshape(
            self.cfg.batch, self.cfg.seq, self.cfg.d_model)
        loss = float(self._loss_fn(jnp.asarray(rows), dense,
                                   self._eval_targets))
        with self._mu:
            self.loss_history.append(loss)
        EVALS.add(1)
        return loss

    # ---- the worker loop ----

    def _worker(self, w: int) -> None:
        jax, jnp = self._jax, self._jnp
        cli = self._worker_clients[w]
        try:
            for s in range(self.steps):
                self._gate(w, s)
                if self._stop:
                    return
                tokens, targets = self._make_batch(
                    self.cfg, key=jax.random.PRNGKey(
                        self.seed * 7919 + w * 104729 + s))
                keys = np.asarray(tokens).reshape(-1).astype(np.int64)
                dense = {k: jnp.asarray(self._io_retry(
                    lambda k=k: cli.pull(k)))
                    for k in self._dense_names}
                rows = self._io_retry(lambda: cli.lookup(keys)).reshape(
                    self.cfg.batch, self.cfg.seq, self.cfg.d_model)
                loss, (g_rows, g_dense) = self._grad_fn(
                    jnp.asarray(rows), dense, targets)
                self._send_wave(
                    cli, w, s, keys,
                    np.asarray(g_rows, np.float32).reshape(
                        -1, self.cfg.d_model))
                for k in self._dense_names:
                    self._io_retry(lambda k=k: cli.push(
                        k, np.asarray(-self.lr_dense * g_dense[k],
                                      np.float32)))
                with self._mu:
                    self.step_losses.append((w, s, float(loss)))
                self._advance(w)
                if self.eval_every and w == 0 and \
                        (s + 1) % self.eval_every == 0:
                    self.eval_loss()
        except BaseException as e:
            with self._mu:
                self._errors.append((w, e))
            self._excuse(w)

    def run(self) -> dict:
        """Train to completion; returns the report.  Raises the first
        worker error AFTER every worker has stopped (the gate excuses
        dead workers, so the rest drain normally)."""
        if not self._dense_names:
            raise RuntimeError("call seed_dense() before run() — the "
                               "service must hold the dense params")
        t0 = time.monotonic()
        self.eval_loss()        # the "before" point of the loss proof
        self._worker_clients = [self._clone_client(w)
                                for w in range(self.n_workers)]
        threads = [threading.Thread(
            target=self._worker, args=(w,),
            name=f"{self.name}_w{w}", daemon=True)
            for w in range(self.n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.eval_loss()
        elapsed = time.monotonic() - t0
        with self._mu:
            if self._errors:
                raise self._errors[0][1]
            return {
                "mode": self.mode,
                "optimizer": self.optimizer.to_wire(),
                "workers": self.n_workers,
                "steps": self.steps,
                "steps_done": int(sum(self._progress)),
                "waves": self.n_waves,
                "wave_retries": self.n_wave_retries,
                "io_retries": self.n_io_retries,
                "paced_waves": self.n_paced,
                "max_lag": self.max_lag,
                "sync": self.sync,
                "elapsed_s": elapsed,
                "updates_per_s": self.n_waves / max(elapsed, 1e-9),
                "loss_first": self.loss_history[0],
                "loss_final": self.loss_history[-1],
                "loss_history": list(self.loss_history),
                "stale_reads": self.stale_reads(),
            }

    def stale_reads(self) -> int:
        """RYW violations summed across the shared client and every
        per-worker clone (the chaos-18 invariant reads this)."""
        clis = {id(self.client): self.client}
        for c in getattr(self, "_worker_clients", ()):
            clis[id(c)] = c
        return sum(c.n_stale_reads for c in clis.values())

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "mode": self.mode,
                "waves": self.n_waves,
                "wave_retries": self.n_wave_retries,
                "io_retries": self.n_io_retries,
                "paced_waves": self.n_paced,
                "progress": list(self._progress),
                "evals": len(self.loss_history),
            }
