"""Async echo (reference example/asynchronous_echo_c++): issue the call
with a done-callback, do other work, never block a thread."""
import os, sys, threading
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class EchoService(brpc.Service):
    @brpc.method(request="json", response="json")
    def Echo(self, cntl, req):
        return {"echo": req["msg"]}


def main():
    server = brpc.Server()
    server.add_service(EchoService())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}")
    done = threading.Event()

    def on_done(cntl):
        if cntl.failed():
            print("failed:", cntl.error_text)
        else:
            print(f"async response: {cntl.response} "
                  f"({cntl.latency_us}us)")
        done.set()

    ch.call("EchoService", "Echo", {"msg": "fire-and-forget"},
            serializer="json", done=on_done)
    print("call issued; main thread free to do other work...")
    assert done.wait(5)
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
