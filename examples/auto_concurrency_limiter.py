"""Auto concurrency limiter demo (reference
example/auto_concurrency_limiter): the server sheds load with ELIMIT once
the gradient limiter decides more concurrency only adds queueing."""
import os, sys, threading, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu import errors


class Work(brpc.Service):
    @brpc.method(request="json", response="json", max_concurrency="auto")
    def Do(self, cntl, req):
        time.sleep(0.005)
        return {"ok": True}


def main(threads=32, seconds=3.0):
    server = brpc.Server()
    server.add_service(Work())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=2000,
                      max_retry=0)
    ok = [0] * threads
    rejected = [0] * threads
    stop = time.monotonic() + seconds

    def worker(i):
        while time.monotonic() < stop:
            try:
                ch.call_sync("Work", "Do", {}, serializer="json")
                ok[i] += 1
            except errors.RpcError as e:
                if e.code == errors.ELIMIT:
                    rejected[i] += 1
                    time.sleep(0.002)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    st = server.method_statuses[("Work", "Do")]
    print(f"served={sum(ok)} rejected={sum(rejected)} "
          f"limit settled at {st.limiter.max_concurrency() if st.limiter else 'n/a'}")
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
