"""Backup request demo (reference example/backup_request_c++): a second
attempt races after backup_request_ms; the first response wins, so a slow
replica can't hold a call hostage."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class Sleepy(brpc.Service):
    NAME = "Sleepy"

    def __init__(self, tag, delay_s):
        self._tag, self._delay = tag, delay_s

    @brpc.method(request="json", response="json")
    def Get(self, cntl, req):
        time.sleep(self._delay)
        return {"from": self._tag}


def main():
    slow = brpc.Server().add_service(Sleepy("slow-replica", 1.0))
    fast = brpc.Server().add_service(Sleepy("fast-replica", 0.0))
    slow.start("127.0.0.1", 0)
    fast.start("127.0.0.1", 0)
    ch = brpc.Channel(
        f"list://127.0.0.1:{slow.port},127.0.0.1:{fast.port}",
        options=brpc.ChannelOptions(timeout_ms=5000, load_balancer="rr",
                                    backup_request_ms=75, max_retry=1))
    for i in range(4):
        t0 = time.monotonic()
        r = ch.call_sync("Sleepy", "Get", {}, serializer="json")
        print(f"call {i}: answered by {r['from']:13s} in "
              f"{(time.monotonic()-t0)*1e3:.0f}ms")
    for s in (slow, fast):
        s.stop()
        s.join()


if __name__ == "__main__":
    main()
