"""Isolated per-service worker pools (reference example/bthread_tag_echo_c++,
bthread tags task_control.h:90-147): a slow service on its own tagged pool
cannot starve the latency-sensitive one."""
import os, sys, time, threading
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class Fast(brpc.Service):
    @brpc.method(request="raw", response="raw")
    def Ping(self, cntl, req):
        return b"pong"


class Slow(brpc.Service):
    @brpc.method(request="raw", response="raw")
    def Crunch(self, cntl, req):
        time.sleep(0.2)
        return b"done"


def main():
    server = brpc.Server()
    server.add_service(Fast())
    server.add_service(Slow(), tag="batch", tag_workers=2)
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=5000)

    # flood the slow (tagged) service
    slow_cntls = [ch.call("Slow", "Crunch", b"") for _ in range(8)]
    # fast service keeps answering with low latency meanwhile
    t0 = time.monotonic()
    lat = []
    for _ in range(20):
        s = time.monotonic()
        assert ch.call_sync("Fast", "Ping", b"") == b"pong"
        lat.append((time.monotonic() - s) * 1e3)
    print(f"fast service p_max={max(lat):.1f} ms while 8 slow calls "
          f"(0.2s each, 2 tagged workers) crunch in the background")
    for c in slow_cntls:
        c.join()
        assert c.response == b"done"
    print(f"slow calls drained in {time.monotonic()-t0:.1f}s on their own pool")
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
