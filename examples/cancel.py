"""Cancel an in-flight RPC (reference example/cancel_c++): StartCancel
completes the call immediately with ECANCELED; the late server response
is dropped as a stale attempt."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu import errors


class Slow(brpc.Service):
    @brpc.method(request="raw", response="raw")
    def Sleep(self, cntl, req):
        time.sleep(2.0)
        return b"too late"


def main():
    server = brpc.Server()
    server.add_service(Slow())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=10_000)
    cntl = ch.call("Slow", "Sleep", b"")
    time.sleep(0.1)
    t0 = time.monotonic()
    assert cntl.cancel()
    cntl.join()
    assert cntl.error_code == errors.ECANCELED, cntl.error_code
    print(f"canceled after {1e3*(time.monotonic()-t0):.1f} ms "
          f"(server handler still sleeping): E{cntl.error_code} "
          f"{cntl.error_text}")
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
