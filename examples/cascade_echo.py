"""Cascade (reference example/cascade_echo_c++): service A calls service B
from inside its handler; rpcz spans nest across the hop via trace ids."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu import rpcz


class Backend(brpc.Service):
    @brpc.method(request="json", response="json")
    def Echo(self, cntl, req):
        return {"from": "backend", "msg": req["msg"]}


class Frontend(brpc.Service):
    def __init__(self, backend_addr):
        self._ch = brpc.Channel(backend_addr)

    @brpc.method(request="json", response="json")
    def Echo(self, cntl, req):
        inner = self._ch.call_sync("Backend", "Echo", req,
                                   serializer="json")
        return {"from": "frontend", "inner": inner}


def main():
    rpcz.set_enabled(True)
    backend = brpc.Server()
    backend.add_service(Backend())
    backend.start("127.0.0.1", 0)
    front = brpc.Server()
    front.add_service(Frontend(f"127.0.0.1:{backend.port}"))
    front.start("127.0.0.1", 0)

    ch = brpc.Channel(f"127.0.0.1:{front.port}")
    out = ch.call_sync("Frontend", "Echo", {"msg": "hi"}, serializer="json")
    print("cascaded response:", out)
    spans = rpcz.recent_spans(10)
    print(f"rpcz recorded {len(spans)} spans across the cascade "
          f"(browse /rpcz on either console)")
    for s in front, backend:
        s.stop(); s.join()


if __name__ == "__main__":
    main()
