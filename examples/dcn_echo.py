"""Cross-process device RPC over the DCN groundwork (ici/dcn.py;
reference analog: RdmaEndpoint's TCP-assisted handshake,
rdma_endpoint.h:112-115).

Spawns a CHILD PROCESS with its own jax runtime serving a device
service, handshakes topologies over TCP, and calls the child's chip 3
from this process.

Run:  python examples/dcn_echo.py
"""
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.join(os.path.dirname(__file__), "..")

CHILD = f"""
import sys
sys.path.insert(0, {REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from brpc_tpu.ici.channel import register_device_service
from brpc_tpu.rpc.server import Server

register_device_service("Mat", "Scale", lambda x: x * 3.0)
srv = Server(enable_dcn=True)
srv.start("127.0.0.1", 0)
print(f"PORT={{srv.port}}", flush=True)
srv.run_until_interrupt()
"""


def main():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    child = subprocess.Popen([sys.executable, "-c", CHILD],
                             stdout=subprocess.PIPE, env=env, text=True)
    port = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and port is None:
        line = child.stdout.readline()
        if line.startswith("PORT="):
            port = int(line.strip().split("=")[1])
    assert port, "child never came up"

    import jax
    jax.config.update("jax_platforms", "cpu")
    from brpc_tpu.ici.dcn import DcnChannel

    ch = DcnChannel(f"ici://127.0.0.1:{port}/3")
    topo = ch.handshake()
    mode = "zero-copy fabric" if topo.get("xfer") else "host fallback"
    print(f"peer pid {topo['pid']}: {len(topo['devices'])} "
          f"{topo['platform']} devices; data plane: {mode} "
          f"(xfer addr {topo.get('xfer')})")
    from brpc_tpu.rpc import serialization
    enc0 = serialization.tensor_host_encodes.get_value()
    out = ch.call_sync("Mat", "Scale",
                       jax.numpy.arange(8, dtype=jax.numpy.float32))
    hc = serialization.tensor_host_encodes.get_value() - enc0
    print(f"Scale on remote chip 3 -> {list(map(float, out))} "
          f"({hc} host tensor encodes on the data path)")
    child.terminate()
    child.wait(10)


if __name__ == "__main__":
    main()
