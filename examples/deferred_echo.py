"""Deferred (asynchronous) server handlers — the done-Closure contract
(reference: svc->CallMethod(..., done) in baidu_rpc_protocol.cpp:398;
see README "the blocking model").

The handler calls cntl.defer() and returns immediately; a worker thread
completes the RPC later.  In-flight RPCs park as closures, not threads —
this demo holds 1000 concurrent calls open at once on ordinary pools.

Run:  python examples/deferred_echo.py
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu.rpc.controller import Controller


class BatchEcho(brpc.Service):
    """Parks every request; a ticker releases them in batches — the
    shape of a server that waits on an external event (a device step,
    an upstream call) without holding worker threads."""

    def __init__(self):
        self.parked = []
        self.mu = threading.Lock()

    @brpc.method(request="raw", response="raw")
    def Echo(self, cntl, req):
        done = cntl.defer()
        with self.mu:
            self.parked.append((done, req))


def main():
    svc = BatchEcho()
    server = brpc.Server()
    server.add_service(svc)
    server.start("127.0.0.1", 0)
    print(f"server on 127.0.0.1:{server.port}")

    def releaser():
        while True:
            time.sleep(0.05)
            with svc.mu:
                batch, svc.parked = svc.parked, []
            for done, req in batch:
                done(b"deferred:" + req)

    threading.Thread(target=releaser, daemon=True).start()

    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=10_000)
    n = 1000
    got = []
    t0 = time.monotonic()
    for i in range(n):
        ch.call(
            "BatchEcho", "Echo", str(i).encode(),
            cntl=Controller(timeout_ms=10_000),
            done=lambda c: got.append(c))
    while len(got) < n and time.monotonic() - t0 < 30:
        time.sleep(0.01)
    ok = sum(1 for c in got if c.error_code == 0)
    print(f"{ok}/{n} deferred RPCs completed in "
          f"{time.monotonic() - t0:.2f}s "
          f"(process threads: {threading.active_count()})")
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
