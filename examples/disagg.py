"""Disaggregated prefill/decode across two REAL processes
(brpc_tpu/migrate; ISSUE 7).

Spawns a DECODE process (KV store + DecodeEngine + the migration
splice) and a PREFILL process (KV store + PrefillReplica shipping
pages to the decode address), then drives ONE generation across the
split from this process: the DisaggCoordinator runs Prefill on the
prefill process — whose finished pages stream over the `_kvmig` plane
— and streams the tokens from the decode process, which prefix-hits
the migrated pages instead of re-prefilling.

Run:  python examples/disagg.py
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.join(os.path.dirname(__file__), "..")

DECODE = f"""
import sys
sys.path.insert(0, {REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
from brpc_tpu.kvcache import KVCacheStore
from brpc_tpu.migrate import register_disagg_decode
from brpc_tpu.rpc.server import Server
from brpc_tpu.serving import DecodeEngine

@jax.jit
def step(tokens, positions, pages):
    return (tokens * 7 + positions) % 997

store = KVCacheStore(page_tokens=4, page_bytes=256, max_blocks=32,
                     name="decode")
engine = DecodeEngine(step, num_slots=4, store=store,
                      max_pages_per_slot=32, name="decode")
srv = Server(enable_dcn=True)
register_disagg_decode(srv, store, engine)
srv.start("127.0.0.1", 0)
print(f"PORT={{srv.port}}", flush=True)
srv.run_until_interrupt()
"""

PREFILL = f"""
import sys
sys.path.insert(0, {REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
from brpc_tpu.kvcache import KVCacheStore
from brpc_tpu.migrate import register_disagg_prefill
from brpc_tpu.rpc.server import Server

store = KVCacheStore(page_tokens=4, page_bytes=256, max_blocks=32,
                     name="prefill")
srv = Server(enable_dcn=True)
register_disagg_prefill(srv, store, sys.argv[1])
srv.start("127.0.0.1", 0)
print(f"PORT={{srv.port}}", flush=True)
srv.run_until_interrupt()
"""


def spawn(code, *args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, "-c", code, *args],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, env=env, text=True)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT="):
            return proc, int(line.strip().split("=", 1)[1])
        if proc.poll() is not None:
            raise RuntimeError("child died during startup")
    proc.kill()
    raise RuntimeError("child never printed its port")


def main():
    print("starting decode process...")
    dec, dec_port = spawn(DECODE)
    print(f"  decode on 127.0.0.1:{dec_port}")
    print("starting prefill process (shipping pages to decode)...")
    pre, pre_port = spawn(PREFILL, f"127.0.0.1:{dec_port}")
    print(f"  prefill on 127.0.0.1:{pre_port}")
    try:
        from brpc_tpu.migrate import DisaggCoordinator
        co = DisaggCoordinator(f"127.0.0.1:{pre_port}",
                               f"127.0.0.1:{dec_port}")
        ta, tb = co.pair()
        print(f"paired: prefill pid {ta['pid']}, decode pid {tb['pid']}")
        prompt = list(range(50, 63))
        print(f"generate({prompt}, 8) across the split:")
        out = co.generate(prompt, 8,
                          emit=lambda t: print(f"  token {t}"))
        info = out["prefill"]
        print(f"prefill handoff: {json.dumps(info)}")
        print(f"tokens: {out['tokens']}")
        assert out["error"] is None
        assert not info["recompute_fallback"], \
            "page stream fell back to recompute"
        print(f"OK — {info['migrated_pages']} pages moved process-to-"
              f"process; the decode side never re-prefilled them")
    finally:
        pre.terminate()
        dec.terminate()
        pre.wait(timeout=10)
        dec.wait(timeout=10)


if __name__ == "__main__":
    main()
