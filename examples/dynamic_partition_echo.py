"""DynamicPartitionChannel (reference example/dynamic_partition_echo_c++):
two partition schemes share one naming service; traffic splits by scheme
capacity and migrates when servers are re-tagged."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class Part(brpc.Service):
    NAME = "Part"
    def __init__(self, label): self.label = label
    @brpc.method(request="raw", response="raw")
    def Which(self, cntl, req): return self.label.encode()


class Concat(brpc.ResponseMerger):
    def merge(self, results): return b"|".join(sorted(results))


def main():
    servers, nodes = [], []
    for cnt in (2, 4):
        for idx in range(cnt):
            s = brpc.Server()
            s.add_service(Part(f"{cnt}way:{idx}"))
            s.start("127.0.0.1", 0)
            servers.append(s)
            nodes.append(f"127.0.0.1:{s.port} {idx}/{cnt}")
    dyn = brpc.DynamicPartitionChannel(response_merger=Concat())
    dyn.init("list://" + ",".join(nodes))
    print("schemes (partition_count -> servers):", dyn.scheme_counts)
    picks = {}
    for _ in range(20):
        out = dyn.call_sync("Part", "Which", b"").decode()
        n = out.count("|") + 1
        picks[n] = picks.get(n, 0) + 1
    print("calls per scheme (weighted by capacity):", picks)
    dyn.stop()
    for s in servers:
        s.stop(); s.join()


if __name__ == "__main__":
    main()
