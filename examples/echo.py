"""Echo — the hello-world demo (reference example/echo_c++).

Run:  python examples/echo.py
Starts a server with an EchoService and calls it through a Channel; then
leaves the server up for 2s so you can poke the console:
    curl 127.0.0.1:<port>/status
"""
import sys, os, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class EchoService(brpc.Service):
    @brpc.method(request="json", response="json")
    def Echo(self, cntl, req):
        return {"message": req["message"]}


def main():
    server = brpc.Server()
    server.add_service(EchoService())
    server.start("127.0.0.1", 0)
    print(f"EchoServer on 127.0.0.1:{server.port} "
          f"(console: http://127.0.0.1:{server.port}/)")

    channel = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=1000)
    cntl = brpc.Controller()
    resp = channel.call_sync("EchoService", "Echo",
                             {"message": "hello tpu-rpc"},
                             serializer="json", cntl=cntl)
    print(f"response: {resp}  latency={cntl.latency_us}us "
          f"from {cntl.remote_side}")
    time.sleep(2)
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
