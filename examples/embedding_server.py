"""Train the seed parameter-server model THROUGH the sharded embedding
service (ISSUE 12): the embedding table lives in N EmbeddingShardServer
partitions behind real RPC servers, the trainer routes every gather and
sparse gradient through PSClient's PartitionChannel fan-out, and dense
params round-trip Pull/Push.  Loss goes down; the table the shards hold
is the one being trained.

    python examples/embedding_server.py [n_shards]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("BRPC_FORCE_CPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import brpc_tpu as brpc
from brpc_tpu.models.parameter_server import (PSConfig, _block,
                                              init_params,
                                              make_example_batch)
from brpc_tpu.psserve import (EmbeddingShardServer, PSClient,
                              register_psserve, unregister_psserve)
from brpc_tpu.rpc.combo_channels import PartitionChannel


def main(n_shards: int = 4):
    cfg = PSConfig(vocab=128, d_model=32, d_ff=64, n_layers=2, seq=16,
                   batch=8)
    params = init_params(cfg, key=jax.random.PRNGKey(0))
    embed0 = np.asarray(params["embed"], np.float32)

    # ---- the service: N shards over real loopback RPC servers ----
    servers, svcs, shards = [], [], []
    pc = PartitionChannel(n_shards)
    for i in range(n_shards):
        sh = EmbeddingShardServer(i, n_shards, cfg.vocab, cfg.d_model,
                                  table=embed0, name="example")
        shards.append(sh)
        s = brpc.Server()
        svcs.append(register_psserve(s, sh, max_delay_us=500,
                                     name=f"example_{i}"))
        s.start("127.0.0.1", 0)
        servers.append(s)
        pc.add_partition(i, brpc.Channel(f"127.0.0.1:{s.port}",
                                         timeout_ms=10_000))
    cli = PSClient(pc, vocab=cfg.vocab, dim=cfg.d_model)
    print(f"serving {cfg.vocab}x{cfg.d_model} embedding over "
          f"{n_shards} shards "
          f"({', '.join(str(sh.n_rows) + ' rows' for sh in shards)})")

    # dense (non-embedding) params live in the service too: push the
    # initial values, pull the working copy (owner = name hash)
    dense = {k: v for k, v in params.items() if k != "embed"}
    for k, v in dense.items():
        cli.push(k, np.asarray(v, np.float32))
    dense = {k: jnp.asarray(cli.pull(k)) for k in dense}
    print(f"dense params pushed + pulled through PS.Pull/PS.Push: "
          f"{sorted(dense)}")

    # ---- loss as a function of GATHERED rows + dense params ----
    def loss_from_rows(rows, dense, targets):
        x = rows.astype(jnp.bfloat16)          # [B, S, D]

        def body(x, layer):
            wqk, wup, wdown = layer
            return _block(x, wqk, wup, wdown), None

        d = {k: v.astype(jnp.bfloat16) for k, v in dense.items()}
        x, _ = jax.lax.scan(body, x, (d["w_qk"], d["w_up"], d["w_down"]))
        logits = (x @ d["w_out"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return -jnp.mean(ll)

    grad_fn = jax.jit(jax.value_and_grad(loss_from_rows, argnums=(0, 1)))
    tokens, targets = make_example_batch(cfg, key=jax.random.PRNGKey(1))
    keys = np.asarray(tokens).reshape(-1).astype(np.int64)
    lr = 0.5

    # ---- the training loop: every gather and every sparse gradient
    # rides the RPC service ----
    for step in range(8):
        rows = cli.lookup(keys).reshape(cfg.batch, cfg.seq, cfg.d_model)
        loss, (g_rows, g_dense) = grad_fn(jnp.asarray(rows), dense,
                                          targets)
        # sparse scatter-add through PS.Update: duplicate tokens in the
        # batch accumulate, exactly like the dense .at[].add would
        cli.update(keys, np.asarray(-lr * g_rows.reshape(-1, cfg.d_model),
                                    np.float32))
        dense = {k: v - lr * g_dense[k] for k, v in dense.items()}
        print(f"  step {step}: loss {float(loss):.4f}  "
              f"(shard versions {[sh.version for sh in shards]})")

    # push the trained dense params back so the service owns the whole
    # model again
    for k, v in dense.items():
        cli.push(k, np.asarray(v - jnp.asarray(cli.pull(k)), np.float32))
    print(f"client stats: {cli.stats()}")
    print(f"shard 0 hot keys: {shards[0].hot_keys(5)}")

    for svc in svcs:
        unregister_psserve(svc)
    for s in servers:
        s.stop()
        s.join()
    cli.close()


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 4)
