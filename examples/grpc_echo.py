"""gRPC over HTTP/2 (reference example/grpc_c++): the same Server answers
gRPC clients on the same port as every other protocol — any stock gRPC
client that targets /<Service>/<Method> with application/grpc works."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class Greeter(brpc.Service):
    NAME = "helloworld.Greeter"

    @brpc.method(request="json", response="json")
    def SayHello(self, cntl, req):
        return {"message": f"Hello {req['name']}"}


def main():
    import json
    server = brpc.Server()
    server.add_service(Greeter())
    server.start("127.0.0.1", 0)
    ch = brpc.GrpcChannel(f"127.0.0.1:{server.port}")
    out = ch.call("helloworld.Greeter", "SayHello",
                  json.dumps({"name": "tpu"}).encode())
    print("grpc response:", json.loads(out))
    futs = [ch.acall("helloworld.Greeter", "SayHello",
                     json.dumps({"name": f"stream-{i}"}).encode())
            for i in range(8)]
    print("8 concurrent h2 streams:",
          [json.loads(f.result(5))["message"] for f in futs][:3], "...")
    ch.close()
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
