"""Server-streaming gRPC demo (reference example/grpc_c++ streaming role).

A handler returning an iterator streams one length-prefixed gRPC frame
per item; the client consumes messages as their frames arrive off the
open h2 stream.  Abandoning the iterator early RSTs the stream and the
server's generator stops.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu.rpc.h2 import GrpcChannel


class Market(brpc.Service):
    NAME = "demo.Market"

    @brpc.method(request="json", response="raw")
    def Watch(self, cntl, req):
        symbol = req.get("symbol", "TPU")

        def ticks():
            price = 100.0
            for i in range(req.get("n", 10)):
                price *= 1.0 + ((i * 2654435761) % 100 - 50) / 5000.0
                yield json.dumps({"symbol": symbol, "seq": i,
                                  "price": round(price, 2)}).encode()
                time.sleep(0.05)
        return ticks()


def main():
    server = brpc.Server()
    server.add_service(Market())
    server.start("127.0.0.1", 0)
    print(f"serving on 127.0.0.1:{server.port}")

    ch = GrpcChannel(f"127.0.0.1:{server.port}", timeout_ms=5000)
    print("watching demo.Market/Watch (full stream):")
    for msg in ch.call_stream("demo.Market", "Watch",
                              json.dumps({"symbol": "TPU", "n": 8}).encode()):
        print("  tick:", json.loads(msg))

    print("early cancel after 3 ticks:")
    for i, msg in enumerate(ch.call_stream(
            "demo.Market", "Watch",
            json.dumps({"symbol": "BIG", "n": 1000}).encode())):
        print("  tick:", json.loads(msg))
        if i == 2:
            break      # RST CANCEL: the server stops generating
    ch.close()
    server.stop()
    server.join()
    print("done")


if __name__ == "__main__":
    main()
