"""HTTP service + client (reference example/http_c++): custom handlers on
the console port, RESTful JSON bridge onto RPC methods, HttpChannel."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class Api(brpc.Service):
    @brpc.method(request="json", response="json")
    def Add(self, cntl, req):
        return {"sum": req["a"] + req["b"]}


def main():
    server = brpc.Server()
    server.add_service(Api())
    server.add_http_handler("/greet", lambda req: ("hello http\n",
                                                   "text/plain"))
    server.start("127.0.0.1", 0)
    h = brpc.HttpChannel(f"127.0.0.1:{server.port}")
    print("custom handler:", h.request("GET", "/greet").body.decode().strip())
    r = h.request("POST", "/Api/Add", '{"a": 40, "b": 2}',
                  headers={"Content-Type": "application/json"})
    print("RESTful bridge:", r.body.decode().strip())
    print("builtin console: /status ->",
          h.request("GET", "/health").body.decode().strip())
    h.close()
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
