"""ICI performance ladder (reference example/rdma_performance): per-size
transfer/echo bandwidth over the device fabric + the collective primitives
over the local mesh."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from brpc_tpu.ici import CollectiveGroup, TensorStream, get_mesh, link_stats


def ladder():
    print(f"devices: {jax.devices()}")
    dev = jax.devices()[-1]
    for size in (4096, 65536, 1 << 20, 1 << 24):
        n = max(128, size // 2)
        x = jnp.ones((n,), jnp.bfloat16)
        got = []
        ts = TensorStream(dev, consumer=got.append)
        reps = 8
        t0 = time.monotonic()
        for _ in range(reps):
            ts.write(x)
        ts.close(wait=True)
        dt = time.monotonic() - t0
        print(f"  {size:>10}B x{reps}: {reps*x.nbytes/dt/1e9:8.3f} GB/s "
              f"({dt/reps*1e6:8.1f} us/chunk)")


def collectives():
    mesh = get_mesh()
    g = CollectiveGroup(mesh)
    n = mesh.shape["chip"]
    x = jnp.arange(n * 1024, dtype=jnp.float32)
    for name, fn in (("ring_shift", lambda: g.ring_shift(x)),
                     ("all_gather", lambda: g.all_gather(x)),
                     ("all_reduce", lambda: g.all_reduce(x)),
                     ("reduce_scatter", lambda: g.reduce_scatter(x))):
        fn()  # compile
        t0 = time.monotonic()
        for _ in range(10):
            out = fn()
        jax.block_until_ready(out)
        print(f"  {name:15s}: {(time.monotonic()-t0)/10*1e6:8.1f} us/op "
              f"over {n} chip(s)")


if __name__ == "__main__":
    ladder()
    collectives()
    print("link stats:", {k: v for k, v in link_stats().items()
                          if k != "devices"})
