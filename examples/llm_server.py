"""Real model serving demo (ISSUE 10): a TransformerRunner — an actual
transformer whose K/V live in the paged KV cache's HBM pages — behind
``Serving.Generate``, with prefix reuse VISIBLY skipping prefill.

What it shows:

  1. the runner's paged-attention decode streaming real greedy tokens
     over the RPC stream layer (identical to the cache-less dense
     reference — printed side by side);
  2. a second identical prompt prefix-HITTING the radix tree: same
     tokens, measurably fewer prompt tokens computed (the server's
     advisory ``prefix_hit`` and the store's hit-rate both show it);
  3. a third prompt sharing only the system-prompt prefix still skips
     that shared portion.

Browse http://127.0.0.1:<port>/kvcache while it runs for pages/hit
rate, or /serving for the slot map.

Run forced-CPU (the paged kernel's gather backend) with
BRPC_FORCE_CPU=1; on a TPU the same code takes the pallas
scalar-prefetch kernel path.
"""
import json
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("BRPC_FORCE_CPU"):
    jax.config.update("jax_platforms", "cpu")

import brpc_tpu as brpc
from brpc_tpu.models.runner import (TransformerConfig, TransformerRunner,
                                    dense_generate, init_runner_params,
                                    make_store_for)
from brpc_tpu.serving import DecodeEngine, register_serving


class _Collector(brpc.StreamHandler):
    def __init__(self):
        self.tokens = []
        self.done = threading.Event()

    def on_received_messages(self, stream, messages):
        for m in messages:
            d = json.loads(m)
            if "token" in d:
                self.tokens.append(d["token"])
            if d.get("done"):
                self.done.set()

    def on_closed(self, stream):
        self.done.set()


def main():
    cfg = TransformerConfig()
    params = init_runner_params(cfg)
    store = make_store_for(cfg, page_tokens=4, max_blocks=32,
                           name="llm")
    runner = TransformerRunner(params, cfg, store=store, name="llm")
    engine = DecodeEngine(runner=runner, num_slots=4, store=store,
                          max_pages_per_slot=32,
                          prefill_buckets=(8, 16, 32), name="llm")
    server = brpc.Server()
    register_serving(server, engine=engine)
    server.start("127.0.0.1", 0)
    print(f"LLM server on 127.0.0.1:{server.port} "
          f"(console: /kvcache, /serving)")
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=10_000)

    def generate(prompt, n=8):
        col = _Collector()
        cntl = brpc.Controller()
        brpc.stream_create(cntl, col)
        resp = ch.call_sync("Serving", "Generate",
                            {"prompt": prompt, "max_new_tokens": n},
                            serializer="json", cntl=cntl)
        col.done.wait(120)
        return col.tokens, resp["prefix_hit"]

    system = [7, 99, 23, 54]                    # "system prompt" prefix
    prompt = system + [5, 17, 42, 9]

    toks, hit = generate(prompt)
    print(f"\n[1] cold generate   prefix_hit={hit:2d}  tokens={toks}")
    ref = dense_generate(params, cfg, prompt, 8)
    print(f"    dense reference (no cache, full recompute): {ref}")
    assert toks == ref, "paged decode diverged from the dense model!"

    toks2, hit2 = generate(prompt)
    print(f"[2] same prompt     prefix_hit={hit2:2d}  tokens={toks2}"
          f"   <- identical output, prefill skipped")
    assert toks2 == toks and hit2 > 0

    other = system + [61, 33, 88, 2]
    toks3, hit3 = generate(other)
    print(f"[3] shared system   prefix_hit={hit3:2d}  tokens={toks3}"
          f"   <- only the system prefix reused")

    st = store.stats()
    print(f"\nkvcache: hit_rate={st['hit_rate']}  "
          f"pages_in_use={st['pages']['pages_in_use']}  "
          f"radix_nodes={st['radix_nodes']}  cow={st['cow_forks']}")

    server.stop()
    server.join()
    engine.close()
    store.clear()
    store.close()


if __name__ == "__main__":
    main()
