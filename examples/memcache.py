"""Memcache binary protocol (reference example/memcache_c++): pipelined
client against the in-memory memcache-speaking service on the RPC port."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


def main():
    server = brpc.Server(brpc.ServerOptions(
        memcache_service=brpc.MemoryMemcacheService()))
    server.start("127.0.0.1", 0)
    mc = brpc.MemcacheChannel(f"127.0.0.1:{server.port}")
    mc.set("greeting", b"hello memcache", flags=42)
    got = mc.get("greeting")
    print(f"get -> {got.value!r} flags={got.flags} cas={got.cas}")
    print("incr counter:", [mc.incr("n", 10, initial=0) for _ in range(3)])
    futs = [mc.execute(0x01, b"k%d" % i, b"\x00" * 8, b"v%d" % i)
            for i in range(100)]
    assert all(f.result(3).status == 0 for f in futs)
    print("100 pipelined sets OK; version:", mc.version())
    mc.close()
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
