"""Expert-parallel MoE served through the framework.

Two things in one demo:
  1. the Switch-style MoE layer with experts sharded over an `ep` mesh
     axis and `lax.all_to_all` token exchanges (models/moe.py) — run
     directly and validated against the single-device reference;
  2. the same layer registered as a DEVICE SERVICE and invoked through
     `IciChannel` — an inference endpoint whose handler IS the sharded
     program, the framework's device-RPC surface over the MoE math.

Run on the virtual mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  BRPC_FORCE_CPU=1 python examples/moe_expert_parallel.py
"""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("BRPC_FORCE_CPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from brpc_tpu.models.moe import (MoEConfig, init_moe_params, make_ep_mesh,
                                 make_sharded_moe_layer,
                                 moe_layer_reference, place_moe_params)


def main():
    n = len(jax.devices())
    cfg = MoEConfig(d_model=64, d_ff=128, n_experts=n, capacity=64, seq=32)
    params = init_moe_params(cfg, jax.random.PRNGKey(0))
    mesh = make_ep_mesh(n)
    layer = make_sharded_moe_layer(mesh, cfg)
    placed = place_moe_params(params, mesh)

    from jax.sharding import NamedSharding, PartitionSpec as P
    tokens = jax.random.normal(jax.random.PRNGKey(1),
                               (n * cfg.seq, cfg.d_model), jnp.float32)
    xs = jax.device_put(tokens, NamedSharding(mesh, P("ep", None)))

    out = layer(placed["router"], placed["wup"], placed["wdown"], xs)
    ref = moe_layer_reference(params, tokens[:cfg.seq], cfg)
    np.testing.assert_allclose(np.asarray(out)[:cfg.seq], np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    print(f"MoE layer: {n} experts over {n} chips, "
          f"{n * cfg.seq} tokens exchanged via all_to_all — matches the "
          f"single-device reference")

    # ---- serve it: the sharded program as a device service ----
    from brpc_tpu.ici import IciChannel, register_device_service

    def moe_service(x):
        # requests arrive on the target chip; the service re-shards them
        # over the ep mesh and runs the sharded program — the endpoint
        # takes plain tokens, the parallelism is its implementation
        xs_ = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
        return layer(placed["router"], placed["wup"], placed["wdown"], xs_)

    register_device_service("MoE", "Forward", moe_service, jit=False)
    ch = IciChannel("ici://slice0/0")
    served = ch.call_sync("MoE", "Forward", tokens)
    np.testing.assert_allclose(np.asarray(served), np.asarray(out),
                               rtol=1e-6, atol=1e-6)
    print("served through IciChannel: identical output — the inference "
          "endpoint IS the sharded program")


if __name__ == "__main__":
    main()
