"""Throughput demo: N client threads hammering one server
(reference example/multi_threaded_echo_c++)."""
import os, sys, threading, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu.bvar import LatencyRecorder


class EchoService(brpc.Service):
    @brpc.method(request="raw", response="raw")
    def Echo(self, cntl, req):
        return req


def main(threads=8, seconds=3.0):
    server = brpc.Server()
    server.add_service(EchoService())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=2000)
    rec = LatencyRecorder()
    counts = [0] * threads
    stop = time.monotonic() + seconds

    def worker(i):
        payload = b"x" * 256
        while time.monotonic() < stop:
            t0 = time.monotonic()
            ch.call_sync("EchoService", "Echo", payload, serializer="raw")
            rec.add(int((time.monotonic() - t0) * 1e6))
            counts[i] += 1

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    t0 = time.monotonic()
    [t.start() for t in ts]
    [t.join() for t in ts]
    wall = time.monotonic() - t0
    print(f"{sum(counts)} echos in {wall:.2f}s with {threads} threads "
          f"-> {sum(counts)/wall:.0f} qps, "
          f"p50={rec.latency_percentile(0.5):.0f}us "
          f"p99={rec.latency_percentile(0.99):.0f}us")
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
