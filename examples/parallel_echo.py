"""ParallelChannel fan-out demo (reference example/parallel_echo_c++) —
both over TCP servers and collective-lowered over the device mesh."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("BRPC_FORCE_CPU"):
    # demo on the virtual mesh even where a site hook pre-pinned a real
    # accelerator (same escape hatch as tests/conftest.py)
    import jax
    jax.config.update("jax_platforms", "cpu")

import brpc_tpu as brpc


class EchoService(brpc.Service):
    NAME = "EchoService"

    def __init__(self, tag):
        self._tag = tag

    @brpc.method(request="json", response="json")
    def Echo(self, cntl, req):
        return {"from": self._tag, "message": req["message"]}


def tcp_fanout():
    servers = []
    pc = brpc.ParallelChannel(fail_limit=1)
    for i in range(3):
        s = brpc.Server()
        s.add_service(EchoService(f"backend-{i}"))
        s.start("127.0.0.1", 0)
        servers.append(s)
        pc.add_channel(brpc.Channel(f"127.0.0.1:{s.port}", timeout_ms=2000))
    resp = pc.call_sync("EchoService", "Echo", {"message": "fan-out"},
                        serializer="json")
    print("tcp fan-out merged:", resp)
    for s in servers:
        s.stop()
        s.join()


def ici_fanout():
    import jax
    import jax.numpy as jnp
    from brpc_tpu.ici import IciChannel, register_device_service

    n = len(jax.devices())
    register_device_service("MatService", "Scale", lambda x: x * 3)
    pc = brpc.ParallelChannel(response_merger=brpc.SumMerger())
    for i in range(n):
        pc.add_channel(IciChannel(f"ici://slice0/{i}"))
    out = pc.call_sync("MatService", "Scale",
                       jnp.ones((4,), jnp.float32))
    print(f"ici fan-out over {n} chip(s), psum-merged:", out)


if __name__ == "__main__":
    tcp_fanout()
    ici_fanout()
