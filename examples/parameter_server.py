"""Parameter-server / sharded-embedding demo — the BASELINE.json north
star: the flagship model served through the RPC surface AND trained with
sharded steps over the mesh."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("BRPC_FORCE_CPU"):
    # demo on the virtual mesh even where a site hook pre-pinned a real
    # accelerator (same escape hatch as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

import brpc_tpu as brpc
from brpc_tpu.ici import IciChannel
from brpc_tpu.models import (PSConfig, init_params, register_ps_services,
                             make_sharded_train_step)
from brpc_tpu.models.parameter_server import (make_example_batch, make_mesh,
                                              param_shardings,
                                              data_shardings)


def serve_lookups():
    register_ps_services()
    n = len(jax.devices())
    ch = IciChannel(f"ici://slice0/{n - 1}")
    tokens = jnp.arange(8) % 256
    emb = ch.call_sync("ParameterServer", "EmbedLookup", tokens)
    print(f"embedding lookup via IciChannel on chip {n-1}: {emb.shape}")
    logits = ch.call_sync("ParameterServer", "Forward",
                          tokens.reshape(1, 8))
    print(f"full forward via RPC: {logits.shape}")


def train_sharded():
    n = len(jax.devices())
    cfg = PSConfig(vocab=512, d_model=64, d_ff=128, n_layers=2, seq=16,
                   batch=max(4, n))
    mesh = make_mesh(n)
    params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, s), init_params(cfg),
        param_shardings(mesh))
    ts, gs = data_shardings(mesh)
    tokens, targets = make_example_batch(cfg)
    tokens, targets = jax.device_put(tokens, ts), jax.device_put(targets, gs)
    step = make_sharded_train_step(mesh, cfg, lr=2.0)
    losses = []
    for i in range(10):
        params, loss = step(params, tokens, targets)
        losses.append(float(loss))
    print(f"sharded training over {mesh.shape}: "
          f"loss {losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    serve_lookups()
    train_sharded()
