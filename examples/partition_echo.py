"""PartitionChannel demo (reference example/partition_echo_c++): servers
tagged N/M in one naming service; each call fans one slice per partition."""
import os, sys, tempfile
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class ShardService(brpc.Service):
    NAME = "ShardService"

    def __init__(self, idx):
        self._idx = idx

    @brpc.method(request="json", response="json")
    def Lookup(self, cntl, req):
        return {"shard": self._idx,
                "values": {k: f"v{k}@shard{self._idx}"
                           for k in req["keys"]}}


class KeyMapper(brpc.CallMapper):
    def map(self, i, n, request):
        mine = [k for k in request["keys"] if k % n == i]
        if not mine:
            return brpc.SubCall.skip_call()
        return brpc.SubCall({"keys": mine})


class MergeValues(brpc.ResponseMerger):
    def merge(self, responses):
        out = {}
        for r in responses:
            out.update(r["values"])
        return out


def main(partitions=3):
    servers = []
    lines = []
    for i in range(partitions):
        s = brpc.Server()
        s.add_service(ShardService(i))
        s.start("127.0.0.1", 0)
        servers.append(s)
        lines.append(f"127.0.0.1:{s.port} {i}/{partitions}")
    with tempfile.NamedTemporaryFile("w", suffix=".list",
                                     delete=False) as f:
        f.write("\n".join(lines) + "\n")
        path = f.name
    pc = brpc.PartitionChannel(partitions, call_mapper=KeyMapper(),
                               response_merger=MergeValues())
    pc.init(f"file://{path}", options=brpc.ChannelOptions(timeout_ms=2000))
    resp = pc.call_sync("ShardService", "Lookup",
                        {"keys": list(range(9))}, serializer="json")
    for k in sorted(resp, key=int):
        print(f"  key {k} -> {resp[k]}")
    os.unlink(path)
    for s in servers:
        s.stop()
        s.join()


if __name__ == "__main__":
    main()
