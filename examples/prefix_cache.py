"""Paged KV-cache demo (brpc_tpu/kvcache): a shared-system-prompt
workload whose radix hit-rate CLIMBS as the cache warms.

Every request opens with the same 32-token "system prompt" plus a
unique user suffix.  The first request prefills everything; once it
retires, its full pages live in the radix tree, so every later request
admits with the system prompt already cached — prefill runs only on
the suffix, and the store's hit-rate gauge climbs wave by wave.

Browse http://127.0.0.1:<port>/kvcache while it runs for hit-rate,
page occupancy, radix-tree size, and eviction/COW counters — or press
the server yourself:

    python -m brpc_tpu.tools.rpc_press --server 127.0.0.1:<port> \
        --service Serving --method Generate --streaming \
        --input '{"max_new_tokens": 4}' --shared-prefix-ratio 0.9
"""
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("BRPC_FORCE_CPU"):
    jax.config.update("jax_platforms", "cpu")

import brpc_tpu as brpc
from brpc_tpu.kvcache import KVCacheStore
from brpc_tpu.serving import DecodeEngine, register_serving


def main():
    store = KVCacheStore(page_tokens=16, page_bytes=1024, max_blocks=16,
                         name="demo")

    @jax.jit
    def prefill(tokens, start):        # toy prefill: just touch the suffix
        return tokens.sum()

    @jax.jit
    def step(tokens, positions, pages):  # toy LM over the page table
        return tokens + 1

    engine = DecodeEngine(step, num_slots=4, store=store,
                          prefill_fn=prefill, name="demo")
    server = brpc.Server()
    register_serving(server, engine=engine)
    server.start("127.0.0.1", 0)
    print(f"console: http://127.0.0.1:{server.port}/kvcache")

    system_prompt = list(range(500, 532))      # 2 pages of 16 tokens
    waves = 5
    per_wave = 4
    for wave in range(waves):
        done = [threading.Event() for _ in range(per_wave)]
        for i in range(per_wave):
            user = [1000 * wave + 10 * i + j for j in range(6)]
            engine.submit(system_prompt + user, 4, lambda t: None,
                          (lambda err, d=done[i]: d.set()))
        for d in done:
            d.wait(60)
        st = store.stats()
        print(f"wave {wave + 1}: hit_rate={st['hit_rate']:.2f} "
              f"hit_tokens={st['hit_tokens']} "
              f"radix_nodes={st['radix_nodes']} "
              f"pages_in_use={st['pages']['pages_in_use']}")

    print("done — later waves admit with the system prompt cached "
          "(hit-rate climbs), only the user suffix prefills")
    engine.close()
    store.close()
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
