"""Redis example — a redis-speaking server plus a pipelined client
(reference example/redis_c++: client against any redis server, and
redis_server demo built on RedisService/RedisCommandHandler).

The server answers RESP on the SAME port as TRPC and the HTTP console —
the native parser detects the protocol per connection.

Run: python examples/redis.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import brpc_tpu as brpc


def main():
    # server: in-memory redis + a custom command
    svc = brpc.MemoryRedisService()

    @svc.command("TOUPPER")
    def _toupper(args):
        return bytes(args[0]).upper()

    srv = brpc.Server(redis_service=svc)
    srv.start("127.0.0.1", 0)
    print(f"redis-speaking server on 127.0.0.1:{srv.port} "
          f"(also TRPC + http console)")

    ch = brpc.RedisChannel(f"127.0.0.1:{srv.port}")
    print("PING         ->", ch.call("PING"))
    print("SET k hello  ->", ch.call("SET", "k", "hello"))
    print("GET k        ->", ch.call("GET", "k"))
    print("TOUPPER k    ->", ch.call("TOUPPER", "hello"))
    print("INCR visits  ->", ch.call("INCR", "visits"))

    # pipeline: many commands, one write, FIFO-matched replies
    with ch.pipeline() as p:
        for i in range(5):
            p.execute("INCR", "visits")
    print("pipelined INCR x5 ->", p.results())

    ch.close()
    srv.stop()
    srv.join()


if __name__ == "__main__":
    main()
