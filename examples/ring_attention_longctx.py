"""Long-context sequence parallelism: exact ring attention over the mesh
(each chip holds 1/n of the sequence; K/V blocks circulate a ppermute
ring with an online-softmax accumulator — the credit-windowed streaming
loop of SURVEY §5.7 in collective form).

Runs on the virtual 8-device CPU mesh; on a real pod the ppermute hops
ride ICI at link speed."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.ops import local_attention, ring_attention, ulysses_attention


def main():
    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    spec = P(None, "sp", None, None)
    B, S, H, D = 1, 1024 * n, 4, 32   # 8k tokens on the CPU demo mesh
    print(f"{S} tokens over {n} chips ({S//n} per chip), "
          f"{H} heads x {D} dims, bf16")
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16) * 0.3
               for kk in jax.random.split(key, 3))
    sh = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    @jax.jit
    def ring(q, k, v):
        return shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp",
                                           causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)

    t0 = time.monotonic()
    out = jax.block_until_ready(ring(q, k, v))
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    out = jax.block_until_ready(ring(q, k, v))
    run_s = time.monotonic() - t0
    flops = 4 * B * H * S * S * D  # 2 matmuls, causal halves then x2 fwd
    print(f"ring attention: compile {compile_s:.1f}s, run {run_s*1e3:.0f}ms "
          f"({flops/run_s/1e12:.2f} TFLOP/s effective)")
    print(f"output {out.shape} {out.dtype}; "
          f"full {S}x{S} scores never materialized "
          f"(peak per-chip K/V: {2*S//n*H*D*2/1e6:.1f} MB)")

    # single-chip comparison on ONE shard's worth of tokens (S//n — the
    # "local block" a ring step computes): the Pallas flash kernel with
    # the causal diagonal cut (blocks above the diagonal are never
    # loaded).  On a real TPU it measured 4.1x the fused-XLA causal
    # reference; here it runs in interpret mode, so only correctness is
    # demonstrated — and only on the shard slice, keeping the demo's
    # "full SxS never materializes" promise intact.
    from brpc_tpu.ops import flash_attention
    qs, ks, vs = (np.asarray(x)[:, : S // n] for x in (q, k, v))
    fa = np.asarray(flash_attention(jnp.asarray(qs), jnp.asarray(ks),
                                    jnp.asarray(vs), causal=True),
                    np.float32)
    rf = np.asarray(local_attention(jnp.asarray(qs), jnp.asarray(ks),
                                    jnp.asarray(vs), causal=True),
                    np.float32)
    print(f"pallas causal flash (one {S//n}-token local block): "
          f"max |diff| vs reference {np.abs(fa - rf).max():.2e}")


if __name__ == "__main__":
    main()
