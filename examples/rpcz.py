"""rpcz tracing (reference example/rpcz_echo_c++): per-RPC spans collected
at sampled rate, browsable at /rpcz on the console."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu import rpcz


class Echo(brpc.Service):
    @brpc.method(request="raw", response="raw")
    def Echo(self, cntl, req):
        span = rpcz.get_current_span()
        if span:
            span.annotate("handler ran")
        return req


def main():
    rpcz.set_enabled(True, sample_rate=1.0)
    server = brpc.Server()
    server.add_service(Echo())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}")
    for i in range(5):
        ch.call_sync("Echo", "Echo", b"x%d" % i)
    spans = rpcz.recent_spans(20)
    print(f"{len(spans)} spans recorded; latest:")
    for s in spans[:4]:
        print(f"  {s.kind:6s} {s.service}.{s.method} "
              f"{s.latency_us}us trace={s.trace_id:x}")
    print(f"browse: http://127.0.0.1:{server.port}/rpcz")
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
