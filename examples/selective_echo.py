"""SelectiveChannel (reference example/selective_echo_c++): a channel of
channels with its own balancer; failures retry a DIFFERENT sub-channel."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class Who(brpc.Service):
    NAME = "Who"
    def __init__(self, label): self.label = label
    @brpc.method(request="raw", response="raw")
    def Am(self, cntl, req):
        return self.label.encode()


def main():
    servers = []
    sel = brpc.SelectiveChannel()
    for i in range(3):
        s = brpc.Server()
        s.add_service(Who(f"replica-{i}"))
        s.start("127.0.0.1", 0)
        servers.append(s)
        sel.add_channel(brpc.Channel(f"127.0.0.1:{s.port}"))
    hits = {}
    for _ in range(30):
        who = sel.call_sync("Who", "Am", b"").decode()
        hits[who] = hits.get(who, 0) + 1
    print("traffic spread:", hits)
    # kill one replica: calls keep succeeding on the others
    servers[0].stop(); servers[0].join()
    for _ in range(10):
        assert sel.call_sync("Who", "Am", b"").decode() != "replica-0"
    print("replica-0 down, calls fail over transparently")
    for s in servers[1:]:
        s.stop(); s.join()


if __name__ == "__main__":
    main()
