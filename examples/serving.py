"""Inference serving demo (brpc_tpu/serving): deadline-aware dynamic
batching + continuous-decode streaming on one server.

Part 1 — batched scoring: concurrent `Serving.Score` RPCs coalesce into
bucket-padded jit calls; a request with a hopeless deadline is
ELIMIT-shed before the batch even forms.

Part 2 — continuous decode: `Serving.Generate` streams tokens per step
over the credit-windowed stream layer; a second request joins the step
loop while the first is mid-flight (no restart, no static batch).

Browse http://127.0.0.1:<port>/serving while it runs for batch
occupancy, the decode slot map, and shed/pad stats — or
/serving/generate?prompt=5&max_new_tokens=8 for the chunked-HTTP
decode stream.
"""
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("BRPC_FORCE_CPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import brpc_tpu as brpc
from brpc_tpu import errors
from brpc_tpu.serving import DecodeEngine, DynamicBatcher, register_serving


def main():
    # ---- the "model": a jitted scorer and a jitted decode step ----
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 64)).astype(np.float32))

    @jax.jit
    def score(x):                       # [batch, 64] -> [batch]
        return jnp.tanh(x @ w).sum(axis=1)

    @jax.jit
    def step(tokens, positions):        # toy LM: next = last + 1
        return tokens + 1

    batcher = DynamicBatcher(score, max_batch_size=8, max_delay_us=5000,
                             length_buckets=(64,), name="demo")
    engine = DecodeEngine(step, num_slots=4, kv_bytes_per_slot=4096,
                          name="demo")
    server = brpc.Server()
    register_serving(server, batcher=batcher, engine=engine)
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=10_000)

    # ---- part 1: batched scoring + deadline shed ----
    results = []

    def score_one(i):
        y = ch.call_sync("Serving", "Score",
                         {"x": [float(i)] * 64}, serializer="json")
        results.append((i, y["y"]))

    ts = [threading.Thread(target=score_one, args=(i,)) for i in range(8)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    print(f"scored {len(results)} concurrent requests; "
          f"stats={batcher.stats()}")
    try:
        ch.call_sync("Serving", "Score", {"x": [1.0] * 64},
                     serializer="json", cntl=brpc.Controller(timeout_ms=1))
    except errors.RpcError as e:
        print(f"hopeless deadline shed up front: E{e.code} ({e.text})")

    # ---- part 2: continuous decode, two overlapping streams ----
    def generate(prompt, max_new):
        toks, done = [], threading.Event()

        def on_msg(stream, data):
            d = json.loads(data)
            if d.get("done"):
                done.set()
            else:
                toks.append(d["token"])

        cntl = brpc.Controller()
        brpc.stream_create(cntl, on_msg)
        ch.call_sync("Serving", "Generate",
                     {"prompt": prompt, "max_new_tokens": max_new},
                     serializer="json", cntl=cntl)
        return toks, done

    a_toks, a_done = generate([100], 400)
    while len(a_toks) < 5:              # A demonstrably mid-flight...
        time.sleep(0.001)
    b_toks, b_done = generate([900], 10)   # ...when B joins the loop
    assert a_done.wait(30) and b_done.wait(30)
    print(f"A streamed {len(a_toks)} tokens (first {a_toks[:3]}...), "
          f"B joined mid-flight and streamed {b_toks}")
    print(f"engine stats: {engine.stats()}")

    server.stop()
    server.join()
    batcher.close()
    engine.close()


if __name__ == "__main__":
    main()
