"""Pooled per-request session data (reference example/session_data_and_thread_local):
a DataFactory-backed pool hands each request a reusable object as
cntl.session_data."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc

created = 0


class Scratch:
    def __init__(self):
        global created
        created += 1
        self.buf = bytearray(1 << 16)


class S(brpc.Service):
    @brpc.method(request="raw", response="json")
    def Use(self, cntl, req):
        sd = cntl.session_data
        sd.buf[:len(req)] = req
        return {"pooled_object_id": id(sd) % 10000}


def main():
    server = brpc.Server(brpc.ServerOptions(session_data_factory=Scratch))
    server.add_service(S())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}")
    ids = {ch.call_sync("S", "Use", b"x", response_serializer="json")
           ["pooled_object_id"] for _ in range(50)}
    print(f"50 sequential requests used {len(ids)} pooled object(s); "
          f"{created} Scratch objects ever constructed")
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
