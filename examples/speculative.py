"""Speculative decoding demo (ISSUE 11): the DecodeEngine's
propose -> verify -> commit mode, side by side with plain decode.

What it shows:

  1. a speculative engine (real TransformerRunner target + host-side
     NGramProposer draft) streaming EXACTLY the tokens plain greedy
     decode streams — identity is the contract, speed is the point;
  2. the speed: tokens/s plain vs speculative at draft depth 4 on the
     same machinery (the draft accepts heavily once the output
     self-repeats, so several tokens commit per verify call);
  3. the acceptance telemetry: per-generation accept_rate /
     draft_depth / tokens_per_step from the generations ring plus the
     aggregate the ``/serving/generations`` console page renders
     (printed here directly — behind a Server, the same numbers are
     one HTTP GET away; see examples/llm_server.py for the served
     variant).

Run forced-CPU (the paged kernel's gather backend) with
BRPC_FORCE_CPU=1; on a TPU the same code takes the pallas
scalar-prefetch kernel path.
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("BRPC_FORCE_CPU"):
    jax.config.update("jax_platforms", "cpu")

from brpc_tpu.models.runner import (TransformerConfig, TransformerRunner,
                                    dense_generate, init_runner_params,
                                    make_store_for)
from brpc_tpu.serving import DecodeEngine, NGramProposer
from brpc_tpu import serving as srv


def generate(eng, prompt, n):
    toks, ev = [], threading.Event()
    eng.submit(prompt, n, toks.append, lambda e: ev.set())
    assert ev.wait(600), "generation hung"
    return toks


def build(cfg, params, tag, draft=None):
    store = make_store_for(cfg, page_tokens=8, max_blocks=64,
                           name=f"{tag}_kv")
    runner = TransformerRunner(params, cfg, store=store, name=f"{tag}_m")
    kw = dict(draft_runner=draft, draft_len=4) if draft else {}
    eng = DecodeEngine(runner=runner, num_slots=2, store=store,
                       max_pages_per_slot=24, prefill_buckets=(16, 32),
                       name=f"{tag}_eng", **kw)
    return store, eng


def main():
    cfg = TransformerConfig()
    params = init_runner_params(cfg)
    prompt = [5, 17, 42, 9, 77, 3]
    n = 48

    print("=== 1. identity: speculative == plain greedy ===")
    oracle = dense_generate(params, cfg, prompt, 12)
    sp_store, sp_eng = build(cfg, params, "spec", NGramProposer())
    pl_store, pl_eng = build(cfg, params, "plain")
    spec = generate(sp_eng, prompt, 12)
    print(f"  plain greedy : {oracle}")
    print(f"  speculative  : {spec}")
    assert spec == oracle, "speculation changed the output!"
    print("  identical — the draft changes cost, never output\n")

    print(f"=== 2. speed: {n}-token generation, plain vs depth-4 draft ===")
    generate(pl_eng, prompt, n)        # warm both jit paths
    generate(sp_eng, prompt, n)
    t0 = time.monotonic()
    generate(pl_eng, prompt, n)
    plain_s = time.monotonic() - t0
    t0 = time.monotonic()
    generate(sp_eng, prompt, n)
    spec_s = time.monotonic() - t0
    print(f"  plain       : {n / plain_s:7.1f} tok/s")
    print(f"  speculative : {n / spec_s:7.1f} tok/s "
          f"({plain_s / spec_s:.2f}x)\n")

    print("=== 3. acceptance telemetry ===")
    rec = [r for r in srv.recent_generations(64)
           if r.get("engine") == "spec_eng" and "accept_rate" in r][-1]
    print(f"  accept_rate={rec['accept_rate']} "
          f"draft_depth={rec['draft_depth']} "
          f"tokens_per_step={rec['tokens_per_step']} "
          f"({rec['spec_accepted']}/{rec['spec_proposed']} drafts "
          f"accepted)")
    agg = srv.generations_snapshot()["aggregates"]["speculative"]
    print(f"  /serving/generations aggregate: {agg}")

    for store, eng in ((sp_store, sp_eng), (pl_store, pl_eng)):
        eng.close()
        store.clear()
        store.close()


if __name__ == "__main__":
    main()
