"""Batched streaming (reference example/streaming_batch_echo_c++): many
chunks pushed back-to-back ride the credit window; the receiver sees them
in order, batched per flush."""
import os, sys, threading, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class BatchEcho(brpc.Service):
    @brpc.method(request="json", response="json")
    def Open(self, cntl, req):
        def on_msg(stream, data):
            stream.write(data)          # echo each chunk
        cntl.accept_stream(on_msg)
        return {"ok": True}


def main(batches=10, per_batch=50, chunk=4096):
    server = brpc.Server()
    server.add_service(BatchEcho())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=5000)
    n_total = batches * per_batch
    got = []
    done = threading.Event()

    def on_reply(stream, data):
        got.append(data)
        if len(got) == n_total:
            done.set()

    cntl = brpc.Controller()
    stream = brpc.stream_create(cntl, on_reply, max_buf_size=1 << 20)
    ch.call_sync("BatchEcho", "Open", {}, serializer="json", cntl=cntl)
    payload = b"\xab" * chunk
    t0 = time.monotonic()
    for b in range(batches):
        for i in range(per_batch):
            stream.write(payload)
    assert done.wait(30), f"{len(got)}/{n_total}"
    dt = time.monotonic() - t0
    mb = n_total * chunk / 1e6
    print(f"echoed {n_total} chunks ({mb:.1f} MB) in {dt*1e3:.0f} ms "
          f"= {2*mb/dt:.0f} MB/s both directions")
    stream.close()
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
