"""Streaming RPC demo (reference example/streaming_echo_c++):
client attaches a stream to an RPC, pushes chunks, server echoes them back
through the same credit-windowed pipe."""
import os, sys, threading
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc


class StreamEcho(brpc.Service):
    @brpc.method(request="json", response="json")
    def Open(self, cntl, req):
        def on_msg(stream, data):
            stream.write(b"echo:" + data)
        cntl.accept_stream(on_msg)
        return {"accepted": True}


def main(n_chunks=20):
    server = brpc.Server()
    server.add_service(StreamEcho())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=2000)

    got = []
    done = threading.Event()

    def on_reply(stream, data):
        got.append(data)
        if len(got) == n_chunks:
            done.set()

    cntl = brpc.Controller()
    stream = brpc.stream_create(cntl, on_reply, max_buf_size=256 * 1024)
    print("open:", ch.call_sync("StreamEcho", "Open", {}, serializer="json",
                                cntl=cntl))
    for i in range(n_chunks):
        stream.write(b"chunk-%03d" % i)
    assert done.wait(10), f"got {len(got)}/{n_chunks}"
    print(f"received {len(got)} echoed chunks, first={got[0]!r} "
          f"last={got[-1]!r}")
    stream.close()
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
