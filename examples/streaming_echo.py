"""Streaming RPC demo (reference example/streaming_echo_c++):
client attaches a stream to an RPC, pushes chunks, server echoes them back
through the same credit-windowed pipe.

Part 2 shows the ICI rail (the use_rdma analog, rdma_endpoint.h:82): the
server advertises a device, and an ordinary `Channel.call_sync` carrying a
jax device tensor moves its payload over BlockPool + IciEndpoint — zero
host copies, only the control frame touches the socket.

Part 3 is the unified StreamWrite: the SAME stream.write() that carried
bytes in part 1 carries jax device arrays HBM->HBM — tensors ride the
rail under the socket (socket.cpp:1751-1757's RDMA slide-under), the
socket sees only claim tickets, and host_copy_count() stays zero.
"""
import os, sys, threading
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("BRPC_FORCE_CPU"):
    # demo on the virtual mesh even where a site hook pre-pinned a real
    # accelerator (same escape hatch as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

import brpc_tpu as brpc
from brpc_tpu.ici import rail


class StreamEcho(brpc.Service):
    @brpc.method(request="json", response="json")
    def Open(self, cntl, req):
        def on_msg(stream, data):
            stream.write(b"echo:" + data)
        cntl.accept_stream(on_msg)
        return {"accepted": True}

    @brpc.method(request="json", response="json")
    def OpenTensor(self, cntl, req):
        # tensor echo: receives device arrays on the advertised chip and
        # writes them straight back through the same stream
        def on_msg(stream, payload):
            stream.write(payload)
        cntl.accept_stream(on_msg, device=jax.devices()[-1])
        return {"accepted": True}

    @brpc.method(request="tensor", response="tensor")
    def Scale(self, cntl, req):
        # req arrives as a device array on the server's advertised chip;
        # the result rides the rail back to the caller's chip
        return req * 2


def main(n_chunks=20):
    devs = jax.devices()
    server = brpc.Server(ici_device=devs[-1])
    server.add_service(StreamEcho())
    server.start("127.0.0.1", 0)
    # generous deadline: on a tunneled real chip the first jit compile of
    # the stage/unstage kernels takes tens of seconds (cached afterwards)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=180000)

    # --- part 1: byte streaming over the credit-windowed stream pipe ---
    got = []
    done = threading.Event()

    def on_reply(stream, data):
        got.append(data)
        if len(got) == n_chunks:
            done.set()

    cntl = brpc.Controller()
    stream = brpc.stream_create(cntl, on_reply, max_buf_size=256 * 1024)
    print("open:", ch.call_sync("StreamEcho", "Open", {}, serializer="json",
                                cntl=cntl))
    for i in range(n_chunks):
        stream.write(b"chunk-%03d" % i)
    assert done.wait(10), f"got {len(got)}/{n_chunks}"
    print(f"received {len(got)} echoed chunks, first={got[0]!r} "
          f"last={got[-1]!r}")
    stream.close()

    # --- part 2: device tensors on an ordinary call ride the ICI rail ---
    x = jax.device_put(jnp.arange(1 << 18, dtype=jnp.float32), devs[0])
    host_copies_before = rail.host_copy_count()
    out = ch.call_sync("StreamEcho", "Scale", x, serializer="tensor")
    assert bool(jnp.array_equal(out, x * 2))
    assert out.devices() == {devs[0]}, "response must land on the caller's chip"
    hc = rail.host_copy_count() - host_copies_before
    print(f"rail: {x.nbytes} tensor bytes moved {devs[0]}->{devs[-1]}->"
          f"{devs[0]} with {hc} host copies "
          f"(payloads so far: {rail.rail_payloads.get_value()})")
    assert hc == 0

    # --- part 3: the SAME StreamWrite carries device tensors zero-copy ---
    tensors_back = []
    tdone = threading.Event()

    def on_tensor(stream, payload):
        tensors_back.append(payload)
        if len(tensors_back) == 8:
            tdone.set()

    cntl2 = brpc.Controller()
    tstream = brpc.stream_create(cntl2, on_tensor, device=devs[0])
    print("open tensor stream:",
          ch.call_sync("StreamEcho", "OpenTensor", {}, serializer="json",
                       cntl=cntl2))
    before = rail.host_copy_count()
    chunks = [jax.device_put(jnp.full((1 << 16,), i, dtype=jnp.float32),
                             devs[0]) for i in range(8)]
    for c in chunks:
        tstream.write(c)                 # same API as the byte writes
    assert tdone.wait(30), f"got {len(tensors_back)}/8 tensors"
    for i, t in enumerate(tensors_back):
        assert isinstance(t, jax.Array) and t.devices() == {devs[0]}
        assert bool(jnp.array_equal(t, chunks[i]))
    hc = rail.host_copy_count() - before
    total = sum(c.nbytes for c in chunks)
    print(f"stream: {total} tensor bytes {devs[0]}->{devs[-1]}->{devs[0]} "
          f"through StreamWrite with {hc} host copies")
    assert hc == 0
    tstream.close()

    server.stop()
    server.join()


if __name__ == "__main__":
    main()
