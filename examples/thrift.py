"""Thrift framed-binary protocol (reference example/thrift_extension_c++):
schema-free TBinaryProtocol calls against a method registry."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu.rpc.thrift import T_I32, T_STRING, TField


def main():
    svc = brpc.ThriftService()

    @svc.method("add")
    def add(args):
        return TField(0, T_I32, args[1] + args[2])

    @svc.method("greet")
    def greet(args):
        return f"hello {args[1].decode()}"

    server = brpc.Server(brpc.ServerOptions(thrift_service=svc))
    server.start("127.0.0.1", 0)
    ch = brpc.ThriftChannel(f"127.0.0.1:{server.port}")
    print("add(2,40) ->", ch.call("add", [TField(1, T_I32, 2),
                                          TField(2, T_I32, 40)])[0])
    print("greet ->", ch.call("greet",
                              [TField(1, T_STRING, "thrift")])[0].decode())
    ch.close()
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
