"""In-socket TLS demo (rpc/tls_engine.py; the reference integrates SSL
into the Socket itself, socket.h:276-278): ONE TLS port carries every
protocol — TRPC echo calls, a gRPC call, and an HTTPS console fetch —
with no proxy hop.  The older stunnel-shaped proxy topology
(rpc/tls.py TlsTerminator) still exists — this file's own pre-round-5
git history demos that shape.

Generates a throwaway self-signed cert, stands up a TLS server, and
drives three protocols through the encrypted port.

Run:  python examples/tls_echo.py
"""
import os
import ssl
import subprocess
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc  # noqa: E402
from brpc_tpu.rpc.h2 import GrpcChannel  # noqa: E402
from brpc_tpu.rpc.tls_engine import (make_client_context,  # noqa: E402
                                     make_server_context)


class Echo(brpc.Service):
    @brpc.method(request="raw", response="raw")
    def Echo(self, cntl, req):
        return bytes(req)


def main():
    d = tempfile.mkdtemp(prefix="tls-demo-")
    cert, key = os.path.join(d, "cert.pem"), os.path.join(d, "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", cert, "-days", "2", "-nodes", "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)

    srv = brpc.Server(brpc.ServerOptions(
        tls_context=make_server_context(cert, key)))
    srv.add_service(Echo())
    srv.start("127.0.0.1", 0)
    print(f"TLS server on 127.0.0.1:{srv.port} (every protocol encrypted)")

    ctx = make_client_context(cafile=cert)
    ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=10_000,
                      tls_context=ctx)
    out = ch.call_sync("Echo", "Echo", b"hello over TLS", serializer="raw")
    print(f"TRPC over TLS : {bytes(out)!r}")

    g = GrpcChannel(f"127.0.0.1:{srv.port}", tls_context=ctx)
    print(f"gRPC over TLS : {g.call('Echo', 'Echo', b'h2 says hi')!r}")
    g.close()

    sctx = ssl.create_default_context(cafile=cert)
    with urllib.request.urlopen(f"https://127.0.0.1:{srv.port}/health",
                                context=sctx, timeout=10) as r:
        print(f"HTTPS console : {r.read().decode().strip()!r}")

    srv.stop()
    srv.join()
    print("done.")


if __name__ == "__main__":
    main()
