"""TLS-encrypted RPC (reference ServerOptions.ssl_options role; see
README "TLS and unix sockets" for why this build terminates TLS with
in-process proxies over Python's ssl).

Generates a throwaway self-signed cert, stands up a server + TLS
terminator, and calls through an encrypted channel.

Run:  python examples/tls_echo.py
"""
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import brpc_tpu as brpc
from brpc_tpu.rpc.tls import TlsTerminator, tls_channel_address, tls_stats


class Echo(brpc.Service):
    @brpc.method(request="raw", response="raw")
    def Echo(self, cntl, req):
        return req


def main():
    d = tempfile.mkdtemp()
    cert, key = f"{d}/cert.pem", f"{d}/key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", cert, "-days", "1", "-nodes", "-subj", "/CN=localhost",
         "-addext", "subjectAltName=DNS:localhost"],
        check=True, capture_output=True)

    server = brpc.Server()
    server.add_service(Echo())
    server.start("127.0.0.1", 0)
    term = TlsTerminator(server, cert, key, address="127.0.0.1")
    print(f"plaintext backend :{server.port}; TLS front :{term.port}")

    addr = tls_channel_address("localhost", term.port, cafile=cert)
    ch = brpc.Channel(addr, timeout_ms=10_000)
    for i in range(100):
        assert ch.call_sync("Echo", "Echo", b"x" * 4096) == b"x" * 4096
    print(f"100 encrypted echoes OK; {tls_stats()}")
    term.stop()
    server.stop()
    server.join()


if __name__ == "__main__":
    main()
