#include "bthread/butex.h"

#include <climits>

#include "bthread/executor.h"
#include "bthread/timer.h"
#include "butil/common.h"
#include "butil/flight.h"
#include "bvar/combiner.h"

namespace bthread {

// butex traffic stats (per-thread combiner cells; /bthreads console row).
static bvar::Adder g_butex_waits;
static bvar::Adder g_butex_wakes;
static bvar::Adder g_butex_timeouts;
static bvar::Adder g_mutex_contended;

void Butex::counters(int64_t* waits, int64_t* wakes, int64_t* timeouts,
                     int64_t* mutex_contended) {
  if (waits) *waits = g_butex_waits.get();
  if (wakes) *wakes = g_butex_wakes.get();
  if (timeouts) *timeouts = g_butex_timeouts.get();
  if (mutex_contended) *mutex_contended = g_mutex_contended.get();
}

void Butex::note_mutex_contention() { g_mutex_contended.add(1); }

void Butex::note_contended_unlock(const void* lock) {
  butil::contention_note(lock);
}

// Heap-allocated, refcounted waiter record.  Two owners can hold a pointer
// concurrently: the butex list/waker side and the timer callback.  The
// claim word decides who resumes the coroutine (exactly once); the
// refcount decides who frees the record (exactly once).  The reference
// keeps its ButexWaiter on the waiting bthread's stack and relies on the
// stack outliving the wake (butex.cpp erase_from_butex) — with coroutine
// frames destroyed on completion we cannot, hence the refcount.
struct Waiter {
  std::coroutine_handle<> handle;
  std::atomic<Butex*> owner{nullptr}; // list the waiter currently sits on
  Waiter* next = nullptr;
  Waiter* prev = nullptr;
  uint64_t timer_id = 0;
  std::atomic<int> claim{0};          // 0 pending, 1 woken, 2 timed out
  std::atomic<int> refs{1};
  WaitResult* result_slot = nullptr;  // points into the Awaiter (frame-owned)

  void unref() {
    if (refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete this;
  }
};

namespace {

void resume_waiter_task(void* arg) {
  std::coroutine_handle<>::from_address(arg).resume();
}

// Resume on the executor, never inline: the caller may be the timer thread
// or an event-dispatcher thread, and user code behind the co_await must
// run on worker threads only (scheduler discipline — the reference wakes
// through ready_to_run_general for the same reason).
void schedule_resume(std::coroutine_handle<> h) {
  Executor::global()->submit(resume_waiter_task, h.address());
}

}  // namespace

void Butex::unlink_locked(Waiter* w) {
  if (w->prev) w->prev->next = w->next; else _head = w->next;
  if (w->next) w->next->prev = w->prev; else _tail = w->prev;
  w->prev = w->next = nullptr;
}

void Butex::TimeoutTask(void* arg) {
  Waiter* w = (Waiter*)arg;
  int expected = 0;
  if (w->claim.compare_exchange_strong(expected, 2,
                                       std::memory_order_acq_rel)) {
    // We own the wakeup.  Unlink from whichever butex the waiter sits on —
    // requeue may have moved it since the timer was armed, so re-read the
    // owner after taking its lock.
    for (;;) {
      Butex* b = w->owner.load(std::memory_order_acquire);
      std::unique_lock<std::mutex> g(b->_mu);
      if (w->owner.load(std::memory_order_acquire) != b) continue;
      b->unlink_locked(w);
      break;
    }
    *w->result_slot = WaitResult::kTimeout;
    g_butex_timeouts.add(1);
    butil::flight::record(butil::flight::EV_BUTEX_TIMEOUT,
                          (uint64_t)(uintptr_t)w->owner.load(
                              std::memory_order_relaxed));
    schedule_resume(w->handle);
  }
  w->unref();
}

Butex::~Butex() = default;

bool Butex::Awaiter::await_suspend(std::coroutine_handle<> h) {
  Butex* b = butex;
  // Everything that touches the coroutine frame (the Awaiter fields)
  // happens under the lock: a concurrent wake() cannot claim the waiter —
  // and therefore cannot resume/destroy the frame — until this unlocks at
  // return, by which point the frame is fully parked.
  std::unique_lock<std::mutex> g(b->_mu);
  if (b->value.load(std::memory_order_relaxed) != expected) {
    result = WaitResult::kMismatch;
    return false;  // do not suspend; resume inline
  }
  Waiter* w = new Waiter();
  w->handle = h;
  w->owner.store(b, std::memory_order_release);
  w->result_slot = &result;
  w->prev = b->_tail;                 // append FIFO
  if (b->_tail) b->_tail->next = w; else b->_head = w;
  b->_tail = w;
  waiter = w;
  if (timeout_us >= 0) {
    w->refs.fetch_add(1, std::memory_order_relaxed);  // timer's reference
    w->timer_id = TimerThread::global()->schedule_after(
        &Butex::TimeoutTask, w, timeout_us);
  }
  g_butex_waits.add(1);
  butil::flight::record(butil::flight::EV_BUTEX_WAIT,
                        (uint64_t)(uintptr_t)b, timeout_us);
  return true;
}

WaitResult Butex::Awaiter::await_resume() noexcept {
  if (waiter != nullptr) {
    // On the woken path, reclaim the timer's reference if the timer is
    // still armed; if unschedule fails the callback is running or ran and
    // will drop its own reference (its claim CAS loses).
    if (waiter->timer_id != 0 && result == WaitResult::kWoken) {
      if (TimerThread::global()->unschedule(waiter->timer_id)) {
        waiter->unref();
      }
    }
    waiter->unref();
    waiter = nullptr;
  }
  return result;
}

int Butex::wake(int n) {
  Waiter* resume_list = nullptr;   // singly chained via ->next, LIFO then
  Waiter* resume_tail = nullptr;   // ...kept FIFO with a tail pointer
  int woken = 0;
  {
    std::lock_guard<std::mutex> g(_mu);
    Waiter* w = _head;
    while (w != nullptr && woken < n) {
      Waiter* next_in_list = w->next;
      int expected = 0;
      if (w->claim.compare_exchange_strong(expected, 1,
                                           std::memory_order_acq_rel)) {
        unlink_locked(w);
        if (resume_tail) resume_tail->next = w; else resume_list = w;
        resume_tail = w;
        ++woken;
      }
      // a timer-claimed waiter stays in the list; TimeoutTask unlinks it
      w = next_in_list;
    }
  }
  if (woken > 0) {
    g_butex_wakes.add(woken);
    butil::flight::record(butil::flight::EV_BUTEX_WAKE,
                          (uint64_t)(uintptr_t)this, woken);
  }
  for (Waiter* w = resume_list; w != nullptr;) {
    Waiter* next = w->next;
    w->next = nullptr;
    *w->result_slot = WaitResult::kWoken;
    schedule_resume(w->handle);
    w = next;
  }
  return woken;
}

int Butex::wake_all() { return wake(INT_MAX); }

int Butex::requeue(Butex* target, int n_wake) {
  const int woken = wake(n_wake);
  if (target == this) return woken;
  // Lock both in address order to dodge a concurrent opposite requeue.
  Butex* a = this < target ? this : target;
  Butex* b = this < target ? target : this;
  std::scoped_lock g(a->_mu, b->_mu);
  while (_head != nullptr) {
    Waiter* w = _head;
    unlink_locked(w);
    w->owner.store(target, std::memory_order_release);
    w->prev = target->_tail;
    if (target->_tail) target->_tail->next = w; else target->_head = w;
    target->_tail = w;
  }
  return woken;
}

int Butex::waiter_count() {
  std::lock_guard<std::mutex> g(_mu);
  int c = 0;
  for (Waiter* w = _head; w != nullptr; w = w->next) ++c;
  return c;
}

}  // namespace bthread
