// Butex — THE blocking primitive, rebuilt for C++20 coroutines.
//
// Reference (src/bthread/butex.h:41-84, butex.cpp ~850 LoC): a 32-bit word
// that bthreads wait on and any thread can wake; every other blocking
// construct (mutex, cond, id, join, fd wait) is built on top.  The
// reference parks a *fiber stack* (fcontext); we park a *coroutine frame*.
// Same M:N economics — a blocked wait costs a ~100-byte heap frame, not an
// OS thread — with the suspension point visible in the type system
// (co_await) instead of hidden behind a stack switch.
//
// Semantics kept from the reference:
//   - wait(expected): atomically "suspend iff *value == expected"; a wake
//     or a value change between the caller's load and the enqueue is never
//     missed (the check happens under the waiter lock).
//   - wake(n)/wake_all: move waiters out under the lock, resume them on
//     the executor (never inline on the waker's stack — the waker may be
//     a timer or dispatcher thread, reference butex.cpp wakes through the
//     scheduler for the same reason).
//   - timed wait via TimerThread; timeout and wake race through an atomic
//     claim so a waiter is resumed exactly once.
//   - requeue: move waiters to another butex without waking (the
//     cond->mutex handoff, reference butex_requeue).
//
// Deliberately not kept: pthread-mode waiters (our blocking Python callers
// wait on a std::condition_variable bridge instead, see capi.cc) and the
// bthread interrupt machinery (cancellation composes at the RPC layer).
#pragma once

#include <atomic>
#include <coroutine>
#include <cstdint>
#include <mutex>

namespace bthread {

enum class WaitResult : int {
  kWoken = 0,      // a wake() claimed and resumed us
  kMismatch = 1,   // *value != expected at enqueue time; never suspended
  kTimeout = 2,    // the deadline fired first
};

class Butex {
 public:
  Butex() : Butex(0) {}
  explicit Butex(int32_t initial) : value(initial) {}
  ~Butex();

  Butex(const Butex&) = delete;
  Butex& operator=(const Butex&) = delete;

  // The waitable word.  Callers mutate it with ordinary atomic ops; the
  // butex only reads it (under the waiter lock) to decide suspension.
  std::atomic<int32_t> value;

  struct [[nodiscard]] Awaiter {
    Butex* butex;
    int32_t expected;
    int64_t timeout_us;            // <0: no timeout
    struct Waiter* waiter = nullptr;
    WaitResult result = WaitResult::kMismatch;

    bool await_ready() const noexcept { return false; }
    bool await_suspend(std::coroutine_handle<> h);
    WaitResult await_resume() noexcept;
  };

  // co_await b.wait(expected): suspend iff value==expected, until wake()
  // or timeout.  Spurious wakeups do not happen; re-checking the predicate
  // is still on the caller (same contract as futex).
  Awaiter wait(int32_t expected, int64_t timeout_us = -1) {
    return Awaiter{this, expected, timeout_us};
  }

  // Wake up to n waiters (FIFO).  Returns the number resumed.
  int wake(int n = 1);
  int wake_all();
  // Move all waiters except up to n_wake woken ones onto `target` without
  // resuming them.  Returns number woken.
  int requeue(Butex* target, int n_wake = 1);

  // Waiters currently parked (approximate; for stats/tests).
  int waiter_count();

  // Process-wide butex stats (bvar combiners): parks, wakes, timeouts,
  // and FiberMutex contention events.  The reference instruments
  // bthread_mutex for its contention profiler (mutex.cpp:62-107); these
  // counters are that role's first stage, surfaced on /bthreads.
  static void counters(int64_t* waits, int64_t* wakes, int64_t* timeouts,
                       int64_t* mutex_contended);
  static void note_mutex_contention();
  // Contended UNLOCK (waiters existed): samples a stack for
  // /hotspots/contention — the unlocker's physical stack names the lock
  // SITE (the waiter's would name the scheduler's resume path), which
  // is exactly why the reference samples on unlock (mutex.cpp:122-145).
  static void note_contended_unlock(const void* lock);

 private:
  friend struct Awaiter;
  friend struct Waiter;
  static void TimeoutTask(void* arg);   // TimerThread callback
  void unlink_locked(struct Waiter* w);
  std::mutex _mu;
  struct Waiter* _head = nullptr;  // FIFO: append at tail, pop at head
  struct Waiter* _tail = nullptr;
};

}  // namespace bthread
