// ExecutionQueue — MPSC serialized executor (SURVEY.md §2.2; reference
// src/bthread/execution_queue.h:35-187).
//
// Producers push nodes onto a lock-free Treiber stack; the first producer to
// make the queue non-empty schedules one drain task on the Executor, which
// reverses the stack into FIFO order and feeds batches to the consumer
// callback.  Exactly one drain runs at a time, so consumption is serialized
// without a mutex — the property streams rely on for in-order delivery
// (reference stream_impl.h:133).
#pragma once

#include <atomic>
#include <functional>
#include <thread>

#include "bthread/executor.h"

namespace bthread {

template <typename T>
class ExecutionQueue {
 public:
  // consume(item) is called serially, in push order.
  ExecutionQueue(Executor* ex, std::function<void(T&)> consume)
      : _ex(ex), _consume(std::move(consume)) {}

  ~ExecutionQueue() {
    // Callers must stop producers first.  A drain task submitted by the last
    // producer may not have finished (or even started); _inflight covers the
    // whole drain lambda, so waiting on it prevents a use-after-free of the
    // pending `this` capture.
    while (_inflight.load(std::memory_order_acquire) != 0) {
      std::this_thread::yield();
    }
    // Consume (not just delete) leftovers: queued values may own
    // resources (heap messages, IOBufs) that only the consumer releases.
    Node* head = _head.exchange(nullptr, std::memory_order_acquire);
    Node* prev = nullptr;
    while (head != nullptr) {  // reverse to FIFO for a faithful last drain
      Node* next = head->next;
      head->next = prev;
      prev = head;
      head = next;
    }
    while (prev != nullptr) {
      _consume(prev->value);
      Node* next = prev->next;
      delete prev;
      prev = next;
    }
  }

  void execute(T value) {
    Node* n = new Node{std::move(value), nullptr};
    Node* old = _head.load(std::memory_order_relaxed);
    do {
      n->next = old;
    } while (!_head.compare_exchange_weak(old, n, std::memory_order_seq_cst,
                                          std::memory_order_relaxed));
    // Become the drainer unless one is already running.  seq_cst on the push
    // and this exchange (and on the drainer's release+recheck) guarantees
    // that either we take the busy flag or the active drainer sees our node.
    if (!_busy.exchange(true, std::memory_order_seq_cst)) {
      submit_drain();
    }
  }

  // Deferred self-deletion for owners that may be destroying the queue
  // from INSIDE one of its own callbacks (a delivered message dropping a
  // socket's last reference): the active drainer — or a freshly submitted
  // one — consumes every remaining value and then deletes the queue.  No
  // thread ever blocks or spins waiting for the drain.  The caller must
  // guarantee no further execute() calls.
  void destroy() {
    _delete_requested.store(true, std::memory_order_seq_cst);
    if (!_busy.exchange(true, std::memory_order_seq_cst)) {
      submit_drain();
    }
  }

 private:
  struct Node {
    T value;
    Node* next;
  };

  void submit_drain() {
    _inflight.fetch_add(1, std::memory_order_acq_rel);
    _ex->submit([](void* arg) {
      auto* self = (ExecutionQueue*)arg;
      if (self->drain()) return;  // deleted itself; no further touch
      self->_inflight.fetch_sub(1, std::memory_order_acq_rel);
    }, this);
  }

  // Returns true when the queue deleted itself (destroy() path).
  bool drain() {
    while (true) {
      Node* head = _head.exchange(nullptr, std::memory_order_seq_cst);
      if (head == nullptr) {
        if (_delete_requested.load(std::memory_order_acquire)) {
          // producers are stopped (destroy contract); we own the busy
          // flag, so nothing else touches the object: balance our
          // submit_drain's inflight and go
          _inflight.fetch_sub(1, std::memory_order_acq_rel);
          delete this;
          return true;
        }
        _busy.store(false, std::memory_order_seq_cst);
        // Recheck BOTH conditions: a producer may have pushed — or
        // destroy() may have been called — between our exchange and the
        // release.  seq_cst on the store/loads guarantees that either we
        // observe the destroy flag here or destroy()'s busy-exchange
        // succeeds and submits its own final drain.
        if ((_head.load(std::memory_order_seq_cst) != nullptr ||
             _delete_requested.load(std::memory_order_seq_cst)) &&
            !_busy.exchange(true, std::memory_order_seq_cst)) {
          continue;
        }
        return false;
      }
      // Reverse to FIFO.
      Node* prev = nullptr;
      while (head != nullptr) {
        Node* next = head->next;
        head->next = prev;
        prev = head;
        head = next;
      }
      while (prev != nullptr) {
        _consume(prev->value);
        Node* next = prev->next;
        delete prev;
        prev = next;
      }
    }
  }

  Executor* _ex;
  std::function<void(T&)> _consume;
  std::atomic<Node*> _head{nullptr};
  std::atomic<bool> _busy{false};
  std::atomic<int> _inflight{0};
  std::atomic<bool> _delete_requested{false};
};

}  // namespace bthread
