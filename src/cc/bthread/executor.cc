#include "bthread/executor.h"

#include "butil/common.h"
#include "butil/flight.h"

namespace bthread {

// ---- WorkStealingQueue (Chase-Lev) ----

WorkStealingQueue::WorkStealingQueue(size_t cap) : _cap(cap) {
  _buf = new std::atomic<TaskNode*>[cap];
}
WorkStealingQueue::~WorkStealingQueue() { delete[] _buf; }

bool WorkStealingQueue::push(TaskNode* t) {
  const int64_t b = _bottom.load(std::memory_order_relaxed);
  const int64_t top = _top.load(std::memory_order_acquire);
  if (b - top >= (int64_t)_cap) return false;
  _buf[b % _cap].store(t, std::memory_order_relaxed);
  _bottom.store(b + 1, std::memory_order_release);
  return true;
}

TaskNode* WorkStealingQueue::pop() {
  int64_t b = _bottom.load(std::memory_order_relaxed);
  if (b == _top.load(std::memory_order_relaxed)) return nullptr;
  --b;
  _bottom.store(b, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  int64_t t = _top.load(std::memory_order_relaxed);
  TaskNode* task = _buf[b % _cap].load(std::memory_order_relaxed);
  if (t < b) return task;  // more than one element left
  bool won = true;
  if (t == b) {
    // Last element: race with thieves via CAS on top.
    won = _top.compare_exchange_strong(t, t + 1, std::memory_order_seq_cst,
                                       std::memory_order_relaxed);
  } else {
    won = false;
  }
  _bottom.store(b + 1, std::memory_order_relaxed);
  return won ? task : nullptr;
}

TaskNode* WorkStealingQueue::steal() {
  int64_t t = _top.load(std::memory_order_acquire);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  const int64_t b = _bottom.load(std::memory_order_acquire);
  if (t >= b) return nullptr;
  TaskNode* task = _buf[t % _cap].load(std::memory_order_relaxed);
  if (!_top.compare_exchange_strong(t, t + 1, std::memory_order_seq_cst,
                                    std::memory_order_relaxed)) {
    return nullptr;
  }
  return task;
}

size_t WorkStealingQueue::volatile_size() const {
  const int64_t b = _bottom.load(std::memory_order_relaxed);
  const int64_t t = _top.load(std::memory_order_relaxed);
  return b > t ? (size_t)(b - t) : 0;
}

// ---- ParkingLot ----

void ParkingLot::signal(int n) {
  {
    std::lock_guard<std::mutex> g(_mu);
    _pending.fetch_add(1, std::memory_order_release);
  }
  if (n >= 2) _cv.notify_all();
  else _cv.notify_one();
}

void ParkingLot::wait(int expected_state) {
  std::unique_lock<std::mutex> g(_mu);
  // If state moved since the caller's snapshot, a signal already happened —
  // don't sleep (the miss-proofing from reference task_group.h:227-229).
  _cv.wait(g, [&] {
    return _pending.load(std::memory_order_acquire) != expected_state ||
           _stopped.load(std::memory_order_acquire);
  });
}

void ParkingLot::stop() {
  {
    std::lock_guard<std::mutex> g(_mu);
    _stopped.store(true, std::memory_order_release);
  }
  _cv.notify_all();
}

// ---- Executor ----

static thread_local Executor* tls_executor = nullptr;
static thread_local int tls_worker_index = -1;

Executor::Executor(int num_workers, const char* tag) : _tag(tag) {
  if (num_workers <= 0) {
    // Reference default is cores+1 (bthread_concurrency).  A floor of 4
    // keeps headroom for blocking handlers (the FLAGS_usercode_in_pthread
    // problem, SURVEY.md §5.10) without the GIL thrash a wide pool causes
    // on small hosts: 8 workers contending for the GIL on a 1-core box
    // scrambled service order and cost ~25% qps + 40% p99 at 64
    // concurrent Python-handler calls vs 4 workers.
    const int hw = (int)std::thread::hardware_concurrency();
    num_workers = hw + 1 > 4 ? hw + 1 : 4;
  }
  _workers.reserve(num_workers);
  for (int i = 0; i < num_workers; ++i) _workers.push_back(new Worker());
  for (int i = 0; i < num_workers; ++i)
    _workers[i]->thread = std::thread([this, i] { worker_main(i); });
}

Executor::~Executor() { stop_and_join(); for (auto* w : _workers) delete w; }

bool Executor::in_worker() const { return tls_executor == this; }

void Executor::submit(TaskFn fn, void* arg) {
  auto* t = new TaskNode{fn, arg};
  const bool is_worker = (tls_executor == this && tls_worker_index >= 0);
  if (is_worker && _workers[tls_worker_index]->rq.push(t)) {
    // Local fast path still signals so siblings can steal (NOSIGNAL batching
    // would go here; round-1 keeps it simple and always signals once).
    _signals.add(1);
    _pl.signal(1);
    return;
  }
  // Remote path: bounded ring.  On full, a FOREIGN thread backpressures
  // (wake workers, yield, retry — the reference spins its remote push the
  // same way, task_group start_background<REMOTE>); a WORKER must never
  // spin waiting for other workers — if every worker is inside submit
  // (tasks spawning tasks at full backlog) nobody is left to drain — so a
  // worker whose local AND remote queues are full parks the task on the
  // unbounded overflow deque.  submit() therefore never executes the task
  // inline on a live executor (inline execution deadlocks a submitter
  // holding a non-reentrant lock the task also takes); only the
  // post-stop path runs inline, when no worker will ever drain.
  // The stopping check lives UNDER the remote mutex: stop_and_join's
  // final drain takes the same mutex after setting _stopping, so a push
  // either lands before that drain (and is consumed by it) or observes
  // _stopping and runs inline — no task can strand in the ring.
  for (;;) {
    bool stopped;
    {
      std::lock_guard<std::mutex> g(_remote_mu);
      stopped = _stopping.load(std::memory_order_acquire);
      if (!stopped && _remote.push(t)) {
        break;
      }
      if (!stopped && is_worker) {
        _overflow.push_back(t);
        break;
      }
    }
    if (stopped) {
      t->fn(t->arg);
      delete t;
      _executed.add(1);
      return;
    }
    _pl.signal(2);
    std::this_thread::yield();
  }
  _signals.add(1);
  _pl.signal(1);
}

struct FnHolder {
  std::function<void()> fn;
};

void run_function_task(void* arg) {
  FnHolder* h = (FnHolder*)arg;
  h->fn();
  delete h;
}

void Executor::submit(std::function<void()> fn) {
  submit(run_function_task, new FnHolder{std::move(fn)});
}

TaskNode* Executor::pop_remote() {
  std::lock_guard<std::mutex> g(_remote_mu);
  // Alternate ring/overflow: either source alone can be refilled faster
  // than it drains (spinning foreign submitters keep the ring full;
  // self-feeding workers at full backlog keep overflow growing), so a
  // fixed priority starves the other side.  Taking turns bounds both
  // waits at one pop each.
  _overflow_turn = !_overflow_turn;
  TaskNode* t = nullptr;
  if (_overflow_turn && !_overflow.empty()) {
    t = _overflow.front();
    _overflow.pop_front();
    return t;
  }
  if (_remote.pop(&t)) return t;
  if (!_overflow.empty()) {
    t = _overflow.front();
    _overflow.pop_front();
    return t;
  }
  return nullptr;
}

TaskNode* Executor::steal_task(int self) {
  const int n = (int)_workers.size();
  // Random-victim sweep (reference task_control.cpp:423).
  for (int attempt = 0; attempt < 2 * n; ++attempt) {
    const int v = (int)butil::fast_rand_less_than(n);
    if (v == self) continue;
    TaskNode* t = _workers[v]->rq.steal();
    if (t != nullptr) {
      _steals.add(1);
      butil::flight::record(butil::flight::EV_STEAL, (uint64_t)v);
      return t;
    }
  }
  return pop_remote();
}

void Executor::worker_main(int index) {
  tls_executor = this;
  tls_worker_index = index;
  butil::flight::set_thread_name("worker/%d", index);
  Worker* w = _workers[index];
  while (!_stopping.load(std::memory_order_acquire)) {
    TaskNode* t = w->rq.pop();
    if (t == nullptr) t = pop_remote();
    if (t == nullptr) t = steal_task(index);
    if (t == nullptr) {
      const int state = _pl.get_state();
      // Re-check after snapshot to close the missed-wakeup window.
      t = pop_remote();
      if (t == nullptr) t = steal_task(index);
      if (t == nullptr) {
        butil::flight::record(butil::flight::EV_PARK, (uint64_t)state);
        _pl.wait(state);
        butil::flight::record(butil::flight::EV_UNPARK);
        continue;
      }
    }
    butil::flight::record(butil::flight::EV_TASK_BEGIN,
                          (uint64_t)(uintptr_t)t->fn);
    t->fn(t->arg);
    butil::flight::record(butil::flight::EV_TASK_END,
                          (uint64_t)(uintptr_t)t->fn);
    delete t;
    _executed.add(1);
  }
  // Drain remaining tasks so shutdown doesn't leak work.
  TaskNode* t;
  while ((t = w->rq.pop()) != nullptr || (t = pop_remote()) != nullptr) {
    t->fn(t->arg);
    delete t;
    _executed.add(1);
  }
  tls_executor = nullptr;
  tls_worker_index = -1;
}

void Executor::stop_and_join() {
  bool expected = false;
  if (!_stopping.compare_exchange_strong(expected, true)) {
    return;
  }
  _pl.stop();
  for (auto* w : _workers)
    if (w->thread.joinable()) w->thread.join();
  // Final drain: a submit may have pushed into the ring after the last
  // worker's exit drain but before observing _stopping.  Taking the same
  // mutex the push used makes this drain see every such task; submits
  // serialized after it observe _stopping and run inline.
  for (;;) {
    TaskNode* t = nullptr;
    {
      std::lock_guard<std::mutex> g(_remote_mu);
      if (!_remote.pop(&t)) {
        if (_overflow.empty()) break;
        t = _overflow.front();
        _overflow.pop_front();
      }
    }
    t->fn(t->arg);
    delete t;
    _executed.add(1);
  }
}

static std::mutex g_global_mu;
static Executor* g_global = nullptr;
static int g_global_workers = 0;

Executor* Executor::global() {
  std::lock_guard<std::mutex> g(g_global_mu);
  if (g_global == nullptr) g_global = new Executor(g_global_workers, "default");
  return g_global;
}

void Executor::init_global(int num_workers) {
  std::lock_guard<std::mutex> g(g_global_mu);
  if (g_global == nullptr) g_global_workers = num_workers;
}

void Executor::shutdown_global() {
  std::lock_guard<std::mutex> g(g_global_mu);
  if (g_global != nullptr) {
    g_global->stop_and_join();
    delete g_global;
    g_global = nullptr;
  }
}

}  // namespace bthread
