// Work-stealing task executor — the TPU-host analog of bthread's
// TaskControl/TaskGroup (SURVEY.md §2.2; reference src/bthread/task_group.*).
//
// Design kept from the reference: per-worker Chase-Lev deques with random-
// victim stealing, a ParkingLot that idle workers sleep on after snapshotting
// its state (so a signal between snapshot and wait is never missed,
// reference task_group.h:227-229), remote submission queue for non-worker
// threads, and worker "tags" (isolated pools) so one service's load cannot
// starve another (task_control.h:39).
//
// Deliberately NOT kept: user-space fcontext stack switching.  Our tasks are
// run-to-completion callbacks; blocking composition is done with
// continuations (the RPC state machine is callback-driven end to end), and
// user Python code runs on its own threads.  This trades bRPC's "block
// anywhere" fiber model for a simpler engine that the XLA host runtime —
// which is itself callback-driven — composes with naturally.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

#include "butil/containers.h"
#include "bvar/combiner.h"

namespace bthread {

typedef void (*TaskFn)(void*);

struct TaskNode {
  TaskFn fn;
  void* arg;
};

// Chase-Lev work-stealing deque over TaskNode pointers
// (reference work_stealing_queue.h:31-120 semantics).
class WorkStealingQueue {
 public:
  explicit WorkStealingQueue(size_t cap = 8192);
  ~WorkStealingQueue();
  bool push(TaskNode* t);    // owner only; false if full
  TaskNode* pop();           // owner only
  TaskNode* steal();         // any thread
  size_t volatile_size() const;

 private:
  std::atomic<int64_t> _top{0};
  std::atomic<int64_t> _bottom{0};
  size_t _cap;
  std::atomic<TaskNode*>* _buf;
};

// Idle-worker parking with a miss-proof state snapshot
// (reference parking_lot.h:31-74).
class ParkingLot {
 public:
  int get_state() const { return _pending.load(std::memory_order_acquire); }
  void signal(int n);
  void wait(int expected_state);
  void stop();
  bool stopped() const { return _stopped.load(std::memory_order_acquire); }

 private:
  std::mutex _mu;
  std::condition_variable _cv;
  std::atomic<int> _pending{0};
  std::atomic<bool> _stopped{false};
};

class Executor {
 public:
  // One tagged worker pool (reference bthread tag).
  explicit Executor(int num_workers, const char* tag = "default");
  ~Executor();

  // Submit from any thread.  Worker threads push to their local deque;
  // foreign threads go through the remote queue + wake.
  void submit(TaskFn fn, void* arg);
  void submit(std::function<void()> fn);

  void stop_and_join();

  int num_workers() const { return (int)_workers.size(); }
  // True if the calling thread is one of this executor's workers.
  bool in_worker() const;

  // bvar combiner counters (per-thread cells, src/cc/bvar/combiner.h):
  // the per-task increments were shared-cacheline fetch_adds bouncing
  // across every worker; now each worker writes its own cell.
  int64_t tasks_executed() const { return _executed.get(); }
  int64_t steals() const { return _steals.get(); }
  int64_t signals() const { return _signals.get(); }

  static Executor* global();            // lazily started default pool
  static void init_global(int num_workers);
  static void shutdown_global();

 private:
  struct Worker {
    WorkStealingQueue rq;
    std::thread thread;
  };

  void worker_main(int index);
  TaskNode* steal_task(int self);
  TaskNode* pop_remote();

  std::string _tag;
  std::vector<Worker*> _workers;
  ParkingLot _pl;
  // Remote submissions: bounded ring under a mutex, the reference's
  // RemoteTaskQueue shape (task_group.h:261).  A full ring backpressures
  // the submitter (signal + yield + retry) instead of growing without
  // bound while workers are wedged.
  std::mutex _remote_mu;
  butil::BoundedQueue<TaskNode*> _remote{kRemoteCapacity};
  // Worker-side overflow: when a WORKER's local deque and the remote ring
  // are both full, the task lands here (unbounded, same mutex) instead of
  // running inline — inline execution made submit() synchronous under
  // load, which deadlocks a submitter holding a non-reentrant lock the
  // task also takes.  Only workers push here, and only at full backlog,
  // so growth is bounded by the burst the workers themselves generate.
  std::deque<TaskNode*> _overflow;
  bool _overflow_turn = false;  // pop_remote alternates ring/overflow
  static constexpr size_t kRemoteCapacity = 1 << 16;
  std::atomic<bool> _stopping{false};
  bvar::Adder _executed, _steals, _signals;
};

// Run std::function tasks through the C-style TaskFn interface.
void run_function_task(void* arg);

}  // namespace bthread
