// Fiber & Task — C++20 coroutine tasks scheduled on the Executor; the
// TPU-host answer to bthread's fcontext fibers (reference
// src/bthread/task_group.cpp:601 sched_to, context.h:84 asm switch).
//
// A fiber's suspension points (co_await Butex::wait, FiberMutex::lock,
// fiber_sleep_us) park a heap frame, not an OS thread: 10k blocked RPCs
// cost 10k small frames on an 8-thread pool — the M:N economics that are
// the whole point of bthread (SURVEY.md §2.2).  Where the reference hides
// the switch behind a pthread-lookalike C API (bthread_start_background /
// bthread_usleep), we surface it in the type system: anything that can
// park is a co_await.  We control the ABI; bRPC had to look like pthreads.
//
// Two coroutine types:
//   Fiber — detached root task (a bthread).  Frame self-destroys at
//           completion; join composes via CountdownEvent, mirroring how
//           bthread_join is butex_wait on the TaskMeta version word.
//   Task  — awaitable child coroutine with symmetric transfer; lets
//           primitives like FiberMutex::lock() loop and re-park.
#pragma once

#include <coroutine>
#include <utility>

#include "bthread/butex.h"
#include "bthread/executor.h"

namespace bthread {

struct Fiber {
  struct promise_type {
    Fiber get_return_object() {
      return Fiber{std::coroutine_handle<promise_type>::from_promise(*this)};
    }
    // Lazy start: the creator decides where the first resume runs
    // (spawn() submits it to the executor).
    std::suspend_always initial_suspend() noexcept { return {}; }
    // Self-destroying: no one observes a finished fiber via the handle.
    std::suspend_never final_suspend() noexcept { return {}; }
    void return_void() noexcept {}
    // A fiber body is a top-level task (like a bthread entry fn); an
    // escaped exception has nowhere to go.  Fail fast.
    void unhandled_exception() noexcept { std::terminate(); }
  };

  std::coroutine_handle<promise_type> handle;

  // Start the fiber on the executor's worker pool.  The handle must not
  // be touched afterwards (the frame may already be gone).
  void spawn(Executor* ex = nullptr) {
    auto h = std::exchange(handle, {});
    (ex ? ex : Executor::global())
        ->submit([](void* p) {
          std::coroutine_handle<>::from_address(p).resume();
        }, h.address());
  }

  // Run the first step inline on the calling thread (tests / callers
  // already on a worker).
  void run_inline() { std::exchange(handle, {}).resume(); }
};

// Awaitable void coroutine: starts when awaited, resumes the awaiter via
// symmetric transfer at completion.  Single-shot, must be co_awaited.
struct [[nodiscard]] Task {
  struct promise_type {
    std::coroutine_handle<> continuation;
    Task get_return_object() {
      return Task{std::coroutine_handle<promise_type>::from_promise(*this)};
    }
    std::suspend_always initial_suspend() noexcept { return {}; }
    struct FinalAwaiter {
      bool await_ready() const noexcept { return false; }
      std::coroutine_handle<> await_suspend(
          std::coroutine_handle<promise_type> h) noexcept {
        // Child stays suspended at final; the Task destructor in the
        // parent frame reclaims it after the parent resumes.
        return h.promise().continuation;
      }
      void await_resume() const noexcept {}
    };
    FinalAwaiter final_suspend() noexcept { return {}; }
    void return_void() noexcept {}
    void unhandled_exception() noexcept { std::terminate(); }
  };

  explicit Task(std::coroutine_handle<promise_type> h) : handle(h) {}
  Task(Task&& o) noexcept : handle(std::exchange(o.handle, {})) {}
  Task(const Task&) = delete;
  ~Task() { if (handle) handle.destroy(); }

  bool await_ready() const noexcept { return false; }
  std::coroutine_handle<> await_suspend(std::coroutine_handle<> cont) {
    handle.promise().continuation = cont;
    return handle;  // symmetric transfer into the child
  }
  void await_resume() const noexcept {}

  std::coroutine_handle<promise_type> handle;
};

// ---- sync primitives over Butex (reference mutex.cpp / countdown_event) --

// futex-classic mutex: value 0 unlocked, 1 locked, 2 locked+maybe-waiters.
// lock() is a Task so the acquire loop can re-park after a wake — the
// wake hands no ownership (same as futex; reference mutex.cpp).
class FiberMutex {
 public:
  Task lock() {
    // two-phase futex mutex (Drepper): uncontended acquire leaves 1, so
    // unlock can tell "nobody ever waited" (prev 1: no wake, no
    // contention sample) from "waiters may exist" (prev 2).  The old
    // always-exchange-2 form made EVERY unlock look contended — it paid
    // a wake() on an empty list per uncontended unlock and flooded the
    // contention sampler with non-events.
    int32_t zero = 0;
    if (_b.value.compare_exchange_strong(zero, 1, std::memory_order_acquire,
                                         std::memory_order_relaxed)) {
      co_return;
    }
    co_await lock_contended();
  }

  bool try_lock() {
    int32_t zero = 0;
    return _b.value.compare_exchange_strong(
        zero, 1, std::memory_order_acquire, std::memory_order_relaxed);
  }

  // Contended-path acquire: always leaves the value at 2 so the next
  // unlock wakes the butex list.  REQUIRED for waiters that may have
  // been requeued onto this mutex (FiberCond wait-morphing): acquiring
  // via the CAS 0->1 fast path would erase the waiters flag while
  // parked waiters still sit on the list, and their wake would never
  // come (found by the stress suite's countdown section).
  Task lock_contended() {
    for (;;) {
      const int32_t prev = _b.value.exchange(2, std::memory_order_acquire);
      if (prev == 0) co_return;
      Butex::note_mutex_contention();
      co_await _b.wait(2);
    }
  }

  void unlock() {
    if (_b.value.exchange(0, std::memory_order_release) == 2) {
      // waiters existed: sample for /hotspots/contention with THIS
      // mutex's address as the site identity (see profiler.cc — the
      // caller frames alone can be eaten by coroutine tail calls)
      Butex::note_contended_unlock(this);
      _b.wake(1);
    }
  }

 private:
  friend class FiberCond;   // wait-morphing requeues onto _b
  Butex _b{0};
};

// Condition variable with WAIT-MORPHING: notify_all wakes one waiter and
// requeues the rest onto the mutex's butex, so they wake one-at-a-time as
// the lock hands over instead of thundering onto it (the reference's
// bthread_cond is butex_requeue for the same reason; butex.h requeue).
class FiberCond {
 public:
  // Caller HOLDS m.  Atomically releases it, parks, and re-acquires
  // before returning (missed-wake-safe: notify bumps the sequence word
  // between our snapshot and the park, which turns the park into a
  // no-op mismatch).
  Task wait(FiberMutex& m) {
    const int32_t seq = _seq.value.load(std::memory_order_acquire);
    m.unlock();
    co_await _seq.wait(seq);
    // re-acquire via the CONTENDED path: this waiter may have been
    // requeued onto m's butex alongside others — see lock_contended()
    co_await m.lock_contended();
  }

  void notify_one() {
    _seq.value.fetch_add(1, std::memory_order_acq_rel);
    _seq.wake(1);
  }

  // m is the mutex waiters passed to wait(); requeue survivors onto it.
  // Best called with m held (the classic discipline); also safe without:
  // if the mutex is FREE there is no holder to hand waiters to, so we
  // fall back to waking everyone (they re-contend through lock()).
  void notify_all(FiberMutex& m) {
    _seq.value.fetch_add(1, std::memory_order_acq_rel);
    // mark the mutex contended (1 -> 2) so the holder's unlock wakes the
    // requeued waiters; blindly storing 2 on a FREE mutex would brick it
    // (every future lock() would park with nobody left to unlock)
    int32_t one = 1;
    if (m._b.value.compare_exchange_strong(one, 2,
                                           std::memory_order_acq_rel,
                                           std::memory_order_acquire) ||
        one == 2) {
      _seq.requeue(&m._b, /*n_wake=*/1);
    } else {
      _seq.wake_all();   // mutex free: no handoff possible; thunder
    }
  }

 private:
  Butex _seq{0};
};

// Counting semaphore (reference bthread/semaphore.cpp shape).
class FiberSemaphore {
 public:
  explicit FiberSemaphore(int permits) : _b(permits) {}

  Task acquire() {
    for (;;) {
      int32_t cur = _b.value.load(std::memory_order_acquire);
      if (cur > 0 &&
          _b.value.compare_exchange_weak(cur, cur - 1,
                                         std::memory_order_acquire,
                                         std::memory_order_relaxed)) {
        co_return;
      }
      if (cur > 0) continue;          // CAS raced; retry the grab
      co_await _b.wait(cur);          // park while empty
    }
  }

  bool try_acquire() {
    int32_t cur = _b.value.load(std::memory_order_acquire);
    while (cur > 0) {
      if (_b.value.compare_exchange_weak(cur, cur - 1,
                                         std::memory_order_acquire,
                                         std::memory_order_relaxed)) {
        return true;
      }
    }
    return false;
  }

  void release(int n = 1) {
    _b.value.fetch_add(n, std::memory_order_acq_rel);
    _b.wake(n);
  }

  int permits() const { return _b.value.load(std::memory_order_acquire); }

 private:
  Butex _b;
};

// Reader/writer lock: state -1 = writer, 0 = free, n>0 = n readers
// (reference bthread/rwlock.cpp role; simple reader-preferring form).
class FiberRwLock {
 public:
  Task lock_shared() {
    for (;;) {
      int32_t s = _b.value.load(std::memory_order_acquire);
      if (s >= 0 &&
          _b.value.compare_exchange_weak(s, s + 1,
                                         std::memory_order_acquire,
                                         std::memory_order_relaxed)) {
        co_return;
      }
      if (s >= 0) continue;           // CAS raced; retry
      co_await _b.wait(s);            // writer holds it: park
    }
  }

  void unlock_shared() {
    if (_b.value.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      _b.wake_all();                  // last reader out: writers may go
    }
  }

  Task lock() {
    for (;;) {
      int32_t s = 0;
      if (_b.value.compare_exchange_weak(s, -1, std::memory_order_acquire,
                                         std::memory_order_relaxed)) {
        co_return;
      }
      if (s == 0) continue;  // spurious CAS failure (weak, LL/SC): the
                             // lock IS free — parking on expected==0
                             // would sleep forever on an unheld lock
      co_await _b.wait(s);   // s holds the observed non-zero value
    }
  }

  void unlock() {
    _b.value.store(0, std::memory_order_release);
    _b.wake_all();
  }

 private:
  Butex _b{0};
};

// Countdown to zero; await parks until it hits zero.  The join primitive
// (reference bthread/countdown_event.{h,cpp}); also how a fiber joins a
// group of fibers.
class CountdownEvent {
 public:
  explicit CountdownEvent(int initial) : _b(initial) {}

  void signal(int n = 1) {
    const int32_t prev = _b.value.fetch_sub(n, std::memory_order_acq_rel);
    if (prev - n <= 0) _b.wake_all();
  }

  Task wait() {
    for (;;) {
      const int32_t cur = _b.value.load(std::memory_order_acquire);
      if (cur <= 0) co_return;
      co_await _b.wait(cur);  // woken at zero, or mismatch => re-check
    }
  }

  int count() const { return _b.value.load(std::memory_order_acquire); }

 private:
  Butex _b;
};

// ---- fiber sleep (reference bthread_usleep -> TimerThread) ----

struct [[nodiscard]] SleepAwaiter {
  int64_t us;
  Butex b{0};
  Butex::Awaiter inner{};
  bool await_ready() const noexcept { return us <= 0; }
  bool await_suspend(std::coroutine_handle<> h) {
    inner = b.wait(0, us);
    return inner.await_suspend(h);  // value never changes: pure timeout
  }
  void await_resume() { (void)inner.await_resume(); }
};

inline SleepAwaiter fiber_sleep_us(int64_t us) { return SleepAwaiter{us}; }

}  // namespace bthread
