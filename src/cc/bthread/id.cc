#include "bthread/id.h"

#include <atomic>
#include <chrono>
#include <mutex>
#include <thread>
#include <vector>

#include "butil/resource_pool.h"

namespace bthread {

namespace {

// One pooled slot.  `first_ver` is the base of the LIVE version range;
// destroy advances it past the whole range, invalidating every
// outstanding handle in one store (the ABA-proof property,
// reference id.cpp Id::first_ver/locked_ver design).
struct IdSlot {
  std::mutex mu;                 // guards the fields below (slow path)
  uint32_t first_ver = 1;        // live range = [first_ver, first_ver+range)
  uint32_t range = 0;            // 0 = dead
  bool locked = false;
  void* data = nullptr;
  Butex lock_butex;              // word bumps on unlock; lockers park
  Butex join_butex;              // word bumps on destroy; joiners park
};

butil::ResourcePool<IdSlot>* pool() {
  return butil::ResourcePool<IdSlot>::singleton();
}

std::atomic<int64_t> g_live{0};

inline IdSlot* slot_of(CallId id, uint32_t* ver) {
  const butil::VersionedId v{id};
  *ver = v.version();
  return pool()->address(v.slot());
}

inline bool version_live(const IdSlot* s, uint32_t ver) {
  return s->range != 0 && ver >= s->first_ver &&
         ver < s->first_ver + s->range;
}

}  // namespace

CallId id_create(void* data, uint32_t range) {
  if (range == 0) range = 1;
  uint32_t slot_index = 0;
  IdSlot* s = pool()->get_resource(&slot_index);
  if (s == nullptr) return INVALID_CALL_ID;
  std::lock_guard<std::mutex> g(s->mu);
  s->range = range;
  s->locked = false;
  s->data = data;
  g_live.fetch_add(1, std::memory_order_relaxed);
  return butil::VersionedId::make(s->first_ver, slot_index).value;
}

bool id_valid(CallId id) {
  uint32_t ver;
  IdSlot* s = slot_of(id, &ver);
  if (s == nullptr) return false;
  std::lock_guard<std::mutex> g(s->mu);
  return version_live(s, ver);
}

int id_trylock(CallId id, void** data_out) {
  uint32_t ver;
  IdSlot* s = slot_of(id, &ver);
  if (s == nullptr) return ID_EINVAL;
  std::lock_guard<std::mutex> g(s->mu);
  if (!version_live(s, ver)) return ID_EINVAL;
  if (s->locked) return ID_EBUSY;
  s->locked = true;
  if (data_out != nullptr) *data_out = s->data;
  return ID_OK;
}

Task id_lock(CallId id, int* rc_out, void** data_out) {
  uint32_t ver;
  IdSlot* s = slot_of(id, &ver);
  if (s == nullptr) {
    *rc_out = ID_EINVAL;
    co_return;
  }
  for (;;) {
    int32_t seq;
    {
      std::lock_guard<std::mutex> g(s->mu);
      if (!version_live(s, ver)) {
        *rc_out = ID_EINVAL;
        co_return;
      }
      if (!s->locked) {
        s->locked = true;
        if (data_out != nullptr) *data_out = s->data;
        *rc_out = ID_OK;
        co_return;
      }
      // snapshot the wake sequence UNDER the slot lock: an unlock after
      // we release the mutex bumps the word and the park mismatches
      seq = s->lock_butex.value.load(std::memory_order_acquire);
    }
    co_await s->lock_butex.wait(seq);
  }
}

int id_unlock(CallId id) {
  uint32_t ver;
  IdSlot* s = slot_of(id, &ver);
  if (s == nullptr) return ID_EINVAL;
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (!version_live(s, ver) || !s->locked) return ID_EINVAL;
    s->locked = false;
    s->lock_butex.value.fetch_add(1, std::memory_order_acq_rel);
  }
  s->lock_butex.wake(1);
  return ID_OK;
}

int id_unlock_and_destroy(CallId id) {
  uint32_t ver;
  IdSlot* s = slot_of(id, &ver);
  if (s == nullptr) return ID_EINVAL;
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (!version_live(s, ver)) return ID_EINVAL;
    if (!s->locked) return ID_EPERM;   // destroy IS an unlock: the caller
                                       // must hold the lock, or an active
                                       // critical section could be ripped
                                       // out from under its owner
                                       // (reference id.cpp contract)
    // advance past the whole range: every handle in [first_ver,
    // first_ver+range) goes stale in one step.  Keep versions growing so
    // a recycled slot never reuses an old version (ABA-proof).
    s->first_ver += s->range;
    s->range = 0;
    s->locked = false;
    s->data = nullptr;
    s->lock_butex.value.fetch_add(1, std::memory_order_acq_rel);
    s->join_butex.value.fetch_add(1, std::memory_order_acq_rel);
  }
  s->lock_butex.wake_all();    // parked lockers resume, see stale, EINVAL
  s->join_butex.wake_all();    // joiners proceed
  g_live.fetch_sub(1, std::memory_order_relaxed);
  pool()->return_resource(butil::VersionedId{id}.slot());
  return ID_OK;
}

Task id_join(CallId id) {
  uint32_t ver;
  IdSlot* s = slot_of(id, &ver);
  if (s == nullptr) co_return;
  for (;;) {
    int32_t seq;
    {
      std::lock_guard<std::mutex> g(s->mu);
      if (!version_live(s, ver)) co_return;   // destroyed (or never live)
      seq = s->join_butex.value.load(std::memory_order_acquire);
    }
    co_await s->join_butex.wait(seq);
  }
}

int id_join_blocking(CallId id, int timeout_ms) {
  const auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(timeout_ms);
  while (id_valid(id)) {
    if (std::chrono::steady_clock::now() > deadline) return ID_ETIMEDOUT;
    std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
  return ID_OK;
}

int64_t id_live_count() { return g_live.load(std::memory_order_relaxed); }

}  // namespace bthread
