// CallId — versioned lockable handles (reference src/bthread/id.{h,cpp},
// list_of_abafree_id.h; SURVEY §2.2 "bthread_id").
//
// The reference's trickiest primitive: a 64-bit handle = version⊕slot
// over a pool.  One live handle maps to one RPC call; lock() serializes
// access to the call's state; unlock_and_destroy() bumps the version so
// every outstanding copy of the handle goes stale ATOMICALLY (the
// ABA-proof property — a late response addressing a finished call fails
// validation instead of racing the next call that reused the slot);
// join() parks until destruction.  RANGED ids give each retry attempt
// its own id value addressing the same slot (controller.h:692-703), so a
// stale attempt can be told apart from the live one by value while both
// still reach the same call state.
//
// Lockers and joiners park as coroutine fibers on the slot's butexes
// (the reference parks bthreads the same way).  Non-blocking try_ and
// polling variants serve pthread/Python callers.
#pragma once

#include <cstdint>

#include "bthread/butex.h"
#include "bthread/fiber.h"

namespace bthread {

typedef uint64_t CallId;
constexpr CallId INVALID_CALL_ID = 0;

// Error codes (subset of errno-style, matching the reference's returns).
enum IdError {
  ID_OK = 0,
  ID_EPERM = 1,      // unlock_and_destroy without holding the lock
  ID_EINVAL = 22,    // stale/invalid handle
  ID_EBUSY = 16,     // try_lock: locked by someone else
  ID_ETIMEDOUT = 110,
};

// Create a live handle covering `range` consecutive versions (range >= 1);
// data rides the slot and comes back from lock().
CallId id_create(void* data = nullptr, uint32_t range = 1);

// The id addressing version k (0-based) within the range is
// id + ((CallId)k << 32): the version lives in the HIGH 32 bits of the
// handle (butil::VersionedId layout), the slot in the low 32 — attempt
// ids differ in version while addressing the same slot (the reference's
// bthread_id_ranged arithmetic, controller.h:692-703).

// Validity check (cheap, racy-by-nature like the reference's).
bool id_valid(CallId id);

// Lock the slot through any id in the live range.  Returns ID_OK with
// *data_out set, or ID_EINVAL when stale.  Fiber-awaitable.
Task id_lock(CallId id, int* rc_out, void** data_out = nullptr);
// Non-blocking variant for pthread/Python callers.
int id_trylock(CallId id, void** data_out = nullptr);

int id_unlock(CallId id);

// Unlock + kill every version in the range: outstanding handles go
// stale, parked lockers resume with ID_EINVAL, joiners wake.  The caller
// MUST hold the lock (ID_EPERM otherwise) — destroy races an active
// critical section otherwise.
int id_unlock_and_destroy(CallId id);

// Park until the id's range is destroyed (returns immediately if
// already stale).  Fiber-awaitable.
Task id_join(CallId id);
// Polling join for pthread/Python callers; ID_OK or ID_ETIMEDOUT.
int id_join_blocking(CallId id, int timeout_ms);

// live slots (tests / console)
int64_t id_live_count();

}  // namespace bthread
