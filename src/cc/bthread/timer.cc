#include "bthread/timer.h"

#include "butil/common.h"
#include "butil/flight.h"

namespace bthread {

TimerThread::TimerThread() { _thread = std::thread([this] { run(); }); }

TimerThread::~TimerThread() { stop_and_join(); }

uint64_t TimerThread::schedule(TimerFn fn, void* arg, int64_t abstime_us) {
  std::lock_guard<std::mutex> g(_mu);
  const uint64_t id = _next_id++;
  _heap.push(Item{abstime_us, id, fn, arg});
  _pending_ids.insert(id);
  // wake only when this timer preempts the current sleep target; a later
  // deadline will be picked up when the thread next wakes anyway
  if (abstime_us < _sleeping_until_us) _cv.notify_one();
  return id;
}

uint64_t TimerThread::schedule_after(TimerFn fn, void* arg, int64_t delay_us) {
  return schedule(fn, arg, butil::monotonic_time_us() + delay_us);
}

bool TimerThread::unschedule(uint64_t id) {
  std::lock_guard<std::mutex> g(_mu);
  // True only if the callback has not run and will not run.  Ids of fired
  // timers are removed from _pending_ids, so both sets stay bounded.
  if (_pending_ids.erase(id) == 0) return false;
  _cancelled.insert(id);
  butil::flight::record(butil::flight::EV_TIMER_CANCEL, id);
  return true;
}

size_t TimerThread::pending() const {
  std::lock_guard<std::mutex> g(_mu);
  return _heap.size();
}

void TimerThread::run() {
  butil::flight::set_thread_name("timer");
  std::unique_lock<std::mutex> g(_mu);
  while (!_stop) {
    if (_heap.empty()) {
      _sleeping_until_us = INT64_MAX;  // any new timer must wake us
      _cv.wait(g);
      _sleeping_until_us = 0;
      continue;
    }
    const Item top = _heap.top();
    const int64_t now = butil::monotonic_time_us();
    if (top.when_us > now) {
      _sleeping_until_us = top.when_us;
      _cv.wait_for(g, std::chrono::microseconds(top.when_us - now));
      _sleeping_until_us = 0;
      continue;
    }
    _heap.pop();
    auto it = _cancelled.find(top.id);
    if (it != _cancelled.end()) {
      _cancelled.erase(it);
      continue;
    }
    _pending_ids.erase(top.id);
    g.unlock();
    butil::flight::record(butil::flight::EV_TIMER_FIRE, top.id);
    top.fn(top.arg);  // fired outside the lock
    _fired.fetch_add(1, std::memory_order_relaxed);
    g.lock();
  }
}

void TimerThread::stop_and_join() {
  {
    std::lock_guard<std::mutex> g(_mu);
    if (_stop) {
      if (!_thread.joinable()) return;
    }
    _stop = true;
    _cv.notify_all();
  }
  if (_thread.joinable()) _thread.join();
}

static std::mutex g_timer_mu;
static TimerThread* g_timer = nullptr;

TimerThread* TimerThread::global() {
  std::lock_guard<std::mutex> g(g_timer_mu);
  if (g_timer == nullptr) g_timer = new TimerThread();
  return g_timer;
}

void TimerThread::shutdown_global() {
  std::lock_guard<std::mutex> g(g_timer_mu);
  if (g_timer != nullptr) {
    g_timer->stop_and_join();
    delete g_timer;
    g_timer = nullptr;
  }
}

}  // namespace bthread
