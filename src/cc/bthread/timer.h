// TimerThread — one dedicated thread firing scheduled callbacks
// (SURVEY.md §2.2; reference src/bthread/timer_thread.{h,cpp}).
//
// The reference shards its schedule lock over 13 hashed buckets and sleeps on
// a futex keyed by the nearest run time.  We keep the single dedicated
// thread + nearest-deadline sleep, but use one mutex + min-heap with lazy
// cancellation (version-checked ids): timer insertion is off the RPC fast
// path in our design (timeouts are armed per call, fired rarely), so bucket
// sharding is deferred until contention shows up in the bvar counters.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_set>
#include <vector>

namespace bthread {

typedef void (*TimerFn)(void*);

class TimerThread {
 public:
  TimerThread();
  ~TimerThread();

  // Run fn(arg) at absolute monotonic time `abstime_us`; returns timer id.
  uint64_t schedule(TimerFn fn, void* arg, int64_t abstime_us);
  uint64_t schedule_after(TimerFn fn, void* arg, int64_t delay_us);
  // Best-effort cancel; returns true if the timer had not fired yet.
  bool unschedule(uint64_t id);

  void stop_and_join();

  int64_t fired() const { return _fired.load(std::memory_order_relaxed); }
  size_t pending() const;

  static TimerThread* global();
  static void shutdown_global();

 private:
  struct Item {
    int64_t when_us;
    uint64_t id;
    TimerFn fn;
    void* arg;
    bool operator>(const Item& o) const { return when_us > o.when_us; }
  };

  void run();

  mutable std::mutex _mu;
  std::condition_variable _cv;
  std::priority_queue<Item, std::vector<Item>, std::greater<Item>> _heap;
  std::unordered_set<uint64_t> _cancelled;
  std::unordered_set<uint64_t> _pending_ids;  // scheduled, not yet fired
  uint64_t _next_id = 1;
  // deadline the run() loop is currently sleeping toward; schedule() only
  // wakes the thread when a NEW nearest arrives (the reference
  // TimerThread's nearest_run_time discipline, timer_thread.cpp) — without
  // this every RPC's deadline arm costs a futex wake + context switch
  int64_t _sleeping_until_us = 0;
  bool _stop = false;
  std::atomic<int64_t> _fired{0};
  std::thread _thread;
};

}  // namespace bthread
