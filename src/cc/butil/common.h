// Base definitions for the tpu-rpc native core.
//
// This library is a from-scratch TPU-host runtime shaped like bRPC's butil
// layer (reference: /root/reference/src/butil).  It is NOT a port: the code
// here is new, written against the behavioral spec in SURVEY.md §2.1.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

namespace butil {

inline int64_t monotonic_time_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

inline int64_t monotonic_time_us() { return monotonic_time_ns() / 1000; }

// Cheap cycle counter for hot-loop timestamping (reference butil
// cpuwide_time_us, src/butil/time.h — TSC with calibrated frequency).
// x86 rdtsc is ~8ns vs ~25ns for the vdso clock_gettime; on other arches
// fall back to the clock.  Use cpuwide_time_us() ONLY for intervals (the
// epoch is arbitrary); calibration is one-time, invariant-TSC assumed
// (every x86_64 this decade).
#if defined(__x86_64__)
inline uint64_t rdtsc() { return __builtin_ia32_rdtsc(); }
// Calibration data, eagerly initialized at library load (logging.cc) so
// the read path below is branch-and-guard-free.
struct TscCalib {
  uint64_t tsc0;
  int64_t ns0;
  double ns_per_tick;
};
extern TscCalib g_tsc_calib;
inline int64_t cpuwide_time_us() {
  return g_tsc_calib.ns0 / 1000 +
         int64_t(double(rdtsc() - g_tsc_calib.tsc0) *
                 g_tsc_calib.ns_per_tick) /
             1000;
}
#else
inline int64_t cpuwide_time_us() { return monotonic_time_us(); }
#endif

inline int64_t realtime_time_us() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return ts.tv_sec * 1000000LL + ts.tv_nsec / 1000;
}

// xorshift128+ thread-local fast rand (the role fast_rand.cpp plays in the
// reference: cheap per-thread randomness for work stealing victims etc).
inline uint64_t fast_rand() {
  static thread_local uint64_t s0 = 0, s1 = 0;
  if (s0 == 0 && s1 == 0) {
    s0 = monotonic_time_ns() ^ (uint64_t)(uintptr_t)&s0;
    s1 = s0 * 0x9E3779B97F4A7C15ULL + 1;
  }
  uint64_t x = s0;
  const uint64_t y = s1;
  s0 = y;
  x ^= x << 23;
  s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
  return s1 + y;
}

inline uint64_t fast_rand_less_than(uint64_t bound) {
  return bound ? fast_rand() % bound : 0;
}

// Minimal leveled logging with a pluggable sink (SURVEY.md §2.1 "Logging").
enum LogLevel { LOG_DEBUG = 0, LOG_INFO = 1, LOG_WARNING = 2, LOG_ERROR = 3, LOG_FATAL = 4 };

typedef void (*LogSinkFn)(int level, const char* msg, void* arg);

void set_log_sink(LogSinkFn fn, void* arg);
void set_min_log_level(int level);

// crc32c (Castagnoli; butil/crc32c.cc) — chained: pass the previous
// call's result as init_crc to checksum split buffers.
unsigned int crc32c(const void* data, unsigned long n,
                    unsigned int init_crc = 0);

// Native CPU profiler (butil/profiler.cc): SIGPROF sampling, legacy
// pprof binary dump + folded-stacks text.
int prof_start(int hz);
int prof_stop();                 // returns samples collected, -1 if idle
int prof_dump(const char* path); // legacy pprof format + /proc/self/maps
int prof_folded(char* out, unsigned long cap);
long long prof_sample_count();
// Contention sampler (event-driven; FiberMutex contended-lock hook).
// Always armed — capture is rate-bounded, so steady state costs one
// atomic per contention event.
void contention_note(const void* lock_addr);
int contention_folded(char* out, unsigned long cap);
int64_t contention_event_count();
int64_t contention_sample_count();
void contention_reset();
// IOBuf block-allocation-site sampler (reference butil/iobuf_profiler.h
// analog): sampled in iobuf create_block, same ring/rate machinery.
void iobuf_alloc_note();
int iobuf_alloc_folded(char* out, unsigned long cap);
int64_t iobuf_alloc_event_count();
int64_t iobuf_alloc_sample_count();
void iobuf_alloc_reset();
int min_log_level();
void log_message(int level, const char* fmt, ...) __attribute__((format(printf, 2, 3)));

#define BLOG(level, ...)                                        \
  do {                                                          \
    if ((int)(butil::LOG_##level) >= butil::min_log_level())    \
      butil::log_message(butil::LOG_##level, __VA_ARGS__);      \
  } while (0)

}  // namespace butil
