// Small containers (SURVEY.md §2.1 "other containers" row; reference
// src/butil/containers/bounded_queue.h, mpsc_queue.h).
//
// BoundedQueue: fixed-capacity ring over raw storage.  NOT thread-safe —
// callers bring their own lock, exactly like the reference's
// RemoteTaskQueue (bounded_queue under the TaskGroup's remote mutex,
// task_group.h:261).  Used here as Executor's remote submission queue so a
// burst of foreign-thread submissions is backpressured at a fixed memory
// bound instead of growing a deque without limit.
#pragma once

#include <cstddef>
#include <new>
#include <utility>

namespace butil {

template <typename T>
class BoundedQueue {
 public:
  explicit BoundedQueue(size_t cap)
      : _cap(cap),
        _buf(static_cast<T*>(::operator new[](sizeof(T) * cap))) {}

  BoundedQueue(const BoundedQueue&) = delete;
  BoundedQueue& operator=(const BoundedQueue&) = delete;

  ~BoundedQueue() {
    clear();
    ::operator delete[](_buf);
  }

  bool push(T v) {
    if (_size >= _cap) return false;
    new (&_buf[(_start + _size) % _cap]) T(std::move(v));
    ++_size;
    return true;
  }

  bool pop(T* out) {
    if (_size == 0) return false;
    T& slot = _buf[_start];
    *out = std::move(slot);
    slot.~T();
    _start = (_start + 1) % _cap;
    --_size;
    return true;
  }

  void clear() {
    while (_size > 0) {
      _buf[_start].~T();
      _start = (_start + 1) % _cap;
      --_size;
    }
  }

  bool empty() const { return _size == 0; }
  bool full() const { return _size >= _cap; }
  size_t size() const { return _size; }
  size_t capacity() const { return _cap; }

 private:
  size_t _cap;
  T* _buf;
  size_t _start = 0;
  size_t _size = 0;
};

}  // namespace butil
