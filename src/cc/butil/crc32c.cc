// crc32c (Castagnoli) — the reference vendors an SSE4.2 crc32c
// (butil/crc32c.cc); same role here: payload checksums for recordio /
// rpc_dump and user code.  Hardware path uses the SSE4.2 CRC32
// instruction when the CPU has it; fallback is the standard table-driven
// form.  Polynomial 0x1EDC6F41 (reflected 0x82F63B78), init/final XOR
// 0xFFFFFFFF — matches every other crc32c implementation bit for bit.
#include "butil/common.h"

#include <cstdint>
#include <cstring>

#if defined(__x86_64__)
#include <cpuid.h>
#include <nmmintrin.h>
#endif

namespace butil {

namespace {

uint32_t* software_table() {
  static uint32_t table[256];
  static bool init = [] {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      }
      table[i] = c;
    }
    return true;
  }();
  (void)init;
  return table;
}

uint32_t crc32c_sw(uint32_t crc, const void* data, size_t n) {
  const uint32_t* t = software_table();
  const uint8_t* p = (const uint8_t*)data;
  for (size_t i = 0; i < n; ++i) {
    crc = t[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  }
  return crc;
}

#if defined(__x86_64__)
bool cpu_has_sse42() {
  unsigned eax, ebx, ecx, edx;
  if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
  return (ecx & bit_SSE4_2) != 0;
}

__attribute__((target("sse4.2")))
uint32_t crc32c_hw(uint32_t crc, const void* data, size_t n) {
  const uint8_t* p = (const uint8_t*)data;
  while (n >= 8) {
    uint64_t v;
    memcpy(&v, p, 8);
    crc = (uint32_t)_mm_crc32_u64(crc, v);
    p += 8;
    n -= 8;
  }
  while (n > 0) {
    crc = _mm_crc32_u8(crc, *p);
    ++p;
    --n;
  }
  return crc;
}
#endif

}  // namespace

unsigned int crc32c(const void* data, unsigned long n,
                    unsigned int init_crc) {
  uint32_t crc = init_crc ^ 0xFFFFFFFFu;
#if defined(__x86_64__)
  static const bool hw = cpu_has_sse42();
  crc = hw ? crc32c_hw(crc, data, n) : crc32c_sw(crc, data, n);
#else
  crc = crc32c_sw(crc, data, n);
#endif
  return crc ^ 0xFFFFFFFFu;
}

}  // namespace butil
