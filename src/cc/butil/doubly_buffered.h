// DoublyBufferedData — fg/bg double buffer with near-lock-free reads.
//
// The reference's butil/containers/doubly_buffered_data.h:38-75 design:
// every reader thread owns a thread-local mutex it locks around a read of
// the foreground copy (uncontended in steady state — one CAS each way);
// a writer mutates the background copy, flips the index, then serially
// acquires and releases every reader's mutex — after that no reader can
// still be inside the old foreground — and finally applies the same
// mutation to the (new) background so both copies converge.  Backs every
// hot read-mostly registry (load-balancer server lists, the native method
// map).
#pragma once

#include <pthread.h>

#include <atomic>
#include <mutex>
#include <vector>

namespace butil {

template <typename T>
class DoublyBufferedData {
 public:
  class ScopedPtr {
   public:
    ScopedPtr() = default;
    ~ScopedPtr() {
      if (_mu != nullptr) _mu->unlock();
    }
    ScopedPtr(const ScopedPtr&) = delete;
    ScopedPtr& operator=(const ScopedPtr&) = delete;
    const T* get() const { return _data; }
    const T& operator*() const { return *_data; }
    const T* operator->() const { return _data; }

   private:
    friend class DoublyBufferedData;
    const T* _data = nullptr;
    std::mutex* _mu = nullptr;
  };

  DoublyBufferedData() { pthread_key_create(&_tls_key, nullptr); }
  ~DoublyBufferedData() {
    pthread_key_delete(_tls_key);
    for (Wrapper* w : _wrappers) delete w;
  }

  // Acquire a read handle to the foreground copy.  The handle holds this
  // thread's own mutex; destroy it promptly.
  void Read(ScopedPtr* out) {
    Wrapper* w = tls_wrapper();
    w->mu.lock();
    out->_data = &_data[_index.load(std::memory_order_acquire)];
    out->_mu = &w->mu;
  }

  // Apply fn to both copies with the flip protocol.  fn(T&) -> bool
  // (false = no change, skip the flip).  Serialized across writers.
  template <typename Fn>
  bool Modify(Fn&& fn) {
    std::lock_guard<std::mutex> lk(_modify_mu);
    const int bg = 1 - _index.load(std::memory_order_relaxed);
    if (!fn(_data[bg])) return false;
    _index.store(bg, std::memory_order_release);
    {
      // wait out readers still holding the old foreground
      std::lock_guard<std::mutex> wk(_wrappers_mu);
      for (Wrapper* w : _wrappers) {
        w->mu.lock();
        w->mu.unlock();
      }
    }
    fn(_data[1 - bg]);  // converge the other copy (now background)
    return true;
  }

 private:
  struct Wrapper {
    std::mutex mu;
  };

  Wrapper* tls_wrapper() {
    auto* w = static_cast<Wrapper*>(pthread_getspecific(_tls_key));
    if (w == nullptr) {
      w = new Wrapper;
      pthread_setspecific(_tls_key, w);
      std::lock_guard<std::mutex> lk(_wrappers_mu);
      _wrappers.push_back(w);
    }
    return w;
  }

  T _data[2];
  std::atomic<int> _index{0};
  pthread_key_t _tls_key;
  std::mutex _modify_mu;
  std::mutex _wrappers_mu;
  // wrappers live until the map dies; threads that exit leave their
  // wrapper behind (same tradeoff as the reference)
  std::vector<Wrapper*> _wrappers;
};

}  // namespace butil
