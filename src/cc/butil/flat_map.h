// FlatMap — open-addressing (linear-probe) hash map.
//
// Role of the reference's butil/containers/flat_map.h: the lookup structure
// behind the server's service/method maps (reference server.h:399,432).
// Power-of-two capacity, backward-shift deletion (no tombstones), resize at
// ~70% load.  Not thread-safe — writers wrap it in DoublyBufferedData.
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>
#include <utility>
#include <vector>

namespace butil {

template <typename K, typename V, typename Hash = std::hash<K>,
          typename Eq = std::equal_to<K>>
class FlatMap {
 public:
  explicit FlatMap(size_t initial_cap = 16) { rehash(pow2_at_least(initial_cap)); }

  // Insert or overwrite.
  template <typename KeyT>
  void insert(KeyT&& key, V value) {
    if ((_size + 1) * 10 >= _buckets.size() * 7) rehash(_buckets.size() * 2);
    const size_t mask = _buckets.size() - 1;
    size_t i = _hash(key) & mask;
    while (true) {
      Bucket& b = _buckets[i];
      if (!b.used) {
        b.used = true;
        b.kv.first = std::forward<KeyT>(key);
        b.kv.second = std::move(value);
        ++_size;
        return;
      }
      if (_eq(b.kv.first, key)) {
        b.kv.second = std::move(value);
        return;
      }
      i = (i + 1) & mask;
    }
  }

  // Heterogeneous lookup: LookupT only needs Hash(LookupT) and
  // Eq(K, LookupT) — lets string maps be probed with string_view without
  // allocating.
  template <typename LookupT>
  const V* seek(const LookupT& key) const {
    const size_t mask = _buckets.size() - 1;
    size_t i = _hash(key) & mask;
    while (true) {
      const Bucket& b = _buckets[i];
      if (!b.used) return nullptr;
      if (_eq(b.kv.first, key)) return &b.kv.second;
      i = (i + 1) & mask;
    }
  }

  template <typename LookupT>
  V* seek(const LookupT& key) {
    return const_cast<V*>(static_cast<const FlatMap*>(this)->seek(key));
  }

  // Backward-shift deletion keeps probe chains contiguous without
  // tombstones.  Returns true if the key existed.
  template <typename LookupT>
  bool erase(const LookupT& key) {
    const size_t mask = _buckets.size() - 1;
    size_t i = _hash(key) & mask;
    while (true) {
      Bucket& b = _buckets[i];
      if (!b.used) return false;
      if (_eq(b.kv.first, key)) break;
      i = (i + 1) & mask;
    }
    size_t hole = i;
    while (true) {
      i = (i + 1) & mask;
      Bucket& b = _buckets[i];
      if (!b.used) break;
      const size_t home = _hash(b.kv.first) & mask;
      // can b legally move into the hole? (its home must not lie strictly
      // between hole and current slot in probe order)
      const size_t dist_home = (i - home) & mask;
      const size_t dist_hole = (i - hole) & mask;
      if (dist_home >= dist_hole) {
        _buckets[hole].kv = std::move(b.kv);
        hole = i;
      }
    }
    _buckets[hole].used = false;
    _buckets[hole].kv = {};
    --_size;
    return true;
  }

  size_t size() const { return _size; }
  bool empty() const { return _size == 0; }
  void clear() {
    for (auto& b : _buckets) { b.used = false; b.kv = {}; }
    _size = 0;
  }

  // Iterate all entries: fn(const K&, const V&).
  template <typename Fn>
  void for_each(Fn&& fn) const {
    for (const auto& b : _buckets)
      if (b.used) fn(b.kv.first, b.kv.second);
  }

 private:
  struct Bucket {
    bool used = false;
    std::pair<K, V> kv;
  };

  static size_t pow2_at_least(size_t n) {
    size_t c = 16;
    while (c < n) c <<= 1;
    return c;
  }

  void rehash(size_t new_cap) {
    std::vector<Bucket> old = std::move(_buckets);
    _buckets.assign(new_cap, Bucket{});
    _size = 0;
    for (auto& b : old)
      if (b.used) insert(std::move(b.kv.first), std::move(b.kv.second));
  }

  std::vector<Bucket> _buckets;
  size_t _size = 0;
  Hash _hash;
  Eq _eq;
};

}  // namespace butil
