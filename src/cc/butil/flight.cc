#include "butil/flight.h"

#include <stdarg.h>
#include <stdio.h>
#include <string.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <algorithm>
#include <mutex>
#include <vector>

#include "butil/common.h"

// NOTE: this TU is linked both into libbrpc_core.so and (standalone,
// with serving_hotpath.cc) into the `make tsan` ring-stress binary — it
// must not reference logging.cc/profiler.cc symbols (no BLOG here).

namespace butil {
namespace flight {

namespace {

std::atomic<bool> g_enabled{true};
std::atomic<ThreadRing*> g_rings{nullptr};
std::atomic<int64_t> g_ring_count{0};
// Rings retired by exited threads, awaiting reuse (plain mutex: thread
// birth/death is cold).  Events recorded on rings that were later
// recycled accumulate here so stats() stays cumulative.
std::mutex g_free_mu;
ThreadRing* g_free = nullptr;
std::atomic<int64_t> g_retired_events{0};
std::atomic<int64_t> g_retired_dropped{0};

void pack_name(ThreadRing* r, const char* name) {
  char tmp[16];
  memset(tmp, 0, sizeof(tmp));
  strncpy(tmp, name, sizeof(tmp) - 1);
  uint64_t lo, hi;
  memcpy(&lo, tmp, 8);
  memcpy(&hi, tmp + 8, 8);
  r->name_lo.store(lo, std::memory_order_relaxed);
  r->name_hi.store(hi, std::memory_order_relaxed);
}

void unpack_name(const ThreadRing* r, char out[16]) {
  uint64_t lo = r->name_lo.load(std::memory_order_relaxed);
  uint64_t hi = r->name_hi.load(std::memory_order_relaxed);
  memcpy(out, &lo, 8);
  memcpy(out + 8, &hi, 8);
  out[15] = 0;
  if (out[0] == 0) strcpy(out, "ext");
}

ThreadRing* register_thread() {
  const uint64_t tid = (uint64_t)syscall(SYS_gettid);
  {
    // reuse a retired ring first: per-request threads register at
    // serving rates and must not leak 64KB each
    std::lock_guard<std::mutex> g(g_free_mu);
    if (g_free != nullptr) {
      ThreadRing* r = g_free;
      g_free = r->free_next;
      r->free_next = nullptr;
      const uint64_t h = r->head.load(std::memory_order_relaxed);
      g_retired_events.fetch_add((int64_t)h, std::memory_order_relaxed);
      if (h > kRingCap) {
        g_retired_dropped.fetch_add((int64_t)(h - kRingCap),
                                    std::memory_order_relaxed);
      }
      // head back to 0 republishes the ring empty: collect() only
      // reads slots below head, so the previous occupant's events
      // become unreachable without touching the 2048 version words
      r->head.store(0, std::memory_order_release);
      r->name_lo.store(0, std::memory_order_relaxed);
      r->name_hi.store(0, std::memory_order_relaxed);
      r->tid.store(tid, std::memory_order_relaxed);
      r->live.store(true, std::memory_order_release);
      return r;
    }
  }
  auto* r = new ThreadRing();
  r->tid.store(tid, std::memory_order_relaxed);
  ThreadRing* head = g_rings.load(std::memory_order_acquire);
  do {
    r->next = head;
  } while (!g_rings.compare_exchange_weak(head, r,
                                          std::memory_order_acq_rel,
                                          std::memory_order_acquire));
  g_ring_count.fetch_add(1, std::memory_order_relaxed);
  return r;
}

// A ring whose thread exited stays on the registration list (marked
// !live, events intact — a wedge autopsy can still show what a dead
// thread last did) AND goes onto the recycle list for the next
// registering thread, so the ring population is bounded by the peak
// CONCURRENT thread count, not by thread churn.
struct TlsHolder {
  ThreadRing* ring = nullptr;
  ~TlsHolder() {
    if (ring != nullptr) {
      ring->live.store(false, std::memory_order_release);
      std::lock_guard<std::mutex> g(g_free_mu);
      ring->free_next = g_free;
      g_free = ring;
    }
  }
};
thread_local TlsHolder tls_holder;

inline ThreadRing* my_ring() {
  ThreadRing* r = tls_holder.ring;
  if (r == nullptr) {
    r = register_thread();
    tls_holder.ring = r;
  }
  return r;
}

// Validated read of one slot: true when the copy is a complete event
// (version even, unchanged across the field reads).  *seq_out is the
// event's ring sequence.
bool read_slot(const Event& e, int64_t* ts, uint64_t* a, int32_t* b,
               uint16_t* kind, uint64_t* seq_out) {
  const uint64_t v1 = e.ver.load(std::memory_order_acquire);
  if (v1 == 0 || (v1 & 1) != 0) return false;  // empty or mid-write
  *ts = e.ts_us.load(std::memory_order_relaxed);
  *a = e.a.load(std::memory_order_relaxed);
  *b = e.b.load(std::memory_order_relaxed);
  *kind = e.kind.load(std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_acquire);
  const uint64_t v2 = e.ver.load(std::memory_order_relaxed);
  if (v1 != v2) return false;                  // overwritten mid-copy
  if (*kind >= EV_KIND_MAX) return false;      // belt and braces
  *seq_out = v2 / 2 - 1;
  return true;
}

struct DumpEvent {
  int64_t ts;
  uint64_t seq;
  uint64_t tid;
  uint64_t a;
  int32_t b;
  uint16_t kind;
  char name[16];
};

}  // namespace

const char* kind_name(uint16_t k) {
  switch (k) {
    case EV_NONE: return "none";
    case EV_TASK_BEGIN: return "task_begin";
    case EV_TASK_END: return "task_end";
    case EV_STEAL: return "steal";
    case EV_PARK: return "park";
    case EV_UNPARK: return "unpark";
    case EV_BUTEX_WAIT: return "butex_wait";
    case EV_BUTEX_WAKE: return "butex_wake";
    case EV_BUTEX_TIMEOUT: return "butex_timeout";
    case EV_TIMER_FIRE: return "timer_fire";
    case EV_TIMER_CANCEL: return "timer_cancel";
    case EV_SOCK_CREATE: return "sock_create";
    case EV_SOCK_EPOLLIN: return "sock_epollin";
    case EV_READ_ENTER: return "read_enter";
    case EV_READ_EXIT: return "read_exit";
    case EV_WRITE_ENTER: return "write_enter";
    case EV_WRITE_EXIT: return "write_exit";
    case EV_SOCK_FAILED: return "sock_failed";
    case EV_SOCK_CLOSE: return "sock_close";
    case EV_RING_PUSH: return "ring_push";
    case EV_RING_FULL: return "ring_full";
    case EV_RING_POP: return "ring_pop";
    case EV_RING_TERMINAL: return "ring_terminal";
    case EV_SPANQ_DRAIN: return "spanq_drain";
    case EV_PROBE: return "probe";
    default: return "?";
  }
}

bool enabled() { return g_enabled.load(std::memory_order_relaxed); }

void set_enabled(bool on) {
  g_enabled.store(on, std::memory_order_relaxed);
}

void record(uint16_t kind, uint64_t a, int64_t b) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadRing* r = my_ring();
  const uint64_t h = r->head.load(std::memory_order_relaxed);
  Event& e = r->buf[h & (kRingCap - 1)];
  // seqlock write: odd while the fields are in flux, even when done.
  e.ver.store(2 * (h + 1) - 1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  e.ts_us.store(monotonic_time_us(), std::memory_order_relaxed);
  e.a.store(a, std::memory_order_relaxed);
  const int64_t clamped =
      b > INT32_MAX ? INT32_MAX : (b < INT32_MIN ? INT32_MIN : b);
  e.b.store((int32_t)clamped, std::memory_order_relaxed);
  e.kind.store(kind, std::memory_order_relaxed);
  e.ver.store(2 * (h + 1), std::memory_order_release);
  r->head.store(h + 1, std::memory_order_release);
}

void set_thread_name(const char* fmt, ...) {
  char buf[16];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(buf, sizeof(buf), fmt, ap);
  va_end(ap);
  pack_name(my_ring(), buf);
}

namespace {

// Collect every consistent event from every ring into `out`.
void collect(std::vector<DumpEvent>* out) {
  for (ThreadRing* r = g_rings.load(std::memory_order_acquire);
       r != nullptr; r = r->next) {
    char name[16];
    unpack_name(r, name);
    const uint64_t h = r->head.load(std::memory_order_acquire);
    const uint64_t n = h < kRingCap ? h : kRingCap;
    for (uint64_t i = 0; i < n; ++i) {
      const Event& e = r->buf[i];
      DumpEvent d;
      if (!read_slot(e, &d.ts, &d.a, &d.b, &d.kind, &d.seq)) continue;
      d.tid = r->tid.load(std::memory_order_relaxed);
      memcpy(d.name, name, sizeof(d.name));
      out->push_back(d);
    }
  }
}

}  // namespace

int dump(char* out, size_t cap, int max_events) {
  if (out == nullptr || cap == 0) return 0;
  out[0] = 0;
  std::vector<DumpEvent> evs;
  evs.reserve(1024);
  collect(&evs);
  std::sort(evs.begin(), evs.end(),
            [](const DumpEvent& x, const DumpEvent& y) {
              if (x.ts != y.ts) return x.ts < y.ts;
              if (x.tid != y.tid) return x.tid < y.tid;
              return x.seq < y.seq;
            });
  size_t first = 0;
  if (max_events > 0 && evs.size() > (size_t)max_events) {
    first = evs.size() - (size_t)max_events;
  }
  size_t off = 0;
  for (size_t i = first; i < evs.size(); ++i) {
    const DumpEvent& d = evs[i];
    const int n = snprintf(out + off, cap - off,
                           "%lld %llu %s %s a=0x%llx b=%d\n",
                           (long long)d.ts, (unsigned long long)d.tid,
                           d.name, kind_name(d.kind),
                           (unsigned long long)d.a, (int)d.b);
    if (n < 0 || (size_t)n >= cap - off) {
      out[off] = 0;  // truncate at a line boundary
      break;
    }
    off += (size_t)n;
  }
  return (int)off;
}

int threads_table(char* out, size_t cap) {
  if (out == nullptr || cap == 0) return 0;
  out[0] = 0;
  const int64_t now = monotonic_time_us();
  size_t off = 0;
  for (ThreadRing* r = g_rings.load(std::memory_order_acquire);
       r != nullptr; r = r->next) {
    char name[16];
    unpack_name(r, name);
    const uint64_t h = r->head.load(std::memory_order_acquire);
    const int64_t dropped =
        h > kRingCap ? (int64_t)(h - kRingCap) : 0;
    const char* last_kind = "-";
    int64_t age_us = -1;
    if (h > 0) {
      const Event& e = r->buf[(h - 1) & (kRingCap - 1)];
      int64_t ts;
      uint64_t a, seq;
      int32_t b;
      uint16_t kind;
      if (read_slot(e, &ts, &a, &b, &kind, &seq)) {
        last_kind = kind_name(kind);
        age_us = now - ts;
      }
    }
    const int n = snprintf(
        out + off, cap - off,
        "%llu %s %s events=%llu dropped=%lld last=%s age_us=%lld\n",
        (unsigned long long)r->tid.load(std::memory_order_relaxed), name,
        r->live.load(std::memory_order_acquire) ? "live" : "exited",
        (unsigned long long)h, (long long)dropped, last_kind,
        (long long)age_us);
    if (n < 0 || (size_t)n >= cap - off) {
      out[off] = 0;
      break;
    }
    off += (size_t)n;
  }
  return (int)off;
}

void stats(int64_t* events, int64_t* threads, int64_t* dropped) {
  // cumulative: live ring heads + events retired when rings recycled
  int64_t ev = g_retired_events.load(std::memory_order_relaxed);
  int64_t dr = g_retired_dropped.load(std::memory_order_relaxed);
  for (ThreadRing* r = g_rings.load(std::memory_order_acquire);
       r != nullptr; r = r->next) {
    const uint64_t h = r->head.load(std::memory_order_acquire);
    ev += (int64_t)h;
    if (h > kRingCap) dr += (int64_t)(h - kRingCap);
  }
  if (events) *events = ev;
  if (threads) *threads = g_ring_count.load(std::memory_order_relaxed);
  if (dropped) *dropped = dr;
}

}  // namespace flight
}  // namespace butil
