// Native flight recorder (ISSUE 15) — always-on per-thread event rings.
//
// PR 14 proved the intermittent tier-1 wedge is NOT a Python lock cycle
// (the runtime witness saw zero nesting edges under the full native
// modules), which leaves the root cause in the one layer the repo could
// not see: the native executor / butex / socket core.  rpcz spans, the
// /hotspots sampler and the lockprof ledger all stop at the ctypes
// boundary.  This is the in-core answer, in the bvar tradition: every
// load-bearing transition (executor task begin/end, steal, park/unpark,
// butex wait/wake/timeout, timer fire/cancel, socket lifecycle + read/
// write syscalls, TokenRing batch push/pop/terminal) records one
// fixed-size 32-byte event into the calling thread's bounded ring.
//
// Design constraints, in order:
//   * Always-on: rings overwrite-oldest, so there is nothing to arm and
//     nothing to leak — the last ~2048 transitions per thread are
//     simply always there when a wedge autopsy needs them.  Rings of
//     EXITED threads go onto a recycle list and are reused by the next
//     registering thread (per-request emitter threads must not leak a
//     64KB ring each at serving scale); until reuse they keep their
//     events, so a dead thread's tail is still dumpable.
//   * Near-zero hot-path cost: one relaxed enabled-flag load, one TLS
//     pointer read, four relaxed atomic stores and a vDSO clock read —
//     no locks, no allocation, no syscalls.  Gated <2% on the echo and
//     emit_fanout bench rungs (bench.py microbench "flight_recorder").
//   * Torn-read-proof dumps: each slot carries a seqlock version word
//     (odd while the owner writes, even when complete), so a dump
//     taken WHILE every thread keeps writing returns only consistent
//     events — a slot overwritten mid-copy either fails the version
//     double-check and is dropped, or yields the complete newer event.
//     All fields are relaxed atomics, which also keeps `make tsan`'s
//     ring stress sound (no seqlock false positives).
//
// Granularity note: TokenRing events are recorded per CALL (push_many /
// pop_many / terminal / full-ring push failure), not per token — the
// per-token single-push path is the emit_fanout hot loop and a per-token
// event would blow the <2% overhead gate while adding nothing a
// per-batch event does not show.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>

namespace butil {
namespace flight {

// Event kinds.  Append-only: the dump format names them, and tools
// parse the names, not the values.
enum EventKind : uint16_t {
  EV_NONE = 0,
  // executor worker loop
  EV_TASK_BEGIN,      // a = task fn ptr
  EV_TASK_END,        // a = task fn ptr
  EV_STEAL,           // a = victim worker index
  EV_PARK,            // a = parking-lot state snapshot
  EV_UNPARK,          //
  // butex
  EV_BUTEX_WAIT,      // a = butex ptr, b = timeout_us (clamped, -1 none)
  EV_BUTEX_WAKE,      // a = butex ptr, b = waiters woken
  EV_BUTEX_TIMEOUT,   // a = butex ptr
  // timer thread
  EV_TIMER_FIRE,      // a = timer id
  EV_TIMER_CANCEL,    // a = timer id
  // socket lifecycle + syscalls
  EV_SOCK_CREATE,     // a = socket id, b = fd
  EV_SOCK_EPOLLIN,    // a = socket id, b = epoll event bits
  EV_READ_ENTER,      // a = socket id
  EV_READ_EXIT,       // a = socket id, b = bytes read (or -errno)
  EV_WRITE_ENTER,     // a = socket id, b = bytes attempted
  EV_WRITE_EXIT,      // a = socket id, b = bytes written (or -errno)
  EV_SOCK_FAILED,     // a = socket id, b = error code
  EV_SOCK_CLOSE,      // a = socket id, b = fd
  // serving TokenRing (batch granularity — see header comment)
  EV_RING_PUSH,       // a = first ring handle, b = rings pushed OK
  EV_RING_FULL,       // a = ring handle (single-push hit a full ring)
  EV_RING_POP,        // a = ring handle, b = tokens drained
  EV_RING_TERMINAL,   // a = ring handle, b = error code
  // rpcz native span queue (fastrpc_module.cc)
  EV_SPANQ_DRAIN,     // b = spans drained
  // test/self-probe marker (brpc_flight_selftest_* in capi.cc)
  EV_PROBE,           // a = caller tag, b = sequence
  EV_KIND_MAX,
};

const char* kind_name(uint16_t k);  // "task_begin", "butex_wait", ...

// Per-thread ring capacity (power of two).  2048 x 32B = 64KB/thread.
constexpr uint64_t kRingCap = 2048;

// One recorded transition.  32 bytes; all fields relaxed atomics so
// concurrent dumps are data-race-free (see header comment).
struct Event {
  std::atomic<uint64_t> ver;    // seq*2+1 writing, seq*2+2 complete
  std::atomic<int64_t> ts_us;   // monotonic
  std::atomic<uint64_t> a;      // primary id (socket id, ptr, index)
  std::atomic<int32_t> b;       // small arg (bytes, errno, count)
  std::atomic<uint16_t> kind;
  uint16_t _pad;
};
static_assert(sizeof(Event) == 32, "event must stay ~32 bytes");

struct ThreadRing {
  Event buf[kRingCap];
  std::atomic<uint64_t> head{0};   // next sequence to write (owner only)
  std::atomic<uint64_t> tid{0};
  // thread role, 15 chars + NUL packed into two atomic words so the
  // owner can (re)name itself while a dump reads concurrently
  std::atomic<uint64_t> name_lo{0}, name_hi{0};
  std::atomic<bool> live{true};
  ThreadRing* next = nullptr;      // registration list, push-front once
  ThreadRing* free_next = nullptr; // recycle list (under its mutex)
};

// ---- recording (hot path) ----

bool enabled();
void set_enabled(bool on);

// Record one event on the calling thread's ring (registering the ring
// on first use).  No-op while disabled.
void record(uint16_t kind, uint64_t a = 0, int64_t b = 0);

// Name the calling thread's ring ("worker/3", "timer", "epoll/0").
// Threads that never call this show up as "ext".
void set_thread_name(const char* fmt, ...)
    __attribute__((format(printf, 1, 2)));

// ---- introspection (cold path) ----

// Merged time-ordered tail of every thread's ring: up to max_events
// consistent events, oldest first, one per line:
//   <ts_us> <tid> <name> <kind> a=<hex> b=<dec>
// Returns bytes written (0 terminated, truncating at cap).
int dump(char* out, size_t cap, int max_events);

// Per-thread state table ("what is every native thread doing RIGHT
// NOW"), one line per ring:
//   <tid> <name> <live|exited> events=<n> dropped=<n> last=<kind> age_us=<n>
int threads_table(char* out, size_t cap);

// events = total recorded, threads = rings registered,
// dropped = events overwritten before any dump could see them.
void stats(int64_t* events, int64_t* threads, int64_t* dropped);

}  // namespace flight
}  // namespace butil
