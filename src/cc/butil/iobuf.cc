#include "butil/iobuf.h"

#include "butil/common.h"

#include <errno.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cassert>
#include <new>

namespace butil {
namespace iobuf {

static std::atomic<int64_t> g_live_blocks{0};

struct Block {
  std::atomic<int32_t> nshared;
  uint32_t size;          // claim cursor: bytes handed out to refs
  uint32_t cap;
  void (*deleter)(void*, void*);  // non-null => user block
  void* deleter_arg;
  char* data;
  Block* next_cached;     // TLS free-list link
};

// ---- thread-local block cache (reference iobuf.cpp:379-449 role) ----

struct TlsBlockCache {
  Block* head = nullptr;
  size_t count = 0;
  Block* write_block = nullptr;  // current shared append target (one ref held)
  ~TlsBlockCache();
};

static constexpr size_t kMaxCachedBlocks = 64;
static thread_local TlsBlockCache tls_cache;

static void destroy_block(Block* b) {
  g_live_blocks.fetch_sub(1, std::memory_order_relaxed);
  if (b->deleter != nullptr) {
    b->deleter(b->data, b->deleter_arg);
    free(b);
  } else {
    free(b);  // header + payload are one allocation
  }
}

Block* create_block(size_t payload_cap) {
  iobuf_alloc_note();  // sampled alloc-site stacks (/memory)
  TlsBlockCache& c = tls_cache;
  if (payload_cap == kDefaultPayload && c.head != nullptr) {
    Block* b = c.head;
    c.head = b->next_cached;
    --c.count;
    b->nshared.store(1, std::memory_order_relaxed);
    b->size = 0;
    return b;
  }
  auto* b = (Block*)malloc(sizeof(Block) + payload_cap);
  if (b == nullptr) return nullptr;
  b->nshared.store(1, std::memory_order_relaxed);
  b->size = 0;
  b->cap = (uint32_t)payload_cap;
  b->deleter = nullptr;
  b->deleter_arg = nullptr;
  b->data = (char*)(b + 1);
  b->next_cached = nullptr;
  g_live_blocks.fetch_add(1, std::memory_order_relaxed);
  return b;
}

Block* create_user_block(void* data, size_t size, void (*deleter)(void*, void*),
                         void* arg) {
  auto* b = (Block*)malloc(sizeof(Block));
  b->nshared.store(1, std::memory_order_relaxed);
  b->size = (uint32_t)size;  // fully claimed: never appended into
  b->cap = (uint32_t)size;
  b->deleter = deleter;
  b->deleter_arg = arg;
  b->data = (char*)data;
  b->next_cached = nullptr;
  g_live_blocks.fetch_add(1, std::memory_order_relaxed);
  return b;
}

void block_inc_ref(Block* b) { b->nshared.fetch_add(1, std::memory_order_relaxed); }

void block_dec_ref(Block* b) {
  if (b->nshared.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    TlsBlockCache& c = tls_cache;
    if (b->deleter == nullptr && b->cap == kDefaultPayload &&
        c.count < kMaxCachedBlocks) {
      b->next_cached = c.head;
      c.head = b;
      ++c.count;
      return;
    }
    destroy_block(b);
  }
}

TlsBlockCache::~TlsBlockCache() {
  if (write_block != nullptr) {
    // Drop our ref without re-entering the (dying) cache.
    Block* wb = write_block;
    write_block = nullptr;
    if (wb->nshared.fetch_sub(1, std::memory_order_acq_rel) == 1)
      destroy_block(wb);
  }
  while (head != nullptr) {
    Block* b = head;
    head = b->next_cached;
    destroy_block(b);
  }
  count = 0;
}

char* block_data(Block* b) { return b->data; }
size_t block_cap(Block* b) { return b->cap; }
size_t block_size(Block* b) { return b->size; }
void block_set_size(Block* b, size_t n) { b->size = (uint32_t)n; }
int block_ref_count(Block* b) { return b->nshared.load(std::memory_order_relaxed); }
size_t tls_cached_blocks() { return tls_cache.count; }
int64_t live_block_count() { return g_live_blocks.load(std::memory_order_relaxed); }

// The thread-shared write block (reference share_tls_block, iobuf.cpp:411):
// sequential appends from one thread claim ranges of one block, so many small
// messages pack densely and appends rarely allocate.
static Block* tls_write_block_with_room() {
  TlsBlockCache& c = tls_cache;
  Block* b = c.write_block;
  if (b != nullptr && b->size < b->cap) return b;
  if (b != nullptr) {
    block_dec_ref(b);
    c.write_block = nullptr;
  }
  b = create_block(kDefaultPayload);
  c.write_block = b;  // hold one ref as the TLS owner
  return b;
}

}  // namespace iobuf

using iobuf::Block;

// ---- IOBuf ----

IOBuf::IOBuf() { }

IOBuf::~IOBuf() { unref_all(); }

IOBuf::IOBuf(const IOBuf& rhs) : IOBuf() { append(rhs); }

IOBuf& IOBuf::operator=(const IOBuf& rhs) {
  if (this != &rhs) {
    clear();
    append(rhs);
  }
  return *this;
}

IOBuf::IOBuf(IOBuf&& rhs) noexcept {
  memcpy(_inline, rhs._inline, sizeof(_inline));
  _ring = rhs._ring;
  _ring_cap = rhs._ring_cap;
  _start = rhs._start;
  _nref = rhs._nref;
  _nbytes = rhs._nbytes;
  rhs._ring = nullptr;
  rhs._ring_cap = rhs._start = rhs._nref = 0;
  rhs._nbytes = 0;
}

IOBuf& IOBuf::operator=(IOBuf&& rhs) noexcept {
  if (this != &rhs) {
    unref_all();
    memcpy(_inline, rhs._inline, sizeof(_inline));
    _ring = rhs._ring;
    _ring_cap = rhs._ring_cap;
    _start = rhs._start;
    _nref = rhs._nref;
    _nbytes = rhs._nbytes;
    rhs._ring = nullptr;
    rhs._ring_cap = rhs._start = rhs._nref = 0;
    rhs._nbytes = 0;
  }
  return *this;
}

BlockRef& IOBuf::ref_at(size_t i) {
  return _ring != nullptr ? _ring[(_start + i) & (_ring_cap - 1)] : _inline[i];
}
const BlockRef& IOBuf::ref_at(size_t i) const {
  return _ring != nullptr ? _ring[(_start + i) & (_ring_cap - 1)] : _inline[i];
}

const BlockRef& IOBuf::backing_block(size_t i) const { return ref_at(i); }

void IOBuf::unref_all() {
  // empty-buffer fast path: destroying/clearing empty IOBufs is the
  // single most frequent call on the echo hot path (~half the 11M
  // unref_all calls per 1M echoes were no-ops) — one check here gives
  // the dtor, clear(), and move-assignment the fast path alike
  if (_nref == 0 && _ring == nullptr) {
    _nbytes = 0;
    return;
  }
  for (size_t i = 0; i < _nref; ++i) iobuf::block_dec_ref(ref_at(i).block);
  free(_ring);
  _ring = nullptr;
  _ring_cap = _start = _nref = 0;
  _nbytes = 0;
}

void IOBuf::clear() { unref_all(); }

void IOBuf::grow_ring() {
  uint32_t new_cap = _ring == nullptr ? 8 : _ring_cap * 2;
  auto* nr = (BlockRef*)malloc(new_cap * sizeof(BlockRef));
  for (size_t i = 0; i < _nref; ++i) nr[i] = ref_at(i);
  free(_ring);
  _ring = nr;
  _ring_cap = new_cap;
  _start = 0;
}

void IOBuf::push_ref(const BlockRef& r) {
  // Merge with tail if contiguous in the same block (keeps ref count low when
  // one thread appends repeatedly through the TLS write block).
  if (_nref > 0) {
    BlockRef& tail = ref_at(_nref - 1);
    if (tail.block == r.block && tail.offset + tail.length == r.offset) {
      tail.length += r.length;
      _nbytes += r.length;
      iobuf::block_dec_ref(r.block);  // merged: drop the extra count
      return;
    }
  }
  if (_ring == nullptr && _nref >= 2) grow_ring();
  else if (_ring != nullptr && _nref == _ring_cap) grow_ring();
  if (_ring != nullptr)
    _ring[(_start + _nref) & (_ring_cap - 1)] = r;
  else
    _inline[_nref] = r;
  ++_nref;
  _nbytes += r.length;
}

void IOBuf::add_block_ref(const BlockRef& ref) {
  iobuf::block_inc_ref(ref.block);
  push_ref(ref);
}

void IOBuf::pop_front_ref() {
  iobuf::block_dec_ref(ref_at(0).block);
  if (_ring != nullptr) _start = (_start + 1) & (_ring_cap - 1);
  else _inline[0] = _inline[1];
  --_nref;
}

void IOBuf::pop_back_ref() {
  iobuf::block_dec_ref(ref_at(_nref - 1).block);
  --_nref;
}

void IOBuf::append(const void* data, size_t n) {
  const char* p = (const char*)data;
  while (n > 0) {
    Block* b = iobuf::tls_write_block_with_room();
    const size_t room = iobuf::block_cap(b) - iobuf::block_size(b);
    const size_t m = std::min(n, room);
    const uint32_t off = (uint32_t)iobuf::block_size(b);
    memcpy(iobuf::block_data(b) + off, p, m);
    iobuf::block_set_size(b, off + m);
    // Tail-merge FIRST: consecutive appends through the TLS write block
    // are the hot path, and going through inc_ref + push_ref's merge
    // (which dec_refs right back) cost two atomic RMWs per call for
    // nothing.  Only a genuinely new ref touches the refcount.
    if (_nref > 0) {
      BlockRef& tail = ref_at(_nref - 1);
      if (tail.block == b && tail.offset + tail.length == off) {
        tail.length += (uint32_t)m;
        _nbytes += m;
        p += m;
        n -= m;
        continue;
      }
    }
    iobuf::block_inc_ref(b);
    push_ref(BlockRef{off, (uint32_t)m, b});
    p += m;
    n -= m;
  }
}

void IOBuf::append(const IOBuf& other) {
  // Snapshot the count so self-append (`buf.append(buf)`) terminates: pushed
  // refs are copies of existing ones (never offset-contiguous with the tail),
  // so they don't merge and indexes 0..n-1 stay stable while we push.
  const size_t n = other._nref;
  for (size_t i = 0; i < n; ++i) add_block_ref(other.ref_at(i));
}

void IOBuf::append(IOBuf&& other) {
  if (_nref == 0) {
    *this = std::move(other);
    return;
  }
  for (size_t i = 0; i < other._nref; ++i) {
    iobuf::block_inc_ref(other.ref_at(i).block);
    push_ref(other.ref_at(i));
  }
  other.clear();
}

void IOBuf::append_user_data(void* data, size_t n, void (*deleter)(void*, void*),
                             void* arg) {
  if (n == 0) {
    // Nothing to reference; still honor the ownership contract (the
    // deleter releases the caller's resource exactly once).  Pushing a
    // zero-length ref would plant a degenerate span for every cursor to
    // trip over.
    if (deleter != nullptr) deleter(data, arg);
    return;
  }
  Block* b = iobuf::create_user_block(data, n, deleter, arg);
  push_ref(BlockRef{0, (uint32_t)n, b});  // takes the creation ref
}

size_t IOBuf::pop_front(size_t n) {
  size_t popped = 0;
  while (n > 0 && _nref > 0) {
    BlockRef& r = ref_at(0);
    if (r.length > n) {
      r.offset += (uint32_t)n;
      r.length -= (uint32_t)n;
      popped += n;
      _nbytes -= n;
      return popped;
    }
    n -= r.length;
    popped += r.length;
    _nbytes -= r.length;
    pop_front_ref();
  }
  return popped;
}

size_t IOBuf::pop_back(size_t n) {
  size_t popped = 0;
  while (n > 0 && _nref > 0) {
    BlockRef& r = ref_at(_nref - 1);
    if (r.length > n) {
      r.length -= (uint32_t)n;
      popped += n;
      _nbytes -= n;
      return popped;
    }
    n -= r.length;
    popped += r.length;
    _nbytes -= r.length;
    pop_back_ref();
  }
  return popped;
}

size_t IOBuf::cutn(IOBuf* out, size_t n) {
  size_t moved = 0;
  while (n > 0 && _nref > 0) {
    BlockRef& r = ref_at(0);
    if (r.length <= n) {
      iobuf::block_inc_ref(r.block);
      out->push_ref(r);
      n -= r.length;
      moved += r.length;
      _nbytes -= r.length;
      pop_front_ref();
    } else {
      BlockRef part{r.offset, (uint32_t)n, r.block};
      iobuf::block_inc_ref(r.block);
      out->push_ref(part);
      r.offset += (uint32_t)n;
      r.length -= (uint32_t)n;
      _nbytes -= n;
      moved += n;
      n = 0;
    }
  }
  return moved;
}

size_t IOBuf::cutn(void* out, size_t n) {
  const size_t m = copy_to(out, n, 0);
  pop_front(m);
  return m;
}

size_t IOBuf::copy_to(void* buf, size_t n, size_t pos) const {
  char* out = (char*)buf;
  size_t copied = 0;
  for (size_t i = 0; i < _nref && n > 0; ++i) {
    const BlockRef& r = ref_at(i);
    if (pos >= r.length) {
      pos -= r.length;
      continue;
    }
    const size_t m = std::min((size_t)r.length - pos, n);
    memcpy(out, iobuf::block_data(r.block) + r.offset + pos, m);
    out += m;
    copied += m;
    n -= m;
    pos = 0;
  }
  return copied;
}

std::string IOBuf::to_string() const {
  std::string s;
  s.resize(_nbytes);
  copy_to(s.data(), _nbytes, 0);
  return s;
}

char IOBuf::byte_at(size_t pos) const {
  char c = 0;
  copy_to(&c, 1, pos);
  return c;
}

ssize_t IOBuf::cut_into_file_descriptor(int fd, size_t max_refs) {
  if (_nref == 0) return 0;
  iovec vec[64];
  const size_t nvec = std::min({(size_t)_nref, max_refs, (size_t)64});
  for (size_t i = 0; i < nvec; ++i) {
    const BlockRef& r = ref_at(i);
    vec[i].iov_base = iobuf::block_data(r.block) + r.offset;
    vec[i].iov_len = r.length;
  }
  const ssize_t nw = writev(fd, vec, (int)nvec);
  if (nw > 0) pop_front((size_t)nw);
  return nw;
}

// ---- IOBufBytesIterator ----

IOBufBytesIterator::IOBufBytesIterator(const IOBuf& buf)
    : _buf(&buf), _bytes_left(buf.size()) {
  load_ref();
}

void IOBufBytesIterator::load_ref() {
  while (_ref < _buf->backing_block_num()) {
    const BlockRef& r = _buf->backing_block(_ref);
    if (r.length > 0) {
      _ptr = iobuf::block_data(r.block) + r.offset;
      _end = _ptr + r.length;
      return;
    }
    ++_ref;
  }
  _ptr = _end = nullptr;
}

void IOBufBytesIterator::operator++() {
  ++_ptr;
  --_bytes_left;
  if (_ptr == _end) {
    ++_ref;
    load_ref();
  }
}

size_t IOBufBytesIterator::copy_and_forward(void* out, size_t n) {
  char* dst = (char*)out;
  size_t copied = 0;
  while (n > 0 && _bytes_left > 0) {
    const size_t span = (size_t)(_end - _ptr);
    const size_t m = std::min(n, span);
    memcpy(dst, _ptr, m);
    dst += m;
    copied += m;
    n -= m;
    _ptr += m;
    _bytes_left -= m;
    if (_ptr == _end) {
      ++_ref;
      load_ref();
    }
  }
  return copied;
}

size_t IOBufBytesIterator::forward(size_t n) {
  size_t skipped = 0;
  while (n > 0 && _bytes_left > 0) {
    const size_t span = (size_t)(_end - _ptr);
    const size_t m = std::min(n, span);
    skipped += m;
    n -= m;
    _ptr += m;
    _bytes_left -= m;
    if (_ptr == _end) {
      ++_ref;
      load_ref();
    }
  }
  return skipped;
}

// ---- IOBufCutter ----

IOBufCutter::IOBufCutter(IOBuf* buf) : _buf(buf) {}

IOBufCutter::~IOBufCutter() { flush(); }

void IOBufCutter::flush() {
  const size_t consumed = consumed_pending();
  if (consumed > 0) _buf->pop_front(consumed);
  _span_begin = _ptr = _end = nullptr;
}

bool IOBufCutter::refill() {
  flush();
  // Zero-length refs are producible (append_user_data with n == 0);
  // loading one would make cut1 read out of bounds and cutn spin — skip
  // them like IOBufBytesIterator::load_ref does.
  while (_buf->backing_block_num() > 0) {
    const BlockRef& r = _buf->backing_block(0);
    if (r.length == 0) {
      _buf->pop_front_ref();
      continue;
    }
    _span_begin = _ptr = iobuf::block_data(r.block) + r.offset;
    _end = _ptr + r.length;
    return true;
  }
  return false;
}

bool IOBufCutter::cut1(char* c) {
  if (_ptr == _end && !refill()) return false;
  *c = *_ptr++;
  return true;
}

size_t IOBufCutter::cutn(void* out, size_t n) {
  char* dst = (char*)out;
  size_t cut = 0;
  while (n > 0) {
    if (_ptr == _end && !refill()) break;
    const size_t m = std::min(n, (size_t)(_end - _ptr));
    memcpy(dst, _ptr, m);
    dst += m;
    _ptr += m;
    cut += m;
    n -= m;
  }
  return cut;
}

size_t IOBufCutter::cutn(IOBuf* out, size_t n) {
  flush();  // hand back the cached span before the zero-copy move
  return _buf->cutn(out, n);
}

// ---- IOBufAppender ----

IOBufAppender::~IOBufAppender() {
  commit();
  if (_block != nullptr) iobuf::block_dec_ref(_block);
}

void IOBufAppender::grab_block() {
  commit();
  if (_block != nullptr) {
    iobuf::block_dec_ref(_block);
    _block = nullptr;
  }
  Block* b = iobuf::tls_write_block_with_room();  // thread-shared tail
  iobuf::block_inc_ref(b);                        // appender's own ref
  _block = b;
  _begin = (uint32_t)iobuf::block_size(b);
  _cur = iobuf::block_data(b) + _begin;
  _end = iobuf::block_data(b) + iobuf::block_cap(b);
}

void IOBufAppender::commit() {
  if (_block == nullptr) return;
  const uint32_t end_off = (uint32_t)(_cur - iobuf::block_data(_block));
  const uint32_t len = end_off - _begin;
  if (len == 0) return;
  _buf->add_block_ref(BlockRef{_begin, len, _block});
  _begin = end_off;
}

void IOBufAppender::append(const void* data, size_t n) {
  const char* p = (const char*)data;
  while (n > 0) {
    // Re-grab when the span is exhausted OR someone else advanced the
    // shared block's claim cursor since our last write (a plain
    // IOBuf::append or another appender on this thread): our staged
    // bytes are safe (claimed eagerly below) but writing past a foreign
    // claim would corrupt theirs.
    if (_block == nullptr || _cur == _end ||
        _cur != iobuf::block_data(_block) + iobuf::block_size(_block)) {
      grab_block();
    }
    const size_t m = std::min(n, (size_t)(_end - _cur));
    memcpy(_cur, p, m);
    _cur += m;
    // claim eagerly: interleaved appends on this thread must see the
    // span as taken, or they would overwrite staged bytes
    iobuf::block_set_size(_block, (size_t)(_cur - iobuf::block_data(_block)));
    p += m;
    n -= m;
  }
}

// ---- IOPortal ----

ssize_t IOPortal::append_from_file_descriptor(int fd, size_t max_bytes) {
  // Scatter-read into up to 16 blocks (~128KB) per syscall: first the TLS
  // write block's tail room, then fresh cache blocks.
  Block* blocks[16];
  iovec vec[16];
  size_t nvec = 0;
  size_t planned = 0;
  while (planned < max_bytes && nvec < 16) {
    Block* b = (nvec == 0) ? iobuf::tls_write_block_with_room()
                           : iobuf::create_block(iobuf::kDefaultPayload);
    const size_t room = iobuf::block_cap(b) - iobuf::block_size(b);
    blocks[nvec] = b;
    vec[nvec].iov_base = iobuf::block_data(b) + iobuf::block_size(b);
    vec[nvec].iov_len = std::min(room, max_bytes - planned);
    planned += vec[nvec].iov_len;
    ++nvec;
  }
  ssize_t nr = readv(fd, vec, (int)nvec);
  // Blocks past the first are plain new blocks we own; consume or recycle.
  ssize_t remain = nr < 0 ? 0 : nr;
  for (size_t i = 0; i < nvec; ++i) {
    Block* b = blocks[i];
    const size_t filled = std::min((size_t)remain, (size_t)vec[i].iov_len);
    if (filled > 0) {
      const uint32_t off = (uint32_t)iobuf::block_size(b);
      iobuf::block_set_size(b, off + filled);
      if (i == 0) {
        iobuf::block_inc_ref(b);
        push_ref(BlockRef{off, (uint32_t)filled, b});
      } else {
        push_ref(BlockRef{0, (uint32_t)filled, b});  // takes creation ref
      }
      remain -= filled;
    } else if (i != 0) {
      iobuf::block_dec_ref(b);  // untouched fresh block → cache
    }
  }
  return nr;
}

}  // namespace butil
