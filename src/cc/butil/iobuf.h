// IOBuf — zero-copy chained buffer.
//
// Behavioral spec from the reference (SURVEY.md §2.1; /root/reference
// src/butil/iobuf.h:62-102): a queue of BlockRef{offset,length,block*} over
// refcounted blocks, with a small inline view for <=2 refs and a heap ring
// beyond, a thread-local block cache so appends rarely hit malloc, O(1)
// zero-copy cut/append between IOBufs, and scatter/gather file-descriptor IO.
//
// This implementation is new code written to that spec.  One deliberate
// extension for the TPU build: blocks may wrap *user-owned* memory with a
// custom deleter (append_user_data), which is how HBM-registered host staging
// buffers and PJRT-donated regions enter the buffer chain without a copy —
// the role rdma::BlockPool-backed blocks play in the reference (§5.8).
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

#include "butil/common.h"

namespace butil {

namespace iobuf {

// Payload bytes per default block.  Header+payload is one allocation sized
// close to 8KB like the reference's default block (iobuf.cpp block size).
constexpr size_t kDefaultPayload = 8192 - 64;

struct Block;

Block* create_block(size_t payload_cap);               // refcount = 1
Block* create_user_block(void* data, size_t size,
                         void (*deleter)(void*, void*), void* arg);
void block_inc_ref(Block* b);
void block_dec_ref(Block* b);
char* block_data(Block* b);
size_t block_cap(Block* b);
// Number of bytes already claimed in the block (append cursor).
size_t block_size(Block* b);
void block_set_size(Block* b, size_t n);
int block_ref_count(Block* b);

// Thread-local block cache stats (for tests / bvar export).
size_t tls_cached_blocks();
// Global count of live blocks (leak checks in tests).
int64_t live_block_count();

}  // namespace iobuf

struct BlockRef {
  uint32_t offset;
  uint32_t length;
  iobuf::Block* block;
};

// A queue of BlockRefs.  SmallView: up to 2 inline refs.  BigView: heap ring.
class IOBuf {
 public:
  IOBuf();
  ~IOBuf();
  IOBuf(const IOBuf& rhs);             // shares blocks (refcount++)
  IOBuf& operator=(const IOBuf& rhs);
  IOBuf(IOBuf&& rhs) noexcept;
  IOBuf& operator=(IOBuf&& rhs) noexcept;

  void clear();
  size_t size() const { return _nbytes; }
  bool empty() const { return _nbytes == 0; }
  size_t backing_block_num() const { return _nref; }
  const BlockRef& backing_block(size_t i) const;

  // ---- writing ----
  void append(const void* data, size_t n);
  void append(const std::string& s) { append(s.data(), s.size()); }
  void append(const IOBuf& other);              // zero-copy share
  void append(IOBuf&& other);                   // zero-copy steal
  // Wrap caller-owned memory as a block; deleter(data, arg) runs when the
  // last ref drops.  Zero-copy entry point for HBM staging buffers.
  void append_user_data(void* data, size_t n, void (*deleter)(void*, void*),
                        void* arg);
  void push_back(char c) { append(&c, 1); }

  // ---- removing / slicing ----
  size_t pop_front(size_t n);
  size_t pop_back(size_t n);
  // Move first n bytes into *out (appended), zero-copy.  Returns moved count.
  size_t cutn(IOBuf* out, size_t n);
  size_t cutn(void* out, size_t n);             // copying variant
  size_t copy_to(void* buf, size_t n, size_t pos = 0) const;
  std::string to_string() const;
  // Byte at pos (slow path, for parsers peeking at small headers).
  char byte_at(size_t pos) const;

  // ---- fd IO (DCN/TCP path) ----
  // writev() up to max_refs refs; pops written bytes; returns bytes written
  // or -1 with errno set.
  ssize_t cut_into_file_descriptor(int fd, size_t max_refs = 64);

  // Internal: append a raw ref (takes one reference on ref.block).
  void add_block_ref(const BlockRef& ref);

 protected:
  void push_ref(const BlockRef& r);      // takes ownership of the count

 private:
  friend class IOBufCutter;  // pops skipped zero-length refs in refill()
  void unref_all();
  BlockRef& ref_at(size_t i);
  const BlockRef& ref_at(size_t i) const;
  void pop_front_ref();
  void pop_back_ref();
  void grow_ring();

  // Ring storage: first 2 refs inline, rest on heap ring.
  BlockRef _inline[2];
  BlockRef* _ring = nullptr;   // when non-null, holds all refs
  uint32_t _ring_cap = 0;      // power of two
  uint32_t _start = 0;         // ring start index
  uint32_t _nref = 0;
  size_t _nbytes = 0;
};

// IOPortal — an IOBuf you read *into* from an fd with scatter IO, modeled on
// reference iobuf.h:448-465.  Keeps a partially-filled tail block across
// reads so small reads don't fragment.
class IOPortal : public IOBuf {
 public:
  // readv() into cached blocks; appends read bytes; returns bytes read,
  // 0 on EOF, -1 on error (errno set; EAGAIN for would-block).
  ssize_t append_from_file_descriptor(int fd, size_t max_bytes);
  // Append from memory through the same tail-block machinery.
  void append_from_memory(const void* data, size_t n) { append(data, n); }
};

// IOBufBytesIterator — non-destructive forward cursor (reference iobuf.h
// IOBufBytesIterator): caches the current ref's span so sequential scans
// cost O(total bytes), where repeated copy_to(pos) walks the ref chain
// from the start each call (O(refs) per read — quadratic over a long
// multi-block message).  The buf must not be mutated while iterating.
class IOBufBytesIterator {
 public:
  explicit IOBufBytesIterator(const IOBuf& buf);
  size_t bytes_left() const { return _bytes_left; }
  char operator*() const { return *_ptr; }
  void operator++();
  // Copy up to n bytes and advance; returns copied count.
  size_t copy_and_forward(void* out, size_t n);
  // Skip up to n bytes; returns skipped count.
  size_t forward(size_t n);

 private:
  void load_ref();
  const IOBuf* _buf;
  const char* _ptr = nullptr;
  const char* _end = nullptr;
  size_t _ref = 0;
  size_t _bytes_left = 0;
};

// IOBufCutter — destructive sequential reader with a cached front span
// (reference iobuf_inl.h IOBufCutter): cut1/cutn without a front-ref
// lookup per call.  Consumed bytes are popped from the buf lazily (on
// span refill / destruction); cutn(IOBuf*) flushes first so zero-copy
// handoff and cached reads interleave correctly.
class IOBufCutter {
 public:
  explicit IOBufCutter(IOBuf* buf);
  ~IOBufCutter();
  size_t remaining() const { return _buf->size() - consumed_pending(); }
  bool cut1(char* c);
  size_t cutn(void* out, size_t n);
  size_t cutn(IOBuf* out, size_t n);   // zero-copy

 private:
  size_t consumed_pending() const { return (size_t)(_ptr - _span_begin); }
  void flush();                        // pop consumed prefix off the buf
  bool refill();
  IOBuf* _buf;
  const char* _span_begin = nullptr;
  const char* _ptr = nullptr;
  const char* _end = nullptr;
};

// IOBufAppender — staged writer with a cached tail span (reference
// iobuf_inl.h IOBufAppender): repeated small writes go through a raw
// cursor and publish to the IOBuf as ONE ref on commit() / destruction.
// Spans are claimed eagerly from the thread-shared write block (the
// block's append cursor advances as bytes land), so frames stay densely
// packed — a queue of small frames shares blocks instead of pinning one
// block each, which keeps EOVERCROWDED's byte accounting honest.
class IOBufAppender {
 public:
  explicit IOBufAppender(IOBuf* buf) : _buf(buf) {}
  ~IOBufAppender();
  void append(const void* data, size_t n);
  void append(const std::string& s) { append(s.data(), s.size()); }
  void push_back(char c) { append(&c, 1); }
  void commit();

 private:
  void grab_block();
  IOBuf* _buf;
  iobuf::Block* _block = nullptr;  // one ref held while staging
  uint32_t _begin = 0;             // start of the uncommitted span
  char* _cur = nullptr;
  char* _end = nullptr;
};

}  // namespace butil
