#include "butil/common.h"

#include <cstdarg>
#include <mutex>

namespace butil {

static LogSinkFn g_sink = nullptr;
static void* g_sink_arg = nullptr;
static std::atomic<int> g_min_level{LOG_WARNING};

void set_log_sink(LogSinkFn fn, void* arg) {
  g_sink = fn;
  g_sink_arg = arg;
}

void set_min_log_level(int level) { g_min_level.store(level, std::memory_order_relaxed); }
int min_log_level() { return g_min_level.load(std::memory_order_relaxed); }

void log_message(int level, const char* fmt, ...) {
  char buf[2048];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(buf, sizeof(buf), fmt, ap);
  va_end(ap);
  LogSinkFn sink = g_sink;
  if (sink != nullptr) {
    sink(level, buf, g_sink_arg);
  } else {
    static const char* names[] = {"D", "I", "W", "E", "F"};
    fprintf(stderr, "[%s] %s\n", names[level < 5 ? level : 4], buf);
  }
  if (level >= LOG_FATAL) abort();
}

#if defined(__x86_64__)
// One-time TSC calibration against CLOCK_MONOTONIC at library load:
// sample both clocks ~10ms apart, derive ns-per-tick.  Invariant TSC
// keeps the rate constant across cores/frequency states on any modern
// x86_64 (the same assumption the reference's butil::cpuwide_time makes,
// src/butil/time.h).
static TscCalib make_tsc_calib() {
  TscCalib c;
  c.tsc0 = rdtsc();
  c.ns0 = monotonic_time_ns();
  timespec req{0, 10 * 1000 * 1000};
  nanosleep(&req, nullptr);
  const uint64_t tsc1 = rdtsc();
  const int64_t ns1 = monotonic_time_ns();
  c.ns_per_tick =
      tsc1 > c.tsc0 ? double(ns1 - c.ns0) / double(tsc1 - c.tsc0) : 1.0;
  return c;
}
TscCalib g_tsc_calib = make_tsc_calib();
#endif

}  // namespace butil
