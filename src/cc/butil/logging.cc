#include "butil/common.h"

#include <cstdarg>
#include <mutex>

namespace butil {

static LogSinkFn g_sink = nullptr;
static void* g_sink_arg = nullptr;
static std::atomic<int> g_min_level{LOG_WARNING};

void set_log_sink(LogSinkFn fn, void* arg) {
  g_sink = fn;
  g_sink_arg = arg;
}

void set_min_log_level(int level) { g_min_level.store(level, std::memory_order_relaxed); }
int min_log_level() { return g_min_level.load(std::memory_order_relaxed); }

void log_message(int level, const char* fmt, ...) {
  char buf[2048];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(buf, sizeof(buf), fmt, ap);
  va_end(ap);
  LogSinkFn sink = g_sink;
  if (sink != nullptr) {
    sink(level, buf, g_sink_arg);
  } else {
    static const char* names[] = {"D", "I", "W", "E", "F"};
    fprintf(stderr, "[%s] %s\n", names[level < 5 ? level : 4], buf);
  }
  if (level >= LOG_FATAL) abort();
}

}  // namespace butil
