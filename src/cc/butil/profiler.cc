// Native CPU profiler — SIGPROF stack sampling with pprof-compatible
// output (VERDICT r2 task 10; reference builtin/hotspots_service.cpp:36
// drives gperftools' ProfilerStart the same way).
//
// The Python-frame profiler (builtin/profiler.py) cannot see the
// dispatcher/executor/drainer threads where the hot path actually runs.
// This sampler can: ITIMER_PROF delivers SIGPROF on whichever thread is
// burning CPU; the handler captures a backtrace into a fixed ring.
// Output formats:
//   - legacy pprof CPU profile binary (header/sample/trailer words +
//     /proc/self/maps), readable by `pprof ./binary profile` and modern
//     `pprof -http` alike;
//   - folded stacks text ("sym1;sym2;sym3 count"), flamegraph input and
//     human-greppable.
//
// backtrace(3) in a signal handler: formally unsafe (first call may
// allocate inside the unwinder), standard profiler practice regardless —
// we force that initialization in prof_start before arming the timer,
// exactly like gperftools.
#include "butil/common.h"

#include <dlfcn.h>
#include <execinfo.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/time.h>

#include <atomic>
#include <cstdint>
#include <map>
#include <string>
#include <vector>

namespace butil {

namespace {

constexpr int kMaxDepth = 48;
constexpr int kMaxSamples = 65536;

struct Sample {
  std::atomic<bool> ready{false};  // slot fully written (handler races stop)
  int depth;
  void* pcs[kMaxDepth];
};

Sample* g_samples = nullptr;            // allocated at first start
std::atomic<int> g_count{0};
std::atomic<bool> g_running{false};
int g_period_us = 10000;
struct sigaction g_old_action;

void prof_handler(int, siginfo_t*, void*) {
  if (!g_running.load(std::memory_order_relaxed)) return;
  const int i = g_count.fetch_add(1, std::memory_order_relaxed);
  if (i >= kMaxSamples) {
    g_count.store(kMaxSamples, std::memory_order_relaxed);
    return;
  }
  Sample& s = g_samples[i];
  const int n = backtrace(s.pcs, kMaxDepth);
  // drop the top frames (this handler + the signal trampoline)
  const int skip = n > 2 ? 2 : 0;
  s.depth = n - skip;
  if (skip > 0) {
    memmove(s.pcs, s.pcs + skip, sizeof(void*) * (size_t)s.depth);
  }
  // publish LAST: readers after prof_stop skip slots whose fill was
  // preempted mid-write (the index was claimed before the data landed)
  s.ready.store(true, std::memory_order_release);
}

}  // namespace

int prof_start(int hz) {
  if (hz <= 0 || hz > 1000) hz = 100;
  bool expected = false;
  if (!g_running.compare_exchange_strong(expected, true)) return -1;
  if (g_samples == nullptr) {
    g_samples = new Sample[kMaxSamples]();  // value-init: depth 0, !ready
  }
  for (int i = 0; i < kMaxSamples; ++i) {
    g_samples[i].ready.store(false, std::memory_order_relaxed);
  }
  g_count.store(0, std::memory_order_relaxed);
  g_period_us = 1000000 / hz;
  // force-load the unwinder outside signal context (gperftools dance)
  void* warm[4];
  backtrace(warm, 4);
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_sigaction = prof_handler;
  sa.sa_flags = SA_SIGINFO | SA_RESTART;
  sigemptyset(&sa.sa_mask);
  if (sigaction(SIGPROF, &sa, &g_old_action) != 0) {
    g_running.store(false);
    return -1;
  }
  itimerval tv;
  tv.it_interval.tv_sec = 0;
  tv.it_interval.tv_usec = g_period_us;
  tv.it_value = tv.it_interval;
  if (setitimer(ITIMER_PROF, &tv, nullptr) != 0) {
    sigaction(SIGPROF, &g_old_action, nullptr);
    g_running.store(false);
    return -1;
  }
  return 0;
}

int prof_stop() {
  if (!g_running.load(std::memory_order_acquire)) return -1;
  itimerval off;
  memset(&off, 0, sizeof(off));
  setitimer(ITIMER_PROF, &off, nullptr);
  // Deliberately do NOT restore the old SIGPROF disposition: a SIGPROF
  // generated before the timer was disarmed can still be pending, and
  // restoring SIG_DFL (default: terminate) would kill the process on
  // delivery.  Our handler stays installed and no-ops via g_running —
  // the gperftools approach.
  g_running.store(false, std::memory_order_release);
  const int n = g_count.load(std::memory_order_acquire);
  return n > kMaxSamples ? kMaxSamples : n;
}

long long prof_sample_count() {
  const int n = g_count.load(std::memory_order_acquire);
  return n > kMaxSamples ? kMaxSamples : n;
}

// Legacy pprof CPU profile: words are uintptr_t.
// header: [0, 3, 0, period_us, 0]; per sample: [count, depth, pcs...];
// trailer: [0, 1, 0]; then the text of /proc/self/maps.
int prof_dump(const char* path) {
  if (g_running.load(std::memory_order_acquire)) return -1;  // stop first
  const int n = (int)prof_sample_count();
  FILE* f = fopen(path, "wb");
  if (f == nullptr) return -1;
  const uintptr_t header[5] = {0, 3, 0, (uintptr_t)g_period_us, 0};
  fwrite(header, sizeof(uintptr_t), 5, f);
  for (int i = 0; i < n; ++i) {
    const Sample& s = g_samples[i];
    if (!s.ready.load(std::memory_order_acquire) || s.depth <= 0) continue;
    const uintptr_t rec[2] = {1, (uintptr_t)s.depth};
    fwrite(rec, sizeof(uintptr_t), 2, f);
    fwrite(s.pcs, sizeof(void*), (size_t)s.depth, f);
  }
  const uintptr_t trailer[3] = {0, 1, 0};
  fwrite(trailer, sizeof(uintptr_t), 3, f);
  // address->binary mapping so pprof can symbolize
  FILE* maps = fopen("/proc/self/maps", "r");
  if (maps != nullptr) {
    char buf[4096];
    size_t got;
    while ((got = fread(buf, 1, sizeof(buf), maps)) > 0) {
      fwrite(buf, 1, got, f);
    }
    fclose(maps);
  }
  fclose(f);
  return n;
}

// Folded stacks ("leaf-last;..;root count" per flamegraph convention is
// root-first — we emit root;..;leaf).  Aggregates identical stacks.
int prof_folded(char* out, unsigned long cap) {
  if (g_running.load(std::memory_order_acquire)) return -1;
  const int n = (int)prof_sample_count();
  std::map<std::string, int> folded;
  for (int i = 0; i < n; ++i) {
    const Sample& s = g_samples[i];
    if (!s.ready.load(std::memory_order_acquire) || s.depth <= 0) continue;
    char** syms = backtrace_symbols(s.pcs, s.depth);
    if (syms == nullptr) continue;
    std::string key;
    for (int d = s.depth - 1; d >= 0; --d) {  // root first
      // backtrace_symbols gives "module(function+0x..) [addr]"; keep the
      // function token when present, else the module
      const char* t = syms[d];
      const char* lp = strchr(t, '(');
      std::string frame;
      if (lp != nullptr && lp[1] != ')' && lp[1] != '+') {
        const char* e = strpbrk(lp + 1, "+)");
        frame.assign(lp + 1, e ? (size_t)(e - lp - 1) : strlen(lp + 1));
      } else {
        const char* sl = strrchr(t, '/');
        const char* base = sl ? sl + 1 : t;
        const char* e = strchr(base, '(');
        frame.assign(base, e ? (size_t)(e - base) : strlen(base));
      }
      if (!key.empty()) key += ';';
      key += frame;
    }
    free(syms);
    folded[key] += 1;
  }
  std::string text;
  for (const auto& [k, c] : folded) {
    text += k;
    text += ' ';
    text += std::to_string(c);
    text += '\n';
  }
  if (cap == 0) return -1;
  if (text.size() + 1 > cap) {
    static const char kMark[] = "\n...truncated\n";
    if (cap <= sizeof(kMark)) {
      text.clear();             // too small for data + marker: just NUL
    } else {
      text.resize(cap - sizeof(kMark));
      text += kMark;            // sizeof includes the NUL slot
    }
  }
  memcpy(out, text.data(), text.size());
  out[text.size()] = 0;
  return (int)text.size();
}

// ---- event samplers (contention + IOBuf alloc sites) ----
//
// Shared shape: event-driven (not time-driven) stack capture into a
// seqlock-protected ring, rate-bounded by a token bucket so a hot path
// costs one relaxed atomic per event in steady state.  Two instances:
//  * contention (VERDICT r4 #8): like the reference ContentionProfiler
//    (src/bthread/mutex.cpp:66,122-145) capture happens on the
//    contended UNLOCK; the caller stack there is usually the executor's
//    resume loop (coroutine symmetric transfer is tail-called), so the
//    LOCK'S OWN ADDRESS rides each sample as the leaf frame.
//  * iobuf_alloc (reference butil/iobuf_profiler.h): block allocation
//    sites, sampled in iobuf.cc create_block — answers WHERE buffer
//    memory is being minted when /sockets' live-block count grows.
namespace {

constexpr int kCMaxDepth = 32;
constexpr int kCMaxSamples = 8192;
constexpr int64_t kCSamplePeriodNs = 1000000;  // >= 1ms apart => <=1k/s

struct CSample {
  std::atomic<uint64_t> seq{0};  // even = stable, odd = being written
  int depth;
  const void* leaf;  // event identity (lock address; null for allocs)
  void* pcs[kCMaxDepth];
};

struct EventSampler {
  CSample ring[kCMaxSamples];
  std::atomic<int64_t> events{0};    // every event, sampled or not
  std::atomic<int64_t> captured{0};
  std::atomic<int64_t> last_ns{0};   // token bucket

  void note(const void* leaf_addr, int skip_frames, int64_t clock_every) {
    const int64_t ev = events.fetch_add(1, std::memory_order_relaxed);
    // hot-event instances (block allocs) only consult the clock every
    // Nth event, keeping steady-state cost at one relaxed atomic; rare-
    // event instances (contention) pass 1 and check every time
    if (clock_every > 1 && (ev % clock_every) != 0) return;
    const int64_t now = monotonic_time_ns();
    int64_t last = last_ns.load(std::memory_order_relaxed);
    if (now - last < kCSamplePeriodNs) return;
    if (!last_ns.compare_exchange_strong(last, now,
                                         std::memory_order_relaxed)) {
      return;  // another thread took this token
    }
    const int64_t i = captured.fetch_add(1, std::memory_order_relaxed);
    CSample& s = ring[i % kCMaxSamples];
    const uint64_t seq = s.seq.load(std::memory_order_relaxed) | 1;
    s.seq.store(seq, std::memory_order_release);     // mark mid-write
    std::atomic_thread_fence(std::memory_order_release);
    s.leaf = leaf_addr;
    const int n = backtrace(s.pcs, kCMaxDepth);
    const int skip = n > skip_frames ? skip_frames : 0;
    s.depth = n - skip;
    if (skip > 0) {
      memmove(s.pcs, s.pcs + skip, sizeof(void*) * (size_t)s.depth);
    }
    // fences pair with the reader's acquire fence: payload writes cannot
    // sink below the stable-marking store, and the reader's copies
    // cannot hoist above its seq check (the seqlock protocol)
    std::atomic_thread_fence(std::memory_order_release);
    s.seq.store(seq + 1, std::memory_order_release);  // stable
  }

  int64_t sample_count() const {
    const int64_t n = captured.load(std::memory_order_relaxed);
    return n > kCMaxSamples ? kCMaxSamples : n;
  }

  void reset() {
    captured.store(0, std::memory_order_relaxed);
    events.store(0, std::memory_order_relaxed);
    for (auto& s : ring) s.seq.store(0, std::memory_order_relaxed);
  }
};

EventSampler g_contention;
EventSampler g_iobuf_alloc;

// dladdr-based naming: exported functions get their symbol; local/
// coroutine-clone frames (not in dynsym) get "module+0xoffset", which
// `addr2line -e module 0xoffset` resolves to the exact site — without
// this every local frame collapsed into one opaque "libbrpc_core.so"
// bucket.
std::string symbolize_pc(const void* pc, const char* prefix) {
  Dl_info info;
  char buf[160];
  if (pc != nullptr && dladdr(pc, &info) != 0 && info.dli_fname != nullptr) {
    if (info.dli_sname != nullptr) {
      snprintf(buf, sizeof(buf), "%s%s", prefix, info.dli_sname);
    } else {
      const char* sl = strrchr(info.dli_fname, '/');
      snprintf(buf, sizeof(buf), "%s%s+0x%zx", prefix,
               sl ? sl + 1 : info.dli_fname,
               (size_t)((const char*)pc - (char*)info.dli_fbase));
    }
  } else {
    snprintf(buf, sizeof(buf), "%s%p", prefix, pc);
  }
  return buf;
}

int render_ring(EventSampler& es, const char* what, bool leaf_is_identity,
                const char* leaf_prefix, char* out, unsigned long cap) {
  const int n = (int)es.sample_count();
  std::map<std::string, int> folded;
  for (int i = 0; i < n; ++i) {
    CSample& s = es.ring[i];
    const uint64_t seq0 = s.seq.load(std::memory_order_acquire);
    if (seq0 == 0 || (seq0 & 1)) continue;  // empty or mid-write
    std::atomic_thread_fence(std::memory_order_acquire);
    int depth = s.depth;
    const void* leaf = s.leaf;
    void* pcs[kCMaxDepth];
    if (depth <= 0 || depth > kCMaxDepth) continue;
    memcpy(pcs, s.pcs, sizeof(void*) * (size_t)depth);
    std::atomic_thread_fence(std::memory_order_acquire);
    if (s.seq.load(std::memory_order_relaxed) != seq0) continue;  // torn
    std::string key;
    for (int d = depth - 1; d >= 0; --d) {  // root first
      if (!key.empty()) key += ';';
      key += symbolize_pc(pcs[d], "");
    }
    if (leaf_is_identity) {
      // e.g. a mutex address as the site identity: a global/static
      // object resolves to its symbol via dladdr; heap ones print raw
      if (!key.empty()) key += ';';
      key += symbolize_pc(leaf, leaf_prefix);
    }
    folded[key] += 1;
  }
  std::string text;
  text += "# ";
  text += what;
  text += " events: " +
          std::to_string(es.events.load(std::memory_order_relaxed)) +
          ", stacks sampled: " + std::to_string(n) +
          " (rate-bounded 1/ms)\n";
  for (const auto& [k, c] : folded) {
    text += k;
    text += ' ';
    text += std::to_string(c);
    text += '\n';
  }
  if (cap == 0) return -1;
  if (text.size() + 1 > cap) {
    static const char kMark[] = "\n...truncated\n";
    if (cap <= sizeof(kMark)) {
      text.clear();
    } else {
      text.resize(cap - sizeof(kMark));
      text += kMark;
    }
  }
  memcpy(out, text.data(), text.size());
  out[text.size()] = 0;
  return (int)text.size();
}

}  // namespace

void contention_note(const void* lock_addr) {
  g_contention.note(lock_addr, /*skip=*/1, /*clock_every=*/1);
}
int64_t contention_event_count() {
  return g_contention.events.load(std::memory_order_relaxed);
}
int64_t contention_sample_count() { return g_contention.sample_count(); }
void contention_reset() { g_contention.reset(); }
int contention_folded(char* out, unsigned long cap) {
  return render_ring(g_contention, "contention", /*leaf=*/true, "lock:",
                     out, cap);
}

void iobuf_alloc_note() {
  // skip 2: this function + create_block (the caller IS the site)
  g_iobuf_alloc.note(nullptr, /*skip=*/2, /*clock_every=*/64);
}
int64_t iobuf_alloc_event_count() {
  return g_iobuf_alloc.events.load(std::memory_order_relaxed);
}
int64_t iobuf_alloc_sample_count() { return g_iobuf_alloc.sample_count(); }
void iobuf_alloc_reset() { g_iobuf_alloc.reset(); }
int iobuf_alloc_folded(char* out, unsigned long cap) {
  return render_ring(g_iobuf_alloc, "iobuf block alloc", /*leaf=*/false,
                     "", out, cap);
}

}  // namespace butil
