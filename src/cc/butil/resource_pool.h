// ResourcePool — slab allocator addressing objects by dense 32-bit slot ids.
//
// Spec from the reference (SURVEY.md §2.1; /root/reference
// src/butil/resource_pool.h:28-70): objects live forever in chunked slabs and
// are recycled through free lists; a 32-bit slot id addresses any object in
// O(1).  Combined with a per-object 32-bit version (see VersionedId below),
// a stale 64-bit handle simply fails validation instead of racing on freed
// memory — the safety backbone of SocketId and call ids (§5.3).
//
// New implementation: global chunk table + per-thread free-slot caches with a
// mutex-guarded overflow list (the reference uses lock-free thread-local
// chunks; our hot paths hit the TLS cache and take the lock only to refill).
#pragma once

#include <cstdint>
#include <mutex>
#include <vector>

namespace butil {

// 64-bit versioned handle: high 32 bits version, low 32 bits slot.
struct VersionedId {
  uint64_t value;
  uint32_t slot() const { return (uint32_t)value; }
  uint32_t version() const { return (uint32_t)(value >> 32); }
  static VersionedId make(uint32_t version, uint32_t slot) {
    return VersionedId{((uint64_t)version << 32) | slot};
  }
};

template <typename T>
class ResourcePool {
 public:
  static constexpr size_t kChunkItems = 256;
  static constexpr size_t kTlsCacheMax = 64;

  // Get a free object; *slot receives its id.  Object is NOT reconstructed —
  // callers reset fields (mirrors reference semantics where pooled objects
  // keep internal version state across reuse).
  // Returns nullptr if the pool is exhausted (kMaxChunks reached).
  T* get_resource(uint32_t* slot) {
    auto& tls = tls_free();
    if (tls.empty()) refill_tls(tls);
    if (tls.empty()) return nullptr;
    uint32_t s = tls.back();
    tls.pop_back();
    *slot = s;
    return address(s);
  }

  void return_resource(uint32_t slot) {
    auto& tls = tls_free();
    tls.push_back(slot);
    if (tls.size() > kTlsCacheMax) {
      std::lock_guard<std::mutex> g(_mu);
      _free.insert(_free.end(), tls.begin() + kTlsCacheMax / 2, tls.end());
      tls.resize(kTlsCacheMax / 2);
    }
  }

  // O(1) slot → object.  Valid for any slot ever returned by
  // get_resource; an arbitrary/corrupt slot (a handle forged or damaged
  // upstream) returns nullptr instead of dereferencing an unallocated
  // chunk — versioned-handle validity checks depend on this being safe
  // to call with garbage.
  // Lock-free: the chunk table is a fixed array of pointers published with
  // release stores, so it never moves under a reader.
  T* address(uint32_t slot) {
    const uint32_t chunk_idx = slot / kChunkItems;
    if (chunk_idx >= kMaxChunks) return nullptr;
    Chunk* c = _chunks[chunk_idx].load(std::memory_order_acquire);
    if (c == nullptr) return nullptr;
    return &c->items[slot % kChunkItems];
  }

  size_t allocated() const { return _allocated; }

  static ResourcePool* singleton() {
    static ResourcePool pool;
    return &pool;
  }

 private:
  struct Chunk {
    T items[kChunkItems];
  };

  std::vector<uint32_t>& tls_free() {
    static thread_local std::vector<uint32_t> cache;
    return cache;
  }

  void refill_tls(std::vector<uint32_t>& tls) {
    std::lock_guard<std::mutex> g(_mu);
    if (_free.empty() && _nchunks < kMaxChunks) {
      // Carve a new chunk.
      auto* c = new Chunk();
      _chunks[_nchunks].store(c, std::memory_order_release);
      const uint32_t base = (uint32_t)(_nchunks * kChunkItems);
      ++_nchunks;
      _allocated += kChunkItems;
      for (uint32_t i = 0; i < kChunkItems; ++i)
        _free.push_back(base + kChunkItems - 1 - i);
    }
    const size_t take = _free.size() < kTlsCacheMax / 2 ? _free.size()
                                                        : kTlsCacheMax / 2;
    tls.insert(tls.end(), _free.end() - take, _free.end());
    _free.resize(_free.size() - take);
  }

  static constexpr size_t kMaxChunks = 65536;  // 16.7M objects max per pool

  std::mutex _mu;
  std::atomic<Chunk*> _chunks[kMaxChunks] = {};
  size_t _nchunks = 0;
  std::vector<uint32_t> _free;
  size_t _allocated = 0;
};

}  // namespace butil
