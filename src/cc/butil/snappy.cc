#include "butil/snappy.h"

#include <cstring>

namespace butil {

namespace {

// Emission helpers -----------------------------------------------------

inline uint8_t* emit_varint(uint8_t* dst, uint32_t v) {
  while (v >= 0x80) {
    *dst++ = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  *dst++ = (uint8_t)v;
  return dst;
}

inline uint8_t* emit_literal(uint8_t* dst, const uint8_t* src, size_t len) {
  // tag: low 2 bits 00, upper 6 bits encode len-1 (<60) or a byte count
  // 60..62 for 1..3 little-endian extra length bytes.
  const size_t n = len - 1;
  if (n < 60) {
    *dst++ = (uint8_t)(n << 2);
  } else if (n < (1u << 8)) {
    *dst++ = 60 << 2;
    *dst++ = (uint8_t)n;
  } else if (n < (1u << 16)) {
    *dst++ = 61 << 2;
    *dst++ = (uint8_t)n;
    *dst++ = (uint8_t)(n >> 8);
  } else {
    *dst++ = 62 << 2;
    *dst++ = (uint8_t)n;
    *dst++ = (uint8_t)(n >> 8);
    *dst++ = (uint8_t)(n >> 16);
  }
  std::memcpy(dst, src, len);
  return dst + len;
}

// One copy element, 4 <= len <= 64, offset < 65536.
inline uint8_t* emit_copy_upto64(uint8_t* dst, size_t offset, size_t len) {
  if (len <= 11 && offset < 2048) {
    // copy-1: 3-bit len-4, 11-bit offset (high 3 bits in the tag)
    *dst++ = (uint8_t)(0x01 | ((len - 4) << 2) | ((offset >> 8) << 5));
    *dst++ = (uint8_t)offset;
  } else {
    // copy-2: 6-bit len-1, 16-bit LE offset
    *dst++ = (uint8_t)(0x02 | ((len - 1) << 2));
    *dst++ = (uint8_t)offset;
    *dst++ = (uint8_t)(offset >> 8);
  }
  return dst;
}

inline uint8_t* emit_copy(uint8_t* dst, size_t offset, size_t len) {
  // Long matches become several elements; keep every remainder >= 4.
  while (len >= 68) {
    dst = emit_copy_upto64(dst, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    dst = emit_copy_upto64(dst, offset, 60);
    len -= 60;
  }
  return emit_copy_upto64(dst, offset, len);
}

inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash32(uint32_t v) { return (v * 0x1e35a7bdu) >> 18; }  // 14b

constexpr size_t kBlockSize = 1 << 16;
constexpr size_t kHashSize = 1 << 14;

}  // namespace

size_t snappy_max_compressed_length(size_t n) {
  // varint header (<=5) + worst-case literal framing: one 3-byte tag per
  // 64KB block plus the bytes themselves.  Google's own bound.
  return 32 + n + n / 6;
}

size_t snappy_compress(const uint8_t* src, size_t n, uint8_t* dst) {
  // The format's length header is 32-bit; refuse instead of silently
  // truncating (the decompressor rejects >32-bit varints for the same
  // reason).  Callers chunk payloads this large far upstream.
  if (n > 0xffffffffu) return 0;
  uint8_t* op = emit_varint(dst, (uint32_t)n);
  uint16_t table[kHashSize];

  for (size_t block = 0; block < n || block == 0; block += kBlockSize) {
    const size_t block_len = (n - block < kBlockSize) ? n - block
                                                      : kBlockSize;
    const uint8_t* base = src + block;
    std::memset(table, 0, sizeof(table));
    size_t i = 0;          // scan position within block
    size_t lit_start = 0;  // first unemitted literal byte
    if (block_len >= 4) {
      while (i + 4 <= block_len) {
        const uint32_t h = hash32(load32(base + i));
        const size_t cand = table[h];
        table[h] = (uint16_t)i;
        if (cand < i && load32(base + cand) == load32(base + i)) {
          // extend the match
          size_t len = 4;
          while (i + len < block_len && base[cand + len] == base[i + len]) {
            ++len;
          }
          if (lit_start < i) {
            op = emit_literal(op, base + lit_start, i - lit_start);
          }
          op = emit_copy(op, i - cand, len);
          i += len;
          lit_start = i;
        } else {
          ++i;
        }
      }
    }
    if (lit_start < block_len) {
      op = emit_literal(op, base + lit_start, block_len - lit_start);
    }
    if (n == 0) break;  // the block==0 pass for empty input
  }
  return (size_t)(op - dst);
}

namespace {

bool read_varint(const uint8_t** p, const uint8_t* end, uint32_t* out) {
  uint32_t v = 0;
  int shift = 0;
  const uint8_t* ip = *p;
  while (ip < end && shift < 35) {
    const uint8_t b = *ip++;
    v |= (uint32_t)(b & 0x7f) << shift;
    if ((b & 0x80) == 0) {
      // reject bits above 32 (shift 28 with a byte > 0x0f)
      if (shift == 28 && (b & 0x70) != 0) return false;
      *p = ip;
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

}  // namespace

bool snappy_uncompressed_length(const uint8_t* src, size_t n, size_t* out) {
  uint32_t v = 0;
  const uint8_t* p = src;
  if (!read_varint(&p, src + n, &v)) return false;
  *out = v;
  return true;
}

bool snappy_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                       size_t dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const end = src + n;
  uint32_t expected = 0;
  if (!read_varint(&ip, end, &expected)) return false;
  if (expected > dst_cap) return false;
  size_t op = 0;

  while (ip < end) {
    const uint8_t tag = *ip++;
    if ((tag & 3) == 0) {
      // literal
      size_t len = (size_t)(tag >> 2) + 1;
      if (len > 60) {
        const size_t extra = len - 60;  // 1..3 (64 would need 4; tag>>2
                                        // caps at 63 so extra <= 3... but
                                        // the format allows 63 = 4 bytes)
        if (extra > 4 || ip + extra > end) return false;
        uint32_t l = 0;
        for (size_t k = 0; k < extra; ++k) l |= (uint32_t)ip[k] << (8 * k);
        ip += extra;
        len = (size_t)l + 1;
      }
      if ((size_t)(end - ip) < len || expected - op < len) return false;
      std::memcpy(dst + op, ip, len);
      ip += len;
      op += len;
    } else {
      size_t len, offset;
      if ((tag & 3) == 1) {
        if (ip >= end) return false;
        len = ((size_t)(tag >> 2) & 7) + 4;
        offset = ((size_t)(tag >> 5) << 8) | *ip++;
      } else if ((tag & 3) == 2) {
        if (ip + 2 > end) return false;
        len = (size_t)(tag >> 2) + 1;
        offset = (size_t)ip[0] | ((size_t)ip[1] << 8);
        ip += 2;
      } else {
        if (ip + 4 > end) return false;
        len = (size_t)(tag >> 2) + 1;
        offset = (size_t)ip[0] | ((size_t)ip[1] << 8) |
                 ((size_t)ip[2] << 16) | ((size_t)ip[3] << 24);
        ip += 4;
      }
      if (offset == 0 || offset > op) return false;      // hostile offset
      if (expected - op < len) return false;             // output overrun
      // overlap-safe: offset < len duplicates the tail as it grows
      const uint8_t* from = dst + op - offset;
      uint8_t* to = dst + op;
      for (size_t k = 0; k < len; ++k) to[k] = from[k];
      op += len;
    }
  }
  return op == expected;
}

}  // namespace butil
