// Snappy block-format codec (SURVEY.md §2.1 crypto/encoding row; the
// reference vendors Google snappy under butil/third_party and registers it
// as a compression policy, global.cpp:393-403).  Clean-room implementation
// from the public format description (format_description.txt): varint
// uncompressed length, then literal / copy-1 / copy-2 / copy-4 tagged
// elements.  The compressor is a greedy 4-byte-hash LZ within 64KB blocks
// (offsets always fit copy-2); the decompressor is strictly bounds-checked
// and rejects hostile input instead of reading or writing out of range.
#pragma once

#include <cstddef>
#include <cstdint>

namespace butil {

// Worst-case output size for n input bytes (all-literal emission).
size_t snappy_max_compressed_length(size_t n);

// Compress src[0..n) into dst (capacity >= snappy_max_compressed_length(n)).
// Returns bytes written.
size_t snappy_compress(const uint8_t* src, size_t n, uint8_t* dst);

// Parse the uncompressed-length header.  Returns false on a malformed
// varint (or one exceeding 32 bits).
bool snappy_uncompressed_length(const uint8_t* src, size_t n, size_t* out);

// Decompress src[0..n) into dst (capacity dst_cap).  Returns false on any
// malformed input: bad varint, truncated element, offset outside the
// produced output, or output size mismatch.
bool snappy_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                       size_t dst_cap);

}  // namespace butil
