#include "bvar/combiner.h"

#include <mutex>
#include <vector>

namespace bvar {

// ---- thread block registry (immortal) ----

namespace {

std::atomic<ThreadBlock*> g_blocks{nullptr};

// Blocks from exited threads, recycled for new threads.  A dead thread's
// counts stay in its block (still on the g_blocks list, still summed);
// handing the block to a NEW thread just stacks its adds on top — correct
// for sums, counts, histograms and max alike.  Bounds memory by the PEAK
// number of concurrent combiner-touching threads, not the total ever
// created (thread-per-request churn would otherwise leak ~72KB/thread).
std::mutex g_free_mu;
std::vector<ThreadBlock*> g_free_blocks;

struct BlockHolder {
  ThreadBlock* block = nullptr;
  ThreadBlock* get() {
    if (block == nullptr) {
      {
        std::lock_guard<std::mutex> g(g_free_mu);
        if (!g_free_blocks.empty()) {
          block = g_free_blocks.back();   // already on the g_blocks list
          g_free_blocks.pop_back();
        }
      }
      if (block == nullptr) {
        block = new ThreadBlock();
        ThreadBlock* head = g_blocks.load(std::memory_order_acquire);
        do {
          block->next = head;
        } while (!g_blocks.compare_exchange_weak(head, block,
                                                 std::memory_order_acq_rel,
                                                 std::memory_order_acquire));
      }
    }
    return block;
  }
  ~BlockHolder() {
    if (block != nullptr) {
      std::lock_guard<std::mutex> g(g_free_mu);
      g_free_blocks.push_back(block);
    }
  }
};

thread_local BlockHolder tls_block;

// ---- slot allocators (slot id + per-slot generation) ----

struct SlotAlloc {
  explicit SlotAlloc(int max) : gens(max, 0), used(max, false) {}
  std::mutex mu;
  std::vector<uint32_t> gens;
  std::vector<bool> used;
  int hint = 0;

  // returns slot or -1 when exhausted; *gen is the slot's new generation
  int acquire(uint32_t* gen) {
    std::lock_guard<std::mutex> g(mu);
    const int n = (int)gens.size();
    for (int i = 0; i < n; ++i) {
      const int s = (hint + i) % n;
      if (!used[s]) {
        used[s] = true;
        hint = s + 1;
        *gen = ++gens[s];  // bump: every stale cell becomes invisible
        return s;
      }
    }
    return -1;
  }

  void release(int slot) {
    if (slot < 0) return;
    std::lock_guard<std::mutex> g(mu);
    used[slot] = false;
    ++gens[slot];  // invalidate cells immediately
  }
};

SlotAlloc& adder_slots() {
  static SlotAlloc a(kMaxAdders);
  return a;
}
SlotAlloc& latency_slots() {
  static SlotAlloc a(kMaxLatencyRecs);
  return a;
}

}  // namespace

ThreadBlock* this_thread_block() { return tls_block.get(); }
ThreadBlock* all_blocks() { return g_blocks.load(std::memory_order_acquire); }

// ---- Adder ----

Adder::Adder() {
  uint32_t gen = 0;
  _slot = adder_slots().acquire(&gen);
  // Exhaustion (>4096 live counters) is a misconfiguration; writes become
  // no-ops rather than UB: park on slot 0 with generation 0, which the
  // allocator never hands out.
  if (_slot < 0) {
    _slot = 0;
    gen = 0;
  }
  _gen.store(gen, std::memory_order_release);
}

void Adder::close() {
  const uint32_t gen = _gen.exchange(0, std::memory_order_acq_rel);
  if (gen != 0) adder_slots().release(_slot);
}

Adder::~Adder() { close(); }

int64_t Adder::get() const {
  const uint32_t gen = _gen.load(std::memory_order_acquire);
  if (gen == 0) return 0;
  int64_t total = 0;
  for (ThreadBlock* b = all_blocks(); b != nullptr; b = b->next) {
    const AdderCell& c = b->adders[_slot];
    if (c.gen.load(std::memory_order_acquire) == gen) {
      total += c.v.load(std::memory_order_relaxed);
    }
  }
  return total;
}

// ---- LatencyRecorder ----

LatencyRecorder::LatencyRecorder() {
  uint32_t gen = 0;
  _slot = latency_slots().acquire(&gen);
  if (_slot < 0) {
    _slot = 0;
    gen = 0;
  }
  _gen.store(gen, std::memory_order_release);
}

void LatencyRecorder::close() {
  const uint32_t gen = _gen.exchange(0, std::memory_order_acq_rel);
  if (gen != 0) latency_slots().release(_slot);
}

LatencyRecorder::~LatencyRecorder() { close(); }

LatencyCell* LatencyRecorder::local_cell(uint32_t gen) {
  ThreadBlock* b = this_thread_block();
  LatencyCell* c = b->lat[_slot].load(std::memory_order_acquire);
  if (c == nullptr) {
    c = new LatencyCell();  // lives with its (recycled) block
    b->lat[_slot].store(c, std::memory_order_release);
  }
  if (c->gen.load(std::memory_order_relaxed) != gen) {
    c->count.store(0, std::memory_order_relaxed);
    c->sum.store(0, std::memory_order_relaxed);
    c->max.store(0, std::memory_order_relaxed);
    for (auto& h : c->hist) h.store(0, std::memory_order_relaxed);
    c->gen.store(gen, std::memory_order_release);
  }
  return c;
}

void LatencyRecorder::record(int64_t us) {
  const uint32_t gen = _gen.load(std::memory_order_relaxed);
  if (gen == 0) return;
  LatencyCell* c = local_cell(gen);
  // single writer per cell: plain read-modify-write, no RMW atomics
  c->count.store(c->count.load(std::memory_order_relaxed) + 1,
                 std::memory_order_relaxed);
  c->sum.store(c->sum.load(std::memory_order_relaxed) + us,
               std::memory_order_relaxed);
  if (us > c->max.load(std::memory_order_relaxed)) {
    c->max.store(us, std::memory_order_relaxed);
  }
  auto& h = c->hist[latency_bucket(us)];
  h.store(h.load(std::memory_order_relaxed) + 1, std::memory_order_relaxed);
}

LatencyStats LatencyRecorder::stats() const {
  LatencyStats out;
  const uint32_t gen = _gen.load(std::memory_order_acquire);
  if (gen == 0) return out;
  for (ThreadBlock* b = all_blocks(); b != nullptr; b = b->next) {
    LatencyCell* c = b->lat[_slot].load(std::memory_order_acquire);
    if (c == nullptr || c->gen.load(std::memory_order_acquire) != gen) {
      continue;
    }
    out.count += c->count.load(std::memory_order_relaxed);
    out.sum += c->sum.load(std::memory_order_relaxed);
    const int64_t m = c->max.load(std::memory_order_relaxed);
    if (m > out.max) out.max = m;
  }
  return out;
}

double LatencyRecorder::percentile(double ratio) const {
  const uint32_t gen = _gen.load(std::memory_order_acquire);
  if (gen == 0) return 0.0;
  uint64_t merged[kLatencyBuckets] = {0};
  uint64_t total = 0;
  for (ThreadBlock* b = all_blocks(); b != nullptr; b = b->next) {
    LatencyCell* c = b->lat[_slot].load(std::memory_order_acquire);
    if (c == nullptr || c->gen.load(std::memory_order_acquire) != gen) {
      continue;
    }
    for (int i = 0; i < kLatencyBuckets; ++i) {
      const uint32_t n = c->hist[i].load(std::memory_order_relaxed);
      merged[i] += n;
      total += n;
    }
  }
  if (total == 0) return 0.0;
  if (ratio < 0) ratio = 0;
  if (ratio > 1) ratio = 1;
  uint64_t target = (uint64_t)(ratio * (double)total + 0.5);
  if (target == 0) target = 1;
  if (target > total) target = total;
  uint64_t acc = 0;
  for (int i = 0; i < kLatencyBuckets; ++i) {
    acc += merged[i];
    if (acc >= target) return latency_bucket_mid(i);
  }
  return latency_bucket_mid(kLatencyBuckets - 1);
}

}  // namespace bvar
