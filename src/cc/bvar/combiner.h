// Per-thread combined counters & latency cells (reference
// bvar/detail/combiner.h:71-156, agent_group.h, latency_recorder.h:49-75;
// SURVEY.md §2.7).
//
// Write path: one relaxed store to the calling thread's OWN cell — no
// shared cacheline, no lock, no CAS (each cell has exactly one writer).
// Read path: sum matching cells across every thread's block under a short
// registry lock.  The reference's economics exactly.
//
// Lifetime scheme (differs from the reference's agent reclamation):
// thread blocks are IMMORTAL — registered on a global list at first touch
// and never freed, so readers can walk them without coordinating with
// thread exit, and a dying thread's final counts are never lost (they
// simply stay in its block and keep being summed).  Object slots are
// recycled through a (slot, generation) pair: destroying a counter bumps
// the slot's generation, making every thread's stale cell invisible to
// the slot's next owner.  Bounded cost: one block per thread that ever
// touched a counter (~72KB + lazily-allocated latency cells).
#pragma once

#include <atomic>
#include <cstdint>

namespace bvar {

constexpr int kMaxAdders = 4096;       // combiner objects process-wide
constexpr int kMaxLatencyRecs = 512;   // latency recorders process-wide
constexpr int kLatencyBuckets = 512;   // 8 sub-buckets/octave log2 hist

struct AdderCell {
  std::atomic<uint32_t> gen{0};
  std::atomic<int64_t> v{0};
};

struct LatencyCell {
  std::atomic<uint32_t> gen{0};
  std::atomic<int64_t> count{0};
  std::atomic<int64_t> sum{0};
  std::atomic<int64_t> max{0};
  std::atomic<uint32_t> hist[kLatencyBuckets];
  LatencyCell() { for (auto& h : hist) h.store(0, std::memory_order_relaxed); }
};

struct ThreadBlock {
  AdderCell adders[kMaxAdders];
  std::atomic<LatencyCell*> lat[kMaxLatencyRecs];  // lazily allocated
  ThreadBlock* next = nullptr;                     // global immortal list
};

// The calling thread's block (created + registered on first use) and the
// global list head for readers.
ThreadBlock* this_thread_block();
ThreadBlock* all_blocks();

// value(us) -> histogram bucket: exact below 8, then 8 sub-buckets per
// power of two (12.5% worst-case resolution).
inline int latency_bucket(int64_t v) {
  if (v <= 0) return 0;
  uint64_t u = (uint64_t)v;
  if (u < 8) return (int)u;
  const int oct = 63 - __builtin_clzll(u);
  const int sub = (int)((u >> (oct - 3)) & 7);
  const int idx = (oct - 3) * 8 + sub + 8;
  return idx >= kLatencyBuckets ? kLatencyBuckets - 1 : idx;
}

inline double latency_bucket_mid(int idx) {
  if (idx < 8) return (double)idx;
  const int oct = (idx - 8) / 8 + 3;
  const int sub = (idx - 8) % 8;
  const double base = (double)(1ull << oct) * (1.0 + sub / 8.0);
  return base + (double)(1ull << oct) / 16.0;
}

// Combined int64 sum.  add() is a single-writer relaxed load+store on the
// caller's own cell; get() sums cells whose generation matches.
class Adder {
 public:
  Adder();
  ~Adder();
  Adder(const Adder&) = delete;
  Adder& operator=(const Adder&) = delete;

  void add(int64_t d) {
    const uint32_t gen = _gen.load(std::memory_order_relaxed);
    if (gen == 0) return;   // closed, or slot pool exhausted: no-op —
                            // never touch slot 0's legitimate owner
    AdderCell& c = this_thread_block()->adders[_slot];
    if (c.gen.load(std::memory_order_relaxed) != gen) {
      c.v.store(0, std::memory_order_relaxed);
      c.gen.store(gen, std::memory_order_release);
    }
    c.v.store(c.v.load(std::memory_order_relaxed) + d,
              std::memory_order_relaxed);
  }

  int64_t get() const;

  // Release the slot and go inert: adds become no-ops, reads return 0.
  // The C-ABI "free" calls this WITHOUT deleting the object, so stale
  // readers (a sampler thread holding the handle across a Python GC)
  // read zeros instead of freed memory; the slot — the scarce resource —
  // recycles.  close() must not race add() on the same object.
  void close();

 private:
  int _slot;
  std::atomic<uint32_t> _gen;
};

struct LatencyStats {
  int64_t count = 0;
  int64_t sum = 0;
  int64_t max = 0;
};

// Combined latency recorder: count/sum/max + log-bucket histogram, all in
// the caller's own cell; merged on read.
class LatencyRecorder {
 public:
  LatencyRecorder();
  ~LatencyRecorder();
  LatencyRecorder(const LatencyRecorder&) = delete;
  LatencyRecorder& operator=(const LatencyRecorder&) = delete;

  void record(int64_t us);
  LatencyStats stats() const;
  // latency at `ratio` (0.5 = p50) from the merged histogram.
  double percentile(double ratio) const;
  // See Adder::close().
  void close();

 private:
  LatencyCell* local_cell(uint32_t gen);
  int _slot;
  std::atomic<uint32_t> _gen;
};

}  // namespace bvar
