// C ABI for the tpu-rpc native core.  Python binds these with ctypes
// (brpc_tpu/_core/lib.py).  The surface mirrors how the reference layers
// protobuf stubs over a native core: transport, framing, buffers, timers and
// the executor are native; protocol semantics live above.
#include <cstring>
#include <unistd.h>

#include "bthread/butex.h"
#include "bthread/fiber.h"
#include "bthread/executor.h"
#include "bthread/timer.h"
#include "butil/common.h"
#include "butil/flight.h"
#include "butil/iobuf.h"
#include "butil/snappy.h"
#include "bvar/combiner.h"
#include "net/event_dispatcher.h"
#include "net/parser.h"
#include "net/fd_wait.h"
#include "net/h2.h"
#include "net/socket.h"

using butil::IOBuf;

extern "C" {

// ---- lifecycle ----

void brpc_core_init(int num_workers, int num_dispatchers) {
  bthread::Executor::init_global(num_workers);
  brpc::EventDispatcher::InitGlobal(num_dispatchers);
  (void)bthread::Executor::global();
  (void)bthread::TimerThread::global();
}

void brpc_core_shutdown() {
  brpc::EventDispatcher::ShutdownGlobal();
  bthread::TimerThread::shutdown_global();
  bthread::Executor::shutdown_global();
}

void brpc_set_log_sink(butil::LogSinkFn fn, void* arg) { butil::set_log_sink(fn, arg); }
void brpc_set_min_log_level(int level) { butil::set_min_log_level(level); }

uint32_t brpc_crc32c(const void* data, size_t n, uint32_t init_crc) {
  return butil::crc32c(data, n, init_crc);
}

// ---- snappy block-format codec (butil/snappy.cc) ----
size_t brpc_snappy_max_compressed_length(size_t n) {
  return butil::snappy_max_compressed_length(n);
}
size_t brpc_snappy_compress(const void* src, size_t n, void* dst) {
  return butil::snappy_compress((const uint8_t*)src, n, (uint8_t*)dst);
}
int64_t brpc_snappy_uncompressed_length(const void* src, size_t n) {
  size_t out = 0;
  if (!butil::snappy_uncompressed_length((const uint8_t*)src, n, &out)) {
    return -1;
  }
  return (int64_t)out;
}
int brpc_snappy_decompress(const void* src, size_t n, void* dst,
                           size_t dst_cap) {
  return butil::snappy_decompress((const uint8_t*)src, n, (uint8_t*)dst,
                                  dst_cap)
             ? 0
             : -1;
}

// ---- native CPU profiler (/hotspots native view; butil/profiler.cc) ----
int brpc_prof_start(int hz) { return butil::prof_start(hz); }
int brpc_prof_stop() { return butil::prof_stop(); }
int brpc_prof_dump(const char* path) { return butil::prof_dump(path); }
int brpc_prof_folded(char* out, size_t cap) {
  return butil::prof_folded(out, cap);
}
int64_t brpc_prof_samples() { return butil::prof_sample_count(); }

// ---- contention sampler (/hotspots/contention per-site stacks) ----
int brpc_contention_folded(char* out, size_t cap) {
  return butil::contention_folded(out, cap);
}
int64_t brpc_contention_events() { return butil::contention_event_count(); }
int64_t brpc_contention_samples() { return butil::contention_sample_count(); }
void brpc_contention_reset() { butil::contention_reset(); }

// ---- IOBuf alloc-site sampler (/memory; butil/iobuf_profiler analog) ----
int brpc_iobuf_alloc_folded(char* out, size_t cap) {
  return butil::iobuf_alloc_folded(out, cap);
}
int64_t brpc_iobuf_alloc_events() { return butil::iobuf_alloc_event_count(); }
void brpc_iobuf_alloc_reset() { butil::iobuf_alloc_reset(); }

}  // extern "C" (coroutines need C++ linkage: with C linkage the ramp
   // and its clones collide on one unmangled symbol)

namespace {
// Deliberately contended FiberMutexes behind two DISTINCT coroutine
// bodies — the "two deliberately contended locks" acceptance test.
// The coroutine resume clones are local symbols, so the folded output
// distinguishes the sites as module+0xoffset (addr2line-able), not by
// name; the test asserts two distinct stacks appear.
bthread::FiberMutex g_ctest_mu_a;
bthread::FiberMutex g_ctest_mu_b;
std::atomic<int64_t> g_ctest_done{0};

bthread::Fiber contention_fiber_alpha(int hold_us) {
  co_await g_ctest_mu_a.lock();
  // hold across a SUSPENSION: on a single core a spinning hold never
  // spans a timeslice, so no other worker ever observes the lock taken
  // and zero contention gets recorded — parking the holder guarantees
  // the waiters pile up
  co_await bthread::fiber_sleep_us(hold_us);
  g_ctest_mu_a.unlock();
  g_ctest_done.fetch_add(1, std::memory_order_release);
}

bthread::Fiber contention_fiber_beta(int hold_us) {
  co_await g_ctest_mu_b.lock();
  // deliberately different hold time: with EQUAL holds the two unlock
  // chains stay phase-locked and one of them wins every 1/ms sample
  // token — the page then shows a single site no matter how long the
  // test runs
  co_await bthread::fiber_sleep_us(hold_us + hold_us / 3 + 137);
  g_ctest_mu_b.unlock();
  g_ctest_done.fetch_add(1, std::memory_order_release);
}
}  // namespace

extern "C" {

// Spawn `tasks` fibers split across two lock sites and wait for them —
// the contention self-test driver for tests/test_native_profiler.py.
int brpc_contention_selftest(int tasks, int hold_us, int timeout_ms) {
  g_ctest_done.store(0, std::memory_order_relaxed);
  for (int i = 0; i < tasks; ++i) {
    if (i & 1) {
      contention_fiber_beta(hold_us).spawn();
    } else {
      contention_fiber_alpha(hold_us).spawn();
    }
  }
  const int64_t deadline = butil::monotonic_time_us() + timeout_ms * 1000ll;
  while (g_ctest_done.load(std::memory_order_acquire) < tasks) {
    if (butil::monotonic_time_us() > deadline) return -1;
    usleep(1000);
  }
  return 0;
}

// ---- IOBuf ----

void* brpc_iobuf_new() { return new IOBuf(); }
void brpc_iobuf_free(void* h) { delete (IOBuf*)h; }
void brpc_iobuf_clear(void* h) { ((IOBuf*)h)->clear(); }
size_t brpc_iobuf_size(void* h) { return ((IOBuf*)h)->size(); }
size_t brpc_iobuf_block_num(void* h) { return ((IOBuf*)h)->backing_block_num(); }
void brpc_iobuf_append(void* h, const void* data, size_t n) {
  ((IOBuf*)h)->append(data, n);
}
void brpc_iobuf_append_iobuf(void* h, void* other) {
  ((IOBuf*)h)->append(*(IOBuf*)other);
}
size_t brpc_iobuf_copy_to(void* h, void* out, size_t n, size_t pos) {
  return ((IOBuf*)h)->copy_to(out, n, pos);
}
size_t brpc_iobuf_cutn(void* h, void* out_iobuf, size_t n) {
  return ((IOBuf*)h)->cutn((IOBuf*)out_iobuf, n);
}
size_t brpc_iobuf_pop_front(void* h, size_t n) { return ((IOBuf*)h)->pop_front(n); }
void brpc_iobuf_append_user_data(void* h, void* data, size_t n,
                                 void (*deleter)(void*, void*), void* arg) {
  ((IOBuf*)h)->append_user_data(data, n, deleter, arg);
}
int64_t brpc_iobuf_live_blocks() { return butil::iobuf::live_block_count(); }

// ---- executor / timers ----

typedef void (*brpc_task_fn)(void*);

void brpc_executor_submit(brpc_task_fn fn, void* arg) {
  bthread::Executor::global()->submit(fn, arg);
}
int64_t brpc_executor_tasks_executed() {
  return bthread::Executor::global()->tasks_executed();
}
int64_t brpc_executor_steals() { return bthread::Executor::global()->steals(); }
void brpc_fiber_counters(int64_t* waits, int64_t* wakes, int64_t* timeouts,
                         int64_t* mutex_contended) {
  bthread::Butex::counters(waits, wakes, timeouts, mutex_contended);
}
int brpc_executor_num_workers() { return bthread::Executor::global()->num_workers(); }

uint64_t brpc_timer_add(brpc_task_fn fn, void* arg, int64_t delay_us) {
  return bthread::TimerThread::global()->schedule_after(fn, arg, delay_us);
}
int brpc_timer_cancel(uint64_t id) {
  return bthread::TimerThread::global()->unschedule(id) ? 0 : -1;
}
int64_t brpc_timer_fired() { return bthread::TimerThread::global()->fired(); }

int64_t brpc_now_us() { return butil::monotonic_time_us(); }

// ---- sockets ----

typedef void (*brpc_message_cb)(uint64_t sid, int kind, const char* meta,
                                size_t meta_len, void* body_iobuf, void* user);
typedef void (*brpc_failed_cb)(uint64_t sid, int error_code, void* user);
typedef void (*brpc_accepted_cb)(uint64_t listener, uint64_t conn, void* user);

static brpc::SocketOptions make_opts(brpc_message_cb on_msg, brpc_failed_cb on_fail,
                                     brpc_accepted_cb on_accept, void* user,
                                     int native_echo) {
  brpc::SocketOptions o;
  o.on_message = (brpc::MessageCallback)on_msg;
  o.on_failed = (brpc::SocketFailedCallback)on_fail;
  o.on_accepted = (brpc::AcceptedCallback)on_accept;
  o.user = user;
  o.native_echo = native_echo != 0;
  return o;
}

int brpc_listen(const char* addr, int port, brpc_message_cb on_msg,
                brpc_failed_cb on_fail, brpc_accepted_cb on_accept, void* user,
                int native_echo, uint64_t* sid_out, int* bound_port) {
  return brpc::Listen(addr, port,
                      make_opts(on_msg, on_fail, on_accept, user, native_echo),
                      sid_out, bound_port);
}

int brpc_connect(const char* host, int port, brpc_message_cb on_msg,
                 brpc_failed_cb on_fail, void* user, uint64_t* sid_out) {
  return brpc::Connect(host, port,
                       make_opts(on_msg, on_fail, nullptr, user, 0), sid_out);
}

// Write one TRPC frame: header + meta + body.  body_iobuf may be null.
int brpc_socket_write_frame(uint64_t sid, const void* meta, size_t meta_len,
                            const void* body, size_t body_len,
                            void* body_iobuf) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  IOBuf out;
  char hdr[brpc::kTrpcHeaderLen];
  const uint64_t blen = body_iobuf != nullptr ? ((IOBuf*)body_iobuf)->size()
                                              : body_len;
  brpc::make_trpc_header(hdr, (uint32_t)meta_len, blen);
  out.append(hdr, sizeof(hdr));
  if (meta_len > 0) out.append(meta, meta_len);
  if (body_iobuf != nullptr) out.append(std::move(*(IOBuf*)body_iobuf));
  else if (body_len > 0) out.append(body, body_len);
  const int rc = s->Write(std::move(out));
  s->Dereference();
  return rc;
}

// Write raw bytes (HTTP responses etc.).
int brpc_socket_write_raw(uint64_t sid, const void* data, size_t len,
                          void* body_iobuf) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  IOBuf out;
  if (data != nullptr && len > 0) out.append(data, len);
  if (body_iobuf != nullptr) out.append(std::move(*(IOBuf*)body_iobuf));
  const int rc = s->Write(std::move(out));
  s->Dereference();
  return rc;
}

// Pre-select the wire protocol on a connection (parser.h MessageKind).
int brpc_socket_set_protocol(uint64_t sid, int kind) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  s->set_forced_protocol(kind);
  s->Dereference();
  return 0;
}

// ---- transport filter (in-socket TLS; net/socket.h set_filter_mode) ----

int brpc_socket_set_filter(uint64_t sid, int on) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  s->set_filter_mode(on != 0);
  s->Dereference();
  return 0;
}

namespace {
struct InjectTask {
  uint64_t sid;
  butil::IOBuf data;
};

void run_inject(void* arg) {
  auto* t = (InjectTask*)arg;
  brpc::Socket* s = brpc::Socket::Address(t->sid);
  if (s != nullptr) {
    s->InjectBytes(std::move(t->data));
    s->Dereference();
  }
  delete t;
}
}  // namespace

// Feed decrypted plaintext back into `sid`'s parse/dispatch path.  Runs
// on the socket's dispatcher loop thread (the only thread allowed to
// touch its read buffer); safe from any caller.
int brpc_socket_inject(uint64_t sid, const void* data, size_t len) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  const int shard = s->dispatcher_shard();
  s->Dereference();
  auto* t = new InjectTask{sid, butil::IOBuf()};
  t->data.append(data, len);
  brpc::EventDispatcher::GetDispatcher(shard)->RunOnLoop(run_inject, t);
  return 0;
}

int brpc_socket_set_failed(uint64_t sid, int error_code) {
  return brpc::Socket::SetFailed(sid, error_code);
}

int brpc_socket_alive(uint64_t sid) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return 0;
  s->Dereference();
  return 1;
}

int brpc_socket_stats(uint64_t sid, int64_t* nread, int64_t* nwritten,
                      int64_t* nmsg, char* ip_out, int ip_cap, int* port) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  if (nread) *nread = s->bytes_read();
  if (nwritten) *nwritten = s->bytes_written();
  if (nmsg) *nmsg = s->messages_read();
  if (ip_out && ip_cap > 0) {
    strncpy(ip_out, s->remote_ip(), ip_cap - 1);
    ip_out[ip_cap - 1] = 0;
  }
  if (port) *port = (int)s->remote_port();
  s->Dereference();
  return 0;
}

int64_t brpc_socket_active_count() { return brpc::Socket::active_count(); }

void brpc_socket_traffic(int64_t* nread, int64_t* nwritten, int64_t* nmsg) {
  brpc::Socket::GlobalTraffic(nread, nwritten, nmsg);
}

// ---- bvar combiners (per-thread cells; src/cc/bvar/combiner.h) ----
// Handles for the Python bvar registry: the per-request metrics path
// (MethodStatus, LatencyRecorder) becomes ONE C call into thread-local
// cells — no Python-level locks (VERDICT r2 task 5).

// "free" releases the SLOT (the scarce resource) but never deletes the
// object: a Python-side sampler thread may still hold the handle after
// GC runs __del__ — reads on a closed handle return zeros instead of
// touching freed memory.  The ~16-byte husk is the price of that safety.
// Exact shared atomic counter (NOT a combiner): admission control needs a
// linearizable count — the combiner's relaxed cell-walk can transiently
// undercount in-flight requests and over-admit past max_concurrency.
void* brpc_atomic_new() { return new std::atomic<int64_t>(0); }
void brpc_atomic_free(void* h) { delete (std::atomic<int64_t>*)h; }
int64_t brpc_atomic_incr(void* h, int64_t d) {
  return ((std::atomic<int64_t>*)h)->fetch_add(d,
                                               std::memory_order_acq_rel) + d;
}
int64_t brpc_atomic_get(void* h) {
  return ((std::atomic<int64_t>*)h)->load(std::memory_order_acquire);
}

void* brpc_adder_new() { return new bvar::Adder(); }
void brpc_adder_free(void* h) { ((bvar::Adder*)h)->close(); }
void brpc_adder_add(void* h, int64_t v) { ((bvar::Adder*)h)->add(v); }
int64_t brpc_adder_get(void* h) { return ((bvar::Adder*)h)->get(); }

void* brpc_latency_new() { return new bvar::LatencyRecorder(); }
void brpc_latency_free(void* h) { ((bvar::LatencyRecorder*)h)->close(); }
void brpc_latency_record(void* h, int64_t us) {
  ((bvar::LatencyRecorder*)h)->record(us);
}
void brpc_latency_stats(void* h, int64_t* count, int64_t* sum, int64_t* max) {
  const bvar::LatencyStats s = ((bvar::LatencyRecorder*)h)->stats();
  if (count) *count = s.count;
  if (sum) *sum = s.sum;
  if (max) *max = s.max;
}
double brpc_latency_percentile(void* h, double ratio) {
  return ((bvar::LatencyRecorder*)h)->percentile(ratio);
}

// EOVERCROWDED backpressure controls (reference socket.h:326-380).
void brpc_socket_set_overcrowded_limit(int64_t bytes) {
  brpc::Socket::set_overcrowded_limit(bytes);
}
int64_t brpc_socket_overcrowded_limit() {
  return brpc::Socket::overcrowded_limit();
}
int64_t brpc_socket_pending_write(uint64_t sid) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  const int64_t v = s->pending_write_bytes();
  s->Dereference();
  return v;
}

// ---- native unary RPC hot path (net/rpc.h) ----

// ctypes mirrors brpc::RequestHeader field-for-field (lib.py RequestHeader).
typedef void (*brpc_request_cb)(uint64_t sid, const brpc::RequestHeader* hdr,
                                void* body_iobuf, void* user);
typedef void (*brpc_response_cb)(uint64_t sid, const brpc::RequestHeader* hdr,
                                 void* body_iobuf, void* user);

void brpc_register_python_method(const char* service, const char* method) {
  brpc::MethodRegistry::global()->RegisterPython(service, method);
}

typedef int32_t (*brpc_native_method_fn)(uint64_t sid, void* body_iobuf,
                                         void* resp_iobuf, void* user);

void brpc_register_native_method(const char* service, const char* method,
                                 brpc_native_method_fn fn, void* user,
                                 int inline_run) {
  brpc::MethodRegistry::global()->Register(
      service, method, (brpc::NativeMethodFn)fn, user, inline_run != 0);
}

int brpc_unregister_method(const char* service, const char* method) {
  return brpc::MethodRegistry::global()->Unregister(service, method) ? 0 : -1;
}

void brpc_set_request_callback(brpc_request_cb cb, void* user) {
  brpc::SetRequestCallback((brpc::RequestCallback)cb, user);
}

int64_t brpc_rpc_dropped_responses() {
  return brpc::MethodRegistry::global()->dropped_responses();
}

void brpc_rpc_counters(int64_t* native_calls, int64_t* python_fast_calls) {
  if (native_calls)
    *native_calls = brpc::MethodRegistry::global()->native_calls();
  if (python_fast_calls)
    *python_fast_calls = brpc::MethodRegistry::global()->python_fast_calls();
}

// Usercode admission control (net/rpc.h; VERDICT r4 #4).
void brpc_set_usercode_budget_us(int64_t us) {
  brpc::SetUsercodeLatencyBudgetUs(us);
}
int64_t brpc_usercode_budget_us() { return brpc::UsercodeLatencyBudgetUs(); }
int64_t brpc_usercode_shed_count() { return brpc::UsercodeShedCount(); }
int64_t brpc_usercode_pending() { return brpc::UsercodePending(); }
double brpc_usercode_ema_us() { return brpc::UsercodeEmaUs(); }
void brpc_set_usercode_inline(int on) { brpc::SetUsercodeInline(on != 0); }
int brpc_usercode_inline() { return brpc::UsercodeInline() ? 1 : 0; }

// Pack + write a TRPC response frame natively (server -> client).
int brpc_send_response(uint64_t sid, uint64_t cid, uint16_t attempt,
                       int32_t error_code, const char* error_text,
                       const char* content_type, const void* body,
                       size_t body_len, void* body_iobuf) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  IOBuf b;
  if (body_iobuf != nullptr) b.append(std::move(*(IOBuf*)body_iobuf));
  else if (body != nullptr && body_len > 0) b.append(body, body_len);
  IOBuf frame;
  brpc::PackResponseFrame(&frame, cid, attempt, error_code,
                          error_text, error_text ? strlen(error_text) : 0,
                          content_type, content_type ? strlen(content_type) : 0,
                          std::move(b));
  const int rc = s->Write(std::move(frame));
  s->Dereference();
  return rc;
}

// Pack + write a TRPC request frame natively (client -> server).
int brpc_send_request(uint64_t sid, uint64_t cid, uint16_t attempt,
                      const char* service, const char* method,
                      uint32_t timeout_ms, uint8_t compress,
                      const char* content_type, const void* body,
                      size_t body_len, void* body_iobuf) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  IOBuf b;
  if (body_iobuf != nullptr) b.append(std::move(*(IOBuf*)body_iobuf));
  else if (body != nullptr && body_len > 0) b.append(body, body_len);
  IOBuf frame;
  brpc::PackRequestFrame(&frame, cid, attempt, service, strlen(service),
                         method, strlen(method), timeout_ms, compress,
                         content_type, content_type ? strlen(content_type) : 0,
                         std::move(b));
  const int rc = s->Write(std::move(frame));
  s->Dereference();
  return rc;
}

// Listen with native request dispatch enabled (method registry consulted
// before the generic on_message callback).
int brpc_listen_rpc(const char* addr, int port, brpc_message_cb on_msg,
                    brpc_failed_cb on_fail, brpc_accepted_cb on_accept,
                    void* user, uint64_t* sid_out, int* bound_port) {
  brpc::SocketOptions o = make_opts(on_msg, on_fail, on_accept, user, 0);
  o.enable_rpc_dispatch = true;
  return brpc::Listen(addr, port, o, sid_out, bound_port);
}

// Connect with a pre-parsed response fast path.
int brpc_connect_rpc(const char* host, int port, brpc_message_cb on_msg,
                     brpc_failed_cb on_fail, brpc_response_cb on_resp,
                     void* user, uint64_t* sid_out) {
  brpc::SocketOptions o = make_opts(on_msg, on_fail, nullptr, user, 0);
  o.on_response = (brpc::ResponseCallback)on_resp;
  o.response_user = user;
  return brpc::Connect(host, port, o, sid_out);
}

// ---- native h2/gRPC server data plane (net/h2.h) ----

// Listen with BOTH the native TRPC dispatch and the native h2 session
// enabled on accepted connections.
int brpc_listen_rpc_h2(const char* addr, int port, brpc_message_cb on_msg,
                       brpc_failed_cb on_fail, brpc_accepted_cb on_accept,
                       void* user, uint64_t* sid_out, int* bound_port) {
  brpc::SocketOptions o = make_opts(on_msg, on_fail, on_accept, user, 0);
  o.enable_rpc_dispatch = true;
  o.h2_native = true;
  return brpc::Listen(addr, port, o, sid_out, bound_port);
}

// body_iobuf is an owned IOBuf* handle (free with brpc_iobuf_free after
// reading) or NULL.  mflags: gRPC message flag byte; kind: h2.h EventKind.
typedef void (*brpc_h2_event_cb)(uint64_t sid, uint32_t stream_id, int kind,
                                 const char* service, size_t service_len,
                                 const char* method, size_t method_len,
                                 const char* headers, size_t headers_len,
                                 void* body_iobuf, int mflags, void* user);

void brpc_h2_set_event_cb(brpc_h2_event_cb cb, void* user) {
  brpc::h2::SetH2EventCallback((brpc::h2::H2EventCallback)cb, user);
}

namespace {
// "name\0value\0" pairs -> pointer array (the buffer's own NULs make
// each piece a C string).  Returns the pair count.
size_t split_kv(const char* extra, size_t extra_len,
                std::vector<const char*>* out) {
  size_t off = 0;
  while (off < extra_len) {
    const char* k = extra + off;
    const size_t klen = strnlen(k, extra_len - off);
    if (off + klen >= extra_len) break;  // key's NUL not in range
    off += klen + 1;
    const char* v = extra + off;
    const size_t vlen = strnlen(v, extra_len - off);
    if (off + vlen >= extra_len) break;  // value's NUL not in range:
                                         // downstream strlen would read
                                         // past the caller's buffer
    out->push_back(k);
    out->push_back(v);
    off += vlen + 1;
  }
  return out->size() / 2;
}
}  // namespace

int brpc_h2_respond_unary(uint64_t sid, uint32_t stream_id, int grpc_status,
                          const char* grpc_message, size_t grpc_message_len,
                          const char* payload, size_t payload_len,
                          const char* extra, size_t extra_len) {
  std::vector<const char*> kv;
  const size_t n = extra != nullptr ? split_kv(extra, extra_len, &kv) : 0;
  return brpc::h2::H2RespondUnary(sid, stream_id, grpc_status, grpc_message,
                                  grpc_message_len, payload, payload_len,
                                  n ? kv.data() : nullptr, n)
             ? 0
             : -1;
}

int brpc_h2_send_response_headers(uint64_t sid, uint32_t stream_id,
                                  const char* extra, size_t extra_len) {
  std::vector<const char*> kv;
  const size_t n = extra != nullptr ? split_kv(extra, extra_len, &kv) : 0;
  return brpc::h2::H2SendResponseHeaders(sid, stream_id,
                                         n ? kv.data() : nullptr, n)
             ? 0
             : -1;
}

int brpc_h2_send_message(uint64_t sid, uint32_t stream_id,
                         const char* payload, size_t len, int mflags) {
  return brpc::h2::H2SendGrpcMessage(sid, stream_id, payload, len,
                                     (uint8_t)mflags)
             ? 0
             : -1;
}

int brpc_h2_send_trailers(uint64_t sid, uint32_t stream_id, int grpc_status,
                          const char* grpc_message, size_t grpc_message_len,
                          const char* extra, size_t extra_len) {
  std::vector<const char*> kv;
  const size_t n = extra != nullptr ? split_kv(extra, extra_len, &kv) : 0;
  return brpc::h2::H2SendTrailers(sid, stream_id, grpc_status, grpc_message,
                                  grpc_message_len,
                                  n ? kv.data() : nullptr, n)
             ? 0
             : -1;
}

void brpc_h2_native_stats(int64_t* requests, int64_t* responses,
                          int64_t* python_events) {
  if (requests != nullptr) *requests = brpc::h2::h2_native_requests();
  if (responses != nullptr) *responses = brpc::h2::h2_native_responses();
  if (python_events != nullptr) *python_events = brpc::h2::h2_python_events();
}

}  // extern "C"

// ---- fiber / butex (the M:N runtime; reference src/bthread/butex.cpp) ----
//
// Python-visible demos and stress drivers for the coroutine fiber layer.
// These are product probes, not test scaffolding: /bthreads-style stats and
// the 10k-in-flight story (VERDICT r2 task 3) hang off them.

#include <chrono>

#include "bthread/fiber.h"
#include "bthread/id.h"

namespace {

using bthread::Butex;
using bthread::CountdownEvent;
using bthread::Fiber;
using bthread::FiberMutex;

// Shared-ownership discipline for the driver structs: each fiber holds a
// reference and drops it as its LAST action; the C wrapper holds one too.
// CountdownEvent::signal alone cannot gate deletion — the poller can see
// count()==0 between the count decrement and the wake_all that still
// touches the event's internal mutex, so "count hit zero" does not mean
// "no fiber is still inside the object" (classic sem_post lifetime bug).
template <typename T>
void unref(T* p) {
  if (p->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete p;
}

struct FiberDemo {
  Butex gate{0};          // 0 = hold; release() stores 1 and wakes all
  CountdownEvent done;
  std::atomic<int64_t> started{0};
  std::atomic<int> refs;
  explicit FiberDemo(int n) : done(n), refs(n + 1) {}
};

Fiber fiber_demo_body(FiberDemo* d) {
  d->started.fetch_add(1, std::memory_order_relaxed);
  while (d->gate.value.load(std::memory_order_acquire) == 0) {
    co_await d->gate.wait(0);
  }
  d->done.signal();
  unref(d);
}

// Blocking bridge for Python/pthread callers: poll a CountdownEvent.
// Test-path only; fibers themselves use co_await.
bool poll_countdown(CountdownEvent* e, int timeout_ms) {
  const auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(timeout_ms);
  while (e->count() > 0) {
    if (std::chrono::steady_clock::now() > deadline) return false;
    std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
  return true;
}

struct PingPong {
  Butex word{0};
  CountdownEvent done{2};
  std::atomic<int> refs{3};   // 2 fibers + the wrapper
  int rounds;
};

Fiber pingpong_body(PingPong* p, int32_t mine, int32_t theirs) {
  for (int i = 0; i < p->rounds; ++i) {
    while (p->word.value.load(std::memory_order_acquire) != mine) {
      co_await p->word.wait(theirs);
    }
    p->word.value.store(theirs, std::memory_order_release);
    p->word.wake_all();
  }
  p->done.signal();
  unref(p);
}

struct MutexStress {
  FiberMutex mu;
  int64_t counter = 0;        // deliberately unsynchronized: the mutex IS
                              // the synchronization under test
  CountdownEvent done;
  std::atomic<int> refs;
  explicit MutexStress(int n) : done(n), refs(n + 1) {}
};

Fiber mutex_stress_body(MutexStress* s, int iters) {
  for (int i = 0; i < iters; ++i) {
    co_await s->mu.lock();
    s->counter += 1;
    s->mu.unlock();
    if ((i & 63) == 0) co_await bthread::fiber_sleep_us(0);
  }
  s->done.signal();
  unref(s);
}

// Bounded producer/consumer over FiberCond (wait-morphing via
// butex_requeue) + FiberMutex — the classic cond-var correctness mill.
struct CondPipe {
  bthread::FiberMutex mu;
  bthread::FiberCond not_empty;
  bthread::FiberCond not_full;
  std::vector<int64_t> q;
  size_t cap = 8;
  int64_t produced = 0, consumed = 0, checksum = 0;
  int64_t total;
  CountdownEvent done;
  std::atomic<int> refs;
  CondPipe(int64_t n, int parties) : total(n), done(parties),
                                     refs(parties + 1) {}
};

Fiber cond_producer(CondPipe* p) {
  for (int64_t i = 0; i < p->total; ++i) {
    co_await p->mu.lock();
    while (p->q.size() >= p->cap) {
      co_await p->not_full.wait(p->mu);
    }
    p->q.push_back(i);
    ++p->produced;
    p->not_empty.notify_all(p->mu);   // held: wait-morph contract
    p->mu.unlock();
  }
  p->done.signal();
  unref(p);
}

Fiber cond_consumer(CondPipe* p) {
  for (int64_t i = 0; i < p->total; ++i) {
    co_await p->mu.lock();
    while (p->q.empty()) {
      co_await p->not_empty.wait(p->mu);
    }
    p->checksum += p->q.back();
    p->q.pop_back();
    ++p->consumed;
    p->not_full.notify_all(p->mu);
    p->mu.unlock();
  }
  p->done.signal();
  unref(p);
}

// Semaphore as a permit-bounded critical region: at most `permits`
// fibers inside at once; returns max concurrency observed.
struct SemProbe {
  bthread::FiberSemaphore sem;
  std::atomic<int> inside{0};
  std::atomic<int> max_inside{0};
  CountdownEvent done;
  std::atomic<int> refs;
  SemProbe(int permits, int fibers) : sem(permits), done(fibers),
                                      refs(fibers + 1) {}
};

Fiber sem_body(SemProbe* s, int iters) {
  for (int i = 0; i < iters; ++i) {
    co_await s->sem.acquire();
    const int now = s->inside.fetch_add(1, std::memory_order_acq_rel) + 1;
    int prev = s->max_inside.load(std::memory_order_relaxed);
    while (now > prev &&
           !s->max_inside.compare_exchange_weak(prev, now)) {
    }
    co_await bthread::fiber_sleep_us(0);
    s->inside.fetch_sub(1, std::memory_order_acq_rel);
    s->sem.release();
  }
  s->done.signal();
  unref(s);
}

// RwLock: readers verify the invariant datum is stable; one writer
// mutates it under the exclusive lock.
struct RwProbe {
  bthread::FiberRwLock rw;
  int64_t a = 0, b = 0;           // invariant: a == b
  std::atomic<int64_t> violations{0};
  CountdownEvent done;
  std::atomic<int> refs;
  explicit RwProbe(int parties) : done(parties), refs(parties + 1) {}
};

Fiber rw_reader(RwProbe* p, int iters) {
  for (int i = 0; i < iters; ++i) {
    co_await p->rw.lock_shared();
    if (p->a != p->b) p->violations.fetch_add(1);
    p->rw.unlock_shared();
  }
  p->done.signal();
  unref(p);
}

Fiber rw_writer(RwProbe* p, int iters) {
  for (int i = 0; i < iters; ++i) {
    co_await p->rw.lock();
    ++p->a;
    ++p->b;                        // non-atomic on purpose: the lock is
    p->rw.unlock();                // the synchronization under test
  }
  p->done.signal();
  unref(p);
}

struct SleepProbe {
  CountdownEvent done{1};
  std::atomic<int> refs{2};
  int64_t woke_after_us = 0;
};

Fiber sleep_probe_body(SleepProbe* p, int64_t us) {
  const int64_t t0 = butil::monotonic_time_us();
  co_await bthread::fiber_sleep_us(us);
  p->woke_after_us = butil::monotonic_time_us() - t0;
  p->done.signal();
  unref(p);
}

struct FdWaitProbe {
  CountdownEvent done{1};
  std::atomic<int> refs{2};
  std::atomic<int> rc{-1};
};

Fiber fd_wait_probe_body(FdWaitProbe* p, int fd, uint32_t events, int to) {
  int rc = -1;
  co_await brpc::fiber_fd_wait(fd, events, to, &rc);
  p->rc.store(rc, std::memory_order_release);
  p->done.signal();
  unref(p);
}

}  // namespace

extern "C" {

// 10k-in-flight demo: spawn n fibers that all park on one butex.
void* brpc_fiber_demo_start(int n) {
  auto* d = new FiberDemo(n);
  for (int i = 0; i < n; ++i) fiber_demo_body(d).spawn();
  return d;
}
// Fibers currently parked on the gate (each is a heap frame, not a thread).
int brpc_fiber_demo_blocked(void* h) {
  return ((FiberDemo*)h)->gate.waiter_count();
}
int64_t brpc_fiber_demo_started(void* h) {
  return ((FiberDemo*)h)->started.load(std::memory_order_relaxed);
}
void brpc_fiber_demo_release(void* h) {
  auto* d = (FiberDemo*)h;
  d->gate.value.store(1, std::memory_order_release);
  d->gate.wake_all();
}
int brpc_fiber_demo_join(void* h, int timeout_ms) {
  return poll_countdown(&((FiberDemo*)h)->done, timeout_ms) ? 0 : -1;
}
void brpc_fiber_demo_free(void* h) { unref((FiberDemo*)h); }

// Butex ping-pong: two fibers bounce one word `rounds` times across the
// worker pool (the wake/wait/claim race mill; reference
// test/bthread_ping_pong_unittest.cpp).  Returns 0 on success.
int brpc_fiber_pingpong(int rounds, int timeout_ms) {
  auto* p = new PingPong();
  p->rounds = rounds;
  pingpong_body(p, 0, 1).spawn();
  pingpong_body(p, 1, 0).spawn();
  const bool ok = poll_countdown(&p->done, timeout_ms);
  unref(p);   // straggler fibers hold their own refs; last one frees
  return ok ? 0 : -1;
}

// FiberMutex stress: `fibers` x `iters` unsynchronized increments under
// the mutex; returns the counter (== fibers*iters iff mutual exclusion
// held), or -1 on timeout.
int64_t brpc_fiber_mutex_stress(int fibers, int iters, int timeout_ms) {
  auto* s = new MutexStress(fibers);
  for (int i = 0; i < fibers; ++i) mutex_stress_body(s, iters).spawn();
  const bool ok = poll_countdown(&s->done, timeout_ms);
  const int64_t v = ok ? s->counter : -1;
  unref(s);
  return v;
}

// FiberCond producer/consumer: returns the checksum (== n*(n-1)/2 iff
// every produced item was consumed exactly once), or -1 on timeout.
int64_t brpc_fiber_cond_stress(int64_t n, int timeout_ms) {
  auto* p = new CondPipe(n, 2);
  cond_producer(p).spawn();
  cond_consumer(p).spawn();
  const bool ok = poll_countdown(&p->done, timeout_ms);
  const int64_t v = ok ? p->checksum : -1;
  unref(p);
  return v;
}

// FiberSemaphore: `fibers` contenders over `permits` permits; returns the
// max concurrency observed inside the region (must be <= permits), or -1.
int brpc_fiber_sem_stress(int permits, int fibers, int iters,
                          int timeout_ms) {
  auto* s = new SemProbe(permits, fibers);
  for (int i = 0; i < fibers; ++i) sem_body(s, iters).spawn();
  const bool ok = poll_countdown(&s->done, timeout_ms);
  const int v = ok ? s->max_inside.load() : -1;
  unref(s);
  return v;
}

// FiberRwLock: `readers` checking an invariant vs 1 writer mutating it;
// returns invariant violations seen under shared locks (must be 0), -1
// on timeout.
int64_t brpc_fiber_rw_stress(int readers, int iters, int timeout_ms) {
  auto* p = new RwProbe(readers + 1);
  for (int i = 0; i < readers; ++i) rw_reader(p, iters).spawn();
  rw_writer(p, iters).spawn();
  const bool ok = poll_countdown(&p->done, timeout_ms);
  const int64_t v = ok ? p->violations.load() : -1;
  unref(p);
  return v;
}

// ---- CallId (bthread_id analog; bthread/id.h) ----

}  // extern "C"

namespace {

struct IdLockSt {
  uint64_t id;
  int64_t counter = 0;
  CountdownEvent done;
  std::atomic<int> refs;
  IdLockSt(int n) : done(n), refs(n + 1) {}
};

Fiber id_lock_body(IdLockSt* st, int iters) {
  for (int k = 0; k < iters; ++k) {
    int rc = -1;
    co_await bthread::id_lock(st->id, &rc);
    if (rc == bthread::ID_OK) {
      ++st->counter;
      bthread::id_unlock(st->id);
    }
  }
  st->done.signal();
  unref(st);
}

struct IdDestroySt {
  uint64_t id;
  std::atomic<int64_t> einval{0};
  std::atomic<int64_t> parked{0};
  CountdownEvent done;
  std::atomic<int> refs;
  IdDestroySt(int n) : done(n + 1), refs(n + 2) {}
};

Fiber id_destroy_locker(IdDestroySt* st) {
  st->parked.fetch_add(1, std::memory_order_acq_rel);
  int rc = -1;
  co_await bthread::id_lock(st->id, &rc);   // parks: id is held
  if (rc == bthread::ID_EINVAL) st->einval.fetch_add(1);
  st->done.signal();
  unref(st);
}

Fiber id_destroy_joiner(IdDestroySt* st) {
  co_await bthread::id_join(st->id);
  st->done.signal();
  unref(st);
}

}  // namespace

extern "C" {

uint64_t brpc_id_create(uint32_t range) {
  return bthread::id_create(nullptr, range);
}
int brpc_id_valid(uint64_t id) { return bthread::id_valid(id) ? 1 : 0; }
int brpc_id_trylock(uint64_t id) { return bthread::id_trylock(id); }
int brpc_id_unlock(uint64_t id) { return bthread::id_unlock(id); }
int brpc_id_unlock_and_destroy(uint64_t id) {
  return bthread::id_unlock_and_destroy(id);
}
int brpc_id_join(uint64_t id, int timeout_ms) {
  return bthread::id_join_blocking(id, timeout_ms);
}
int64_t brpc_id_live_count() { return bthread::id_live_count(); }

// Locker storm: `fibers` fibers each lock/increment/unlock the id
// `iters` times (fiber-awaitable id_lock under contention); returns the
// protected counter, or -1 on timeout.
int64_t brpc_id_lock_stress(int fibers, int iters, int timeout_ms) {
  auto* st = new IdLockSt(fibers);
  st->id = bthread::id_create(nullptr, 1);
  for (int i = 0; i < fibers; ++i) id_lock_body(st, iters).spawn();
  const bool ok = poll_countdown(&st->done, timeout_ms);
  int64_t v = -1;
  if (ok) v = st->counter;
  // destroy requires holding the lock; best-effort on the timeout path
  // too so the slot is not leaked out of the pool
  if (bthread::id_trylock(st->id) == bthread::ID_OK) {
    bthread::id_unlock_and_destroy(st->id);
  }
  unref(st);
  return v;
}

// Destroy-under-contention: lockers park on a HELD id; destroy flushes
// them all out with EINVAL and wakes the joiners.  Returns the number of
// lockers that saw EINVAL (must be `fibers`), or -1 on timeout.
int64_t brpc_id_destroy_stress(int fibers, int timeout_ms) {
  auto* st = new IdDestroySt(fibers);
  st->id = bthread::id_create(nullptr, 1);
  if (bthread::id_trylock(st->id) != bthread::ID_OK) {
    unref(st);
    return -1;
  }
  for (int i = 0; i < fibers; ++i) id_destroy_locker(st).spawn();
  // joiner fiber: must wake when destroy runs
  id_destroy_joiner(st).spawn();
  // give lockers a moment to reach the park, then pull the rug
  const auto until = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(500);
  while (st->parked.load() < fibers &&
         std::chrono::steady_clock::now() < until) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  std::this_thread::sleep_for(std::chrono::milliseconds(20));
  bthread::id_unlock_and_destroy(st->id);   // we hold the trylock above
  const bool ok = poll_countdown(&st->done, timeout_ms);
  const int64_t v = ok ? st->einval.load() : -1;
  unref(st);
  return v;
}

// ---- fd wait (net/fd_wait.h; reference bthread_fd_wait fd.cpp:343) ----

int brpc_fd_wait(int fd, uint32_t events, int timeout_ms) {
  return brpc::fd_wait(fd, events, timeout_ms);
}

// Spawns a fiber running fiber_fd_wait and joins it from this pthread:
// proves the park/deliver path from Python.  Returns the wait rc, or -1
// when the fiber never finished inside the poll budget.
int brpc_fiber_fd_wait_probe(int fd, uint32_t events, int timeout_ms) {
  auto* p = new FdWaitProbe();
  // Clamp "wait forever" to below the poll budget: a fiber outliving the
  // poll would leak the probe AND leave the fd armed in the registry,
  // poisoning every later wait on it with EEXIST.
  const int fiber_to = (timeout_ms < 0 || timeout_ms > 55000) ? 55000
                                                              : timeout_ms;
  fd_wait_probe_body(p, fd, events, fiber_to).spawn();
  const bool ok = poll_countdown(&p->done, fiber_to + 5000);
  const int v = ok ? p->rc.load(std::memory_order_acquire) : -1;
  unref(p);
  return v;
}

// Timed sleep: returns actual wake delay in us, or -1 on timeout.
int64_t brpc_fiber_sleep_probe(int64_t us, int timeout_ms) {
  auto* p = new SleepProbe();
  sleep_probe_body(p, us).spawn();
  const bool ok = poll_countdown(&p->done, timeout_ms);
  const int64_t v = ok ? p->woke_after_us : -1;
  unref(p);
  return v;
}

// ---- native flight recorder (ISSUE 15; butil/flight.h) ----

void brpc_flight_enable(int on) { butil::flight::set_enabled(on != 0); }
int brpc_flight_enabled() { return butil::flight::enabled() ? 1 : 0; }

// Merged time-ordered tail of every native thread's event ring; one
// text line per event.  Returns bytes written.
int brpc_flight_dump(char* out, size_t cap, int max_events) {
  return butil::flight::dump(out, cap, max_events);
}

// Per-thread last-event-age table ("what is every native thread doing
// RIGHT NOW").  Returns bytes written.
int brpc_flight_threads(char* out, size_t cap) {
  return butil::flight::threads_table(out, cap);
}

void brpc_flight_stats(int64_t* events, int64_t* threads,
                       int64_t* dropped) {
  butil::flight::stats(events, threads, dropped);
}

// Test driver: record `n` probe events tagged `tag` on the CALLING
// thread's ring (ring-semantics tests: wrap, concurrent writers,
// dump-while-writing, disabled no-op).
void brpc_flight_selftest_emit(int n, uint64_t tag) {
  for (int i = 0; i < n; ++i) {
    butil::flight::record(butil::flight::EV_PROBE, tag, i);
  }
}

}  // extern "C" (the stall task below is a plain C++ internal helper)

namespace {
struct StallSt {
  std::atomic<int> done{0};
  int hold_ms;
};

void stall_task(void* arg) {
  auto* s = (StallSt*)arg;
  // a recognizable last event for the stalled worker: the autopsy test
  // asserts a worker ring whose newest event is this probe
  butil::flight::record(butil::flight::EV_PROBE, 0x57A11, s->hold_ms);
  usleep((useconds_t)s->hold_ms * 1000);
  s->done.store(1, std::memory_order_release);
}
}  // namespace

extern "C" {

// Forced-stall probe (the wedge-autopsy acceptance test): occupies one
// executor worker with a fault-injected native delay and BLOCKS the
// caller until it completes — run it under a WedgeGuard deadline
// shorter than hold_ms and the deadline miss dumps a flight tail whose
// per-thread table names the stalled worker and its last event.
int brpc_flight_stall_probe(int hold_ms) {
  StallSt st;
  st.hold_ms = hold_ms;
  bthread::Executor::global()->submit(stall_task, &st);
  while (!st.done.load(std::memory_order_acquire)) {
    usleep(1000);
  }
  return 0;
}

// ---- syscall attribution (ISSUE 15 satellite; ROADMAP 1(e)) ----

void brpc_syscall_counters(int64_t* read_sys, int64_t* write_sys,
                           int64_t* batch_hits, int64_t* batch_misses) {
  brpc::Socket::SyscallCounters(read_sys, write_sys, batch_hits,
                                batch_misses);
}

// Fills up to n log2 buckets of the bytes-per-write histogram
// (<=64B, <=128B, ... open-ended); returns the bucket count.
int brpc_write_size_hist(int64_t* out, int n) {
  return brpc::Socket::WriteSizeHist(out, n);
}

int brpc_socket_syscalls(uint64_t sid, int64_t* read_sys,
                         int64_t* write_sys) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  if (read_sys) *read_sys = s->read_syscalls();
  if (write_sys) *write_sys = s->write_syscalls();
  s->Dereference();
  return 0;
}

}  // extern "C"
