// _fastrpc — CPython C-extension for the RPC hot boundary.
//
// ctypes marshalling costs ~10-20us per crossing (measured via cProfile:
// send_request alone ~20us tottime) and CFUNCTYPE trampolines are similar
// on the way back — at ~170us/request end-to-end that is the single
// largest removable cost.  This module replaces the hot crossings with
// direct C API calls: request/response frames are packed and written in
// one call, and natively pre-parsed requests/responses are delivered to
// Python as plain argument tuples (strings + bytes), with the IOBuf
// consumed C-side.  The ctypes surface (lib.py) remains for everything
// cold (listen/connect, timers, stats, streams).
//
// Reference analog: the generated pb stub layer sitting directly on the
// C++ core (baidu_rpc_protocol.cpp pack/process), with no FFI toll booth.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>

#include "butil/iobuf.h"
#include "net/rpc.h"
#include "net/socket.h"

namespace {

PyObject* g_request_handler = nullptr;   // called with 10-tuple args
PyObject* g_response_handler = nullptr;  // called with 9-tuple args

PyObject* iobuf_steal_bytes(butil::IOBuf* b) {
  const size_t n = b->size();
  PyObject* out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)n);
  if (out == nullptr) return nullptr;
  b->copy_to(PyBytes_AS_STRING(out), n, 0);
  return out;
}

// ---- native -> Python trampolines (run on executor/dispatcher threads) ----

// If the Python handler raises (or the payload can't be materialized), the
// peer must still get a reply — a silently dropped frame hangs the caller
// until its RPC deadline.  Pack a native EINTERNAL response instead.
constexpr int32_t kEInternal = 2001;  // errors.py EINTERNAL

void send_error_response(brpc::SocketId sid, const brpc::RequestHeader* hdr) {
  static const char kMsg[] = "python handler raised";
  butil::IOBuf frame;
  brpc::PackResponseFrame(&frame, hdr->cid, hdr->attempt, kEInternal, kMsg,
                          sizeof(kMsg) - 1, "", 0, butil::IOBuf());
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s != nullptr) {
    if (s->Write(std::move(frame)) != 0) {
      brpc::MethodRegistry::NoteDroppedResponse();
    }
    s->Dereference();
  }
}

void fast_request_cb(brpc::SocketId sid, const brpc::RequestHeader* hdr,
                     butil::IOBuf* body, void* /*user*/) {
  PyGILState_STATE g = PyGILState_Ensure();
  PyObject* handler = g_request_handler;
  bool handled = false;
  if (handler != nullptr) {
    PyObject* payload = iobuf_steal_bytes(body);
    delete body;
    if (payload != nullptr) {
      PyObject* r = PyObject_CallFunction(
          handler, "KKHs#s#BIs#KN", (unsigned long long)sid,
          (unsigned long long)hdr->cid, (unsigned short)hdr->attempt,
          hdr->service ? hdr->service : "", (Py_ssize_t)hdr->service_len,
          hdr->method ? hdr->method : "", (Py_ssize_t)hdr->method_len,
          hdr->compress, hdr->timeout_ms,
          hdr->content_type ? hdr->content_type : "",
          (Py_ssize_t)hdr->content_type_len,
          (unsigned long long)hdr->attachment_size, payload);
      if (r == nullptr) {
        PyErr_Print();
      } else {
        Py_DECREF(r);
        handled = true;
      }
    } else {
      PyErr_Print();
    }
  } else {
    delete body;
  }
  if (!handled) send_error_response(sid, hdr);
  PyGILState_Release(g);
}

void fast_response_cb(brpc::SocketId sid, const brpc::RequestHeader* hdr,
                      butil::IOBuf* body, void* /*user*/) {
  PyGILState_STATE g = PyGILState_Ensure();
  PyObject* handler = g_response_handler;
  if (handler != nullptr) {
    PyObject* payload = iobuf_steal_bytes(body);
    delete body;
    if (payload != nullptr) {
      PyObject* r = PyObject_CallFunction(
          handler, "KKHis#Bs#KN", (unsigned long long)sid,
          (unsigned long long)hdr->cid, (unsigned short)hdr->attempt,
          (int)hdr->error_code, hdr->error_text ? hdr->error_text : "",
          (Py_ssize_t)hdr->error_text_len, hdr->compress,
          hdr->content_type ? hdr->content_type : "",
          (Py_ssize_t)hdr->content_type_len,
          (unsigned long long)hdr->attachment_size, payload);
      if (r == nullptr) PyErr_Print();
      else Py_DECREF(r);
    } else {
      PyErr_Print();
    }
  } else {
    delete body;
  }
  PyGILState_Release(g);
}

// ---- Python -> native ----

PyObject* py_send_request(PyObject*, PyObject* args) {
  unsigned long long sid, cid;
  unsigned short attempt;
  const char *service, *method, *content_type;
  Py_ssize_t service_len, method_len, ct_len;
  unsigned int timeout_ms;
  unsigned char compress;
  const char* body;
  Py_ssize_t body_len;
  if (!PyArg_ParseTuple(args, "KKHs#s#IBs#y#", &sid, &cid, &attempt, &service,
                        &service_len, &method, &method_len, &timeout_ms,
                        &compress, &content_type, &ct_len, &body, &body_len))
    return nullptr;
  butil::IOBuf b;
  if (body_len > 0) b.append(body, (size_t)body_len);
  butil::IOBuf frame;
  brpc::PackRequestFrame(&frame, cid, attempt, service, (size_t)service_len,
                         method, (size_t)method_len, timeout_ms, compress,
                         content_type, (size_t)ct_len, std::move(b));
  int rc = -1;
  Py_BEGIN_ALLOW_THREADS
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s != nullptr) {
    rc = s->Write(std::move(frame));
    s->Dereference();
  }
  Py_END_ALLOW_THREADS
  return PyLong_FromLong(rc);
}

PyObject* py_send_response(PyObject*, PyObject* args) {
  unsigned long long sid, cid;
  unsigned short attempt;
  int error_code;
  const char *error_text, *content_type;
  Py_ssize_t et_len, ct_len;
  const char* body;
  Py_ssize_t body_len;
  if (!PyArg_ParseTuple(args, "KKHis#s#y#", &sid, &cid, &attempt, &error_code,
                        &error_text, &et_len, &content_type, &ct_len, &body,
                        &body_len))
    return nullptr;
  butil::IOBuf b;
  if (body_len > 0) b.append(body, (size_t)body_len);
  butil::IOBuf frame;
  brpc::PackResponseFrame(&frame, cid, attempt, error_code, error_text,
                          (size_t)et_len, content_type, (size_t)ct_len,
                          std::move(b));
  int rc = -1;
  Py_BEGIN_ALLOW_THREADS
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s != nullptr) {
    rc = s->Write(std::move(frame));
    s->Dereference();
  }
  Py_END_ALLOW_THREADS
  return PyLong_FromLong(rc);
}

PyObject* py_set_request_handler(PyObject*, PyObject* arg) {
  if (arg != Py_None && !PyCallable_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "request handler must be callable");
    return nullptr;
  }
  PyObject* next = (arg == Py_None) ? nullptr : arg;
  Py_XINCREF(next);
  PyObject* old = g_request_handler;
  g_request_handler = next;
  Py_XDECREF(old);
  brpc::SetRequestCallback(fast_request_cb, nullptr);
  Py_RETURN_NONE;
}

PyObject* py_set_response_handler(PyObject*, PyObject* arg) {
  if (arg != Py_None && !PyCallable_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "response handler must be callable");
    return nullptr;
  }
  PyObject* next = (arg == Py_None) ? nullptr : arg;
  Py_XINCREF(next);
  PyObject* old = g_response_handler;
  g_response_handler = next;
  Py_XDECREF(old);
  Py_RETURN_NONE;
}

// ctypes casts this integer to RESPONSE_CB when calling brpc_connect_rpc,
// so client sockets get the C trampoline with zero ctypes on the hot path.
PyObject* py_response_cb_ptr(PyObject*, PyObject*) {
  return PyLong_FromVoidPtr((void*)fast_response_cb);
}

PyMethodDef kMethods[] = {
    {"send_request", py_send_request, METH_VARARGS,
     "send_request(sid, cid, attempt, service, method, timeout_ms, "
     "compress, content_type, body) -> rc"},
    {"send_response", py_send_response, METH_VARARGS,
     "send_response(sid, cid, attempt, error_code, error_text, "
     "content_type, body) -> rc"},
    {"set_request_handler", py_set_request_handler, METH_O,
     "Install the process-wide pre-parsed request handler."},
    {"set_response_handler", py_set_response_handler, METH_O,
     "Install the process-wide pre-parsed response handler."},
    {"response_cb_ptr", py_response_cb_ptr, METH_NOARGS,
     "Address of the C response trampoline (for brpc_connect_rpc)."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "_fastrpc",
                       "Zero-ctypes RPC hot boundary", -1, kMethods};

}  // namespace

PyMODINIT_FUNC PyInit__fastrpc() { return PyModule_Create(&kModule); }
