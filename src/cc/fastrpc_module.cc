// _fastrpc — CPython C-extension for the RPC hot boundary.
//
// ctypes marshalling costs ~10-20us per crossing (measured via cProfile:
// send_request alone ~20us tottime) and CFUNCTYPE trampolines are similar
// on the way back — at ~170us/request end-to-end that is the single
// largest removable cost.  This module replaces the hot crossings with
// direct C API calls: request/response frames are packed and written in
// one call, and natively pre-parsed requests/responses are delivered to
// Python as plain argument tuples (strings + bytes), with the IOBuf
// consumed C-side.  The ctypes surface (lib.py) remains for everything
// cold (listen/connect, timers, stats, streams).
//
// Reference analog: the generated pb stub layer sitting directly on the
// C++ core (baidu_rpc_protocol.cpp pack/process), with no FFI toll booth.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <cstring>

#include "butil/flight.h"
#include "butil/iobuf.h"
#include "net/rpc.h"
#include "net/socket.h"
#include "spanq.h"

namespace {

PyObject* g_request_handler = nullptr;   // called with 10-tuple args
PyObject* g_response_handler = nullptr;  // called with 9-tuple args

// ---- FastBody: IOBuf-backed buffer object (zero-copy boundary) ----
//
// VERDICT r2 task 9: fast-path bodies used to be memcpy'd into Python
// bytes.  FastBody owns the native IOBuf and exposes its bytes through
// the buffer protocol: single-block bodies (every body <= one 8KB block
// — the common case) are exposed IN PLACE; multi-block bodies coalesce
// once on first access.  Python sees a standard memoryview over it, so
// slicing (payload/attachment split) stays zero-copy and the IOBuf block
// refs live exactly as long as Python references do — the SURVEY §2.1
// splice semantics carried across the language boundary.

struct FastBodyObject {
  PyObject_HEAD
  butil::IOBuf* buf;
  char* flat;     // coalesced copy for multi-block bodies (lazy)
  size_t size;
};

int fastbody_getbuffer(PyObject* self, Py_buffer* view, int flags) {
  auto* fb = (FastBodyObject*)self;
  void* ptr = nullptr;
  if (fb->flat != nullptr) {
    ptr = fb->flat;
  } else if (fb->size == 0) {
    ptr = (void*)"";  // zero-length: any non-null pointer is fine
  } else if (fb->buf->backing_block_num() == 1) {
    const butil::BlockRef& r = fb->buf->backing_block(0);
    ptr = butil::iobuf::block_data(r.block) + r.offset;
  } else {
    fb->flat = (char*)PyMem_Malloc(fb->size);
    if (fb->flat == nullptr) {
      PyErr_NoMemory();
      return -1;
    }
    fb->buf->copy_to(fb->flat, fb->size, 0);
    // the flat copy fully replaces the blocks: release them now rather
    // than doubling memory for the view's lifetime (dealloc handles null)
    delete fb->buf;
    fb->buf = nullptr;
    ptr = fb->flat;
  }
  return PyBuffer_FillInfo(view, self, ptr, (Py_ssize_t)fb->size,
                           /*readonly=*/1, flags);
}

void fastbody_dealloc(PyObject* self) {
  auto* fb = (FastBodyObject*)self;
  delete fb->buf;
  if (fb->flat != nullptr) PyMem_Free(fb->flat);
  Py_TYPE(self)->tp_free(self);
}

Py_ssize_t fastbody_length(PyObject* self) {
  return (Py_ssize_t)((FastBodyObject*)self)->size;
}

PyBufferProcs fastbody_as_buffer = {fastbody_getbuffer, nullptr};
PySequenceMethods fastbody_as_sequence = {fastbody_length};

PyTypeObject FastBodyType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "_fastrpc.FastBody",            /* tp_name */
    sizeof(FastBodyObject),         /* tp_basicsize */
};

// Wrap `b` (ownership taken) as a read-only memoryview whose lifetime
// keeps the IOBuf blocks alive.  Returns nullptr with an exception set.
PyObject* iobuf_to_memoryview(butil::IOBuf* b) {
  auto* fb = PyObject_New(FastBodyObject, &FastBodyType);
  if (fb == nullptr) {
    delete b;
    return nullptr;
  }
  fb->buf = b;
  fb->flat = nullptr;
  fb->size = b->size();
  PyObject* mv = PyMemoryView_FromObject((PyObject*)fb);
  Py_DECREF(fb);  // the memoryview holds the buffer reference
  return mv;
}

// ---- native -> Python trampolines (run on executor/dispatcher threads) ----

// If the Python handler raises (or the payload can't be materialized), the
// peer must still get a reply — a silently dropped frame hangs the caller
// until its RPC deadline.  Pack a native EINTERNAL response instead.
constexpr int32_t kEInternal = 2001;  // errors.py EINTERNAL

void send_error_response(brpc::SocketId sid, const brpc::RequestHeader* hdr) {
  static const char kMsg[] = "python handler raised";
  butil::IOBuf frame;
  brpc::PackResponseFrame(&frame, hdr->cid, hdr->attempt, kEInternal, kMsg,
                          sizeof(kMsg) - 1, "", 0, butil::IOBuf());
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s != nullptr) {
    if (s->Write(std::move(frame)) != 0) {
      brpc::MethodRegistry::NoteDroppedResponse();
    }
    s->Dereference();
  }
}

void fast_request_cb(brpc::SocketId sid, const brpc::RequestHeader* hdr,
                     butil::IOBuf* body, void* /*user*/) {
  PyGILState_STATE g = PyGILState_Ensure();
  PyObject* handler = g_request_handler;
  bool handled = false;
  if (handler != nullptr) {
    PyObject* payload = iobuf_to_memoryview(body);  // takes ownership
    if (payload != nullptr) {
      PyObject* r = PyObject_CallFunction(
          handler, "KKHs#s#BIs#KN", (unsigned long long)sid,
          (unsigned long long)hdr->cid, (unsigned short)hdr->attempt,
          hdr->service ? hdr->service : "", (Py_ssize_t)hdr->service_len,
          hdr->method ? hdr->method : "", (Py_ssize_t)hdr->method_len,
          hdr->compress, hdr->timeout_ms,
          hdr->content_type ? hdr->content_type : "",
          (Py_ssize_t)hdr->content_type_len,
          (unsigned long long)hdr->attachment_size, payload);
      if (r == nullptr) {
        PyErr_Print();
      } else {
        Py_DECREF(r);
        handled = true;
      }
    } else {
      PyErr_Print();
    }
  } else {
    delete body;
  }
  if (!handled) send_error_response(sid, hdr);
  PyGILState_Release(g);
}

void fast_response_cb(brpc::SocketId sid, const brpc::RequestHeader* hdr,
                      butil::IOBuf* body, void* /*user*/) {
  PyGILState_STATE g = PyGILState_Ensure();
  PyObject* handler = g_response_handler;
  if (handler != nullptr) {
    PyObject* payload = iobuf_to_memoryview(body);  // takes ownership
    if (payload != nullptr) {
      PyObject* r = PyObject_CallFunction(
          handler, "KKHis#Bs#KN", (unsigned long long)sid,
          (unsigned long long)hdr->cid, (unsigned short)hdr->attempt,
          (int)hdr->error_code, hdr->error_text ? hdr->error_text : "",
          (Py_ssize_t)hdr->error_text_len, hdr->compress,
          hdr->content_type ? hdr->content_type : "",
          (Py_ssize_t)hdr->content_type_len,
          (unsigned long long)hdr->attachment_size, payload);
      if (r == nullptr) PyErr_Print();
      else Py_DECREF(r);
    } else {
      PyErr_Print();
    }
  } else {
    delete body;
  }
  PyGILState_Release(g);
}

// ---- Python -> native ----

// Zero-copy send threshold: below it a memcpy into the IOBuf beats the
// Py_buffer bookkeeping + GIL reacquisition in the deleter.
constexpr Py_ssize_t kZeroCopySendBytes = 4096;

struct PyBufHolder { Py_buffer view; };

void release_pybuf(void* /*data*/, void* arg) {
  // Runs when the last block ref drops (usually the writer thread after
  // the bytes hit the fd) — must retake the GIL to release the exporter.
  PyGILState_STATE g = PyGILState_Ensure();
  auto* h = (PyBufHolder*)arg;
  PyBuffer_Release(&h->view);
  delete h;
  PyGILState_Release(g);
}

// Move `view`'s bytes into b: small payloads copy; large ones wrap the
// Python buffer as a user block that pins the exporter until written.
void append_pybuffer(butil::IOBuf* b, Py_buffer* view) {
  if (view->len <= 0) {
    PyBuffer_Release(view);
    return;
  }
  if (view->len < kZeroCopySendBytes || !view->readonly) {
    // writable exporters (bytearray, numpy) must be copied: the caller is
    // free to mutate after we return, and a pinned mutable buffer would
    // silently corrupt the queued frame if the write queue is backlogged
    b->append(view->buf, (size_t)view->len);
    PyBuffer_Release(view);
    return;
  }
  auto* h = new PyBufHolder{*view};
  b->append_user_data(h->view.buf, (size_t)h->view.len, release_pybuf, h);
}

// Write one framed buffer to a socket, deciding whether to yield the
// GIL: Socket::Write is wait-free-producer + nonblocking inline drain,
// so a SMALL frame onto a SMALL backlog finishes in microseconds and
// dropping the GIL around it costs a full handoff cycle per call under
// load (measured ~17us/req at 64 concurrent on 1 core).  Yield when this
// frame is big OR the socket's backlog is — winning _write_busy there
// can inline-drain the whole multi-thread backlog, and that must not run
// with the GIL held.
static int write_frame_gil_aware(unsigned long long sid,
                                 butil::IOBuf&& frame) {
  brpc::Socket* s = brpc::Socket::Address(sid);
  if (s == nullptr) return -1;
  const bool yield_gil = frame.size() > 64 * 1024 ||
                         s->pending_write_bytes() > 256 * 1024;
  int rc;
  if (yield_gil) {
    Py_BEGIN_ALLOW_THREADS
    rc = s->Write(std::move(frame));
    s->Dereference();
    Py_END_ALLOW_THREADS
  } else {
    rc = s->Write(std::move(frame));
    s->Dereference();
  }
  return rc;
}

PyObject* py_send_request(PyObject*, PyObject* args) {
  unsigned long long sid, cid;
  unsigned short attempt;
  const char *service, *method, *content_type;
  Py_ssize_t service_len, method_len, ct_len;
  unsigned int timeout_ms;
  unsigned char compress;
  Py_buffer body;
  if (!PyArg_ParseTuple(args, "KKHs#s#IBs#y*", &sid, &cid, &attempt, &service,
                        &service_len, &method, &method_len, &timeout_ms,
                        &compress, &content_type, &ct_len, &body))
    return nullptr;
  butil::IOBuf b;
  append_pybuffer(&b, &body);
  butil::IOBuf frame;
  brpc::PackRequestFrame(&frame, cid, attempt, service, (size_t)service_len,
                         method, (size_t)method_len, timeout_ms, compress,
                         content_type, (size_t)ct_len, std::move(b));
  return PyLong_FromLong(write_frame_gil_aware(sid, std::move(frame)));
}

PyObject* py_send_response(PyObject*, PyObject* args) {
  unsigned long long sid, cid;
  unsigned short attempt;
  int error_code;
  const char *error_text, *content_type;
  Py_ssize_t et_len, ct_len;
  Py_buffer body;
  if (!PyArg_ParseTuple(args, "KKHis#s#y*", &sid, &cid, &attempt, &error_code,
                        &error_text, &et_len, &content_type, &ct_len, &body))
    return nullptr;
  butil::IOBuf b;
  append_pybuffer(&b, &body);
  butil::IOBuf frame;
  brpc::PackResponseFrame(&frame, cid, attempt, error_code, error_text,
                          (size_t)et_len, content_type, (size_t)ct_len,
                          std::move(b));
  return PyLong_FromLong(write_frame_gil_aware(sid, std::move(frame)));
}

PyObject* py_set_request_handler(PyObject*, PyObject* arg) {
  if (arg != Py_None && !PyCallable_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "request handler must be callable");
    return nullptr;
  }
  PyObject* next = (arg == Py_None) ? nullptr : arg;
  Py_XINCREF(next);
  PyObject* old = g_request_handler;
  g_request_handler = next;
  Py_XDECREF(old);
  brpc::SetRequestCallback(fast_request_cb, nullptr);
  Py_RETURN_NONE;
}

PyObject* py_set_response_handler(PyObject*, PyObject* arg) {
  if (arg != Py_None && !PyCallable_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "response handler must be callable");
    return nullptr;
  }
  PyObject* next = (arg == Py_None) ? nullptr : arg;
  Py_XINCREF(next);
  PyObject* old = g_response_handler;
  g_response_handler = next;
  Py_XDECREF(old);
  Py_RETURN_NONE;
}

// ctypes casts this integer to RESPONSE_CB when calling brpc_connect_rpc,
// so client sockets get the C trampoline with zero ctypes on the hot path.
PyObject* py_response_cb_ptr(PyObject*, PyObject*) {
  return PyLong_FromVoidPtr((void*)fast_response_cb);
}

// Single-copy IOBuf -> bytes (lib.py IOBuf.to_bytes rode
// create_string_buffer + .raw slice: two copies plus a zero-init per
// call — visible on the h2 frame path at 6 frames/unary-call).
PyObject* py_iobuf_bytes(PyObject*, PyObject* args) {
  unsigned long long handle;
  Py_ssize_t pos = 0;
  Py_ssize_t n = -1;
  if (!PyArg_ParseTuple(args, "K|nn", &handle, &pos, &n)) return nullptr;
  auto* b = (butil::IOBuf*)(uintptr_t)handle;
  const Py_ssize_t size = (Py_ssize_t)b->size();
  if (pos < 0 || pos > size) pos = size;
  Py_ssize_t avail = size - pos;
  if (n < 0 || n > avail) n = avail;
  PyObject* out = PyBytes_FromStringAndSize(nullptr, n);
  if (out == nullptr) return nullptr;
  if (n > 0) {
    const size_t got = b->copy_to(PyBytes_AS_STRING(out), (size_t)n,
                                  (size_t)pos);
    if ((Py_ssize_t)got != n && _PyBytes_Resize(&out, (Py_ssize_t)got) < 0)
      return nullptr;
  }
  return out;
}

// ---- native span queue (ISSUE 9: off-thread rpcz recording) ----
//
// rpcz.submit used to pay two Python lock acquisitions (speed-limit
// grab + collector pending append) plus a wrapper allocation per span,
// ON the token path.  Now the hot side is ONE lock-free Treiber push of
// the span object (incref under the GIL we already hold, CAS, done);
// the collector thread drains the stack in FIFO order and does the
// rate-limiting, store append and SpanDB IO there.  Same shape as
// bthread's ExecutionQueue producer half — a drain-side-serialized MPSC
// stack — holding PyObject* instead of nodes on an Executor.

// The stack itself lives in spanq.h (ISSUE 14) so `make tsan`'s ring
// stress exercises the exact producer/drain algorithm without Python.
brpc_spanq::Stack g_spanq;

PyObject* py_spanq_push(PyObject*, PyObject* arg) {
  Py_INCREF(arg);
  g_spanq.push(arg);
  Py_RETURN_NONE;
}

PyObject* py_spanq_drain(PyObject*, PyObject*) {
  int64_t count = 0;
  brpc_spanq::Node* chain = g_spanq.drain_fifo(&count);
  if (count > 0) {
    // drain cadence on the collector thread (one event per BATCH; the
    // per-span push stays event-free, same discipline as TokenRing)
    butil::flight::record(butil::flight::EV_SPANQ_DRAIN, 0, count);
  }
  PyObject* out = PyList_New((Py_ssize_t)count);
  if (out == nullptr) {
    // push the chain back so the spans are not lost (order within
    // this failed batch is preserved relative to itself)
    while (chain != nullptr) {
      brpc_spanq::Node* next = chain->next;
      g_spanq.push_node(chain);
      chain = next;
    }
    return nullptr;
  }
  Py_ssize_t i = 0;
  while (chain != nullptr) {
    PyList_SET_ITEM(out, i++, (PyObject*)chain->obj);  // steals the ref
    brpc_spanq::Node* next = chain->next;
    delete chain;
    chain = next;
  }
  return out;
}

PyObject* py_spanq_pending(PyObject*, PyObject*) {
  return PyLong_FromLongLong(g_spanq.count());
}

// ---- native batch assembly + token-ring fast entries (ISSUE 9) ----
//
// The ctypes bindings in _core/lib.py pay ~25us of marshalling per
// call (a .ctypes view object per numpy row) and ALWAYS drop the GIL —
// right for a bulk or blocking call, fatally wrong for the per-token
// and per-formation hot path.  These entries parse via the buffer
// protocol (no per-row Python objects) and choose per call whether the
// GIL is worth releasing: batch_pad/page_table_fill release it for the
// memset+memcpy pass only; tokring_push HOLDS it — a sub-microsecond
// mutex push is cheaper than a GIL handoff convoy.

extern "C" int brpc_tokring_push(void* h, int32_t tok);  // serving_hotpath.cc

// batch_pad(out2d, rows) -> None.  Zero-fill the C-contiguous 2-D
// buffer `out2d`, then copy rows[i]'s bytes into row i (truncated to
// the row stride).  Rows must be C-contiguous 1-D buffers of out's
// dtype (the batcher's enqueue coercion guarantees this).
PyObject* py_batch_pad(PyObject*, PyObject* args) {
  PyObject* out_obj;
  PyObject* rows_obj;
  if (!PyArg_ParseTuple(args, "OO", &out_obj, &rows_obj)) return nullptr;
  Py_buffer out;
  if (PyObject_GetBuffer(out_obj, &out,
                         PyBUF_WRITABLE | PyBUF_STRIDES) != 0) {
    return nullptr;
  }
  if (out.ndim != 2 || !PyBuffer_IsContiguous(&out, 'C')) {
    PyBuffer_Release(&out);
    PyErr_SetString(PyExc_ValueError, "out must be C-contiguous 2-D");
    return nullptr;
  }
  PyObject* fast = PySequence_Fast(rows_obj, "rows must be a sequence");
  if (fast == nullptr) {
    PyBuffer_Release(&out);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  if (n > out.shape[0]) {
    Py_DECREF(fast);
    PyBuffer_Release(&out);
    PyErr_SetString(PyExc_ValueError, "more rows than out has");
    return nullptr;
  }
  // collect every row buffer under the GIL, then copy without it
  Py_buffer* rows = (Py_buffer*)PyMem_Malloc(sizeof(Py_buffer) * (n ? n : 1));
  if (rows == nullptr) {
    Py_DECREF(fast);
    PyBuffer_Release(&out);
    return PyErr_NoMemory();
  }
  Py_ssize_t got = 0;
  for (; got < n; ++got) {
    if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(fast, got),
                           &rows[got], PyBUF_SIMPLE) != 0) {
      break;
    }
  }
  if (got < n) {
    for (Py_ssize_t i = 0; i < got; ++i) PyBuffer_Release(&rows[i]);
    PyMem_Free(rows);
    Py_DECREF(fast);
    PyBuffer_Release(&out);
    return nullptr;
  }
  const Py_ssize_t stride = out.strides[0];
  Py_BEGIN_ALLOW_THREADS
  memset(out.buf, 0, (size_t)out.len);
  for (Py_ssize_t i = 0; i < n; ++i) {
    Py_ssize_t m = rows[i].len < stride ? rows[i].len : stride;
    if (m > 0) memcpy((char*)out.buf + i * stride, rows[i].buf, (size_t)m);
  }
  Py_END_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; ++i) PyBuffer_Release(&rows[i]);
  PyMem_Free(rows);
  Py_DECREF(fast);
  PyBuffer_Release(&out);
  Py_RETURN_NONE;
}

// page_table_fill(table2d_int32, lists, slot_idx) -> None.  Fill the
// C-contiguous int32 table with -1, then copy int32 buffer lists[k]
// into row slot_idx[k] (truncated to the table width).
PyObject* py_page_table_fill(PyObject*, PyObject* args) {
  PyObject* table_obj;
  PyObject* lists_obj;
  PyObject* idx_obj;
  if (!PyArg_ParseTuple(args, "OOO", &table_obj, &lists_obj, &idx_obj)) {
    return nullptr;
  }
  Py_buffer table;
  if (PyObject_GetBuffer(table_obj, &table,
                         PyBUF_WRITABLE | PyBUF_STRIDES) != 0) {
    return nullptr;
  }
  if (table.ndim != 2 || !PyBuffer_IsContiguous(&table, 'C') ||
      table.itemsize != 4) {
    PyBuffer_Release(&table);
    PyErr_SetString(PyExc_ValueError,
                    "table must be C-contiguous 2-D int32");
    return nullptr;
  }
  PyObject* lists = PySequence_Fast(lists_obj, "lists must be a sequence");
  PyObject* idx = lists ? PySequence_Fast(idx_obj,
                                          "slot_idx must be a sequence")
                        : nullptr;
  if (idx == nullptr) {
    Py_XDECREF(lists);
    PyBuffer_Release(&table);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(lists);
  const Py_ssize_t rows = table.shape[0];
  const Py_ssize_t width_bytes = table.strides[0];
  if (PySequence_Fast_GET_SIZE(idx) != n) {
    Py_DECREF(lists);
    Py_DECREF(idx);
    PyBuffer_Release(&table);
    PyErr_SetString(PyExc_ValueError, "lists/slot_idx length mismatch");
    return nullptr;
  }
  // collect every row index and id buffer under the GIL, then do the
  // -1 fill + row copies without it (same discipline as batch_pad —
  // the module header and the engine call site both promise it)
  Py_buffer* ids =
      (Py_buffer*)PyMem_Malloc(sizeof(Py_buffer) * (n ? n : 1));
  long* rowidx = (long*)PyMem_Malloc(sizeof(long) * (n ? n : 1));
  if (ids == nullptr || rowidx == nullptr) {
    PyMem_Free(ids);
    PyMem_Free(rowidx);
    Py_DECREF(lists);
    Py_DECREF(idx);
    PyBuffer_Release(&table);
    return PyErr_NoMemory();
  }
  Py_ssize_t got = 0;
  for (; got < n; ++got) {
    long row = PyLong_AsLong(PySequence_Fast_GET_ITEM(idx, got));
    if ((row == -1 && PyErr_Occurred()) || row < 0 || row >= rows) {
      if (!PyErr_Occurred()) {
        PyErr_SetString(PyExc_ValueError, "slot index out of range");
      }
      break;
    }
    rowidx[got] = row;
    if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(lists, got),
                           &ids[got], PyBUF_SIMPLE) != 0) {
      break;
    }
  }
  if (got < n) {
    for (Py_ssize_t i = 0; i < got; ++i) PyBuffer_Release(&ids[i]);
    PyMem_Free(ids);
    PyMem_Free(rowidx);
    Py_DECREF(lists);
    Py_DECREF(idx);
    PyBuffer_Release(&table);
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS
  int32_t* base = (int32_t*)table.buf;
  const Py_ssize_t total = table.len / 4;
  for (Py_ssize_t i = 0; i < total; ++i) base[i] = -1;
  for (Py_ssize_t k = 0; k < n; ++k) {
    Py_ssize_t m = ids[k].len < width_bytes ? ids[k].len : width_bytes;
    if (m > 0) {
      memcpy((char*)table.buf + rowidx[k] * width_bytes, ids[k].buf,
             (size_t)m);
    }
  }
  Py_END_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; ++i) PyBuffer_Release(&ids[i]);
  PyMem_Free(ids);
  PyMem_Free(rowidx);
  Py_DECREF(lists);
  Py_DECREF(idx);
  PyBuffer_Release(&table);
  Py_RETURN_NONE;
}

// tokring_push(handle, tok) -> 1 pushed / 0 full.  Deliberately HOLDS
// the GIL: the ring mutex is held for nanoseconds and never blocks, so
// a GIL release/reacquire per token would cost more than the push (and
// under N producer threads becomes a handoff convoy).
PyObject* py_tokring_push(PyObject*, PyObject* args) {
  unsigned long long handle;
  int tok;
  if (!PyArg_ParseTuple(args, "Ki", &handle, &tok)) return nullptr;
  return PyLong_FromLong(
      brpc_tokring_push((void*)(uintptr_t)handle, (int32_t)tok));
}

PyMethodDef kMethods[] = {
    {"spanq_push", py_spanq_push, METH_O,
     "Push one span object onto the native MPSC queue (lock-free)."},
    {"spanq_drain", py_spanq_drain, METH_NOARGS,
     "Drain every queued span, FIFO order -> list."},
    {"spanq_pending", py_spanq_pending, METH_NOARGS,
     "Spans pushed but not yet drained."},
    {"batch_pad", py_batch_pad, METH_VARARGS,
     "batch_pad(out2d, rows): zero-fill + row gather, GIL released."},
    {"page_table_fill", py_page_table_fill, METH_VARARGS,
     "page_table_fill(table2d, lists, slot_idx): -1 fill + row copy."},
    {"tokring_push", py_tokring_push, METH_VARARGS,
     "tokring_push(handle, tok) -> 1 pushed / 0 full (GIL held)."},
    {"send_request", py_send_request, METH_VARARGS,
     "send_request(sid, cid, attempt, service, method, timeout_ms, "
     "compress, content_type, body) -> rc"},
    {"send_response", py_send_response, METH_VARARGS,
     "send_response(sid, cid, attempt, error_code, error_text, "
     "content_type, body) -> rc"},
    {"set_request_handler", py_set_request_handler, METH_O,
     "Install the process-wide pre-parsed request handler."},
    {"set_response_handler", py_set_response_handler, METH_O,
     "Install the process-wide pre-parsed response handler."},
    {"response_cb_ptr", py_response_cb_ptr, METH_NOARGS,
     "Address of the C response trampoline (for brpc_connect_rpc)."},
    {"iobuf_bytes", py_iobuf_bytes, METH_VARARGS,
     "iobuf_bytes(handle, pos=0, n=-1) -> bytes (single copy)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "_fastrpc",
                       "Zero-ctypes RPC hot boundary", -1, kMethods};

}  // namespace

PyMODINIT_FUNC PyInit__fastrpc() {
  FastBodyType.tp_dealloc = fastbody_dealloc;
  FastBodyType.tp_flags = Py_TPFLAGS_DEFAULT;
  FastBodyType.tp_as_buffer = &fastbody_as_buffer;
  FastBodyType.tp_as_sequence = &fastbody_as_sequence;
  FastBodyType.tp_doc = "IOBuf-backed read-only buffer (zero-copy body)";
  FastBodyType.tp_new = nullptr;  // only created from C
  if (PyType_Ready(&FastBodyType) < 0) return nullptr;
  return PyModule_Create(&kModule);
}
