// In-process loopback echo benchmark: C++ client pump against the native
// method-registry dispatch path.  The reference measures its hot path the
// same way — C++ client, C++ server, pipelined connections
// (docs/cn/benchmark.md methodology; example/multi_threaded_echo_c++).
// Round 1's "native echo" number timed a Python ctypes write loop, i.e.
// the client, not the framework.  This pump keeps `inflight` frames per
// connection in the air, embeds the send timestamp as the correlation id,
// and computes p50/p99 from response-side timestamps.
#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <vector>

#include "butil/common.h"
#include "butil/iobuf.h"
#include "net/rpc.h"
#include "net/socket.h"

namespace brpc {
namespace {

struct BenchState {
  std::atomic<uint64_t> sent{0};
  std::atomic<uint64_t> done{0};
  std::atomic<uint64_t> errs{0};  // error responses (e.g. ELIMIT sheds)
  std::atomic<uint64_t> lat_idx{0};
  uint64_t total = 0;
  int payload_len = 0;
  std::string service = "BenchEcho";
  std::string method = "Echo";
  std::vector<uint32_t> lat_us;  // preallocated, atomically indexed
  std::mutex mu;
  std::condition_variable cv;
  bool finished = false;
};

int32_t bench_echo_handler(SocketId, butil::IOBuf* body,
                           butil::IOBuf* resp_body, void*) {
  resp_body->append(std::move(*body));
  return 0;
}

// Zero-ref form: request viewed in the read block, response memcpy'd into
// the dispatch loop's flat stage (net/rpc.h NativeMethodFlatFn).
int32_t bench_echo_handler_flat(SocketId, const char* req, size_t req_len,
                                char* resp, size_t resp_cap, void*) {
  if (req_len > resp_cap) return -1;  // oversized: IOBuf fallback
  memcpy(resp, req, req_len);
  return (int32_t)req_len;
}

void bench_send_one(SocketId sid, BenchState* st) {
  static const char kPayload[4096] = {0};
  const uint64_t cid = (uint64_t)butil::cpuwide_time_us();
  // Inside this socket's dispatch drain (pipelined next-send from the
  // response callback): stage the whole frame into the write batch.
  butil::IOBuf* batch = Socket::CurrentBatchFor(sid, st->payload_len + 96);
  if (batch != nullptr) {
    PackRequestFrameFlat(batch, cid, 0, st->service.data(),
                         st->service.size(), st->method.data(),
                         st->method.size(), 0, 0, nullptr, 0, kPayload,
                         st->payload_len);
    return;
  }
  butil::IOBuf frame;
  PackRequestFrameFlat(&frame, cid, 0, st->service.data(),
                       st->service.size(), st->method.data(),
                       st->method.size(), 0, 0, nullptr, 0, kPayload,
                       st->payload_len);
  Socket* s = Socket::Address(sid);
  if (s != nullptr) {
    s->Write(std::move(frame));
    s->Dereference();
  }
}

void bench_note_response(SocketId sid, const RequestHeader* hdr, void* user) {
  auto* st = (BenchState*)user;
  if (hdr->error_code != 0) {
    // shed/error replies keep the pipeline moving but are counted (and
    // timed) separately: mixing fail-fast latencies into the success
    // distribution would flatter p99 dishonestly
    st->errs.fetch_add(1, std::memory_order_relaxed);
  } else {
    const uint64_t now = (uint64_t)butil::cpuwide_time_us();
    const uint64_t idx = st->lat_idx.fetch_add(1, std::memory_order_relaxed);
    if (idx < st->lat_us.size()) {
      st->lat_us[idx] =
          (uint32_t)std::min<uint64_t>(now - hdr->cid, 0xffffffff);
    }
  }
  // keep the pipe full: claim a send ticket; tickets >= total mean the
  // pipeline is winding down
  if (st->sent.fetch_add(1, std::memory_order_relaxed) < st->total) {
    bench_send_one(sid, st);
  }
  const uint64_t d = st->done.fetch_add(1, std::memory_order_relaxed) + 1;
  if (d >= st->total) {
    std::lock_guard<std::mutex> lk(st->mu);
    st->finished = true;
    st->cv.notify_all();
  }
}

void bench_on_response(SocketId sid, const RequestHeader* hdr,
                       butil::IOBuf* body, void* user) {
  // body is BORROWED (response_inline mode) — do not free
  (void)body;
  bench_note_response(sid, hdr, user);
}

void bench_on_response_flat(SocketId sid, const RequestHeader* hdr,
                            const char* body, size_t body_len, void* user) {
  (void)body;
  (void)body_len;
  bench_note_response(sid, hdr, user);
}

void bench_noop_failed(SocketId, int, void*) {}

}  // namespace
}  // namespace brpc

extern "C" {

namespace {
using namespace brpc;

// Client pump core shared by the self-contained echo bench and the
// external-server pump: `conns` pipelined connections to 127.0.0.1:port,
// `inflight` frames outstanding each, p50/p99 from send-timestamp cids.
int run_pump(int port, const char* service, const char* method, int conns,
             int inflight, uint64_t total, int payload_len, double* qps_out,
             double* p50_us, double* p99_us, double* err_frac = nullptr) {
  // Heap-allocated: on the timeout path, in-flight responses can still
  // hit bench_on_response on dispatcher threads after we return, so the
  // state must outlive this frame — it is intentionally leaked then.
  auto* stp = new BenchState;
  BenchState& st = *stp;
  st.total = total;
  st.payload_len = payload_len;
  st.service = service;
  st.method = method;
  st.lat_us.assign(std::min<uint64_t>(total, 2'000'000), 0);

  std::vector<SocketId> clients;
  for (int i = 0; i < conns; ++i) {
    SocketOptions copts;
    copts.on_response = bench_on_response;
    copts.on_response_flat = bench_on_response_flat;
    copts.response_user = &st;
    copts.response_inline = true;
    copts.on_failed = bench_noop_failed;
    SocketId cid = INVALID_SOCKET_ID;
    if (Connect("127.0.0.1", port, copts, &cid) != 0) {
      for (SocketId c : clients) Socket::SetFailed(c, 0);
      delete stp;
      return -3;
    }
    clients.push_back(cid);
  }

  const int64_t t0 = butil::monotonic_time_us();
  // seed the pipeline: `inflight` outstanding frames per connection, each
  // claiming a ticket exactly like the response path (responses may
  // already be arriving while we seed)
  const uint64_t seed_target =
      std::min<uint64_t>((uint64_t)conns * (uint64_t)inflight, total);
  for (uint64_t i = 0; i < seed_target; ++i) {
    if (st.sent.fetch_add(1, std::memory_order_relaxed) < total) {
      bench_send_one(clients[i % clients.size()], &st);
    }
  }

  bool completed_in_time;
  {
    std::unique_lock<std::mutex> lk(st.mu);
    completed_in_time = st.cv.wait_for(lk, std::chrono::seconds(120),
                                       [&] { return st.finished; });
  }
  const int64_t t1 = butil::monotonic_time_us();

  for (SocketId cid : clients) Socket::SetFailed(cid, 0);

  const uint64_t completed = st.done.load();
  const uint64_t errs = st.errs.load();
  const double wall_s = (t1 - t0) / 1e6;
  // qps counts SUCCESSFUL responses only; sheds are reported as err_frac
  if (qps_out)
    *qps_out = (completed > errs ? completed - errs : 0) /
               (wall_s > 0 ? wall_s : 1e-9);
  if (err_frac) *err_frac = completed > 0 ? double(errs) / completed : 0.0;
  const uint64_t n = std::min<uint64_t>(st.lat_idx.load(), st.lat_us.size());
  if (n > 0) {
    std::vector<uint32_t> lats(st.lat_us.begin(), st.lat_us.begin() + n);
    std::sort(lats.begin(), lats.end());
    if (p50_us) *p50_us = lats[n / 2];
    if (p99_us) *p99_us = lats[(size_t)(n * 0.99)];
  } else {
    if (p50_us) *p50_us = 0;
    if (p99_us) *p99_us = 0;
  }
  if (completed_in_time) {
    delete stp;
    return completed >= total ? 0 : -4;
  }
  // Timed out: dispatcher threads may still reference *stp — leak it.
  return -4;
}

}  // namespace

// Returns 0 on success.  inline_run selects dispatcher-inline execution of
// the echo handler (the reference's "last message inline" discipline) vs
// one executor task per message.
int brpc_bench_echo(int conns, int inflight, uint64_t total, int payload_len,
                    int inline_run, double* qps_out, double* p50_us,
                    double* p99_us) {
  using namespace brpc;
  if (conns <= 0 || inflight <= 0 || total == 0 || payload_len < 0 ||
      payload_len > 4096) {
    return -1;
  }
  MethodRegistry::global()->RegisterFlat("BenchEcho", "Echo",
                                         bench_echo_handler,
                                         bench_echo_handler_flat, nullptr,
                                         inline_run != 0);
  SocketOptions server_opts;
  server_opts.enable_rpc_dispatch = true;
  SocketId listener = INVALID_SOCKET_ID;
  int port = 0;
  if (Listen("127.0.0.1", 0, server_opts, &listener, &port) != 0) {
    return -2;
  }
  const int rc = run_pump(port, "BenchEcho", "Echo", conns, inflight, total,
                          payload_len, qps_out, p50_us, p99_us);
  Socket::SetFailed(listener, 0);
  MethodRegistry::global()->Unregister("BenchEcho", "Echo");
  return rc;
}

// Pump an EXISTING server (e.g. a Python-handler service on `port`) with
// the same native client: measures the SERVER's dispatch + handler path
// with zero client-side Python cost — the reference's C++-client
// methodology (docs/cn/benchmark.md) pointed at user handlers.
int brpc_bench_pump(int port, const char* service, const char* method,
                    int conns, int inflight, uint64_t total, int payload_len,
                    double* qps_out, double* p50_us, double* p99_us,
                    double* err_frac) {
  if (port <= 0 || service == nullptr || method == nullptr || conns <= 0 ||
      inflight <= 0 || total == 0 || payload_len < 0 || payload_len > 4096) {
    return -1;
  }
  return run_pump(port, service, method, conns, inflight, total, payload_len,
                  qps_out, p50_us, p99_us, err_frac);
}

}  // extern "C"
