// In-process loopback echo benchmark: C++ client pump against the native
// method-registry dispatch path.  The reference measures its hot path the
// same way — C++ client, C++ server, pipelined connections
// (docs/cn/benchmark.md methodology; example/multi_threaded_echo_c++).
// Round 1's "native echo" number timed a Python ctypes write loop, i.e.
// the client, not the framework.  This pump keeps `inflight` frames per
// connection in the air, embeds the send timestamp as the correlation id,
// and computes p50/p99 from response-side timestamps.
#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <vector>

#include "butil/common.h"
#include "butil/iobuf.h"
#include "net/rpc.h"
#include "net/socket.h"

namespace brpc {
namespace {

struct BenchState {
  std::atomic<uint64_t> sent{0};
  std::atomic<uint64_t> done{0};
  std::atomic<uint64_t> errs{0};  // error responses (e.g. ELIMIT sheds)
  std::atomic<uint64_t> lat_idx{0};
  uint64_t total = 0;
  int payload_len = 0;
  std::string service = "BenchEcho";
  std::string method = "Echo";
  std::vector<uint32_t> lat_us;  // preallocated, atomically indexed
  std::mutex mu;
  std::condition_variable cv;
  bool finished = false;
};

int32_t bench_echo_handler(SocketId, butil::IOBuf* body,
                           butil::IOBuf* resp_body, void*) {
  resp_body->append(std::move(*body));
  return 0;
}

// Zero-ref form: request viewed in the read block, response memcpy'd into
// the dispatch loop's flat stage (net/rpc.h NativeMethodFlatFn).
int32_t bench_echo_handler_flat(SocketId, const char* req, size_t req_len,
                                char* resp, size_t resp_cap, void*) {
  if (req_len > resp_cap) return -1;  // oversized: IOBuf fallback
  memcpy(resp, req, req_len);
  return (int32_t)req_len;
}

void bench_send_one(SocketId sid, BenchState* st) {
  static const char kPayload[4096] = {0};
  const uint64_t cid = (uint64_t)butil::cpuwide_time_us();
  // Inside this socket's dispatch drain (pipelined next-send from the
  // response callback): stage the whole frame into the write batch.
  butil::IOBuf* batch = Socket::CurrentBatchFor(sid, st->payload_len + 96);
  if (batch != nullptr) {
    PackRequestFrameFlat(batch, cid, 0, st->service.data(),
                         st->service.size(), st->method.data(),
                         st->method.size(), 0, 0, nullptr, 0, kPayload,
                         st->payload_len);
    return;
  }
  butil::IOBuf frame;
  PackRequestFrameFlat(&frame, cid, 0, st->service.data(),
                       st->service.size(), st->method.data(),
                       st->method.size(), 0, 0, nullptr, 0, kPayload,
                       st->payload_len);
  Socket* s = Socket::Address(sid);
  if (s != nullptr) {
    s->Write(std::move(frame));
    s->Dereference();
  }
}

void bench_note_response(SocketId sid, const RequestHeader* hdr, void* user) {
  auto* st = (BenchState*)user;
  if (hdr->error_code != 0) {
    // shed/error replies keep the pipeline moving but are counted (and
    // timed) separately: mixing fail-fast latencies into the success
    // distribution would flatter p99 dishonestly
    st->errs.fetch_add(1, std::memory_order_relaxed);
  } else {
    const uint64_t now = (uint64_t)butil::cpuwide_time_us();
    const uint64_t idx = st->lat_idx.fetch_add(1, std::memory_order_relaxed);
    if (idx < st->lat_us.size()) {
      st->lat_us[idx] =
          (uint32_t)std::min<uint64_t>(now - hdr->cid, 0xffffffff);
    }
  }
  // keep the pipe full: claim a send ticket; tickets >= total mean the
  // pipeline is winding down
  if (st->sent.fetch_add(1, std::memory_order_relaxed) < st->total) {
    bench_send_one(sid, st);
  }
  const uint64_t d = st->done.fetch_add(1, std::memory_order_relaxed) + 1;
  if (d >= st->total) {
    std::lock_guard<std::mutex> lk(st->mu);
    st->finished = true;
    st->cv.notify_all();
  }
}

void bench_on_response(SocketId sid, const RequestHeader* hdr,
                       butil::IOBuf* body, void* user) {
  // body is BORROWED (response_inline mode) — do not free
  (void)body;
  bench_note_response(sid, hdr, user);
}

void bench_on_response_flat(SocketId sid, const RequestHeader* hdr,
                            const char* body, size_t body_len, void* user) {
  (void)body;
  (void)body_len;
  bench_note_response(sid, hdr, user);
}

void bench_noop_failed(SocketId, int, void*) {}

}  // namespace
}  // namespace brpc

extern "C" {

namespace {
using namespace brpc;

// Client pump core shared by the self-contained echo bench and the
// external-server pump: `conns` pipelined connections to 127.0.0.1:port,
// `inflight` frames outstanding each, p50/p99 from send-timestamp cids.
int run_pump(int port, const char* service, const char* method, int conns,
             int inflight, uint64_t total, int payload_len, double* qps_out,
             double* p50_us, double* p99_us, double* err_frac = nullptr) {
  // Heap-allocated: on the timeout path, in-flight responses can still
  // hit bench_on_response on dispatcher threads after we return, so the
  // state must outlive this frame — it is intentionally leaked then.
  auto* stp = new BenchState;
  BenchState& st = *stp;
  st.total = total;
  st.payload_len = payload_len;
  st.service = service;
  st.method = method;
  st.lat_us.assign(std::min<uint64_t>(total, 2'000'000), 0);

  std::vector<SocketId> clients;
  for (int i = 0; i < conns; ++i) {
    SocketOptions copts;
    copts.on_response = bench_on_response;
    copts.on_response_flat = bench_on_response_flat;
    copts.response_user = &st;
    copts.response_inline = true;
    copts.on_failed = bench_noop_failed;
    SocketId cid = INVALID_SOCKET_ID;
    if (Connect("127.0.0.1", port, copts, &cid) != 0) {
      for (SocketId c : clients) Socket::SetFailed(c, 0);
      delete stp;
      return -3;
    }
    clients.push_back(cid);
  }

  const int64_t t0 = butil::monotonic_time_us();
  // seed the pipeline: `inflight` outstanding frames per connection, each
  // claiming a ticket exactly like the response path (responses may
  // already be arriving while we seed)
  const uint64_t seed_target =
      std::min<uint64_t>((uint64_t)conns * (uint64_t)inflight, total);
  for (uint64_t i = 0; i < seed_target; ++i) {
    if (st.sent.fetch_add(1, std::memory_order_relaxed) < total) {
      bench_send_one(clients[i % clients.size()], &st);
    }
  }

  bool completed_in_time;
  {
    std::unique_lock<std::mutex> lk(st.mu);
    completed_in_time = st.cv.wait_for(lk, std::chrono::seconds(120),
                                       [&] { return st.finished; });
  }
  const int64_t t1 = butil::monotonic_time_us();

  for (SocketId cid : clients) Socket::SetFailed(cid, 0);

  const uint64_t completed = st.done.load();
  const uint64_t errs = st.errs.load();
  const double wall_s = (t1 - t0) / 1e6;
  // qps counts SUCCESSFUL responses only; sheds are reported as err_frac
  if (qps_out)
    *qps_out = (completed > errs ? completed - errs : 0) /
               (wall_s > 0 ? wall_s : 1e-9);
  if (err_frac) *err_frac = completed > 0 ? double(errs) / completed : 0.0;
  const uint64_t n = std::min<uint64_t>(st.lat_idx.load(), st.lat_us.size());
  if (n > 0) {
    std::vector<uint32_t> lats(st.lat_us.begin(), st.lat_us.begin() + n);
    std::sort(lats.begin(), lats.end());
    if (p50_us) *p50_us = lats[n / 2];
    if (p99_us) *p99_us = lats[(size_t)(n * 0.99)];
  } else {
    if (p50_us) *p50_us = 0;
    if (p99_us) *p99_us = 0;
  }
  if (completed_in_time) {
    delete stp;
    return completed >= total ? 0 : -4;
  }
  // Timed out: dispatcher threads may still reference *stp — leak it.
  return -4;
}

}  // namespace

// Returns 0 on success.  inline_run selects dispatcher-inline execution of
// the echo handler (the reference's "last message inline" discipline) vs
// one executor task per message.
int brpc_bench_echo(int conns, int inflight, uint64_t total, int payload_len,
                    int inline_run, double* qps_out, double* p50_us,
                    double* p99_us) {
  using namespace brpc;
  if (conns <= 0 || inflight <= 0 || total == 0 || payload_len < 0 ||
      payload_len > 4096) {
    return -1;
  }
  MethodRegistry::global()->RegisterFlat("BenchEcho", "Echo",
                                         bench_echo_handler,
                                         bench_echo_handler_flat, nullptr,
                                         inline_run != 0);
  SocketOptions server_opts;
  server_opts.enable_rpc_dispatch = true;
  SocketId listener = INVALID_SOCKET_ID;
  int port = 0;
  if (Listen("127.0.0.1", 0, server_opts, &listener, &port) != 0) {
    return -2;
  }
  const int rc = run_pump(port, "BenchEcho", "Echo", conns, inflight, total,
                          payload_len, qps_out, p50_us, p99_us);
  Socket::SetFailed(listener, 0);
  MethodRegistry::global()->Unregister("BenchEcho", "Echo");
  return rc;
}

// Pump an EXISTING server (e.g. a Python-handler service on `port`) with
// the same native client: measures the SERVER's dispatch + handler path
// with zero client-side Python cost — the reference's C++-client
// methodology (docs/cn/benchmark.md) pointed at user handlers.
int brpc_bench_pump(int port, const char* service, const char* method,
                    int conns, int inflight, uint64_t total, int payload_len,
                    double* qps_out, double* p50_us, double* p99_us,
                    double* err_frac) {
  if (port <= 0 || service == nullptr || method == nullptr || conns <= 0 ||
      inflight <= 0 || total == 0 || payload_len < 0 || payload_len > 4096) {
    return -1;
  }
  return run_pump(port, service, method, conns, inflight, total, payload_len,
                  qps_out, p50_us, p99_us, err_frac);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native h2/gRPC client pump: measures the native h2 SERVER data plane
// (net/h2.cc) the way run_pump measures the TRPC path — a C++ client
// with `inflight` open streams per connection, canned stateless-HPACK
// request header blocks, completions counted at END_STREAM trailers.
// ---------------------------------------------------------------------------

#include <deque>

#include "net/h2.h"

namespace brpc {
namespace {

struct H2PumpShared {
  std::atomic<uint64_t> sent{0};
  std::atomic<uint64_t> done{0};
  std::atomic<uint64_t> lat_idx{0};
  uint64_t total = 0;
  int payload_len = 0;
  std::string header_block;  // canned request HEADERS block
  std::vector<uint32_t> lat_us;
  std::mutex mu;
  std::condition_variable cv;
  bool finished = false;
};

struct H2PumpConn {
  H2PumpShared* st = nullptr;
  SocketId sid = INVALID_SOCKET_ID;
  std::mutex mu;                  // guards next_stream + t_send
  uint32_t next_stream = 1;
  std::deque<uint64_t> t_send;    // echo servers respond in order
  int64_t unacked_data = 0;       // server DATA bytes since last topup
};

void h2_pump_send_one(H2PumpConn* c) {
  H2PumpShared* st = c->st;
  char prefix[5];
  prefix[0] = 0;
  prefix[1] = (char)(st->payload_len >> 24);
  prefix[2] = (char)(st->payload_len >> 16);
  prefix[3] = (char)(st->payload_len >> 8);
  prefix[4] = (char)st->payload_len;
  static const char kPayload[4096] = {0};
  uint32_t stream_id;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    stream_id = c->next_stream;
    c->next_stream += 2;
    c->t_send.push_back((uint64_t)butil::cpuwide_time_us());
  }
  butil::IOBuf out;
  char hdr[9];
  // HEADERS (END_HEADERS)
  hdr[0] = (char)(st->header_block.size() >> 16);
  hdr[1] = (char)(st->header_block.size() >> 8);
  hdr[2] = (char)st->header_block.size();
  hdr[3] = 0x1;
  hdr[4] = 0x4;
  hdr[5] = (char)(stream_id >> 24);
  hdr[6] = (char)(stream_id >> 16);
  hdr[7] = (char)(stream_id >> 8);
  hdr[8] = (char)stream_id;
  out.append(hdr, 9);
  out.append(st->header_block.data(), st->header_block.size());
  // DATA (END_STREAM): 5-byte gRPC prefix + payload
  const uint32_t dlen = (uint32_t)st->payload_len + 5;
  hdr[0] = (char)(dlen >> 16);
  hdr[1] = (char)(dlen >> 8);
  hdr[2] = (char)dlen;
  hdr[3] = 0x0;
  hdr[4] = 0x1;
  out.append(hdr, 9);
  out.append(prefix, 5);
  if (st->payload_len > 0) out.append(kPayload, st->payload_len);
  Socket* s = Socket::Address(c->sid);
  if (s != nullptr) {
    s->Write(std::move(out));
    s->Dereference();
  }
}

// MSG_H2 delivery on the client socket: meta = concatenated 9-byte frame
// headers (H2Accum), body = payloads.  Completions are END_STREAM
// HEADERS (trailers); sends are pipelined from here.
void h2_pump_on_message(SocketId sid, int kind, const char* meta,
                        size_t meta_len, butil::IOBuf* body, void* user) {
  auto* c = (H2PumpConn*)user;
  H2PumpShared* st = c->st;
  size_t boff = 0;
  int completions = 0;
  int64_t data_bytes = 0;
  for (size_t off = 0; off + 9 <= meta_len; off += 9) {
    const uint8_t* h = (const uint8_t*)meta + off;
    const uint32_t len =
        ((uint32_t)h[0] << 16) | ((uint32_t)h[1] << 8) | h[2];
    const uint8_t type = h[3];
    const uint8_t flags = h[4];
    boff += len;
    if (type == 0x0) data_bytes += len;                  // DATA
    if (type == 0x1 && (flags & 0x1)) ++completions;     // trailers
  }
  (void)boff;
  delete body;
  if (data_bytes > 0) {
    // top up the connection recv window every 16MB so long runs don't
    // stall the server's sender
    bool topup = false;
    int64_t n = 0;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      c->unacked_data += data_bytes;
      if (c->unacked_data >= (16 << 20)) {
        n = c->unacked_data;
        c->unacked_data = 0;
        topup = true;
      }
    }
    if (topup) {
      butil::IOBuf wu;
      char f[13];
      f[0] = 0;
      f[1] = 0;
      f[2] = 4;
      f[3] = 0x8;
      f[4] = 0;
      f[5] = f[6] = f[7] = f[8] = 0;  // stream 0
      f[9] = (char)(n >> 24);
      f[10] = (char)(n >> 16);
      f[11] = (char)(n >> 8);
      f[12] = (char)n;
      wu.append(f, 13);
      Socket* s = Socket::Address(sid);
      if (s != nullptr) {
        s->Write(std::move(wu));
        s->Dereference();
      }
    }
  }
  for (int i = 0; i < completions; ++i) {
    uint64_t t0 = 0;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      if (!c->t_send.empty()) {
        t0 = c->t_send.front();
        c->t_send.pop_front();
      }
    }
    if (t0 != 0) {
      const uint64_t now = (uint64_t)butil::cpuwide_time_us();
      const uint64_t idx =
          st->lat_idx.fetch_add(1, std::memory_order_relaxed);
      if (idx < st->lat_us.size())
        st->lat_us[idx] =
            (uint32_t)std::min<uint64_t>(now - t0, 0xffffffff);
    }
    if (st->sent.fetch_add(1, std::memory_order_relaxed) < st->total) {
      h2_pump_send_one(c);
    }
    const uint64_t d = st->done.fetch_add(1, std::memory_order_relaxed) + 1;
    if (d >= st->total) {
      std::lock_guard<std::mutex> lk(st->mu);
      st->finished = true;
      st->cv.notify_all();
    }
  }
}

}  // namespace
}  // namespace brpc

extern "C" {

// Register a C++ echo handler under (service, method) so the h2 pump
// can measure the PURE-NATIVE gRPC path (session dispatch -> native
// handler -> native response pack; Python never runs).
static int32_t h2_bench_native_echo(brpc::SocketId, butil::IOBuf* body,
                                    butil::IOBuf* resp_body, void*) {
  resp_body->append(std::move(*body));
  return 0;
}

void brpc_bench_register_native_echo(const char* service, const char* method,
                                     int inline_run) {
  brpc::MethodRegistry::global()->Register(service, method,
                                           h2_bench_native_echo, nullptr,
                                           inline_run != 0);
}

// gRPC unary pump against an existing server's native h2 plane.
// path = "/Service/Method".  Returns 0 on success.
int brpc_bench_pump_h2(int port, const char* path, int conns, int inflight,
                       uint64_t total, int payload_len, double* qps_out,
                       double* p50_us, double* p99_us) {
  using namespace brpc;
  if (port <= 0 || path == nullptr || path[0] != '/' || conns <= 0 ||
      inflight <= 0 || total == 0 || payload_len < 0 || payload_len > 4096) {
    return -1;
  }
  auto* stp = new H2PumpShared;  // leaked on timeout (in-flight callbacks)
  H2PumpShared& st = *stp;
  st.total = total;
  st.payload_len = payload_len;
  st.lat_us.assign(std::min<uint64_t>(total, 2'000'000), 0);
  // canned request block: stateless encoder, identical for every request
  h2::EncodeHeader(&st.header_block, ":method", 7, "POST", 4);
  h2::EncodeHeader(&st.header_block, ":scheme", 7, "http", 4);
  h2::EncodeHeader(&st.header_block, ":path", 5, path, strlen(path));
  h2::EncodeHeader(&st.header_block, ":authority", 10, "bench", 5);
  h2::EncodeHeader(&st.header_block, "content-type", 12,
                   "application/grpc", 16);
  h2::EncodeHeader(&st.header_block, "te", 2, "trailers", 8);

  std::vector<H2PumpConn*> cs;
  for (int i = 0; i < conns; ++i) {
    auto* c = new H2PumpConn;
    c->st = &st;
    SocketOptions copts;
    copts.on_message = h2_pump_on_message;
    copts.on_failed = bench_noop_failed;
    copts.user = c;
    SocketId cid = INVALID_SOCKET_ID;
    if (Connect("127.0.0.1", port, copts, &cid) != 0) {
      for (auto* cc : cs) Socket::SetFailed(cc->sid, 0);
      return -3;
    }
    c->sid = cid;
    Socket* s = Socket::Address(cid);
    if (s != nullptr) {
      s->set_forced_protocol(MSG_H2);
      // preface + SETTINGS(max initial window) + conn WINDOW_UPDATE
      butil::IOBuf first;
      first.append("PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n", 24);
      char sf[9 + 6];
      sf[0] = 0;
      sf[1] = 0;
      sf[2] = 6;
      sf[3] = 0x4;
      sf[4] = 0;
      sf[5] = sf[6] = sf[7] = sf[8] = 0;
      sf[9] = 0;
      sf[10] = 0x4;  // INITIAL_WINDOW_SIZE
      sf[11] = 0x7f;
      sf[12] = (char)0xff;
      sf[13] = (char)0xff;
      sf[14] = (char)0xff;
      first.append(sf, sizeof(sf));
      char wu[13];
      wu[0] = 0;
      wu[1] = 0;
      wu[2] = 4;
      wu[3] = 0x8;
      wu[4] = 0;
      wu[5] = wu[6] = wu[7] = wu[8] = 0;
      const uint32_t inc = 0x7fffffffu - 65535u;
      wu[9] = (char)(inc >> 24);
      wu[10] = (char)(inc >> 16);
      wu[11] = (char)(inc >> 8);
      wu[12] = (char)inc;
      first.append(wu, 13);
      s->Write(std::move(first));
      s->Dereference();
    }
    cs.push_back(c);
  }

  const int64_t t0 = butil::monotonic_time_us();
  const uint64_t seed_target =
      std::min<uint64_t>((uint64_t)conns * (uint64_t)inflight, total);
  for (uint64_t i = 0; i < seed_target; ++i) {
    if (st.sent.fetch_add(1, std::memory_order_relaxed) < total) {
      h2_pump_send_one(cs[i % cs.size()]);
    }
  }
  bool completed_in_time;
  {
    std::unique_lock<std::mutex> lk(st.mu);
    completed_in_time = st.cv.wait_for(lk, std::chrono::seconds(120),
                                       [&] { return st.finished; });
  }
  const int64_t t1 = butil::monotonic_time_us();
  for (auto* c : cs) Socket::SetFailed(c->sid, 0);

  const uint64_t completed = st.done.load();
  const double wall_s = (t1 - t0) / 1e6;
  if (qps_out) *qps_out = completed / (wall_s > 0 ? wall_s : 1e-9);
  const uint64_t n = std::min<uint64_t>(st.lat_idx.load(), st.lat_us.size());
  if (n > 0) {
    std::vector<uint32_t> lats(st.lat_us.begin(), st.lat_us.begin() + n);
    std::sort(lats.begin(), lats.end());
    if (p50_us) *p50_us = lats[n / 2];
    if (p99_us) *p99_us = lats[(size_t)(n * 0.99)];
  } else {
    if (p50_us) *p50_us = 0;
    if (p99_us) *p99_us = 0;
  }
  if (!completed_in_time) return -4;  // st leaked deliberately
  // conn structs may still be referenced by in-flight FIFO callbacks for
  // a beat after SetFailed; the failure notification rides the same lane
  // as deliveries, so once it runs the lane is drained — small leak on
  // timeout, clean delete otherwise is still unsafe; leak both (bench
  // process scope).
  return 0;
}

}  // extern "C"
