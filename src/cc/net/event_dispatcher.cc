#include "net/event_dispatcher.h"

#include <errno.h>
#include <fcntl.h>
#include <sys/epoll.h>
#include <unistd.h>

#include <mutex>
#include <thread>

#include "butil/common.h"
#include "butil/flight.h"
#include "net/rpc.h"

namespace brpc {

// Monotonic naming index for the flight recorder's per-thread table
// ("epoll/0", "epoll/1", ...).
static std::atomic<int> g_dispatcher_seq{0};

EventDispatcher::EventDispatcher() {
  _epfd = epoll_create1(EPOLL_CLOEXEC);
  if (pipe(_wakeup) != 0) {
    BLOG(ERROR, "EventDispatcher: pipe() failed: %d", errno);
  } else {
    // read end must be non-blocking: the loop drains it until empty
    fcntl(_wakeup[0], F_SETFL,
          fcntl(_wakeup[0], F_GETFL, 0) | O_NONBLOCK);
  }
  epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.u64 = (uint64_t)-1;  // wakeup marker
  epoll_ctl(_epfd, EPOLL_CTL_ADD, _wakeup[0], &ev);
  _thread = std::thread([this] { Run(); });
}

EventDispatcher::~EventDispatcher() {
  Stop();
  Join();
  if (_epfd >= 0) close(_epfd);
  if (_wakeup[0] >= 0) close(_wakeup[0]);
  if (_wakeup[1] >= 0) close(_wakeup[1]);
}

int EventDispatcher::AddConsumer(SocketId sid, int fd) {
  epoll_event ev;
  ev.events = EPOLLIN | EPOLLOUT | EPOLLET | EPOLLRDHUP;
  ev.data.u64 = sid;
  return epoll_ctl(_epfd, EPOLL_CTL_ADD, fd, &ev);
}

int EventDispatcher::Rearm(SocketId sid, int fd) {
  epoll_event ev;
  ev.events = EPOLLIN | EPOLLOUT | EPOLLET | EPOLLRDHUP;
  ev.data.u64 = sid;
  return epoll_ctl(_epfd, EPOLL_CTL_MOD, fd, &ev);
}

void EventDispatcher::RemoveConsumer(int fd) {
  epoll_ctl(_epfd, EPOLL_CTL_DEL, fd, nullptr);
}

void EventDispatcher::Stop() {
  bool expected = false;
  if (_stop.compare_exchange_strong(expected, true)) {
    const char c = 0;
    ssize_t rc = write(_wakeup[1], &c, 1);
    (void)rc;
  }
}

void EventDispatcher::Join() {
  if (_thread.joinable()) _thread.join();
}

void EventDispatcher::RunOnLoop(void (*fn)(void*), void* arg) {
  {
    std::lock_guard<std::mutex> g(_tasks_mu);
    _tasks.emplace_back(fn, arg);
  }
  const char c = 1;
  ssize_t rc = write(_wakeup[1], &c, 1);
  (void)rc;
}

void EventDispatcher::DrainLoopTasks() {
  for (;;) {
    std::pair<void (*)(void*), void*> t;
    {
      std::lock_guard<std::mutex> g(_tasks_mu);
      if (_tasks.empty()) return;
      t = _tasks.front();
      _tasks.pop_front();
    }
    t.first(t.second);
  }
}

void EventDispatcher::Run() {
  // NOTE: boosting this thread's priority (nice -10) was tried and
  // REVERTED: on a core-starved host it starves the usercode workers —
  // the dispatcher admits load faster than handlers can drain, queues
  // explode and p99 went 7.7ms -> 47ms in the 64-conn Python bench.
  // 512, not 64: with C client + server sockets sharing one dispatcher
  // (the 64-conn loopback bench has 128 busy fds), a 64-slot sweep
  // leaves half the ready sockets for the NEXT epoll round — every
  // affected request eats a whole extra drain cycle, which showed up as
  // a clean 2x p50 tail.
  butil::flight::set_thread_name(
      "epoll/%d", g_dispatcher_seq.fetch_add(1, std::memory_order_relaxed));
  epoll_event events[512];
  while (!_stop.load(std::memory_order_acquire)) {
    const int n = epoll_wait(_epfd, events, 512, 1000);
    if (n < 0 && errno != EINTR) {
      BLOG(ERROR, "epoll_wait failed: %d", errno);
      return;
    }
    NoteDispatchSweepStart();  // inline-usercode admission window
    for (int i = 0; i < n; ++i) {
      const SocketId sid = events[i].data.u64;
      if (sid == (uint64_t)-1) {
        // wakeup pipe: drain it (level-triggered registration — leftover
        // bytes would spin the loop) and run queued loop tasks
        char buf[256];
        while (read(_wakeup[0], buf, sizeof(buf)) > 0) {
        }
        DrainLoopTasks();
        continue;
      }
      Socket* s = Socket::Address(sid);
      if (s == nullptr) continue;  // stale: slot recycled, drop
      if (events[i].events & (EPOLLIN | EPOLLRDHUP | EPOLLHUP | EPOLLERR)) {
        butil::flight::record(butil::flight::EV_SOCK_EPOLLIN, sid,
                              (int64_t)events[i].events);
        s->OnReadable();
      }
      if (events[i].events & EPOLLOUT) {
        s->OnWritable();
      }
      s->Dereference();
    }
  }
}

// ---- global sharded set ----

static std::mutex g_disp_mu;
static std::atomic<std::vector<EventDispatcher*>*> g_dispatchers{nullptr};

void EventDispatcher::InitGlobal(int num) {
  std::lock_guard<std::mutex> g(g_disp_mu);
  if (g_dispatchers.load(std::memory_order_acquire) != nullptr) return;
  if (num <= 0) {
    // The reference runs ONE event dispatcher by default
    // (FLAGS_event_dispatcher_num=1): on small hosts extra epoll
    // threads only time-slice against each other and the p99 tail
    // inflates by whole scheduler quanta (measured 6x at 8 conns on a
    // 1-core box).  Scale up only when there are plenty of cores.
    const int hw = (int)std::thread::hardware_concurrency();
    num = hw >= 16 ? 4 : hw >= 8 ? 2 : 1;
  }
  auto* v = new std::vector<EventDispatcher*>();
  for (int i = 0; i < num; ++i) v->push_back(new EventDispatcher());
  g_dispatchers.store(v, std::memory_order_release);
}

EventDispatcher* EventDispatcher::GetDispatcher(int fd) {
  auto* v = g_dispatchers.load(std::memory_order_acquire);
  if (v == nullptr) {
    InitGlobal(0);
    v = g_dispatchers.load(std::memory_order_acquire);
  }
  return (*v)[fd % v->size()];
}

void EventDispatcher::ShutdownGlobal() {
  std::lock_guard<std::mutex> g(g_disp_mu);
  auto* v = g_dispatchers.load(std::memory_order_acquire);
  if (v == nullptr) return;
  for (auto* d : *v) d->Stop();
  for (auto* d : *v) {
    d->Join();
    delete d;
  }
  g_dispatchers.store(nullptr, std::memory_order_release);
  delete v;
}

}  // namespace brpc
