// EventDispatcher — N epoll loops dispatching socket events
// (SURVEY.md §2.3; reference src/brpc/event_dispatcher_epoll.cpp).
//
// Each dispatcher owns one epoll fd and one thread running epoll_wait;
// sockets are registered edge-triggered with their versioned SocketId as the
// epoll cookie, so a stale event on a recycled slot simply fails Address()
// and is dropped — the same structural safety the reference gets.  Sockets
// are sharded across dispatchers by fd (event_dispatcher.cpp:44).
#pragma once

#include <atomic>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include "net/socket.h"

namespace brpc {

class EventDispatcher {
 public:
  EventDispatcher();
  ~EventDispatcher();

  int AddConsumer(SocketId sid, int fd);
  // EPOLL_CTL_MOD with the same event set: re-arms edge-triggered readiness
  // so an EPOLLOUT edge missed between EAGAIN and this call is re-delivered.
  int Rearm(SocketId sid, int fd);
  void RemoveConsumer(int fd);
  void Stop();
  void Join();

  // Run `fn(arg)` on this dispatcher's loop thread between epoll sweeps
  // (wakes the loop).  The ONLY way foreign threads may touch
  // loop-thread-owned socket state (e.g. InjectBytes for the TLS
  // filter); fns must be quick and non-blocking.
  void RunOnLoop(void (*fn)(void*), void* arg);

  static void InitGlobal(int num);        // idempotent; default 2
  static EventDispatcher* GetDispatcher(int fd);
  static void ShutdownGlobal();

 private:
  void Run();
  void DrainLoopTasks();

  int _epfd = -1;
  int _wakeup[2] = {-1, -1};
  std::atomic<bool> _stop{false};
  std::thread _thread;
  std::mutex _tasks_mu;
  std::deque<std::pair<void (*)(void*), void*>> _tasks;
};

}  // namespace brpc
