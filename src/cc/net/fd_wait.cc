#include "net/fd_wait.h"

#include <errno.h>
#include <poll.h>
#include <sys/epoll.h>
#include <unistd.h>

#include <chrono>
#include <mutex>
#include <thread>
#include <unordered_map>

#include "bthread/butex.h"
#include "butil/common.h"

namespace brpc {

int fd_wait(int fd, uint32_t events, int timeout_ms) {
  pollfd p;
  p.fd = fd;
  p.events = 0;
  if (events & FD_WAIT_READ) p.events |= POLLIN;
  if (events & FD_WAIT_WRITE) p.events |= POLLOUT;
  // EINTR restarts must not extend the deadline (a SIGPROF storm would
  // otherwise make a 150ms wait unbounded)
  const int64_t deadline_us =
      timeout_ms < 0 ? -1 : butil::monotonic_time_us() +
                                (int64_t)timeout_ms * 1000;
  for (;;) {
    int remaining = -1;
    if (deadline_us >= 0) {
      const int64_t left = deadline_us - butil::monotonic_time_us();
      if (left <= 0) return ETIMEDOUT;
      remaining = (int)((left + 999) / 1000);
    }
    const int rc = poll(&p, 1, remaining);
    if (rc > 0) {
      // an invalid fd is an error, not readiness (POLLERR/POLLHUP count
      // as ready: the caller's IO surfaces the condition, like epoll)
      return (p.revents & POLLNVAL) ? EBADF : 0;
    }
    if (rc == 0) return ETIMEDOUT;
    if (errno != EINTR) return errno;
  }
}

namespace {

struct FdWaiter {
  bthread::Butex ready{0};
  uint32_t armed_events = 0;  // epoll mask, for the staleness probe
  // Set (under the registry lock) when a stale-release woke this waiter:
  // its fd NUMBER was recycled to an unrelated descriptor, so reporting
  // "ready" would have the caller do IO on someone else's fd.
  std::atomic<bool> orphaned{false};
};

// One shared epoll + thread watching fibers' one-shot fd waits.  ALL
// waiter touches by the epoll thread happen under the registry lock —
// including the butex bump and wake_all — so a timed-out fiber that
// takes the lock and finds itself already claimed can safely free its
// frame after returning: the claimer is provably done with it.
class WaitRegistry {
 public:
  static WaitRegistry* instance() {
    static WaitRegistry reg;
    return &reg;
  }

  // 0 on success; EEXIST when the fd already has a waiter; errno else.
  int arm(int fd, uint32_t events, FdWaiter* w) {
    std::lock_guard<std::mutex> g(_mu);
    auto it = _map.find(fd);
    if (it != _map.end()) {
      // A map entry whose fd the kernel no longer tracks means the
      // waited fd was close()d (the kernel auto-removes it from the
      // epoll set) and the NUMBER was recycled: the old waiter can
      // never be delivered.  Probe with a same-mask MOD — ENOENT is
      // the stale signature; release the orphan (it wakes, its caller's
      // IO surfaces EBADF) instead of poisoning this fd with EEXIST
      // forever.
      epoll_event probe;
      probe.events = it->second->armed_events;
      probe.data.fd = fd;
      if (epoll_ctl(_epfd, EPOLL_CTL_MOD, fd, &probe) == 0 ||
          errno != ENOENT) {
        return EEXIST;  // genuinely armed
      }
      FdWaiter* old = it->second;
      _map.erase(it);
      old->orphaned.store(true, std::memory_order_release);
      old->ready.value.fetch_add(1, std::memory_order_release);
      old->ready.wake_all();
    }
    epoll_event ev;
    ev.events = EPOLLONESHOT | EPOLLRDHUP;
    if (events & FD_WAIT_READ) ev.events |= EPOLLIN;
    if (events & FD_WAIT_WRITE) ev.events |= EPOLLOUT;
    ev.data.fd = fd;
    w->armed_events = ev.events;
    _map.emplace(fd, w);
    if (epoll_ctl(_epfd, EPOLL_CTL_ADD, fd, &ev) != 0) {
      const int err = errno;
      _map.erase(fd);
      return err;
    }
    return 0;
  }

  // Timeout/cancel path: true when WE removed the waiter (not yet
  // claimed by the epoll thread); false when delivery already happened.
  bool disarm(int fd, FdWaiter* w) {
    std::lock_guard<std::mutex> g(_mu);
    auto it = _map.find(fd);
    if (it == _map.end() || it->second != w) return false;
    _map.erase(it);
    epoll_ctl(_epfd, EPOLL_CTL_DEL, fd, nullptr);
    return true;
  }

 private:
  WaitRegistry() {
    _epfd = epoll_create1(EPOLL_CLOEXEC);
    _thread = std::thread([this] { run(); });
    _thread.detach();  // process-lifetime singleton
  }

  void run() {
    epoll_event events[32];
    for (;;) {
      const int n = epoll_wait(_epfd, events, 32, -1);
      if (n < 0) {
        if (errno == EINTR) continue;
        // Never exit: a dead delivery thread turns every future fiber
        // wait into a silent park (arm() would keep succeeding).  Log,
        // back off, keep serving.
        BLOG(ERROR, "fd_wait epoll_wait failed: %d", errno);
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
        continue;
      }
      for (int i = 0; i < n; ++i) {
        const int fd = events[i].data.fd;
        std::lock_guard<std::mutex> g(_mu);
        auto it = _map.find(fd);
        if (it == _map.end()) continue;  // raced with disarm
        FdWaiter* w = it->second;
        _map.erase(it);
        epoll_ctl(_epfd, EPOLL_CTL_DEL, fd, nullptr);
        w->ready.value.fetch_add(1, std::memory_order_release);
        w->ready.wake_all();
        // no touches of w after the lock drops — see class comment
      }
    }
  }

  int _epfd = -1;
  std::mutex _mu;
  std::unordered_map<int, FdWaiter*> _map;
  std::thread _thread;
};

}  // namespace

bthread::Task fiber_fd_wait(int fd, uint32_t events, int timeout_ms,
                            int* rc_out) {
  FdWaiter w;
  const int arm_rc = WaitRegistry::instance()->arm(fd, events, &w);
  if (arm_rc != 0) {
    *rc_out = arm_rc;
    co_return;
  }
  const int64_t timeout_us =
      timeout_ms < 0 ? -1 : (int64_t)timeout_ms * 1000;
  const auto r = co_await w.ready.wait(0, timeout_us);
  // EVERY exit path must pass through disarm's registry lock before the
  // frame (and the butex inside it) dies: the epoll thread bumps the
  // value and calls wake_all while holding that lock, so a fiber that
  // raced past the wait (kMismatch: the bump landed before we enqueued;
  // kWoken: resumed while wake_all was still returning) would otherwise
  // free the butex out from under the waker — the lock acquisition
  // proves the claimer is completely done with the waiter.
  const bool we_removed = WaitRegistry::instance()->disarm(fd, &w);
  if (w.orphaned.load(std::memory_order_acquire)) {
    // our fd was close()d and its number recycled; "ready" would send
    // the caller to IO on an unrelated descriptor
    *rc_out = EBADF;
  } else if (r == bthread::WaitResult::kTimeout) {
    // losing the disarm race means the event arrived between our
    // timeout and the lock — that is a delivery
    *rc_out = we_removed ? ETIMEDOUT : 0;
  } else {
    *rc_out = 0;
  }
}

}  // namespace brpc
