// fd_wait — general readiness-wait API (SURVEY.md §2.2 "fd wait" row;
// reference src/bthread/fd.cpp:343,442 bthread_fd_wait).
//
// Two forms:
//   * fd_wait()        — pthread-blocking, for Python/foreign threads.
//     A plain poll(2): the calling OS thread sleeps in the kernel.
//   * fiber_fd_wait()  — parks the calling COROUTINE on a butex while a
//     shared epoll watches the fd: a blocked wait costs a heap frame,
//     not an OS thread, exactly the reference's bthread_fd_wait
//     economics.  One waiter per fd at a time (EEXIST otherwise).
#pragma once

#include <cstdint>

#include "bthread/fiber.h"

namespace brpc {

// Event bits (deliberately not raw EPOLL* so the C API is stable).
constexpr uint32_t FD_WAIT_READ = 1;
constexpr uint32_t FD_WAIT_WRITE = 2;

// Returns 0 when ready, ETIMEDOUT, or a positive errno.
int fd_wait(int fd, uint32_t events, int timeout_ms);

// Fiber form: *rc_out receives the same codes as fd_wait.
bthread::Task fiber_fd_wait(int fd, uint32_t events, int timeout_ms,
                            int* rc_out);

}  // namespace brpc
