// Native HTTP/2 + gRPC server data plane (see h2.h).
#include "net/h2.h"

#include <atomic>
#include <cstdio>
#include <cstring>

#include "bthread/executor.h"
#include "butil/common.h"
#include "net/rpc.h"
#include "net/socket.h"

namespace brpc {
namespace h2 {

namespace {

// frame types (RFC 7540 §6)
constexpr uint8_t FT_DATA = 0x0;
constexpr uint8_t FT_HEADERS = 0x1;
constexpr uint8_t FT_PRIORITY = 0x2;
constexpr uint8_t FT_RST_STREAM = 0x3;
constexpr uint8_t FT_SETTINGS = 0x4;
constexpr uint8_t FT_PUSH_PROMISE = 0x5;
constexpr uint8_t FT_PING = 0x6;
constexpr uint8_t FT_GOAWAY = 0x7;
constexpr uint8_t FT_WINDOW_UPDATE = 0x8;
constexpr uint8_t FT_CONTINUATION = 0x9;

// flags
constexpr uint8_t FLAG_END_STREAM = 0x1;  // DATA / HEADERS
constexpr uint8_t FLAG_ACK = 0x1;         // SETTINGS / PING
constexpr uint8_t FLAG_END_HEADERS = 0x4;
constexpr uint8_t FLAG_PADDED = 0x8;
constexpr uint8_t FLAG_PRIORITY = 0x20;

// error codes (RFC 7540 §7)
constexpr uint32_t EC_PROTOCOL_ERROR = 0x1;
constexpr uint32_t EC_REFUSED_STREAM = 0x7;

// settings ids
constexpr uint16_t SET_MAX_CONCURRENT_STREAMS = 0x3;
constexpr uint16_t SET_INITIAL_WINDOW_SIZE = 0x4;
constexpr uint16_t SET_MAX_FRAME_SIZE = 0x5;

std::atomic<H2EventCallback> g_event_cb{nullptr};
std::atomic<void*> g_event_user{nullptr};
std::atomic<int64_t> g_native_requests{0};
std::atomic<int64_t> g_native_responses{0};
std::atomic<int64_t> g_python_events{0};

inline uint32_t rd32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | p[3];
}

inline void put_frame_header(char* b, uint32_t len, uint8_t type,
                             uint8_t flags, uint32_t stream_id) {
  b[0] = (char)(len >> 16);
  b[1] = (char)(len >> 8);
  b[2] = (char)len;
  b[3] = (char)type;
  b[4] = (char)flags;
  b[5] = (char)(stream_id >> 24);
  b[6] = (char)(stream_id >> 16);
  b[7] = (char)(stream_id >> 8);
  b[8] = (char)stream_id;
}

void append_frame(butil::IOBuf* out, uint8_t type, uint8_t flags,
                  uint32_t stream_id, const void* payload, size_t len) {
  char hdr[9];
  put_frame_header(hdr, (uint32_t)len, type, flags, stream_id);
  out->append(hdr, 9);
  if (len > 0) out->append(payload, len);
}

void append_window_update(butil::IOBuf* out, uint32_t stream_id,
                          uint32_t increment) {
  char p[4] = {(char)(increment >> 24), (char)(increment >> 16),
               (char)(increment >> 8), (char)increment};
  append_frame(out, FT_WINDOW_UPDATE, 0, stream_id, p, 4);
}

// The unary hot path's header blocks are CONSTANT — encode them once.
const std::string& ok_response_headers_block() {
  static const std::string block = [] {
    std::string b;
    EncodeHeader(&b, ":status", 7, "200", 3);
    EncodeHeader(&b, "content-type", 12, "application/grpc", 16);
    return b;
  }();
  return block;
}

const std::string& ok_trailers_block() {
  static const std::string block = [] {
    std::string b;
    EncodeHeader(&b, "grpc-status", 11, "0", 1);
    return b;
  }();
  return block;
}

void encode_response_headers(std::string* block, const char* const* extra_kv,
                             size_t n_extra) {
  block->append(ok_response_headers_block());
  for (size_t i = 0; i + 1 < 2 * n_extra; i += 2)
    EncodeHeader(block, extra_kv[i], std::strlen(extra_kv[i]),
                 extra_kv[i + 1], std::strlen(extra_kv[i + 1]));
}

void encode_trailers(std::string* block, int grpc_status,
                     const char* grpc_message, size_t grpc_message_len,
                     const char* const* extra_kv, size_t n_extra) {
  if (grpc_status == 0 && grpc_message_len == 0 && n_extra == 0) {
    block->append(ok_trailers_block());
    return;
  }
  char st[12];
  const int n = std::snprintf(st, sizeof(st), "%d", grpc_status);
  EncodeHeader(block, "grpc-status", 11, st, (size_t)n);
  if (grpc_message_len > 0)
    EncodeHeader(block, "grpc-message", 12, grpc_message, grpc_message_len);
  for (size_t i = 0; i + 1 < 2 * n_extra; i += 2)
    EncodeHeader(block, extra_kv[i], std::strlen(extra_kv[i]),
                 extra_kv[i + 1], std::strlen(extra_kv[i + 1]));
}

// Python event, delivered on the socket's FIFO lane so per-connection
// order (headers -> messages -> end) survives the executor hop.
struct PendingH2Event {
  SocketId sid;
  uint32_t stream_id;
  int kind;
  int mflags;
  std::string service;
  std::string method;
  std::string headers;
  butil::IOBuf* body;  // owned; may be nullptr
};

// FIFO-lane backlog accounting for one event.  A single admissible
// message can legitimately exceed the socket's whole overcrowded limit
// (the gRPC message cap is 256MB, the backlog limit 64MB); accounting
// the full size would make such a message undeliverable no matter how
// idle the consumer.  Cap one event's charge at half the limit:
// delivery is always possible, and a sustained pile-up (2+ undrained
// big events) still trips the bound.
int64_t event_bytes(size_t body_size) {
  const int64_t cap = Socket::overcrowded_limit() / 2;
  const int64_t n = 256 + (int64_t)body_size;
  return (cap > 0 && n > cap) ? cap : n;
}

void run_h2_event_task(void* arg) {
  auto* p = (PendingH2Event*)arg;
  H2EventCallback cb = g_event_cb.load(std::memory_order_acquire);
  if (cb != nullptr) {
    g_python_events.fetch_add(1, std::memory_order_relaxed);
    cb(p->sid, p->stream_id, p->kind, p->service.data(), p->service.size(),
       p->method.data(), p->method.size(), p->headers.data(),
       p->headers.size(), p->body, p->mflags,
       g_event_user.load(std::memory_order_acquire));
  } else {
    delete p->body;
  }
  delete p;
}

// Native handler run off the dispatch thread (non-inline registrations).
struct PendingH2Native {
  SocketId sid;
  uint32_t stream_id;
  MethodRegistry::Entry entry;
  butil::IOBuf message;
};

void run_h2_native_task(void* arg) {
  auto* p = (PendingH2Native*)arg;
  butil::IOBuf resp;
  const int32_t rc = p->entry.fn(p->sid, &p->message, &resp, p->entry.user);
  std::string flat = resp.to_string();
  if (rc == 0) {
    H2RespondUnary(p->sid, p->stream_id, 0, nullptr, 0, flat.data(),
                   flat.size(), nullptr, 0);
  } else {
    H2RespondUnary(p->sid, p->stream_id, 2, "native handler error", 20,
                   nullptr, 0, nullptr, 0);
  }
  delete p;
}

}  // namespace

void SetH2EventCallback(H2EventCallback cb, void* user) {
  g_event_user.store(user, std::memory_order_release);
  g_event_cb.store(cb, std::memory_order_release);
}

int64_t h2_native_requests() {
  return g_native_requests.load(std::memory_order_relaxed);
}
int64_t h2_native_responses() {
  return g_native_responses.load(std::memory_order_relaxed);
}
int64_t h2_python_events() {
  return g_python_events.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// send helpers
// ---------------------------------------------------------------------------

bool H2Session::WriteOut(butil::IOBuf&& out) {
  if (out.empty()) return true;
  // dispatch-thread writes join the drain's write batch for free
  butil::IOBuf* batch = Socket::CurrentBatchFor(sid_, out.size());
  if (batch != nullptr) {
    batch->append(std::move(out));
    return true;
  }
  Socket* s = Socket::Address(sid_);
  if (s == nullptr) return false;
  const int rc = s->Write(std::move(out));
  s->Dereference();
  return rc == 0;
}

H2Session::Stream* H2Session::FindStream(uint32_t stream_id) {
  auto it = streams_.find(stream_id);
  return it == streams_.end() ? nullptr : &it->second;
}

// lock held.  Mark a stream whose BOTH halves are now closed for
// reaping.  Response threads must never erase directly: the dispatch
// thread may hold a Stream reference across its frame processing, and
// unordered_map::erase would invalidate it mid-use.  The dispatch
// thread reaps at the top of the next OnFrames call.
void H2Session::MarkDeadLocked(uint32_t stream_id) {
  dead_streams_.push_back(stream_id);
}

void H2Session::ReapDeadStreams() {
  std::lock_guard<std::mutex> lk(send_mu_);
  for (uint32_t id : dead_streams_) streams_.erase(id);
  dead_streams_.clear();
}

// lock held.  Append one gRPC message as DATA frames, splitting at the
// peer's max frame size and respecting both flow-control windows;
// window-starved bytes queue on the stream and drain on WINDOW_UPDATE.
void H2Session::AppendData(butil::IOBuf* out, Stream& st, uint32_t stream_id,
                           const void* payload, size_t len, uint8_t mflags) {
  char prefix[5];
  prefix[0] = (char)mflags;
  prefix[1] = (char)(len >> 24);
  prefix[2] = (char)(len >> 16);
  prefix[3] = (char)(len >> 8);
  prefix[4] = (char)len;
  if (!st.send_queue.empty()) {
    // already blocked: preserve byte order
    st.send_queue.append(prefix, 5);
    if (len > 0) st.send_queue.append(payload, len);
    return;
  }
  // fast path: whole message fits the windows and one frame
  const int64_t window = conn_send_window_ < st.send_window
                             ? conn_send_window_
                             : st.send_window;
  const size_t total = len + 5;
  if ((int64_t)total <= window && total <= peer_max_frame_) {
    char hdr[9];
    put_frame_header(hdr, (uint32_t)total, FT_DATA, 0, stream_id);
    out->append(hdr, 9);
    out->append(prefix, 5);
    if (len > 0) out->append(payload, len);
    conn_send_window_ -= (int64_t)total;
    st.send_window -= (int64_t)total;
    return;
  }
  butil::IOBuf whole;
  whole.append(prefix, 5);
  if (len > 0) whole.append(payload, len);
  st.send_queue.append(std::move(whole));
  DrainSendQueueLocked(st, stream_id, out);
}

// lock held
void H2Session::DrainSendQueueLocked(Stream& st, uint32_t stream_id,
                                     butil::IOBuf* out) {
  while (!st.send_queue.empty()) {
    const int64_t window = conn_send_window_ < st.send_window
                               ? conn_send_window_
                               : st.send_window;
    if (window <= 0) return;
    size_t n = st.send_queue.size();
    if ((int64_t)n > window) n = (size_t)window;
    if (n > peer_max_frame_) n = peer_max_frame_;
    butil::IOBuf chunk;
    st.send_queue.cutn(&chunk, n);
    char hdr[9];
    put_frame_header(hdr, (uint32_t)n, FT_DATA, 0, stream_id);
    out->append(hdr, 9);
    out->append(std::move(chunk));
    conn_send_window_ -= (int64_t)n;
    st.send_window -= (int64_t)n;
  }
  if (st.send_queue.empty() && st.trailers_queued) {
    out->append(st.queued_trailers);
    st.queued_trailers.clear();
    st.trailers_queued = false;
    st.closed_local = true;
    if (st.end_received) MarkDeadLocked(stream_id);
  }
}

bool H2Session::RespondUnary(uint32_t stream_id, int grpc_status,
                             const char* grpc_message,
                             size_t grpc_message_len, const void* payload,
                             size_t payload_len, const char* const* extra_kv,
                             size_t n_extra) {
  butil::IOBuf out;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    Stream* st = FindStream(stream_id);
    if (st == nullptr || st->closed_local) return false;
    if (grpc_status != 0 && !st->resp_headers_sent) {
      // trailers-only response: one HEADERS frame, END_STREAM
      std::string block;
      block.append(ok_response_headers_block());
      encode_trailers(&block, grpc_status, grpc_message, grpc_message_len,
                      extra_kv, n_extra);
      append_frame(&out, FT_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                   stream_id, block.data(), block.size());
      st->closed_local = true;
    } else {
      if (!st->resp_headers_sent) {
        const std::string& block = ok_response_headers_block();
        append_frame(&out, FT_HEADERS, FLAG_END_HEADERS, stream_id,
                     block.data(), block.size());
        st->resp_headers_sent = true;
      }
      AppendData(&out, *st, stream_id, payload, payload_len, 0);
      std::string tblock;
      encode_trailers(&tblock, grpc_status, grpc_message, grpc_message_len,
                      extra_kv, n_extra);
      if (st->send_queue.empty() && !st->trailers_queued) {
        append_frame(&out, FT_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                     stream_id, tblock.data(), tblock.size());
        st->closed_local = true;
      } else {
        butil::IOBuf tb;
        append_frame(&tb, FT_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                     stream_id, tblock.data(), tblock.size());
        st->queued_trailers = tb.to_string();
        st->trailers_queued = true;
      }
    }
    if (st->closed_local && st->end_received) MarkDeadLocked(stream_id);
  }
  g_native_responses.fetch_add(1, std::memory_order_relaxed);
  return WriteOut(std::move(out));
}

bool H2Session::SendResponseHeaders(uint32_t stream_id,
                                    const char* const* extra_kv,
                                    size_t n_extra) {
  butil::IOBuf out;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    Stream* st = FindStream(stream_id);
    if (st == nullptr || st->closed_local || st->resp_headers_sent)
      return false;
    std::string block;
    encode_response_headers(&block, extra_kv, n_extra);
    append_frame(&out, FT_HEADERS, FLAG_END_HEADERS, stream_id, block.data(),
                 block.size());
    st->resp_headers_sent = true;
  }
  return WriteOut(std::move(out));
}

bool H2Session::SendGrpcMessage(uint32_t stream_id, const void* payload,
                                size_t len, uint8_t mflags) {
  butil::IOBuf out;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    Stream* st = FindStream(stream_id);
    if (st == nullptr || st->closed_local) return false;
    if (!st->resp_headers_sent) {
      const std::string& block = ok_response_headers_block();
      append_frame(&out, FT_HEADERS, FLAG_END_HEADERS, stream_id,
                   block.data(), block.size());
      st->resp_headers_sent = true;
    }
    AppendData(&out, *st, stream_id, payload, len, mflags);
  }
  return WriteOut(std::move(out));
}

bool H2Session::SendTrailers(uint32_t stream_id, int grpc_status,
                             const char* grpc_message,
                             size_t grpc_message_len,
                             const char* const* extra_kv, size_t n_extra) {
  butil::IOBuf out;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    Stream* st = FindStream(stream_id);
    if (st == nullptr || st->closed_local) return false;
    std::string tblock;
    if (!st->resp_headers_sent) {
      // no messages were sent: degenerate to trailers-only
      tblock.append(ok_response_headers_block());
      st->resp_headers_sent = true;
    }
    encode_trailers(&tblock, grpc_status, grpc_message, grpc_message_len,
                    extra_kv, n_extra);
    if (st->send_queue.empty() && !st->trailers_queued) {
      append_frame(&out, FT_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                   stream_id, tblock.data(), tblock.size());
      st->closed_local = true;
    } else {
      butil::IOBuf tb;
      append_frame(&tb, FT_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                   stream_id, tblock.data(), tblock.size());
      st->queued_trailers = tb.to_string();
      st->trailers_queued = true;
    }
    if (st->closed_local && st->end_received) MarkDeadLocked(stream_id);
  }
  g_native_responses.fetch_add(1, std::memory_order_relaxed);
  return WriteOut(std::move(out));
}

// ---------------------------------------------------------------------------
// receive side (dispatch thread)
// ---------------------------------------------------------------------------

void H2Session::MaybeSendInitialFrames() {
  if (sent_initial_) return;
  sent_initial_ = true;
  butil::IOBuf out;
  char s[12];
  s[0] = 0;
  s[1] = (char)SET_INITIAL_WINDOW_SIZE;
  s[2] = (char)(kInitialStreamWindow >> 24);
  s[3] = (char)(kInitialStreamWindow >> 16);
  s[4] = (char)(kInitialStreamWindow >> 8);
  s[5] = (char)kInitialStreamWindow;
  s[6] = 0;
  s[7] = (char)SET_MAX_CONCURRENT_STREAMS;
  s[8] = (char)(kMaxStreams >> 24);
  s[9] = (char)(kMaxStreams >> 16);
  s[10] = (char)(kMaxStreams >> 8);
  s[11] = (char)kMaxStreams;
  append_frame(&out, FT_SETTINGS, 0, 0, s, sizeof(s));
  // the connection window starts at 64KB and only WINDOW_UPDATE raises
  // it: top it up immediately so clients never stall on upload
  append_window_update(&out, 0, 16 * 1024 * 1024);
  WriteOut(std::move(out));
}

void H2Session::WriteRst(uint32_t stream_id, uint32_t error_code) {
  butil::IOBuf out;
  char p[4] = {(char)(error_code >> 24), (char)(error_code >> 16),
               (char)(error_code >> 8), (char)error_code};
  append_frame(&out, FT_RST_STREAM, 0, stream_id, p, 4);
  WriteOut(std::move(out));
}

void H2Session::WriteGoaway(uint32_t error_code) {
  if (goaway_sent_) return;
  goaway_sent_ = true;
  butil::IOBuf out;
  char p[8];
  p[0] = (char)(last_stream_id_ >> 24);
  p[1] = (char)(last_stream_id_ >> 16);
  p[2] = (char)(last_stream_id_ >> 8);
  p[3] = (char)last_stream_id_;
  p[4] = (char)(error_code >> 24);
  p[5] = (char)(error_code >> 16);
  p[6] = (char)(error_code >> 8);
  p[7] = (char)error_code;
  append_frame(&out, FT_GOAWAY, 0, 0, p, 8);
  WriteOut(std::move(out));
}

bool H2Session::OnSettings(uint8_t flags, const uint8_t* p, size_t n) {
  if (flags & FLAG_ACK) return n == 0;
  if (n % 6 != 0) return false;
  butil::IOBuf drained;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    for (size_t off = 0; off + 6 <= n; off += 6) {
      const uint16_t id = (uint16_t)((p[off] << 8) | p[off + 1]);
      const uint32_t val = rd32(p + off + 2);
      switch (id) {
        case SET_INITIAL_WINDOW_SIZE: {
          if (val > 0x7fffffffu) return false;  // FLOW_CONTROL_ERROR
          const int64_t delta = (int64_t)val - peer_initial_window_;
          peer_initial_window_ = val;
          for (auto& kv : streams_) kv.second.send_window += delta;
          if (delta > 0) {
            // RFC 7540 §6.9.2: a window made positive by SETTINGS must
            // resume blocked senders, exactly like WINDOW_UPDATE
            for (auto& kv : streams_)
              DrainSendQueueLocked(kv.second, kv.first, &drained);
          }
          break;
        }
        case SET_MAX_FRAME_SIZE:
          if (val < 16384 || val > 16777215) return false;
          peer_max_frame_ = val;
          break;
        default:
          break;  // HEADER_TABLE_SIZE etc: our encoder is stateless
      }
    }
  }
  butil::IOBuf out;
  append_frame(&out, FT_SETTINGS, FLAG_ACK, 0, nullptr, 0);
  out.append(std::move(drained));
  WriteOut(std::move(out));
  return true;
}

bool H2Session::OnWindowUpdate(uint32_t stream_id, const uint8_t* p,
                               size_t n) {
  if (n != 4) return false;
  const uint32_t inc = rd32(p) & 0x7fffffffu;
  if (inc == 0) return false;
  butil::IOBuf out;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    if (stream_id == 0) {
      conn_send_window_ += inc;
      if (conn_send_window_ > 0x7fffffffll) return false;
      // credit may unblock any stream's queue
      for (auto& kv : streams_)
        DrainSendQueueLocked(kv.second, kv.first, &out);
    } else {
      Stream* st = FindStream(stream_id);
      if (st != nullptr) {
        st->send_window += inc;
        DrainSendQueueLocked(*st, stream_id, &out);
      }
    }
  }
  return WriteOut(std::move(out));
}

// Track consumed DATA bytes and top up the peer's view of our windows.
void H2Session::SendConnWindowUpdates(uint32_t stream_id, Stream* st,
                                      size_t bytes) {
  conn_recv_consumed_ += (int64_t)bytes;
  butil::IOBuf out;
  if (conn_recv_consumed_ >= kConnWindowTopup) {
    append_window_update(&out, 0, (uint32_t)conn_recv_consumed_);
    conn_recv_consumed_ = 0;
  }
  if (st != nullptr && !st->end_received) {
    st->recv_consumed += (int64_t)bytes;
    if (st->recv_consumed >= kStreamWindowTopup) {
      append_window_update(&out, stream_id, (uint32_t)st->recv_consumed);
      st->recv_consumed = 0;
    }
  }
  WriteOut(std::move(out));
}

bool H2Session::OnHeadersPayload(uint32_t stream_id, uint8_t flags,
                                 const uint8_t* p, size_t n) {
  // strip padding / priority
  if (flags & FLAG_PADDED) {
    if (n < 1) return false;
    const uint8_t pad = p[0];
    ++p;
    --n;
    if (pad > n) return false;
    n -= pad;
  }
  if (flags & FLAG_PRIORITY) {
    if (n < 5) return false;
    p += 5;
    n -= 5;
  }
  // the block budget applies to a single END_HEADERS frame too — the
  // parser admits frames far larger than the budget, and an unbounded
  // block is a memory-amplification hole (the Python plane's
  // OUR_MAX_FRAME guard, rpc/h2.py)
  if (n > kMaxHeaderBlock) return false;
  header_block_.assign((const char*)p, n);
  cont_stream_ = stream_id;
  cont_flags_ = flags;
  in_headers_ = true;
  if (flags & FLAG_END_HEADERS) return FinishHeaderBlock();
  return true;
}

bool H2Session::FinishHeaderBlock() {
  in_headers_ = false;
  const uint32_t stream_id = cont_stream_;
  std::vector<Header> headers;
  if (!hpack_.Decode((const uint8_t*)header_block_.data(),
                     header_block_.size(), &headers)) {
    header_block_.clear();
    return false;  // COMPRESSION_ERROR: connection dies
  }
  header_block_.clear();

  bool exists;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    exists = FindStream(stream_id) != nullptr;
  }
  if (exists) {
    // trailers on an open request stream: gRPC clients don't send
    // these; accept only as an end-of-stream marker
    if (cont_flags_ & FLAG_END_STREAM)
      return OnData(stream_id, FLAG_END_STREAM, butil::IOBuf());
    WriteRst(stream_id, EC_PROTOCOL_ERROR);
    return true;
  }
  if ((stream_id & 1) == 0 || stream_id <= last_stream_id_) return false;
  bool live_streaming = false;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    if (streams_.size() >= kMaxStreams) {
      WriteRst(stream_id, EC_REFUSED_STREAM);
      return true;
    }
    last_stream_id_ = stream_id;
    Stream st;
    st.send_window = peer_initial_window_;
    for (const Header& h : headers) {
      // a request marked bidi must dispatch at HEADERS time (the
      // handler consumes messages while responding) — holding its
      // first message for the unary decision would deadlock it
      if (h.name == "grpc-bidi" && h.value == "1") live_streaming = true;
      if (h.name == ":path") {
        // "/pkg.Service/Method"
        const std::string& path = h.value;
        const size_t slash = path.rfind('/');
        if (!path.empty() && path[0] == '/' && slash > 0) {
          st.service = path.substr(1, slash - 1);
          st.method = path.substr(slash + 1);
        }
      }
      // expose pseudo headers the bridge routes on plus every regular
      // header (metadata, authorization, grpc-encoding, grpc-timeout)
      if (h.name.empty()) continue;
      if (h.name[0] == ':' && h.name != ":path" && h.name != ":method" &&
          h.name != ":authority")
        continue;
      st.headers_flat.append(h.name);
      st.headers_flat.push_back('\0');
      st.headers_flat.append(h.value);
      st.headers_flat.push_back('\0');
    }
    st.headers_done = true;
    if (live_streaming) {
      st.streaming = true;
      st.delivered = true;
    }
    streams_.emplace(stream_id, std::move(st));
  }
  if (live_streaming) {
    Stream* st2;
    {
      std::lock_guard<std::mutex> lk(send_mu_);
      st2 = FindStream(stream_id);
    }
    if (st2 != nullptr) {
      auto* ev = new PendingH2Event{sid_, stream_id, H2_EV_HEADERS, 0,
                                    st2->service, st2->method,
                                    st2->headers_flat, nullptr};
      Socket* s = Socket::Address(sid_);
      if (s == nullptr) {
        delete ev;
        return false;
      }
      const bool ok = s->FifoSubmit(run_h2_event_task, ev, 256);
      s->Dereference();
      if (!ok) return false;
    }
  }
  if (cont_flags_ & FLAG_END_STREAM)
    return OnData(stream_id, FLAG_END_STREAM, butil::IOBuf());
  return true;
}

// Extract complete gRPC messages from st.data.  Streaming requests get
// incremental MESSAGE events; the first message of a
// not-yet-classified stream is HELD so a request that turns out to be
// unary (END_STREAM right after one message) costs ONE Python upcall.
bool H2Session::DeliverMessages(Stream& st, uint32_t stream_id) {
  std::vector<std::pair<butil::IOBuf, uint8_t>> msgs;
  bool went_streaming = false;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    while (st.data.size() >= 5) {
      char pfx[5];
      st.data.copy_to(pfx, 5, 0);
      const uint32_t mlen = rd32((const uint8_t*)pfx + 1);
      if (mlen > kMaxGrpcMessage) return false;
      if (st.data.size() < 5 + (size_t)mlen) break;
      st.data.pop_front(5);
      butil::IOBuf msg;
      st.data.cutn(&msg, mlen);
      msgs.emplace_back(std::move(msg), (uint8_t)pfx[0]);
    }
    if (msgs.empty()) return true;
    if (!st.streaming) {
      if (!st.have_first && msgs.size() == 1 && st.data.empty()) {
        // single complete message on an open stream: unary candidate
        st.first_msg = std::move(msgs[0].first);
        st.first_flags = msgs[0].second;
        st.have_first = true;
        return true;
      }
      // a second message (or bytes behind the first): streaming request
      st.streaming = true;
      went_streaming = true;
      if (st.have_first) {
        msgs.emplace(msgs.begin(), std::move(st.first_msg), st.first_flags);
        st.first_msg.clear();
        st.have_first = false;
      }
    }
  }
  Socket* s = Socket::Address(sid_);
  if (s == nullptr) return false;
  bool ok = true;
  if (went_streaming && !st.delivered) {
    st.delivered = true;
    auto* ev = new PendingH2Event{sid_, stream_id, H2_EV_HEADERS, 0,
                                  st.service, st.method, st.headers_flat,
                                  nullptr};
    ok = s->FifoSubmit(run_h2_event_task, ev, 256);
    if (!ok) {
      delete ev;
    }
  }
  for (auto& m : msgs) {
    if (!ok) break;
    auto* ev = new PendingH2Event{
        sid_, stream_id, H2_EV_MESSAGE, (int)m.second, std::string(),
        std::string(), std::string(), new butil::IOBuf(std::move(m.first))};
    ok = s->FifoSubmit(run_h2_event_task, ev, event_bytes(ev->body->size()));
    if (!ok) {
      delete ev->body;
      delete ev;
    }
  }
  s->Dereference();
  return ok;
}

void H2Session::DispatchNative(Stream& st, uint32_t stream_id,
                               butil::IOBuf&& message, int mflags) {
  MethodRegistry::Entry e;
  bool found = MethodRegistry::global()->Lookup(
      st.service.data(), st.service.size(), st.method.data(),
      st.method.size(), &e);
  if (!found) {
    const size_t dot = st.service.rfind('.');
    if (dot != std::string::npos) {
      // gRPC paths carry package-qualified names; the registry may hold
      // the bare service name (mirrors server.py invoke_grpc fallback)
      found = MethodRegistry::global()->Lookup(
          st.service.data() + dot + 1, st.service.size() - dot - 1,
          st.method.data(), st.method.size(), &e);
    }
  }
  if (found && e.fn != nullptr) {
    g_native_requests.fetch_add(1, std::memory_order_relaxed);
    if (e.inline_run) {
      butil::IOBuf resp;
      const int32_t rc = e.fn(sid_, &message, &resp, e.user);
      std::string flat = resp.to_string();
      if (rc == 0) {
        RespondUnary(stream_id, 0, nullptr, 0, flat.data(), flat.size(),
                     nullptr, 0);
      } else {
        RespondUnary(stream_id, 2, "native handler error", 20, nullptr, 0,
                     nullptr, 0);
      }
    } else {
      auto* p = new PendingH2Native{sid_, stream_id, e, std::move(message)};
      bthread::Executor::global()->submit(run_h2_native_task, p);
    }
    return;
  }
  // Python-owned (registered python method, unknown service, non-gRPC
  // h2 request): surface the whole unary request in ONE event
  if (g_event_cb.load(std::memory_order_acquire) == nullptr) {
    RespondUnary(stream_id, 12, "unimplemented", 13, nullptr, 0, nullptr, 0);
    return;
  }
  auto* ev = new PendingH2Event{
      sid_, stream_id, H2_EV_UNARY, mflags, st.service,
      st.method, st.headers_flat, new butil::IOBuf(std::move(message))};
  Socket* s = Socket::Address(sid_);
  if (s == nullptr) {
    delete ev->body;
    delete ev;
    return;
  }
  if (!s->FifoSubmit(run_h2_event_task, ev,
                     event_bytes(ev->body->size()))) {
    // socket failed; nothing left to respond to
  }
  s->Dereference();
}

// The request half closed: dispatch (unary) or emit END (streaming).
void H2Session::DeliverTerminal(Stream& st, uint32_t stream_id) {
  bool unary = false;
  butil::IOBuf message;
  int mflags = -1;  // -1 = request ended with NO message (the bridge
                    // must tell an absent message from one empty one)
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    if (!st.streaming) {
      unary = true;
      st.delivered = true;
      if (st.have_first) {
        message = std::move(st.first_msg);
        st.first_msg.clear();
        mflags = st.first_flags;
        st.have_first = false;
      }
    }
  }
  if (unary) {
    DispatchNative(st, stream_id, std::move(message), mflags);
    return;
  }
  auto* ev = new PendingH2Event{sid_,          stream_id,     H2_EV_END, 0,
                                std::string(), std::string(), std::string(),
                                nullptr};
  Socket* s = Socket::Address(sid_);
  if (s == nullptr) {
    delete ev;
    return;
  }
  s->FifoSubmit(run_h2_event_task, ev, 256);
  s->Dereference();
}

bool H2Session::OnData(uint32_t stream_id, uint8_t flags,
                       butil::IOBuf&& payload) {
  Stream* st;
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    st = FindStream(stream_id);
  }
  // flow control counts the whole payload, padding included
  const size_t flow_bytes = payload.size();
  if (st == nullptr) {
    // closed/unknown stream (e.g. reaped after reset): account the
    // connection window so the peer's credit view stays consistent
    if (flow_bytes > 0) SendConnWindowUpdates(stream_id, nullptr, flow_bytes);
    return true;
  }
  if (st->end_received) return false;  // DATA after END_STREAM
  if (flags & FLAG_PADDED) {
    if (payload.size() < 1) return false;
    char padc;
    payload.copy_to(&padc, 1, 0);
    const uint8_t pad = (uint8_t)padc;
    payload.pop_front(1);
    if (pad > payload.size()) return false;
    payload.pop_back(pad);
  }
  {
    std::lock_guard<std::mutex> lk(send_mu_);
    st->data.append(std::move(payload));
    if (st->data.size() > kMaxGrpcMessage + 5) return false;
  }
  if (!DeliverMessages(*st, stream_id)) return false;
  if (flow_bytes > 0) SendConnWindowUpdates(stream_id, st, flow_bytes);
  if (flags & FLAG_END_STREAM) {
    bool already_closed;
    {
      std::lock_guard<std::mutex> lk(send_mu_);
      st->end_received = true;
      already_closed = st->closed_local;
      if (already_closed) MarkDeadLocked(stream_id);
    }
    if (!already_closed) DeliverTerminal(*st, stream_id);
  }
  return true;
}

bool H2Session::OnFrames(const char* meta, size_t meta_len,
                         butil::IOBuf* body) {
  ReapDeadStreams();
  MaybeSendInitialFrames();
  size_t off = 0;
  while (off + 9 <= meta_len) {
    const uint8_t* h = (const uint8_t*)meta + off;
    const uint32_t len =
        ((uint32_t)h[0] << 16) | ((uint32_t)h[1] << 8) | h[2];
    const uint8_t type = h[3];
    const uint8_t flags = h[4];
    const uint32_t stream_id = rd32(h + 5) & 0x7fffffffu;
    off += 9;
    butil::IOBuf payload;
    if (len > 0) {
      if (body->size() < len) return false;  // H2Accum contract broken
      body->cutn(&payload, len);
    }
    // CONTINUATION must directly follow its HEADERS frame
    if (in_headers_ && type != FT_CONTINUATION) {
      WriteGoaway(EC_PROTOCOL_ERROR);
      return false;
    }
    bool ok = true;
    switch (type) {
      case FT_DATA:
        ok = OnData(stream_id, flags, std::move(payload));
        break;
      case FT_HEADERS: {
        std::string flat = payload.to_string();
        ok = stream_id != 0 &&
             OnHeadersPayload(stream_id, flags, (const uint8_t*)flat.data(),
                              flat.size());
        break;
      }
      case FT_CONTINUATION: {
        if (!in_headers_ || stream_id != cont_stream_) {
          ok = false;
          break;
        }
        std::string flat = payload.to_string();
        header_block_.append(flat);
        if (header_block_.size() > kMaxHeaderBlock) {
          ok = false;
          break;
        }
        if (flags & FLAG_END_HEADERS) ok = FinishHeaderBlock();
        break;
      }
      case FT_SETTINGS: {
        std::string flat = payload.to_string();
        ok = stream_id == 0 &&
             OnSettings(flags, (const uint8_t*)flat.data(), flat.size());
        break;
      }
      case FT_WINDOW_UPDATE: {
        std::string flat = payload.to_string();
        ok = OnWindowUpdate(stream_id, (const uint8_t*)flat.data(),
                            flat.size());
        break;
      }
      case FT_PING: {
        if (len != 8 || stream_id != 0) {
          ok = false;
          break;
        }
        if (!(flags & FLAG_ACK)) {
          std::string flat = payload.to_string();
          butil::IOBuf out;
          append_frame(&out, FT_PING, FLAG_ACK, 0, flat.data(), flat.size());
          WriteOut(std::move(out));
        }
        break;
      }
      case FT_RST_STREAM: {
        if (len != 4 || stream_id == 0) {
          ok = false;
          break;
        }
        bool notify = false;
        {
          std::lock_guard<std::mutex> lk(send_mu_);
          Stream* st = FindStream(stream_id);
          if (st != nullptr) {
            notify = st->delivered && st->streaming;
            st->closed_local = true;
            st->end_received = true;
            MarkDeadLocked(stream_id);
          }
        }
        if (notify) {
          auto* ev = new PendingH2Event{sid_, stream_id, H2_EV_RESET, 0,
                                        std::string(), std::string(),
                                        std::string(), nullptr};
          Socket* s = Socket::Address(sid_);
          if (s != nullptr) {
            if (!s->FifoSubmit(run_h2_event_task, ev, 256)) delete ev;
            s->Dereference();
          } else {
            delete ev;
          }
        }
        break;
      }
      case FT_GOAWAY:
      case FT_PRIORITY:
      case FT_PUSH_PROMISE:  // clients must not push; tolerate + ignore
      default:
        break;  // unknown frame types are ignored per RFC 7540 §4.1
    }
    if (!ok) {
      BLOG(WARNING,
           "h2 fatal frame: type=%u flags=%u stream=%u len=%u",
           (unsigned)type, (unsigned)flags, (unsigned)stream_id,
           (unsigned)len);
      WriteGoaway(EC_PROTOCOL_ERROR);
      return false;
    }
  }
  return off == meta_len && body->empty();
}

// ---------------------------------------------------------------------------
// sid-addressed helpers
// ---------------------------------------------------------------------------

#define H2_SID_FORWARD(expr)                  \
  Socket* s = Socket::Address(sid);           \
  if (s == nullptr) return false;             \
  H2Session* sess = s->h2_session();          \
  if (sess == nullptr) {                      \
    s->Dereference();                         \
    return false;                             \
  }                                           \
  const bool rc = (expr);                     \
  s->Dereference();                           \
  return rc

bool H2RespondUnary(SocketId sid, uint32_t stream_id, int grpc_status,
                    const char* grpc_message, size_t grpc_message_len,
                    const void* payload, size_t payload_len,
                    const char* const* extra_kv, size_t n_extra) {
  H2_SID_FORWARD(sess->RespondUnary(stream_id, grpc_status, grpc_message,
                                    grpc_message_len, payload, payload_len,
                                    extra_kv, n_extra));
}

bool H2SendResponseHeaders(SocketId sid, uint32_t stream_id,
                           const char* const* extra_kv, size_t n_extra) {
  H2_SID_FORWARD(sess->SendResponseHeaders(stream_id, extra_kv, n_extra));
}

bool H2SendGrpcMessage(SocketId sid, uint32_t stream_id, const void* payload,
                       size_t len, uint8_t mflags) {
  H2_SID_FORWARD(sess->SendGrpcMessage(stream_id, payload, len, mflags));
}

bool H2SendTrailers(SocketId sid, uint32_t stream_id, int grpc_status,
                    const char* grpc_message, size_t grpc_message_len,
                    const char* const* extra_kv, size_t n_extra) {
  H2_SID_FORWARD(sess->SendTrailers(stream_id, grpc_status, grpc_message,
                                    grpc_message_len, extra_kv, n_extra));
}

}  // namespace h2
}  // namespace brpc
