// Native HTTP/2 + gRPC server data plane.
//
// Reference: src/brpc/policy/http2_rpc_protocol.cpp (SURVEY.md §2.4) — the
// reference parses h2 frames, HPACK and gRPC framing natively and only
// surfaces whole requests to service code.  Our round-4 plane was pure
// Python (brpc_tpu/rpc/h2.py, ~9k qps with native frame coalescing); this
// module moves the per-frame work — frame state machine, HPACK, flow
// control, gRPC message framing, response packing — into C++.  Python is
// upcalled once per MESSAGE (or once per unary REQUEST), not per frame,
// and natively-registered methods never surface to Python at all.
//
// Threading: OnFrames() runs only on the socket's dispatch thread (frames
// of one connection are inherently ordered).  Send-side state (windows,
// pending response data) is guarded by a mutex because Python handler
// threads respond concurrently.  The Python h2 client (h2.py GrpcChannel)
// is unchanged — this is the server role.
#pragma once

#include <cstddef>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "butil/iobuf.h"
#include "net/hpack.h"

namespace brpc {

typedef uint64_t SocketId;
class Socket;

namespace h2 {

// ---- events surfaced to the Python bridge ----
//
// UNARY: a complete one-message request (the hot path — one upcall).
// HEADERS/MESSAGE/END: a streaming request, surfaced incrementally so
// bidi handlers can consume while responding.  RESET: the stream (or
// whole connection) died; the bridge cancels the handler.
enum EventKind {
  H2_EV_UNARY = 0,
  H2_EV_HEADERS = 1,
  H2_EV_MESSAGE = 2,
  H2_EV_END = 3,
  H2_EV_RESET = 4,
};

// headers: concatenated "name\0value\0" pairs (non-pseudo headers).
// body ownership passes to the callee (may be nullptr for no-body
// events).  mflags: gRPC message flag byte (bit 0 = compressed) for
// UNARY/MESSAGE events.
typedef void (*H2EventCallback)(SocketId sid, uint32_t stream_id, int kind,
                                const char* service, size_t service_len,
                                const char* method, size_t method_len,
                                const char* headers, size_t headers_len,
                                butil::IOBuf* body, int mflags, void* user);

void SetH2EventCallback(H2EventCallback cb, void* user);

// ---- counters (exported on /ici-style console pages) ----
int64_t h2_native_requests();   // requests dispatched by native sessions
int64_t h2_native_responses();  // responses packed natively
int64_t h2_python_events();     // events surfaced to the Python bridge

class H2Session {
 public:
  explicit H2Session(SocketId sid) : sid_(sid) {}

  // Feed a coalesced run of complete h2 frames (meta = concatenated
  // 9-byte headers, body = payloads in order — the exact shape
  // Socket::DispatchMessages' H2Accum builds).  Dispatch-thread only.
  // Returns false on a fatal connection error (caller closes).
  // Connection failure cleanup is the Python bridge's job: it already
  // receives the socket-failed notification and cancels live streams.
  bool OnFrames(const char* meta, size_t meta_len, butil::IOBuf* body);

  // ---- response paths (any thread; sid-addressed helpers below) ----

  // One-shot unary response: HEADERS + DATA(grpc frame) + trailers in a
  // single write.  grpc_status != 0 sends trailers-only (no DATA).
  bool RespondUnary(uint32_t stream_id, int grpc_status,
                    const char* grpc_message, size_t grpc_message_len,
                    const void* payload, size_t payload_len,
                    const char* const* extra_kv, size_t n_extra);

  // Streaming response: headers once, then messages, then trailers.
  bool SendResponseHeaders(uint32_t stream_id, const char* const* extra_kv,
                           size_t n_extra);
  bool SendGrpcMessage(uint32_t stream_id, const void* payload, size_t len,
                       uint8_t mflags);
  bool SendTrailers(uint32_t stream_id, int grpc_status,
                    const char* grpc_message, size_t grpc_message_len,
                    const char* const* extra_kv, size_t n_extra);

 private:
  struct Stream {
    std::string service;
    std::string method;
    std::string headers_flat;  // "name\0value\0" pairs
    butil::IOBuf data;         // undelivered DATA bytes (gRPC framing)
    butil::IOBuf first_msg;    // first complete message, pending the
    uint8_t first_flags = 0;   // unary-vs-streaming decision
    bool have_first = false;
    bool streaming = false;    // python saw H2_EV_HEADERS
    bool headers_done = false;
    bool end_received = false;
    bool delivered = false;    // terminal event sent to python/native
    // send side (guarded by session send mutex)
    int64_t send_window;
    bool resp_headers_sent = false;
    bool closed_local = false;
    int64_t recv_consumed = 0;  // stream-level WINDOW_UPDATE accounting
    butil::IOBuf send_queue;    // DATA bytes waiting for window credit
    bool trailers_queued = false;
    std::string queued_trailers;  // encoded trailer HEADERS frame
  };

  // frame handlers (dispatch thread)
  bool OnHeadersPayload(uint32_t stream_id, uint8_t flags,
                        const uint8_t* p, size_t n);
  bool OnData(uint32_t stream_id, uint8_t flags, butil::IOBuf&& payload);
  bool OnSettings(uint8_t flags, const uint8_t* p, size_t n);
  bool OnWindowUpdate(uint32_t stream_id, const uint8_t* p, size_t n);
  bool FinishHeaderBlock();
  bool DeliverMessages(Stream& st, uint32_t stream_id);
  void DeliverTerminal(Stream& st, uint32_t stream_id);
  // mflags: the request message's gRPC flag byte, or -1 when the
  // request ended with no message at all
  void DispatchNative(Stream& st, uint32_t stream_id,
                      butil::IOBuf&& message, int mflags);
  void MaybeSendInitialFrames();
  void SendConnWindowUpdates(uint32_t stream_id, Stream* st, size_t bytes);
  void WriteRst(uint32_t stream_id, uint32_t error_code);
  void WriteGoaway(uint32_t error_code);
  // deferred stream reaping: response threads mark, the dispatch thread
  // erases (a direct erase could invalidate a Stream& the dispatch
  // thread still holds)
  void MarkDeadLocked(uint32_t stream_id);
  void ReapDeadStreams();

  // send helpers (any thread; lock held by caller where noted)
  bool WriteOut(butil::IOBuf&& out);
  void AppendData(butil::IOBuf* out, Stream& st, uint32_t stream_id,
                  const void* payload, size_t len,
                  uint8_t mflags);  // lock held
  void DrainSendQueueLocked(Stream& st, uint32_t stream_id,
                            butil::IOBuf* out);
  Stream* FindStream(uint32_t stream_id);

  SocketId sid_;
  HpackDecoder hpack_;
  std::unordered_map<uint32_t, Stream> streams_;
  std::vector<uint32_t> dead_streams_;  // guarded by send_mu_
  uint32_t last_stream_id_ = 0;
  // CONTINUATION accumulation
  std::string header_block_;
  uint32_t cont_stream_ = 0;
  uint8_t cont_flags_ = 0;
  bool in_headers_ = false;
  bool sent_initial_ = false;
  bool goaway_sent_ = false;
  int64_t conn_recv_consumed_ = 0;
  // peer-controlled send parameters
  std::mutex send_mu_;
  int64_t conn_send_window_ = 65535;
  int64_t peer_initial_window_ = 65535;
  uint32_t peer_max_frame_ = 16384;
  // budgets (mirror rpc/h2.py bounds)
  static constexpr size_t kMaxHeaderBlock = 256 * 1024;
  // per-message bound: generous (the Python plane bounds decompression
  // expansion, not raw size — tests echo 72MB payloads); the flow
  // control windows bound per-connection memory growth rate
  static constexpr size_t kMaxGrpcMessage = 256 * 1024 * 1024;
  static constexpr size_t kMaxStreams = 1024;
  static constexpr int64_t kConnWindowTopup = 8 * 1024 * 1024;
  static constexpr int64_t kStreamWindowTopup = 1 * 1024 * 1024;
  static constexpr uint32_t kInitialStreamWindow = 4 * 1024 * 1024;
};

// sid-addressed response helpers for the C API / Python bridge: resolve
// the socket, take its session, forward.  Safe on dead sockets (no-op
// false).
bool H2RespondUnary(SocketId sid, uint32_t stream_id, int grpc_status,
                    const char* grpc_message, size_t grpc_message_len,
                    const void* payload, size_t payload_len,
                    const char* const* extra_kv, size_t n_extra);
bool H2SendResponseHeaders(SocketId sid, uint32_t stream_id,
                           const char* const* extra_kv, size_t n_extra);
bool H2SendGrpcMessage(SocketId sid, uint32_t stream_id, const void* payload,
                       size_t len, uint8_t mflags);
bool H2SendTrailers(SocketId sid, uint32_t stream_id, int grpc_status,
                    const char* grpc_message, size_t grpc_message_len,
                    const char* const* extra_kv, size_t n_extra);

}  // namespace h2
}  // namespace brpc
