// Native HPACK codec (see hpack.h).  Clean-room from RFC 7541; tables
// generated from the Python codec (tools/gen_hpack_tables.py).
#include "net/hpack.h"

#include <cstring>
#include <mutex>

namespace brpc {
namespace h2 {

struct StaticEntry {
  const char* name;
  const char* value;
};
struct HuffCode {
  uint32_t code;
  uint8_t bits;
};

#include "net/hpack_tables.inc"

// ---- integers ----

bool DecodeInt(const uint8_t** p, const uint8_t* end, uint8_t prefix_mask,
               uint64_t* out) {
  if (*p >= end) return false;
  uint64_t v = **p & prefix_mask;
  ++*p;
  if (v < prefix_mask) {
    *out = v;
    return true;
  }
  int shift = 0;
  while (true) {
    if (*p >= end || shift > 28) return false;  // > 2^32: reject
    const uint8_t b = **p;
    ++*p;
    v += (uint64_t)(b & 0x7f) << shift;
    if ((b & 0x80) == 0) break;
    shift += 7;
  }
  if (v > 0xffffffffull) return false;
  *out = v;
  return true;
}

void EncodeInt(std::string* out, uint8_t first, uint8_t prefix_mask,
               uint64_t v) {
  if (v < prefix_mask) {
    out->push_back((char)(first | v));
    return;
  }
  out->push_back((char)(first | prefix_mask));
  v -= prefix_mask;
  while (v >= 0x80) {
    out->push_back((char)(0x80 | (v & 0x7f)));
    v >>= 7;
  }
  out->push_back((char)v);
}

// ---- Huffman decode: binary trie built once from the code table ----

namespace {

struct HuffNode {
  int16_t child[2];  // node index, or -1
  int16_t sym;       // decoded symbol, or -1
};

// 257 codes, <= 30 bits each => < 2*257*30 nodes; 8192 is generous.
static HuffNode g_huff_nodes[8192];
static int g_huff_node_count = 0;
static std::once_flag g_huff_once;

void BuildHuffTrie() {
  g_huff_node_count = 1;
  g_huff_nodes[0] = {{-1, -1}, -1};
  for (int sym = 0; sym < 257; ++sym) {
    const uint32_t code = kHuffTable[sym].code;
    const int bits = kHuffTable[sym].bits;
    int node = 0;
    for (int i = bits - 1; i >= 0; --i) {
      const int b = (code >> i) & 1;
      int16_t next = g_huff_nodes[node].child[b];
      if (next < 0) {
        next = (int16_t)g_huff_node_count++;
        g_huff_nodes[next] = {{-1, -1}, -1};
        g_huff_nodes[node].child[b] = next;
      }
      node = next;
    }
    g_huff_nodes[node].sym = (int16_t)sym;
  }
}

}  // namespace

bool HuffmanDecode(const uint8_t* p, size_t n, std::string* out) {
  std::call_once(g_huff_once, BuildHuffTrie);
  int node = 0;
  int depth = 0;       // bits consumed since the last emitted symbol
  bool all_ones = true;  // those bits were all 1s (valid padding prefix)
  out->reserve(out->size() + n * 2);
  for (size_t i = 0; i < n; ++i) {
    const uint8_t byte = p[i];
    for (int bit = 7; bit >= 0; --bit) {
      const int b = (byte >> bit) & 1;
      const int16_t next = g_huff_nodes[node].child[b];
      if (next < 0) return false;  // invalid code
      node = next;
      ++depth;
      all_ones = all_ones && (b == 1);
      const int16_t sym = g_huff_nodes[node].sym;
      if (sym >= 0) {
        if (sym == 256) return false;  // EOS in the data is an error
        out->push_back((char)sym);
        node = 0;
        depth = 0;
        all_ones = true;
      }
    }
  }
  // trailing bits must be a prefix of EOS (all ones), < 8 bits
  return depth < 8 && all_ones;
}

// ---- decoder ----

bool HpackDecoder::ReadString(const uint8_t** p, const uint8_t* end,
                              std::string* out) {
  if (*p >= end) return false;
  const bool huff = (**p & 0x80) != 0;
  uint64_t len;
  if (!DecodeInt(p, end, 0x7f, &len)) return false;
  if (len > (uint64_t)(end - *p)) return false;
  if (huff) {
    if (!HuffmanDecode(*p, (size_t)len, out)) return false;
  } else {
    out->append((const char*)*p, (size_t)len);
  }
  *p += len;
  return true;
}

bool HpackDecoder::LookupIndex(uint64_t idx, Header* out) const {
  if (idx == 0) return false;
  if (idx <= 61) {
    out->name = kStaticTable[idx - 1].name;
    out->value = kStaticTable[idx - 1].value;
    return true;
  }
  const uint64_t di = idx - 62;
  if (di >= dyn_.size()) return false;
  out->name = dyn_[di].name;
  out->value = dyn_[di].value;
  return true;
}

void HpackDecoder::EvictTo(size_t limit) {
  while (size_ > limit && !dyn_.empty()) {
    size_ -= dyn_.back().name.size() + dyn_.back().value.size() + 32;
    dyn_.pop_back();
  }
}

void HpackDecoder::Insert(std::string name, std::string value) {
  const size_t esz = name.size() + value.size() + 32;
  if (esz > cap_) {  // larger than the table: clears it (RFC §4.4)
    EvictTo(0);
    return;
  }
  EvictTo(cap_ - esz);
  dyn_.push_front(Entry{std::move(name), std::move(value)});
  size_ += esz;
}

bool HpackDecoder::Decode(const uint8_t* p, size_t n,
                          std::vector<Header>* out, size_t max_decoded) {
  const uint8_t* end = p + n;
  size_t decoded = 0;
  const auto charge = [&decoded, max_decoded](const Header& h) {
    decoded += h.name.size() + h.value.size() + 32;
    return decoded <= max_decoded;
  };
  while (p < end) {
    const uint8_t b = *p;
    if (b & 0x80) {
      // indexed field
      uint64_t idx;
      if (!DecodeInt(&p, end, 0x7f, &idx)) return false;
      Header h;
      if (!LookupIndex(idx, &h)) return false;
      if (!charge(h)) return false;
      out->push_back(std::move(h));
    } else if (b & 0x40) {
      // literal with incremental indexing
      uint64_t idx;
      if (!DecodeInt(&p, end, 0x3f, &idx)) return false;
      Header h;
      if (idx != 0) {
        if (!LookupIndex(idx, &h)) return false;
        h.value.clear();
      } else if (!ReadString(&p, end, &h.name)) {
        return false;
      }
      if (!ReadString(&p, end, &h.value)) return false;
      if (!charge(h)) return false;
      Insert(h.name, h.value);
      out->push_back(std::move(h));
    } else if (b & 0x20) {
      // dynamic table size update
      uint64_t sz;
      if (!DecodeInt(&p, end, 0x1f, &sz)) return false;
      if (sz > cap_limit_) return false;
      cap_ = (size_t)sz;
      EvictTo(cap_);
    } else {
      // literal without indexing (0x00) / never indexed (0x10)
      uint64_t idx;
      if (!DecodeInt(&p, end, 0x0f, &idx)) return false;
      Header h;
      if (idx != 0) {
        if (!LookupIndex(idx, &h)) return false;
        h.value.clear();
      } else if (!ReadString(&p, end, &h.name)) {
        return false;
      }
      if (!ReadString(&p, end, &h.value)) return false;
      if (!charge(h)) return false;
      out->push_back(std::move(h));
    }
  }
  return true;
}

// ---- encoder ----

namespace {

// (name, value) -> static index for the pairs worth matching on the
// response path; name -> first static index for name-only refs.
int StaticPairIndex(const char* name, size_t nl, const char* value,
                    size_t vl) {
  for (int i = 0; i < 61; ++i) {
    const StaticEntry& e = kStaticTable[i];
    if (std::strlen(e.name) == nl && std::memcmp(e.name, name, nl) == 0 &&
        std::strlen(e.value) == vl && std::memcmp(e.value, value, vl) == 0)
      return i + 1;
  }
  return 0;
}

int StaticNameIndex(const char* name, size_t nl) {
  for (int i = 0; i < 61; ++i) {
    const StaticEntry& e = kStaticTable[i];
    if (std::strlen(e.name) == nl && std::memcmp(e.name, name, nl) == 0)
      return i + 1;
  }
  return 0;
}

}  // namespace

void EncodeHeader(std::string* out, const char* name, size_t name_len,
                  const char* value, size_t value_len) {
  const int pair = StaticPairIndex(name, name_len, value, value_len);
  if (pair > 0) {
    EncodeInt(out, 0x80, 0x7f, (uint64_t)pair);
    return;
  }
  const int nidx = StaticNameIndex(name, name_len);
  // literal without indexing
  EncodeInt(out, 0x00, 0x0f, (uint64_t)nidx);
  if (nidx == 0) {
    EncodeInt(out, 0x00, 0x7f, name_len);  // no Huffman
    out->append(name, name_len);
  }
  EncodeInt(out, 0x00, 0x7f, value_len);
  out->append(value, value_len);
}

}  // namespace h2
}  // namespace brpc
