// Native HPACK (RFC 7541) — the h2 data plane's header codec.
//
// Reference: src/brpc/details/hpack.cpp (SURVEY.md §2.4) implements the
// same RFC natively for its h2 protocol; this is a clean-room build from
// the RFC.  The Python codec (brpc_tpu/rpc/hpack.py) remains the client
// side and the fallback; the wire-spec tables are generated from it
// (hpack_tables.inc) so the two can never drift.
//
// Decoder: full RFC — static + dynamic table, incremental indexing,
// table-size updates, Huffman-coded strings.
// Encoder: stateless strategy (static-table refs + literals without
// indexing, no Huffman) — legal HPACK any peer must accept, and it keeps
// response encoding lock-free across threads.
#pragma once

#include <cstddef>
#include <cstdint>
#include <deque>
#include <string>
#include <vector>

namespace brpc {
namespace h2 {

struct Header {
  std::string name;
  std::string value;
};

// ---- integer primitives (RFC 7541 §5.1) ----

// Decode an integer with an N-bit prefix starting at *p (the prefix bits
// of **p are masked by the caller via `prefix_mask`).  Advances *p past
// the integer.  Returns false on truncation/overflow (> 2^32).
bool DecodeInt(const uint8_t** p, const uint8_t* end, uint8_t prefix_mask,
               uint64_t* out);

// Append an integer with an N-bit prefix; `first` carries the pattern
// bits above the prefix (e.g. 0x80 for an indexed field).
void EncodeInt(std::string* out, uint8_t first, uint8_t prefix_mask,
               uint64_t v);

// ---- Huffman (RFC 7541 §5.2, Appendix B) ----

// Decode `n` Huffman bytes into *out.  Returns false on an invalid
// code, embedded EOS, or padding longer than 7 bits / not all-ones.
bool HuffmanDecode(const uint8_t* p, size_t n, std::string* out);

// ---- decoder ----

class HpackDecoder {
 public:
  explicit HpackDecoder(size_t max_table = 4096)
      : cap_limit_(max_table), cap_(max_table) {}

  // Decode one complete header block.  Appends to *out.  Returns false
  // on any malformed input (the connection must then die, RFC 7540 §4.3
  // COMPRESSION_ERROR — dynamic-table state is unrecoverable) or when
  // the DECODED size exceeds `max_decoded` bytes — indexed fields
  // expand (1 wire byte -> a full dynamic-table entry), so bounding the
  // input block alone still allows ~4000x memory amplification.
  bool Decode(const uint8_t* p, size_t n, std::vector<Header>* out,
              size_t max_decoded = 4 * 1024 * 1024);

  size_t dynamic_size() const { return size_; }

 private:
  struct Entry {
    std::string name;
    std::string value;
  };
  bool LookupIndex(uint64_t idx, Header* out) const;
  void Insert(std::string name, std::string value);
  void EvictTo(size_t limit);
  static bool ReadString(const uint8_t** p, const uint8_t* end,
                         std::string* out);

  std::deque<Entry> dyn_;  // front = most recent (index 62)
  size_t size_ = 0;        // RFC size: sum(name+value+32)
  size_t cap_limit_;       // SETTINGS_HEADER_TABLE_SIZE we advertised
  size_t cap_;             // current cap (<= cap_limit_, set by updates)
};

// ---- encoder (stateless) ----

// Append one header field: indexed when (name, value) is in the static
// table, literal-without-indexing (static name ref when possible)
// otherwise.  Never touches dynamic state — safe concurrently.
void EncodeHeader(std::string* out, const char* name, size_t name_len,
                  const char* value, size_t value_len);

inline void EncodeHeader(std::string* out, const std::string& name,
                         const std::string& value) {
  EncodeHeader(out, name.data(), name.size(), value.data(), value.size());
}

}  // namespace h2
}  // namespace brpc
