#include "net/parser.h"

#include <cstring>

namespace brpc {

size_t g_max_body_size = (size_t)2 * 1024 * 1024 * 1024;

static uint32_t load_be32(const char* p) {
  return ((uint32_t)(uint8_t)p[0] << 24) | ((uint32_t)(uint8_t)p[1] << 16) |
         ((uint32_t)(uint8_t)p[2] << 8) | (uint32_t)(uint8_t)p[3];
}

static uint64_t load_be64(const char* p) {
  return ((uint64_t)load_be32(p) << 32) | load_be32(p + 4);
}

static void store_be32(char* p, uint32_t v) {
  p[0] = (char)(v >> 24);
  p[1] = (char)(v >> 16);
  p[2] = (char)(v >> 8);
  p[3] = (char)v;
}

void make_trpc_header(char out[16], uint32_t meta_size, uint64_t body_size) {
  memcpy(out, kTrpcMagic, 4);
  store_be32(out + 4, meta_size);
  store_be32(out + 8, (uint32_t)(body_size >> 32));
  store_be32(out + 12, (uint32_t)body_size);
}

static bool looks_like_http(const char* p, size_t n) {
  // Methods the console/RESTful layer accepts, plus response lines.
  static const char* kTokens[] = {"GET ",  "POST ",   "PUT ",  "DELETE ",
                                  "HEAD ", "OPTIONS ", "PATCH ", "HTTP/1."};
  for (const char* t : kTokens) {
    const size_t tl = strlen(t);
    if (n >= tl && memcmp(p, t, tl) == 0) return true;
    if (n < tl && memcmp(p, t, n) == 0) return true;  // maybe, need more
  }
  return false;
}

static ParseResult parse_http(butil::IOBuf* in, ParseState* st,
                              ParsedMessage* out) {
  // Copy up to 64KB of header zone to scan for CRLFCRLF; console traffic is
  // small so the copy is fine (the TRPC hot path never comes here).
  if (st->http_header_end == 0) {
    const size_t scan = in->size() < 65536 ? in->size() : 65536;
    std::string hdr;
    hdr.resize(scan);
    in->copy_to(hdr.data(), scan, 0);
    const size_t pos = hdr.find("\r\n\r\n");
    if (pos == std::string::npos) {
      if (in->size() > 65536) return PARSE_ERROR;  // header too large
      return PARSE_NEED_MORE;
    }
    st->http_header_end = pos + 4;
    // Walk header lines properly: a substring scan would match inside
    // e.g. "X-Content-Length" and mis-frame the stream.
    st->http_body_len = 0;
    std::string lower = hdr.substr(0, pos + 4);
    for (auto& c : lower) c = (char)tolower(c);
    size_t line = lower.find("\r\n");  // skip request/status line
    while (line != std::string::npos && line + 2 < lower.size()) {
      const size_t start = line + 2;
      const size_t end = lower.find("\r\n", start);
      if (end == std::string::npos || end == start) break;
      const size_t colon = lower.find(':', start);
      if (colon != std::string::npos && colon < end) {
        std::string key = lower.substr(start, colon - start);
        // trim trailing spaces from key, leading spaces from value
        while (!key.empty() && (key.back() == ' ' || key.back() == '\t'))
          key.pop_back();
        size_t vs = colon + 1;
        while (vs < end && (lower[vs] == ' ' || lower[vs] == '\t')) ++vs;
        const std::string val = lower.substr(vs, end - vs);
        if (key == "content-length") {
          st->http_body_len = atoll(val.c_str());
          if (st->http_body_len < 0 ||
              (size_t)st->http_body_len > g_max_body_size)
            return PARSE_ERROR;
        } else if (key == "transfer-encoding" &&
                   val.find("chunked") != std::string::npos) {
          return PARSE_ERROR;  // chunked unsupported in the native core
        }
      }
      line = end;
    }
  }
  const size_t total = st->http_header_end + (size_t)st->http_body_len;
  if (in->size() < total) return PARSE_NEED_MORE;
  out->kind = MSG_HTTP;
  out->meta.clear();
  in->cutn(&out->body, total);
  st->http_header_end = 0;
  st->http_body_len = -1;
  return PARSE_OK;
}

// ---- RESP (redis serialization protocol, reference policy/redis_protocol
// .cpp + redis_reply.cpp) -------------------------------------------------
//
// Completeness scan over the IOBuf without copying bulk bodies: header lines
// are read through a small window, $N bodies are skipped arithmetically.

// Reads one CRLF-terminated line starting at *off.  On success stores the
// line (without CRLF) and advances *off past the CRLF.
static ParseResult resp_read_line(const butil::IOBuf& in, size_t* off,
                                  std::string* line) {
  char buf[256];
  size_t pos = *off;
  line->clear();
  while (pos < in.size()) {
    const size_t n = in.copy_to(buf, sizeof(buf), pos);
    for (size_t i = 0; i < n; ++i) {
      if (buf[i] == '\n') {
        if (line->empty() && i == 0) return PARSE_ERROR;
        // strip the '\r' (it may be the last char of the previous chunk)
        line->append(buf, i);
        if (line->empty() || line->back() != '\r') return PARSE_ERROR;
        line->pop_back();
        *off = pos + i + 1;
        return PARSE_OK;
      }
    }
    line->append(buf, n);
    if (line->size() > 65536) return PARSE_ERROR;  // redis line limit
    pos += n;
  }
  return PARSE_NEED_MORE;
}

// Scans one complete RESP value starting at offset 0; sets *end past it.
static ParseResult resp_scan(const butil::IOBuf& in, size_t* end) {
  size_t off = 0;
  std::string line;
  // stack of remaining element counts for nested arrays
  int64_t stack[32];
  int depth = 0;
  stack[depth] = 1;
  while (depth >= 0) {
    if (stack[depth] == 0) {
      --depth;
      continue;
    }
    const ParseResult r = resp_read_line(in, &off, &line);
    if (r != PARSE_OK) return r;
    if (line.empty()) return PARSE_ERROR;
    const char t = line[0];
    if (t == '+' || t == '-' || t == ':') {
      --stack[depth];
    } else if (t == '$') {
      const long long n = atoll(line.c_str() + 1);
      if (n > (long long)g_max_body_size) return PARSE_ERROR;
      if (n >= 0) {
        const size_t body_end = off + (size_t)n + 2;
        if (in.size() < body_end) return PARSE_NEED_MORE;
        off = body_end;
      }
      --stack[depth];
    } else if (t == '*') {
      const long long n = atoll(line.c_str() + 1);
      --stack[depth];
      if (n > 0) {
        if (depth + 1 >= (int)(sizeof(stack) / sizeof(stack[0])))
          return PARSE_ERROR;  // nesting too deep
        stack[++depth] = n;
      }
    } else {
      return PARSE_ERROR;
    }
  }
  *end = off;
  return PARSE_OK;
}

static ParseResult parse_redis(butil::IOBuf* in, ParsedMessage* out) {
  size_t end = 0;
  const ParseResult r = resp_scan(*in, &end);
  if (r != PARSE_OK) return r;
  out->kind = MSG_REDIS;
  out->meta.clear();
  out->body.clear();
  in->cutn(&out->body, end);
  return PARSE_OK;
}

static bool looks_like_redis(char c) {
  return c == '*' || c == '+' || c == '-' || c == ':' || c == '$';
}

ParseResult parse_message(butil::IOBuf* in, ParseState* st, ParsedMessage* out) {
  if (in->empty()) return PARSE_NEED_MORE;
  if (st->detected == MSG_HTTP) return parse_http(in, st, out);
  if (st->detected == MSG_REDIS) return parse_redis(in, out);

  char hdr[kTrpcHeaderLen];
  const size_t got = in->copy_to(hdr, kTrpcHeaderLen, 0);
  if (memcmp(hdr, kTrpcMagic, got < 4 ? got : 4) != 0) {
    // Not TRPC: try-next-protocol (input_messenger.cpp:144-160 pattern).
    if (looks_like_redis(hdr[0])) {
      st->detected = MSG_REDIS;
      return parse_redis(in, out);
    }
    if (looks_like_http(hdr, got)) {
      st->detected = MSG_HTTP;
      return parse_http(in, st, out);
    }
    return PARSE_ERROR;
  }
  if (got < kTrpcHeaderLen) return PARSE_NEED_MORE;
  const uint32_t meta_size = load_be32(hdr + 4);
  const uint64_t body_size = load_be64(hdr + 8);
  if (meta_size > kMaxMetaSize || body_size > g_max_body_size)
    return PARSE_ERROR;
  const uint64_t total = kTrpcHeaderLen + meta_size + body_size;
  if (in->size() < total) return PARSE_NEED_MORE;
  in->pop_front(kTrpcHeaderLen);
  out->kind = MSG_TRPC;
  out->meta.resize(meta_size);
  in->cutn(out->meta.data(), meta_size);
  out->body.clear();
  in->cutn(&out->body, body_size);
  return PARSE_OK;
}

}  // namespace brpc
