#include "net/parser.h"

#include <cstring>

namespace brpc {

size_t g_max_body_size = (size_t)2 * 1024 * 1024 * 1024;

static uint32_t load_be32(const char* p) {
  return ((uint32_t)(uint8_t)p[0] << 24) | ((uint32_t)(uint8_t)p[1] << 16) |
         ((uint32_t)(uint8_t)p[2] << 8) | (uint32_t)(uint8_t)p[3];
}

static uint64_t load_be64(const char* p) {
  return ((uint64_t)load_be32(p) << 32) | load_be32(p + 4);
}

static void store_be32(char* p, uint32_t v) {
  p[0] = (char)(v >> 24);
  p[1] = (char)(v >> 16);
  p[2] = (char)(v >> 8);
  p[3] = (char)v;
}

void make_trpc_header(char out[16], uint32_t meta_size, uint64_t body_size) {
  memcpy(out, kTrpcMagic, 4);
  store_be32(out + 4, meta_size);
  store_be32(out + 8, (uint32_t)(body_size >> 32));
  store_be32(out + 12, (uint32_t)body_size);
}

static uint32_t load_le32(const char* p) {
  return ((uint32_t)(uint8_t)p[3] << 24) | ((uint32_t)(uint8_t)p[2] << 16) |
         ((uint32_t)(uint8_t)p[1] << 8) | (uint32_t)(uint8_t)p[0];
}

static bool looks_like_http(const char* p, size_t n) {
  // Methods the console/RESTful layer accepts, plus response lines.
  static const char* kTokens[] = {"GET ",  "POST ",   "PUT ",  "DELETE ",
                                  "HEAD ", "OPTIONS ", "PATCH ", "HTTP/1."};
  for (const char* t : kTokens) {
    const size_t tl = strlen(t);
    if (n >= tl && memcmp(p, t, tl) == 0) return true;
    if (n < tl && memcmp(p, t, n) == 0) return true;  // maybe, need more
  }
  return false;
}

static ParseResult parse_http(butil::IOBuf* in, ParseState* st,
                              ParsedMessage* out) {
  // Copy up to 64KB of header zone to scan for CRLFCRLF; console traffic is
  // small so the copy is fine (the TRPC hot path never comes here).
  if (st->http_header_end == 0) {
    const size_t scan = in->size() < 65536 ? in->size() : 65536;
    std::string hdr;
    hdr.resize(scan);
    in->copy_to(hdr.data(), scan, 0);
    const size_t pos = hdr.find("\r\n\r\n");
    if (pos == std::string::npos) {
      if (in->size() > 65536) return PARSE_ERROR;  // header too large
      return PARSE_NEED_MORE;
    }
    st->http_header_end = pos + 4;
    // Walk header lines properly: a substring scan would match inside
    // e.g. "X-Content-Length" and mis-frame the stream.
    st->http_body_len = 0;
    std::string lower = hdr.substr(0, pos + 4);
    for (auto& c : lower) c = (char)tolower(c);
    size_t line = lower.find("\r\n");  // skip request/status line
    while (line != std::string::npos && line + 2 < lower.size()) {
      const size_t start = line + 2;
      const size_t end = lower.find("\r\n", start);
      if (end == std::string::npos || end == start) break;
      const size_t colon = lower.find(':', start);
      if (colon != std::string::npos && colon < end) {
        std::string key = lower.substr(start, colon - start);
        // trim trailing spaces from key, leading spaces from value
        while (!key.empty() && (key.back() == ' ' || key.back() == '\t'))
          key.pop_back();
        size_t vs = colon + 1;
        while (vs < end && (lower[vs] == ' ' || lower[vs] == '\t')) ++vs;
        const std::string val = lower.substr(vs, end - vs);
        if (key == "content-length") {
          st->http_body_len = atoll(val.c_str());
          if (st->http_body_len < 0 ||
              (size_t)st->http_body_len > g_max_body_size)
            return PARSE_ERROR;
        } else if (key == "transfer-encoding" &&
                   val.find("chunked") != std::string::npos) {
          st->http_body_len = -2;  // chunked: scan chunk sizes below
        }
      }
      line = end;
    }
  }
  size_t total;
  if (st->http_body_len == -2) {
    // Chunked body: walk "SIZE\r\n" + data + "\r\n" until the 0-chunk,
    // then consume trailers up to the final CRLF.  The whole message
    // (headers + raw chunked body) is delivered; Python de-chunks.
    // Scan resumes at http_chunk_off so incremental arrival costs O(n),
    // not O(n^2), on the dispatcher thread.
    if (st->http_chunk_off < st->http_header_end)
      st->http_chunk_off = st->http_header_end;
    // http_chunk_off always points at the START of a chunk-size line; it
    // only advances past fully-buffered chunks, so resuming re-reads at
    // most one size line + the trailers (never chunk payload as a size).
    size_t off = st->http_chunk_off;
    char win[4096];  // size line incl. chunk extensions must fit
    while (true) {
      const size_t line_start = off;
      const size_t n = in->copy_to(win, sizeof(win), off);
      size_t i = 0;
      // parse hex size up to ';' or CR
      long long v = 0;
      bool any = false;
      for (; i < n; ++i) {
        const char c = win[i];
        int d;
        if (c >= '0' && c <= '9') d = c - '0';
        else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
        else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
        else break;
        v = v * 16 + d;
        any = true;
        if (v > (long long)g_max_body_size) return PARSE_ERROR;
      }
      if (!any) return (n < 1) ? PARSE_NEED_MORE : PARSE_ERROR;
      // skip chunk extensions to CRLF
      while (i < n && win[i] != '\n') ++i;
      if (i >= n) return (n == sizeof(win)) ? PARSE_ERROR : PARSE_NEED_MORE;
      const long long sz = v;
      off += i + 1;
      if (sz == 0) {
        // trailers: consume lines until empty line
        while (true) {
          char tw[4096];
          const size_t tn = in->copy_to(tw, sizeof(tw), off);
          size_t j = 0;
          while (j < tn && tw[j] != '\n') ++j;
          if (j >= tn)
            return (tn == sizeof(tw)) ? PARSE_ERROR : PARSE_NEED_MORE;
          const bool empty_line = (j == 0) || (j == 1 && tw[0] == '\r');
          off += j + 1;
          if (empty_line) break;
        }
        total = off;
        break;
      }
      off += (size_t)sz + 2;  // data + CRLF
      if (off > g_max_body_size) return PARSE_ERROR;  // cumulative cap
      if (in->size() < off) {
        st->http_chunk_off = line_start;  // resume at this size line
        return PARSE_NEED_MORE;
      }
      st->http_chunk_off = off;  // chunk fully buffered; next size line
    }
    if (in->size() < total) return PARSE_NEED_MORE;
  } else {
    total = st->http_header_end + (size_t)st->http_body_len;
  }
  if (in->size() < total) return PARSE_NEED_MORE;
  out->kind = MSG_HTTP;
  out->meta.clear();
  in->cutn(&out->body, total);
  st->http_header_end = 0;
  st->http_body_len = -1;
  st->http_chunk_off = 0;
  return PARSE_OK;
}

// ---- RESP (redis serialization protocol, reference policy/redis_protocol
// .cpp + redis_reply.cpp) -------------------------------------------------
//
// Completeness scan over the IOBuf without copying bulk bodies: header lines
// are read through a small window, $N bodies are skipped arithmetically.

// Reads one CRLF-terminated line at the iterator.  On success stores the
// line (without CRLF) and leaves the iterator past the LF.  The iterator
// (IOBufBytesIterator, a cached-span cursor) makes the whole scan
// O(total bytes); the previous copy_to(pos)-per-line version re-walked
// the ref chain from the start for every line — quadratic over a large
// pipelined batch spanning many blocks.
static ParseResult resp_read_line(butil::IOBufBytesIterator& it,
                                  std::string* line) {
  line->clear();
  while (it.bytes_left() > 0) {
    const char c = *it;
    ++it;
    if (c == '\n') {
      if (line->empty() || line->back() != '\r') return PARSE_ERROR;
      line->pop_back();
      return PARSE_OK;
    }
    line->push_back(c);
    if (line->size() > 65536) return PARSE_ERROR;  // redis line limit
  }
  return PARSE_NEED_MORE;
}

// Scans one complete RESP value starting at offset 0; sets *end past it.
static ParseResult resp_scan(const butil::IOBuf& in, size_t* end) {
  butil::IOBufBytesIterator it(in);
  std::string line;
  // stack of remaining element counts for nested arrays
  int64_t stack[32];
  int depth = 0;
  stack[depth] = 1;
  while (depth >= 0) {
    if (stack[depth] == 0) {
      --depth;
      continue;
    }
    const ParseResult r = resp_read_line(it, &line);
    if (r != PARSE_OK) return r;
    if (line.empty()) return PARSE_ERROR;
    const char t = line[0];
    if (t == '+' || t == '-' || t == ':') {
      --stack[depth];
    } else if (t == '$') {
      const long long n = atoll(line.c_str() + 1);
      if (n > (long long)g_max_body_size) return PARSE_ERROR;
      if (n >= 0) {
        const size_t body = (size_t)n + 2;  // payload + CRLF
        if (it.bytes_left() < body) return PARSE_NEED_MORE;
        it.forward(body);
      }
      --stack[depth];
    } else if (t == '*') {
      const long long n = atoll(line.c_str() + 1);
      --stack[depth];
      if (n > 0) {
        if (depth + 1 >= (int)(sizeof(stack) / sizeof(stack[0])))
          return PARSE_ERROR;  // nesting too deep
        stack[++depth] = n;
      }
    } else {
      return PARSE_ERROR;
    }
  }
  *end = in.size() - it.bytes_left();
  return PARSE_OK;
}

static ParseResult parse_redis(butil::IOBuf* in, ParsedMessage* out) {
  size_t end = 0;
  const ParseResult r = resp_scan(*in, &end);
  if (r != PARSE_OK) return r;
  out->kind = MSG_REDIS;
  out->meta.clear();
  out->body.clear();
  in->cutn(&out->body, end);
  return PARSE_OK;
}

static bool looks_like_redis(char c) {
  return c == '*' || c == '+' || c == '-' || c == ':' || c == '$';
}

// ---- memcache binary (reference policy/memcache_binary_protocol.cpp):
// 24-byte header, total body length big-endian at offset 8. -----------------
static ParseResult parse_memcache(butil::IOBuf* in, ParsedMessage* out) {
  char hdr[24];
  if (in->copy_to(hdr, 24, 0) < 24) return PARSE_NEED_MORE;
  if ((uint8_t)hdr[0] != 0x80 && (uint8_t)hdr[0] != 0x81) return PARSE_ERROR;
  const uint32_t body = load_be32(hdr + 8);
  if (body > g_max_body_size) return PARSE_ERROR;
  const size_t total = 24 + (size_t)body;
  if (in->size() < total) return PARSE_NEED_MORE;
  out->kind = MSG_MEMCACHE;
  out->meta.clear();
  out->body.clear();
  in->cutn(&out->body, total);
  return PARSE_OK;
}

// ---- framed thrift (reference policy/thrift_protocol.cpp): u32be length +
// TBinaryProtocol payload starting 0x80 0x01. ------------------------------
static ParseResult parse_thrift(butil::IOBuf* in, ParsedMessage* out) {
  char hdr[6];
  if (in->copy_to(hdr, 6, 0) < 6) return PARSE_NEED_MORE;
  const uint32_t len = load_be32(hdr);
  if ((uint8_t)hdr[4] != 0x80 || (uint8_t)hdr[5] != 0x01) return PARSE_ERROR;
  if (len > g_max_body_size || len < 2) return PARSE_ERROR;
  const size_t total = 4 + (size_t)len;
  if (in->size() < total) return PARSE_NEED_MORE;
  in->pop_front(4);
  out->kind = MSG_THRIFT;
  out->meta.clear();
  out->body.clear();
  in->cutn(&out->body, len);
  return PARSE_OK;
}

// ---- mongo wire (reference policy/mongo_protocol.cpp): 16-byte LE header
// {messageLength, requestID, responseTo, opCode}. --------------------------
static bool mongo_known_opcode(uint32_t op) {
  return op == 1 /*OP_REPLY*/ || op == 2004 /*OP_QUERY*/ ||
         op == 2010 /*OP_COMMAND*/ || op == 2011 /*OP_COMMANDREPLY*/ ||
         op == 2012 /*OP_COMPRESSED*/ || op == 2013 /*OP_MSG*/;
}

static ParseResult parse_mongo(butil::IOBuf* in, ParsedMessage* out) {
  char hdr[16];
  if (in->copy_to(hdr, 16, 0) < 16) return PARSE_NEED_MORE;
  const uint32_t len = load_le32(hdr);
  const uint32_t op = load_le32(hdr + 12);
  if (!mongo_known_opcode(op) || len < 16 || len > g_max_body_size)
    return PARSE_ERROR;
  if (in->size() < len) return PARSE_NEED_MORE;
  out->kind = MSG_MONGO;
  out->meta.clear();
  out->body.clear();
  in->cutn(&out->body, len);
  return PARSE_OK;
}

// ---- nshead (reference policy/nshead_protocol.cpp): 36-byte LE header with
// magic 0xfb709394 at offset 24, body_len at offset 32. --------------------
static constexpr uint32_t kNsheadMagic = 0xfb709394u;

static ParseResult parse_nshead(butil::IOBuf* in, ParsedMessage* out) {
  char hdr[36];
  if (in->copy_to(hdr, 36, 0) < 36) return PARSE_NEED_MORE;
  if (load_le32(hdr + 24) != kNsheadMagic) return PARSE_ERROR;
  const uint32_t body = load_le32(hdr + 32);
  if (body > g_max_body_size) return PARSE_ERROR;
  const size_t total = 36 + (size_t)body;
  if (in->size() < total) return PARSE_NEED_MORE;
  out->kind = MSG_NSHEAD;
  out->meta.assign(hdr, 36);
  in->pop_front(36);
  out->body.clear();
  in->cutn(&out->body, body);
  return PARSE_OK;
}

// ---- HTTP/2 (reference policy/http2_rpc_protocol.cpp): 24-byte client
// preface then 9-byte-header frames; each frame is one message with the
// header in meta. ----------------------------------------------------------
static const char kH2Preface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
static constexpr size_t kH2PrefaceLen = 24;
static constexpr size_t kH2MaxFrame = 16 * 1024 * 1024;

static ParseResult parse_h2(butil::IOBuf* in, ParseState* st,
                            ParsedMessage* out) {
  if (!st->h2_preface_done) {
    char pre[kH2PrefaceLen];
    const size_t got = in->copy_to(pre, kH2PrefaceLen, 0);
    const size_t cmp = got < 4 ? got : 4;
    if (memcmp(pre, "PRI ", cmp) == 0) {
      // Looks like (a prefix of) the client preface; don't commit to
      // frame mode until enough bytes arrive to be sure.
      if (got < kH2PrefaceLen) return PARSE_NEED_MORE;
      if (memcmp(pre, kH2Preface, kH2PrefaceLen) != 0) return PARSE_ERROR;
      in->pop_front(kH2PrefaceLen);
    }
    // Server-to-client traffic (and post-preface frames) have no preface.
    st->h2_preface_done = true;
  }
  char hdr[9];
  if (in->copy_to(hdr, 9, 0) < 9) return PARSE_NEED_MORE;
  const uint32_t len = ((uint32_t)(uint8_t)hdr[0] << 16) |
                       ((uint32_t)(uint8_t)hdr[1] << 8) | (uint8_t)hdr[2];
  if (len > kH2MaxFrame) return PARSE_ERROR;
  const size_t total = 9 + (size_t)len;
  if (in->size() < total) return PARSE_NEED_MORE;
  out->kind = MSG_H2;
  out->meta.assign(hdr, 9);
  in->pop_front(9);
  out->body.clear();
  in->cutn(&out->body, len);
  return PARSE_OK;
}

static ParseResult parse_raw(butil::IOBuf* in, ParsedMessage* out) {
  out->kind = MSG_RAW;
  out->meta.clear();
  out->body.clear();
  in->cutn(&out->body, in->size());
  return PARSE_OK;
}

ParseResult parse_message(butil::IOBuf* in, ParseState* st, ParsedMessage* out) {
  if (in->empty()) return PARSE_NEED_MORE;
  switch (st->detected) {
    case MSG_HTTP: return parse_http(in, st, out);
    case MSG_REDIS: return parse_redis(in, out);
    case MSG_MEMCACHE: return parse_memcache(in, out);
    case MSG_THRIFT: return parse_thrift(in, out);
    case MSG_MONGO: return parse_mongo(in, out);
    case MSG_H2: return parse_h2(in, st, out);
    case MSG_RAW: return parse_raw(in, out);
    case MSG_NSHEAD: return parse_nshead(in, out);
    default: break;
  }

  char hdr[kTrpcHeaderLen];
  const size_t got = in->copy_to(hdr, kTrpcHeaderLen, 0);
  if (memcmp(hdr, kTrpcMagic, got < 4 ? got : 4) != 0) {
    // Not TRPC: try-next-protocol (input_messenger.cpp:144-160 pattern).
    if (got >= 4 && memcmp(hdr, "PRI ", 4) == 0) {
      st->detected = MSG_H2;
      return parse_h2(in, st, out);
    }
    // nshead's magic sits at offset 24; when enough bytes are buffered,
    // check it before the single-byte detectors (an nshead id whose low
    // byte happens to be '*', 'G', 0x80, … would otherwise misdetect as
    // redis/http/memcache).  A magic's 2^-32 false-positive rate against
    // binary redis payloads is far below the ASCII-collision rate of
    // nshead ids.  If an nshead header trickles in fewer than 28 bytes at
    // a time AND its id low byte collides, the single-byte detector wins —
    // same inherent ambiguity the reference resolves by try-order
    // (input_messenger.cpp:144-160).
    {
      char nh[28];
      if (in->copy_to(nh, 28, 0) >= 28 &&
          load_le32(nh + 24) == kNsheadMagic) {
        st->detected = MSG_NSHEAD;
        return parse_nshead(in, out);
      }
    }
    // Mongo before the single-byte detectors: its 16-byte header check
    // (known LE opcode at offset 12 + plausible length) is a far stronger
    // signal than redis'/memcache's first-byte match, and a mongo
    // messageLength whose low byte is 0x24 ('$'), 0x2A ('*'), 0x80 … would
    // otherwise be latched as redis/memcache.  With fewer than 16 bytes
    // buffered the weak detectors still win — the reference's inherent
    // try-order ambiguity (input_messenger.cpp:144-160).
    if (got >= 16) {
      const uint32_t mongo_op = load_le32(hdr + 12);
      if (mongo_known_opcode(mongo_op) && load_le32(hdr) >= 16) {
        if (in->size() < 28) {
          const uint32_t mg_total = load_le32(hdr);  // includes header
          if (in->size() < mg_total) return PARSE_NEED_MORE;
        }
        st->detected = MSG_MONGO;
        return parse_mongo(in, out);
      }
    }
    if (looks_like_redis(hdr[0])) {
      st->detected = MSG_REDIS;
      return parse_redis(in, out);
    }
    if (got < 4 && memcmp(hdr, "PRI ", got) == 0) {
      // 'P'/'PR'/'PRI' could become either the h2 preface or POST/PUT/
      // PATCH — don't let the HTTP prefix-match below latch MSG_HTTP
      // until 4 bytes distinguish them.
      return PARSE_NEED_MORE;
    }
    if (looks_like_http(hdr, got)) {
      st->detected = MSG_HTTP;
      return parse_http(in, st, out);
    }
    if ((uint8_t)hdr[0] == 0x80 || (uint8_t)hdr[0] == 0x81) {
      // Could still be nshead if fewer than 28 bytes have arrived.  Decide
      // memcache only once either (a) 28 bytes are here and the nshead
      // check above failed, or (b) the complete candidate memcache packet
      // is shorter than 28 bytes and fully buffered (it can never grow to
      // reveal nshead's magic).
      if (in->size() < 28) {
        char mh[12];
        if (in->copy_to(mh, 12, 0) < 12) return PARSE_NEED_MORE;
        const uint32_t mc_total = 24 + load_be32(mh + 8);
        if (in->size() < mc_total) return PARSE_NEED_MORE;
      }
      st->detected = MSG_MEMCACHE;
      return parse_memcache(in, out);
    }
    if (got >= 6 && (uint8_t)hdr[4] == 0x80 && (uint8_t)hdr[5] == 0x01) {
      // Same 28-byte nshead disambiguation window as memcache above: an
      // nshead whose log_id low bytes are 0x80 0x01 would otherwise be
      // latched as thrift and its id/version misread as a frame length.
      if (in->size() < 28) {
        const uint64_t th_total = 4 + (uint64_t)load_be32(hdr);
        if (in->size() < th_total) return PARSE_NEED_MORE;
      }
      st->detected = MSG_THRIFT;
      return parse_thrift(in, out);
    }
    // Fewer than 28 bytes can't yet rule out the longer-magic framings
    // (thrift @6, mongo @16, nshead @28) — same contract as the
    // reference's PARSE_ERROR_NOT_ENOUGH_DATA: wait rather than guess.
    // Short pure-garbage connections stay open until idle-close, exactly
    // like a half-sent frame would.
    if (in->size() < 28) return PARSE_NEED_MORE;
    return PARSE_ERROR;
  }
  if (got < kTrpcHeaderLen) return PARSE_NEED_MORE;
  // Magic matched: latch the protocol like every other detector so the
  // dispatch loop's in-place fast path (parse_trpc_view) can engage —
  // without this every TRPC frame re-ran detection AND the copying parse.
  st->detected = MSG_TRPC;
  const uint32_t meta_size = load_be32(hdr + 4);
  const uint64_t body_size = load_be64(hdr + 8);
  if (meta_size > kMaxMetaSize || body_size > g_max_body_size)
    return PARSE_ERROR;
  const uint64_t total = kTrpcHeaderLen + meta_size + body_size;
  if (in->size() < total) return PARSE_NEED_MORE;
  in->pop_front(kTrpcHeaderLen);
  out->kind = MSG_TRPC;
  out->meta.resize(meta_size);
  in->cutn(out->meta.data(), meta_size);
  out->body.clear();
  in->cutn(&out->body, body_size);
  return PARSE_OK;
}

ParseResult parse_trpc_peek(butil::IOBuf* in, const char** meta,
                            size_t* meta_len, const char** body,
                            uint64_t* body_size, uint64_t* total_len) {
  // ZERO-COPY, ZERO-REF peek: the common case has header+meta (and for
  // small frames the body too) contiguous in the read buffer's first
  // block (8KB blocks vs ~50B metas + ~100B bodies).  Nothing is
  // consumed and no block ref is taken — the bytes stay at the front of
  // `in` while the dispatch runs, so the views are naturally alive; the
  // caller pops after dispatch.  *meta == nullptr with PARSE_OK means
  // "not contiguous / not TRPC — use the generic parse_message".
  *meta = nullptr;
  *body = nullptr;
  if (in->size() < kTrpcHeaderLen) return PARSE_NEED_MORE;
  if (in->backing_block_num() == 0) return PARSE_NEED_MORE;
  const butil::BlockRef& r0 = in->backing_block(0);
  if ((size_t)r0.length < kTrpcHeaderLen) return PARSE_OK;   // split header
  const char* p = butil::iobuf::block_data(r0.block) + r0.offset;
  if (memcmp(p, kTrpcMagic, 4) != 0) return PARSE_OK;  // redetect/garbage
  const uint32_t msz = load_be32(p + 4);
  const uint64_t bsz = load_be64(p + 8);
  if (msz > kMaxMetaSize || bsz > g_max_body_size) return PARSE_ERROR;
  const uint64_t total = kTrpcHeaderLen + msz + bsz;
  if (in->size() < total) return PARSE_NEED_MORE;
  if ((uint64_t)r0.length < kTrpcHeaderLen + (uint64_t)msz)
    return PARSE_OK;                                   // meta split
  *meta = p + kTrpcHeaderLen;
  *meta_len = msz;
  *body_size = bsz;
  *total_len = total;
  if ((uint64_t)r0.length >= total) *body = p + kTrpcHeaderLen + msz;
  return PARSE_OK;
}

}  // namespace brpc
