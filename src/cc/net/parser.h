// Wire framing for the TPU-RPC socket core (SURVEY.md §2.4).
//
// The extension point in the reference is `struct Protocol` — a function
// table tried in order until one recognizes the bytes, which is how all
// protocols share one port (protocol.h:77-166, input_messenger.cpp:144-160).
// Our native core implements the same try-in-order scheme over the built-in
// framings, and hands *complete messages* (not bytes) upward; higher-level
// protocol semantics (method dispatch, JSON↔tensor mapping, redis RESP,
// HPACK, BSON, …) live in the Python protocol registry which receives
// (kind, meta, body).
//
//  * TRPC framing (our baidu_std analog, reference baidu_rpc_protocol.cpp:
//    97-137): 16-byte header = "TRPC" + u32be meta_size + u64be body_size,
//    then meta bytes, then body bytes.  Meta is opaque to the core.
//  * HTTP/1.x detection: request/status line + headers until CRLFCRLF +
//    content-length or chunked body, delivered as one raw message (kind
//    HTTP).  Enough for the debug console, RESTful access and the HTTP
//    client channel.
//  * HTTP/2: the 24-byte client preface is consumed, then each 9-byte-header
//    frame is delivered as one message (meta = frame header, body =
//    payload).  Clients pre-select h2 via set_protocol.
//  * memcache binary / framed thrift / mongo wire / nshead: length-prefixed
//    framings detected by magic (reference policy/memcache_binary_protocol
//    .cpp, policy/thrift_protocol.cpp, policy/mongo_protocol.cpp,
//    policy/nshead_protocol.cpp).
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

#include "butil/iobuf.h"

namespace brpc {

enum MessageKind {
  MSG_TRPC = 0,
  MSG_HTTP = 1,
  // One complete RESP value (redis wire format) per message; body holds the
  // raw RESP bytes.  Commands from clients are RESP arrays ('*'), replies
  // are any of + - : $ *.  Inline commands are not supported (their first
  // byte is ambiguous with HTTP detection).  RESP has no correlation ids —
  // per-connection FIFO order is the protocol contract — so the socket
  // delivers MSG_REDIS inline on its dispatcher thread instead of fanning
  // out to the executor (see Socket::DispatchMessages).
  MSG_REDIS = 2,
  // One memcache binary-protocol packet (24-byte header + body), delivered
  // whole in body.  Detected by magic 0x80/0x81.
  MSG_MEMCACHE = 3,
  // One framed thrift message; body holds the payload WITHOUT the 4-byte
  // frame length.  Detected by TBinaryProtocol version bytes 0x80 0x01 at
  // offset 4.
  MSG_THRIFT = 4,
  // One mongo wire-protocol message including its 16-byte header, delivered
  // whole in body.  Detected by a plausible little-endian messageLength +
  // known opCode.  Ambiguous with redis for tiny messages — mongo clients
  // should set_protocol().
  MSG_MONGO = 5,
  // One HTTP/2 frame: meta = the 9-byte frame header, body = payload.  The
  // connection preface (PRI * HTTP/2.0...) is consumed silently when seen.
  MSG_H2 = 6,
  // Raw passthrough: whatever bytes are buffered are delivered as one
  // message.  Selected only explicitly via set_protocol (progressive /
  // chunked streaming readers).
  MSG_RAW = 7,
  // One nshead message: meta = the 36-byte nshead header, body = body.
  // Detected by magic 0xfb709394 at offset 24.
  MSG_NSHEAD = 8,
  // Transport-filter delivery (in-socket TLS): ALL buffered inbound
  // bytes handed to the filter callback as ciphertext; the filter
  // decrypts and re-injects plaintext via Socket::InjectBytes, which
  // runs the normal parse/dispatch over it.  Selected only via
  // set_filter_mode; never auto-detected.
  MSG_FILTERED = 9,
};

enum ParseResult {
  PARSE_OK = 0,
  PARSE_NEED_MORE = 1,
  PARSE_ERROR = 2,
};

constexpr char kTrpcMagic[4] = {'T', 'R', 'P', 'C'};
constexpr size_t kTrpcHeaderLen = 16;
constexpr size_t kMaxMetaSize = 16 * 1024 * 1024;
extern size_t g_max_body_size;  // FLAGS_max_body_size analog (default 2GB)

struct ParsedMessage {
  int kind = MSG_TRPC;
  std::string meta;      // contiguous, small
  butil::IOBuf body;     // zero-copy cut from the read buffer
};

struct ParseState {
  int detected = -1;     // -1 unknown, else MessageKind
  // http incremental state
  size_t http_header_end = 0;   // offset past CRLFCRLF once found
  ssize_t http_body_len = -1;   // from content-length; -2 = chunked
  // chunked-scan resume point: absolute offset of the next unvalidated
  // chunk-size line (avoids re-walking validated chunks each dispatch)
  size_t http_chunk_off = 0;
  bool h2_preface_done = false;
};

// Try to cut one message off `in`.  On PARSE_OK, fills *out and removes the
// consumed bytes from `in`; PARSE_NEED_MORE leaves `in` intact.
ParseResult parse_message(butil::IOBuf* in, ParseState* st, ParsedMessage* out);

// In-place TRPC fast path for the dispatch loop — a pure PEEK: nothing
// is consumed and NO block refs are taken (the per-frame guard
// inc_ref/dec_ref pair was 17% of the echo hot path).  On PARSE_OK with
// *meta != nullptr: header+meta are contiguous and viewed in place;
// *body is additionally non-null when the body is contiguous too;
// *total_len is the full frame length for the caller's pop_front after
// dispatch.  Views stay valid only while the caller has not consumed
// the front of `in`.  PARSE_OK with *meta == nullptr: not TRPC / split
// header or meta — use the generic parse_message.
ParseResult parse_trpc_peek(butil::IOBuf* in, const char** meta,
                            size_t* meta_len, const char** body,
                            uint64_t* body_size, uint64_t* total_len);

// Serialize a TRPC frame header.
void make_trpc_header(char out[16], uint32_t meta_size, uint64_t body_size);

// Whether a message kind must be delivered inline on the dispatcher thread
// (per-connection FIFO is part of the protocol contract: RESP pipelining,
// h2 HPACK state, memcache pipelining, raw streaming, …).
inline bool kind_requires_fifo(int kind) {
  return kind != MSG_TRPC && kind != MSG_HTTP;
}

}  // namespace brpc
