// Wire framing for the TPU-RPC socket core (SURVEY.md §2.4).
//
// The extension point in the reference is `struct Protocol` — a function
// table tried in order until one recognizes the bytes, which is how all
// protocols share one port (protocol.h:77-166, input_messenger.cpp:144-160).
// Our native core implements the same try-in-order scheme over two built-in
// framings, and hands *complete messages* (not bytes) upward; higher-level
// protocol semantics (method dispatch, JSON↔tensor mapping, redis RESP, …)
// live in the Python protocol registry which receives (kind, meta, body).
//
//  * TRPC framing (our baidu_std analog, reference baidu_rpc_protocol.cpp:
//    97-137): 16-byte header = "TRPC" + u32be meta_size + u64be body_size,
//    then meta bytes, then body bytes.  Meta is opaque to the core.
//  * HTTP/1.x detection: request/status line + headers until CRLFCRLF +
//    content-length body, delivered as one raw message (kind HTTP).  Enough
//    for the builtin debug console and RESTful access; chunked uploads are
//    handled by the Python layer over streaming reads in a later round.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

#include "butil/iobuf.h"

namespace brpc {

enum MessageKind {
  MSG_TRPC = 0,
  MSG_HTTP = 1,
  // One complete RESP value (redis wire format) per message; body holds the
  // raw RESP bytes.  Commands from clients are RESP arrays ('*'), replies
  // are any of + - : $ *.  Inline commands are not supported (their first
  // byte is ambiguous with HTTP detection).  RESP has no correlation ids —
  // per-connection FIFO order is the protocol contract — so the socket
  // delivers MSG_REDIS inline on its dispatcher thread instead of fanning
  // out to the executor (see Socket::DispatchMessages).
  MSG_REDIS = 2,
};

enum ParseResult {
  PARSE_OK = 0,
  PARSE_NEED_MORE = 1,
  PARSE_ERROR = 2,
};

constexpr char kTrpcMagic[4] = {'T', 'R', 'P', 'C'};
constexpr size_t kTrpcHeaderLen = 16;
constexpr size_t kMaxMetaSize = 16 * 1024 * 1024;
extern size_t g_max_body_size;  // FLAGS_max_body_size analog (default 2GB)

struct ParsedMessage {
  int kind = MSG_TRPC;
  std::string meta;      // contiguous, small
  butil::IOBuf body;     // zero-copy cut from the read buffer
};

struct ParseState {
  int detected = -1;     // -1 unknown, else MessageKind
  // http incremental state
  size_t http_header_end = 0;   // offset past CRLFCRLF once found
  ssize_t http_body_len = -1;   // from content-length
};

// Try to cut one message off `in`.  On PARSE_OK, fills *out and removes the
// consumed bytes from `in`; PARSE_NEED_MORE leaves `in` intact.
ParseResult parse_message(butil::IOBuf* in, ParseState* st, ParsedMessage* out);

// Serialize a TRPC frame header.
void make_trpc_header(char out[16], uint32_t meta_size, uint64_t body_size);

}  // namespace brpc
