#include "net/rpc.h"

#include <atomic>
#include <cstring>
#include <string>
#include <string_view>

#include "bthread/executor.h"
#include "butil/common.h"
#include "butil/doubly_buffered.h"
#include "butil/flat_map.h"
#include "net/parser.h"
#include "net/socket.h"

namespace brpc {

// ---- meta codec ----

static inline uint16_t rd16(const char* p) {
  uint16_t v;
  memcpy(&v, p, 2);
  return v;  // wire is little-endian, as is every supported host
}
static inline uint32_t rd32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
static inline uint64_t rd64(const char* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}

bool ParseMeta(const char* p, size_t n, ParsedMeta* out) {
  if (n < kMetaFixedLen) return false;
  out->version = (uint8_t)p[0];
  if (out->version != 1) return false;
  out->msg_type = (uint8_t)p[1];
  out->flags = rd16(p + 2);
  out->cid = rd64(p + 4);
  out->attempt = rd16(p + 12);
  size_t off = kMetaFixedLen;
  while (off + 5 <= n) {
    const uint8_t tag = (uint8_t)p[off];
    const uint32_t len = rd32(p + off + 1);
    off += 5;
    if (off + len > n) return false;
    const char* v = p + off;
    off += len;
    if (tag < 32) out->present_mask |= (1u << tag);
    switch (tag) {
      case TAG_SERVICE:
        out->service = v;
        out->service_len = len;
        break;
      case TAG_METHOD:
        out->method = v;
        out->method_len = len;
        break;
      case TAG_ERROR_CODE:
        if (len == 4) out->error_code = (int32_t)rd32(v);
        break;
      case TAG_ERROR_TEXT:
        out->error_text = v;
        out->error_text_len = len;
        break;
      case TAG_COMPRESS:
        if (len >= 1) out->compress = (uint8_t)v[0];
        break;
      case TAG_ATTACHMENT_SIZE:
        if (len == 8) out->attachment_size = rd64(v);
        break;
      case TAG_TIMEOUT_MS:
        if (len == 4) out->timeout_ms = rd32(v);
        break;
      case TAG_CONTENT_TYPE:
        out->content_type = v;
        out->content_type_len = len;
        break;
      default:
        break;  // recorded in present_mask; content skipped
    }
  }
  return off == n || off + 5 > n;  // trailing garbage < one TLV header: ok
}

// Meta emission is written ONCE as a templated sequence over a sink
// (put_fixed/put_tlv): FlatStage stages small header+meta spans in a
// stack buffer appended in one call (halves the per-frame appender
// calls on the hot path); AppenderStage is the general fallback for
// oversized metas.  One sequence per direction = no drift between the
// fast and slow encodings.
struct FlatStage {
  char buf[512];
  size_t n = 0;
  bool fits(size_t more) const { return n + more <= sizeof(buf); }
  void put(const void* p, size_t len) {
    memcpy(buf + n, p, len);
    n += len;
  }
  void put_fixed(uint8_t msg_type, uint64_t cid, uint16_t attempt) {
    char* p = buf + n;
    p[0] = 1;  // version
    p[1] = (char)msg_type;
    p[2] = p[3] = 0;  // flags
    memcpy(p + 4, &cid, 8);
    memcpy(p + 12, &attempt, 2);
    n += kMetaFixedLen;
  }
  void put_tlv(uint8_t tag, const void* v, uint32_t len) {
    char* p = buf + n;
    p[0] = (char)tag;
    memcpy(p + 1, &len, 4);
    memcpy(p + 5, v, len);
    n += 5 + len;
  }
};

struct AppenderStage {
  butil::IOBufAppender ap;
  explicit AppenderStage(butil::IOBuf* out) : ap(out) {}
  void put(const void* p, size_t len) { ap.append(p, len); }
  void put_fixed(uint8_t msg_type, uint64_t cid, uint16_t attempt) {
    char fixed[kMetaFixedLen];
    fixed[0] = 1;  // version
    fixed[1] = (char)msg_type;
    fixed[2] = fixed[3] = 0;  // flags
    memcpy(fixed + 4, &cid, 8);
    memcpy(fixed + 12, &attempt, 2);
    ap.append(fixed, sizeof(fixed));
  }
  void put_tlv(uint8_t tag, const void* v, uint32_t len) {
    char hdr[5];
    hdr[0] = (char)tag;
    memcpy(hdr + 1, &len, 4);
    ap.append(hdr, 5);
    ap.append((const char*)v, len);
  }
};

template <class Sink>
static void emit_response_seq(Sink& sk, uint64_t cid, uint16_t attempt,
                              int32_t error_code, const char* error_text,
                              size_t error_text_len, const char* content_type,
                              size_t content_type_len) {
  sk.put_fixed(META_RESPONSE, cid, attempt);
  if (error_code != 0) sk.put_tlv(TAG_ERROR_CODE, &error_code, 4);
  if (error_text_len > 0)
    sk.put_tlv(TAG_ERROR_TEXT, error_text, (uint32_t)error_text_len);
  if (content_type_len > 0)
    sk.put_tlv(TAG_CONTENT_TYPE, content_type, (uint32_t)content_type_len);
}

template <class Sink>
static void emit_request_seq(Sink& sk, uint64_t cid, uint16_t attempt,
                             const char* service, size_t service_len,
                             const char* method, size_t method_len,
                             uint32_t timeout_ms, uint8_t compress,
                             const char* content_type,
                             size_t content_type_len) {
  sk.put_fixed(META_REQUEST, cid, attempt);
  if (service_len > 0)
    sk.put_tlv(TAG_SERVICE, service, (uint32_t)service_len);
  if (method_len > 0) sk.put_tlv(TAG_METHOD, method, (uint32_t)method_len);
  if (compress != 0) sk.put_tlv(TAG_COMPRESS, &compress, 1);
  if (timeout_ms != 0) sk.put_tlv(TAG_TIMEOUT_MS, &timeout_ms, 4);
  if (content_type_len > 0)
    sk.put_tlv(TAG_CONTENT_TYPE, content_type, (uint32_t)content_type_len);
}

void PackResponseFrame(butil::IOBuf* out, uint64_t cid, uint16_t attempt,
                       int32_t error_code, const char* error_text,
                       size_t error_text_len, const char* content_type,
                       size_t content_type_len, butil::IOBuf&& body) {
  const uint32_t meta_size =
      kMetaFixedLen + (error_code != 0 ? 5u + 4u : 0u) +
      (error_text_len > 0 ? 5u + (uint32_t)error_text_len : 0u) +
      (content_type_len > 0 ? 5u + (uint32_t)content_type_len : 0u);
  char hdr[kTrpcHeaderLen];
  make_trpc_header(hdr, meta_size, body.size());
  FlatStage st;
  if (st.fits(kTrpcHeaderLen + meta_size)) {
    st.put(hdr, sizeof(hdr));
    emit_response_seq(st, cid, attempt, error_code, error_text,
                      error_text_len, content_type, content_type_len);
    out->append(st.buf, st.n);
  } else {
    AppenderStage ap(out);
    ap.put(hdr, sizeof(hdr));
    emit_response_seq(ap, cid, attempt, error_code, error_text,
                      error_text_len, content_type, content_type_len);
  }
  out->append(std::move(body));
}

static uint32_t request_meta_size(size_t service_len, size_t method_len,
                                  uint32_t timeout_ms, uint8_t compress,
                                  size_t content_type_len) {
  return kMetaFixedLen +
         (service_len > 0 ? 5u + (uint32_t)service_len : 0u) +
         (method_len > 0 ? 5u + (uint32_t)method_len : 0u) +
         (compress != 0 ? 5u + 1u : 0u) + (timeout_ms != 0 ? 5u + 4u : 0u) +
         (content_type_len > 0 ? 5u + (uint32_t)content_type_len : 0u);
}

static void emit_request_meta(butil::IOBuf* out, uint64_t cid,
                              uint16_t attempt, const char* service,
                              size_t service_len, const char* method,
                              size_t method_len, uint32_t timeout_ms,
                              uint8_t compress, const char* content_type,
                              size_t content_type_len, uint64_t body_size) {
  const uint32_t meta_size = request_meta_size(
      service_len, method_len, timeout_ms, compress, content_type_len);
  char hdr[kTrpcHeaderLen];
  make_trpc_header(hdr, meta_size, body_size);
  FlatStage st;
  if (st.fits(kTrpcHeaderLen + meta_size)) {
    st.put(hdr, sizeof(hdr));
    emit_request_seq(st, cid, attempt, service, service_len, method,
                     method_len, timeout_ms, compress, content_type,
                     content_type_len);
    out->append(st.buf, st.n);
    return;
  }
  AppenderStage ap(out);
  ap.put(hdr, sizeof(hdr));
  emit_request_seq(ap, cid, attempt, service, service_len, method,
                   method_len, timeout_ms, compress, content_type,
                   content_type_len);
}

void PackRequestFrame(butil::IOBuf* out, uint64_t cid, uint16_t attempt,
                      const char* service, size_t service_len,
                      const char* method, size_t method_len,
                      uint32_t timeout_ms, uint8_t compress,
                      const char* content_type, size_t content_type_len,
                      butil::IOBuf&& body) {
  emit_request_meta(out, cid, attempt, service, service_len, method,
                    method_len, timeout_ms, compress, content_type,
                    content_type_len, body.size());
  out->append(std::move(body));
}

void PackRequestFrameFlat(butil::IOBuf* out, uint64_t cid, uint16_t attempt,
                          const char* service, size_t service_len,
                          const char* method, size_t method_len,
                          uint32_t timeout_ms, uint8_t compress,
                          const char* content_type, size_t content_type_len,
                          const void* body, size_t body_len) {
  emit_request_meta(out, cid, attempt, service, service_len, method,
                    method_len, timeout_ms, compress, content_type,
                    content_type_len, body_len);
  if (body_len > 0) out->append(body, body_len);
}

// ---- method registry ----

namespace {

struct SvHash {
  using is_transparent = void;
  size_t operator()(const std::string& s) const {
    return std::hash<std::string_view>()(s);
  }
  size_t operator()(std::string_view s) const {
    return std::hash<std::string_view>()(s);
  }
};
struct SvEq {
  bool operator()(const std::string& a, const std::string& b) const {
    return a == b;
  }
  bool operator()(const std::string& a, std::string_view b) const {
    return a == b;
  }
};

using MethodMap =
    butil::FlatMap<std::string, MethodRegistry::Entry, SvHash, SvEq>;

// Function-local magic static: thread-safe one-time construction even when
// the first Register (Python thread) races the first Lookup (dispatcher).
butil::DoublyBufferedData<MethodMap>& methods() {
  static butil::DoublyBufferedData<MethodMap> maps;
  return maps;
}
// Bumped on every registry mutation; validates the per-thread last-hit
// cache below (consecutive requests on a connection overwhelmingly name
// the same method — the hash+DBD probe was a visible hot-path cost).
std::atomic<uint64_t> g_registry_version{1};
std::atomic<int64_t> g_native_calls{0};
std::atomic<int64_t> g_python_fast_calls{0};
// replies whose socket Write was rejected (EOVERCROWDED / failed socket)
std::atomic<int64_t> g_dropped_responses{0};
std::atomic<RequestCallback> g_request_cb{nullptr};
std::atomic<void*> g_request_user{nullptr};

// ---- usercode admission control (VERDICT r4 #4) ----
// The Python lane is GIL-serialized: requests queued behind a saturated
// lane wait (queue depth x service time) before their handler even
// starts.  When a latency budget is set, new requests are shed NOW with
// ELIMIT while the lane's MEASURED queue wait (EMA of submit->upcall
// delay, stamped per task) sits above the budget — the reference's
// ConcurrencyLimiter/ELIMIT fail-fast semantics (server.h
// max_concurrency) with the bound expressed in time.  Closed loop on
// the measured wait, not (pending x upcall-time): the open-loop
// estimate over-sheds under GIL contention (upcall wall time includes
// the very queueing it predicts), idling the lane while still letting
// accepted tails breach the budget.
constexpr int32_t kELimit = 2004;  // brpc_tpu/errors.py ELIMIT
// Inline usercode mode flag (see the dispatch section for the design
// note): upcalls run synchronously on the dispatcher thread.
std::atomic<bool> g_py_inline{false};
// Inline upcalls processed in the current epoll sweep of this
// dispatcher thread (reset by NoteDispatchSweepStart).
thread_local int tls_sweep_upcalls = 0;
std::atomic<int64_t> g_py_pending{0};
std::atomic<int64_t> g_py_budget_us{0};  // 0 = admission control off
std::atomic<int64_t> g_py_shed{0};
// EMA of measured queue wait in us, stored as double bits (racy
// load-modify-store is fine: it's a smoothed estimate)
std::atomic<uint64_t> g_py_ema_us_bits{0};

double py_ema_us() {
  uint64_t b = g_py_ema_us_bits.load(std::memory_order_relaxed);
  double d;
  memcpy(&d, &b, 8);
  return d;
}

void py_ema_update(double sample_us) {
  // alpha 0.25: fast enough that a drained queue re-admits within a few
  // tasks, smooth enough that one stall doesn't slam the gate
  const double prev = py_ema_us();
  const double next =
      prev == 0.0 ? sample_us : prev + 0.25 * (sample_us - prev);
  uint64_t b;
  memcpy(&b, &next, 8);
  g_py_ema_us_bits.store(b, std::memory_order_relaxed);
}

std::string make_key(const char* service, size_t service_len,
                     const char* method, size_t method_len) {
  std::string k;
  k.reserve(service_len + method_len + 1);
  k.append(service, service_len);
  k.push_back('\0');
  k.append(method, method_len);
  return k;
}

}  // namespace

MethodRegistry* MethodRegistry::global() {
  static MethodRegistry reg;
  return &reg;
}

void MethodRegistry::Register(const char* service, const char* method,
                              NativeMethodFn fn, void* user, bool inline_run) {
  RegisterFlat(service, method, fn, nullptr, user, inline_run);
}

void MethodRegistry::RegisterFlat(const char* service, const char* method,
                                  NativeMethodFn fn, NativeMethodFlatFn flat,
                                  void* user, bool inline_run) {
  std::string key = make_key(service, strlen(service), method, strlen(method));
  Entry e{fn, flat, user, inline_run};
  methods().Modify([&](MethodMap& m) {
    m.insert(key, e);
    return true;
  });
  g_registry_version.fetch_add(1, std::memory_order_release);
}

void MethodRegistry::RegisterPython(const char* service, const char* method) {
  Register(service, method, nullptr, nullptr, false);
}

bool MethodRegistry::Unregister(const char* service, const char* method) {
  std::string key = make_key(service, strlen(service), method, strlen(method));
  bool existed = false;
  methods().Modify([&](MethodMap& m) {
    existed = m.erase(key);
    return true;
  });
  g_registry_version.fetch_add(1, std::memory_order_release);
  return existed;
}

bool MethodRegistry::Lookup(const char* service, size_t service_len,
                            const char* method, size_t method_len,
                            Entry* out) {
  // heterogeneous probe: the key view lives on the stack, no allocation
  char buf[256];
  std::string heap_key;
  std::string_view key;
  const size_t total = service_len + 1 + method_len;
  // per-thread last-hit cache: a connection's requests overwhelmingly
  // repeat one method, so a 20-byte memcmp replaces hash + DBD read +
  // probe.  Only HITS are cached; any registry mutation bumps
  // g_registry_version and invalidates every thread's entry.
  struct LastHit {
    uint64_t version = 0;
    size_t len = 0;
    Entry e;
    char key[128];
  };
  static thread_local LastHit tls_hit;
  const uint64_t ver = g_registry_version.load(std::memory_order_acquire);
  if (total <= sizeof(buf)) {
    memcpy(buf, service, service_len);
    buf[service_len] = '\0';
    memcpy(buf + service_len + 1, method, method_len);
    key = std::string_view(buf, total);
    if (tls_hit.version == ver && tls_hit.len == total &&
        memcmp(tls_hit.key, buf, total) == 0) {
      *out = tls_hit.e;
      return true;
    }
  } else {
    heap_key = make_key(service, service_len, method, method_len);
    key = heap_key;
  }
  butil::DoublyBufferedData<MethodMap>::ScopedPtr ptr;
  methods().Read(&ptr);
  const Entry* e = ptr->seek(key);
  if (e == nullptr) return false;
  *out = *e;
  if (total <= sizeof(tls_hit.key)) {
    tls_hit.version = ver;
    tls_hit.len = total;
    memcpy(tls_hit.key, key.data(), total);
    tls_hit.e = *e;
  }
  return true;
}

int64_t MethodRegistry::native_calls() const {
  return g_native_calls.load(std::memory_order_relaxed);
}
int64_t MethodRegistry::python_fast_calls() const {
  return g_python_fast_calls.load(std::memory_order_relaxed);
}
int64_t MethodRegistry::dropped_responses() const {
  return g_dropped_responses.load(std::memory_order_relaxed);
}
void MethodRegistry::NoteDroppedResponse() {
  g_dropped_responses.fetch_add(1, std::memory_order_relaxed);
}

void SetRequestCallback(RequestCallback cb, void* user) {
  g_request_user.store(user, std::memory_order_release);
  g_request_cb.store(cb, std::memory_order_release);
}

void SetUsercodeLatencyBudgetUs(int64_t us) {
  g_py_budget_us.store(us, std::memory_order_relaxed);
}
void SetUsercodeInline(bool on) {
  g_py_inline.store(on, std::memory_order_relaxed);
}
bool UsercodeInline() { return g_py_inline.load(std::memory_order_relaxed); }
void NoteDispatchSweepStart() { tls_sweep_upcalls = 0; }
int64_t UsercodeLatencyBudgetUs() {
  return g_py_budget_us.load(std::memory_order_relaxed);
}
int64_t UsercodeShedCount() {
  return g_py_shed.load(std::memory_order_relaxed);
}
int64_t UsercodePending() {
  return g_py_pending.load(std::memory_order_relaxed);
}
double UsercodeEmaUs() { return py_ema_us(); }

// ---- dispatch ----

namespace {

void fill_header(RequestHeader* hdr, const ParsedMeta& m) {
  hdr->cid = m.cid;
  hdr->timeout_ms = m.timeout_ms;
  hdr->present_mask = m.present_mask;
  hdr->service = m.service;
  hdr->service_len = m.service_len;
  hdr->method = m.method;
  hdr->method_len = m.method_len;
  hdr->attempt = m.attempt;
  hdr->compress = m.compress;
  hdr->msg_type = m.msg_type;
  hdr->content_type = m.content_type;
  hdr->content_type_len = m.content_type_len;
  hdr->error_code = m.error_code;
  hdr->error_text = m.error_text;
  hdr->error_text_len = m.error_text_len;
  hdr->attachment_size = m.attachment_size;
}

void run_native(SocketId sid, const MethodRegistry::Entry& e, uint64_t cid,
                uint16_t attempt, butil::IOBuf* body) {
  butil::IOBuf resp_body;
  const int32_t rc = e.fn(sid, body, &resp_body, e.user);
  g_native_calls.fetch_add(1, std::memory_order_relaxed);
  // Inline on the dispatcher drain: pack the response STRAIGHT into the
  // socket's write batch — no intermediate frame IOBuf, no per-response
  // Write() (ref churn there was >20% of the echo hot path in gprof).
  butil::IOBuf* batch = Socket::CurrentBatchFor(sid, resp_body.size() + 64);
  if (batch != nullptr) {
    PackResponseFrame(batch, cid, attempt, rc, nullptr, 0, nullptr, 0,
                      std::move(resp_body));
    return;
  }
  butil::IOBuf frame;
  PackResponseFrame(&frame, cid, attempt, rc, nullptr, 0, nullptr, 0,
                    std::move(resp_body));
  Socket* s = Socket::Address(sid);
  if (s != nullptr) {
    if (s->Write(std::move(frame)) != 0) {
      // overcrowded backlog or racing SetFailed: the reply is gone and the
      // client can only learn via its deadline — keep it visible here
      g_dropped_responses.fetch_add(1, std::memory_order_relaxed);
    }
    s->Dereference();
  }
}

struct PendingNative {
  SocketId sid;
  MethodRegistry::Entry entry;
  uint64_t cid;
  uint16_t attempt;
  butil::IOBuf body;
};

void run_native_task(void* arg) {
  auto* p = (PendingNative*)arg;
  run_native(p->sid, p->entry, p->cid, p->attempt, &p->body);
  delete p;
}

struct PendingFastRequest {
  SocketId sid;
  std::string meta;  // owned copy; re-parsed on the worker
  butil::IOBuf* body;
  RequestCallback cb;
  void* user;
  int64_t submit_us;  // queue-wait measurement (admission control)
};

void run_fast_request_task(void* arg) {
  auto* p = (PendingFastRequest*)arg;
  // the controlled variable: how long this request sat in the lane
  // before its upcall began
  py_ema_update(double(butil::cpuwide_time_us() - p->submit_us));
  ParsedMeta m;
  if (ParseMeta(p->meta.data(), p->meta.size(), &m)) {
    RequestHeader hdr;
    fill_header(&hdr, m);
    g_python_fast_calls.fetch_add(1, std::memory_order_relaxed);
    p->cb(p->sid, &hdr, p->body, p->user);  // callee owns body
  } else {
    delete p->body;
  }
  g_py_pending.fetch_sub(1, std::memory_order_relaxed);
  delete p;
}

// Inline usercode mode (g_py_inline above): run the Python upcall
// synchronously ON the dispatcher thread — the single-threaded
// event-loop discipline.  On a core-starved host the dominant tail term
// is CFS interleaving the dispatcher with GIL-bound worker threads in
// multi-ms quanta (a dedicated lane thread and a renice were both
// tried: p99 went UP in the 64-conn bench).  Inline, there is no
// cross-thread handoff at all: RTT = queued handler times with variance
// reduced to GC pauses, and responses join the dispatch write batch for
// free.  STRICTLY for non-blocking handlers (a handler that blocks
// stalls this dispatcher's sockets; a nested RPC through the same
// dispatcher can deadlock) — blocking handlers belong to the default
// executor path + usercode_in_pthread, exactly like the reference.

struct PendingFastResponse {
  SocketId sid;
  std::string meta;
  butil::IOBuf* body;
  ResponseCallback cb;
  void* user;
};

void run_fast_response_task(void* arg) {
  auto* p = (PendingFastResponse*)arg;
  ParsedMeta m;
  if (ParseMeta(p->meta.data(), p->meta.size(), &m)) {
    RequestHeader hdr;
    fill_header(&hdr, m);
    p->cb(p->sid, &hdr, p->body, p->user);
  } else {
    delete p->body;
  }
  delete p;
}

}  // namespace

bool TryDispatchTrpc(SocketId sid, const SocketOptions& opts, const char* meta,
                     size_t meta_len, butil::IOBuf* body) {
  ParsedMeta m;
  if (!ParseMeta(meta, meta_len, &m)) return false;
  if (!MetaIsFastPath(m)) return false;

  if (m.msg_type == META_REQUEST) {
    if (!opts.enable_rpc_dispatch) return false;
    if (m.service == nullptr || m.method == nullptr) return false;
    MethodRegistry::Entry e;
    if (!MethodRegistry::global()->Lookup(m.service, m.service_len, m.method,
                                          m.method_len, &e)) {
      return false;  // unknown method: Python path owns the error reply
    }
    if (e.fn != nullptr) {
      if (e.inline_run) {
        run_native(sid, e, m.cid, m.attempt, body);
        body->clear();
      } else {
        auto* p = new PendingNative{sid, e, m.cid, m.attempt,
                                    std::move(*body)};
        bthread::Executor::global()->submit(run_native_task, p);
      }
      return true;
    }
    RequestCallback cb = g_request_cb.load(std::memory_order_acquire);
    if (cb == nullptr) return false;
    const int64_t budget = g_py_budget_us.load(std::memory_order_relaxed);
    if (budget > 0) {
      const int64_t pending =
          g_py_pending.load(std::memory_order_relaxed);
      // pending > 2: with a near-empty lane ALWAYS admit — the measured
      // wait of those tasks is what refreshes the estimate, so a stale
      // high EMA can never starve the lane (and a 2-deep queue can't
      // breach any sane budget anyway)
      if (pending > 2 && py_ema_us() > double(budget)) {
        // estimated GIL-lane wait exceeds the budget: fail fast with
        // ELIMIT instead of making the caller eat the whole queue
        g_py_shed.fetch_add(1, std::memory_order_relaxed);
        static const char kShedText[] = "usercode latency budget exceeded";
        butil::IOBuf* batch = Socket::CurrentBatchFor(sid, 96);
        if (batch != nullptr) {
          PackResponseFrame(batch, m.cid, m.attempt, kELimit, kShedText,
                            sizeof(kShedText) - 1, nullptr, 0,
                            butil::IOBuf());
        } else {
          butil::IOBuf frame;
          PackResponseFrame(&frame, m.cid, m.attempt, kELimit, kShedText,
                            sizeof(kShedText) - 1, nullptr, 0,
                            butil::IOBuf());
          Socket* s = Socket::Address(sid);
          if (s != nullptr) {
            if (s->Write(std::move(frame)) != 0)
              g_dropped_responses.fetch_add(1, std::memory_order_relaxed);
            s->Dereference();
          }
        }
        body->clear();
        return true;
      }
    }
    if (g_py_inline.load(std::memory_order_relaxed)) {
      // single-threaded event-loop mode: upcall NOW on this dispatcher
      // thread; the response rides the current write batch.
      // Admission control here is per EPOLL SWEEP: position-in-sweep x
      // EMA(handler time) estimates how long this request already
      // waited behind the sweep's earlier handlers.  In steady state a
      // sweep finishes under any sane budget and nothing sheds; an
      // abnormal pileup (stall, burst) sheds its tail with ELIMIT so
      // the cycle length — and therefore p99 — stays bounded.
      if (budget > 0 &&
          double(tls_sweep_upcalls) * py_ema_us() > double(budget)) {
        g_py_shed.fetch_add(1, std::memory_order_relaxed);
        static const char kShedText[] = "usercode latency budget exceeded";
        butil::IOBuf* batch = Socket::CurrentBatchFor(sid, 96);
        if (batch != nullptr) {
          PackResponseFrame(batch, m.cid, m.attempt, kELimit, kShedText,
                            sizeof(kShedText) - 1, nullptr, 0,
                            butil::IOBuf());
        } else {
          // overcrowded/failed socket: still try a direct write — a shed
          // with no reply would leave the caller waiting out its full
          // deadline, the very thing admission control exists to avoid
          butil::IOBuf frame;
          PackResponseFrame(&frame, m.cid, m.attempt, kELimit, kShedText,
                            sizeof(kShedText) - 1, nullptr, 0,
                            butil::IOBuf());
          Socket* s = Socket::Address(sid);
          if (s != nullptr) {
            if (s->Write(std::move(frame)) != 0)
              g_dropped_responses.fetch_add(1, std::memory_order_relaxed);
            s->Dereference();
          }
        }
        body->clear();
        return true;
      }
      ++tls_sweep_upcalls;
      RequestHeader hdr;
      fill_header(&hdr, m);
      g_python_fast_calls.fetch_add(1, std::memory_order_relaxed);
      const int64_t t0 = butil::cpuwide_time_us();
      auto* owned = new butil::IOBuf(std::move(*body));
      cb(sid, &hdr, owned, g_request_user.load());  // callee owns body
      py_ema_update(double(butil::cpuwide_time_us() - t0));
      return true;
    }
    g_py_pending.fetch_add(1, std::memory_order_relaxed);
    auto* p = new PendingFastRequest{sid, std::string(meta, meta_len),
                                     new butil::IOBuf(std::move(*body)), cb,
                                     g_request_user.load(),
                                     butil::cpuwide_time_us()};
    // one executor task per message (the "one bthread per message" rule,
    // input_messenger.cpp:175-213): a blocking handler must not
    // head-of-line-block other requests.  (A serialized global lane was
    // tried and reverted: one sleeping handler delayed every other
    // Python upcall in the process, starving backup requests.)
    bthread::Executor::global()->submit(run_fast_request_task, p);
    return true;
  }

  if (m.msg_type == META_RESPONSE) {
    if (opts.on_response == nullptr && opts.on_response_flat == nullptr)
      return false;
    if (opts.on_response == nullptr) {
      // flat-only client: deliver borrowed multi-block body inline (the
      // flat path handles the contiguous common case; this is the
      // split-frame tail of the same contract)
      RequestHeader hdr;
      fill_header(&hdr, m);
      std::string tmp = body->to_string();
      opts.on_response_flat(sid, &hdr, tmp.data(), tmp.size(),
                            opts.response_user);
      body->clear();
      return true;
    }
    if (opts.response_inline) {
      RequestHeader hdr;
      fill_header(&hdr, m);
      opts.on_response(sid, &hdr, body, opts.response_user);  // borrowed
      body->clear();
      return true;
    }
    auto* p = new PendingFastResponse{sid, std::string(meta, meta_len),
                                      new butil::IOBuf(std::move(*body)),
                                      opts.on_response, opts.response_user};
    // ORDERING: responses ride the socket's FIFO lane, the same queue
    // SetFailed delivers on_failed through — so a peer close arriving
    // right after the final responses can never overtake them and fail
    // calls that actually completed (the graceful-shutdown race: the
    // server closes the moment its last response is queued).
    brpc::Socket* s = brpc::Socket::Address(sid);
    if (s == nullptr) {
      delete p->body;
      delete p;
      return true;
    }
    // bytes=0: response backlog is bounded by the CALLER's own
    // in-flight count (unlike server reads fed by a foreign peer), and
    // the old executor path never killed a socket for slow local
    // completion — the lane is for ORDERING only here.  Completions
    // serialize per connection; done-callbacks must stay light (same
    // contract as response handling in general).
    // bytes=0 cannot trip the overcrowded bound, so this always queues
    s->FifoSubmit(run_fast_response_task, p, 0);
    s->Dereference();
    return true;
  }
  return false;  // stream frames etc. go to the generic path
}

bool TryDispatchTrpcFlat(SocketId sid, const SocketOptions& opts,
                         const char* meta, size_t meta_len, const char* body,
                         size_t body_len) {
  ParsedMeta m;
  if (!ParseMeta(meta, meta_len, &m)) return false;
  if (!MetaIsFastPath(m)) return false;

  if (m.msg_type == META_RESPONSE) {
    if (opts.on_response_flat == nullptr) return false;
    RequestHeader hdr;
    fill_header(&hdr, m);
    opts.on_response_flat(sid, &hdr, body, body_len, opts.response_user);
    return true;
  }
  if (m.msg_type != META_REQUEST) return false;
  if (!opts.enable_rpc_dispatch) return false;
  if (m.service == nullptr || m.method == nullptr) return false;
  MethodRegistry::Entry e;
  if (!MethodRegistry::global()->Lookup(m.service, m.service_len, m.method,
                                        m.method_len, &e)) {
    return false;
  }
  if (e.fn_flat == nullptr || !e.inline_run) return false;
  // One stack stage holds the whole response frame:
  //   [16B trpc header][14B rc==0 response meta][resp body]
  // so the write batch gets ONE contiguous append — no body IOBuf on
  // either side of the handler, no block refs, one iovec span.
  char stage[kTrpcHeaderLen + kMetaFixedLen + kFlatRespCap];
  char* const meta_p = stage + kTrpcHeaderLen;
  char* const resp_p = meta_p + kMetaFixedLen;
  const int32_t rlen =
      e.fn_flat(sid, body, body_len, resp_p, kFlatRespCap, e.user);
  if (rlen < 0) return false;  // declined pre-side-effect: IOBuf path
  g_native_calls.fetch_add(1, std::memory_order_relaxed);
  meta_p[0] = 1;  // version
  meta_p[1] = (char)META_RESPONSE;
  meta_p[2] = meta_p[3] = 0;  // flags
  memcpy(meta_p + 4, &m.cid, 8);
  memcpy(meta_p + 12, &m.attempt, 2);
  make_trpc_header(stage, kMetaFixedLen, (uint64_t)rlen);
  const size_t frame_len = kTrpcHeaderLen + kMetaFixedLen + (size_t)rlen;
  butil::IOBuf* batch = Socket::CurrentBatchFor(sid, frame_len);
  if (batch != nullptr) {
    batch->append(stage, frame_len);
    return true;
  }
  butil::IOBuf frame;
  frame.append(stage, frame_len);
  Socket* s = Socket::Address(sid);
  if (s != nullptr) {
    if (s->Write(std::move(frame)) != 0) {
      g_dropped_responses.fetch_add(1, std::memory_order_relaxed);
    }
    s->Dereference();
  }
  return true;
}

}  // namespace brpc
