// Native unary-RPC hot path: meta codec, method map, dispatch.
//
// The reference parses baidu_std meta, finds the method and serializes the
// response entirely in C++ (baidu_rpc_protocol.cpp:97-137 parse, :398
// ProcessRpcRequest, server.h:399,432 FlatMap method maps) — the Python
// round-trip per request was round 1's architectural QPS cap.  This layer
// mirrors that: TRPC meta (meta.py layout: fixed <BBHQH> + u8/u32le TLVs)
// is parsed natively; methods registered in a FlatMap behind
// DoublyBufferedData are dispatched either to a pure-native handler (the
// request never surfaces to Python) or to Python through a pre-parsed
// request callback; responses are packed natively.
#pragma once

#include <cstddef>
#include <cstdint>

#include "butil/iobuf.h"

namespace brpc {

typedef uint64_t SocketId;

// ---- meta codec (mirrors brpc_tpu/rpc/meta.py) ----

enum MetaMsgType {
  META_REQUEST = 0,
  META_RESPONSE = 1,
  // stream frame types 2..4 are not handled natively
};

enum MetaTag {
  TAG_SERVICE = 1,
  TAG_METHOD = 2,
  TAG_ERROR_CODE = 3,
  TAG_ERROR_TEXT = 4,
  TAG_COMPRESS = 5,
  TAG_ATTACHMENT_SIZE = 6,
  TAG_TIMEOUT_MS = 7,
  TAG_CONTENT_TYPE = 12,
};

constexpr size_t kMetaFixedLen = 14;  // <BBHQH>

struct ParsedMeta {
  uint8_t version = 0;
  uint8_t msg_type = 0;
  uint16_t flags = 0;
  uint64_t cid = 0;
  uint16_t attempt = 0;
  // string fields point into the raw meta buffer
  const char* service = nullptr;
  uint32_t service_len = 0;
  const char* method = nullptr;
  uint32_t method_len = 0;
  const char* error_text = nullptr;
  uint32_t error_text_len = 0;
  const char* content_type = nullptr;
  uint32_t content_type_len = 0;
  int32_t error_code = 0;
  uint8_t compress = 0;
  uint64_t attachment_size = 0;
  uint32_t timeout_ms = 0;
  uint32_t present_mask = 0;  // bit (1<<tag) for every TLV seen, tag<32
};

// Parse; returns false on malformed meta.  String fields alias `p`.
bool ParseMeta(const char* p, size_t n, ParsedMeta* out);

// Tags the native fast path fully understands; metas with any other tag
// (auth, trace ids, stream state, tensor headers, user fields) fall back
// to the Python decoder so nothing is silently dropped.
constexpr uint32_t kFastPathTags =
    (1u << TAG_SERVICE) | (1u << TAG_METHOD) | (1u << TAG_ERROR_CODE) |
    (1u << TAG_ERROR_TEXT) | (1u << TAG_COMPRESS) |
    (1u << TAG_ATTACHMENT_SIZE) | (1u << TAG_TIMEOUT_MS) |
    (1u << TAG_CONTENT_TYPE);

inline bool MetaIsFastPath(const ParsedMeta& m) {
  return (m.present_mask & ~kFastPathTags) == 0;
}

// Build a complete TRPC response frame (header + response meta + body)
// into *out.  Consumes body.
void PackResponseFrame(butil::IOBuf* out, uint64_t cid, uint16_t attempt,
                       int32_t error_code, const char* error_text,
                       size_t error_text_len, const char* content_type,
                       size_t content_type_len, butil::IOBuf&& body);

// Build a complete TRPC request frame natively (client-side fast path).
void PackRequestFrame(butil::IOBuf* out, uint64_t cid, uint16_t attempt,
                      const char* service, size_t service_len,
                      const char* method, size_t method_len,
                      uint32_t timeout_ms, uint8_t compress,
                      const char* content_type, size_t content_type_len,
                      butil::IOBuf&& body);

// Same, but the body is raw bytes staged through the one appender — for
// small payloads this skips the body IOBuf's block-ref round entirely.
void PackRequestFrameFlat(butil::IOBuf* out, uint64_t cid, uint16_t attempt,
                          const char* service, size_t service_len,
                          const char* method, size_t method_len,
                          uint32_t timeout_ms, uint8_t compress,
                          const char* content_type, size_t content_type_len,
                          const void* body, size_t body_len);

// ---- method registry ----

// Pure-native handler: fills *resp_body, returns an error code (0 = ok).
// body ownership stays with the caller.
typedef int32_t (*NativeMethodFn)(SocketId sid, butil::IOBuf* body,
                                  butil::IOBuf* resp_body, void* user);

// Flat inline handler (the zero-ref hot path): the request body is a VIEW
// into the socket's read block (valid only for the duration of the call)
// and the response body is written straight into a stack stage that lands
// in the dispatch write batch as ONE contiguous span — no IOBuf, no
// block refs, no extra iovecs on either side.  Returns the response
// length (>= 0, rc 0 implied), or -1 to fall back to the IOBuf handler
// `fn` (only allowed BEFORE any side effect: the request is re-delivered).
typedef int32_t (*NativeMethodFlatFn)(SocketId sid, const char* req,
                                      size_t req_len, char* resp,
                                      size_t resp_cap, void* user);

// Response stage capacity offered to flat handlers (stack-allocated in
// the dispatch loop; responses above this take the IOBuf path).
constexpr size_t kFlatRespCap = 4096;

// Pre-parsed request surfaced to Python.  hdr fields alias raw_meta, which
// is only valid during the call; body ownership transfers to the callee.
struct RequestHeader {
  uint64_t cid;
  uint32_t timeout_ms;
  uint32_t present_mask;
  const char* service;
  uint32_t service_len;
  const char* method;
  uint32_t method_len;
  uint16_t attempt;
  uint8_t compress;
  uint8_t msg_type;
  const char* content_type;
  uint32_t content_type_len;
  int32_t error_code;
  const char* error_text;
  uint32_t error_text_len;
  uint64_t attachment_size;
};

typedef void (*RequestCallback)(SocketId sid, const RequestHeader* hdr,
                                butil::IOBuf* body, void* user);
// Client side: pre-parsed response.  Same aliasing rules.
typedef void (*ResponseCallback)(SocketId sid, const RequestHeader* hdr,
                                 butil::IOBuf* body, void* user);
// Flat inline response: body is a view into the read block, valid only
// for the duration of the call (zero-ref client hot path).
typedef void (*ResponseFlatCallback)(SocketId sid, const RequestHeader* hdr,
                                     const char* body, size_t body_len,
                                     void* user);

class MethodRegistry {
 public:
  static MethodRegistry* global();

  // kind: 0 = native handler, 1 = python (dispatched via RequestCallback).
  // inline_run: run the native handler on the dispatcher thread instead of
  // an executor task (only for handlers that never block).
  void Register(const char* service, const char* method, NativeMethodFn fn,
                void* user, bool inline_run);
  // Register both forms: `flat` runs when the request body is contiguous
  // in the read block and the response fits kFlatRespCap; `fn` is the
  // fallback for split/oversized frames (and MUST be provided).
  void RegisterFlat(const char* service, const char* method,
                    NativeMethodFn fn, NativeMethodFlatFn flat, void* user,
                    bool inline_run);
  void RegisterPython(const char* service, const char* method);
  bool Unregister(const char* service, const char* method);

  struct Entry {
    NativeMethodFn fn = nullptr;  // null => python
    NativeMethodFlatFn fn_flat = nullptr;
    void* user = nullptr;
    bool inline_run = false;
  };
  // Returns true and fills *out when (service, method) is registered.
  bool Lookup(const char* service, size_t service_len, const char* method,
              size_t method_len, Entry* out);

  int64_t native_calls() const;
  int64_t dropped_responses() const;
  // Count a reply whose socket Write was rejected (callers outside this
  // TU: fastrpc extension, capi response paths).
  static void NoteDroppedResponse();
  int64_t python_fast_calls() const;
};

// Install the process-wide Python-side request callback for the fast path
// (server role; responses are per-socket via SocketOptions.on_response).
void SetRequestCallback(RequestCallback cb, void* user);

// Usercode admission control (reference ELIMIT fail-fast semantics with a
// time-denominated bound): when a budget is set and the estimated wait
// for the GIL-serialized Python lane (pending x EMA upcall time) exceeds
// it, new requests are answered ELIMIT natively instead of queueing.
void SetUsercodeLatencyBudgetUs(int64_t us);  // 0 disables (default)
int64_t UsercodeLatencyBudgetUs();
int64_t UsercodeShedCount();
int64_t UsercodePending();
double UsercodeEmaUs();

// Inline usercode mode (single-threaded event loop): Python upcalls run
// synchronously on the dispatcher thread.  Lowest possible latency
// variance on core-starved hosts; STRICTLY for non-blocking handlers.
void SetUsercodeInline(bool on);
bool UsercodeInline();
// Called by the event dispatcher at the top of each epoll sweep: resets
// the per-sweep inline-upcall counter that the inline admission control
// uses to estimate how long a request sat behind this sweep's handlers.
void NoteDispatchSweepStart();

struct SocketOptions;

// Socket::DispatchMessages hook for MSG_TRPC.  Returns true if the message
// was fully handled natively (or handed to the fast-path callbacks) — the
// callee then owns *body (heap).  false => caller falls back to the
// generic on_message path and still owns body.
bool TryDispatchTrpc(SocketId sid, const SocketOptions& opts,
                     const char* meta, size_t meta_len, butil::IOBuf* body);

// Zero-ref variant: meta AND body are views into the read block.  Returns
// true when fully handled (caller pops the body bytes); false => caller
// takes the IOBuf path (cutn + TryDispatchTrpc) with NOTHING consumed —
// flat handlers must not have had side effects before falling back.
bool TryDispatchTrpcFlat(SocketId sid, const SocketOptions& opts,
                         const char* meta, size_t meta_len, const char* body,
                         size_t body_len);

}  // namespace brpc
