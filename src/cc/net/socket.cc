#include "net/socket.h"

#include <arpa/inet.h>
#include <errno.h>
#include <sys/un.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stddef.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include "bthread/execution_queue.h"
#include "bthread/executor.h"
#include "bthread/fiber.h"
#include "butil/flight.h"
#include "bvar/combiner.h"
#include "net/event_dispatcher.h"
#include "net/h2.h"

namespace brpc {

using butil::ResourcePool;

static ResourcePool<Socket>* pool() { return ResourcePool<Socket>::singleton(); }

static std::atomic<int64_t> g_active_sockets{0};
// Process-wide traffic totals as bvar combiners (per-thread cells,
// bvar/combiner.h): dispatcher and drainer threads each write their own
// cell instead of bouncing one shared cacheline per read/write/message
// (reference SocketVarsCollector, socket.h:126-157).
static bvar::Adder g_total_read_bytes;
static bvar::Adder g_total_written_bytes;
static bvar::Adder g_total_messages;

void Socket::GlobalTraffic(int64_t* nread, int64_t* nwritten, int64_t* nmsg) {
  if (nread) *nread = g_total_read_bytes.get();
  if (nwritten) *nwritten = g_total_written_bytes.get();
  if (nmsg) *nmsg = g_total_messages.get();
}

// Syscall attribution (ISSUE 15 / ROADMAP 1(e)): on this class of box a
// 64-byte loopback send costs the same ~260us as a 16KB one — syscall
// COUNT, not bytes, is the floor — so the frame-coalescing work needs
// these as its before/after metric.
static bvar::Adder g_read_syscalls;
static bvar::Adder g_write_syscalls;
static bvar::Adder g_batch_hits;    // writes coalesced into the TLS batch
static bvar::Adder g_batch_misses;  // writes that had to take their own path
// log2-bucketed bytes-per-write histogram; exact atomics, not combiner
// cells — 16 counters bumped once per SYSCALL are not a hot cacheline.
static std::atomic<int64_t> g_write_size_hist[Socket::kWriteHistBuckets];

static void note_write_syscall(ssize_t nw) {
  g_write_syscalls.add(1);
  if (nw <= 0) return;
  int idx = 0;
  uint64_t bound = 64;
  while (idx < Socket::kWriteHistBuckets - 1 && (uint64_t)nw > bound) {
    bound <<= 1;
    ++idx;
  }
  g_write_size_hist[idx].fetch_add(1, std::memory_order_relaxed);
}

void Socket::SyscallCounters(int64_t* read_sys, int64_t* write_sys,
                             int64_t* batch_hits, int64_t* batch_misses) {
  if (read_sys) *read_sys = g_read_syscalls.get();
  if (write_sys) *write_sys = g_write_syscalls.get();
  if (batch_hits) *batch_hits = g_batch_hits.get();
  if (batch_misses) *batch_misses = g_batch_misses.get();
}

int Socket::WriteSizeHist(int64_t* out, int n) {
  const int m = n < kWriteHistBuckets ? n : kWriteHistBuckets;
  for (int i = 0; i < m; ++i) {
    out[i] = g_write_size_hist[i].load(std::memory_order_relaxed);
  }
  return m;
}
// Per-socket unwritten-byte cap (reference FLAGS_socket_max_unwritten_bytes;
// EOVERCROWDED backpressure, socket.h:326-380).
static std::atomic<int64_t> g_overcrowded_limit{64 << 20};
// errno surfaced to on_failed when a backlog bound closes the socket
// (errors.py EOVERCROWDED).
constexpr int EOVERCROWDED_ERRNO = 1011;

int64_t Socket::active_count() { return g_active_sockets.load(std::memory_order_relaxed); }

void Socket::set_overcrowded_limit(int64_t bytes) {
  g_overcrowded_limit.store(bytes, std::memory_order_relaxed);
}
int64_t Socket::overcrowded_limit() {
  return g_overcrowded_limit.load(std::memory_order_relaxed);
}

static int make_nonblocking(int fd) {
  const int flags = fcntl(fd, F_GETFL, 0);
  return fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

// ---- versioned lifecycle ----

int Socket::Create(const SocketOptions& opts, SocketId* id_out) {
  uint32_t slot = 0;
  Socket* s = pool()->get_resource(&slot);
  if (s == nullptr) {
    BLOG(ERROR, "socket pool exhausted");
    return -1;
  }
  const uint64_t v = s->_vref.load(std::memory_order_acquire);
  const uint32_t version = (uint32_t)(v >> 32);  // even for a recycled slot
  s->_id = ((uint64_t)version << 32) | slot;
  s->_fd = opts.fd;
  s->_error_code = 0;
  s->_opts = opts;
  s->_out_buf.clear();
  s->_read_buf.clear();
  s->_parse = ParseState();
  s->_forced_protocol.store(-1, std::memory_order_relaxed);
  s->_filter_mode.store(false, std::memory_order_relaxed);  // recycled slot
  s->_write_stack.store(nullptr, std::memory_order_relaxed);
  s->_write_busy.store(false, std::memory_order_relaxed);
  s->_waiting_epollout.store(false, std::memory_order_relaxed);
  s->_pending_write.store(0, std::memory_order_relaxed);
  s->_fifo_q.store(nullptr, std::memory_order_relaxed);  // detached in cleanup
  s->_fifo_pending_bytes.store(0, std::memory_order_relaxed);
  s->_nread.store(0, std::memory_order_relaxed);
  s->_nwritten.store(0, std::memory_order_relaxed);
  s->_nmsg.store(0, std::memory_order_relaxed);
  s->_read_sys.store(0, std::memory_order_relaxed);
  s->_write_sys.store(0, std::memory_order_relaxed);
  s->FillRemoteAddr();
  if (opts.on_response != nullptr && !opts.response_inline) {
    // rpc client socket: responses ride the FIFO lane; create it HERE,
    // before the fd is armed, so SetFailed can never observe a missing
    // lane and deliver on_failed ahead of queued responses
    s->EnsureFifoLane();
  }
  // Publish with one "registration" ref (dropped by SetFailed).
  s->_vref.store(((uint64_t)version << 32) | 1, std::memory_order_release);
  g_active_sockets.fetch_add(1, std::memory_order_relaxed);
  *id_out = s->_id;
  butil::flight::record(butil::flight::EV_SOCK_CREATE, s->_id, opts.fd);
  if (opts.fd >= 0) {
    make_nonblocking(opts.fd);
    if (!opts.is_listener) {
      const int one = 1;
      setsockopt(opts.fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    if (!opts.defer_register &&
        EventDispatcher::GetDispatcher(opts.fd)->AddConsumer(s->_id, opts.fd) != 0) {
      SetFailed(s->_id, errno);
      return -1;
    }
  }
  return 0;
}

Socket* Socket::Address(SocketId id) {
  Socket* s = pool()->address((uint32_t)id);
  if (s == nullptr) return nullptr;
  uint64_t v = s->_vref.load(std::memory_order_acquire);
  const uint32_t want = (uint32_t)(id >> 32);
  while (true) {
    if ((uint32_t)(v >> 32) != want) return nullptr;
    if (s->_vref.compare_exchange_weak(v, v + 1, std::memory_order_acq_rel,
                                       std::memory_order_acquire)) {
      return s;
    }
  }
}

bool Socket::failed() const {
  // Alive iff the packed version still equals this socket's id version
  // (SetFailed bumps it to id_version+1, recycle to id_version+2).
  return (uint32_t)(_id >> 32) !=
         (uint32_t)(_vref.load(std::memory_order_acquire) >> 32);
}

int Socket::SetFailed(SocketId id, int error_code) {
  Socket* s = Socket::Address(id);
  if (s == nullptr) return -1;
  const uint32_t want = (uint32_t)(id >> 32);
  uint64_t v = s->_vref.load(std::memory_order_acquire);
  bool won = false;
  while ((uint32_t)(v >> 32) == want) {
    const uint64_t nv = ((uint64_t)(want + 1) << 32) | (uint32_t)v;
    if (s->_vref.compare_exchange_weak(v, nv, std::memory_order_acq_rel,
                                       std::memory_order_acquire)) {
      won = true;
      break;
    }
  }
  if (won) {
    s->_error_code = error_code;
    butil::flight::record(butil::flight::EV_SOCK_FAILED, id, error_code);
    // a KeepWrite fiber parked on writability must not sleep through the
    // failure (the dispatcher is being detached; no EPOLLOUT will come)
    s->_epollout_butex.value.fetch_add(1, std::memory_order_acq_rel);
    s->_epollout_butex.wake_all();
    if (s->_fd >= 0) EventDispatcher::GetDispatcher(s->_fd)->RemoveConsumer(s->_fd);
    if (s->_opts.on_failed != nullptr) {
      auto* q = s->_fifo_q.load(std::memory_order_acquire);
      if (q != nullptr) {
        // The failure notification must be delivered AFTER messages
        // already queued on the FIFO lane: a server that replies and
        // closes must not make the client see EFAILEDSOCKET before the
        // reply it already received (inline delivery used to give this
        // ordering for free).  We still hold the Address reference, so
        // cleanup's destroy() cannot have run: execute() is safe.
        struct FailNote {
          SocketFailedCallback cb;
          SocketId id;
          int err;
          void* user;
        };
        auto* note = new FailNote{s->_opts.on_failed, id, error_code,
                                  s->_opts.user};
        q->execute(bthread::TaskNode{
            [](void* arg) {
              auto* n = (FailNote*)arg;
              n->cb(n->id, n->err, n->user);
              delete n;
            },
            note});
      } else {
        s->_opts.on_failed(id, error_code, s->_opts.user);
      }
    }
    s->Dereference();  // drop the registration ref
  }
  s->Dereference();  // drop the Address ref
  return won ? 0 : -1;
}

void Socket::CloseFd() {
  if (_fd >= 0) {
    butil::flight::record(butil::flight::EV_SOCK_CLOSE, _id, _fd);
    close(_fd);
    _fd = -1;
  }
}

void Socket::Dereference() {
  const uint64_t v = _vref.fetch_sub(1, std::memory_order_acq_rel);
  if ((uint32_t)v != 1) return;
  // Last ref: recycle.  Version is odd (SetFailed ran); make it even for the
  // next Create so the slot can be reused with a fresh id.
  const uint32_t ver = (uint32_t)(v >> 32);
  CloseFd();
  WriteRequest* head = _write_stack.exchange(nullptr, std::memory_order_acquire);
  while (head != nullptr) {
    WriteRequest* next = head->next;
    delete head;
    head = next;
  }
  _out_buf.clear();
  _read_buf.clear();
  // no references can exist here (last deref): the session dies with us
  h2::H2Session* sess = _h2_session.exchange(nullptr,
                                             std::memory_order_acq_rel);
  delete sess;
  auto* q = _fifo_q.exchange(nullptr, std::memory_order_acq_rel);
  if (q != nullptr) {
    // destroy(): the (possibly currently-running) drainer consumes every
    // leftover message, then the queue deletes itself — no blocking, no
    // spinning, safe even when this Dereference is running INSIDE one of
    // the queue's own callbacks.
    q->destroy();
  }
  g_active_sockets.fetch_sub(1, std::memory_order_relaxed);
  const uint32_t slot = (uint32_t)_id;
  _vref.store((uint64_t)(ver + 1) << 32, std::memory_order_release);
  pool()->return_resource(slot);
}

void Socket::FillRemoteAddr() {
  _remote_ip[0] = 0;
  _remote_port = 0;
  if (_fd < 0) return;
  sockaddr_storage ss;
  socklen_t len = sizeof(ss);
  if (getpeername(_fd, (sockaddr*)&ss, &len) == 0) {
    if (ss.ss_family == AF_INET) {
      auto* a = (sockaddr_in*)&ss;
      inet_ntop(AF_INET, &a->sin_addr, _remote_ip, sizeof(_remote_ip));
      _remote_port = ntohs(a->sin_port);
    } else if (ss.ss_family == AF_INET6) {
      auto* a = (sockaddr_in6*)&ss;
      inet_ntop(AF_INET6, &a->sin6_addr, _remote_ip, sizeof(_remote_ip));
      _remote_port = ntohs(a->sin6_port);
    }
  }
}

// ---- write path (wait-free producers, single drainer) ----

// Dispatch-loop write batching: while DispatchMessages drains one read
// buffer, writes issued from that same thread to that same socket (inline
// native handlers' responses; inline response callbacks sending the next
// pipelined request) coalesce into one buffer flushed with a single
// syscall after the loop.  On a pipelined connection this turns K
// responses = K writev calls into 1, which is the difference between
// syscall-bound and memory-bound on small frames.
static thread_local Socket* tls_batch_socket = nullptr;
static thread_local butil::IOBuf* tls_batch_buf = nullptr;

butil::IOBuf* Socket::CurrentBatchFor(SocketId sid, size_t more) {
  Socket* s = tls_batch_socket;
  if (s == nullptr || s->_id != sid || s->failed()) return nullptr;
  const int64_t limit = g_overcrowded_limit.load(std::memory_order_relaxed);
  if (limit > 0 &&
      s->_pending_write.load(std::memory_order_relaxed) +
              (int64_t)tls_batch_buf->size() + (int64_t)more > limit) {
    return nullptr;  // stalled peer: Write path drops with EOVERCROWDED
  }
  g_batch_hits.add(1);
  return tls_batch_buf;
}

int Socket::Write(butil::IOBuf&& data, bool admitted) {
  const int64_t limit =
      admitted ? 0 : g_overcrowded_limit.load(std::memory_order_relaxed);
  if (tls_batch_socket == this) {
    // same failed() contract as the direct path; enqueued-then-failed
    // still drops data with only on_failed as the signal (identical to
    // the MPSC-stack path and the reference's WriteRequest semantics)
    if (failed()) return -1;
    // batch bytes are accounted when the guard flushes through Write;
    // the check here includes them so a stalled peer can't hide behind
    // the thread-local batch
    if (limit > 0 &&
        _pending_write.load(std::memory_order_relaxed) +
                (int64_t)tls_batch_buf->size() + (int64_t)data.size() > limit) {
      return -2;  // EOVERCROWDED
    }
    tls_batch_buf->append(std::move(data));
    g_batch_hits.add(1);
    return 0;
  }
  if (failed()) return -1;
  if (limit > 0 &&
      _pending_write.load(std::memory_order_relaxed) + (int64_t)data.size() >
          limit) {
    return -2;  // EOVERCROWDED
  }
  // `admitted` writes are the batch's own deferred flush — one write
  // carrying many coalesced frames — so only unadmitted direct writes
  // count as coalescing misses.
  if (!admitted) g_batch_misses.add(1);
  _pending_write.fetch_add((int64_t)data.size(), std::memory_order_relaxed);
  auto* req = new WriteRequest{std::move(data), nullptr};
  WriteRequest* old = _write_stack.load(std::memory_order_relaxed);
  do {
    req->next = old;
  } while (!_write_stack.compare_exchange_weak(old, req,
                                               std::memory_order_seq_cst,
                                               std::memory_order_relaxed));
  if (!_write_busy.exchange(true, std::memory_order_seq_cst)) {
    // We own the drain: write inline once on the caller thread (the wait-free
    // fast path — one syscall in caller context, reference socket.cpp:1748).
    DrainWriteQueue(false);
  }
  return 0;
}

void Socket::DrainWriteQueue(bool from_keepwrite) {
  while (true) {
    if (failed()) {
      int64_t dropped = (int64_t)_out_buf.size();
      WriteRequest* head = _write_stack.exchange(nullptr, std::memory_order_acquire);
      while (head != nullptr) {
        WriteRequest* next = head->next;
        dropped += (int64_t)head->data.size();
        delete head;
        head = next;
      }
      _out_buf.clear();
      _pending_write.fetch_sub(dropped, std::memory_order_relaxed);
      _write_busy.store(false, std::memory_order_seq_cst);
      return;
    }
    // Move queued requests into _out_buf in FIFO order (zero-copy).
    WriteRequest* head = _write_stack.exchange(nullptr, std::memory_order_seq_cst);
    WriteRequest* prev = nullptr;
    while (head != nullptr) {
      WriteRequest* next = head->next;
      head->next = prev;
      prev = head;
      head = next;
    }
    while (prev != nullptr) {
      _out_buf.append(std::move(prev->data));
      WriteRequest* next = prev->next;
      delete prev;
      prev = next;
    }
    if (_out_buf.empty()) {
      // Release with recheck (single-drainer protocol, see execution_queue.h).
      _write_busy.store(false, std::memory_order_seq_cst);
      if (_write_stack.load(std::memory_order_seq_cst) != nullptr &&
          !_write_busy.exchange(true, std::memory_order_seq_cst)) {
        continue;
      }
      return;
    }
    while (!_out_buf.empty()) {
      butil::flight::record(butil::flight::EV_WRITE_ENTER, _id,
                            (int64_t)_out_buf.size());
      const ssize_t nw = _out_buf.cut_into_file_descriptor(_fd);
      note_write_syscall(nw);
      _write_sys.fetch_add(1, std::memory_order_relaxed);
      butil::flight::record(butil::flight::EV_WRITE_EXIT, _id,
                            nw >= 0 ? (int64_t)nw : (int64_t)-errno);
      if (nw >= 0) {
        _nwritten.fetch_add(nw, std::memory_order_relaxed);
        _pending_write.fetch_sub(nw, std::memory_order_relaxed);
        g_total_written_bytes.add(nw);
        continue;
      }
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        // Hand the remainder to the KeepWrite FIBER: it parks on the
        // writability butex until OnWritable (or SetFailed) wakes it,
        // then continues draining — the reference's KeepWrite bthread
        // (socket.cpp:1800-1920) on the coroutine runtime.  Snapshot the
        // butex word BEFORE the epoll re-arm so an EPOLLOUT edge firing
        // between the re-arm and the fiber's park is never missed (the
        // wake bumps the word; the park's expected-value check fails and
        // the fiber proceeds immediately).
        Socket* self = Socket::Address(_id);
        if (self == nullptr) {
          // lost a race with SetFailed: the failed() branch on the next
          // KeepWrite pass would clean up, but there is no next pass —
          // drop the leftovers now
          int64_t dropped = (int64_t)_out_buf.size();
          _out_buf.clear();
          _pending_write.fetch_sub(dropped, std::memory_order_relaxed);
          _write_busy.store(false, std::memory_order_seq_cst);
          return;
        }
        const int32_t seq =
            _epollout_butex.value.load(std::memory_order_acquire);
        _waiting_epollout.store(true, std::memory_order_seq_cst);
        EventDispatcher::GetDispatcher(_fd)->Rearm(_id, _fd);
        KeepWriteFiber(self, seq).spawn();
        return;
      }
      SetFailed(_id, errno);
      break;  // failed() branch cleans up on the next loop
    }
  }
}

void Socket::OnWritable() {
  if (_waiting_epollout.exchange(false, std::memory_order_seq_cst)) {
    // Wake the parked KeepWrite fiber (resumes on the executor).
    _epollout_butex.value.fetch_add(1, std::memory_order_acq_rel);
    _epollout_butex.wake_all();
  }
}

// KeepWrite: park until writable (or failed), then resume the drain.
// Holds a socket reference for its whole life, so the slot cannot recycle
// under the parked frame; the 500ms timeout is a safety net that rechecks
// failed() even if a wake was somehow lost.
bthread::Fiber Socket::KeepWriteFiber(Socket* self, int32_t seq) {
  co_await self->_epollout_butex.wait(seq, 500 * 1000);
  self->DrainWriteQueue(true);
  self->Dereference();
}

// ---- read path ----

struct PendingMessage {
  SocketId sid;
  int kind;
  std::string meta;
  butil::IOBuf* body;
  MessageCallback cb;
  void* user;
};

static void run_message_task(void* arg) {
  auto* m = (PendingMessage*)arg;
  m->cb(m->sid, m->kind, m->meta.data(), m->meta.size(), m->body, m->user);
  delete m;  // callback owns *body (freed via C ABI)
}

void Socket::OnReadable() {
  if (_opts.is_listener) {
    DoAcceptLoop();
    return;
  }
  const bool filtered = _filter_mode.load(std::memory_order_acquire);
  while (true) {
    // Filter mode (in-socket TLS): ciphertext reads go into a LOCAL
    // portal and straight to the filter callback — _read_buf holds ONLY
    // injected plaintext, so split plaintext frames can never
    // interleave with later ciphertext reads.
    butil::IOPortal local;
    butil::IOPortal& buf = filtered ? local : _read_buf;
    butil::flight::record(butil::flight::EV_READ_ENTER, _id);
    const ssize_t nr = buf.append_from_file_descriptor(_fd, 256 * 1024);
    g_read_syscalls.add(1);
    _read_sys.fetch_add(1, std::memory_order_relaxed);
    butil::flight::record(butil::flight::EV_READ_EXIT, _id,
                          nr >= 0 ? (int64_t)nr : (int64_t)-errno);
    if (nr > 0) {
      _nread.fetch_add(nr, std::memory_order_relaxed);
      g_total_read_bytes.add(nr);
      if (filtered) {
        DeliverFiltered(&local);
      } else {
        DispatchMessages();
      }
      // Edge-triggered: must keep reading until EAGAIN.
      continue;
    }
    if (nr == 0) {
      SetFailed(_id, 0);  // clean EOF
      return;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK) return;
    if (errno == EINTR) continue;
    SetFailed(_id, errno);
    return;
  }
}

void Socket::DeliverFiltered(butil::IOPortal* cipher) {
  if (_opts.on_message == nullptr) {
    cipher->clear();
    return;
  }
  const int64_t bytes = (int64_t)cipher->size() + 256;
  auto* pm = new PendingMessage{_id, MSG_FILTERED, std::string(),
                                new butil::IOBuf(std::move(*cipher)),
                                _opts.on_message, _opts.user};
  // the FIFO lane keeps ciphertext chunks ordered for the TLS engine
  // (and orders them ahead of the failure notification)
  if (!FifoSubmit(run_message_task, pm, bytes)) {
    delete pm->body;
    delete pm;
  }
}

void Socket::InjectBytes(butil::IOBuf&& data) {
  // dispatcher-loop thread only (EventDispatcher::RunOnLoop): append the
  // filter's plaintext and run the normal parse/dispatch over it
  _read_buf.append(std::move(data));
  DispatchMessages();
}

struct FifoTask {
  Socket* owner;
  int64_t bytes;
  bthread::TaskFn fn;
  void* arg;
};

static void run_fifo_task(void* a) {
  auto* w = (FifoTask*)a;
  // release backlog credit BEFORE the callback (its work is the
  // consumer's cost, not queued bytes) — same discipline as
  // run_message_task
  w->owner->fifo_release(w->bytes);
  w->fn(w->arg);
  delete w;
}

bthread::ExecutionQueue<bthread::TaskNode>* Socket::EnsureFifoLane() {
  auto* q = _fifo_q.load(std::memory_order_acquire);
  if (q == nullptr) {
    // Creation sites: Create() (before the fd is armed — no concurrent
    // SetFailed can exist yet) and the dispatcher thread.  Without the
    // eager Create()-time lane for response sockets, a cross-thread
    // SetFailed racing the FIRST response's lazy creation could read
    // nullptr and deliver on_failed inline, overtaking that response.
    q = new bthread::ExecutionQueue<bthread::TaskNode>(
        bthread::Executor::global(),
        [](bthread::TaskNode& t) { t.fn(t.arg); });
    _fifo_q.store(q, std::memory_order_release);
  }
  return q;
}

bool Socket::FifoSubmit(bthread::TaskFn fn, void* arg, int64_t bytes) {
  auto* q = EnsureFifoLane();
  const int64_t limit = g_overcrowded_limit.load(std::memory_order_relaxed);
  if (bytes > 0 && limit > 0 &&
      _fifo_pending_bytes.load(std::memory_order_relaxed) + bytes > limit) {
    BLOG(WARNING, "socket %llu FIFO backlog over %lld bytes, closing",
         (unsigned long long)_id, (long long)limit);
    SetFailed(_id, EOVERCROWDED_ERRNO);
    return false;
  }
  if (bytes == 0) {
    // no accounting to release: skip the wrapper allocation entirely
    // (the rpc response hot path runs here once per call)
    q->execute(bthread::TaskNode{fn, arg});
    return true;
  }
  _fifo_pending_bytes.fetch_add(bytes, std::memory_order_relaxed);
  q->execute(bthread::TaskNode{run_fifo_task,
                               new FifoTask{this, bytes, fn, arg}});
  return true;
}

// Consecutive MSG_H2 frames coalesced into ONE FIFO delivery: at ~6
// frames per unary gRPC call, per-frame lane tasks + Python upcalls +
// GIL cycles were a visible slice of the h2 floor.  meta = the 9-byte
// frame headers concatenated (self-describing: payload length is the
// first 3 bytes of each header), body = payloads in order; h2.py
// feed_frames() walks them.
struct H2Accum {
  Socket* s = nullptr;
  std::string meta;
  butil::IOBuf body;
  int count = 0;

  void add(ParsedMessage& m) {
    meta.append(m.meta);
    body.append(std::move(m.body));
    ++count;
  }
  // Returns false when the socket failed (delivery impossible).
  bool flush() {
    if (count == 0) return true;
    const int64_t bytes = (int64_t)(meta.size() + body.size() + 256);
    auto* pm = new PendingMessage{s->id(), MSG_H2, std::move(meta),
                                  new butil::IOBuf(std::move(body)),
                                  s->_opts.on_message, s->_opts.user};
    meta.clear();
    body.clear();
    count = 0;
    if (!s->FifoSubmit(run_message_task, pm, bytes)) {
      delete pm->body;
      delete pm;
      return false;
    }
    return true;
  }
};

void Socket::DispatchMessages() {
  ParsedMessage msg;
  H2Accum h2acc;
  h2acc.s = this;
  if (_parse.detected == -1) {
    const int forced = _forced_protocol.load(std::memory_order_acquire);
    if (forced >= 0) _parse.detected = forced;
  }
  // arm the write batch for the duration of this drain (flushed by the
  // RAII guard on every exit path)
  butil::IOBuf batch_out;
  struct BatchGuard {
    Socket* s;
    butil::IOBuf* buf;
    ~BatchGuard() {
      tls_batch_socket = nullptr;
      tls_batch_buf = nullptr;
      if (!buf->empty()) s->Write(std::move(*buf), /*admitted=*/true);
    }
  } guard{this, &batch_out};
  tls_batch_socket = this;
  tls_batch_buf = &batch_out;
  while (true) {
    // TRPC in-place fast path: header+meta viewed in the read block —
    // no meta copy, no ParsedMessage round (a top-3 hot-path cost).
    // Falls back to the generic parser for split frames / other
    // protocols with nothing consumed.
    if (_parse.detected == MSG_TRPC && !_opts.native_echo &&
        (_opts.enable_rpc_dispatch || _opts.on_response != nullptr ||
         _opts.on_response_flat != nullptr)) {
      const char* mview = nullptr;
      size_t mlen = 0;
      const char* bview = nullptr;
      uint64_t blen = 0;
      uint64_t total = 0;
      const ParseResult r = parse_trpc_peek(&_read_buf, &mview, &mlen,
                                            &bview, &blen, &total);
      if (r == PARSE_NEED_MORE) {
        h2acc.flush();
        return;
      }
      if (r == PARSE_ERROR) {
        BLOG(WARNING, "parse error on socket %llu, closing",
             (unsigned long long)_id);
        h2acc.flush();  // frames parsed before the error stay ordered
        SetFailed(_id, EPROTO);  // ...ahead of the failure notification
        return;
      }
      if (mview != nullptr) {
        _nmsg.fetch_add(1, std::memory_order_relaxed);
        g_total_messages.add(1);
        // body also contiguous (the common case for small frames):
        // zero-ref flat dispatch — no pops yet, no body IOBuf, no block
        // refs; the response is staged flat into the write batch
        if (bview != nullptr || blen == 0) {
          if (TryDispatchTrpcFlat(_id, _opts, mview, mlen,
                                  bview != nullptr ? bview : "",
                                  (size_t)blen)) {
            _read_buf.pop_front(total);
            continue;
          }
        }
        // IOBuf path: take ONE guard ref so the meta view survives the
        // pops, then cut the body out
        butil::IOBuf meta_guard;  // NOT the write-batch RAII guard above
        meta_guard.add_block_ref(_read_buf.backing_block(0));
        _read_buf.pop_front(kTrpcHeaderLen + mlen);
        msg.body.clear();
        _read_buf.cutn(&msg.body, blen);
        if (TryDispatchTrpc(_id, _opts, mview, mlen, &msg.body)) {
          continue;
        }
        // not fast-dispatchable (stream frame, unknown method, generic
        // Python path): materialize the meta and take generic delivery
        msg.kind = MSG_TRPC;
        msg.meta.assign(mview, mlen);
        meta_guard.clear();
        goto generic_delivery;
      }
      // mview==nullptr: split frame or protocol re-detection — fall
      // through to the full parser
    }
    {
    const ParseResult r = parse_message(&_read_buf, &_parse, &msg);
    if (r == PARSE_NEED_MORE) {
      h2acc.flush();
      return;
    }
    if (r == PARSE_ERROR) {
      BLOG(WARNING, "parse error on socket %llu, closing",
           (unsigned long long)_id);
      h2acc.flush();
      SetFailed(_id, EPROTO);
      return;
    }
    }
    _nmsg.fetch_add(1, std::memory_order_relaxed);
    g_total_messages.add(1);
    if (_opts.native_echo && msg.kind == MSG_TRPC) {
      // Native echo service: reflect the frame without leaving C++.
      butil::IOBuf out;
      char hdr[kTrpcHeaderLen];
      make_trpc_header(hdr, (uint32_t)msg.meta.size(), msg.body.size());
      out.append(hdr, sizeof(hdr));
      out.append(msg.meta);
      out.append(std::move(msg.body));
      Write(std::move(out));
      msg.body.clear();
      continue;
    }
    if (msg.kind == MSG_TRPC &&
        (_opts.enable_rpc_dispatch || _opts.on_response != nullptr ||
         _opts.on_response_flat != nullptr)) {
      // Native unary hot path (net/rpc.h): parse meta, method lookup and
      // response packing in C++; Python sees pre-parsed requests only.
      // The gate must match the peek-path gate above: a flat-only client
      // still needs split-frame responses delivered (rpc.cc's flat-only
      // to_string branch), not dropped at generic_delivery.
      if (TryDispatchTrpc(_id, _opts, msg.meta.data(), msg.meta.size(),
                          &msg.body)) {
        continue;
      }
      // false: body untouched, fall through to the generic path
    }
  generic_delivery:
    if (msg.kind == MSG_H2 && _opts.h2_native) {
      // native h2 data plane: frames feed the in-socket session
      // (framing/HPACK/flow control/gRPC dispatch in C++; Python is
      // upcalled per message, not per frame)
      h2::H2Session* sess = _h2_session.load(std::memory_order_relaxed);
      if (sess == nullptr) {
        sess = new h2::H2Session(_id);
        _h2_session.store(sess, std::memory_order_release);
      }
      if (!sess->OnFrames(msg.meta.data(), msg.meta.size(), &msg.body)) {
        BLOG(WARNING, "h2 session error on socket %llu, closing",
             (unsigned long long)_id);
        msg.body.clear();
        // flush the batch NOW (it holds the session's GOAWAY): the
        // guard's exit-path flush would be rejected once the socket is
        // failed, and the peer would never learn why it died.  Clear
        // the TLS batch pointers FIRST (the guard's order): Write's
        // drain can re-enter dispatch-adjacent code that must not see
        // a half-flushed batch as current.
        tls_batch_socket = nullptr;
        tls_batch_buf = nullptr;
        if (!batch_out.empty()) Write(std::move(batch_out), true);
        SetFailed(_id, EPROTO);
        return;
      }
      msg.body.clear();
      continue;
    }
    if (_opts.on_message == nullptr) {
      msg.body.clear();
      continue;
    }
    if (kind_requires_fifo(msg.kind)) {
      if (msg.kind == MSG_H2) {
        // coalesce consecutive h2 frames; bounded so one drain can't
        // build an unbounded delivery
        h2acc.add(msg);
        if (h2acc.count >= 64 || h2acc.body.size() > (256 << 10)) {
          if (!h2acc.flush()) return;
        }
        continue;
      }
      // a different FIFO kind: deliver pending h2 frames FIRST so the
      // lane preserves arrival order
      if (!h2acc.flush()) return;
      // RESP/memcache pipelining, h2 HPACK + stream state, thrift/mongo
      // reply order and raw streaming all make per-connection FIFO part
      // of the protocol contract.  Deliver through this socket's
      // ExecutionQueue: order is preserved (serialized drain) but the
      // GIL-bound Python callback runs on an executor worker, not the
      // dispatcher thread — one slow connection can no longer stall the
      // whole event loop (the reference's per-stream ExecutionQueue,
      // stream_impl.h:133, in the socket's FIFO slot).
      // read-side EOVERCROWDED: inline delivery used to throttle reads
      // naturally; a queued lane needs an explicit bound or a fast peer
      // with a slow consumer grows memory without limit (same limit as
      // the write side)
      const int64_t msg_bytes =
          (int64_t)(msg.meta.size() + msg.body.size() + 256);
      auto* pm = new PendingMessage{_id, msg.kind, std::move(msg.meta),
                                    new butil::IOBuf(std::move(msg.body)),
                                    _opts.on_message, _opts.user};
      if (!FifoSubmit(run_message_task, pm, msg_bytes)) {
        delete pm->body;   // overcrowded: socket failed, task not queued
        delete pm;
        return;
      }
      continue;
    }
    if (!h2acc.flush()) return;   // order vs non-FIFO deliveries too
    auto* pm = new PendingMessage{_id, msg.kind, std::move(msg.meta),
                                  new butil::IOBuf(std::move(msg.body)),
                                  _opts.on_message, _opts.user};
    bthread::Executor::global()->submit(run_message_task, pm);
  }
}

void Socket::DoAcceptLoop() {
  while (true) {
    sockaddr_storage ss;
    socklen_t len = sizeof(ss);
    const int fd = accept4(_fd, (sockaddr*)&ss, &len, SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return;
      if (errno == EINTR || errno == ECONNABORTED) continue;
      BLOG(WARNING, "accept4 failed: %d", errno);
      return;
    }
    SocketOptions copts = _opts;
    copts.fd = fd;
    copts.is_listener = false;
    copts.defer_register = true;
    SocketId cid;
    if (Socket::Create(copts, &cid) != 0) continue;
    // Callback BEFORE the fd can generate events: the consumer registers
    // its handler for cid here, so the first message can't outrun it.
    if (_opts.on_accepted != nullptr) {
      _opts.on_accepted(_id, cid, _opts.user);
    }
    if (EventDispatcher::GetDispatcher(fd)->AddConsumer(cid, fd) != 0) {
      Socket::SetFailed(cid, errno);
    }
  }
}

// ---- connect / listen ----

// "unix:/path" addresses select AF_UNIX (reference butil/unix_socket.*;
// EndPoint UDS support, SURVEY §2.1) — same Socket machinery, different
// address family.
static socklen_t fill_sockaddr_un(const char* path, sockaddr_un* sa) {
  memset(sa, 0, sizeof(*sa));
  sa->sun_family = AF_UNIX;
  const size_t n = strlen(path);
  if (n >= sizeof(sa->sun_path)) return 0;  // overlong path
  memcpy(sa->sun_path, path, n);
  return (socklen_t)(offsetof(sockaddr_un, sun_path) + n + 1);
}

static int connect_unix(const char* path, const SocketOptions& opts,
                        SocketId* id) {
  sockaddr_un sa;
  const socklen_t len = fill_sockaddr_un(path, &sa);
  if (len == 0) return -1;
  const int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) return -1;
  if (connect(fd, (sockaddr*)&sa, len) != 0) {
    close(fd);
    return -1;
  }
  SocketOptions o = opts;
  o.fd = fd;
  return Socket::Create(o, id);
}

static int listen_unix(const char* path, const SocketOptions& opts,
                       SocketId* id, int* bound_port) {
  sockaddr_un sa;
  const socklen_t len = fill_sockaddr_un(path, &sa);
  if (len == 0) return -1;
  // Remove ONLY a stale socket file: unlinking whatever happens to live
  // at a typo'd path (a regular file, a directory) would destroy user
  // data before bind even fails.
  struct stat st;
  if (lstat(path, &st) == 0) {
    if (!S_ISSOCK(st.st_mode)) {
      errno = EEXIST;
      return -1;
    }
    unlink(path);
  }
  const int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) return -1;
  if (bind(fd, (sockaddr*)&sa, len) != 0 || listen(fd, 1024) != 0) {
    close(fd);
    return -1;
  }
  if (bound_port != nullptr) *bound_port = 0;  // no port space on UDS
  SocketOptions o = opts;
  o.fd = fd;
  o.is_listener = true;
  return Socket::Create(o, id);
}

int Connect(const char* host, int port, const SocketOptions& opts, SocketId* id) {
  if (strncmp(host, "unix:", 5) == 0) {
    return connect_unix(host + 5, opts, id);
  }
  addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || res == nullptr) {
    return -1;
  }
  int fd = -1;
  for (addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return -1;
  SocketOptions o = opts;
  o.fd = fd;
  return Socket::Create(o, id);
}

int Listen(const char* addr, int port, const SocketOptions& opts, SocketId* id,
           int* bound_port) {
  if (addr != nullptr && strncmp(addr, "unix:", 5) == 0) {
    return listen_unix(addr + 5, opts, id, bound_port);
  }
  const int fd = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) return -1;
  const int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_port = htons((uint16_t)port);
  if (addr == nullptr || addr[0] == 0) {
    sa.sin_addr.s_addr = htonl(INADDR_ANY);
  } else if (inet_pton(AF_INET, addr, &sa.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  if (bind(fd, (sockaddr*)&sa, sizeof(sa)) != 0 || listen(fd, 1024) != 0) {
    close(fd);
    return -1;
  }
  if (bound_port != nullptr) {
    socklen_t len = sizeof(sa);
    getsockname(fd, (sockaddr*)&sa, &len);
    *bound_port = ntohs(sa.sin_port);
  }
  SocketOptions o = opts;
  o.fd = fd;
  o.is_listener = true;
  return Socket::Create(o, id);
}

}  // namespace brpc
