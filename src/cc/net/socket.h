// Socket — the central connection object (SURVEY.md §2.3; reference
// src/brpc/socket.{h,cpp}).
//
// Shapes kept from the reference, re-implemented:
//  * Versioned addressing: SocketId = version⊕slot over a ResourcePool;
//    Address() only yields a pointer while the packed (version|nref) word
//    matches, so stale handles fail instead of racing (socket_id.h:26-34,
//    versioned_ref_with_id.h).  SetFailed bumps the version.
//  * Wait-free write: Write() pushes onto a lock-free MPSC stack; exactly one
//    drainer exists at a time (busy-flag protocol); the thread that takes the
//    flag writes inline once and hands leftovers to a KeepWrite task that
//    waits for EPOLLOUT on EAGAIN (socket.cpp:1692-1920 behavior).
//  * Input side: edge-triggered read into an IOPortal, protocol parse cuts
//    messages, each message dispatched as one Executor task (the "one bthread
//    per message" rule, input_messenger.cpp:175-213).
#pragma once

#include <atomic>
#include <cstdint>
#include <mutex>
#include <vector>

#include "bthread/butex.h"
#include "bthread/execution_queue.h"
#include "bthread/fiber.h"
#include "butil/common.h"
#include "butil/iobuf.h"
#include "butil/resource_pool.h"
#include "net/parser.h"
#include "net/rpc.h"

namespace brpc {

typedef uint64_t SocketId;
constexpr SocketId INVALID_SOCKET_ID = (SocketId)-1;

class EventDispatcher;
class Socket;
namespace h2 {
class H2Session;
}

// Complete-message callback.  kind: see parser.h MessageKind.
// meta/meta_len: contiguous protocol meta bytes (frame header payload).
// body: heap IOBuf* whose ownership passes to the callback.
typedef void (*MessageCallback)(SocketId sid, int kind, const char* meta,
                                size_t meta_len, butil::IOBuf* body,
                                void* user);
// Called once when a socket transitions to failed.
typedef void (*SocketFailedCallback)(SocketId sid, int error_code, void* user);
// Called for a listening socket when a new connection is accepted.
typedef void (*AcceptedCallback)(SocketId listener, SocketId conn, void* user);

struct SocketOptions {
  int fd = -1;
  MessageCallback on_message = nullptr;
  SocketFailedCallback on_failed = nullptr;
  AcceptedCallback on_accepted = nullptr;  // listener sockets only
  void* user = nullptr;
  bool is_listener = false;
  // Echo TRPC frames back in native code without surfacing to the callback
  // (benchmark fast path; models a native service implementation).
  bool native_echo = false;
  // Don't register with the dispatcher inside Create; the caller will.
  // Accepted sockets need this: their on_accepted callback must run before
  // any IO event can fire (the fd may land on a DIFFERENT dispatcher thread,
  // which would otherwise race handler registration with the first message).
  bool defer_register = false;
  // Native RPC fast path (net/rpc.h).  When a TRPC RESPONSE meta parses
  // cleanly, it is delivered pre-parsed here instead of on_message.
  ResponseCallback on_response = nullptr;
  void* response_user = nullptr;
  // Run on_response inline on the dispatcher thread with a BORROWED body
  // (callee must not free it) instead of an executor task with an owned
  // heap body.  Only for non-blocking native callbacks (the bench pump);
  // writes issued from the callback join the dispatch write batch.
  bool response_inline = false;
  // Zero-ref inline response delivery: when set (implies the
  // response_inline contract), a response whose body is contiguous in
  // the read block is delivered as a flat view — no body IOBuf, no
  // block refs.  Split/oversized bodies still arrive via on_response.
  ResponseFlatCallback on_response_flat = nullptr;
  // Opt in to native REQUEST dispatch via the MethodRegistry (server
  // sockets); off by default so raw-frame users see every message.
  bool enable_rpc_dispatch = false;
  // Native h2/gRPC server data plane (net/h2.h): MSG_H2 frames feed an
  // in-socket H2Session (framing, HPACK, flow control, gRPC dispatch in
  // C++) instead of being delivered to on_message.  Server sockets only.
  bool h2_native = false;
};

struct WriteRequest {
  butil::IOBuf data;
  WriteRequest* next = nullptr;
};

class Socket {
 public:
  // ---- lifecycle (static, pool-based) ----
  static int Create(const SocketOptions& opts, SocketId* id);
  // Returns a referenced Socket* or nullptr if the id is stale/failed.
  // Callers MUST pair with Dereference().
  static Socket* Address(SocketId id);
  static int SetFailed(SocketId id, int error_code);
  static int64_t active_count();
  // Process-wide traffic totals (bvar combiner cells; SURVEY §2.7).
  static void GlobalTraffic(int64_t* nread, int64_t* nwritten, int64_t* nmsg);
  // Syscall attribution (ISSUE 15 / ROADMAP 1(e)): process-wide read/
  // write syscall counts plus the dispatch write batch's coalescing
  // hit/miss counters — the before/after metric for frame coalescing.
  static void SyscallCounters(int64_t* read_sys, int64_t* write_sys,
                              int64_t* batch_hits, int64_t* batch_misses);
  // bytes-per-write histogram: log2 buckets starting at <=64B; bucket i
  // counts writes of size in (64*2^(i-1), 64*2^i], the last bucket is
  // open-ended.  Fills up to n buckets, returns the bucket count.
  static constexpr int kWriteHistBuckets = 16;
  static int WriteSizeHist(int64_t* out, int n);

  void Dereference();

  // ---- IO ----
  // Queue a frame for writing (wait-free producer side).  Takes ownership of
  // data's refs.  Returns 0, -1 if the socket is failed, or -2
  // (EOVERCROWDED) when the socket's unwritten backlog exceeds the
  // overcrowded limit — the reference's EOVERCROWDED backpressure
  // (socket.h:326-380): a stalled peer must surface as an error to
  // producers, not as unbounded memory growth.  `admitted` skips the
  // overcrowded check — only for bytes already admitted per-append by the
  // dispatch write batch (rejecting its deferred flush would drop them).
  int Write(butil::IOBuf&& data, bool admitted = false);
  // The dispatch-loop write batch for `sid`, when the CALLING thread is
  // inside DispatchMessages for that socket (inline handlers/response
  // callbacks); nullptr otherwise.  Packing frames straight into this
  // buffer skips the whole intermediate-IOBuf + Write() round per frame
  // — the per-message block-ref churn was 20%+ of the echo hot path.
  // `more` = bytes the caller is about to append: the overcrowded limit
  // is enforced HERE (nullptr on exceed → caller takes the Write path,
  // which drops with -2), since the batch flushes with admitted=true.
  static butil::IOBuf* CurrentBatchFor(SocketId sid, size_t more = 0);
  // Enqueue a task on this socket's per-connection FIFO lane
  // (ExecutionQueue), creating the lane on first use.  DISPATCHER-THREAD
  // ONLY (lane creation and ordering assume it).  `bytes` counts against
  // the read-side EOVERCROWDED bound; on overflow the socket is failed
  // and false is returned (the task was NOT queued).  Tasks run in
  // submission order, and SetFailed's on_failed notification rides the
  // SAME lane — so a peer close can never overtake queued deliveries.
  bool FifoSubmit(bthread::TaskFn fn, void* arg, int64_t bytes);
  // Create the FIFO lane if absent.  Safe only from Create() (pre-arm)
  // or the dispatcher thread.
  bthread::ExecutionQueue<bthread::TaskNode>* EnsureFifoLane();
  // Bytes accepted by Write but not yet written to the fd.
  int64_t pending_write_bytes() const {
    return _pending_write.load(std::memory_order_relaxed);
  }
  // Process-wide backlog cap per socket; 0 disables (reference
  // FLAGS_socket_max_unwritten_bytes, default 64MB).
  static void set_overcrowded_limit(int64_t bytes);
  static int64_t overcrowded_limit();
  int fd() const { return _fd; }
  SocketId id() const { return _id; }
  bool failed() const;
  // The native h2 server session, if this socket has one (created by the
  // dispatch thread on the first MSG_H2 frame when opts.h2_native).
  // Callers must hold an Address() reference.
  h2::H2Session* h2_session() const {
    return _h2_session.load(std::memory_order_acquire);
  }

  // stats (exported through bvar)
  int64_t bytes_read() const { return _nread.load(std::memory_order_relaxed); }
  int64_t bytes_written() const { return _nwritten.load(std::memory_order_relaxed); }
  int64_t messages_read() const { return _nmsg.load(std::memory_order_relaxed); }
  int64_t read_syscalls() const {
    return _read_sys.load(std::memory_order_relaxed);
  }
  int64_t write_syscalls() const {
    return _write_sys.load(std::memory_order_relaxed);
  }
  int64_t remote_port() const { return _remote_port; }
  const char* remote_ip() const { return _remote_ip; }

  // Pre-select the wire protocol for this connection (client sockets whose
  // peer's first bytes are ambiguous or absent: h2 upgrades, mongo, raw
  // streaming reads).  Safe to call before the first byte arrives; applied
  // by the dispatcher thread at next parse.
  void set_forced_protocol(int kind) {
    _forced_protocol.store(kind, std::memory_order_release);
  }

  // Transport filter (in-socket TLS): ALL inbound bytes are delivered
  // as MSG_FILTERED ciphertext to on_message (per-connection FIFO lane)
  // instead of being parsed; the filter re-injects plaintext via
  // InjectBytes.  Set BEFORE the first byte parses (accepted-callback /
  // right after connect).
  void set_filter_mode(bool on) {
    _filter_mode.store(on, std::memory_order_release);
  }
  // Dispatcher-loop-thread ONLY (route via EventDispatcher::RunOnLoop):
  // append plaintext to the read buffer and run the normal parse.
  void InjectBytes(butil::IOBuf&& data);
  int dispatcher_shard() const { return _fd; }  // for GetDispatcher routing

  // ---- called by EventDispatcher ----
  void OnReadable();
  void OnWritable();

  // FIFO-lane backlog credit return (run_message_task).
  void fifo_release(int64_t n) {
    _fifo_pending_bytes.fetch_sub(n, std::memory_order_relaxed);
  }

  Socket() = default;

 private:
  friend class EventDispatcher;
  friend struct H2Accum;   // frame-coalescing helper in socket.cc

  void DoAcceptLoop();
  void DeliverFiltered(butil::IOPortal* cipher);
  static bthread::Fiber KeepWriteFiber(Socket* self, int32_t seq);
  void DrainWriteQueue(bool from_keepwrite);
  void ReleaseWriterAndMaybeResume();
  bool BecomeWriter();  // busy-flag acquire
  void DispatchMessages();
  void CloseFd();
  void FillRemoteAddr();

  // packed (version<<32 | nref); even version = alive
  std::atomic<uint64_t> _vref{0};
  SocketId _id = INVALID_SOCKET_ID;
  int _fd = -1;
  int _error_code = 0;
  SocketOptions _opts;

  // write path
  std::atomic<WriteRequest*> _write_stack{nullptr};
  std::atomic<bool> _write_busy{false};
  std::atomic<bool> _waiting_epollout{false};
  // Writability butex: the KeepWrite FIBER parks here on EAGAIN and
  // OnWritable / SetFailed bump + wake it — the reference's KeepWrite is
  // a bthread blocking on EPOLLOUT (socket.cpp:1800-1920), and this is
  // that shape on the coroutine runtime (in-core user of butex).
  bthread::Butex _epollout_butex;
  std::atomic<int64_t> _pending_write{0};  // queued + _out_buf bytes
  butil::IOBuf _out_buf;  // drainer-owned unwritten bytes

  // read path
  butil::IOPortal _read_buf;
  ParseState _parse;
  std::atomic<int> _forced_protocol{-1};
  std::atomic<bool> _filter_mode{false};
  // FIFO-protocol delivery lane (redis/h2/thrift/streams): an
  // ExecutionQueue per socket preserves per-connection order while
  // moving Python-bound callbacks OFF the dispatcher thread — the
  // reference's per-stream ExecutionQueue slot (stream_impl.h:133).
  // Created lazily by the dispatcher thread; torn down via the queue's
  // destroy() protocol (the drainer consumes leftovers then deletes
  // itself) so a callback that drops the socket's last reference can't
  // deadlock or spin on its own drain.  Atomic: SetFailed (any thread)
  // routes the failure notification through it to stay ordered AFTER
  // already-queued messages.
  std::atomic<bthread::ExecutionQueue<bthread::TaskNode>*> _fifo_q{nullptr};
  // FIFO backlog accounting for the EOVERCROWDED read-side bound.
  std::atomic<int64_t> _fifo_pending_bytes{0};

  std::atomic<int64_t> _nread{0}, _nwritten{0}, _nmsg{0};
  // per-socket syscall attribution (ISSUE 15): how many read/write
  // syscalls this connection has cost, next to the byte totals above
  std::atomic<int64_t> _read_sys{0}, _write_sys{0};
  // Native h2 server session (opts.h2_native): created on the dispatch
  // thread, read by response threads under an Address() reference,
  // deleted at slot recycle (when no references can exist).
  std::atomic<h2::H2Session*> _h2_session{nullptr};
  char _remote_ip[46] = {0};
  int _remote_port = 0;
};

// Connect to host:port (blocking connect on caller thread; the reference uses
// bthread_connect, we accept the one-time syscall).  Returns 0 and sets *id.
int Connect(const char* host, int port, const SocketOptions& opts, SocketId* id);

// Listen on addr:port and accept connections; each accepted socket inherits
// the message callbacks from `opts` (acceptor role, reference acceptor.cpp).
int Listen(const char* addr, int port, const SocketOptions& opts, SocketId* id,
           int* bound_port);

}  // namespace brpc
