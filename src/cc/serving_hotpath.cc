// Native serving hot path (ISSUE 9) — the per-token and per-frame work
// the Python serving stack pushes down into the core so the GIL stops
// being the ceiling:
//
//   * TokenRing — bounded emit ring between the shared decode step loop
//     and one request's emitter.  The step loop pushes ONE batch call
//     per step across every active slot (brpc_tokring_push_many: ctypes
//     releases the GIL for the call's duration), and the emitter drains
//     MANY tokens per wakeup (brpc_tokring_pop_many) instead of paying a
//     Python lock round-trip per token.  The PR 3 contract is preserved
//     natively: push never blocks (a full ring returns 0 and the engine
//     cuts the consumer with EOVERCROWDED), the terminal marker is
//     always accepted and only surfaces after every buffered token, and
//     a global live-ring counter keeps the chaos suite's leak baselines
//     honest.
//   * brpc_batch_pad — DynamicBatcher formation's zero-fill + row
//     gather/pad as one GIL-released memset/memcpy pass (bucket choice,
//     EDF lanes and shed policy stay in Python where policy lives).
//   * brpc_page_table_fill — the engine's fixed-shape per-slot page
//     table gather, same discipline.
//
// Everything here is standalone (mutex + condvar, no Executor
// dependency) so the ring also works before brpc_core_init and inside
// forked bench subprocesses.
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>

#include "butil/flight.h"

namespace {

struct TokenRing {
  explicit TokenRing(int cap_) : cap(cap_ > 0 ? cap_ : 1) {
    buf = new int32_t[cap];
  }
  ~TokenRing() { delete[] buf; }

  std::mutex mu;
  std::condition_variable cv;
  int32_t* buf;
  int cap;
  int head = 0;   // next pop index
  int count = 0;  // tokens buffered
  bool terminal = false;
  int32_t terminal_err = 0;  // 0 = clean completion
  // flight-recorder sampling counters (ISSUE 15): pop and full-ring
  // events record 1-in-64 — the autopsy needs "is this ring still
  // advancing, roughly when did it last", not a per-token ledger, and
  // a per-token event would blow the <2% emit_fanout overhead gate.
  std::atomic<uint64_t> pops{0};
  std::atomic<uint64_t> fulls{0};

  // push under mu; returns false when full (never blocks, never grows)
  bool push_locked(int32_t tok) {
    if (count >= cap) return false;
    buf[(head + count) % cap] = tok;
    ++count;
    return true;
  }
};

std::atomic<int64_t> g_live_rings{0};

}  // namespace

extern "C" {

void* brpc_tokring_new(int cap) {
  g_live_rings.fetch_add(1, std::memory_order_relaxed);
  return new TokenRing(cap);
}

void brpc_tokring_free(void* h) {
  if (h == nullptr) return;
  g_live_rings.fetch_sub(1, std::memory_order_relaxed);
  delete (TokenRing*)h;
}

int64_t brpc_tokring_live() {
  return g_live_rings.load(std::memory_order_relaxed);
}

int brpc_tokring_push(void* h, int32_t tok) {
  auto* r = (TokenRing*)h;
  bool ok;
  {
    std::lock_guard<std::mutex> g(r->mu);
    ok = r->push_locked(tok);
  }
  if (ok) {
    r->cv.notify_one();
  } else if ((r->fulls.fetch_add(1, std::memory_order_relaxed) & 63) ==
             0) {
    // flight granularity (butil/flight.h): the per-token success path
    // records nothing — only the interesting transition (ring full,
    // the engine is about to cut this consumer) leaves an event, and
    // sampled at that, since a spinning producer hits full per token
    butil::flight::record(butil::flight::EV_RING_FULL,
                          (uint64_t)(uintptr_t)h);
  }
  return ok ? 1 : 0;
}

// One call per decode step: push toks[i] onto rings[i] for every active
// slot.  ok_out[i] = 1 on success, 0 when that ring is full (the caller
// cuts that consumer with EOVERCROWDED).  Returns the success count.
// The step loop holds Python references to every ring's wrapper while
// this runs, so the raw handles cannot be freed under us.
int brpc_tokring_push_many(void** rings, const int32_t* toks, int n,
                           uint8_t* ok_out) {
  int ok = 0;
  for (int i = 0; i < n; ++i) {
    auto* r = (TokenRing*)rings[i];
    bool pushed;
    {
      std::lock_guard<std::mutex> g(r->mu);
      pushed = r->push_locked(toks[i]);
    }
    if (pushed) {
      r->cv.notify_one();
      ++ok;
    }
    if (ok_out != nullptr) ok_out[i] = pushed ? 1 : 0;
  }
  // one event per STEP CALL, not per ring — what the wedge autopsy
  // needs ("did the step loop keep advancing?") at batch cost
  butil::flight::record(butil::flight::EV_RING_PUSH,
                        n > 0 ? (uint64_t)(uintptr_t)rings[0] : 0, ok);
  return ok;
}

// Always accepted (a cut/finished request must be able to flush and
// notify); first terminal wins.  Returns 1 when THIS call installed the
// terminal, 0 when one was already present — the Python wrapper uses
// the same exactly-once decision for its error-object slot.
int brpc_tokring_push_terminal(void* h, int32_t err_code) {
  auto* r = (TokenRing*)h;
  bool first;
  {
    std::lock_guard<std::mutex> g(r->mu);
    first = !r->terminal;
    if (first) {
      r->terminal = true;
      r->terminal_err = err_code;
    }
  }
  r->cv.notify_all();
  butil::flight::record(butil::flight::EV_RING_TERMINAL,
                        (uint64_t)(uintptr_t)h, err_code);
  return first ? 1 : 0;
}

// Drain up to `cap` tokens into `out`; blocks up to timeout_us when the
// ring is empty and no terminal is set.  *terminal_out becomes 1 only
// once the ring is EMPTY and the terminal marker is present (tokens
// always flush before the terminal — the exactly-once contract's
// ordering half); *err_out then carries the terminal code.
int brpc_tokring_pop_many(void* h, int32_t* out, int cap,
                          int64_t timeout_us, int* terminal_out,
                          int32_t* err_out) {
  auto* r = (TokenRing*)h;
  if (terminal_out != nullptr) *terminal_out = 0;
  std::unique_lock<std::mutex> g(r->mu);
  if (r->count == 0 && !r->terminal && timeout_us > 0) {
    r->cv.wait_for(g, std::chrono::microseconds(timeout_us), [r] {
      return r->count > 0 || r->terminal;
    });
  }
  int n = 0;
  while (n < cap && r->count > 0) {
    out[n++] = r->buf[r->head];
    r->head = (r->head + 1) % r->cap;
    --r->count;
  }
  bool saw_term = false;
  if (r->count == 0 && r->terminal && terminal_out != nullptr) {
    *terminal_out = 1;
    if (err_out != nullptr) *err_out = r->terminal_err;
    saw_term = true;
  }
  g.unlock();  // the record below must not stretch the ring mutex
  if (n > 0 || saw_term) {
    const uint64_t k = r->pops.fetch_add(1, std::memory_order_relaxed);
    if (saw_term || (k & 63) == 0) {
      butil::flight::record(butil::flight::EV_RING_POP,
                            (uint64_t)(uintptr_t)h, n);
    }
  }
  return n;
}

int64_t brpc_tokring_size(void* h) {
  auto* r = (TokenRing*)h;
  std::lock_guard<std::mutex> g(r->mu);
  return r->count;
}

// ---- batch assembly (DynamicBatcher._execute's gather/pad) ----

// Zero-fill `out` (rows * stride_bytes) then copy row i's row_bytes[i]
// payload to out + i*stride_bytes.  One GIL-released pass replaces the
// np.zeros + per-row slice-assign loop that serialized formation
// against every other Python thread.
void brpc_batch_pad(const void** rows, const int64_t* row_bytes, int n,
                    void* out, int64_t stride_bytes, int64_t total_bytes) {
  memset(out, 0, (size_t)total_bytes);
  char* base = (char*)out;
  for (int i = 0; i < n; ++i) {
    int64_t m = row_bytes[i];
    // defensive truncate to the bucket width, same contract as the
    // fastrpc entry and brpc_page_table_fill: an oversized row must
    // not memcpy past its stride (or past total_bytes on the last row)
    if (m > stride_bytes) m = stride_bytes;
    if (m > 0) {
      memcpy(base + (int64_t)i * stride_bytes, rows[i], (size_t)m);
    }
  }
}

// ---- page-table gather (DecodeEngine._gather_page_tables) ----

// Fill the fixed-shape [num_slots, max_pages] int32 table with -1, then
// copy each active slot's page-id list into its row (truncated to
// max_pages).  lists[i] points at slot slot_idx[i]'s contiguous int32
// page ids.
void brpc_page_table_fill(const int32_t** lists, const int64_t* lens,
                          const int32_t* slot_idx, int n, int32_t* table,
                          int num_slots, int max_pages) {
  const int64_t total = (int64_t)num_slots * max_pages;
  for (int64_t i = 0; i < total; ++i) table[i] = -1;
  for (int i = 0; i < n; ++i) {
    int64_t m = lens[i];
    if (m > max_pages) m = max_pages;
    if (m > 0) {
      memcpy(table + (int64_t)slot_idx[i] * max_pages, lists[i],
             (size_t)m * sizeof(int32_t));
    }
  }
}

}  // extern "C"
