// Lock-free MPSC span queue (ISSUE 9's Treiber stack, extracted in
// ISSUE 14 so the SAME producer/drain algorithm the Python extension
// runs (fastrpc_module.cc py_spanq_*) is exercisable under
// -fsanitize=thread without linking Python — src/cc/test/
// ring_stress_main.cc churns it beside the TokenRing (`make tsan`).
//
// Shape: many producers CAS-push nodes (release); one drainer
// exchanges the whole stack (acquire) and reverses to FIFO.  Payloads
// are opaque void* — the extension stores PyObject* (incref'd under
// the GIL before push, ref stolen by the drained list).
#pragma once

#include <atomic>
#include <cstdint>

namespace brpc_spanq {

struct Node {
  void* obj;
  Node* next;
};

struct Stack {
  std::atomic<Node*> head{nullptr};
  std::atomic<int64_t> pending{0};

  // Re-link an existing node (the drain failure path re-pushes a
  // detached chain without reallocating).
  void push_node(Node* n) {
    Node* old = head.load(std::memory_order_relaxed);
    do {
      n->next = old;
    } while (!head.compare_exchange_weak(old, n, std::memory_order_release,
                                         std::memory_order_relaxed));
    pending.fetch_add(1, std::memory_order_relaxed);
  }

  void push(void* obj) { push_node(new Node{obj, nullptr}); }

  // Detach everything and reverse to FIFO submission order.  The
  // caller owns the returned chain (and must delete its nodes);
  // `pending` drops by the returned count.
  Node* drain_fifo(int64_t* count_out = nullptr) {
    Node* h = head.exchange(nullptr, std::memory_order_acquire);
    Node* prev = nullptr;
    int64_t count = 0;
    while (h != nullptr) {
      Node* next = h->next;
      h->next = prev;
      prev = h;
      h = next;
      ++count;
    }
    pending.fetch_sub(count, std::memory_order_relaxed);
    if (count_out != nullptr) *count_out = count;
    return prev;
  }

  int64_t count() const {
    return pending.load(std::memory_order_relaxed);
  }
};

}  // namespace brpc_spanq
