// Ring/spanq stress under -fsanitize=thread (ISSUE 14; `make tsan`).
//
// Exercises the two lock-free/condvar structures of the serving hot
// path exactly as production drives them:
//
//   * TokenRing (src/cc/serving_hotpath.cc): one step-loop thread
//     batch-pushing across many rings (brpc_tokring_push_many — the
//     per-decode-step shape), per-ring emitter threads draining with
//     brpc_tokring_pop_many under timeouts, EOVERCROWDED full-ring
//     returns, terminal exactly-once from racing closers, and the
//     global live-ring counter back to baseline.
//   * brpc_spanq::Stack (src/cc/spanq.h — the SAME algorithm
//     fastrpc_module.cc's py_spanq_* run on PyObject*): many CAS
//     producers against one exchange+reverse drainer; every payload
//     arrives exactly once, in per-producer FIFO order, including
//     across the re-push (drain failure) path.
//   * flight ring (ISSUE 15; src/cc/butil/flight.{h,cc}): per-thread
//     seqlock event rings — N writers recording at full tilt while
//     dump/threads_table readers snapshot concurrently, plus the
//     enabled-flag no-op and exact per-ring head accounting.  All slot
//     fields are relaxed atomics, so TSAN stays sound here (no timed
//     waits, no seqlock false positives).
//
// A violated invariant prints and aborts (so TSAN's halt_on_error and
// our own assertions share one failure mode); a clean exit means no
// data races and no lost/duplicated tokens or spans.
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include "butil/flight.h"
#include "spanq.h"

extern "C" {
void* brpc_tokring_new(int cap);
void brpc_tokring_free(void* h);
int64_t brpc_tokring_live();
int brpc_tokring_push(void* h, int32_t tok);
int brpc_tokring_push_many(void** rings, const int32_t* toks, int n,
                           uint8_t* ok_out);
int brpc_tokring_push_terminal(void* h, int32_t err_code);
int brpc_tokring_pop_many(void* h, int32_t* out, int cap,
                          int64_t timeout_us, int* terminal_out,
                          int32_t* err_out);
int64_t brpc_tokring_size(void* h);
}

#define CHECK(cond, ...)                                   \
  do {                                                     \
    if (!(cond)) {                                         \
      std::fprintf(stderr, "CHECK failed: %s — ", #cond);  \
      std::fprintf(stderr, __VA_ARGS__);                   \
      std::fprintf(stderr, "\n");                          \
      std::abort();                                        \
    }                                                      \
  } while (0)

namespace {

// ---- TokenRing: step-loop fan-out vs emitter drains -----------------------

void tokring_stress() {
  const int kRings = 8;
  const int kSteps = 4000;
  const int kCap = 64;
  const int64_t base_live = brpc_tokring_live();
  // `make tsan` sets RING_STRESS_POP_TIMEOUT_US=0: gcc-10's libtsan
  // does not intercept pthread_cond_clockwait (glibc's wait_for
  // path), so a blocking pop under TSAN misreports "double lock" when
  // the in-wait mutex release goes unseen.  Non-blocking pops keep
  // every push/pop/terminal mutex race visible; the blocking wait
  // path runs under `make ring-stress` (plain) and the Python suite.
  const char* env = std::getenv("RING_STRESS_POP_TIMEOUT_US");
  const int64_t pop_timeout_us = env != nullptr ? std::atoll(env) : 500;

  std::vector<void*> rings(kRings);
  for (auto& r : rings) r = brpc_tokring_new(kCap);

  std::vector<std::atomic<int64_t>> popped_sum(kRings);
  std::vector<std::atomic<int64_t>> popped_n(kRings);
  std::vector<std::atomic<int>> terminals(kRings);
  for (int i = 0; i < kRings; ++i) {
    popped_sum[i] = 0;
    popped_n[i] = 0;
    terminals[i] = 0;
  }

  std::vector<std::thread> emitters;
  for (int i = 0; i < kRings; ++i) {
    emitters.emplace_back([&, i] {
      int32_t buf[32];
      for (;;) {
        int term = 0;
        int32_t err = 0;
        int n = brpc_tokring_pop_many(rings[i], buf, 32, pop_timeout_us,
                                      &term, &err);
        if (n == 0 && !term) std::this_thread::yield();
        for (int k = 0; k < n; ++k) popped_sum[i] += buf[k];
        popped_n[i] += n;
        if (term) {
          CHECK(err == 7, "ring %d terminal err %d != 7", i, err);
          terminals[i]++;
          return;
        }
      }
    });
  }

  // the step loop: ONE push_many per step across every ring (full
  // rings are EOVERCROWDED no-ops whose tokens we re-offer next step,
  // so the pushed/popped ledgers stay exactly balanced)
  std::vector<int64_t> pushed_sum(kRings, 0);
  std::vector<int64_t> pushed_n(kRings, 0);
  {
    std::vector<int32_t> toks(kRings);
    std::vector<uint8_t> ok(kRings);
    for (int step = 0; step < kSteps; ++step) {
      for (int i = 0; i < kRings; ++i) toks[i] = step ^ (i << 16);
      brpc_tokring_push_many(rings.data(), toks.data(), kRings, ok.data());
      for (int i = 0; i < kRings; ++i) {
        if (ok[i]) {
          pushed_sum[i] += toks[i];
          pushed_n[i] += 1;
        }
      }
    }
  }

  // racing closers: every ring gets TWO terminal attempts; exactly one
  // must win (the exactly-once decision the Python wrapper leans on)
  std::vector<std::thread> closers;
  std::vector<std::atomic<int>> won(kRings);
  for (int i = 0; i < kRings; ++i) won[i] = 0;
  for (int c = 0; c < 2; ++c) {
    closers.emplace_back([&] {
      for (int i = 0; i < kRings; ++i) {
        won[i] += brpc_tokring_push_terminal(rings[i], 7);
      }
    });
  }
  for (auto& t : closers) t.join();
  for (auto& t : emitters) t.join();

  for (int i = 0; i < kRings; ++i) {
    CHECK(won[i].load() == 1, "ring %d: %d terminal winners", i,
          won[i].load());
    CHECK(terminals[i].load() == 1, "ring %d: emitter saw %d terminals",
          i, terminals[i].load());
    CHECK(popped_n[i].load() == pushed_n[i],
          "ring %d: popped %lld != pushed %lld tokens", i,
          (long long)popped_n[i].load(), (long long)pushed_n[i]);
    CHECK(popped_sum[i].load() == pushed_sum[i],
          "ring %d: popped checksum %lld != pushed %lld", i,
          (long long)popped_sum[i].load(), (long long)pushed_sum[i]);
    brpc_tokring_free(rings[i]);
  }
  CHECK(brpc_tokring_live() == base_live,
        "live rings %lld != baseline %lld",
        (long long)brpc_tokring_live(), (long long)base_live);
  std::printf("tokring stress: %d rings x %d steps ok (checksums "
              "balanced, terminals exactly-once, live back to "
              "baseline)\n", kRings, kSteps);
}

// ---- spanq: MPSC Treiber producers vs exchange+reverse drainer ------------

void spanq_stress() {
  const int kProducers = 8;
  const int64_t kPerProducer = 50000;
  brpc_spanq::Stack q;

  // payloads encode (producer, seq) so the drainer can assert
  // exactly-once AND per-producer FIFO (the reverse-to-FIFO contract)
  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&, p] {
      for (int64_t s = 1; s <= kPerProducer; ++s) {
        q.push((void*)(uintptr_t)((uint64_t)p << 32 | (uint64_t)s));
      }
    });
  }

  std::vector<int64_t> last_seq(kProducers, 0);
  int64_t drained = 0;
  bool repushed_once = false;
  while (drained < kProducers * kPerProducer) {
    int64_t count = 0;
    brpc_spanq::Node* chain = q.drain_fifo(&count);
    if (count == 0) {
      std::this_thread::yield();
      continue;
    }
    if (!repushed_once && count > 1) {
      // exercise the drain-failure re-push path once mid-churn: the
      // chain re-enters the stack and must come back out exactly once
      repushed_once = true;
      for (brpc_spanq::Node* n = chain; n != nullptr;) {
        brpc_spanq::Node* next = n->next;
        q.push_node(n);
        n = next;
      }
      continue;
    }
    for (brpc_spanq::Node* n = chain; n != nullptr;) {
      uint64_t v = (uint64_t)(uintptr_t)n->obj;
      int p = (int)(v >> 32);
      int64_t s = (int64_t)(v & 0xFFFFFFFFu);
      CHECK(p >= 0 && p < kProducers, "bad producer %d", p);
      if (!repushed_once) {
        // FIFO per producer holds for plain drains; the one deliberate
        // re-push above reverses a batch (documented stack behavior),
        // so after it only exactly-once is asserted
        CHECK(s == last_seq[p] + 1, "producer %d: seq %lld after %lld",
              p, (long long)s, (long long)last_seq[p]);
      }
      last_seq[p] = s;
      ++drained;
      brpc_spanq::Node* next = n->next;
      delete n;
      n = next;
    }
  }
  for (auto& t : producers) t.join();
  CHECK(q.count() == 0, "pending %lld after full drain",
        (long long)q.count());
  CHECK(q.drain_fifo() == nullptr, "stack not empty after full drain");
  std::printf("spanq stress: %d producers x %lld spans ok "
              "(exactly-once, FIFO until the deliberate re-push, "
              "pending back to 0)\n", kProducers,
              (long long)kPerProducer);
}

// ---- flight ring: concurrent writers vs dump-while-writing ----------------

void flight_stress() {
  namespace fl = butil::flight;
  const int kWriters = 8;
  const int64_t kPerWriter = 200000;

  int64_t ev0 = 0, dr0 = 0;
  fl::stats(&ev0, nullptr, &dr0);

  std::atomic<bool> writing{true};
  std::vector<std::thread> writers;
  for (int w = 0; w < kWriters; ++w) {
    writers.emplace_back([&, w] {
      fl::set_thread_name("stress/%d", w);
      for (int64_t i = 0; i < kPerWriter; ++i) {
        fl::record(fl::EV_PROBE, (uint64_t)w, i);
      }
      writing.store(false, std::memory_order_release);
    });
  }

  // dump + thread-table readers racing the writers: every returned
  // event must be CONSISTENT (the seqlock filter's whole job) — a
  // parseable line with a known kind and a writer-consistent payload
  std::vector<std::thread> readers;
  std::atomic<int64_t> dumps{0};
  for (int r = 0; r < 2; ++r) {
    readers.emplace_back([&] {
      std::vector<char> buf(1 << 20);
      while (writing.load(std::memory_order_acquire)) {
        int n = fl::dump(buf.data(), buf.size(), 256);
        CHECK(n >= 0, "dump returned %d", n);
        // parse: every line is "<ts> <tid> <name> <kind> a=0x.. b=.."
        int fields = 0;
        for (char* p = buf.data(); *p != 0; ++p) {
          if (*p == ' ') ++fields;
          if (*p == '\n') {
            CHECK(fields == 5, "malformed dump line (%d gaps)", fields);
            fields = 0;
          }
        }
        n = fl::threads_table(buf.data(), buf.size());
        CHECK(n >= 0, "threads_table returned %d", n);
        dumps.fetch_add(1, std::memory_order_relaxed);
      }
    });
  }

  for (auto& t : writers) t.join();
  for (auto& t : readers) t.join();

  // exact accounting: heads only move by record(), so the global event
  // counter advanced by exactly kWriters * kPerWriter
  int64_t ev1 = 0, dr1 = 0, th1 = 0;
  fl::stats(&ev1, &th1, &dr1);
  CHECK(ev1 - ev0 == kWriters * kPerWriter,
        "events %lld != %lld recorded", (long long)(ev1 - ev0),
        (long long)(kWriters * kPerWriter));
  CHECK(dr1 - dr0 ==
            kWriters * (kPerWriter - (int64_t)fl::kRingCap),
        "dropped %lld != overwrite-oldest math",
        (long long)(dr1 - dr0));

  // a quiesced dump returns only complete, newest-kRingCap events
  {
    std::vector<char> buf(8 << 20);
    const int n = fl::dump(buf.data(), buf.size(), 0 /* no tail cap */);
    CHECK(n > 0, "quiesced dump empty");
  }

  // disabled flag is a recorded-nothing no-op
  fl::set_enabled(false);
  fl::record(fl::EV_PROBE, 0xdead, 1);
  int64_t ev2 = 0;
  fl::stats(&ev2, nullptr, nullptr);
  CHECK(ev2 == ev1, "disabled recorder still recorded (%lld != %lld)",
        (long long)ev2, (long long)ev1);
  fl::set_enabled(true);

  std::printf("flight stress: %d writers x %lld events ok (%lld "
              "concurrent dumps consistent, overwrite math exact, "
              "disabled no-op)\n", kWriters, (long long)kPerWriter,
              (long long)dumps.load());
}

}  // namespace

int main() {
  tokring_stress();
  spanq_stress();
  flight_stress();
  std::printf("ring stress: all invariants held\n");
  return 0;
}
