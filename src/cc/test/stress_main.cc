// Lock-free stress suite — run under TSAN/ASAN via `make tsan` / `make asan`
// (VERDICT r2 task 7; reference test strategy SURVEY.md §4: stress the
// primitive across many threads, assert invariants — the role of
// test/bthread_ping_pong_unittest.cpp and brpc_socket_unittest.cpp).
//
// Each section hammers one lock-free protocol:
//   1. Chase-Lev deque: owner push/pop vs 3 thieves — task conservation.
//   2. Executor: cross-thread submit churn — every task runs exactly once.
//   3. Butex: fiber ping-pong + 10k park/wake-all — claim protocol races.
//   4. FiberMutex: mutual exclusion under 64 fibers.
//   5. Timer: schedule/unschedule churn vs firing.
//   6. Socket write stack: concurrent producers vs drainer handoff vs
//      SetFailed — the wait-free write protocol under fire.
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <netinet/in.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include "bthread/executor.h"
#include "bthread/fiber.h"
#include "bthread/timer.h"
#include "butil/doubly_buffered.h"
#include "butil/iobuf.h"
#include "net/event_dispatcher.h"
#include "net/fd_wait.h"
#include "net/socket.h"

#define CHECK_EQ(a, b)                                                     \
  do {                                                                     \
    auto va = (a);                                                         \
    auto vb = (b);                                                         \
    if (va != vb) {                                                        \
      fprintf(stderr, "FAIL %s:%d: %s=%lld != %s=%lld\n", __FILE__,        \
              __LINE__, #a, (long long)va, #b, (long long)vb);             \
      exit(1);                                                             \
    }                                                                      \
  } while (0)

using namespace bthread;

// ---- 0. BoundedQueue: ring arithmetic + value lifetime ----
static void stress_bounded_queue() {
  butil::BoundedQueue<int> q(7);
  int out = 0;
  CHECK_EQ(q.pop(&out), false);
  // wrap the ring several times with interleaved push/pop
  int pushed = 0, popped = 0;
  for (int round = 0; round < 100; ++round) {
    while (q.push(pushed)) ++pushed;
    CHECK_EQ(q.full(), true);
    CHECK_EQ((long long)q.size(), 7LL);
    for (int i = 0; i < 4; ++i) {
      CHECK_EQ(q.pop(&out), true);
      CHECK_EQ(out, popped);
      ++popped;
    }
  }
  while (q.pop(&out)) {
    CHECK_EQ(out, popped);
    ++popped;
  }
  CHECK_EQ(pushed, popped);
  CHECK_EQ(q.empty(), true);
  printf("bounded_queue: %d values through a 7-slot ring in order\n", pushed);
}

// ---- 0b. IOBuf cutter / appender / bytes-iterator ----
static void stress_iobuf_companions() {
  // Appender: interleave two appenders and a plain append on one thread;
  // eager span claiming must keep all three byte streams intact.
  butil::IOBuf buf;
  {
    butil::IOBufAppender a(&buf), b(&buf);
    for (int i = 0; i < 1000; ++i) {
      char ca = (char)('a' + (i % 26));
      a.append(&ca, 1);
      a.commit();
      char cb = (char)('A' + (i % 26));
      b.append(&cb, 1);
      b.commit();
      if (i % 97 == 0) buf.append("|", 1);
    }
  }
  std::string s = buf.to_string();
  CHECK_EQ((long long)s.size(), 2011LL);  // 2000 staged + 11 separators
  // spot-check order: first three bytes are a0, A0, then a1 or separator
  if (s[0] != 'a' || s[1] != 'A') {
    fprintf(stderr, "FAIL: appender interleave order\n");
    exit(1);
  }

  // Iterator: multi-block content reads back exactly.
  butil::IOBuf big;
  std::string expect;
  for (int i = 0; i < 5000; ++i) {
    char w[16];
    int n = snprintf(w, sizeof(w), "%d,", i);
    big.append(w, (size_t)n);
    expect.append(w, (size_t)n);
  }
  butil::IOBufBytesIterator it(big);
  CHECK_EQ((long long)it.bytes_left(), (long long)expect.size());
  std::string got;
  got.resize(expect.size());
  CHECK_EQ((long long)it.copy_and_forward(got.data(), got.size()),
           (long long)expect.size());
  CHECK_EQ((long long)it.bytes_left(), 0LL);
  if (got != expect) {
    fprintf(stderr, "FAIL: iterator content mismatch\n");
    exit(1);
  }

  // Cutter: cut1/cutn across block boundaries, then zero-copy cutn.
  butil::IOBufCutter cutter(&big);
  char c0 = 0, c1 = 0;
  CHECK_EQ(cutter.cut1(&c0), true);
  CHECK_EQ(cutter.cut1(&c1), true);
  if (c0 != '0' || c1 != ',') {
    fprintf(stderr, "FAIL: cutter cut1\n");
    exit(1);
  }
  char word[8] = {0};
  CHECK_EQ((long long)cutter.cutn(word, 2), 2LL);  // "1,"
  butil::IOBuf rest;
  const size_t left = cutter.remaining();
  CHECK_EQ((long long)cutter.cutn(&rest, left), (long long)left);
  CHECK_EQ((long long)big.size(), 0LL);
  CHECK_EQ((long long)rest.size(), (long long)(expect.size() - 4));
  printf("iobuf companions: appender/iterator/cutter invariants held\n");
}

// ---- 0c. fiber fd_wait: parked fibers vs racing writers/timeouts ----
static void wait_countdown(CountdownEvent* e, int seconds);
struct FdwSt {
  CountdownEvent done;
  std::atomic<int> ready{0};
  std::atomic<int> timed_out{0};
  std::atomic<int> refs;
  explicit FdwSt(int n) : done(n), refs(n + 1) {}
};
static Fiber fdw_body(FdwSt* s, int fd, int timeout_ms) {
  int rc = -1;
  co_await brpc::fiber_fd_wait(fd, brpc::FD_WAIT_READ, timeout_ms, &rc);
  if (rc == 0) s->ready.fetch_add(1);
  if (rc == ETIMEDOUT) s->timed_out.fetch_add(1);
  s->done.signal();
  if (s->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete s;
}
static void stress_fd_wait() {
  const int kPairs = 32;
  int rfd[kPairs], wfd[kPairs];
  for (int i = 0; i < kPairs; ++i) {
    int p[2];
    if (pipe(p) != 0) { perror("pipe"); exit(1); }
    rfd[i] = p[0];
    wfd[i] = p[1];
  }
  auto* s = new FdwSt(kPairs);
  // even pipes get a racing writer (should deliver), odd ones time out
  for (int i = 0; i < kPairs; ++i) {
    fdw_body(s, rfd[i], (i % 2 == 0) ? 5000 : 120).spawn();
  }
  std::vector<std::thread> writers;
  for (int i = 0; i < kPairs; i += 2) {
    writers.emplace_back([fd = wfd[i]] {
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
      const char c = 1;
      ssize_t rc = write(fd, &c, 1);
      (void)rc;
    });
  }
  for (auto& t : writers) t.join();
  wait_countdown(&s->done, 60);
  CHECK_EQ(s->ready.load(), kPairs / 2);
  CHECK_EQ(s->timed_out.load(), kPairs / 2);
  if (s->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete s;
  for (int i = 0; i < kPairs; ++i) {
    close(rfd[i]);
    close(wfd[i]);
  }
  printf("fd_wait: %d delivered + %d timed out, frames reclaimed\n",
         kPairs / 2, kPairs / 2);
}

// ---- 0d. DoublyBufferedData: readers vs the writer flip protocol ----
static void stress_doubly_buffered() {
  // invariant: the vector is always {k, k+1, ..., k+9} for some k —
  // a torn read (old foreground observed mid-flip) breaks it
  butil::DoublyBufferedData<std::vector<int>> dbd;
  dbd.Modify([](std::vector<int>& v) {
    v.clear();
    for (int i = 0; i < 10; ++i) v.push_back(i);
    return true;
  });
  std::atomic<bool> stop{false};
  std::atomic<int64_t> reads{0};
  std::atomic<int64_t> violations{0};
  std::vector<std::thread> readers;
  for (int r = 0; r < 6; ++r) {
    readers.emplace_back([&] {
      while (!stop.load(std::memory_order_acquire)) {
        butil::DoublyBufferedData<std::vector<int>>::ScopedPtr p;
        dbd.Read(&p);
        const std::vector<int>& v = *p;
        const int base = v.empty() ? 0 : v[0];
        for (size_t i = 0; i < v.size(); ++i) {
          if (v[i] != base + (int)i) violations.fetch_add(1);
        }
        reads.fetch_add(1);
      }
    });
  }
  // flips must actually race reads: wait for every reader to be live
  while (reads.load(std::memory_order_acquire) < 6) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  for (int k = 1; k <= 500; ++k) {
    dbd.Modify([k](std::vector<int>& v) {
      v.clear();
      for (int i = 0; i < 10; ++i) v.push_back(k + i);
      return true;
    });
  }
  stop.store(true, std::memory_order_release);
  for (auto& t : readers) t.join();
  CHECK_EQ(violations.load(), 0);
  printf("doubly_buffered: %lld reads across 500 flips, no torn state\n",
         (long long)reads.load());
}

// ---- 1. Chase-Lev: owner pops + thieves steal must conserve tasks ----
static void stress_wsq() {
  WorkStealingQueue q(1024);
  std::atomic<int64_t> consumed{0};
  std::atomic<bool> stop{false};
  const int64_t kTotal = 200000;
  std::vector<TaskNode> nodes((size_t)kTotal);
  std::vector<std::thread> thieves;
  for (int t = 0; t < 3; ++t) {
    thieves.emplace_back([&] {
      while (!stop.load(std::memory_order_acquire)) {
        if (q.steal() != nullptr)
          consumed.fetch_add(1, std::memory_order_relaxed);
      }
      while (q.steal() != nullptr)
        consumed.fetch_add(1, std::memory_order_relaxed);
    });
  }
  int64_t pushed = 0;
  while (pushed < kTotal) {
    if (q.push(&nodes[(size_t)pushed])) {
      ++pushed;
    } else if (q.pop() != nullptr) {  // full: drain some ourselves
      consumed.fetch_add(1, std::memory_order_relaxed);
    }
    if ((pushed & 7) == 0 && q.pop() != nullptr)
      consumed.fetch_add(1, std::memory_order_relaxed);
  }
  while (q.pop() != nullptr) consumed.fetch_add(1, std::memory_order_relaxed);
  stop.store(true, std::memory_order_release);
  for (auto& th : thieves) th.join();
  CHECK_EQ(consumed.load(), kTotal);
  printf("wsq: %lld tasks conserved across owner+3 thieves\n",
         (long long)kTotal);
}

// ---- 2. Executor submit churn ----
static void stress_executor() {
  std::atomic<int64_t> ran{0};
  const int kThreads = 8, kPer = 20000;
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&] {
      for (int i = 0; i < kPer; ++i) {
        // seq_cst: this counter is the ONLY happens-before edge between
        // the worker's last touch and main reusing this stack frame —
        // relaxed would be a real race (TSAN caught it)
        Executor::global()->submit(
            [](void* a) { ((std::atomic<int64_t>*)a)->fetch_add(1); },
            &ran);
      }
    });
  }
  for (auto& th : ts) th.join();
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(60);
  while (ran.load() < kThreads * kPer &&
         std::chrono::steady_clock::now() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  CHECK_EQ(ran.load(), (int64_t)kThreads * kPer);
  printf("executor: %d cross-thread submits all ran\n", kThreads * kPer);
}

// ---- 3. Butex: ping-pong + park/wake-all ----
struct BxPingPong {
  Butex word{0};
  CountdownEvent done{2};
  std::atomic<int> refs{3};
  int rounds = 20000;
};
static Fiber bx_body(BxPingPong* p, int32_t mine, int32_t theirs) {
  for (int i = 0; i < p->rounds; ++i) {
    while (p->word.value.load(std::memory_order_acquire) != mine) {
      co_await p->word.wait(theirs);
    }
    p->word.value.store(theirs, std::memory_order_release);
    p->word.wake_all();
  }
  p->done.signal();
  if (p->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete p;
}
struct BxGate {
  Butex gate{0};
  CountdownEvent done;
  std::atomic<int> refs;
  explicit BxGate(int n) : done(n), refs(n + 1) {}
};
static Fiber bx_gate_body(BxGate* g) {
  while (g->gate.value.load(std::memory_order_acquire) == 0) {
    co_await g->gate.wait(0);
  }
  g->done.signal();
  if (g->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete g;
}
static void wait_countdown(CountdownEvent* e, int seconds) {
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(seconds);
  while (e->count() > 0) {
    if (std::chrono::steady_clock::now() > deadline) {
      fprintf(stderr, "FAIL: countdown timeout\n");
      exit(1);
    }
    std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
}
static void stress_butex() {
  auto* p = new BxPingPong();
  bx_body(p, 0, 1).spawn();
  bx_body(p, 1, 0).spawn();
  wait_countdown(&p->done, 60);
  const int rounds = p->rounds;
  if (p->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete p;
  printf("butex: ping-pong %d rounds\n", rounds);

  auto* g = new BxGate(10000);
  for (int i = 0; i < 10000; ++i) bx_gate_body(g).spawn();
  // release IMMEDIATELY: wake_all races fibers still enqueuing (the
  // mismatch path must catch late arrivals)
  g->gate.value.store(1, std::memory_order_release);
  g->gate.wake_all();
  // keep waking: parked fibers from the race window need a second kick
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(60);
  while (g->done.count() > 0) {
    g->gate.wake_all();
    if (std::chrono::steady_clock::now() > deadline) {
      fprintf(stderr, "FAIL: gate timeout, %d left\n", g->done.count());
      exit(1);
    }
    std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
  if (g->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete g;
  printf("butex: 10k park/wake-all with racing release\n");
}

// ---- 4. FiberMutex mutual exclusion ----
struct MxState {
  FiberMutex mu;
  int64_t counter = 0;
  CountdownEvent done;
  std::atomic<int> refs;
  explicit MxState(int n) : done(n), refs(n + 1) {}
};
static Fiber mx_body(MxState* s, int iters) {
  for (int i = 0; i < iters; ++i) {
    co_await s->mu.lock();
    s->counter += 1;
    s->mu.unlock();
  }
  s->done.signal();
  if (s->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete s;
}
static void stress_fiber_mutex() {
  auto* s = new MxState(64);
  for (int i = 0; i < 64; ++i) mx_body(s, 2000).spawn();
  wait_countdown(&s->done, 120);
  CHECK_EQ(s->counter, 64 * 2000);
  if (s->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete s;
  printf("fiber_mutex: 128k increments excluded correctly\n");
}

// ---- 5. Timer schedule/unschedule churn ----
static void stress_timer() {
  std::atomic<int64_t> fired{0};
  std::atomic<int64_t> cancelled{0};
  const int kThreads = 4, kPer = 5000;
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&] {
      for (int i = 0; i < kPer; ++i) {
        // seq_cst fetch_add: sole HB edge before main's frame is reused
        const uint64_t id = TimerThread::global()->schedule_after(
            [](void* a) { ((std::atomic<int64_t>*)a)->fetch_add(1); },
            &fired, (i % 3) * 1000);
        if ((i & 1) != 0 && TimerThread::global()->unschedule(id)) {
          cancelled.fetch_add(1, std::memory_order_relaxed);
        }
      }
    });
  }
  for (auto& th : ts) th.join();
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(60);
  while (fired.load() + cancelled.load() < (int64_t)kThreads * kPer &&
         std::chrono::steady_clock::now() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
  CHECK_EQ(fired.load() + cancelled.load(), (int64_t)kThreads * kPer);
  printf("timer: %lld fired + %lld cancelled == scheduled\n",
         (long long)fired.load(), (long long)cancelled.load());
}

// ---- 6. Socket write stack: producers vs drainer vs SetFailed ----
static void stress_socket_writes() {
  brpc::EventDispatcher::InitGlobal(1);
  // loopback pair: listener discards, client gets hammered
  brpc::SocketOptions lopts;
  brpc::SocketId lid;
  int port = 0;
  if (brpc::Listen("127.0.0.1", 0, lopts, &lid, &port) != 0) {
    fprintf(stderr, "FAIL: listen\n");
    exit(1);
  }
  for (int round = 0; round < 8; ++round) {
    brpc::SocketOptions copts;
    brpc::SocketId cid;
    if (brpc::Connect("127.0.0.1", port, copts, &cid) != 0) {
      fprintf(stderr, "FAIL: connect\n");
      exit(1);
    }
    std::atomic<bool> stop{false};
    std::vector<std::thread> producers;
    for (int t = 0; t < 4; ++t) {
      producers.emplace_back([&, cid] {
        char payload[512];
        memset(payload, 'a', sizeof(payload));
        while (!stop.load(std::memory_order_acquire)) {
          brpc::Socket* s = brpc::Socket::Address(cid);
          if (s == nullptr) break;   // SetFailed won — expected
          butil::IOBuf b;
          b.append(payload, sizeof(payload));
          (void)s->Write(std::move(b));  // may be dropped on fail: fine
          s->Dereference();
        }
      });
    }
    // let the drainer handoff churn, then kill the socket mid-write
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    brpc::Socket::SetFailed(cid, ECONNRESET);
    stop.store(true, std::memory_order_release);
    for (auto& th : producers) th.join();
  }
  brpc::Socket::SetFailed(lid, 0);
  printf("socket: 8 rounds of 4-producer writes vs SetFailed survived\n");
}

// ---- 7. FiberCond wait-morphing + semaphore + rwlock ----
struct CondState {
  FiberMutex mu;
  bthread::FiberCond cv;
  int turn = 0;
  CountdownEvent done{4};
  std::atomic<int> refs{5};
};
static Fiber cond_round_robin(CondState* s, int me, int parties, int laps) {
  for (int i = 0; i < laps; ++i) {
    co_await s->mu.lock();
    while (s->turn % parties != me) {
      co_await s->cv.wait(s->mu);
    }
    ++s->turn;
    s->cv.notify_all(s->mu);
    s->mu.unlock();
  }
  s->done.signal();
  if (s->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete s;
}
static void stress_cond_sem_rw() {
  auto* s = new CondState();
  const int parties = 4, laps = 5000;
  for (int i = 0; i < parties; ++i)
    cond_round_robin(s, i, parties, laps).spawn();
  wait_countdown(&s->done, 120);
  CHECK_EQ(s->turn, parties * laps);
  if (s->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete s;
  printf("fiber_cond: %d round-robin handoffs in order\n", parties * laps);

  struct SemState {
    bthread::FiberSemaphore sem{2};
    std::atomic<int> inside{0};
    std::atomic<int> overflows{0};
    CountdownEvent done{16};
    std::atomic<int> refs{17};
  };
  auto* q = new SemState();
  for (int i = 0; i < 16; ++i) {
    [](SemState* q, int iters) -> Fiber {
      for (int k = 0; k < iters; ++k) {
        co_await q->sem.acquire();
        if (q->inside.fetch_add(1, std::memory_order_acq_rel) + 1 > 2) {
          q->overflows.fetch_add(1);
        }
        co_await bthread::fiber_sleep_us(0);
        q->inside.fetch_sub(1, std::memory_order_acq_rel);
        q->sem.release();
      }
      q->done.signal();
      if (q->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete q;
    }(q, 1000).spawn();
  }
  wait_countdown(&q->done, 120);
  CHECK_EQ(q->overflows.load(), 0);
  if (q->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete q;
  printf("fiber_sem: 16 fibers x 1000 never exceeded 2 permits\n");

  struct RwState {
    bthread::FiberRwLock rw;
    int64_t a = 0, b = 0;          // invariant: a == b under any lock
    std::atomic<int64_t> violations{0};
    CountdownEvent done{10};
    std::atomic<int> refs{11};
  };
  auto* r = new RwState();
  for (int i = 0; i < 8; ++i) {
    [](RwState* r, int iters) -> Fiber {
      for (int k = 0; k < iters; ++k) {
        co_await r->rw.lock_shared();
        if (r->a != r->b) r->violations.fetch_add(1);
        r->rw.unlock_shared();
      }
      r->done.signal();
      if (r->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete r;
    }(r, 4000).spawn();
  }
  for (int i = 0; i < 2; ++i) {
    [](RwState* r, int iters) -> Fiber {
      for (int k = 0; k < iters; ++k) {
        co_await r->rw.lock();
        ++r->a;
        ++r->b;                     // non-atomic: the lock is the sync
        r->rw.unlock();
      }
      r->done.signal();
      if (r->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete r;
    }(r, 4000).spawn();
  }
  wait_countdown(&r->done, 120);
  CHECK_EQ(r->violations.load(), 0);
  if (r->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete r;
  printf("fiber_rwlock: 8 readers + 2 writers, invariant held\n");
}

// ---- 12. Parser fuzz: >=100k mutated frames across every native
// framing (the reference's test/fuzzing/ libFuzzer targets, run here as
// a deterministic seeded section under ASAN/UBSAN/TSAN).  Seeds are one
// valid frame per protocol; mutations are truncation, bit flips, length
// corruption, splices, and random prefixes, fed through parse_message
// in random-sized chunks AND through parse_trpc_view (the zero-copy
// fast path).  The invariant is simply: no crash, no hang, no
// sanitizer report, and the parser never fabricates more than the fed
// bytes' worth of messages. ----
#include <random>

#include "net/parser.h"
#include "net/rpc.h"

static void stress_parser_fuzz() {
  using brpc::ParsedMessage;
  using brpc::ParseState;
  using brpc::ParseResult;

  std::vector<std::string> seeds;
  {  // TRPC
    butil::IOBuf f;
    butil::IOBuf body;
    body.append("hello-fuzz", 10);
    brpc::PackRequestFrame(&f, 42, 0, "Svc", 3, "Method", 6, 1000, 0,
                           "raw", 3, std::move(body));
    seeds.push_back(f.to_string());
  }
  seeds.push_back(
      "POST /x HTTP/1.1\r\nContent-Length: 5\r\nHost: a\r\n\r\nhello");
  {  // h2 preface + SETTINGS + tiny HEADERS frame
    std::string s = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
    const char settings[9] = {0, 0, 0, 4, 0, 0, 0, 0, 0};
    s.append(settings, 9);
    const char headers[14] = {0, 0, 5, 1, 4, 0, 0, 0, 1,
                              (char)0x82, (char)0x86, (char)0x84,
                              (char)0x41, (char)0x0f};
    s.append(headers, 14);
    seeds.push_back(s);
  }
  seeds.push_back("*2\r\n$4\r\nECHO\r\n$3\r\nabc\r\n");   // redis
  {  // memcache binary: 24B header, 3B key as body
    std::string m(24, '\0');
    m[0] = (char)0x80;                 // request magic
    m[1] = 0x00;                       // GET
    m[3] = 3;                          // key len (be16 low byte)
    m[11] = 3;                         // total body len (be32 low byte)
    m += "key";
    seeds.push_back(m);
  }
  {  // thrift framed: 4B big-endian length + payload
    std::string body = "\x80\x01\x00\x01";  // version | CALL
    body += std::string("\x00\x00\x00\x01m", 5);
    body += std::string("\x00\x00\x00\x01", 4);
    body += '\0';                      // field stop
    std::string t;
    t.push_back(0); t.push_back(0); t.push_back(0);
    t.push_back((char)body.size());
    t += body;
    seeds.push_back(t);
  }
  {  // mongo OP_MSG: 16B header (len, req, resp, opcode=2013 LE) + body
    std::string m;
    const uint32_t len = 16 + 5, req = 7, resp = 0, op = 2013;
    m.append((const char*)&len, 4);
    m.append((const char*)&req, 4);
    m.append((const char*)&resp, 4);
    m.append((const char*)&op, 4);
    m += "body!";
    seeds.push_back(m);
  }
  {  // nshead: 36B header, magic LE at 24, body_len LE at 32
    std::string n(36, '\0');
    const uint32_t magic = 0xfb709394u, blen = 4;
    memcpy(&n[24], &magic, 4);
    memcpy(&n[32], &blen, 4);
    n += "data";
    seeds.push_back(n);
  }
  seeds.push_back(std::string(64, '\x5a'));   // raw (forced protocol)

  std::mt19937 rng(0xF0220422u);
  const int kIters = 110000;
  int64_t parsed_total = 0;
  for (int it = 0; it < kIters; ++it) {
    std::string base = seeds[rng() % seeds.size()];
    std::string data = base;
    switch (rng() % 5) {
      case 0:  // truncate
        data.resize(rng() % (base.size() + 1));
        break;
      case 1:  // bit flips (1-8)
        for (unsigned i = 0, n = 1 + rng() % 8; i < n && !data.empty(); ++i)
          data[rng() % data.size()] ^= (char)(1u << (rng() % 8));
        break;
      case 2:  // splice two seeds at random cut points
      {
        const std::string& other = seeds[rng() % seeds.size()];
        data = base.substr(0, rng() % (base.size() + 1)) +
               other.substr(rng() % (other.size() + 1));
        break;
      }
      case 3:  // random prefix garbage
      {
        std::string pre;
        for (unsigned i = 0, n = rng() % 32; i < n; ++i)
          pre.push_back((char)(rng() % 256));
        data = pre + base;
        break;
      }
      case 4:  // duplicate (pipelined) + mid flips
        data = base + base;
        if (!data.empty())
          data[rng() % data.size()] ^= (char)(1u << (rng() % 8));
        break;
    }

    ParseState st;
    if (rng() % 8 == 0) {
      // forced protocols exercise parse_raw and mid-stream confusion
      static const int kinds[] = {brpc::MSG_TRPC, brpc::MSG_HTTP,
                                  brpc::MSG_H2, brpc::MSG_REDIS,
                                  brpc::MSG_MEMCACHE, brpc::MSG_THRIFT,
                                  brpc::MSG_MONGO, brpc::MSG_RAW,
                                  brpc::MSG_NSHEAD};
      st.detected = kinds[rng() % (sizeof(kinds) / sizeof(kinds[0]))];
    }
    butil::IOBuf in;
    ParsedMessage msg;
    size_t off = 0;
    int safety = 0;
    bool dead = false;
    while (!dead && safety < 256) {
      // feed a random-sized chunk (split reassembly under mutation)
      if (off < data.size()) {
        const size_t n =
            std::min(data.size() - off, (size_t)(1 + rng() % 96));
        in.append(data.data() + off, n);
        off += n;
      }
      for (;; ++safety) {
        if (safety >= 256) break;
        // alternate the zero-copy view path with the generic parser
        if (st.detected == brpc::MSG_TRPC && (rng() & 1)) {
          const char* mv = nullptr;
          size_t ml = 0;
          const char* bv = nullptr;
          uint64_t bl = 0;
          uint64_t total = 0;
          const ParseResult r = brpc::parse_trpc_peek(&in, &mv, &ml, &bv,
                                                      &bl, &total);
          if (r == brpc::PARSE_ERROR) { dead = true; break; }
          if (r == brpc::PARSE_NEED_MORE) break;
          if (mv != nullptr) {
            // fabrication guard: the peeked frame must fit the buffer
            CHECK_EQ(total <= in.size(), true);
            CHECK_EQ(total >= ml, true);
            // touch every meta byte (ASAN validates the view); touch the
            // body view too when contiguous
            unsigned acc = 0;
            for (size_t i = 0; i < ml; ++i) acc += (unsigned char)mv[i];
            if (bv != nullptr)
              for (size_t i = 0; i < bl; ++i) acc += (unsigned char)bv[i];
            (void)acc;
            in.pop_front(total);  // consume exactly one frame
            ++parsed_total;
            continue;
          }
          // mv==nullptr: fall through to the generic parser
        }
        const size_t before = in.size();
        const ParseResult r = brpc::parse_message(&in, &st, &msg);
        if (r == brpc::PARSE_ERROR) { dead = true; break; }
        if (r == brpc::PARSE_NEED_MORE) break;
        // fabrication guard: every accepted frame must consume bytes —
        // a PARSE_OK that leaves the buffer unchanged would loop forever
        // minting messages out of nothing
        CHECK_EQ(in.size() < before, true);
        ++parsed_total;
        msg.body.clear();
      }
      if (off >= data.size()) break;
    }
  }
  printf("parser_fuzz: %d mutated inputs, %lld frames parsed, no "
         "crash/hang\n", kIters, (long long)parsed_total);
}

int main() {
  // writes to a peer that parse-error-closed must surface as EPIPE, not
  // kill the process (the Python embedding ignores SIGPIPE for us; a
  // standalone binary must do it itself, as the reference does in
  // GlobalInitializeOrDie)
  signal(SIGPIPE, SIG_IGN);
  butil::set_min_log_level(3);  // expected parse-error closes are noise here
  Executor::init_global(8);
  (void)Executor::global();
  stress_bounded_queue();
  stress_iobuf_companions();
  stress_fd_wait();
  stress_doubly_buffered();
  stress_wsq();
  stress_executor();
  stress_butex();
  stress_fiber_mutex();
  stress_cond_sem_rw();
  stress_timer();
  stress_socket_writes();
  stress_parser_fuzz();
  printf("ALL STRESS SECTIONS PASSED\n");
  return 0;
}
