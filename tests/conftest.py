"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the TPU-build analog of the
reference's 127.0.0.1 loopback servers, SURVEY.md §4): multi-chip sharding
logic is validated with ``xla_force_host_platform_device_count=8`` so no real
pod is needed.  Real-chip benchmarks live in bench.py, not here.
"""
import os
import sys

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
