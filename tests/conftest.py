"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the TPU-build analog of the
reference's 127.0.0.1 loopback servers, SURVEY.md §4): multi-chip sharding
logic is validated with ``xla_force_host_platform_device_count=8`` so no real
pod is needed.  Real-chip benchmarks live in bench.py, not here.
"""
import os
import sys

import pytest

# Force CPU even when the environment selects the real TPU
# (JAX_PLATFORMS=axon): tests validate sharding logic on the virtual
# 8-device mesh; bench.py uses the real chip.  jax may already be imported
# by site hooks, so set BOTH the env vars (for a fresh import) and the
# config (for an existing import) before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    "tests need the 8-device virtual CPU mesh; a jax backend was "
    "initialized before conftest could configure it")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    # tier-1 runs with -m 'not slow'; chaos scenarios that outgrow ~5s
    # carry this marker so the fast suite stays fast (make chaos runs
    # everything)
    config.addinivalue_line(
        "markers", "slow: long-running chaos/scenario tests excluded "
                   "from the tier-1 fast suite")


@pytest.fixture(autouse=True, scope="session")
def _quiet_naming_refresh_noise():
    """Dead loopback registries from already-finished tests would spam
    '[naming] refresh failed' across the whole run."""
    from brpc_tpu import flags
    from brpc_tpu.policy import naming  # noqa: F401 — defines the flag
    flags.set_flag("naming_log_refresh_failures", False, force=True)
    yield


# ---------------------------------------------------------------------------
# suite-stall watchdog (ISSUE 15)
# ---------------------------------------------------------------------------
#
# The intermittent tier-1 wedge sometimes OUTLIVES every per-call
# WedgeGuard (the hang sits in an unguarded native path), so the run
# dies by the driver's outer `timeout -k` SIGKILL — and a Python signal
# handler can't help, because the main thread is blocked inside the
# wedged ctypes call and never returns to the interpreter.  This
# watchdog is a daemon THREAD instead: every test start refreshes a
# timestamp; if no test starts for BRPC_T1_WATCHDOG_S seconds
# (default 300, 0 disables), it writes the native flight-recorder
# autopsy + lock witness ONCE to the $BRPC_WEDGE_DUMP_DIR artifact
# file (default build/wedge_autopsy/ — the stderr copy is usually
# swallowed by capture), naming the test it stalled inside — so even a
# hard wedge leaves the evidence the outer kill would erase.

_watchdog_state = {"t": None, "test": "", "fired": False}


def _watchdog_dump() -> None:
    import time as _time
    try:
        from tests.wedge_guard import _witness_dump
    except Exception:
        return
    _witness_dump(f"suite watchdog: no test progress for "
                  f"{_time.monotonic() - _watchdog_state['t']:.0f}s "
                  f"(stalled inside {_watchdog_state['test']!r})")


def pytest_sessionstart(session):
    import threading
    import time as _time

    try:
        stall_s = float(os.environ.get("BRPC_T1_WATCHDOG_S", "300"))
    except ValueError:
        stall_s = 300.0
    if stall_s <= 0:
        return
    _watchdog_state["t"] = _time.monotonic()

    def run():
        while True:
            _time.sleep(5.0)
            t = _watchdog_state["t"]
            if t is None or _watchdog_state["fired"]:
                continue
            if _time.monotonic() - t > stall_s:
                _watchdog_state["fired"] = True
                _watchdog_dump()

    threading.Thread(target=run, daemon=True,
                     name="t1-stall-watchdog").start()


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item, nextitem):
    import time as _time
    _watchdog_state["t"] = _time.monotonic()
    _watchdog_state["test"] = item.nodeid
    yield
    _watchdog_state["t"] = _time.monotonic()
