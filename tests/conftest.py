"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the TPU-build analog of the
reference's 127.0.0.1 loopback servers, SURVEY.md §4): multi-chip sharding
logic is validated with ``xla_force_host_platform_device_count=8`` so no real
pod is needed.  Real-chip benchmarks live in bench.py, not here.
"""
import os
import sys

import pytest

# Force CPU even when the environment selects the real TPU
# (JAX_PLATFORMS=axon): tests validate sharding logic on the virtual
# 8-device mesh; bench.py uses the real chip.  jax may already be imported
# by site hooks, so set BOTH the env vars (for a fresh import) and the
# config (for an existing import) before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    "tests need the 8-device virtual CPU mesh; a jax backend was "
    "initialized before conftest could configure it")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    # tier-1 runs with -m 'not slow'; chaos scenarios that outgrow ~5s
    # carry this marker so the fast suite stays fast (make chaos runs
    # everything)
    config.addinivalue_line(
        "markers", "slow: long-running chaos/scenario tests excluded "
                   "from the tier-1 fast suite")


@pytest.fixture(autouse=True, scope="session")
def _quiet_naming_refresh_noise():
    """Dead loopback registries from already-finished tests would spam
    '[naming] refresh failed' across the whole run."""
    from brpc_tpu import flags
    from brpc_tpu.policy import naming  # noqa: F401 — defines the flag
    flags.set_flag("naming_log_refresh_failures", False, force=True)
    yield
