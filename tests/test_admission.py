"""Usercode admission control + inline event-loop mode (VERDICT r4 #4).

The reference sheds excess load with ELIMIT via its ConcurrencyLimiter
(server.h max_concurrency); here the bound is a LATENCY budget: when the
estimated wait for the GIL-serialized Python lane exceeds
ServerOptions.usercode_latency_budget_ms, requests are answered ELIMIT
natively (net/rpc.cc, the request never reaches Python).
usercode_inline runs non-blocking handlers directly on the dispatcher
thread (single-threaded event loop).

Seed-failure triage (ISSUE 16 satellite): the shed path in net/rpc.cc
fires only when BOTH (a) more than two usercode upcalls are pending and
(b) the process-global handler-latency EMA already exceeds the budget.
Both are host-scheduling-dependent: a slow or single-core box can
serialize the client sockets so pending never exceeds two, and the EMA
(which starts at zero and persists across tests in the process) may not
cross the budget before a short storm ends — either way
``test_latency_budget_sheds_with_elimit`` sees zero ELIMITs and fails
while the production mechanism is healthy.  The test now pre-warms the
EMA with sequential calls (pending <= 1, never shed) and releases the
storm through a barrier so all workers' first calls overlap, making the
shed condition deterministic instead of a scheduling accident."""
import threading
import time

import brpc_tpu as brpc
from brpc_tpu import errors
from brpc_tpu._core import core


def test_inline_mode_roundtrip_and_reset():
    class Echo(brpc.Service):
        @brpc.method(request="raw", response="raw")
        def Echo(self, cntl, req):
            return bytes(req)

    srv = brpc.Server(brpc.ServerOptions(usercode_inline=True))
    srv.add_service(Echo())
    srv.start("127.0.0.1", 0)
    try:
        assert core.brpc_usercode_inline() == 1
        ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=5000)
        # small (flat fast path), empty, and split/large (IOBuf path)
        for sz in (0, 128, 70000):
            payload = b"q" * sz
            got = ch.call_sync("Echo", "Echo", payload, serializer="raw")
            assert bytes(got) == payload
    finally:
        srv.stop()
        srv.join()
    # inline is process-wide native state; join() must clear it
    assert core.brpc_usercode_inline() == 0


def test_latency_budget_sheds_with_elimit():
    class Slow(brpc.Service):
        @brpc.method(request="raw", response="raw")
        def Work(self, cntl, req):
            time.sleep(0.005)
            return b"done"

    srv = brpc.Server(brpc.ServerOptions(usercode_latency_budget_ms=2.0))
    srv.add_service(Slow())
    srv.start("127.0.0.1", 0)
    oks, errs = [], []
    # pre-warm the process-global latency EMA past the budget with
    # SEQUENTIAL calls (pending <= 1 never sheds) so the storm below
    # doesn't race the estimator's warm-up — see the module docstring
    warm_ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=8000,
                           max_retry=0)
    for _ in range(3):
        warm_ch.call_sync("Slow", "Work", b"w", serializer="raw")
    # all workers' first calls arrive together: >2 pending upcalls is
    # the other half of the shed condition
    gate = threading.Barrier(8)

    def worker():
        ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=8000,
                          max_retry=0)
        gate.wait(timeout=10)
        for _ in range(6):
            try:
                oks.append(ch.call_sync("Slow", "Work", b"x",
                                        serializer="raw"))
            except errors.RpcError as e:
                errs.append(e.code)

    try:
        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        srv.stop()
        srv.join()
    # under 8-way 5ms-handler pressure against a 2ms budget, some calls
    # must be shed — and the shed surfaces as ELIMIT, not a timeout
    assert oks, "some calls must succeed"
    assert any(c == errors.ELIMIT for c in errs), \
        f"expected ELIMIT sheds; ok={len(oks)} errs={errs[:5]}"
    assert core.brpc_usercode_shed_count() > 0
    # budget cleared for later servers/tests
    assert core.brpc_usercode_budget_us() == 0


def test_budget_zero_never_sheds():
    class Echo(brpc.Service):
        @brpc.method(request="raw", response="raw")
        def Echo(self, cntl, req):
            return bytes(req)

    before = core.brpc_usercode_shed_count()
    srv = brpc.Server()
    srv.add_service(Echo())
    srv.start("127.0.0.1", 0)
    try:
        ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=5000)
        for i in range(50):
            assert bytes(ch.call_sync("Echo", "Echo", b"x%d" % i,
                                      serializer="raw")) == b"x%d" % i
    finally:
        srv.stop()
        srv.join()
    assert core.brpc_usercode_shed_count() == before
