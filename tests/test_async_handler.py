"""Deferred server handlers (cntl.defer() -> done closure) — the RPC-level
half of VERDICT r2 task 3: 10k concurrent in-flight RPCs served without
10k OS threads.

Reference: brpc passes a done Closure into svc->CallMethod
(baidu_rpc_protocol.cpp:398); the handler may return and any thread runs
done->Run() later, so an in-flight RPC is parked state, not a parked
thread.  Here cntl.defer() returns the one-shot done(response) callable.
"""
import threading
import time

import pytest

from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.server import Server
from brpc_tpu.rpc.service import Service, method


def _os_thread_count() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("Threads:"):
                return int(line.split()[1])
    raise RuntimeError("no Threads: line")


class ParkService(Service):
    NAME = "Park"

    def __init__(self):
        self.parked = []
        self.mu = threading.Lock()

    @method(request="raw", response="raw")
    def Hold(self, cntl, request):
        done = cntl.defer()
        with self.mu:
            self.parked.append((done, request))
        return None  # ignored for deferred RPCs

    @method(request="raw", response="raw")
    def Echo(self, cntl, request):
        return request


@pytest.fixture()
def server():
    svc = ParkService()
    srv = Server()
    srv.add_service(svc)
    srv.start("127.0.0.1", 0)
    yield srv, svc
    srv.stop()
    srv.join()


class TestDeferredHandlers:
    def test_single_deferred_roundtrip(self, server):
        srv, svc = server
        ch = Channel(f"127.0.0.1:{srv.port}")
        results = []
        cntl = ch.call("Park", "Hold", b"ping",
                       cntl=Controller(timeout_ms=10_000),
                       done=lambda c: results.append(c))
        deadline = time.monotonic() + 5
        while not svc.parked and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(svc.parked) == 1
        assert not results            # still in flight
        done, req = svc.parked.pop()
        done(req + b"-released")
        deadline = time.monotonic() + 5
        while not results and time.monotonic() < deadline:
            time.sleep(0.005)
        assert results and results[0].error_code == 0
        assert results[0].response == b"ping-released"


    def test_done_twice_raises(self, server):
        srv, svc = server
        ch = Channel(f"127.0.0.1:{srv.port}")
        cntl = ch.call("Park", "Hold", b"x",
                       cntl=Controller(timeout_ms=10_000),
                       done=lambda c: None)
        deadline = time.monotonic() + 5
        while not svc.parked and time.monotonic() < deadline:
            time.sleep(0.005)
        done, req = svc.parked.pop()
        done(req)
        with pytest.raises(RuntimeError):
            done(req)


    def test_defer_outside_handler_raises(self):
        with pytest.raises(RuntimeError):
            Controller().defer()

    def test_raise_after_defer_leaves_completion_to_done(self):
        """Once defer() hands response ownership to done(), a handler
        exception is logged, not auto-responded — the parked done() still
        completes the RPC (the reference's done-Closure contract:
        svc->CallMethod return never sends the response)."""
        class Bad(Service):
            NAME = "Bad"

            @method(request="raw", response="raw")
            def Boom(self, cntl, request):
                d = cntl.defer()
                threading.Timer(0.05, lambda: d(b"late-ok")).start()
                raise ValueError("handler bug after defer")

        srv = Server()
        srv.add_service(Bad())
        srv.start("127.0.0.1", 0)
        try:
            ch = Channel(f"127.0.0.1:{srv.port}")
            assert ch.call_sync("Bad", "Boom", b"x") == b"late-ok"
        finally:
            srv.stop()
            srv.join()

    def test_10k_inflight_without_10k_threads(self, server):
        """The task-3 'done' bar, end to end over real sockets: 10,000
        RPCs accepted and parked server-side while the process thread
        count stays flat; release them all; every client callback fires
        with the right payload."""
        srv, svc = server
        n = 10_000
        ch = Channel(f"127.0.0.1:{srv.port}")
        completed = []
        completed_mu = threading.Lock()

        def on_done(c):
            with completed_mu:
                completed.append(c)

        before = _os_thread_count()
        cntls = [ch.call("Park", "Hold", str(i).encode(),
                         cntl=Controller(timeout_ms=120_000), done=on_done)
                 for i in range(n)]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with svc.mu:
                if len(svc.parked) == n:
                    break
            time.sleep(0.02)
        with svc.mu:
            parked = len(svc.parked)
        during = _os_thread_count()
        assert parked == n, f"only {parked}/{n} RPCs parked"
        assert not completed
        # 10k in-flight RPCs added no per-RPC threads (closures, not
        # stacks); allowance covers lazily-started runtime threads only
        assert during - before < 32, (
            f"thread count grew {before} -> {during} with {n} in-flight")
        with svc.mu:
            batch = list(svc.parked)
            svc.parked.clear()
        for done, req in batch:
            done(req + b"!")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with completed_mu:
                if len(completed) == n:
                    break
            time.sleep(0.02)
        with completed_mu:
            assert len(completed) == n, f"{len(completed)}/{n} completed"
            errs = [c.error_code for c in completed if c.error_code != 0]
            assert not errs, f"{len(errs)} failed, first codes {errs[:5]}"
            bodies = {bytes(c.response) for c in completed}
        assert bodies == {f"{i}!".encode() for i in range(n)}

