"""Sequence-parallel attention on the virtual 8-device mesh: ring and
Ulysses must match single-device full attention exactly (long-context
first-class requirement; SURVEY.md §5.7 design slot)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.ops import (flash_attention, local_attention, ring_attention,
                          ulysses_attention)

B, S, H, D = 2, 256, 8, 32
N = 8


def _qkv(seed=0, dtype=jnp.float32, s=None):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, s if s is not None else S, H, D)
    return tuple(jax.random.normal(k, shape, dtype) * 0.5 for k in ks)


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("sp",))


def _run_sharded(fn, q, k, v, **kw):
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.4.38 exposes it under experimental only
        from jax.experimental.shard_map import shard_map
    mesh = _mesh()
    spec = P(None, "sp", None, None)

    @jax.jit
    def run(q, k, v):
        return shard_map(lambda a, b, c: fn(a, b, c, axis_name="sp", **kw),
                         mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)

    sh = NamedSharding(mesh, spec)
    return run(jax.device_put(q, sh), jax.device_put(k, sh),
               jax.device_put(v, sh))


def test_ring_attention_matches_full():
    q, k, v = _qkv()
    ref = local_attention(q, k, v)
    out = _run_sharded(ring_attention, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_causal_matches_full():
    q, k, v = _qkv(seed=1)
    ref = local_attention(q, k, v, causal=True)
    out = _run_sharded(ring_attention, q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_attention_matches_full():
    q, k, v = _qkv(seed=2)
    ref = local_attention(q, k, v)
    out = _run_sharded(ulysses_attention, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_attention_causal_matches_full():
    q, k, v = _qkv(seed=3)
    ref = local_attention(q, k, v, causal=True)
    out = _run_sharded(ulysses_attention, q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_matches_full():
    q, k, v = _qkv(seed=4)
    ref = local_attention(q, k, v)
    out = flash_attention(q, k, v, blk_q=64, blk_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_causal_matches_full():
    """The causal kernel cuts the K-block loop at each q block's
    diagonal (trip count depends on program_id) and position-masks the
    straddling block; it must match the masked reference exactly —
    including q rows in the FIRST block, whose only visible key is the
    diagonal."""
    q, k, v = _qkv(seed=6)
    ref = local_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, blk_q=64, blk_k=64, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_indivisible_seq_falls_back():
    """S not divisible by the block sizes routes to local_attention —
    with the causal flag FORWARDED (a silently non-causal fallback would
    be a correctness bug, not a perf one)."""
    q, k, v = _qkv(seed=8, s=100)
    ref = local_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, blk_q=64, blk_k=64, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_causal_uneven_blocks():
    """blk_q != blk_k exercises diagonal blocks that straddle unevenly
    (the trip-count formula's rounding); both orderings must match."""
    q, k, v = _qkv(seed=7)
    ref = local_attention(q, k, v, causal=True)
    for bq, bk in ((32, 64), (64, 32)):
        out = flash_attention(q, k, v, blk_q=bq, blk_k=bk, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_ring_attention_bf16():
    q, k, v = _qkv(seed=5, dtype=jnp.bfloat16)
    ref = local_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32))
    out = _run_sharded(ring_attention, q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_ring_attention_long_sequence_memory_shape():
    """32k tokens over 8 chips: each chip sees 4k; this compiles and runs
    where a full 32k x 32k score matrix would not be materialized."""
    S_long = 32768
    q = jnp.ones((1, S_long, 2, 16), jnp.bfloat16) * 0.01
    k, v = q, q
    out = _run_sharded(ring_attention, q, k, v, causal=True)
    assert out.shape == (1, S_long, 2, 16)
    # row 0 attends only to itself -> output == v row 0
    np.testing.assert_allclose(np.asarray(out[0, 0], dtype=np.float32),
                               np.asarray(v[0, 0], dtype=np.float32),
                               rtol=1e-2)


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_sequence_parallel_exact_across_mesh_sizes(n, fn):
    """Regression: Ulysses' head reassembly interleaved wrongly for any
    n < heads (invisible at n == heads where h/n == 1) — every op must be
    exact on every mesh size, causal on."""
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.4.38 exposes it under experimental only
        from jax.experimental.shard_map import shard_map
    q, k, v = _qkv(seed=10 + n)
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
    spec = P(None, "sp", None, None)
    sh = NamedSharding(mesh, spec)

    @jax.jit
    def run(q, k, v):
        return shard_map(
            lambda a, b, c: fn(a, b, c, axis_name="sp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)

    ref = local_attention(q, k, v, causal=True)
    out = run(jax.device_put(q, sh), jax.device_put(k, sh),
              jax.device_put(v, sh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)


# ---- grouped-query attention (GQA / MQA) ----

class TestGQA:
    def _qkv(self, h_q, h_kv, b=2, s=32, d=16, seed=3):
        key = jax.random.PRNGKey(seed)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, s, h_q, d), jnp.float32) * 0.3
        k = jax.random.normal(kk, (b, s, h_kv, d), jnp.float32) * 0.3
        v = jax.random.normal(kv, (b, s, h_kv, d), jnp.float32) * 0.3
        return q, k, v

    @pytest.mark.parametrize("h_q,h_kv", [(8, 2), (8, 1), (4, 4)])
    def test_local_gqa_matches_expanded(self, h_q, h_kv):
        q, k, v = self._qkv(h_q, h_kv)
        out = local_attention(q, k, v, causal=True)
        ke = jnp.repeat(k, h_q // h_kv, axis=2)
        ve = jnp.repeat(v, h_q // h_kv, axis=2)
        ref = local_attention(q, ke, ve, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_bad_head_ratio_rejected(self):
        q, k, v = self._qkv(6, 4)
        with pytest.raises(ValueError):
            local_attention(q, k, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_gqa_exact(self, causal):
        q, k, v = self._qkv(8, 2, s=8 * N)
        out = _run_sharded(ring_attention, q, k, v, causal=causal)
        ref = local_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=3e-5, atol=3e-5)

    def test_ulysses_gqa_exact(self):
        q, k, v = self._qkv(8, 2, s=8 * N)
        out = _run_sharded(ulysses_attention, q, k, v)
        ref = local_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=3e-5, atol=3e-5)

    def test_flash_gqa_matches_expanded(self):
        q, k, v = self._qkv(8, 2, b=1, s=64, d=16)
        out = flash_attention(q, k, v, blk_q=32, blk_k=32)
        ref = local_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
