"""Write-queue backpressure + circuit breaker upgrades (VERDICT r2 task 6).

- EOVERCROWDED: a stalled reader makes the native socket's unwritten
  backlog hit the overcrowded limit; further writes return -2 instead of
  growing memory without bound (reference socket.h:326-380).
- CircuitBreaker: isolates on latency degradation alone (dual windows),
  holds with exponential backoff, re-admits gradually after revival.
- ClusterRecoverPolicy: vetoes isolation that would breach the
  availability floor (reference cluster_recover_policy.{h,cpp}).
"""
import socket as pysocket
import threading
import time

import pytest

from brpc_tpu._core import core, core_init


@pytest.fixture(scope="module", autouse=True)
def _core():
    core_init(num_workers=4, num_dispatchers=1)
    yield


class TestOvercrowded:
    def test_stalled_reader_gets_overcrowded(self):
        """Fill a native socket's write queue against a reader that never
        reads; the producer must see rc=-2 (EOVERCROWDED), and the
        pending counter must sit at/below the limit."""
        from brpc_tpu.rpc.transport import Transport
        tr = Transport.instance()
        # tiny limit so the test doesn't need to fill real kernel buffers
        old = core.brpc_socket_overcrowded_limit()
        core.brpc_socket_set_overcrowded_limit(256 * 1024)
        try:
            # raw TCP server that accepts and then never reads
            srv = pysocket.socket()
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            port = srv.getsockname()[1]
            stalled = []
            def accept_and_stall():
                c, _ = srv.accept()
                stalled.append(c)       # keep it open, never read
            t = threading.Thread(target=accept_and_stall, daemon=True)
            t.start()
            sid = tr.connect("127.0.0.1", port, lambda *a: None)
            chunk = b"x" * 65536
            saw_overcrowded = False
            rc = 0
            for _ in range(1000):
                rc = tr.write_raw(sid, chunk)
                if rc == -2:
                    saw_overcrowded = True
                    break
            assert saw_overcrowded, "never saw EOVERCROWDED (-2)"
            pending = core.brpc_socket_pending_write(sid)
            assert 0 < pending <= 256 * 1024 + len(chunk)
            # the socket is NOT failed: backpressure is an error to the
            # producer, not a connection teardown
            assert tr.alive(sid)
            tr.close(sid)
            for c in stalled:
                c.close()
            srv.close()
        finally:
            core.brpc_socket_set_overcrowded_limit(old)

    def test_pending_drains_when_reader_resumes(self):
        from brpc_tpu.rpc.transport import Transport
        tr = Transport.instance()
        srv = pysocket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        conns = []
        threading.Thread(target=lambda: conns.append(srv.accept()[0]),
                         daemon=True).start()
        sid = tr.connect("127.0.0.1", port, lambda *a: None)
        for _ in range(16):
            assert tr.write_raw(sid, b"y" * 65536) == 0
        deadline = time.monotonic() + 5
        while not conns and time.monotonic() < deadline:
            time.sleep(0.005)
        got = 0
        conns[0].settimeout(5)
        while got < 16 * 65536:
            got += len(conns[0].recv(1 << 20))
        deadline = time.monotonic() + 5
        while (core.brpc_socket_pending_write(sid) > 0
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert core.brpc_socket_pending_write(sid) == 0
        tr.close(sid)
        conns[0].close()
        srv.close()


class TestCircuitBreakerLatency:
    def _fresh(self):
        from brpc_tpu.policy.circuit_breaker import CircuitBreaker
        return CircuitBreaker()

    def test_latency_degradation_alone_isolates(self):
        """Zero errors, latency jumps 10x: must isolate (VERDICT done
        bar: 'CB isolates on latency degradation alone')."""
        from brpc_tpu.butil.endpoint import str2endpoint
        cb = self._fresh()
        isolated = []
        cb.mark_as_broken = lambda ep: isolated.append(ep)
        ep = str2endpoint("10.0.0.1:80")
        for _ in range(100):               # healthy baseline ~1ms
            cb.on_call_end(ep, 0, latency_us=1000)
        assert not isolated
        for _ in range(40):                # degraded: 10x slower, no errors
            cb.on_call_end(ep, 0, latency_us=10_000)
            if isolated:
                break
        assert isolated == [ep]

    def test_5x_latency_degradation_isolates(self):
        """The documented 4-5x regime: with the baseline-poisoning guard
        (degraded samples don't feed the long window once it's mature),
        any sustained slowdown beyond LATENCY_RATIO trips.  Without the
        guard the contaminated baseline meant only >7.7x ever could."""
        from brpc_tpu.butil.endpoint import str2endpoint
        cb = self._fresh()
        isolated = []
        cb.mark_as_broken = lambda ep: isolated.append(ep)
        ep = str2endpoint("10.0.0.9:80")
        for _ in range(100):
            cb.on_call_end(ep, 0, latency_us=1000)
        assert not isolated
        for _ in range(60):                # sustained 5x, zero errors
            cb.on_call_end(ep, 0, latency_us=5000)
            if isolated:
                break
        assert isolated == [ep]

    def test_error_rate_still_isolates(self):
        from brpc_tpu.butil.endpoint import str2endpoint
        cb = self._fresh()
        isolated = []
        cb.mark_as_broken = lambda ep: isolated.append(ep)
        ep = str2endpoint("10.0.0.2:80")
        for _ in range(40):
            cb.on_call_end(ep, 1004, latency_us=0)
        assert isolated

    def test_isolation_hold_backs_off(self):
        from brpc_tpu.butil.endpoint import str2endpoint
        cb = self._fresh()
        ep = str2endpoint("10.0.0.3:80")
        cb._isolation_count[ep] = 1
        h1 = cb._hold_s(ep)
        cb._isolation_count[ep] = 4
        h2 = cb._hold_s(ep)
        assert h2 == 8 * h1
        cb._isolation_count[ep] = 40
        assert cb._hold_s(ep) == cb.MAX_HOLD_S

    def test_gradual_recovery_ramp(self):
        from brpc_tpu.butil.endpoint import str2endpoint
        cb = self._fresh()
        ep = str2endpoint("10.0.0.4:80")
        cb.on_revived(ep)
        # early in the ramp: admission is probabilistic, not total
        admits = sum(1 for _ in range(300) if cb.admit(ep))
        assert 0 < admits < 300
        # after the window the endpoint is fully admitted and state clean
        cb._recovering_until[ep] = time.monotonic() - 0.01
        assert cb.admit(ep)
        assert cb.isolation_count(ep) == 0


class TestClusterRecoverPolicy:
    def test_floor_veto(self):
        from brpc_tpu.policy.cluster_recover_policy import \
            ClusterRecoverPolicy
        p = ClusterRecoverPolicy(min_working=2)
        assert p.can_isolate(total=5, healthy=4)      # 3 remain >= 2
        assert not p.can_isolate(total=5, healthy=2)  # would leave 1 < 2
        assert p.in_recovery()

    def test_ratio_floor(self):
        from brpc_tpu.policy.cluster_recover_policy import \
            ClusterRecoverPolicy
        p = ClusterRecoverPolicy(min_working=1, min_working_ratio=0.5)
        assert not p.can_isolate(total=10, healthy=5)  # floor is 5
        assert p.can_isolate(total=10, healthy=7)

    def test_breaker_respects_veto(self):
        from brpc_tpu.butil.endpoint import str2endpoint
        from brpc_tpu.policy.circuit_breaker import CircuitBreaker

        class VetoAll:
            def can_isolate(self, ep):
                return False

        cb = CircuitBreaker()
        isolated = []
        cb.mark_as_broken = lambda ep: isolated.append(ep)
        ep = str2endpoint("10.0.0.5:80")
        for _ in range(60):
            cb.on_call_end(ep, 1004, cluster=VetoAll())
        assert not isolated
