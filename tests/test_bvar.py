"""bvar unit tests (analog of test_bvar suite, SURVEY.md §4)."""
import threading
import time

from brpc_tpu import bvar


class TestReducers:
    def test_adder_across_threads(self):
        a = bvar.Adder()
        n_threads, per = 8, 10_000

        def w():
            for _ in range(per):
                a.add(1)

        ts = [threading.Thread(target=w) for _ in range(n_threads)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert a.get_value() == n_threads * per

    def test_maxer_miner(self):
        mx, mn = bvar.Maxer(), bvar.Miner()
        for v in (5, 3, 9, 1):
            mx.add(v)
            mn.add(v)
        assert mx.get_value() == 9
        assert mn.get_value() == 1

    def test_lshift_sugar(self):
        a = bvar.Adder()
        a << 5 << 7
        assert a.get_value() == 12

    def test_passive_status(self):
        p = bvar.PassiveStatus(lambda: 42)
        assert p.get_value() == 42

    def test_registry_and_dump(self):
        a = bvar.Adder("test_dump_counter")
        a.add(3)
        d = bvar.dump_exposed("test_dump_*")
        assert d["test_dump_counter"] == 3
        a.hide()
        assert "test_dump_counter" not in bvar.dump_exposed("test_dump_*")


class TestRecorders:
    def test_int_recorder_avg(self):
        r = bvar.IntRecorder()
        for v in (10, 20, 30):
            r.add(v)
        assert r.get_value() == 20
        assert r.count == 3

    def test_latency_recorder_percentiles(self):
        r = bvar.LatencyRecorder()
        for v in range(1, 1001):
            r.add(v)
        p50 = r.latency_percentile(0.5)
        p99 = r.latency_percentile(0.99)
        assert 350 <= p50 <= 700       # log-bucket resolution ~4%
        assert 900 <= p99 <= 1100
        assert r.max_latency() == 1000
        assert r.count() == 1000

    def test_multi_dimension(self):
        md = bvar.MultiDimension(["method", "code"], lambda: bvar.Adder())
        md.get_stats("Echo", "0").add(5)
        md.get_stats("Echo", "500").add(1)
        assert md.count_stats() == 2
        assert md.get_stats("Echo", "0").get_value() == 5
        assert md.has_stats("Echo", "500")
        md.delete_stats("Echo", "500")
        assert not md.has_stats("Echo", "500")


class TestWindowSemantics:
    """Window/PerSecond delta math driven by synthetic samples (the
    bvar_window_unittest role) — take_sample() is called directly so the
    tests are deterministic, no sampler-thread sleeps."""

    def test_window_reports_delta_over_window(self):
        from brpc_tpu.bvar.reducer import Adder
        from brpc_tpu.bvar.window import Window
        a = Adder()
        w = Window(a, window_size=10)
        for add in (100, 50, 25):
            a.add(add)
            w.take_sample()
        # newest (175) minus the sample at/after newest_t - 10s; all
        # samples are within the window here, so delta vs the oldest
        assert w.get_value() == 75      # 175 - 100

    def test_window_drops_samples_past_horizon(self):
        from brpc_tpu.bvar.reducer import Adder
        from brpc_tpu.bvar.window import Window
        a = Adder()
        w = Window(a, window_size=1)
        a.add(10)
        w.take_sample()
        # age the first sample beyond window+2s; next sample must evict it
        with w._mu:
            w._samples[0] = (w._samples[0][0] - 4.0, w._samples[0][1])
        a.add(5)
        w.take_sample()
        assert len(w._samples) == 1     # horizon eviction
        assert w.get_value() == 0       # single sample: no delta yet

    def test_per_second_rate(self):
        from brpc_tpu.bvar.reducer import Adder
        from brpc_tpu.bvar.window import PerSecond
        a = Adder()
        p = PerSecond(a, window_size=10)
        a.add(0)
        p.take_sample()
        # fake 2 seconds of age on the first sample, then +300
        with p._mu:
            p._samples[0] = (p._samples[0][0] - 2.0, p._samples[0][1])
        a.add(300)
        p.take_sample()
        rate = p.get_value()
        assert 140 <= rate <= 160       # 300 over ~2s

    def test_window_non_numeric_passthrough(self):
        from brpc_tpu.bvar.reducer import PassiveStatus
        from brpc_tpu.bvar.window import Window
        v = PassiveStatus(lambda: "status-string")
        w = Window(v, window_size=5)
        w.take_sample()
        w.take_sample()
        assert w.get_value() == "status-string"   # TypeError fallback
