"""Channel behavior matrix (the reference's largest suite,
test/brpc_channel_unittest.cpp: 64 TESTs over cancel/timeout/retry/backup
— SURVEY.md §4).  Loopback servers play the cluster."""
import threading
import time

import pytest

import brpc_tpu as brpc
from brpc_tpu import errors
from brpc_tpu.rpc.channel import ChannelOptions, RetryPolicy


class Echo(brpc.Service):
    @brpc.method(request="raw", response="raw")
    def Echo(self, cntl, req):
        return req

    @brpc.method(request="json", response="json")
    def Sleep(self, cntl, req):
        time.sleep(req.get("s", 0))
        return {"slept": req.get("s", 0)}

    @brpc.method(request="json", response="json")
    def Fail(self, cntl, req):
        cntl.set_failed(int(req.get("code", errors.EINTERNAL)),
                        "requested failure")
        return None


@pytest.fixture
def server():
    srv = brpc.Server()
    srv.add_service(Echo())
    srv.start("127.0.0.1", 0)
    yield srv
    srv.stop()
    srv.join()


class TestDeadlines:
    def test_deadline_enforced_for_async_calls(self, server):
        ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=200)
        done = threading.Event()
        out = {}

        def on_done(cntl):
            out["code"] = cntl.error_code
            done.set()

        ch.call("Echo", "Sleep", {"s": 2}, serializer="json",
                done=on_done)
        assert done.wait(5)
        assert out["code"] == errors.ERPCTIMEDOUT

    def test_server_side_failure_code_propagates(self, server):
        ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=2000)
        with pytest.raises(errors.RpcError) as ei:
            ch.call_sync("Echo", "Fail", {"code": 1234}, serializer="json")
        assert ei.value.code == 1234

    def test_deadline_not_consumed_by_fast_calls(self, server):
        ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=500)
        for _ in range(20):
            assert ch.call_sync("Echo", "Echo", b"q",
                                serializer="raw") == b"q"


class TestRetry:
    def test_no_retry_on_application_error(self, server):
        """EINTERNAL set by the HANDLER must not be retried (the reference
        retries transport errors, not app errors)."""
        calls = []

        class Counting(brpc.Service):
            NAME = "Count"

            @brpc.method(request="json", response="json")
            def Hit(self, cntl, req):
                calls.append(1)
                cntl.set_failed(errors.EPERM_RPC
                                if hasattr(errors, "EPERM_RPC") else 1008,
                                "app error")
                return None

        srv = brpc.Server()
        srv.add_service(Counting())
        srv.start("127.0.0.1", 0)
        try:
            ch = brpc.Channel(f"127.0.0.1:{srv.port}",
                              options=ChannelOptions(timeout_ms=2000,
                                                     max_retry=3))
            with pytest.raises(errors.RpcError):
                ch.call_sync("Count", "Hit", {}, serializer="json")
            assert len(calls) == 1
        finally:
            srv.stop()
            srv.join()

    def test_connection_refused_retries_then_fails(self):
        # a dead port: every attempt fails with a retryable error; the
        # call must exhaust max_retry and surface a connection error
        ch = brpc.Channel("127.0.0.1:1",   # reserved port, nothing listens
                          options=ChannelOptions(timeout_ms=2000,
                                                 max_retry=2))
        with pytest.raises(errors.RpcError) as ei:
            ch.call_sync("Echo", "Echo", b"x", serializer="raw")
        assert ei.value.code in (errors.ECONNREFUSED,
                                 errors.EFAILEDSOCKET)

    def test_custom_retry_policy_consulted(self, server):
        consulted = []

        class Never(RetryPolicy):
            def do_retry(self, cntl):
                consulted.append(cntl.error_code)
                return False

        ch = brpc.Channel("127.0.0.1:1",
                          options=ChannelOptions(timeout_ms=2000,
                                                 max_retry=3,
                                                 retry_policy=Never()))
        with pytest.raises(errors.RpcError):
            ch.call_sync("Echo", "Echo", b"x", serializer="raw")
        assert len(consulted) == 1   # failed once, policy said stop


class TestBackup:
    def test_backup_fires_and_first_response_wins(self, server):
        """backup_request_ms on a slow call: the backup attempt answers
        first; exactly one response reaches the caller."""

        hits = []

        class Lazy(brpc.Service):
            NAME = "Lazy"

            @brpc.method(request="json", response="json")
            def Get(self, cntl, req):
                hits.append(time.monotonic())
                if len(hits) == 1:
                    time.sleep(1.0)      # first attempt dawdles
                return {"n": len(hits)}

        srv = brpc.Server()
        srv.add_service(Lazy())
        srv.start("127.0.0.1", 0)
        try:
            ch = brpc.Channel(
                f"127.0.0.1:{srv.port}",
                options=ChannelOptions(timeout_ms=5000,
                                       backup_request_ms=100))
            t0 = time.monotonic()
            out = ch.call_sync("Lazy", "Get", {}, serializer="json")
            dt = time.monotonic() - t0
            assert out["n"] >= 2          # backup attempt served it
            assert dt < 0.9               # did not wait for the dawdler
        finally:
            srv.stop()
            srv.join()


class TestCancellation:
    def test_cancel_inflight_surfaces_ecanceled(self, server):
        ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=5000)
        cntl = brpc.Controller()
        done = threading.Event()
        out = {}

        def on_done(c):
            out["code"] = c.error_code
            done.set()

        ch.call("Echo", "Sleep", {"s": 2}, serializer="json", cntl=cntl,
                done=on_done)
        time.sleep(0.1)
        assert cntl.cancel()
        assert done.wait(5)
        assert out["code"] == errors.ECANCELED

    def test_cancel_after_completion_is_noop(self, server):
        ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=2000)
        cntl = brpc.Controller()
        ch.call_sync("Echo", "Echo", b"x", serializer="raw", cntl=cntl)
        assert not cntl.cancel()
        assert cntl.error_code == 0


class TestAttachmentAndMeta:
    def test_large_attachment_roundtrip(self, server):
        ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=5000)
        att = bytes(range(256)) * 1000   # 256 KB

        class _:
            pass

        cntl = brpc.Controller()
        cntl.request_attachment = att
        out = ch.call_sync("Echo", "Echo", b"body", serializer="raw",
                           cntl=cntl)
        assert out == b"body"

    def test_user_fields_reach_the_server(self, server):
        seen = {}

        class Meta(brpc.Service):
            NAME = "Meta"

            @brpc.method(request="json", response="json")
            def Peek(self, cntl, req):
                seen.update(cntl.request_meta.user_fields or {})
                return {}

        srv = brpc.Server()
        srv.add_service(Meta())
        srv.start("127.0.0.1", 0)
        try:
            ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=2000)
            cntl = brpc.Controller()
            cntl.user_fields["shard"] = "7"
            ch.call_sync("Meta", "Peek", {}, serializer="json", cntl=cntl)
            # wire convention: user-field VALUES arrive as bytes
            # (meta.py decode; rail._norm documents the same)
            assert seen.get("shard") == b"7"
        finally:
            srv.stop()
            srv.join()


class TestResponseUserFields:
    def test_round_trip(self):
        srv = brpc.Server()

        class Tagger(brpc.Service):
            NAME = "Tagger"

            @brpc.method(request="json", response="json")
            def Get(self, cntl, req):
                cntl.response_user_fields["served-by"] = "replica-3"
                cntl.response_user_fields["blob"] = b"\x01\x02"
                return {}

        srv.add_service(Tagger())
        srv.start("127.0.0.1", 0)
        try:
            ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=3000)
            cntl = brpc.Controller()
            ch.call_sync("Tagger", "Get", {}, serializer="json", cntl=cntl)
            assert cntl.response_user_fields["served-by"] == b"replica-3"
            assert cntl.response_user_fields["blob"] == b"\x01\x02"
        finally:
            srv.stop()
            srv.join()

    def test_reserved_key_is_a_handler_error(self):
        srv = brpc.Server()

        class Bad(brpc.Service):
            NAME = "BadTag"

            @brpc.method(request="json", response="json")
            def Get(self, cntl, req):
                cntl.response_user_fields["icit"] = "spoof"
                return {}

        srv.add_service(Bad())
        srv.start("127.0.0.1", 0)
        try:
            ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=3000)
            with pytest.raises(errors.RpcError) as ei:
                ch.call_sync("BadTag", "Get", {}, serializer="json")
            assert ei.value.code == errors.EINTERNAL
        finally:
            srv.stop()
            srv.join()

    def test_plain_responses_keep_the_native_fast_path(self):
        """No user fields -> the response still packs natively (the
        fast-path condition must not regress for the common case)."""
        srv = brpc.Server()
        srv.add_service(Echo())
        srv.start("127.0.0.1", 0)
        try:
            ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=3000)
            cntl = brpc.Controller()
            assert ch.call_sync("Echo", "Echo", b"q", serializer="raw",
                                cntl=cntl) == b"q"
            assert cntl.response_user_fields == {}
        finally:
            srv.stop()
            srv.join()

    def test_fields_survive_failed_completion(self):
        srv = brpc.Server()

        class FailTag(brpc.Service):
            NAME = "FailTag"

            @brpc.method(request="json", response="json")
            def Get(self, cntl, req):
                cntl.response_user_fields["hint"] = "try-replica-2"
                cntl.set_failed(1404, "not here")
                return None

        srv.add_service(FailTag())
        srv.start("127.0.0.1", 0)
        try:
            ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=3000)
            cntl = brpc.Controller()
            with pytest.raises(errors.RpcError) as ei:
                ch.call_sync("FailTag", "Get", {}, serializer="json",
                             cntl=cntl)
            assert ei.value.code == 1404
            assert cntl.response_user_fields == {"hint": b"try-replica-2"}
        finally:
            srv.stop()
            srv.join()
