"""Chaos test suite — seeded fault injection over the full RPC/ICI data
path (brpc_tpu/fault.py).

Each scenario runs REAL client/server pairs over loopback under a
deterministic fault schedule and asserts the hard invariants the
recovery stack promises:

  * every call finishes exactly once, with a definite success or error
    (never a hang, never a double completion);
  * no leaked deadline/backup timers after calls complete;
  * block-pool occupancy and stream credit return to baseline after
    drain (duplicate-frame credit loss is explained by the
    reorder_replay_bytes_dropped counter, never silent);
  * broken endpoints get probed and revived once reachable, and the
    circuit-breaker isolation hold is respected while broken.

Scenarios are parametrized over three fixed seeds (override with
BRPC_CHAOS_SEEDS=..., comma-separated) so the schedule is a regression
artifact, not a dice roll.  `make chaos` runs exactly this file.
"""
import io
import os
import socket
import threading
import time

import numpy as np
import pytest

import brpc_tpu as brpc
from brpc_tpu import errors, fault
from brpc_tpu.butil.endpoint import str2endpoint
from brpc_tpu.rpc import meta as M
from brpc_tpu.rpc.channel import CallManager, SocketMap
from brpc_tpu.rpc.transport import Transport

from testutil import wait_until

SEEDS = [int(s) for s in
         os.environ.get("BRPC_CHAOS_SEEDS", "101,202,303").split(",")]


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _chaos_hygiene():
    """Fast health probes for the duration, and NEVER leak an installed
    plan or broken-endpoint state into the rest of the suite."""
    from brpc_tpu.policy import health_check as hc
    old = hc.health_check_interval_s
    hc.health_check_interval_s = 0.05
    fault.clear()
    yield
    fault.clear()
    hc.health_check_interval_s = old
    hc.reset_all()


class EchoService(brpc.Service):
    NAME = "ChaosEcho"

    @brpc.method(request="json", response="json")
    def Echo(self, cntl, req):
        return {"msg": req["msg"]}


@pytest.fixture()
def server():
    s = brpc.Server()
    s.add_service(EchoService())
    s.start("127.0.0.1", 0)
    yield s
    s.stop()
    s.join()


class DoneCounter:
    """Counts completions — the exactly-once probe.  Locked: a double
    completion is by definition two threads racing into __call__, and an
    unsynchronized += could lose exactly the increment that proves it."""

    def __init__(self):
        self.n = 0
        self.cntl = None
        self.event = threading.Event()
        self._mu = threading.Lock()

    def __call__(self, cntl):
        with self._mu:
            self.n += 1
        self.cntl = cntl
        self.event.set()


def _timer_count() -> int:
    return len(Transport.instance()._timer_cbs)


def _pending_calls() -> int:
    return len(CallManager.instance()._pending)


def assert_quiesced(timers_before: int) -> None:
    """No call left pending, no deadline/backup timer leaked."""
    assert wait_until(lambda: _pending_calls() == 0, 10), \
        f"{_pending_calls()} calls still pending after chaos"
    assert wait_until(lambda: _timer_count() <= timers_before, 10), \
        f"timers leaked: {_timer_count()} > baseline {timers_before}"


# ---------------------------------------------------------------------------
# scenario 1: connection refused, retry succeeds
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_connect_refused_then_retry(server, seed):
    port = server.port
    plan = fault.FaultPlan(seed).on(
        "transport.connect", fault.REFUSE, times=1,
        match=lambda ctx: ctx.get("port") == port)
    timers0 = _timer_count()
    ch = brpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000, max_retry=3)
    done = DoneCounter()
    with fault.injected(plan):
        ch.call("ChaosEcho", "Echo", {"msg": "hi"}, serializer="json",
                done=done)
        assert done.event.wait(10), "call hung under connect fault"
    time.sleep(0.05)           # a double completion would land here
    assert done.n == 1
    assert not done.cntl.failed()
    assert done.cntl.response == {"msg": "hi"}
    assert plan.injected["transport.connect"] == 1
    assert_quiesced(timers0)


@pytest.mark.parametrize("seed", SEEDS)
def test_connect_refused_persistent_definite_error(server, seed):
    port = server.port
    plan = fault.FaultPlan(seed).on(
        "transport.connect", fault.REFUSE, times=-1,
        match=lambda ctx: ctx.get("port") == port)
    timers0 = _timer_count()
    ch = brpc.Channel(f"127.0.0.1:{port}", timeout_ms=2000, max_retry=2)
    done = DoneCounter()
    with fault.injected(plan):
        ch.call("ChaosEcho", "Echo", {"msg": "hi"}, serializer="json",
                done=done)
        assert done.event.wait(10), "call hung under persistent refusal"
    time.sleep(0.05)
    assert done.n == 1
    assert done.cntl.failed()
    assert done.cntl.error_code == errors.ECONNREFUSED
    # every attempt (first + 2 retries) was refused
    assert plan.injected["transport.connect"] == 3
    assert_quiesced(timers0)


# ---------------------------------------------------------------------------
# scenario 2: mid-call connection reset -> retry + probe revival
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_midcall_reset_retries_and_endpoint_revives(server, seed):
    from brpc_tpu.policy import health_check as hc
    port = server.port
    ep = str2endpoint(f"127.0.0.1:{port}")
    ch = brpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000, max_retry=3)
    assert ch.call_sync("ChaosEcho", "Echo", {"msg": "warm"},
                        serializer="json") == {"msg": "warm"}
    sid = SocketMap.instance()._conns[ep].sid
    plan = fault.FaultPlan(seed).on(
        "transport.send", fault.RESET, times=1,
        match=lambda ctx: ctx.get("sid") == sid)
    timers0 = _timer_count()
    with fault.injected(plan):
        resp = ch.call_sync("ChaosEcho", "Echo", {"msg": "again"},
                            serializer="json")
    assert resp == {"msg": "again"}
    assert plan.injected["transport.send"] == 1
    # the reset marked the endpoint broken; the server is alive, so the
    # probe loop must revive it
    assert wait_until(lambda: not hc.is_broken(ep), 10), \
        "endpoint never revived after injected reset"
    assert_quiesced(timers0)


# ---------------------------------------------------------------------------
# scenario 3: corrupt frame on the gRPC/h2 plane -> definite outcome
# ---------------------------------------------------------------------------

class GrpcEcho(brpc.Service):
    NAME = "chaos.Grpc"

    @brpc.method(request="raw", response="raw")
    def Echo(self, cntl, req):
        return req


@pytest.mark.parametrize("seed", SEEDS)
def test_corrupt_h2_frame_definite_outcome(seed):
    from brpc_tpu.rpc.h2 import GrpcChannel
    srv = brpc.Server()
    srv.add_service(GrpcEcho())
    srv.start("127.0.0.1", 0)
    try:
        ch = GrpcChannel(f"127.0.0.1:{srv.port}", timeout_ms=3000)
        payload = b"chaos-payload-" * 8
        assert ch.call("chaos.Grpc", "Echo", payload) == payload   # warm
        sid = ch._conn.sid
        plan = fault.FaultPlan(seed).on(
            "transport.send", fault.CORRUPT, times=1,
            match=lambda ctx: ctx.get("sid") == sid)
        with fault.injected(plan):
            # one flipped byte mid-request: either the h2/HPACK framing
            # catches it (connection error -> RpcError) or it lands in
            # the opaque payload and the echo returns promptly — a
            # DEFINITE outcome within the deadline either way, never a
            # hang or a wedged connection
            try:
                ch.call("chaos.Grpc", "Echo", payload, timeout_ms=3000)
            except errors.RpcError:
                pass
        assert plan.injected["transport.send"] == 1
        # the plane must recover: a fresh call (reconnecting if the
        # corruption killed the connection) succeeds
        assert ch.call("chaos.Grpc", "Echo", b"after-chaos") == b"after-chaos"
        # the h2.send site covers the JOINED unary fast path too: an
        # injected send failure kills the connection -> definite error,
        # then the channel reconnects
        sid2 = ch._conn.sid
        plan2 = fault.FaultPlan(seed).on(
            "h2.send", fault.ERROR, times=1,
            match=lambda ctx: ctx.get("sid") == sid2)
        with fault.injected(plan2):
            with pytest.raises(errors.RpcError):
                ch.call("chaos.Grpc", "Echo", payload, timeout_ms=3000)
        assert plan2.injected["h2.send"] == 1
        assert ch.call("chaos.Grpc", "Echo", b"final") == b"final"
    finally:
        srv.stop()
        srv.join()


@pytest.mark.parametrize("seed", SEEDS)
def test_recv_drop_definite_outcome(seed):
    """`transport.recv` / `h2.recv` DROP (ISSUE 14: the fault-sites
    pass found both sites with ZERO referencing tests — injection
    surface that silently stopped being exercised).  transport.recv
    sees the Python message trampoline (stream traffic — the fault.py
    caveat: unary rides the C fast path), so the scenario drops one
    stream FEEDBACK frame at the TRANSPORT level and the cumulative-
    offset healing of scenario 8 must still hold; h2.recv drops one
    h2 frame on a live gRPC connection -> definite outcome, then the
    connection recovers."""
    N, MSG = 6, 512
    StreamSink.received = []
    StreamSink.got_all = threading.Event()
    StreamSink.want = 2 * N
    srv = brpc.Server()
    srv.add_service(StreamSink())
    srv.start("127.0.0.1", 0)
    try:
        ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=5000)
        cntl = brpc.Controller()
        stream = brpc.stream_create(cntl, None, max_buf_size=8192)
        assert ch.call_sync("ChaosStream", "Open", {}, serializer="json",
                            cntl=cntl) == {"ok": True}
        # one stream frame swallowed BELOW the stream layer, at the
        # client transport's recv trampoline — scoped by sid to the
        # CLIENT connection, where the only trampoline traffic is the
        # server's CONSUMED feedback; loss heals via the next
        # cumulative offset exactly like scenario 8
        client_sid = stream._sid
        plan = fault.FaultPlan(seed).on(
            "transport.recv", fault.DROP, times=1,
            match=lambda ctx: ctx.get("sid") == client_sid)
        with fault.injected(plan):
            for i in range(N):
                stream.write(bytes([i]) * MSG, timeout_s=10)
            assert wait_until(lambda: len(StreamSink.received) >= N, 10), \
                f"only {len(StreamSink.received)}/{N} delivered"
            assert plan.injected["transport.recv"] == 1
            for i in range(N):
                stream.write(bytes([N + i]) * MSG, timeout_s=10)
            assert StreamSink.got_all.wait(10), \
                f"only {len(StreamSink.received)}/{2 * N} delivered"
            assert wait_until(
                lambda: stream._produced - stream._remote_consumed == 0,
                10), "credit lost with the transport-dropped feedback " \
                     "frame never returned"
        stream.close()
    finally:
        srv.stop()
        srv.join()

    # the h2 layer's own recv site, over a live gRPC connection
    from brpc_tpu.rpc.h2 import GrpcChannel
    srv = brpc.Server()
    srv.add_service(GrpcEcho())
    srv.start("127.0.0.1", 0)
    try:
        gch = GrpcChannel(f"127.0.0.1:{srv.port}", timeout_ms=2000)
        assert gch.call("chaos.Grpc", "Echo", b"warm") == b"warm"
        plan2 = fault.FaultPlan(seed).on("h2.recv", fault.DROP, times=1)
        with fault.injected(plan2):
            try:
                gch.call("chaos.Grpc", "Echo", b"payload", timeout_ms=2000)
            except errors.RpcError:
                pass               # dropped frame -> definite error
        assert plan2.injected["h2.recv"] == 1
        assert gch.call("chaos.Grpc", "Echo", b"after") == b"after"
    finally:
        srv.stop()
        srv.join()


@pytest.mark.parametrize("seed", SEEDS)
def test_injected_write_error_does_not_leak_sockets(server, seed):
    """A plain injected write error (rc=-1, socket left open by the
    fault) must not leak the evicted connection: the retry path fails
    the socket so its fd + handler entries are reclaimed."""
    port = server.port
    ep = str2endpoint(f"127.0.0.1:{port}")
    ch = brpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000, max_retry=3)
    assert ch.call_sync("ChaosEcho", "Echo", {"msg": "warm"},
                        serializer="json") == {"msg": "warm"}
    handlers0 = len(Transport.instance()._handlers)
    for k in range(3):
        sid = SocketMap.instance()._conns[ep].sid
        plan = fault.FaultPlan(seed + k).on(
            "transport.send", fault.ERROR, times=1,
            match=lambda ctx, s=sid: ctx.get("sid") == s)
        with fault.injected(plan):
            resp = ch.call_sync("ChaosEcho", "Echo", {"msg": f"r{k}"},
                                serializer="json")
        assert resp == {"msg": f"r{k}"}
        assert plan.injected["transport.send"] == 1
    # each failed-write socket (and its server-side accepted twin) must
    # be reclaimed through the normal failure path — at most the one
    # live replacement pair outlasts the loop
    assert wait_until(
        lambda: len(Transport.instance()._handlers) <= handlers0 + 2,
        10), (f"leaked socket handlers: "
              f"{len(Transport.instance()._handlers)} > {handlers0} + 2")


@pytest.mark.parametrize("seed", SEEDS)
def test_corrupt_unary_body_definite_outcome(server, seed):
    """CORRUPT on transport.send mangles the body even on the native
    fast-send path (a counted injection is never a no-op): the call ends
    definitively — either an error or a promptly-delivered (possibly
    altered) response — and the channel recovers."""
    port = server.port
    ep = str2endpoint(f"127.0.0.1:{port}")
    ch = brpc.Channel(f"127.0.0.1:{port}", timeout_ms=3000, max_retry=3)
    assert ch.call_sync("ChaosEcho", "Echo", {"msg": "warm"},
                        serializer="json") == {"msg": "warm"}
    sid = SocketMap.instance()._conns[ep].sid
    plan = fault.FaultPlan(seed).on(
        "transport.send", fault.CORRUPT, times=1,
        match=lambda ctx: ctx.get("sid") == sid)
    timers0 = _timer_count()
    with fault.injected(plan):
        try:
            ch.call_sync("ChaosEcho", "Echo", {"msg": "x" * 64},
                         serializer="json")
        except errors.RpcError:
            pass
    assert plan.injected["transport.send"] == 1
    assert ch.call_sync("ChaosEcho", "Echo", {"msg": "after"},
                        serializer="json") == {"msg": "after"}
    assert_quiesced(timers0)


# ---------------------------------------------------------------------------
# scenario 4: slow peer (delayed response) triggers the backup request
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_slow_response_triggers_backup_request(server, seed):
    port = server.port
    ep = str2endpoint(f"127.0.0.1:{port}")
    ch = brpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000, max_retry=3,
                      backup_request_ms=100)
    assert ch.call_sync("ChaosEcho", "Echo", {"msg": "warm"},
                        serializer="json") == {"msg": "warm"}
    client_sid = SocketMap.instance()._conns[ep].sid
    # delay the SERVER's response send for the first attempt (the server
    # writes on its accepted socket, not client_sid); the backup attempt
    # races past it
    plan = fault.FaultPlan(seed).on(
        "transport.send", fault.LATENCY, latency_s=1.5, times=1,
        match=lambda ctx: ctx.get("sid") != client_sid)
    timers0 = _timer_count()
    cntl = brpc.Controller()
    with fault.injected(plan):
        t0 = time.monotonic()
        resp = ch.call_sync("ChaosEcho", "Echo", {"msg": "slowpoke"},
                            serializer="json", cntl=cntl)
        elapsed = time.monotonic() - t0
    assert resp == {"msg": "slowpoke"}
    assert cntl.retried_count >= 1, "backup request never fired"
    assert elapsed < 1.2, \
        f"call waited out the slow attempt ({elapsed:.2f}s) instead of " \
        "completing via the backup request"
    assert plan.injected["transport.send"] == 1
    # the delayed first response is a stale attempt: it must not
    # double-complete the call or leak its timers
    time.sleep(1.7 - elapsed if elapsed < 1.7 else 0)
    assert_quiesced(timers0)


# ---------------------------------------------------------------------------
# scenario 5: HBM block-pool exhaustion -> host-serialized fallback
# ---------------------------------------------------------------------------

class TensorEcho(brpc.Service):
    NAME = "ChaosTensor"

    @brpc.method(request="tensor", response="tensor")
    def Double(self, cntl, req):
        return req * 2


@pytest.mark.parametrize("seed", SEEDS)
def test_rail_transfer_fault_falls_back_to_host(seed):
    """An injected ICI transfer failure on the rail's fast path must
    degrade the call to host serialization, not fail it."""
    import jax
    import jax.numpy as jnp
    from brpc_tpu.ici import rail

    dev = jax.devices()[0]
    srv = brpc.Server(brpc.ServerOptions(ici_device=dev))
    srv.add_service(TensorEcho())
    srv.start("127.0.0.1", 0)
    try:
        ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=10000)
        x = jnp.arange(1024, dtype=jnp.float32)
        # warm call: compiles staging kernels, proves the rail path works
        warm = ch.call_sync("ChaosTensor", "Double", x, serializer="tensor")
        np.testing.assert_allclose(np.asarray(warm), np.asarray(x) * 2)
        fb0 = rail.rail_fallbacks.get_value()
        plan = fault.FaultPlan(seed).on("ici.send", fault.ERROR, times=1)
        timers0 = _timer_count()
        with fault.injected(plan):
            resp = ch.call_sync("ChaosTensor", "Double", x,
                                serializer="tensor")
        np.testing.assert_allclose(np.asarray(resp), np.asarray(x) * 2)
        assert plan.injected["ici.send"] == 1
        assert rail.rail_fallbacks.get_value() > fb0, \
            "failed rail transfer did not fall back to host serialization"
        assert_quiesced(timers0)
    finally:
        srv.stop()
        srv.join()


@pytest.mark.parametrize("seed", SEEDS)
def test_block_pool_exhaustion_releases_credit_and_blocks(seed):
    """Injected HBM block exhaustion mid-staging: the block pipe must
    fail definitively, release its window credit, leak no blocks — and
    the SAME transfer succeeds once the pool recovers."""
    import jax
    from brpc_tpu.ici.block_pool import get_block_pool
    from brpc_tpu.ici.endpoint import IciEndpoint

    dev = jax.devices()[0]
    pool = get_block_pool(dev)

    def occupancy():
        with pool._lock:
            return {c: len(pool._free[c]) for c in pool._free}

    free0 = occupancy()
    ep = IciEndpoint(dev)
    payload = bytes(range(256)) * (20 * 1024)   # 5MB -> three 2MB chunks
    try:
        # exhaustion strikes on the SECOND block of the staging run, so
        # the first block is already allocated and must be freed on the
        # error path
        plan = fault.FaultPlan(seed).on("ici.alloc", fault.EXHAUST,
                                        times=1, after=1)
        with fault.injected(plan):
            with pytest.raises(MemoryError):
                ep.send_bytes(payload, pool)
        assert plan.injected["ici.alloc"] == 1
        # invariants: no leaked blocks, no stuck window credit
        assert wait_until(lambda: occupancy() == free0, 10), \
            f"pool leaked blocks: {occupancy()} != {free0}"
        assert wait_until(lambda: ep.inflight_bytes == 0, 10), \
            f"window credit stuck: {ep.inflight_bytes}B in flight"
        # recovery: the same transfer succeeds with the fault cleared
        out = ep.send_bytes(payload, pool)
        got = b"".join(b.get() for b in out)
        assert got == payload
        for b in out:
            b.free()
        assert wait_until(lambda: occupancy() == free0, 10)
        assert wait_until(lambda: ep.inflight_bytes == 0, 10)
    finally:
        ep.close()


# ---------------------------------------------------------------------------
# scenario 6: DCN hop loss (client- and server-side) -> definite errors,
# next hop succeeds
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_dcn_hop_loss_definite_error_then_recovery(seed):
    import jax.numpy as jnp
    from brpc_tpu.ici.channel import register_device_service
    from brpc_tpu.ici.dcn import DcnChannel

    register_device_service("ChaosMat", "Inc", lambda x: x + 1.0)
    srv = brpc.Server(enable_dcn=True)
    srv.start("127.0.0.1", 0)
    try:
        dch = DcnChannel(f"ici://127.0.0.1:{srv.port}/0", timeout_ms=10000)
        plan = (fault.FaultPlan(seed)
                .on("dcn.call", fault.ERROR, times=1)
                .on("dcn.serve", fault.ERROR, times=1))
        x = jnp.ones((8,), jnp.float32)
        timers0 = _timer_count()
        with fault.injected(plan):
            with pytest.raises(errors.RpcError):    # client-side hop loss
                dch.call_sync("ChaosMat", "Inc", x)
            # server-side hop loss: EINTERNAL is retryable, so the
            # channel re-issues and the second attempt lands — the hop
            # loss is healed TRANSPARENTLY by the recovery stack
            out = dch.call_sync("ChaosMat", "Inc", x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) + 1.0)
        assert plan.injected == {"dcn.call": 1, "dcn.serve": 1}
        # persistent hop loss must end in a DEFINITE error (retries
        # exhausted), never a hang
        plan2 = fault.FaultPlan(seed).on("dcn.serve", fault.ERROR, times=-1)
        with fault.injected(plan2):
            with pytest.raises(errors.RpcError) as ei:
                dch.call_sync("ChaosMat", "Inc", x)
            assert ei.value.code == errors.EINTERNAL
        assert plan2.injected["dcn.serve"] >= 1
        # and the data path recovers once the chaos clears
        out2 = dch.call_sync("ChaosMat", "Inc", x)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(x) + 1.0)
        assert_quiesced(timers0)
    finally:
        srv.stop()
        srv.join()


# ---------------------------------------------------------------------------
# scenario 7: duplicate DATA frames (transport replay) — dropped, counted,
# and the drain still balances the credit ledger
# ---------------------------------------------------------------------------

class StreamSink(brpc.Service):
    NAME = "ChaosStream"
    WINDOW = 1024
    received: list = []
    got_all = threading.Event()
    want = 0

    @brpc.method(request="json", response="json")
    def Open(self, cntl, req):
        def on_msg(stream, data):
            StreamSink.received.append(data)
            if len(StreamSink.received) >= StreamSink.want:
                StreamSink.got_all.set()
        cntl.accept_stream(on_msg, max_buf_size=self.WINDOW)
        return {"ok": True}


@pytest.mark.parametrize("seed", SEEDS)
def test_stream_duplicate_frames_credit_explained(seed):
    from brpc_tpu.rpc import stream as stream_mod
    N, MSG = 8, 512
    StreamSink.received = []
    StreamSink.got_all = threading.Event()
    StreamSink.want = N
    srv = brpc.Server()
    srv.add_service(StreamSink())
    srv.start("127.0.0.1", 0)
    try:
        ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=5000)
        cntl = brpc.Controller()
        stream = brpc.stream_create(cntl, None,
                                    max_buf_size=StreamSink.WINDOW)
        assert ch.call_sync("ChaosStream", "Open", {}, serializer="json",
                            cntl=cntl) == {"ok": True}
        drops0 = stream_mod.reorder_replays_dropped.get_value()
        bytes0 = stream_mod.reorder_replay_bytes_dropped.get_value()
        # every DATA frame to the SERVER's stream is delivered twice
        # (injected transport-level redelivery); the reorder layer must
        # drop each duplicate.  Scoped to this stream so concurrent
        # in-process streams can't consume the schedule.
        sink_id = stream.remote_id
        plan = fault.FaultPlan(seed).on(
            "stream.frame", fault.DUP, times=-1,
            match=lambda ctx: (ctx.get("msg_type") == M.MSG_STREAM_DATA
                               and ctx.get("stream_seq", 0) != 0
                               and ctx.get("stream_id") == sink_id))
        with fault.injected(plan):
            for i in range(N):
                stream.write(bytes([i]) * MSG, timeout_s=10)
            assert StreamSink.got_all.wait(10), \
                f"only {len(StreamSink.received)}/{N} delivered"
            # exactly-once, in-order delivery despite duplicates
            assert StreamSink.received == [bytes([i]) * MSG
                                           for i in range(N)]
            # the last frame's duplicate may still be in flight when the
            # handler fires got_all — wait for the full drop count
            assert wait_until(
                lambda: stream_mod.reorder_replays_dropped.get_value()
                - drops0 == N, 10), "duplicates not all dropped"
            dup_drops = stream_mod.reorder_replays_dropped.get_value() \
                - drops0
            dup_bytes = stream_mod.reorder_replay_bytes_dropped.get_value() \
                - bytes0
            assert dup_drops == N
            # the credit ledger: every byte of shortfall is explained by
            # the replay counter (ADVICE r5 — never a silent wedge)
            assert dup_bytes == dup_drops * MSG
            # delivered credit is acked back: the writer drains to zero
            # outstanding (window 1024, msg 512 -> feedback every msg)
            assert wait_until(
                lambda: stream._produced - stream._remote_consumed == 0,
                10), ("writer credit never returned: "
                      f"{stream._produced - stream._remote_consumed}B "
                      f"outstanding, {dup_bytes}B explained by replays")
        stream.close()
    finally:
        srv.stop()
        srv.join()


# ---------------------------------------------------------------------------
# scenario 8: lost CONSUMED feedback — credit return is delayed, not leaked
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_feedback_loss_heals_via_cumulative_offsets(seed):
    """Feedback offsets are CUMULATIVE: one lost CONSUMED frame delays
    credit return until the next crossing, it never leaks it.  The
    writer's window is sized above the total payload so it can always
    produce the traffic that forces that next crossing (a writer wedged
    at a full window can't — which is exactly why feedback rides the
    reliable socket in production)."""
    N, MSG = 6, 512
    StreamSink.received = []
    StreamSink.got_all = threading.Event()
    StreamSink.want = 2 * N
    srv = brpc.Server()
    srv.add_service(StreamSink())        # server recv window: 1024
    srv.start("127.0.0.1", 0)
    try:
        ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=5000)
        cntl = brpc.Controller()
        stream = brpc.stream_create(cntl, None, max_buf_size=8192)
        assert ch.call_sync("ChaosStream", "Open", {}, serializer="json",
                            cntl=cntl) == {"ok": True}
        sink_id = stream.remote_id
        # the FIRST feedback frame from the server's stream is lost
        # (scoped to this stream — see scenario 7)
        plan = fault.FaultPlan(seed).on(
            "stream.feedback", fault.DROP, times=1,
            match=lambda ctx: ctx.get("stream_id") == sink_id)
        with fault.injected(plan):
            # phase 1 guarantees at least one feedback crossing (3072B
            # consumed vs a 512B threshold) — the drop lands here
            for i in range(N):
                stream.write(bytes([i]) * MSG, timeout_s=10)
            assert wait_until(lambda: len(StreamSink.received) >= N, 10), \
                f"only {len(StreamSink.received)}/{N} delivered"
            assert plan.injected["stream.feedback"] == 1
            # phase 2 forces the NEXT crossing; its cumulative offset
            # must return phase 1's lost credit too
            for i in range(N):
                stream.write(bytes([N + i]) * MSG, timeout_s=10)
            assert StreamSink.got_all.wait(10), \
                f"only {len(StreamSink.received)}/{2 * N} delivered"
            assert wait_until(
                lambda: stream._produced - stream._remote_consumed == 0,
                10), "credit lost with the dropped feedback frame never " \
                     "returned (cumulative offsets should heal it)"
        stream.close()
    finally:
        srv.stop()
        srv.join()


# ---------------------------------------------------------------------------
# health-check revival under faults (satellite): CB hold + generation bump
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# scenario 10: paged KV cache — pool exhaustion + eviction failure mid-decode
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_kvcache_exhaustion_mid_decode_exactly_once_and_baseline(seed):
    """Injected KV faults uphold the paged-cache invariants (ISSUE 3):

    * `kvcache.page_alloc` exhausts the page pool mid-decode and
      `kvcache.evict` kills one pressure-relief attempt -> the affected
      requests complete exactly once with a definite error (ELIMIT),
      the untouched ones stream their full token sequences;
    * no shared page is freed while a forked sequence still references
      it — the fork's contents survive the chaos run bit-exact;
    * refcounts and BLOCK-POOL occupancy return to baseline once the
      sequences retire and the radix cache is dropped.
    """
    import jax

    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.serving import DecodeEngine

    store = KVCacheStore(page_bytes=256, page_tokens=4, max_blocks=16,
                         name=f"chaos_kv{seed}")
    device_pool = store.pagepool.pool

    def occupancy():
        with device_pool._lock:
            return {c: len(device_pool._free[c])
                    for c in device_pool._free}

    free0 = occupancy()

    @jax.jit
    def step(tokens, positions, pages):
        return tokens + 1

    engine = DecodeEngine(step, num_slots=3, store=store,
                          max_pages_per_slot=16,
                          name=f"chaos_kve{seed}")
    try:
        # a forked pair held LIVE across the whole chaos run: its shared
        # pages must never be reclaimed out from under it
        held = store.admit([1, 2, 3, 4, 5, 6])
        forked = store.fork(held)
        store.extend(held, 70)       # COW: tails diverge pre-chaos
        store.extend(forked, 80)
        held_words = store.pagepool.read(held.pages[-1], 3).tolist()
        fork_words = store.pagepool.read(forked.pages[-1], 3).tolist()

        plan = fault.FaultPlan(seed)
        plan.on("kvcache.page_alloc", fault.EXHAUST, times=2, after=6)
        plan.on("kvcache.evict", fault.ERROR, times=1)
        shared = list(range(100, 108))
        with fault.injected(plan):
            n = 12
            outcomes = []
            mu = threading.Lock()
            events = []
            for i in range(n):
                done = threading.Event()
                events.append(done)
                prompt = shared + [300 + i]

                def on_done(err, d=done):
                    with mu:
                        outcomes.append(0 if err is None else err.code)
                    d.set()

                engine.submit(prompt, 4, lambda t: None, on_done)
            for done in events:
                assert done.wait(30), "kvcache chaos request hung"
            # exactly once each: every request has ONE definite outcome
            assert len(outcomes) == n, f"{n - len(outcomes)} calls hung"
            assert plan.injected["kvcache.page_alloc"] == 2
            nerr = sum(1 for c in outcomes if c != 0)
            assert nerr >= 1, "injected exhaustion reached no request"
            assert all(c in (0, errors.ELIMIT) for c in outcomes), outcomes
        # the forked pair's shared prefix and diverged tails are intact:
        # eviction under pressure never touched referenced pages
        assert store.pagepool.read(held.pages[0]).tolist() == [1, 2, 3, 4]
        assert store.pagepool.read(held.pages[-1], 3).tolist() == held_words
        assert store.pagepool.read(forked.pages[-1], 3).tolist() == \
            fork_words
        store.pagepool.assert_consistent()
        # post-chaos the engine still serves
        assert engine.join_idle(10)
        done = threading.Event()
        toks = []
        engine.submit([7, 8, 9], 2, toks.append, lambda err: done.set())
        assert done.wait(20) and len(toks) == 2
        assert engine.join_idle(10)
        # baseline: retire everything, drop the cache -> refcounts zero
        # and every HBM block back in the device pool
        store.retire(held, cache=False)
        store.retire(forked, cache=False)
        assert store.stats()["live_seqs"] == 0
        store.clear()
        store.pagepool.assert_consistent()
        assert store.pagepool.blocks_leased() == 0
        assert wait_until(lambda: occupancy() == free0, 10), \
            f"KV blocks leaked: {occupancy()} != {free0}"
    finally:
        engine.close()
        store.close()


# ---------------------------------------------------------------------------
# scenario 11: engine crash mid-decode -> supervised failover over the
# surviving KV cache (ISSUE 4)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_engine_crash_midstream_failover_exactly_once(seed):
    """Injected `serving.step` crash mid-decode under an
    EngineSupervisor upholds the recovery invariants (ISSUE 4):

    * every in-flight generation completes with an exactly-once,
      BIT-EXACT token stream — no duplicated and no dropped token at
      the restart seam (the emitted-token cursor + resume from the
      last emitted token);
    * recovery re-decodes STRICTLY fewer tokens than a from-scratch
      replay whenever committed prefix pages existed: the detached
      sequences' full pages are committed to the radix tree, so
      re-admission prefix-hits and only the uncommitted tail
      re-prefills (re-decoded-token ratio < 1.0);
    * refcounts and BLOCK-POOL occupancy return to baseline once the
      wave retires and the cache is dropped — recovery pins are
      released, nothing leaks across the engine generations.
    """
    import gc

    import jax

    from brpc_tpu import native_path
    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.serving import DecodeEngine, EngineSupervisor

    store = KVCacheStore(page_bytes=256, page_tokens=4, max_blocks=32,
                         name=f"sup_chaos_kv{seed}")
    device_pool = store.pagepool.pool

    def occupancy():
        with device_pool._lock:
            return {c: len(device_pool._free[c])
                    for c in device_pool._free}

    free0 = occupancy()
    gc.collect()
    ring0 = native_path.tokring_live()

    @jax.jit
    def step(tokens, positions, pages):
        # position-dependent: the resumed stream is bit-exact ONLY if
        # recovery restores the exact (last token, position) cursor
        return (tokens * 7 + positions) % 997

    def expected(prompt, n):
        last, pos, out = prompt[-1], len(prompt), []
        for _ in range(n):
            last = (last * 7 + pos) % 997
            out.append(last)
            pos += 1
        return out

    calm = ({"queue_delay_us": float("inf"), "pool_ratio": 9.9,
             "queue_depth": 1e9},) * 3
    sup = EngineSupervisor(
        lambda: DecodeEngine(step, num_slots=3, store=store,
                             max_pages_per_slot=32,
                             name=f"sup_chaos_e{seed}"),
        store=store, heartbeat_deadline_s=5.0, check_interval_s=0.02,
        ladder=calm, name=f"sup_chaos{seed}")
    try:
        # warm the jit cache; commit the shared prefix by retiring one
        # clean completion into the radix tree
        shared = list(range(700, 708))           # two full pages
        done = threading.Event()
        sup.submit(shared + [1], 2, lambda t: None, lambda e: done.set())
        assert done.wait(30)
        assert sup.join_idle(10)
        h0 = store.hit_tokens.get_value()
        p0 = store.prompt_tokens.get_value()

        plan = fault.FaultPlan(seed)
        plan.on("serving.step", fault.ERROR, times=1, after=2)
        n = 9
        sinks = []
        with fault.injected(plan):
            for i in range(n):
                ev = threading.Event()
                toks: list = []
                errs: list = []
                sinks.append((ev, toks, errs))
                sup.submit(shared + [800 + i], 6, toks.append,
                           lambda e, ev=ev, errs=errs: (errs.append(e),
                                                        ev.set()))
            for ev, _, _ in sinks:
                assert ev.wait(60), "generation hung across the restart"
        assert plan.injected["serving.step"] == 1
        st = sup.stats()
        assert st["restarts"] == 1
        assert st["last_recovery"]["stolen_slots"] >= 1
        assert st["last_recovery"]["pinned_seqs"] >= 1, \
            "no committed prefix pages pinned at takeover"
        # exactly-once + bit-exact across the seam, for every request
        for i, (ev, toks, errs) in enumerate(sinks):
            assert errs == [None], f"req {i}: {errs}"
            assert toks == expected(shared + [800 + i], 6), \
                f"req {i}: stream diverged at the restart seam"
        # re-decoded-token ratio < 1.0: a from-scratch replay would
        # prefill every prompt token of every (re-)admission; the
        # committed prefix pages made some of that compute a cache hit
        dp = store.prompt_tokens.get_value() - p0
        dh = store.hit_tokens.get_value() - h0
        assert dp > 0
        ratio = (dp - dh) / dp
        assert ratio < 1.0, \
            "recovery re-decoded as much as a from-scratch replay"
        # baseline: pins released, sequences retired, cache dropped ->
        # refcounts consistent and every HBM block back in the pool
        assert sup.join_idle(10)
        assert store.stats()["live_seqs"] == 0
        store.clear()
        store.pagepool.assert_consistent()
        assert store.pagepool.blocks_leased() == 0
        assert wait_until(lambda: occupancy() == free0, 10), \
            f"KV blocks leaked across restart: {occupancy()} != {free0}"
    finally:
        sup.close()
        store.close()
    # ISSUE 9: the restart seam must not strand native emit rings —
    # every re-admitted request's old ring is freed with its request
    assert wait_until(
        lambda: (gc.collect(), native_path.tokring_live())[1] <= ring0,
        10), "native emit rings leaked across the engine restart"


# ---------------------------------------------------------------------------
# scenario 11b (ISSUE 10): engine crash mid-step with the REAL model
# runner -> recovery resumes bit-exact over real paged attention state
# ---------------------------------------------------------------------------

_mr_chaos_cache: dict = {}


def _mr_chaos_model():
    """One shared (cfg, params, dense-oracle cache) across the three
    seeds — the module-level jit cache in models/runner.py makes every
    seed after the first compile-free."""
    if not _mr_chaos_cache:
        from brpc_tpu.models.runner import (TransformerConfig,
                                            init_runner_params)
        cfg = TransformerConfig()
        _mr_chaos_cache["cfg"] = cfg
        _mr_chaos_cache["params"] = init_runner_params(cfg)
        _mr_chaos_cache["oracle"] = {}
    return _mr_chaos_cache


def _mr_expected(prompt, n) -> list:
    """Dense cache-less oracle for one prompt (memoized: the same
    prompts recur across seeds)."""
    from brpc_tpu.models.runner import dense_generate
    m = _mr_chaos_model()
    key = (tuple(prompt), n)
    if key not in m["oracle"]:
        m["oracle"][key] = dense_generate(m["params"], m["cfg"],
                                          prompt, n)
    return m["oracle"][key]


@pytest.mark.parametrize("seed", SEEDS)
def test_engine_crash_with_real_runner_resumes_bit_exact(seed):
    """The scenario 11 invariants upgraded from the token harness to
    the REAL TransformerRunner, with the crash injected INSIDE the
    model (`model.step_compute`, the ISSUE 10 fault site):

    * every stream completes exactly-once and matches the cache-less
      dense oracle token for token — recovery resumed from the emitted
      cursor over real paged K/V, re-prefilling only what the detached
      radix commit didn't cover;
    * the re-decode was cheaper than a from-scratch replay
      (hit-token delta > 0 across the restart);
    * page-pool refcounts and HBM block occupancy return to baseline.
    """
    import gc

    from brpc_tpu import native_path
    from brpc_tpu.models.runner import (TransformerRunner,
                                        make_store_for)
    from brpc_tpu.serving import DecodeEngine, EngineSupervisor

    m = _mr_chaos_model()
    cfg, params = m["cfg"], m["params"]
    store = make_store_for(cfg, page_tokens=4, max_blocks=32,
                           name=f"mr_chaos_kv{seed}")
    device_pool = store.pagepool.pool

    def occupancy():
        with device_pool._lock:
            return {c: len(device_pool._free[c])
                    for c in device_pool._free}

    free0 = occupancy()
    gc.collect()
    ring0 = native_path.tokring_live()
    runner = TransformerRunner(params, cfg, store=store,
                               name=f"mr_chaos_m{seed}")
    calm = ({"queue_delay_us": float("inf"), "pool_ratio": 9.9,
             "queue_depth": 1e9},) * 3
    sup = EngineSupervisor(
        lambda: DecodeEngine(runner=runner, num_slots=2, store=store,
                             max_pages_per_slot=24,
                             prefill_buckets=(8, 16),
                             name=f"mr_chaos_e{seed}"),
        store=store, heartbeat_deadline_s=10.0, check_interval_s=0.02,
        ladder=calm, name=f"mr_chaos{seed}")
    try:
        # jit warm + commit a shared 2-page prefix into the radix tree
        shared = [50, 61, 12, 73, 24, 85, 36, 97]
        done = threading.Event()
        sup.submit(shared + [1], 2, lambda t: None,
                   lambda e: done.set())
        assert done.wait(120)
        assert sup.join_idle(30)
        h0 = store.hit_tokens.get_value()
        p0 = store.prompt_tokens.get_value()

        plan = fault.FaultPlan(seed)
        plan.on("model.step_compute", fault.ERROR, times=1, after=2)
        prompts = [shared + [100 + i] for i in range(4)]
        sinks = []
        with fault.injected(plan):
            for p in prompts:
                ev = threading.Event()
                toks: list = []
                errs: list = []
                sinks.append((ev, toks, errs))
                sup.submit(p, 5, toks.append,
                           lambda e, ev=ev, errs=errs: (errs.append(e),
                                                        ev.set()))
            for ev, _, _ in sinks:
                assert ev.wait(180), \
                    "generation hung across the restart"
        assert plan.injected["model.step_compute"] == 1
        st = sup.stats()
        assert st["restarts"] == 1
        assert st["last_recovery"]["stolen_slots"] >= 1
        # exactly-once + bit-exact vs the DENSE oracle: the resumed
        # stream rode real paged K/V across detach/re-admit/prefill
        for p, (ev, toks, errs) in zip(prompts, sinks):
            assert errs == [None], f"{p[-1]}: {errs}"
            assert toks == _mr_expected(p, 5), \
                f"req {p[-1]}: real-runner stream diverged at the seam"
        # cheaper than a from-scratch replay: some prompt tokens were
        # served by committed pages (shared prefix and/or recovery)
        dp = store.prompt_tokens.get_value() - p0
        dh = store.hit_tokens.get_value() - h0
        assert dp > 0 and (dp - dh) / dp < 1.0, \
            "recovery re-decoded as much as a from-scratch replay"
        assert sup.join_idle(30)
        assert store.stats()["live_seqs"] == 0
        store.clear()
        store.pagepool.assert_consistent()
        assert store.pagepool.blocks_leased() == 0
        assert wait_until(lambda: occupancy() == free0, 10), \
            f"KV blocks leaked: {occupancy()} != {free0}"
    finally:
        sup.close()
        store.close()
    assert wait_until(
        lambda: (gc.collect(), native_path.tokring_live())[1] <= ring0,
        10), "native emit rings leaked across the real-runner restart"


# ---------------------------------------------------------------------------
# scenario 12: engine crash mid-decode -> ONE generation trace linking
# pre- and post-crash spans (ISSUE 5, same seeds as scenario 11)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_engine_crash_yields_one_linked_trace(seed):
    """rpcz generation tracing upholds trace CONTINUITY across crash
    recovery: an injected `serving.step` crash mid-decode yields, for
    each interrupted generation, ONE trace whose post-crash attempt
    span carries the SAME trace_id, links its predecessor via
    ``recovered_from``, and annotates the resume cursor and the
    re-decoded-token count — the timeline a person debugging "why was
    this generation slow" actually needs."""
    import jax

    from brpc_tpu import rpcz
    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.serving import DecodeEngine, EngineSupervisor

    store = KVCacheStore(page_bytes=256, page_tokens=4, max_blocks=32,
                         name=f"tr_chaos_kv{seed}")

    @jax.jit
    def step(tokens, positions, pages):
        return (tokens * 7 + positions) % 997

    calm = ({"queue_delay_us": float("inf"), "pool_ratio": 9.9,
             "queue_depth": 1e9},) * 3
    sup = EngineSupervisor(
        lambda: DecodeEngine(step, num_slots=3, store=store,
                             max_pages_per_slot=32,
                             name=f"tr_chaos_e{seed}"),
        store=store, heartbeat_deadline_s=5.0, check_interval_s=0.02,
        ladder=calm, name=f"tr_chaos{seed}")
    rpcz.set_enabled(True)
    try:
        shared = list(range(300, 308))
        done = threading.Event()
        sup.submit(shared + [1], 2, lambda t: None, lambda e: done.set())
        assert done.wait(30)
        assert sup.join_idle(10)

        plan = fault.FaultPlan(seed)
        plan.on("serving.step", fault.ERROR, times=1, after=2)
        sinks = []
        with fault.injected(plan):
            for i in range(6):
                ev = threading.Event()
                errs: list = []
                sinks.append((ev, errs))
                sup.submit(shared + [400 + i], 6, lambda t: None,
                           lambda e, ev=ev, errs=errs: (errs.append(e),
                                                        ev.set()))
            for ev, _ in sinks:
                assert ev.wait(60), "generation hung across the restart"
        assert plan.injected["serving.step"] == 1
        for ev, errs in sinks:
            assert errs == [None]
        assert sup.stats()["restarts"] == 1

        # every interrupted generation produced one recovered_from-
        # linked trace: >= 1 such trace exists, each holding BOTH
        # attempt spans under one trace_id plus both decode spans
        spans = rpcz.recent_spans(limit=2048)
        gens: dict = {}
        for s in spans:
            if s.kind == "generation" and s.method == f"tr_chaos{seed}":
                gens.setdefault(s.trace_id, []).append(s)
        linked = []
        for tid, group in gens.items():
            if len(group) < 2:
                continue
            group.sort(key=lambda s: s.span_id)
            if group[1].recovered_from == group[0].span_id:
                linked.append((tid, group))
        assert linked, \
            f"seed {seed}: no trace links pre- and post-crash attempts"
        full_seam = 0
        for tid, group in linked:
            notes = " | ".join(m for _, m in group[1].annotations)
            assert "recovered_from=span" in notes
            assert "resume_cursor=" in notes
            assert "re_decoded_tokens=" in notes, \
                f"seed {seed}: re-decoded tokens not annotated: {notes}"
            trace = [s for s in spans if s.trace_id == tid]
            decode_spans = [s for s in trace if s.kind == "decode"]
            # a generation that was IN a slot at crash time shows both
            # decode attempts: the pre-crash span closed ELOGOFF at
            # takeover plus the post-crash one; a generation still
            # QUEUED at the crash legitimately has only the second
            if len(decode_spans) >= 2 and any(
                    s.error_code == errors.ELOGOFF for s in decode_spans):
                full_seam += 1
        assert full_seam >= 1, \
            f"seed {seed}: no trace shows the full pre-crash/post-crash " \
            f"decode seam (stolen slots: " \
            f"{sup.stats()['last_recovery']['stolen_slots']})"
    finally:
        rpcz.set_enabled(False)
        sup.close()
        store.clear()
        store.close()


class TestHealthCheckRevival:
    def test_probe_respects_isolation_hold_while_reachable(self, server):
        """The circuit breaker's isolation hold (_hold_until) must be
        respected even when the endpoint is ALREADY reachable — the
        probe may connect, but revival waits out the hold."""
        from brpc_tpu.policy import health_check as hc
        ep = str2endpoint(f"127.0.0.1:{server.port}")
        t0 = time.monotonic()
        hc.mark_broken(ep, hold_s=0.6)
        assert hc.is_broken(ep)
        time.sleep(0.3)
        assert hc.is_broken(ep), "revived inside the CB isolation hold"
        assert wait_until(lambda: not hc.is_broken(ep), 10), \
            "reachable endpoint never revived after the hold elapsed"
        assert time.monotonic() - t0 >= 0.6

    def test_reset_all_generation_stands_down_probes(self):
        """A probe loop started before reset_all() must exit WITHOUT
        reviving the endpoint into the deliberately-cleared state, even
        if the endpoint becomes reachable afterwards."""
        from brpc_tpu.policy import health_check as hc
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        ep = str2endpoint(f"127.0.0.1:{port}")
        revived0 = hc._revived_counter.get_value()
        hc.mark_broken(ep)          # unreachable: probe loop spins
        assert hc.is_broken(ep)
        hc.reset_all()              # generation bump clears everything
        assert not hc.is_broken(ep)
        # NOW the endpoint comes up: the old-generation probe connects,
        # sees the bump, and stands down without touching state
        lst = socket.socket()
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind(("127.0.0.1", port))
        lst.listen(8)
        try:
            assert wait_until(lambda: ep not in hc._probe_threads, 10), \
                "stale-generation probe thread never stood down"
            assert hc._revived_counter.get_value() == revived0, \
                "stale-generation probe fired a revival"
            assert not hc.is_broken(ep)
        finally:
            lst.close()


# ---------------------------------------------------------------------------
# the fault layer itself: determinism + disabled-by-default
# ---------------------------------------------------------------------------

class TestFaultLayer:
    def test_disabled_by_default_and_noop(self):
        assert fault.ENABLED is False
        assert fault.hit("transport.send") is None

    def test_seeded_schedule_replays_exactly(self):
        def run(seed):
            plan = fault.FaultPlan(seed)
            plan.on("chaos.unit", fault.DROP, times=-1, prob=0.3)
            with fault.injected(plan):
                return [fault.hit("chaos.unit") is not None
                        for _ in range(64)]
        assert run(7) == run(7), "same seed must replay the same schedule"
        assert run(7) != run(8), "different seeds must differ"

    def test_after_and_times_fire_by_hit_index(self):
        plan = fault.FaultPlan(0)
        plan.on("chaos.idx", fault.ERROR, times=2, after=3)
        with fault.injected(plan):
            fired = [fault.hit("chaos.idx") is not None for _ in range(8)]
        assert fired == [False, False, False, True, True,
                         False, False, False]

    def test_match_scopes_rules(self):
        plan = fault.FaultPlan(0)
        plan.on("chaos.match", fault.ERROR, times=1,
                match=lambda ctx: ctx.get("who") == "target")
        with fault.injected(plan):
            assert fault.hit("chaos.match", who="bystander") is None
            assert fault.hit("chaos.match", who="target") is not None
            assert fault.hit("chaos.match", who="target") is None

    def test_injected_counts_reach_bvar(self):
        before = fault.injected_counts().get("chaos.bvar", 0)
        plan = fault.FaultPlan(0).on("chaos.bvar", fault.DROP, times=2)
        with fault.injected(plan):
            fault.hit("chaos.bvar")
            fault.hit("chaos.bvar")
        assert fault.injected_counts()["chaos.bvar"] == before + 2


# ---------------------------------------------------------------------------
# ADVICE r5 regressions
# ---------------------------------------------------------------------------

class TestAdviceRegressions:
    def test_recordio_crc_fail_short_tail_returns_none(self):
        """A damaged record at EOF followed by a sub-magic-sized tail
        must end the stream (return None) — NOT rescan its own payload
        and fabricate a record from embedded MAGIC bytes."""
        from brpc_tpu.butil.recordio import RecordReader, RecordWriter
        buf = io.BytesIO()
        w = RecordWriter(buf)
        w.write(b"first-record")
        rec1_len = buf.tell()
        # second record's body EMBEDS a complete valid record — the
        # fabrication bait (rpc_dump bodies are raw network bytes)
        inner = io.BytesIO()
        RecordWriter(inner).write(b"FAKE")
        w.write(b"xx" + inner.getvalue() + b"yy")
        data = bytearray(buf.getvalue())
        # corrupt one body byte OUTSIDE the embedded record: crc fails,
        # lengths stay intact
        data[rec1_len + 20] ^= 0xFF          # the leading 'x'
        data += b"Zq"                        # short (<4B) damaged tail
        r = RecordReader(io.BytesIO(bytes(data)))
        assert r.read() == (b"", b"first-record")
        assert r.read() is None, \
            "fabricated a record from bytes inside a damaged tail record"

    def test_recordio_crc_fail_aligned_next_record_still_skips(self):
        """Counter-case: when the next bytes ARE a magic, the damaged
        record is skipped in place and the next record survives."""
        from brpc_tpu.butil.recordio import RecordReader, RecordWriter
        buf = io.BytesIO()
        w = RecordWriter(buf)
        w.write(b"victim")
        next_off = buf.tell()
        w.write(b"survivor")
        data = bytearray(buf.getvalue())
        data[next_off - 1] ^= 0xFF           # corrupt victim's body tail
        r = RecordReader(io.BytesIO(bytes(data)))
        assert r.read() == (b"", b"survivor")
        assert r.read() is None

    def test_h2_respond_error_claims_stream_atomically(self):
        """Only ONE responder may emit trailers HEADERS on a stream: the
        claim happens under _fc, so a backlog shed and a finishing
        handler can never both respond (ADVICE r5)."""
        from brpc_tpu.rpc.h2 import GrpcServerConnection

        class _RecordingTp:
            def __init__(self):
                self.writes = []

            def write_raw(self, sid, data):
                self.writes.append(bytes(data))
                return 0

            def close(self, sid, err=0):
                pass

            def alive(self, sid):
                return True

        conn = GrpcServerConnection(sock_id=(1 << 62), server=None)
        tp = _RecordingTp()
        conn._tp = tp
        conn.open_stream(1)
        conn._respond_error(1, 13, "boom")
        assert len(tp.writes) == 1, "error trailers not sent"
        conn._respond_error(1, 13, "again")
        assert len(tp.writes) == 1, "duplicate trailers HEADERS emitted"
        # handler wins the claim first: a late shed stays silent
        conn.open_stream(3)
        assert conn.claim_responder(3) is True
        conn._respond_error(3, 13, "late shed")
        assert len(tp.writes) == 1
        assert conn.claim_responder(3) is False
        # closed streams are unclaimable
        conn.close_stream(3)
        assert conn.claim_responder(3) is False

    def test_stream_duplicate_data_bytes_counted(self):
        """Dropped replayed DATA frames consume sender credit forever;
        the byte counter must account for them (ADVICE r5)."""
        from brpc_tpu.rpc import stream as sm
        got = []
        s = sm.Stream(999_999_001, sm._FnHandler(
            lambda st, m: got.append(m)))
        c0 = sm.reorder_replays_dropped.get_value()
        b0 = sm.reorder_replay_bytes_dropped.get_value()
        s._on_data(b"abc", 3, 1)
        s._on_data(b"abc", 3, 1)         # transport replay
        assert got == [b"abc"], "duplicate delivered to the handler"
        assert sm.reorder_replays_dropped.get_value() == c0 + 1
        assert sm.reorder_replay_bytes_dropped.get_value() == b0 + 3

    def test_bench_wedge_deadline_is_per_batch(self):
        """The mid-batch wedge check must measure from the CURRENT
        batch's start, not the whole timed region's (ADVICE r5)."""
        import bench
        region_t0, now = 0.0, 200.0      # region older than the deadline
        batch_t0 = 195.0                 # current batch is 5s old
        assert not bench._batch_wedged(batch_t0, now), \
            "healthy late batch misflagged as wedged"
        assert bench._batch_wedged(
            batch_t0, batch_t0 + bench.WEDGE_TIMEOUT_S + 1)
        # the old bug, kept as documentation: region-relative time flags
        assert bench._batch_wedged(region_t0, now)


# ---------------------------------------------------------------------------
# scenario 9: serving layer — mid-batch failure + KV slot lease failure
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_serving_midbatch_fault_exactly_once_and_kv_baseline(seed):
    """Injected serving faults uphold the serving layer's invariants:

    * `serving.batch` fires mid-batch -> EVERY member call of that batch
      completes exactly once with a definite error (never neither, never
      a partial scatter), calls in other batches succeed, and the
      batcher's queue accounting returns to baseline;
    * `serving.slot_alloc` fails one KV lease -> that request gets a
      definite error, the step loop keeps serving the others, and
      block-pool occupancy returns to baseline (no leaked KV blocks).
    """
    import jax
    import numpy as np

    from brpc_tpu.serving import DecodeEngine, DynamicBatcher, \
        register_serving

    traces = []

    def _fn(x):
        traces.append(tuple(x.shape))
        return x.sum(axis=1)

    batcher = DynamicBatcher(
        jax.jit(_fn), max_batch_size=4, max_delay_us=30_000,
        length_buckets=(16,), name=f"chaos_b{seed}")

    @jax.jit
    def step(tokens, positions):
        return tokens + 1

    from brpc_tpu.ici.block_pool import get_block_pool
    pool = get_block_pool(jax.devices()[0])

    def occupancy():
        with pool._lock:
            return {c: len(pool._free[c]) for c in pool._free}

    free0 = occupancy()
    import gc

    from brpc_tpu import native_path
    gc.collect()
    ring0 = native_path.tokring_live()
    engine = DecodeEngine(step, num_slots=2, kv_bytes_per_slot=1024,
                          pool=pool, name=f"chaos_e{seed}")
    s = brpc.Server()
    register_serving(s, batcher=batcher, engine=engine,
                     http_generate_path=None)
    s.start("127.0.0.1", 0)
    # max_retry=0: the injected batch failure must surface as the
    # definite error it is, not be papered over by a client retry
    ch = brpc.Channel(f"127.0.0.1:{s.port}", timeout_ms=10_000,
                      max_retry=0)
    try:
        plan = fault.FaultPlan(seed)
        plan.on("serving.batch", fault.ERROR, times=1)
        plan.on("serving.slot_alloc", fault.ERROR, times=1)
        with fault.injected(plan):
            # ---- phase 1: mid-batch failure over real RPC ----
            n = 12
            outcomes = []
            mu = threading.Lock()

            def one():
                try:
                    r = ch.call_sync("Serving", "Score", {"x": [1.0, 2.0]},
                                     serializer="json")
                    code = 0
                    assert r["y"] == 3.0
                except errors.RpcError as e:
                    code = e.code
                with mu:
                    outcomes.append(code)

            ts = [threading.Thread(target=one) for _ in range(n)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            # exactly once each: every call has ONE definite outcome
            assert len(outcomes) == n, f"{n - len(outcomes)} calls hung"
            assert plan.injected["serving.batch"] == 1
            nerr = sum(1 for c in outcomes if c != 0)
            assert nerr >= 1, "injected batch failure reached no caller"
            assert all(c in (0, errors.EINTERNAL) for c in outcomes)
            st = batcher.stats()
            assert st["queued"] == 0
            assert st["completed"] + st["errors"] == n

            # ---- phase 2: KV slot lease failure mid-admission ----
            sinks = []
            for i in range(4):
                done = threading.Event()
                toks = []
                errbox = []
                sinks.append((done, toks, errbox))
                engine.submit(
                    [i * 10], 3, toks.append,
                    lambda err, d=done, eb=errbox: (eb.append(err),
                                                    d.set()))
            for done, _, _ in sinks:
                assert done.wait(30), "engine request hung"
            assert plan.injected["serving.slot_alloc"] == 1
            errs = [eb[0] for _, _, eb in sinks]
            failed = [e for e in errs if e is not None]
            assert len(failed) == 1 and failed[0].code == errors.ELIMIT
            for (_, toks, eb), i in zip(sinks, range(4)):
                if eb[0] is None:
                    assert toks == [i * 10 + 1, i * 10 + 2, i * 10 + 3]
        # post-chaos: occupancy back to baseline, engine still serves
        assert engine.join_idle(10)
        assert wait_until(lambda: occupancy() == free0, 10), \
            f"KV blocks leaked: {occupancy()} != {free0}"
        done = threading.Event()
        toks = []
        engine.submit([7], 2, toks.append, lambda err: done.set())
        assert done.wait(20) and toks == [8, 9]
    finally:
        s.stop()
        s.join()
        batcher.close()
        engine.close()
        assert wait_until(lambda: occupancy() == free0, 10)
        # ISSUE 9: zero leaked native emit rings across the wave
        assert wait_until(
            lambda: (gc.collect(), native_path.tokring_live())[1]
            <= ring0, 10), "native emit rings leaked"


# ---------------------------------------------------------------------------
# scenario 13: cross-host KV migration faults + prefill-process death ->
# standby failover (ISSUE 7)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_migration_faults_exactly_once_with_recompute_fallback(seed):
    """Injected faults at every migration site mid-disagg uphold the
    data-plane invariants (ISSUE 7):

    * `dcn.migrate_send` / `dcn.migrate_recv` / `migrate.splice` fire
      mid-migration -> the SOURCE's pinned pages are released (refcounts
      and occupancy to baseline), the DESTINATION either fully splices
      or fully rolls back (its radix tree never serves a half-imported
      chain), and every generation completes exactly once, bit-exact,
      via the recompute fallback;
    * after the chaos window, migration works again and both pools
      return to block baseline once caches drop.
    """
    import jax

    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.migrate import (DisaggCoordinator,
                                  register_disagg_decode,
                                  register_disagg_prefill)
    from brpc_tpu.serving import DecodeEngine

    PT = 4

    @jax.jit
    def step(tokens, positions, pages):
        return (tokens * 7 + positions) % 997

    def expected(prompt, n):
        last, pos, out = prompt[-1], len(prompt), []
        for _ in range(n):
            last = (last * 7 + pos) % 997
            out.append(last)
            pos += 1
        return out

    dstore = KVCacheStore(page_tokens=PT, page_bytes=256, max_blocks=32,
                          name=f"mig_chaos_dec{seed}")
    device_pool = dstore.pagepool.pool

    def occupancy():
        with device_pool._lock:
            return {c: len(device_pool._free[c])
                    for c in device_pool._free}

    free0 = occupancy()
    eng = DecodeEngine(step, num_slots=4, store=dstore,
                       max_pages_per_slot=32,
                       name=f"mig_chaos_eng{seed}")
    dsrv = brpc.Server(enable_dcn=True)
    register_disagg_decode(dsrv, dstore, eng)
    dsrv.start("127.0.0.1", 0)
    pstore = KVCacheStore(page_tokens=PT, page_bytes=256, max_blocks=32,
                          name=f"mig_chaos_pre{seed}")
    psrv = brpc.Server(enable_dcn=True)
    replica = register_disagg_prefill(psrv, pstore,
                                      f"127.0.0.1:{dsrv.port}")
    psrv.start("127.0.0.1", 0)
    try:
        co = DisaggCoordinator(f"127.0.0.1:{psrv.port}",
                               f"127.0.0.1:{dsrv.port}")
        # warm the jit cache outside the fault window
        warm = [9_000_000 + seed, 1, 2]
        out = co.generate(warm, 1)
        assert out["error"] is None

        n = 8
        prompts = [[seed * 100 + 1000 * g + j for j in range(13)]
                   for g in range(n)]
        # one fault per site, staggered by seeded offsets so different
        # migrations (and different phases) take the hit each seed
        plan = fault.FaultPlan(seed)
        plan.on("dcn.migrate_send", fault.ERROR, times=1,
                after=seed % 3)
        plan.on("dcn.migrate_recv", fault.ERROR, times=2,
                after=(seed // 3) % 3)
        plan.on("migrate.splice", fault.ERROR, times=2,
                after=1 + seed % 2)
        fallbacks = 0
        with fault.injected(plan):
            for p in prompts:
                out = co.generate(p, 5, timeout_s=60)
                # exactly-once + bit-exact REGARDLESS of what the
                # migration plane suffered: a failed page stream means
                # recompute, never a wrong or missing token
                assert out["error"] is None
                assert out["tokens"] == expected(p, 5), \
                    "stream diverged under migration chaos"
                if out["prefill"]["recompute_fallback"]:
                    fallbacks += 1
        fired = sum(plan.injected.values())
        assert fired >= 3, f"chaos never fired: {plan.injected}"
        # the destination never serves a HALF-imported chain: each
        # prompt's prefix probe is all-or-nothing at full pages
        for p in prompts:
            hit = dstore.probe(p + [1])
            assert hit in (0, 3 * PT) or hit % PT == 0
        # source pins were released under every outcome: with no live
        # sequence, every page's only ref is the radix tree's — a
        # leaked export pin would show as refs > 1
        with pstore.pagepool._mu:
            extra = [p for _, pages in pstore.pagepool._blocks.values()
                     for p in pages if p.refs > 1]
        assert not extra, \
            f"migration chaos leaked source page pins: {extra}"
        pstore.pagepool.assert_consistent()
        dstore.pagepool.assert_consistent()
        # post-chaos the plane works again
        clean = [seed * 100 + 77_000 + j for j in range(13)]
        out = co.generate(clean, 3)
        assert out["error"] is None
        assert out["tokens"] == expected(clean, 3)
        assert out["prefill"]["recompute_fallback"] is False
        assert out["prefill"]["migrated_pages"] == 3
        # baseline once the caches drop, on BOTH ends
        assert eng.join_idle(10)
        pstore.clear()
        dstore.clear()
        assert pstore.pagepool.blocks_leased() == 0
        assert dstore.pagepool.blocks_leased() == 0
        assert wait_until(lambda: occupancy() == free0, 10), \
            f"migration chaos leaked blocks: {occupancy()} != {free0}"
    finally:
        eng.close()
        psrv.stop()
        psrv.join()
        dsrv.stop()
        dsrv.join()
        pstore.close()
        dstore.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_primary_death_standby_completes_exactly_once(seed):
    """ISSUE 7 acceptance: killing the primary process mid-generation
    (simulated by a seeded `serving.step` crash of its unsupervised
    engine — the in-process analog of process death, like scenario 11's
    engine crash) yields an exactly-once, BIT-EXACT token stream
    completed by the standby side, with `migrated_from`-linked spans
    visible on /rpcz?trace_id= for the generation's trace."""
    import jax

    from brpc_tpu import rpcz
    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.migrate import StandbySync, register_standby
    from brpc_tpu.migrate.disagg import assume_stream
    from brpc_tpu.serving import DecodeEngine

    PT = 4

    @jax.jit
    def step(tokens, positions, pages):
        return (tokens * 7 + positions) % 997

    def expected(prompt, n):
        last, pos, out = prompt[-1], len(prompt), []
        for _ in range(n):
            last = (last * 7 + pos) % 997
            out.append(last)
            pos += 1
        return out

    sstore = KVCacheStore(page_tokens=PT, page_bytes=256, max_blocks=32,
                          name=f"sb_chaos_s{seed}")
    seng = DecodeEngine(step, num_slots=4, store=sstore,
                        max_pages_per_slot=32,
                        name=f"sb_chaos_se{seed}")
    ssrv = brpc.Server(enable_dcn=True)
    replica = register_standby(ssrv, sstore, seng)
    ssrv.start("127.0.0.1", 0)
    standby_addr = f"127.0.0.1:{ssrv.port}"
    pstore = KVCacheStore(page_tokens=PT, page_bytes=256, max_blocks=32,
                          commit_live_pages=True,
                          name=f"sb_chaos_p{seed}")
    peng = DecodeEngine(step, num_slots=4, store=pstore,
                        max_pages_per_slot=32,
                        name=f"sb_chaos_pe{seed}")
    sync = StandbySync(pstore, standby_addr, submit_fn=peng.submit,
                       name=f"sb_chaos_sync{seed}")
    was = (rpcz.enabled(), rpcz.sample_rate())
    rpcz.set_enabled(True, 1.0)
    try:
        prompt = [seed * 10 + j for j in range(13)]
        budget = 9
        got, errs = [], []
        done = threading.Event()
        # the primary's engine crashes at a seeded step mid-generation
        plan = fault.FaultPlan(seed).on("serving.step", fault.ERROR,
                                        times=1, after=2 + seed % 4)
        root = rpcz.new_span("client", "Chaos", "Failover")
        rpcz.set_current_span(root)
        try:
            with fault.injected(plan):
                sid = sync.submit(prompt, budget, got.append,
                                  lambda e: (errs.append(e), done.set()))
                assert done.wait(60), "primary terminal never fired"
        finally:
            rpcz.set_current_span(None)
            rpcz.submit(root)
        assert plan.injected["serving.step"] == 1
        assert errs[0] is not None and errs[0].code == errors.EINTERNAL
        n_before = len(got)
        assert n_before < budget, "crash fired after the budget"
        sync.flush(15)

        out = assume_stream(standby_addr, sid, n_before, timeout_s=60)
        assert out["error"] is None
        full = got + out["tokens"]
        # exactly-once and bit-exact across the process seam
        assert full == expected(prompt, budget), \
            f"seed {seed}: stream diverged across failover"
        assert len(out["tokens"]) == budget - n_before
        assert replica.stats()["assumed"] == 1
        # the migrated pages made the resume a partial re-decode
        # whenever at least one full page had shipped
        if n_before + len(prompt) >= 2 * PT:
            assert out.get("resume_prefix_hit", 0) >= PT

        # migrated_from-linked spans are on the generation's trace and
        # the console timeline renders the link
        spans = rpcz.recent_spans(4096, root.trace_id)
        linked = [s for s in spans if s.migrated_from]
        assert linked, "no migrated_from-linked span on the trace"
        import http.client
        c = http.client.HTTPConnection("127.0.0.1", ssrv.port,
                                       timeout=10)
        c.request("GET", f"/rpcz?trace_id={root.trace_id}")
        r = c.getresponse()
        body = r.read().decode()
        c.close()
        assert r.status == 200
        assert "migrated_from=span" in body
        # baseline on both ends
        assert seng.join_idle(10)
        pstore.clear()
        sstore.clear()
        pstore.pagepool.assert_consistent()
        sstore.pagepool.assert_consistent()
        assert pstore.pagepool.blocks_leased() == 0
        assert sstore.pagepool.blocks_leased() == 0
    finally:
        rpcz.set_enabled(*was)
        sync.close()
        peng.close()
        seng.close()
        ssrv.stop()
        ssrv.join()
        pstore.close()
        sstore.close()


# ---------------------------------------------------------------------------
# scenario 14: replica kill mid-generation under a client that also drops
# and reconnects through the cluster front door (ISSUE 8)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_router_replica_kill_client_drop_resume(seed):
    """The cluster front door's failure drill: while a generation
    streams through the ClusterRouter, an injected ``router.forward``
    fault forces one re-route, the SERVING replica is killed
    mid-decode, and the client drops its connection too.  Invariants:

    * the reconnecting client (session_id + cursor) receives EXACTLY
      the tokens past its cursor — the assembled stream is bit-exact
      (token-for-token equal to an uninterrupted run), never a
      duplicate, never a hole;
    * the resume rode the buddy page migration: ``re_decoded_tokens``
      is strictly less than the generation's total tokens;
    * the killed replica is quarantined and its prefixes REMAPPED (the
      affinity ring now answers with a healthy replica);
    * pools and refcounts return to baseline on the survivor.
    """
    import random

    import numpy as np

    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.migrate import register_migration
    from brpc_tpu.serving import (ClusterRouter, DecodeEngine,
                                  ReplicaHandle, RouterClient,
                                  SessionTable, register_router,
                                  register_serving)

    PT = 4

    def step(tokens, positions, pages=None):
        time.sleep(0.03)           # slow decode: the kill lands mid-gen
        return (np.asarray(tokens) * 7 + np.asarray(positions)) % 997

    def expected(prompt, n):
        last, pos, out = prompt[-1], len(prompt), []
        for _ in range(n):
            last = (last * 7 + pos) % 997
            out.append(last)
            pos += 1
        return out

    replicas = []
    for tag in ("a", "b"):
        store = KVCacheStore(page_tokens=PT, page_bytes=256,
                             max_blocks=32,
                             name=f"rt_chaos_{tag}{seed}",
                             commit_live_pages=True)
        eng = DecodeEngine(step, num_slots=2, store=store,
                           max_pages_per_slot=32,
                           name=f"rt_chaos_eng_{tag}{seed}")
        srv = brpc.Server(enable_dcn=True)
        register_serving(srv, engine=eng)
        register_migration(srv, store)
        srv.start("127.0.0.1", 0)
        replicas.append((store, eng, srv,
                         f"127.0.0.1:{srv.port}"))

    table = SessionTable()
    router = ClusterRouter(
        [ReplicaHandle(addr, name=f"rt_{tag}", engine=eng, store=st,
                       server=srv)
         for (st, eng, srv, addr), tag in zip(replicas, "ab")],
        sessions=table, page_tokens=PT, replicate_sessions=True,
        quarantine_after=1, name=f"rt_chaos_router{seed}",
        check_interval_s=0.02)
    rsrv = brpc.Server()
    register_router(rsrv, router)
    rsrv.start("127.0.0.1", 0)
    cli = RouterClient(f"127.0.0.1:{rsrv.port}")

    rng = random.Random(seed)
    base = rng.randrange(100, 800)
    prompt = [base + i for i in range(13)]      # 3 full pages
    budget = 10
    plan = fault.FaultPlan(seed=seed)
    plan.on("router.forward", fault.ERROR, times=1)
    victim = survivor = None
    try:
        with fault.injected(plan):
            gen = cli.start(prompt, budget)
            assert gen.wait_tokens(3, timeout_s=20), \
                f"seed {seed}: no tokens before the kill"
            sid = gen.session_id
            s = table.get(sid)
            assert wait_until(lambda: s.replicated_pages > 0, 10), \
                f"seed {seed}: no buddy replication before the kill"
            cursor, seen = gen.cursor, gen.tokens
            victim = next(r for r in replicas
                          if r[3] == s.replica
                          or str(ReplicaHandle(r[3]).endpoint)
                          == s.replica)
            survivor = next(r for r in replicas if r is not victim)
            gen.drop()                      # the client dies...
            victim[2].stop()                # ...and the replica too
            victim[2].join()
            victim[1].close(timeout_s=2.0)
            assert wait_until(
                lambda: s.state in ("finished", "failed"), 30), \
                f"seed {seed}: session never completed after the kill"
            assert s.state == "finished", \
                f"seed {seed}: session failed E{s.error_code}"
            assert plan.injected.get("router.forward", 0) == 1
            assert s.resumes >= 2           # injected re-route + kill
            out = cli.resume_wait(sid, cursor, timeout_s=20)
        assert out["error"] is None
        full = seen[:cursor] + out["tokens"]
        assert full == expected(prompt, budget), \
            f"seed {seed}: stream diverged across the router seam"
        # exactly-once: a later reconnect replays the same suffix, no
        # token appears twice
        again = cli.resume_wait(sid, cursor, timeout_s=10)
        assert again["tokens"] == out["tokens"]
        total = len(prompt) + budget
        assert 0 < s.re_decoded_tokens < total, \
            f"seed {seed}: re_decoded={s.re_decoded_tokens} of {total}"
        # quarantine + remap: the ring no longer answers with the dead
        # replica for this prefix
        from brpc_tpu.policy.health_check import is_broken
        from brpc_tpu.policy.load_balancer import prefix_fingerprint
        victim_ep = ReplicaHandle(victim[3]).endpoint
        assert is_broken(victim_ep), \
            f"seed {seed}: killed replica not quarantined"
        remapped = router._lb.select_server(
            request_code=prefix_fingerprint(prompt))
        assert remapped != victim_ep
        # survivor baseline: no leaked sequences, pools consistent
        sstore = survivor[0]
        assert wait_until(
            lambda: sstore.stats()["live_seqs"] == 0, 10)
        sstore.clear()
        sstore.pagepool.assert_consistent()
        assert sstore.pagepool.blocks_leased() == 0
    finally:
        router.close(timeout_s=3.0)
        rsrv.stop()
        rsrv.join()
        for st, eng, srv, _addr in replicas:
            try:
                eng.close(timeout_s=2.0)
            except Exception:
                pass
            try:
                srv.stop()
                srv.join()
            except Exception:
                pass
            st.clear()
            st.close()


# ---------------------------------------------------------------------------
# scenario 15 (ISSUE 11): crash MID-VERIFY in the speculative engine ->
# supervisor resumes every stream bit-exact vs the plain-decode oracle
# with ZERO leaked draft pages
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_spec_verify_crash_resumes_bit_exact_no_draft_leaks(seed):
    """The speculative engine under supervision, crash injected at the
    ``serving.spec_verify`` fault site — between the draft LEASES
    (in-seq cursor pages + side-branch forks) being taken and the
    verify committing any of them:

    * every stream completes exactly-once and matches the plain greedy
      dense oracle token for token (speculation changes cost, never
      output — including across a crash/restart seam);
    * ZERO leaked draft pages: live_seqs, page refcounts, the page
      free-list, HBM block occupancy and the native emit rings all
      return to baseline (a rejected-or-crashed draft lease releases
      like any other holder);
    * the resumed decode was cheaper than a from-scratch replay
      (committed pages prefix-hit across the restart).
    """
    import gc

    from brpc_tpu import native_path
    from brpc_tpu.models.runner import (TransformerRunner,
                                        make_store_for)
    from brpc_tpu.serving import (DecodeEngine, EngineSupervisor,
                                  NGramProposer)

    m = _mr_chaos_model()
    cfg, params = m["cfg"], m["params"]
    store = make_store_for(cfg, page_tokens=4, max_blocks=32,
                           name=f"spec_chaos_kv{seed}")
    device_pool = store.pagepool.pool

    def occupancy():
        with device_pool._lock:
            return {c: len(device_pool._free[c])
                    for c in device_pool._free}

    free0 = occupancy()
    gc.collect()
    ring0 = native_path.tokring_live()
    runner = TransformerRunner(params, cfg, store=store,
                               name=f"spec_chaos_m{seed}")
    calm = ({"queue_delay_us": float("inf"), "pool_ratio": 9.9,
             "queue_depth": 1e9},) * 3
    sup = EngineSupervisor(
        lambda: DecodeEngine(runner=runner, num_slots=2, store=store,
                             max_pages_per_slot=24,
                             prefill_buckets=(8, 16),
                             draft_runner=NGramProposer(width=2),
                             draft_len=4,
                             name=f"spec_chaos_e{seed}"),
        store=store, heartbeat_deadline_s=10.0, check_interval_s=0.02,
        ladder=calm, name=f"spec_chaos{seed}")
    try:
        # jit warm + commit a shared 2-page prefix into the radix tree
        shared = [50, 61, 12, 73, 24, 85, 36, 97]
        done = threading.Event()
        sup.submit(shared + [1], 2, lambda t: None,
                   lambda e: done.set())
        assert done.wait(180)
        assert sup.join_idle(30)
        h0 = store.hit_tokens.get_value()
        p0 = store.prompt_tokens.get_value()

        plan = fault.FaultPlan(seed)
        plan.on("serving.spec_verify", fault.ERROR, times=1, after=2)
        prompts = [shared + [100 + i] for i in range(4)]
        sinks = []
        with fault.injected(plan):
            for p in prompts:
                ev = threading.Event()
                toks: list = []
                errs: list = []
                sinks.append((ev, toks, errs))
                sup.submit(p, 6, toks.append,
                           lambda e, ev=ev, errs=errs: (errs.append(e),
                                                        ev.set()))
            for ev, _, _ in sinks:
                assert ev.wait(240), \
                    "generation hung across the mid-verify crash"
        assert plan.injected["serving.spec_verify"] == 1
        st = sup.stats()
        assert st["restarts"] == 1
        assert st["last_recovery"]["stolen_slots"] >= 1
        # exactly-once + bit-exact vs the plain greedy oracle across
        # the crash seam
        for p, (ev, toks, errs) in zip(prompts, sinks):
            assert errs == [None], f"{p[-1]}: {errs}"
            assert toks == _mr_expected(p, 6), \
                f"req {p[-1]}: speculative stream diverged at the seam"
        # the resume prefix-hit committed pages (cheaper than replay)
        dp = store.prompt_tokens.get_value() - p0
        dh = store.hit_tokens.get_value() - h0
        assert dp > 0 and (dp - dh) / dp < 1.0, \
            "recovery re-decoded as much as a from-scratch replay"
        # zero leaked draft pages: every lease (in-seq cursor, forks)
        # released across crash + takeover + rebuild
        assert sup.join_idle(30)
        assert wait_until(
            lambda: store.stats()["live_seqs"] == 0, 10), \
            "a draft lease (fork or main seq) out-lived its request"
        store.clear()
        store.pagepool.assert_consistent()
        assert store.pagepool.blocks_leased() == 0
        assert wait_until(lambda: occupancy() == free0, 10), \
            f"KV blocks leaked: {occupancy()} != {free0}"
    finally:
        sup.close()
        store.close()
    assert wait_until(
        lambda: (gc.collect(), native_path.tokring_live())[1] <= ring0,
        10), "native emit rings leaked across the speculative restart"


# ---------------------------------------------------------------------------
# scenario 16 (ISSUE 12): partition failures mid-fanout over the sharded
# parameter-server service -> PartitionChannel sub-call retry gives
# exactly-once apply (version counters prove no double scatter-add)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_psserve_partition_faults_exactly_once_apply(seed):
    """Injected `psserve.lookup` / `psserve.update` faults fail
    individual PARTITION sub-calls mid-fanout (pre-apply failures AND
    post-apply ack drops).  The client's partition-level retry must
    heal every request, and the invariants hold:

    * every Update applies EXACTLY once — the per-shard version
      counters advance once per distinct update_id, post-apply retries
      dedup (dup counter > 0 when an ack dropped), and the final table
      is bit-identical to applying each acked update once;
    * every Lookup eventually serves rows bit-identical to the oracle;
    * pools return to baseline: batcher queues drain to zero and the
      shards' applied-id sets hold exactly the distinct updates.
    """
    import numpy as np

    from brpc_tpu.psserve import (EmbeddingShardServer, PSClient,
                                  init_embedding_table, register_psserve,
                                  unregister_psserve)
    from brpc_tpu.rpc.combo_channels import PartitionChannel

    V, D, P = 64, 8, 4
    # integer base + integer grads: every association of float32 adds
    # is exact, so exactly-once shows up as bit-identity
    base = np.round(init_embedding_table(V, D, seed=3) * 100)
    servers, svcs, shards = [], [], []
    pc = PartitionChannel(P)
    for i in range(P):
        sh = EmbeddingShardServer(i, P, V, D, table=base,
                                  name=f"chaos16_{seed}")
        shards.append(sh)
        s = brpc.Server()
        svcs.append(register_psserve(s, sh, max_delay_us=500,
                                     name=f"c16_{seed}_{i}"))
        s.start("127.0.0.1", 0)
        servers.append(s)
        # channel retry OFF: the injected sub-call failure must be
        # healed by the PARTITION-level retry under test, not papered
        # over inside the socket channel
        pc.add_partition(i, brpc.Channel(f"127.0.0.1:{s.port}",
                                         timeout_ms=10_000, max_retry=0))
    rng = np.random.default_rng(seed)
    n_threads, n_updates = 4, 3
    keysets = [rng.integers(0, V, size=6).astype(np.int64)
               for _ in range(n_threads)]
    gradsets = [rng.integers(-3, 4, (6, D)).astype(np.float32)
                for _ in range(n_threads)]
    plan = fault.FaultPlan(seed)
    # drift the firing point with the seed so pre-apply failures,
    # post-apply ack drops and lookup failures all get coverage
    plan.on("psserve.update", fault.ERROR, times=2, after=seed % 3)
    plan.on("psserve.lookup", fault.ERROR, times=2, after=seed % 2)
    results: dict = {}
    mu = threading.Lock()
    try:
        with fault.injected(plan):
            def worker(t):
                cli = PSClient(pc, vocab=V, dim=D, max_retry=3,
                               name=f"c16cli_{seed}_{t}")
                try:
                    for _ in range(n_updates):
                        cli.update(keysets[t], gradsets[t])
                        cli.lookup(keysets[t])
                    with mu:
                        results[t] = (cli.n_retries, cli.n_stale_reads)
                except errors.RpcError as e:   # pragma: no cover
                    with mu:
                        results[t] = e

            ts = [threading.Thread(target=worker, args=(t,))
                  for t in range(n_threads)]
            [t.start() for t in ts]
            [t.join(60) for t in ts]
        # every request healed: no worker surfaced an error or hung
        assert len(results) == n_threads
        failed = {t: r for t, r in results.items()
                  if isinstance(r, Exception)}
        assert not failed, f"workers failed despite retries: {failed}"
        # the schedule actually fired
        assert sum(plan.injected.values()) >= 1
        # exactly-once: version counters advance once per DISTINCT
        # update (n_threads * n_updates sub-applies per owning shard),
        # and any post-apply ack drop shows up as a dedup, never a
        # double add
        import jax.numpy as jnp
        want = jnp.asarray(base)
        for t in range(n_threads):
            for _ in range(n_updates):
                want = want.at[keysets[t]].add(jnp.asarray(gradsets[t]))
        got = np.concatenate([sh.snapshot_rows() for sh in shards])
        np.testing.assert_array_equal(got, np.asarray(want))
        total_applies = sum(sh.n_updates for sh in shards)
        total_version = sum(sh.version for sh in shards)
        assert total_version == total_applies, \
            "version advanced without a distinct apply (double add?)"
        # read-your-writes held through the chaos
        assert all(r[1] == 0 for r in results.values())
        # quiescent lookups (all writers joined) bit-identical to the
        # oracle — through the service, not snapshot_rows
        wantn = np.asarray(want)
        final_cli = PSClient(pc, vocab=V, dim=D, max_retry=3,
                             name=f"c16fin_{seed}")
        for t in range(n_threads):
            np.testing.assert_array_equal(final_cli.lookup(keysets[t]),
                                          wantn[keysets[t]])
        # pools/refcounts to baseline: queues drained, applied-id sets
        # hold exactly the distinct applies (every dup was served from
        # the set, not re-added)
        for svc in svcs:
            for b in (svc._lookup_b, svc._update_b):
                assert wait_until(
                    lambda b=b: b.stats()["queued"] == 0, 10)
        assert sum(len(sh._applied) for sh in shards) == total_applies
    finally:
        for svc in svcs:
            unregister_psserve(svc)
        for s in servers:
            s.stop()
            s.join()
        pc.close()


# ---------------------------------------------------------------------------
# scenario 17 (ISSUE 16): the ROUTER PROCESS dies (SIGKILL, no goodbye)
# plus a replica kill -> a successor process adopts the session WAL and
# every session resumes bit-exact, exactly once, over buddy-warm pages;
# a superseded router's floor pushes are fenced by epoch
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_router_process_kill_wal_adoption_exactly_once(seed):
    """The durable control plane's acceptance drill: N=8 generations
    stream through a router running as its OWN OS PROCESS over a
    session WAL; mid-generation the harness SIGKILLs the router AND
    kills one serving replica.  A successor (fresh process w.r.t. the
    dead router) adopts the fleet from the WAL.  Invariants:

    * every session resumes from the client-held cursor and the
      assembled stream is bit-exact vs the uninterrupted oracle —
      zero duplicate tokens across the adoption seam, zero holes;
    * resumes ride the N-way buddy pages: ``re_decoded_tokens`` is
      strictly less than the generation's total on buddy-warm resumes;
    * the successor's epoch strictly supersedes the dead router's, and
      a floor push carrying the OLD epoch is refused ('stale epoch');
    * the killed replica is quarantined by the survivors;
    * survivor pools and refcounts return to baseline.
    """
    import random

    from brpc_tpu.serving import (ClusterRouter, ReplicaHandle,
                                  RouterClient, SessionTable,
                                  register_router)
    from brpc_tpu.serving.router_proc import spawn_router
    from brpc_tpu.tools.rpc_press import (spin_up_replicas,
                                          tear_down_replicas)

    PT = 4
    N = 8
    budget = 10

    def expected(prompt, n):
        last, pos, out = prompt[-1], len(prompt), []
        for _ in range(n):
            last = (last * 7 + pos) % 997
            out.append(last)
            pos += 1
        return out

    replicas = spin_up_replicas(
        3, page_tokens=PT, step_delay_s=0.03, num_slots=8,
        commit_live_pages=True, name_prefix=f"c17_{seed}")
    addrs = [addr for *_, addr in replicas]
    import tempfile
    wal_dir = tempfile.mkdtemp(prefix=f"chaos17_{seed}_")
    wal_path = os.path.join(wal_dir, "sessions.wal")
    proc, raddr = spawn_router(
        wal_path, addrs, replicate_sessions=True,
        replication_factor=3, page_tokens=PT, check_interval_s=0.02)

    rng = random.Random(seed)
    successor = rsrv2 = None
    try:
        cli = RouterClient(raddr, timeout_ms=20_000)
        gens = []
        for k in range(N):
            base = rng.randrange(100, 800)
            prompt = [base + k + i for i in range(13)]   # 3 full pages
            gens.append((prompt, cli.start(prompt, budget)))
        for prompt, g in gens:
            assert g.wait_tokens(3, timeout_s=30), \
                f"seed {seed}: no tokens before the kill"
        # buddy replication visible through the subprocess router's
        # Stats RPC before the kill
        from brpc_tpu.rpc.channel import Channel

        def _warm():
            st = Channel(raddr, timeout_ms=5000).call_sync(
                "Router", "Stats", {}, serializer="json",
                response_serializer="json")
            return sum(1 for r in st["session_rows"]
                       if r["replicated_pages"] > 0)
        assert wait_until(lambda: _warm() >= 1, 15), \
            f"seed {seed}: no buddy replication before the kill"
        old_epoch = Channel(raddr, timeout_ms=5000).call_sync(
            "Router", "Stats", {}, serializer="json",
            response_serializer="json")["epoch"]

        # -- the crash: router PROCESS and one replica die together --
        proc.kill()
        proc.wait()
        vstore, veng, vsrv, vaddr = replicas[0]
        vsrv.stop()
        vsrv.join()
        veng.close(timeout_s=2.0)

        # client-held cursors (the WAL, by write-ahead, is >= these)
        held = []
        for prompt, g in gens:
            g.drop()
            held.append((prompt, g.session_id, g.cursor, g.tokens))

        # -- adoption: a successor over the same WAL --
        table = SessionTable.recover(wal_path)
        assert table.replay_stats["sessions"] >= N
        assert table.replay_stats["live"] >= 1
        successor = ClusterRouter(
            [ReplicaHandle(a) for a in addrs], sessions=table,
            replicate_sessions=True, replication_factor=3,
            page_tokens=PT, quarantine_after=1,
            name=f"c17_successor{seed}", check_interval_s=0.02)
        assert successor.epoch > old_epoch
        rsrv2 = brpc.Server()
        register_router(rsrv2, successor)
        rsrv2.start("127.0.0.1", 0)
        cli2 = RouterClient(f"127.0.0.1:{rsrv2.port}",
                            timeout_ms=30_000)

        warm_resumes = 0
        for prompt, sid, cursor, seen in held:
            out = cli2.resume_wait(sid, cursor, timeout_s=60)
            assert out["error"] is None, \
                f"seed {seed}: resume failed E{out['error']}"
            full = seen[:cursor] + out["tokens"]
            assert full == expected(prompt, budget), \
                f"seed {seed}: stream diverged across the adoption seam"
            assert len(full) == budget    # zero dups, zero holes
            s = table.get(sid)
            total = len(prompt) + budget
            assert s.re_decoded_tokens < total, \
                f"seed {seed}: resume recomputed everything"
            if s.re_decoded_tokens < total - len(prompt):
                warm_resumes += 1
        assert warm_resumes >= 1, \
            f"seed {seed}: no buddy-warm resume rode the shipped pages"

        # -- epoch fencing: the dead router's epoch is refused --
        ctrl = replicas[1][2]._services["_cluster"]
        assert wait_until(lambda: ctrl.epoch >= successor.epoch, 10), \
            f"seed {seed}: successor floor push never reached replica"
        with pytest.raises(brpc.RpcError) as ei:
            Channel(replicas[1][3], timeout_ms=2000).call_sync(
                "_cluster", "SetFloor",
                {"epoch": old_epoch, "level": 4, "router": "zombie"},
                serializer="tensorframe",
                response_serializer="tensorframe")
        assert ei.value.code == errors.EREQUEST
        assert "stale epoch" in (ei.value.text or "")

        # -- the victim is quarantined by the survivors --
        from brpc_tpu.policy.health_check import is_broken
        victim_ep = ReplicaHandle(vaddr).endpoint
        assert wait_until(lambda: is_broken(victim_ep), 15), \
            f"seed {seed}: killed replica not quarantined"

        # -- survivor baseline: pools and refcounts drain --
        for store, _eng, _srv, _addr in replicas[1:]:
            assert wait_until(
                lambda s=store: s.stats()["live_seqs"] == 0, 15), \
                f"seed {seed}: leaked live sequences on a survivor"
            store.clear()
            store.pagepool.assert_consistent()
            assert store.pagepool.blocks_leased() == 0
    finally:
        try:
            proc.kill()
            proc.wait()
        except Exception:
            pass
        if successor is not None:
            successor.close(timeout_s=3.0)
        if rsrv2 is not None:
            rsrv2.stop()
            rsrv2.join()
        tear_down_replicas(replicas)
        try:
            os.unlink(wal_path)
            os.rmdir(wal_dir)
        except OSError:
            pass


@pytest.mark.parametrize("seed", SEEDS)
def test_durable_control_plane_fault_sites(seed):
    """The three ISSUE 16 fault sites, driven end to end:

    * ``router.wal_append`` — appends fail (un-durable tail), the
      router process 'dies' without healing them, and the successor
      still serves EXACTLY ONCE: the client's cursor outran the WAL,
      the gap is re-decoded bit-exact and never re-delivered;
    * ``cluster.floor_push`` — a dropped push is simply re-pushed next
      tick: the remote floor converges, drops are counted;
    * ``migrate.prefix_fetch`` — a failing pull falls back to
      recompute (generation completes, prefix_hit == 0), pools and
      refcounts at baseline after; the next fetch (no fault) works.
    """
    import random
    import tempfile

    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.migrate import make_prefix_fetcher, register_migration
    from brpc_tpu.serving import (ClusterRouter, DecodeEngine,
                                  ReplicaHandle, SessionTable,
                                  register_cluster_control,
                                  register_serving)

    PT = 4
    rng = random.Random(seed)

    def step(tokens, positions, pages=None):
        time.sleep(0.005)
        return (np.asarray(tokens) * 7 + np.asarray(positions)) % 997

    def expected(prompt, n):
        last, pos, out = prompt[-1], len(prompt), []
        for _ in range(n):
            last = (last * 7 + pos) % 997
            out.append(last)
            pos += 1
        return out

    # ---- (1) WAL append failure -> exactly-once across adoption ----
    wal_dir = tempfile.mkdtemp(prefix=f"c17b_{seed}_")
    wal_path = os.path.join(wal_dir, "s.wal")
    store = KVCacheStore(page_tokens=PT, page_bytes=256, max_blocks=32,
                         name=f"c17b_{seed}", commit_live_pages=True)
    eng = DecodeEngine(step, num_slots=4, store=store,
                       max_pages_per_slot=32,
                       name=f"c17b_eng_{seed}")
    srv = brpc.Server(enable_dcn=True)
    register_serving(srv, engine=eng)
    register_migration(srv, store)
    srv.start("127.0.0.1", 0)
    addr = f"127.0.0.1:{srv.port}"

    table = SessionTable(wal=wal_path)
    router = ClusterRouter(
        [ReplicaHandle(addr, name="c17b", engine=eng, store=store,
                       server=srv)],
        sessions=table, page_tokens=PT, name=f"c17b_router{seed}",
        check_interval_s=0.02)
    successor = None
    budget = 8
    base = rng.randrange(100, 800)
    prompt = [base + i for i in range(9)]
    try:
        plan = fault.FaultPlan(seed=seed)
        # fail every append after the first few: the tail of the
        # stream is never durable
        plan.on("router.wal_append", fault.ERROR, times=100, after=4)
        got = []
        with fault.injected(plan):
            s = router.open_session(prompt, budget)
            router.attach(s.sid, 0, got.append)
            assert wait_until(lambda: s.state == "finished", 30), \
                f"seed {seed}: generation never finished under faults"
        assert got == expected(prompt, budget)
        assert plan.injected.get("router.wal_append", 0) >= 1
        wal_stats = table.wal.stats()
        assert wal_stats["append_failures"] >= 1
        client_cursor = len(got)
        sid = s.sid
        # the router process "dies" with the pending tail UNHEALED
        router.close(timeout_s=3.0)
        table.wal._pending.clear()     # simulate: heal never happened
        table.close()

        table2 = SessionTable.recover(wal_path)
        r = table2.get(sid)
        assert r is not None
        # the WAL is BEHIND the client (its tail appends failed) —
        # legal, because attach-ahead re-decodes and suppresses
        assert r.cursor <= client_cursor
        successor = ClusterRouter(
            [ReplicaHandle(addr, name="c17b2", engine=eng,
                           store=store, server=srv)],
            sessions=table2, page_tokens=PT,
            name=f"c17b_succ{seed}", check_interval_s=0.02)
        got2 = []
        done = threading.Event()
        successor.attach(sid, client_cursor, got2.append,
                         lambda err: done.set())
        assert done.wait(30), f"seed {seed}: resume never finished"
        # the client saw `got` then `got2`: exactly the oracle, no
        # token twice even though the gap was re-decoded
        assert got + got2 == expected(prompt, budget), \
            f"seed {seed}: duplicate or hole across the WAL gap"
        successor.close(timeout_s=3.0)
        successor = None
        table2.close()

        # ---- (2) dropped floor push -> re-pushed next tick ----
        ctrl = register_cluster_control  # noqa: F841  (site below)
        rep_srv = brpc.Server()
        ctrl_svc = register_cluster_control(rep_srv, engine=eng,
                                            store=store,
                                            name=f"c17b_ctrl{seed}")
        rep_srv.start("127.0.0.1", 0)
        wire_router = ClusterRouter(
            [f"127.0.0.1:{rep_srv.port}"], page_tokens=PT,
            name=f"c17b_wire{seed}", auto_tick=False, epoch=3)
        plan2 = fault.FaultPlan(seed=seed)
        plan2.on("cluster.floor_push", fault.ERROR, times=2)
        with fault.injected(plan2):
            wire_router._push_floor(2)     # dropped on the wire
            assert ctrl_svc.level == 0
            wire_router._push_floor(2)     # dropped again
            assert ctrl_svc.level == 0
            wire_router._push_floor(2)     # next tick: lands
            assert ctrl_svc.level == 2 and ctrl_svc.epoch == 3
        assert plan2.injected.get("cluster.floor_push", 0) == 2
        assert wire_router.floor_push_drops == 2
        rows = wire_router.remote_floor_table()
        assert rows[0]["drops"] == 2
        assert rows[0]["acked_level"] == 2
        wire_router.close(timeout_s=2.0)
        rep_srv.stop()
        rep_srv.join()

        # ---- (3) prefix fetch failure -> recompute fallback ----
        cold_store = KVCacheStore(page_tokens=PT, page_bytes=256,
                                  max_blocks=32,
                                  name=f"c17b_cold_{seed}",
                                  commit_live_pages=True)
        cold_eng = DecodeEngine(step, num_slots=4, store=cold_store,
                                max_pages_per_slot=32,
                                name=f"c17b_cold_eng_{seed}")
        cold_srv = brpc.Server(enable_dcn=True)
        cold_svc = register_serving(cold_srv, engine=cold_eng)
        cold_mig = register_migration(cold_srv, cold_store)
        cold_srv.start("127.0.0.1", 0)
        cold_addr = f"127.0.0.1:{cold_srv.port}"
        cold_svc.prefix_fetcher = make_prefix_fetcher(
            cold_mig.migrator, cold_addr)

        from brpc_tpu.rpc.channel import Channel
        from brpc_tpu.rpc.controller import Controller
        from brpc_tpu.rpc.stream import stream_create

        class _Drain:
            def __init__(self):
                self.done = threading.Event()

            def on_received_messages(self, stream, messages):
                import json as _json
                for m in messages:
                    if _json.loads(bytes(m)).get("done") is not None:
                        self.done.set()

            def on_closed(self, stream):
                self.done.set()

        warm_prompt = prompt    # replica `store` is warm from part 1
        plan3 = fault.FaultPlan(seed=seed)
        plan3.on("migrate.prefix_fetch", fault.ERROR, times=1)
        with fault.injected(plan3):
            d = _Drain()
            cntl = Controller(timeout_ms=15_000)
            stream_create(cntl, d)
            resp = Channel(cold_addr, timeout_ms=15_000).call_sync(
                "Serving", "Generate",
                {"prompt": warm_prompt, "max_new_tokens": 4,
                 "prefix_holders": [addr]},
                serializer="json", cntl=cntl)
            assert d.done.wait(15), \
                f"seed {seed}: generation hung on fetch failure"
        assert plan3.injected.get("migrate.prefix_fetch", 0) == 1
        # the fetch failed -> recompute fallback: no prefix served
        assert resp["prefix_hit"] == 0, resp
        assert cold_svc.prefix_fetches == 0
        mig_stats = cold_mig.migrator.stats()
        assert mig_stats["fetch_routes"][addr]["failed"] == 1
        # no fault: the same pull lands on a FRESH cold replica (the
        # first one's recompute fallback warmed its own cache, which
        # is exactly the point of the fallback)
        cold2_store = KVCacheStore(page_tokens=PT, page_bytes=256,
                                   max_blocks=32,
                                   name=f"c17b_cold2_{seed}",
                                   commit_live_pages=True)
        cold2_eng = DecodeEngine(step, num_slots=4, store=cold2_store,
                                 max_pages_per_slot=32,
                                 name=f"c17b_cold2_eng_{seed}")
        cold2_srv = brpc.Server(enable_dcn=True)
        cold2_svc = register_serving(cold2_srv, engine=cold2_eng)
        cold2_mig = register_migration(cold2_srv, cold2_store)
        cold2_srv.start("127.0.0.1", 0)
        cold2_addr = f"127.0.0.1:{cold2_srv.port}"
        cold2_svc.prefix_fetcher = make_prefix_fetcher(
            cold2_mig.migrator, cold2_addr)
        d2 = _Drain()
        cntl2 = Controller(timeout_ms=15_000)
        stream_create(cntl2, d2)
        resp2 = Channel(cold2_addr, timeout_ms=15_000).call_sync(
            "Serving", "Generate",
            {"prompt": warm_prompt, "max_new_tokens": 4,
             "prefix_holders": [addr]},
            serializer="json", cntl=cntl2)
        assert d2.done.wait(15)
        assert resp2["prefix_hit"] >= PT, resp2
        assert cold2_svc.prefix_fetches == 1
        assert cold2_svc.prefix_fetched_pages >= 1
        # baseline on the cold stores after drain
        for c_store, c_eng, c_srv in (
                (cold_store, cold_eng, cold_srv),
                (cold2_store, cold2_eng, cold2_srv)):
            assert wait_until(
                lambda s=c_store: s.stats()["live_seqs"] == 0, 10)
            c_eng.close(timeout_s=2.0)
            c_srv.stop()
            c_srv.join()
            c_store.clear()
            c_store.pagepool.assert_consistent()
            assert c_store.pagepool.blocks_leased() == 0
            c_store.close()
    finally:
        if successor is not None:
            successor.close(timeout_s=2.0)
        try:
            router.close(timeout_s=2.0)
        except Exception:
            pass
        try:
            eng.close(timeout_s=2.0)
        except Exception:
            pass
        try:
            srv.stop()
            srv.join()
        except Exception:
            pass
        store.clear()
        store.close()
        try:
            os.unlink(wal_path)
            os.rmdir(wal_dir)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# scenario 18 (ISSUE 17): kill a shard SERVER mid-update-wave while the
# fleet carries all three traffic shapes -> the trainer heals via
# update_token partition retry (momentum steps exactly once), streamed
# generations stay bit-exact, RYW holds, and queues/pools drain to
# baseline after the restart
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_mixed_traffic_shard_kill_midwave_exactly_once(seed):
    """The training-plane chaos story end to end: one fleet serving
    zipf lookups + streamed generations + fused-optimizer update waves,
    and partition ``seed % 2``'s SERVER dies after at least two
    optimizer applies landed (mid-run, waves in flight) then comes back
    ~0.3s later over the same shard state.  Invariants:

    * exactly-once: every shard's version counter equals its distinct
      applies — the update_token replay dedup'd everything the killed
      server had already applied, so no momentum step ran twice;
    * the trainer completed every step (workers healed via partition
      retry, none died);
    * zero stale reads across every per-shape client (RYW);
    * generations under chaos are bit-exact against their quiesced
      reference streams;
    * batcher queues drain to zero and decode pools return to their
      post-reference baseline.
    """
    from brpc_tpu.train import MixedWorkloadHarness

    h = MixedWorkloadHarness(n_shards=2, vocab=48, dim=8,
                             n_replicas=1, lookup_workers=1,
                             gen_workers=1, gen_tokens=8,
                             train_workers=2, train_steps=4,
                             seed=seed, name=f"c18_{seed}")
    # chaos needs more patience than the default: the dead window is
    # ~0.3s and every retry backs off retry_backoff_s * attempt
    h.trainer.wave_max_retry = 10
    h.trainer.retry_backoff_s = 0.1
    victim = seed % 2
    killed = threading.Event()

    def killer():
        # strike only after the fused optimizer has actually applied
        # waves (mid-run, not before traffic exists)
        if not wait_until(
                lambda: sum(sh.n_opt_updates for sh in h.shards) >= 2,
                30):
            return
        h.kill_shard(victim)
        killed.set()
        time.sleep(0.3)
        h.restart_shard(victim)

    kt = threading.Thread(target=killer, daemon=True,
                          name=f"c18_killer_{seed}")
    try:
        kt.start()
        rep = h.run()
        kt.join(60)
        assert killed.is_set(), "the kill never fired (trainer " \
            "finished before two optimizer applies?)"
        # exactly-once momentum: version counters advance once per
        # DISTINCT apply on every shard, through the kill and replay
        assert all(rep["exactly_once"]), rep["shards"]
        # the replay discipline actually exercised: the trainer retried
        # waves, and any ack the killed server swallowed shows up as a
        # dedup rather than a double apply
        assert rep["train"]["wave_retries"] + \
            rep["train"]["io_retries"] >= 1
        # every worker finished every step (healed, not excused)
        assert rep["train"]["steps_done"] == 2 * 4
        assert rep["train"]["waves"] == 2 * 4
        assert rep["stale_reads"] == 0
        # generations under chaos bit-exact vs the quiesced reference
        gen = rep["shapes"]["generate"]
        assert gen["ok"] > 0 and gen["mismatch"] == 0
        assert rep["queues_drained"], rep["shards"]
        assert rep["pools_at_baseline"]
        # training stayed SANE through the chaos: no NaN, no blow-up
        # from a double-applied wave (the strict loss-decrease proof is
        # test_trainer_loss_decreases_through_service — four steps on a
        # held-out batch are not enough to demand monotonicity here)
        assert np.isfinite(rep["train"]["loss_final"])
        assert rep["train"]["loss_final"] < \
            rep["train"]["loss_first"] + 0.5
    finally:
        kt.join(5)
        h.close()


# ---------------------------------------------------------------------------
# scenario 19 (ISSUE 18): kill the only WARM replica of model B
# mid-decode in a two-model fleet -> B sessions fail over onto the
# LOADING replica serving B (bit-exact, exactly once), model A sessions
# never notice, stale-epoch deploy/undeploy pushes are refused, and no
# page ever crosses a model boundary
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_multimodel_warm_replica_kill_same_model_failover(seed):
    """The multi-model plane's acceptance drill (chaos scenario 19).
    Fleet of N=4 replicas: two serve only model A (warm), one serves
    only model B (warm — the victim), one serves only B but LOADING.
    Mid-decode the victim dies.  Invariants:

    * every B session finishes bit-exact against B's oracle, exactly
      once — the driver re-routed it to the loading B replica (its
      pages arrive by buddy ship or recompute fallback; either way the
      stream may not diverge, duplicate, or hole);
    * the loading replica flips WARM via the completed generations;
    * A sessions stream bit-exact with zero errors — a B-side crash
      is invisible to the other model;
    * ``Deploy``/``Undeploy`` carrying a superseded epoch are refused
      ('stale epoch'), and an injected ``cluster.deploy`` wire fault
      is survivable by retry;
    * zero cross-model page splices: no A-model store ever holds a B
      prompt's pages and vice versa; every misroute counter reads 0;
    * survivor pools/refcounts and the native emit rings return to
      baseline.
    """
    import gc

    from brpc_tpu import native_path
    from brpc_tpu.serving import RouterClient
    from brpc_tpu.serving.modelplane import (LOADING, WARM,
                                             cluster_deploy)
    from brpc_tpu.tools.rpc_press import (expected_model_tokens,
                                          spin_up_multimodel_cluster,
                                          tear_down_multimodel_cluster)

    PT = 4
    budget = 10
    MODELS = ["modela", "modelb"]
    layout = [["modela"], ["modela"], ["modelb"], ["modelb"]]
    replicas, mults, router, rsrv, raddr = spin_up_multimodel_cluster(
        4, MODELS, layout=layout, page_tokens=PT, step_delay_s=0.03,
        commit_live_pages=True, replicate_sessions=True,
        name_prefix=f"c19_{seed}")
    try:
        # replica 3 starts LOADING: it serves B but has not proven
        # itself — still a legal placement/failover target
        replicas[3]["deps"].deploy("modelb", state=LOADING)
        assert wait_until(
            lambda: any(r["state"] == LOADING
                        for r in router.catalog.snapshot().get(
                            replicas[3]["addr"], [])), 10), \
            f"seed {seed}: catalog never saw the loading state"
        ring0 = native_path.tokring_live()

        cli = RouterClient(raddr, timeout_ms=30_000)
        # DISJOINT prompt ranges per model, so a page crossing the
        # model boundary is detectable by probing the stores
        a_prompts = [[100 + 20 * k + i for i in range(13)]
                     for k in range(3)]
        b_prompts = [[500 + 20 * k + i for i in range(13)]
                     for k in range(4)]
        a_gens = [(p, cli.start(p, budget, model="modela"))
                  for p in a_prompts]
        b_gens = [(p, cli.start(p, budget, model="modelb"))
                  for p in b_prompts]
        for p, g in a_gens + b_gens:
            assert g.wait_tokens(3, timeout_s=30), \
                f"seed {seed}: no tokens before the kill"

        # -- the crash: the only WARM replica of model B dies --
        victim = replicas[2]
        victim["server"].stop()
        # Server.join is internally bounded by graceful_quit_timeout_s
        victim["server"].join()  # brpc-check: allow(wedge-hygiene)
        victim["engines"]["modelb"].close(timeout_s=2.0)

        # every stream finishes THROUGH the crash: B rides the driver's
        # same-model failover onto replica 3, A never re-routes
        for p, g in a_gens:
            assert g.wait(60), f"seed {seed}: model A stream hung"
            assert g.error is None, \
                f"seed {seed}: model A session broke (E{g.error})"
            assert g.tokens == expected_model_tokens(
                p, budget, mults["modela"]), \
                f"seed {seed}: model A stream diverged"
        for p, g in b_gens:
            assert g.wait(60), f"seed {seed}: model B stream hung"
            assert g.error is None, \
                f"seed {seed}: model B failover failed (E{g.error})"
            assert g.tokens == expected_model_tokens(
                p, budget, mults["modelb"]), \
                f"seed {seed}: model B stream diverged across failover"
            assert len(g.tokens) == budget    # zero dups, zero holes

        # the loading replica earned its warm state by serving
        assert replicas[3]["deps"].get("modelb")["state"] == WARM

        # -- lifecycle fencing on the wire (replica 3's _cluster) --
        r3addr = replicas[3]["addr"]
        E = router.epoch
        # a fault outlasting the channel's retry budget (4 attempts:
        # initial + max_retry=3) surfaces as EINTERNAL to the pusher
        plan = fault.FaultPlan(seed=seed)
        plan.on("cluster.deploy", fault.ERROR, times=4)
        with fault.injected(plan):
            with pytest.raises(errors.RpcError) as ei0:
                cluster_deploy(r3addr, epoch=E, model="modelb",
                               op="deploy", weight=2)
            assert ei0.value.code == errors.EINTERNAL
        assert plan.injected.get("cluster.deploy", 0) >= 1
        # ...but a ONE-SHOT wire fault is absorbed by the channel's
        # retry: the fault provably fired, yet the push landed — the
        # deploy path is idempotent so the retry is safe
        plan2 = fault.FaultPlan(seed=seed)
        plan2.on("cluster.deploy", fault.ERROR, times=1)
        with fault.injected(plan2):
            out = cluster_deploy(r3addr, epoch=E, model="modelb",
                                 op="deploy", weight=2, state="warm")
            assert out["applied"] and out["epoch"] == E
        assert plan2.injected.get("cluster.deploy", 0) == 1
        assert replicas[3]["deps"].get("modelb")["weight"] == 2
        for op in ("deploy", "undeploy"):
            with pytest.raises(errors.RpcError) as ei:
                cluster_deploy(r3addr, epoch=E - 1, model="modelb",
                               op=op)
            assert ei.value.code == errors.EREQUEST
            assert "stale epoch" in (ei.value.text or "")

        # -- zero cross-model page splices, three witnesses --
        assert router.stats()["wrong_model_routes"] == 0
        for r in replicas:
            assert r["serving"].n_model_misroutes == 0
            mig = r["server"]._services.get("_kvmig")
            if mig is not None:
                assert mig.n_model_refusals == 0
            if r is victim:
                continue
            for m, store in r["stores"].items():
                foreign = a_prompts if m == "modelb" else b_prompts
                for p in foreign:
                    assert store.probe(p) == 0, \
                        f"seed {seed}: {m} store holds a foreign " \
                        f"model's prefix"

        # -- survivor baselines: pools, refcounts, native rings --
        for r in replicas:
            if r is victim:
                continue
            for store in r["stores"].values():
                assert wait_until(
                    lambda s=store: s.stats()["live_seqs"] == 0, 15), \
                    f"seed {seed}: leaked live sequences on a survivor"
                store.clear()
                store.pagepool.assert_consistent()
                assert store.pagepool.blocks_leased() == 0
    finally:
        tear_down_multimodel_cluster(replicas, router, rsrv)
    # after the engines close, every request's native emit ring must be
    # gone — idle slots may pin their LAST request's ring while the
    # engine lives, so this check belongs after teardown
    assert wait_until(
        lambda: (gc.collect(), native_path.tokring_live())[1]
        <= ring0, 10), \
        f"seed {seed}: native emit rings leaked across the failover"


# ---------------------------------------------------------------------------
# chaos scenario 20: replica kill mid-collection — the telemetry plane
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_replica_kill_mid_collection_tombstones_and_slo_holds(seed):
    """The fleet telemetry plane's chaos drill (ISSUE 20).  A 2-replica
    canary fleet (m@v1 baseline / m@v2 canary, both warm on both
    replicas) streams under an attached SLO engine while the router's
    collector pulls at tick cadence.  Mid-collection one replica is
    killed.  Invariants:

    * the victim is TOMBSTONED on the collector — its series freeze
      and drop out of aggregates, never silently averaged in;
    * the SLO engine HOLDs every canary decision for the disruption
      window: a clean canary must NOT promote (and chaos-induced burn
      must not roll back) while the fleet is disrupted — the ramp
      stays ``ramping`` with ``holds`` ticking;
    * every in-flight stream finishes bit-exact against the oracle of
      whichever version the router bound it to, exactly once, through
      the failover — telemetry is observation, never a correctness
      dependency;
    * survivor pools/refcounts and the native emit rings return to
      baseline.
    """
    import gc

    from brpc_tpu import native_path
    from brpc_tpu.serving import RouterClient
    from brpc_tpu.serving.slo import (HOLD, RAMPING, Objective,
                                      SLOEngine)
    from brpc_tpu.tools.rpc_press import (expected_model_tokens,
                                          spin_up_multimodel_cluster,
                                          tear_down_multimodel_cluster)

    PT = 4
    budget = 10
    replicas, mults, router, rsrv, raddr = spin_up_multimodel_cluster(
        2, ["m@v1", "m@v2"], page_tokens=PT, step_delay_s=0.03,
        commit_live_pages=True, replicate_sessions=True,
        name_prefix=f"c20_{seed}")
    try:
        ring0 = native_path.tokring_live()
        eng = SLOEngine(
            "m", "m@v1", "m@v2",
            # generous targets: the canary is CLEAN — only the HOLD may
            # stop it; clean_windows is set far past this test's
            # horizon so the ramp is provably still open at kill time
            [Objective("ttft_p99_ms", 60_000.0),
             Objective("itl_p99_ms", 60_000.0)],
            short_window_s=0.3, long_window_s=0.8, clean_windows=1000)
        router.attach_slo(eng)
        # collection is live on BOTH replicas before the kill — the
        # crash lands mid-collection, not before it
        assert wait_until(
            lambda: all(r["pulls"] > 0
                        for r in router.collector.replica_table()), 10), \
            f"seed {seed}: collector never pulled both replicas"

        cli = RouterClient(raddr, timeout_ms=30_000)
        prompts = [[100 + 20 * k + i for i in range(13)]
                   for k in range(4)]
        gens = [(p, cli.start(p, budget, model="m")) for p in prompts]
        for p, g in gens:
            assert g.wait_tokens(3, timeout_s=30), \
                f"seed {seed}: no tokens before the kill"

        # -- the crash --
        victim = replicas[0]
        victim["server"].stop()
        victim["server"].join()  # brpc-check: allow(wedge-hygiene)
        for e in victim["engines"].values():
            e.close(timeout_s=2.0)

        # the collector tombstones the victim (consecutive pull
        # failures or the router's quarantine note — either path)
        assert wait_until(
            lambda: victim["addr"] in router.collector.tombstoned(),
            15), f"seed {seed}: victim never tombstoned"
        # the SLO engine HOLDs the ramp for the disruption
        assert wait_until(lambda: eng.holds > 0, 10), \
            f"seed {seed}: SLO never held during the disruption"
        assert eng.state == RAMPING, \
            f"seed {seed}: ramp decided during a disruption " \
            f"({eng.state}): {eng.trail()}"

        # every stream finishes THROUGH the crash, bit-exact against
        # the version the router bound it to
        for p, g in gens:
            assert g.wait(60), f"seed {seed}: stream hung"
            assert g.error is None, \
                f"seed {seed}: stream broke (E{g.error})"
            oracle_v1 = expected_model_tokens(p, budget, mults["m@v1"])
            oracle_v2 = expected_model_tokens(p, budget, mults["m@v2"])
            assert g.tokens in (oracle_v1, oracle_v2), \
                f"seed {seed}: stream matches NEITHER version's oracle"
            assert len(g.tokens) == budget    # zero dups, zero holes
        assert router.stats()["wrong_model_routes"] == 0

        # the hold persists while the tombstone is active
        assert eng.tick(router.collector, router) == HOLD
        assert eng.state == RAMPING

        # -- survivor baselines --
        surv = replicas[1]
        for store in surv["stores"].values():
            assert wait_until(
                lambda s=store: s.stats()["live_seqs"] == 0, 15), \
                f"seed {seed}: leaked live sequences on the survivor"
            store.clear()
            store.pagepool.assert_consistent()
            assert store.pagepool.blocks_leased() == 0
    finally:
        tear_down_multimodel_cluster(replicas, router, rsrv)
    assert wait_until(
        lambda: (gc.collect(), native_path.tokring_live())[1]
        <= ring0, 10), \
        f"seed {seed}: native emit rings leaked across the kill"
