"""brpc-check suite + lock-order witness tests (ISSUE 14).

Each static pass gets a positive/negative synthetic fixture proving it
fires exactly on its seeded violation; the runtime witness tests prove
a live two-thread ABBA is flagged while ordered nesting stays silent,
and that a wedge-guard deadline miss dumps held-lock state.  The
repo-self-check test runs the full suite against the committed
CHECK_BASELINE.json, making `make check`'s guarantee a tier-1 fact.
"""
import json
import os
import textwrap
import threading
import time

import pytest

from brpc_tpu.butil import lockprof
from brpc_tpu.check import run_checks
from brpc_tpu.check.base import Repo
from brpc_tpu.check.baseline import (load_baseline, split_findings,
                                     write_baseline)
from brpc_tpu.check.bounded_decode import BoundedDecodePass
from brpc_tpu.check.fault_sites import FaultSitePass, render_registry
from brpc_tpu.check.jit_hot_path import JitHotPathPass
from brpc_tpu.check.lock_hygiene import LockHygienePass
from brpc_tpu.check.lock_order import LockOrderPass
from brpc_tpu.check.wedge_hygiene import WedgeHygienePass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_repo(tmp_path, files: dict) -> Repo:
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return Repo(str(tmp_path))


# ---------------------------------------------------------------------------
# pass 1: lock-order
# ---------------------------------------------------------------------------

def test_lock_order_flags_seeded_abba_cycle(tmp_path):
    repo = make_repo(tmp_path, {"brpc_tpu/mod.py": """
        import threading
        from brpc_tpu.butil.lockprof import InstrumentedLock

        class S:
            def __init__(self):
                self.a = InstrumentedLock("fix.a")
                self.b = InstrumentedLock("fix.b")

            def one(self):
                with self.a:
                    with self.b:
                        pass

            def two(self):
                with self.b:
                    with self.a:
                        pass
    """})
    out = LockOrderPass().run(repo)
    assert len(out) == 1
    assert "fix.a" in out[0].message and "fix.b" in out[0].message
    assert out[0].key.startswith("lock-order:cycle:")


def test_lock_order_interprocedural_cycle_and_ordered_silent(tmp_path):
    # the cycle closes only ACROSS a call: one() holds a and calls
    # helper() which takes b; two() holds b then takes a directly
    repo = make_repo(tmp_path, {"brpc_tpu/mod.py": """
        import threading

        class S:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def helper(self):
                with self.b:
                    pass

            def one(self):
                with self.a:
                    self.helper()

            def two(self):
                with self.b:
                    with self.a:
                        pass
    """})
    out = LockOrderPass().run(repo)
    assert len(out) == 1 and "via" in out[0].message

    repo2 = make_repo(tmp_path / "ordered", {"brpc_tpu/mod.py": """
        import threading

        class S:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def one(self):
                with self.a:
                    with self.b:
                        pass

            def also_ordered(self):
                self.a.acquire()
                try:
                    with self.b:
                        pass
                finally:
                    self.a.release()
    """})
    assert LockOrderPass().run(repo2) == []


# ---------------------------------------------------------------------------
# pass 2: bounded-decode
# ---------------------------------------------------------------------------

_WIRE_BAD = """
    import struct
    import numpy as np

    def parse(data):
        n = struct.unpack("<I", data[:4])[0]
        payload = data[4:4 + n]
        return payload

    def alloc(data):
        n = int.from_bytes(data[:4], "little")
        return bytearray(n)
"""

_WIRE_GOOD = """
    import struct
    import numpy as np

    def parse(data):
        n = struct.unpack("<I", data[:4])[0]
        if 4 + n > len(data):
            raise ValueError("truncated")
        payload = data[4:4 + n]
        return payload

    def alloc(data):
        n = int.from_bytes(data[:4], "little")
        return bytearray(min(n, 65536))
"""


def test_bounded_decode_flags_unchecked_wire_length(tmp_path):
    repo = make_repo(tmp_path, {"pkg/wire.py": _WIRE_BAD})
    out = BoundedDecodePass(modules=("pkg/wire.py",)).run(repo)
    kinds = {f.key.rsplit(":", 2)[-2:][0] for f in out}
    assert len(out) == 2                       # slice in parse, alloc
    assert {"parse", "alloc"} == kinds
    assert all(f.pass_id == "bounded-decode" for f in out)


def test_bounded_decode_silent_when_checked_or_bounded(tmp_path):
    repo = make_repo(tmp_path, {"pkg/wire.py": _WIRE_GOOD})
    assert BoundedDecodePass(modules=("pkg/wire.py",)).run(repo) == []


# ---------------------------------------------------------------------------
# pass 3: jit-in-hot-path
# ---------------------------------------------------------------------------

def test_jit_hot_path_flags_per_call_jit_only(tmp_path):
    repo = make_repo(tmp_path, {"brpc_tpu/mod.py": """
        import jax
        from jax.experimental.shard_map import shard_map

        STEP = jax.jit(lambda x: x + 1)          # module level: fine

        class Engine:
            def __init__(self):
                self._fn = jax.jit(self._step)   # bucketed init: fine

            def _step(self, x):
                return x

            def hot(self, x):
                f = jax.jit(lambda y: y * 2)     # per call: FLAGGED
                return f(x)

        def build_program(mesh):
            return shard_map(lambda x: x, mesh)  # builder: fine
    """})
    out = JitHotPathPass().run(repo)
    assert len(out) == 1
    assert "Engine.hot" in out[0].key and out[0].pass_id == "jit-hot-path"


# ---------------------------------------------------------------------------
# pass 4: fault-site registry
# ---------------------------------------------------------------------------

def _fault_repo(tmp_path, *, with_test=True, registry=True, extra_reg=""):
    files = {"brpc_tpu/mod.py": """
        from brpc_tpu import fault

        def op():
            if fault.ENABLED and fault.hit("fix.site") is not None:
                raise RuntimeError
    """}
    if with_test:
        files["tests/test_fix.py"] = """
        def test_site():
            assert "fix.site"
        """
    repo = make_repo(tmp_path, files)
    if registry:
        reg = render_registry(repo) + extra_reg
        p = tmp_path / "docs" / "fault_sites.md"
        p.parent.mkdir(exist_ok=True)
        p.write_text(reg)
    return repo


def test_fault_sites_clean_when_registered_and_tested(tmp_path):
    repo = _fault_repo(tmp_path)
    assert FaultSitePass().run(repo) == []


def test_fault_sites_flags_unregistered_orphaned_untested(tmp_path):
    # unknown: site in code, registry generated WITHOUT it
    repo = _fault_repo(tmp_path, registry=False)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "fault_sites.md").write_text(
        "| site | defined in | referencing tests |\n|---|---|---|\n"
        "| `ghost.site` | brpc_tpu/gone.py | test_fix |\n")
    keys = {f.key for f in FaultSitePass().run(repo)}
    assert "fault-sites:unknown:fix.site" in keys
    assert "fault-sites:orphaned:ghost.site" in keys

    # untested: registered but no referencing test
    repo2 = _fault_repo(tmp_path / "untested", with_test=False)
    keys2 = {f.key for f in FaultSitePass().run(repo2)}
    assert "fault-sites:untested:fix.site" in keys2

    # missing registry entirely
    repo3 = _fault_repo(tmp_path / "noreg", registry=False)
    keys3 = {f.key for f in FaultSitePass().run(repo3)}
    assert "fault-sites:missing-registry" in keys3


# ---------------------------------------------------------------------------
# pass 5: lock hygiene
# ---------------------------------------------------------------------------

def test_lock_hygiene_flags_raw_lock_not_instrumented(tmp_path):
    repo = make_repo(tmp_path, {"brpc_tpu/serving/mod.py": """
        import threading
        from brpc_tpu.butil.lockprof import InstrumentedLock

        class Hot:
            def __init__(self):
                self._raw = threading.Lock()                  # FLAGGED
                self._cv = threading.Condition()              # FLAGGED
                self._ok = InstrumentedLock("fix.ok")
                self._rok = InstrumentedLock("fix.rok",
                                             threading.RLock())
                self._cok = threading.Condition(
                    InstrumentedLock("fix.cok"))
    """})
    out = LockHygienePass().run(repo)
    targets = {f.key.rsplit(":", 1)[-1] for f in out}
    assert targets == {"_raw", "_cv"}
    assert all(f.pass_id == "lock-hygiene" for f in out)


# ---------------------------------------------------------------------------
# pass 6: wedge hygiene
# ---------------------------------------------------------------------------

def test_wedge_hygiene_flags_guardless_join_and_native(tmp_path):
    repo = make_repo(tmp_path, {"tests/test_fix.py": """
        import threading
        from brpc_tpu._core.lib import load

        lib = load()

        def test_burn():
            t = threading.Thread(target=lambda: None)
            t.start()
            t.join()                     # FLAGGED: unbounded
            lib.brpc_rpc_counters(0)     # FLAGGED: module has no guard

        def test_bounded(srv):
            t = threading.Thread(target=lambda: None)
            t.start()
            t.join(5)                    # fine
            t.join(timeout=5)            # fine
            srv.join()                   # fine: Server.join is bounded
    """})
    out = WedgeHygienePass().run(repo)
    whats = {f.key.split(":", 3)[-1] for f in out}
    assert whats == {"join", "native:brpc_rpc_counters"}
    assert all(":test_burn:" in f.key for f in out)

    # same module WITH a WedgeGuard: native call no longer flagged
    repo2 = make_repo(tmp_path / "guarded", {"tests/test_fix.py": """
        from wedge_guard import WedgeGuard
        GUARD = WedgeGuard("native", deadline_s=60)

        def test_burn(lib):
            GUARD.deadline(lib.brpc_rpc_counters, 0)
    """})
    assert WedgeHygienePass().run(repo2) == []


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------

def test_baseline_freezes_old_flags_new_reports_stale(tmp_path):
    repo = make_repo(tmp_path, {"pkg/wire.py": _WIRE_BAD})
    findings = BoundedDecodePass(modules=("pkg/wire.py",)).run(repo)
    assert len(findings) == 2
    path = str(tmp_path / "BASE.json")
    write_baseline(path, findings[:1])
    base = load_baseline(path)
    new, suppressed, stale = split_findings(findings, base)
    assert len(new) == 1 and len(suppressed) == 1 and stale == []
    # the frozen finding stops firing -> reported stale, never hidden
    new2, sup2, stale2 = split_findings(findings[1:], base)
    assert len(new2) == 1 and sup2 == [] and len(stale2) == 1


def test_repo_self_check_is_clean_against_committed_baseline():
    """`make check`'s guarantee as a tier-1 fact: the tree as committed
    has NO non-baseline findings, the semantic passes are baseline-
    EMPTY (all frozen findings are hygiene-pass debt), and the suite
    stays well inside its 30s budget."""
    t0 = time.monotonic()
    findings, timings = run_checks(REPO_ROOT)
    took = time.monotonic() - t0
    base = load_baseline(os.path.join(REPO_ROOT, "CHECK_BASELINE.json"))
    new, suppressed, _stale = split_findings(findings, base)
    assert new == [], "new brpc-check findings:\n" + \
        "\n".join(str(f) for f in new)
    assert set(timings) == {"lock-order", "bounded-decode", "jit-hot-path",
                            "fault-sites", "lock-hygiene", "wedge-hygiene"}
    for key in base:
        assert key.split(":")[0] in ("lock-hygiene", "wedge-hygiene"), \
            f"semantic-pass finding frozen in baseline: {key}"
    assert took < 30, f"brpc-check took {took:.1f}s (budget 30s)"


def test_cli_json_output_shape():
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "brpc_check.py"),
         "--json", "--pass", "lock-order"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    data = json.loads(out.stdout)
    assert data["counts"]["new"] == 0
    assert "lock-order" in data["timings_s"]


# ---------------------------------------------------------------------------
# runtime lock-order witness
# ---------------------------------------------------------------------------

@pytest.fixture()
def fresh_witness():
    lockprof.reset_witness()
    yield
    lockprof.reset_witness()


def test_witness_flags_two_thread_abba(fresh_witness):
    """Opposite acquisition orders across two threads close a cycle —
    flagged from the order history alone, NO actual deadlock needed."""
    a = lockprof.InstrumentedLock("tcw.a")
    b = lockprof.InstrumentedLock("tcw.b")
    with a:
        with b:
            pass
    done = threading.Event()

    def other():
        with b:
            with a:
                pass
        done.set()

    t = threading.Thread(target=other, daemon=True)
    t.start()
    assert done.wait(10)
    t.join(10)
    viols = [v for v in lockprof.order_violations()
             if set(v["cycle"]) == {"tcw.a", "tcw.b"}]
    assert len(viols) == 1
    v = viols[0]
    assert v["edge"] == ["tcw.a", "tcw.b"] or v["edge"] == ["tcw.b", "tcw.a"]
    assert "test_check.py" in v["site"]
    assert set(v["edge_sites"]) == {"tcw.a->tcw.b", "tcw.b->tcw.a"}
    # duplicate observations never double-report
    with b:
        with a:
            pass
    assert len([v for v in lockprof.order_violations()
                if set(v["cycle"]) == {"tcw.a", "tcw.b"}]) == 1


def test_witness_silent_on_ordered_nesting(fresh_witness):
    a = lockprof.InstrumentedLock("tcw.oa")
    b = lockprof.InstrumentedLock("tcw.ob")

    def worker():
        for _ in range(50):
            with a:
                with b:
                    pass

    ts = [threading.Thread(target=worker) for _ in range(4)]
    [t.start() for t in ts]
    [t.join(20) for t in ts]
    assert lockprof.order_violations() == []
    assert "tcw.oa->tcw.ob" in lockprof.lock_order_edges()


def test_witness_condition_reacquire_accounted(fresh_witness):
    """Condition.wait over an InstrumentedLock keeps the held set
    coherent (released during the wait, re-held after)."""
    outer = lockprof.InstrumentedLock("tcw.outer")
    cv = threading.Condition(lockprof.InstrumentedLock("tcw.cvl"))
    with outer:
        with cv:
            cv.wait(0.01)
    assert lockprof.order_violations() == []
    snap = lockprof.held_locks_snapshot()
    for row in snap.values():
        assert "tcw.cvl" not in row["held"]


def test_witness_snapshot_shows_held_and_waiting(fresh_witness):
    lock = lockprof.InstrumentedLock("tcw.held")
    other = lockprof.InstrumentedLock("tcw.wanted")
    holding = threading.Event()
    release = threading.Event()

    def holder():
        with other:
            holding.set()
            release.wait(10)

    def blocked():
        with lock:
            holding.wait(10)
            with other:        # parks behind holder
                pass

    t1 = threading.Thread(target=holder, name="tcw-holder", daemon=True)
    t2 = threading.Thread(target=blocked, name="tcw-blocked", daemon=True)
    t1.start()
    t2.start()
    assert holding.wait(10)
    deadline = time.monotonic() + 10
    snap = {}
    while time.monotonic() < deadline:
        snap = lockprof.held_locks_snapshot()
        row = snap.get("tcw-blocked")
        if row and row["waiting_for"] == "tcw.wanted":
            break
        time.sleep(0.01)
    assert snap["tcw-blocked"]["held"] == ["tcw.held"]
    assert snap["tcw-blocked"]["waiting_for"] == "tcw.wanted"
    assert snap["tcw-holder"]["held"] == ["tcw.wanted"]
    release.set()
    t1.join(10)
    t2.join(10)


def test_wedge_guard_timeout_dumps_held_locks(fresh_witness, capsys,
                                              tmp_path, monkeypatch):
    """The acceptance scenario: a synthetic ABBA DEADLOCK wedges a
    guarded call past its deadline -> the guard SKIPS (bounded suite)
    and dumps every thread's held locks + the witness's cycle to
    stderr — the PR 11 silent-hang class now leaves evidence."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from wedge_guard import WedgeGuard

    # ISSUE 15: the dump is also archived to a file artifact; keep this
    # DELIBERATE wedge's artifact out of build/wedge_autopsy so real
    # harvests stay unpolluted
    monkeypatch.setenv("BRPC_WEDGE_DUMP_DIR", str(tmp_path))

    a = lockprof.InstrumentedLock("tcw.da")
    b = lockprof.InstrumentedLock("tcw.db")
    got_a = threading.Event()
    got_b = threading.Event()

    def left():
        with a:
            got_a.set()
            got_b.wait(30)
            with b:            # deadlocks against right()
                pass

    t_left = threading.Thread(target=left, name="tcw-left", daemon=True)
    t_left.start()

    def right():
        with b:
            got_b.set()
            got_a.wait(30)
            with a:            # deadlocks against left()
                pass

    guard = WedgeGuard("synthetic abba", deadline_s=1.0)
    t_right = guard.start_thread(right)
    with pytest.raises(pytest.skip.Exception) as si:
        guard.join_thread(t_right, what="synthetic abba")
    assert "wedged past" in str(si.value)
    assert guard.wedged
    err = capsys.readouterr().err
    assert "lock-order witness dump" in err
    assert "tcw.da" in err and "tcw.db" in err
    assert "BLOCKED acquiring" in err
    # the witness ALSO flagged the cycle itself (edges recorded at
    # acquire-attempt time — a deadlock that never completes its second
    # acquire still closes the graph)
    viols = [v for v in lockprof.order_violations()
             if set(v["cycle"]) == {"tcw.da", "tcw.db"}]
    assert len(viols) == 1
    # a subsequent guarded call short-circuits instead of hanging
    with pytest.raises(pytest.skip.Exception):
        guard.deadline(lambda: None)


def test_witness_reregisters_threads_after_reset(fresh_witness):
    """Review-pass regression: reset_witness() clears the held-set
    table, and a thread whose thread-local list PREDATES the reset
    must re-register on its next acquisition — otherwise every
    post-reset wedge dump reads '(none held)' exactly when the
    diagnostic matters."""
    lock = lockprof.InstrumentedLock("tcw.rr")
    with lock:
        pass                       # main thread's TLS list now exists
    lockprof.reset_witness()
    with lock:
        snap = lockprof.held_locks_snapshot()
        assert any("tcw.rr" in row["held"] for row in snap.values()), snap


def test_witness_report_renders_cycles(fresh_witness):
    a = lockprof.InstrumentedLock("tcw.ra")
    b = lockprof.InstrumentedLock("tcw.rb")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    rep = lockprof.witness_report()
    assert "ABBA violations: 1" in rep
    assert "tcw.ra" in rep and "tcw.rb" in rep
    assert "first seen at" in rep
    lockprof.reset_witness()
    assert "ABBA violations: none" in lockprof.witness_report()
