"""Cluster-behavior tests: LB channels over real loopback servers, retry on
server death, backup requests — the reference tests "distributed" behavior
exactly this way (SURVEY.md §4: many loopback servers as 'the cluster')."""
import threading
import time

import pytest

import brpc_tpu as brpc
from brpc_tpu import errors


@pytest.fixture(autouse=True)
def _fresh_cluster_state():
    """Health-check and circuit-breaker state is process-global and keyed
    by endpoint; an ephemeral port REUSED from an earlier test would
    inherit its broken/ramp state and make these timing-sensitive tests
    flake.  Start each one clean."""
    from brpc_tpu.policy import circuit_breaker, health_check
    # generation bump: stale probe loops from earlier tests stand down
    # instead of reviving endpoints into the cleared state
    health_check.reset_all()
    b = circuit_breaker.global_breaker()
    with b._mu:
        b._short.clear()
        b._long.clear()
        b._isolation_count.clear()
        b._recovering_until.clear()
    yield


class WhoAmI(brpc.Service):
    NAME = "WhoAmI"

    def __init__(self, tag, delay_s=0.0):
        self._tag = tag
        self._delay = delay_s

    @brpc.method(request="json", response="json")
    def Get(self, cntl, req):
        if self._delay:
            time.sleep(self._delay)
        return {"server": self._tag}


def _start(tag, delay_s=0.0):
    s = brpc.Server()
    s.add_service(WhoAmI(tag, delay_s))
    s.start("127.0.0.1", 0)
    return s


class TestClusterChannel:
    def test_rr_over_cluster(self):
        servers = [_start(f"s{i}") for i in range(3)]
        try:
            addr = "list://" + ",".join(f"127.0.0.1:{s.port}"
                                        for s in servers)
            ch = brpc.Channel(addr, options=brpc.ChannelOptions(
                timeout_ms=5000, load_balancer="rr"))
            seen = [ch.call_sync("WhoAmI", "Get", {}, serializer="json")
                    ["server"] for _ in range(9)]
            assert sorted(set(seen)) == ["s0", "s1", "s2"]
        finally:
            for s in servers:
                s.stop()
                s.join()

    def test_retry_when_one_server_dies(self):
        servers = [_start(f"s{i}") for i in range(2)]
        addr = "list://" + ",".join(f"127.0.0.1:{s.port}" for s in servers)
        ch = brpc.Channel(addr, options=brpc.ChannelOptions(
            timeout_ms=5000, load_balancer="rr", max_retry=3))
        try:
            # warm both connections
            for _ in range(4):
                ch.call_sync("WhoAmI", "Get", {}, serializer="json")
            # kill server 0: in-flight and future calls must survive via
            # retry on the living server
            dead_port = servers[0].port
            servers[0].stop()
            servers[0].join()
            ok = 0
            for _ in range(12):
                r = ch.call_sync("WhoAmI", "Get", {}, serializer="json")
                assert r["server"] == "s1"
                ok += 1
            assert ok == 12
        finally:
            for s in servers:
                s.stop()
                s.join()

    def test_backup_request_beats_slow_server(self):
        slow = _start("slow", delay_s=1.0)
        fast = _start("fast")
        try:
            # la LB would avoid the slow one; force rr so the backup path is
            # what saves latency
            addr = f"list://127.0.0.1:{slow.port},127.0.0.1:{fast.port}"
            ch = brpc.Channel(addr, options=brpc.ChannelOptions(
                timeout_ms=8000, load_balancer="rr",
                backup_request_ms=100, max_retry=1))
            latencies = []
            hit = []
            for _ in range(4):
                t0 = time.monotonic()
                r = ch.call_sync("WhoAmI", "Get", {}, serializer="json")
                latencies.append(time.monotonic() - t0)
                hit.append(r["server"])
            # every call returns well under the slow server's 1s delay
            assert max(latencies) < 0.9, latencies
            assert "fast" in hit
        finally:
            for s in (slow, fast):
                s.stop()
                s.join()
