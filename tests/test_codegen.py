"""protoc codegen plugin (tools/protoc_gen_brpc.py) — the reference's
code-generator slot (mcpack2pb/generator.cpp emits a protoc plugin the
same way; SURVEY §2.4).  Generates a typed Service base + client Stub
from .proto service definitions; this test runs protoc for real and
round-trips an RPC through the generated classes.
"""
import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(shutil.which("protoc") is None,
                                reason="protoc not installed")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROTO = """
syntax = "proto3";
package demo;

message AddRequest { int32 a = 1; int32 b = 2; }
message AddResponse { int32 sum = 1; }

service Calc {
  rpc Add(AddRequest) returns (AddResponse);
}
"""


@pytest.fixture(scope="module")
def generated(tmp_path_factory):
    d = tmp_path_factory.mktemp("gen")
    (d / "calc.proto").write_text(PROTO)
    r = subprocess.run(
        ["protoc", f"--plugin=protoc-gen-brpc={REPO}/tools/protoc_gen_brpc.py",
         "--python_out=.", "--brpc_out=.", "calc.proto"],
        cwd=d, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert (d / "calc_brpc.py").exists()
    sys.path.insert(0, str(d))
    yield d
    sys.path.remove(str(d))


class TestCodegen:
    def test_generated_roundtrip(self, generated):
        import brpc_tpu as brpc
        import calc_brpc
        import calc_pb2

        class Calc(calc_brpc.CalcBase):
            def Add(self, cntl, request):
                return calc_pb2.AddResponse(sum=request.a + request.b)

        srv = brpc.Server()
        srv.add_service(Calc())
        srv.start("127.0.0.1", 0)
        try:
            stub = calc_brpc.CalcStub(
                brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=10_000))
            res = stub.Add(calc_pb2.AddRequest(a=2, b=40))
            assert isinstance(res, calc_pb2.AddResponse)
            assert res.sum == 42
        finally:
            srv.stop()
            srv.join()

    def test_unimplemented_base_errors(self, generated):
        import brpc_tpu as brpc
        import calc_brpc
        import calc_pb2
        from brpc_tpu import errors

        srv = brpc.Server()
        srv.add_service(calc_brpc.CalcBase())   # no implementation
        srv.start("127.0.0.1", 0)
        try:
            stub = calc_brpc.CalcStub(
                brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=10_000))
            with pytest.raises(errors.RpcError):
                stub.Add(calc_pb2.AddRequest(a=1, b=1))
        finally:
            srv.stop()
            srv.join()

    def test_async_stub(self, generated):
        import time
        import brpc_tpu as brpc
        import calc_brpc
        import calc_pb2

        class Calc(calc_brpc.CalcBase):
            def Add(self, cntl, request):
                return calc_pb2.AddResponse(sum=request.a + request.b)

        srv = brpc.Server()
        srv.add_service(Calc())
        srv.start("127.0.0.1", 0)
        try:
            stub = calc_brpc.CalcStub(
                brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=10_000))
            got = []
            stub.Add_async(calc_pb2.AddRequest(a=3, b=4),
                           done=lambda c: got.append(c))
            deadline = time.monotonic() + 10
            while not got and time.monotonic() < deadline:
                time.sleep(0.01)
            assert got and got[0].error_code == 0
            assert got[0].response.sum == 7
        finally:
            srv.stop()
            srv.join()
