"""CollectiveGroup semantics on the virtual 8-device mesh
(ici/collective.py — the XLA-collective lowering behind
ParallelChannel/PartitionChannel and the §5.8 communication backend).
Each primitive is checked against its numpy definition."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.ici.collective import CollectiveGroup
from brpc_tpu.ici.mesh import get_mesh


@pytest.fixture(scope="module")
def group():
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    return CollectiveGroup()


def test_parallel_apply_stack_and_sum(group):
    n = group.size
    x = jnp.arange(12.0)

    def double(v):
        return v * 2.0

    stacked = group.parallel_apply(double, x, merge="stack")
    assert stacked.shape == (n, 12)
    np.testing.assert_allclose(np.asarray(stacked),
                               np.tile(np.arange(12.0) * 2, (n, 1)))
    summed = group.parallel_apply(double, x, merge="sum")
    np.testing.assert_allclose(np.asarray(summed), np.arange(12.0) * 2 * n)


def test_partition_apply_concat_matches_local(group):
    n = group.size
    x = jnp.arange(n * 4.0).reshape(n * 4)

    def inc(v):
        return v + 1.0

    out = group.partition_apply(inc, x, merge="concat")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) + 1.0)
    summed = group.partition_apply(lambda v: jnp.sum(v, keepdims=True), x,
                                   merge="sum")
    np.testing.assert_allclose(np.asarray(summed), [np.asarray(x).sum()])


def test_ring_shift_permutes_shards(group):
    n = group.size
    x = jnp.arange(n * 2.0)          # shard i holds [2i, 2i+1]
    out = np.asarray(group.ring_shift(x, steps=1))
    expect = np.roll(np.asarray(x).reshape(n, 2), 1, axis=0).reshape(-1)
    np.testing.assert_allclose(out, expect)
    # a full ring of shifts restores the input
    y = x
    for _ in range(n):
        y = group.ring_shift(y, steps=1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))


def test_all_gather_all_reduce_reduce_scatter(group):
    n = group.size
    x = jnp.arange(n * 3.0)
    gathered = np.asarray(group.all_gather(x))
    np.testing.assert_allclose(gathered, np.asarray(x))  # tiled re-assembly
    reduced = np.asarray(group.all_reduce(x))
    # psum over shards: result replicated = sum of per-shard views is the
    # full vector summed across the axis groups — each position summed n?
    # in_specs P(axis): each chip holds a distinct shard; psum adds the
    # SHARDS elementwise, output replicated with shard shape
    shards = np.asarray(x).reshape(n, 3)
    np.testing.assert_allclose(reduced, shards.sum(axis=0))
    rs = np.asarray(group.reduce_scatter(jnp.ones((n * 2,))))
    # every chip contributed the full ones-vector; chip i keeps slice i of
    # the n-fold sum
    np.testing.assert_allclose(rs, np.full((n * 2,), float(n)))


def test_compiled_programs_are_cached(group):
    def f(v):
        return v * 3.0

    x = jnp.arange(8.0)
    group.parallel_apply(f, x)
    before = len(group._cache)
    group.parallel_apply(f, x)     # same fn object: no rebuild
    assert len(group._cache) == before
