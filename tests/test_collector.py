"""bvar Collector — shared speed-limited sampling (reference
bvar/collector.{h,cpp}; SURVEY.md §2.7 Collector row)."""
import threading
import time

from brpc_tpu.bvar.collector import (Collected, Collector,
                                     CollectorSpeedLimit)


class _Probe(Collected):
    def __init__(self, sink):
        self.sink = sink

    def dump_and_destroy(self):
        self.sink.append(threading.current_thread().name)


class TestCollector:
    def test_samples_run_off_the_submitting_thread(self):
        sink = []
        c = Collector.instance()
        for _ in range(5):
            c.submit(_Probe(sink))
        c.flush()
        assert len(sink) == 5
        # at least the flushed batch ran somewhere deterministic; the key
        # property is that submit() itself never ran dump_and_destroy
        # (submit returns before the sink fills unless flushed)

    def test_flush_observes_prior_submissions(self):
        sink = []
        c = Collector.instance()
        for i in range(100):
            c.submit(_Probe(sink))
        c.flush()
        assert len(sink) == 100

    def test_speed_limit_bounds_grabs(self):
        # injected clock: the 500-grab loop can never straddle a window
        now = [100.0]
        limit = CollectorSpeedLimit("test_family", max_per_second=50,
                                    clock=lambda: now[0])
        granted = sum(1 for _ in range(500) if limit.grab())
        assert granted == 50
        # counters add up
        assert limit.grabbed.get_value() + limit.denied.get_value() >= 500

    def test_speed_limit_window_refills(self):
        now = [5.0]
        limit = CollectorSpeedLimit("test_refill", max_per_second=2,
                                    clock=lambda: now[0])
        assert limit.grab() and limit.grab()
        assert not limit.grab()
        now[0] += 1.1                       # the window rolls over
        assert limit.grab()

    def test_grab_n_batches_the_window_budget(self):
        """grab_n (ISSUE 9: the rpcz spanq drainer's batch grab) grants
        from the same fixed-window budget grab() uses — partial grants
        at the boundary, denial counted, window refill honored."""
        now = [50.0]
        limit = CollectorSpeedLimit("test_batch", max_per_second=100,
                                    clock=lambda: now[0])
        assert limit.grab_n(60) == 60
        assert limit.grab_n(60) == 40       # partial: budget boundary
        assert limit.grab_n(10) == 0        # exhausted window
        assert limit.grabbed.get_value() == 100
        assert limit.denied.get_value() == 30
        now[0] += 1.1                       # the window rolls over
        assert limit.grab_n(10) == 10
        # grab() and grab_n() share one budget, either order
        assert limit.grab_n(89) == 89
        assert limit.grab() and not limit.grab()

    def test_broken_sample_does_not_kill_the_drainer(self):
        class Bad(Collected):
            def dump_and_destroy(self):
                raise RuntimeError("boom")

        sink = []
        c = Collector.instance()
        c.submit(Bad())
        c.submit(_Probe(sink))
        c.flush()
        assert len(sink) == 1

    def test_concurrent_submit_and_flush(self):
        sink = []
        c = Collector.instance()
        stop = time.monotonic() + 0.5
        counts = [0] * 4

        def producer(i):
            while time.monotonic() < stop:
                c.submit(_Probe(sink))
                counts[i] += 1

        ts = [threading.Thread(target=producer, args=(i,))
              for i in range(4)]
        [t.start() for t in ts]
        while time.monotonic() < stop:
            c.flush()
        [t.join() for t in ts]
        c.flush()
        # exactly once: every submission dumped, none duplicated/lost
        assert len(sink) == sum(counts)
        assert not c._pending


class TestRpczThroughCollector:
    def test_spans_flow(self):
        from brpc_tpu import rpcz
        rpcz.set_enabled(True)
        try:
            s = rpcz.new_span("server", "Svc", "M")
            rpcz.submit(s)
            spans = rpcz.recent_spans(limit=10)
            assert any(x.service == "Svc" and x.method == "M"
                       for x in spans)
        finally:
            rpcz.set_enabled(False)


def test_rpcz_on_disk_spandb(tmp_path):
    """On-disk SpanDB (reference span.h:227-230): spans persist to
    recordio segments and load back, surviving the in-memory window."""
    from brpc_tpu import rpcz

    rpcz.set_database_dir(str(tmp_path))
    rpcz.set_enabled(True)
    try:
        for i in range(40):
            s = rpcz.new_span("server", "DbSvc", f"M{i % 4}")
            s.request_size = i
            s.annotate("persisted")
            rpcz.submit(s)
        # collector flush drives dump_and_destroy (disk write included)
        spans = rpcz.recent_spans(limit=50)
        assert len(spans) >= 40
        disk = rpcz.load_disk_spans(limit=100)
        assert len(disk) >= 40
        by_method = {d.method for d in disk}
        assert {"M0", "M1", "M2", "M3"} <= by_method
        assert any(d.annotations for d in disk)
        # trace filter works on the disk path too
        one = disk[-1]
        got = rpcz.load_disk_spans(trace_id=one.trace_id)
        assert got and all(g.trace_id == one.trace_id for g in got)
    finally:
        rpcz.set_enabled(False)
        rpcz.set_database_dir(None)
