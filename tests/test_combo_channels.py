"""Combo channel tests (analog of the parallel/selective/partition parts of
brpc_channel_unittest, SURVEY.md §4)."""
import threading
import time

import pytest

import brpc_tpu as brpc
from brpc_tpu import errors


class Part(brpc.Service):
    NAME = "Part"

    def __init__(self, tag, fail=False):
        self._tag = tag
        self._fail = fail

    @brpc.method(request="json", response="json")
    def Q(self, cntl, req):
        if self._fail:
            cntl.set_failed(errors.EINTERNAL, "down")
            return None
        return {"part": self._tag, "got": req}


def _start(tag, fail=False):
    s = brpc.Server()
    s.add_service(Part(tag, fail))
    s.start("127.0.0.1", 0)
    return s


class TestParallelChannel:
    def test_broadcast_and_merge(self):
        servers = [_start(f"p{i}") for i in range(3)]
        try:
            pc = brpc.ParallelChannel()
            for s in servers:
                pc.add_channel(brpc.Channel(f"127.0.0.1:{s.port}",
                                            timeout_ms=5000))
            resp = pc.call_sync("Part", "Q", {"k": 1}, serializer="json")
            assert sorted(r["part"] for r in resp) == ["p0", "p1", "p2"]
            assert all(r["got"] == {"k": 1} for r in resp)
        finally:
            for s in servers:
                s.stop()
                s.join()

    def test_call_mapper_slices_request(self):
        servers = [_start(f"p{i}") for i in range(2)]
        try:
            class Slicer(brpc.CallMapper):
                def map(self, i, n, request):
                    return brpc.SubCall({"slice": request["items"][i::n]})

            pc = brpc.ParallelChannel(call_mapper=Slicer())
            for s in servers:
                pc.add_channel(brpc.Channel(f"127.0.0.1:{s.port}",
                                            timeout_ms=5000))
            resp = pc.call_sync("Part", "Q", {"items": [0, 1, 2, 3]},
                                serializer="json")
            slices = sorted(tuple(r["got"]["slice"]) for r in resp)
            assert slices == [(0, 2), (1, 3)]
        finally:
            for s in servers:
                s.stop()
                s.join()

    def test_fail_limit(self):
        ok = _start("ok")
        bad = _start("bad", fail=True)
        try:
            strict = brpc.ParallelChannel(fail_limit=0)
            strict.add_channel(brpc.Channel(f"127.0.0.1:{ok.port}",
                                            timeout_ms=5000))
            strict.add_channel(brpc.Channel(f"127.0.0.1:{bad.port}",
                                            timeout_ms=5000))
            with pytest.raises(errors.RpcError) as ei:
                strict.call_sync("Part", "Q", {}, serializer="json")
            assert ei.value.code == errors.ETOOMANYFAILS

            tolerant = brpc.ParallelChannel(fail_limit=1)
            tolerant.add_channel(brpc.Channel(f"127.0.0.1:{ok.port}",
                                              timeout_ms=5000))
            tolerant.add_channel(brpc.Channel(f"127.0.0.1:{bad.port}",
                                              timeout_ms=5000))
            resp = tolerant.call_sync("Part", "Q", {}, serializer="json")
            assert len(resp) == 1 and resp[0]["part"] == "ok"
        finally:
            for s in (ok, bad):
                s.stop()
                s.join()


class TestSelectiveChannel:
    def test_skips_dead_subchannel(self):
        alive = _start("alive")
        try:
            sc = brpc.SelectiveChannel(max_retry=3)
            sc.add_channel(brpc.Channel("127.0.0.1:1", timeout_ms=400,
                                        max_retry=0))
            sc.add_channel(brpc.Channel(f"127.0.0.1:{alive.port}",
                                        timeout_ms=5000))
            for _ in range(4):
                r = sc.call_sync("Part", "Q", {}, serializer="json")
                assert r["part"] == "alive"
        finally:
            alive.stop()
            alive.join()


class TestPartitionChannel:
    def test_partition_fanout(self):
        servers = [_start(f"shard{i}") for i in range(2)]
        try:
            addr = ",".join(
                f"127.0.0.1:{s.port}" for s in servers)
            # tag servers as partitions 0/2 and 1/2 via a list file
            import tempfile, os
            with tempfile.NamedTemporaryFile("w", suffix=".list",
                                             delete=False) as f:
                f.write(f"127.0.0.1:{servers[0].port} 0/2\n")
                f.write(f"127.0.0.1:{servers[1].port} 1/2\n")
                path = f.name

            class KeyMapper(brpc.CallMapper):
                def map(self, i, n, request):
                    return brpc.SubCall({"partition": i,
                                         "keys": request["keys"][i::n]})

            pc = brpc.PartitionChannel(2, call_mapper=KeyMapper())
            pc.init(f"file://{path}",
                    options=brpc.ChannelOptions(timeout_ms=5000))
            resp = pc.call_sync("Part", "Q", {"keys": list(range(6))},
                                serializer="json")
            assert len(resp) == 2
            tags = sorted(r["part"] for r in resp)
            assert tags == ["shard0", "shard1"]
            os.unlink(path)
        finally:
            for s in servers:
                s.stop()
                s.join()


def test_parallel_channel_jit_false_service_takes_per_channel_path():
    """A self-sharding device service (registered jit=False) cannot be
    wrapped in the collective lowering's outer jit; an all-ICI
    ParallelChannel must fall back to per-channel calls and still
    deliver merged results."""
    import jax

    from brpc_tpu.ici import IciChannel, register_device_service
    from brpc_tpu.ici.channel import device_service_registry

    def self_managed(x):
        # eager (unjitted) service doing its own placement
        return jax.device_put(x * 2.0, next(iter(x.devices())))

    register_device_service("SelfSharded", "Double", self_managed,
                            jit=False)
    # excluded from the lowering registry...
    assert ("SelfSharded", "Double") not in device_service_registry()
    from brpc_tpu.rpc.combo_channels import ParallelChannel
    pc = ParallelChannel()
    for i in range(2):
        pc.add_channel(IciChannel(f"ici://slice0/{i}"))
    x = jax.numpy.arange(8, dtype=jax.numpy.float32)
    cntl = pc.call("SelfSharded", "Double", x)
    cntl.join()
    assert not cntl.failed(), cntl.error_text
    # ...but the per-channel path still served both chips
    merged = cntl.response
    assert len(merged) == 2
    for out in merged:
        assert jax.numpy.allclose(out, x * 2.0)
