"""Deeper combo-channel matrix (reference brpc_channel_unittest's
parallel/selective sections: mapper skip, merger errors, failover order,
all-dead clusters — SURVEY.md §2.5, §4)."""
import threading

import pytest

import brpc_tpu as brpc
from brpc_tpu import errors
from brpc_tpu.rpc.combo_channels import (CallMapper, ParallelChannel,
                                         ResponseMerger, SelectiveChannel,
                                         SubCall)


class SkipIndex(CallMapper):
    def __init__(self, skip_i):
        self.skip_i = skip_i

    def map(self, i, n, request):
        return SubCall(skip=True) if i == self.skip_i else SubCall(request)


class TagFold(ResponseMerger):
    def merge(self, responses):
        return {"tags": sorted(r["tag"] for r in responses if r)}


class Node(brpc.Service):
    NAME = "Node"

    def __init__(self, tag, fail=False, calls=None):
        self._tag = tag
        self._fail = fail
        self._calls = calls if calls is not None else []

    @brpc.method(request="json", response="json")
    def Q(self, cntl, req):
        self._calls.append(self._tag)
        if self._fail:
            # app-level code outside RetryPolicy.RETRYABLE: the inner
            # Channel must NOT retry it, so `calls` counts exactly the
            # combo layer's attempts
            cntl.set_failed(1234, f"{self._tag} down")
            return None
        return {"tag": self._tag}


def _srv(tag, fail=False, calls=None):
    s = brpc.Server()
    s.add_service(Node(tag, fail, calls))
    s.start("127.0.0.1", 0)
    return s


class TestParallelMapperMerger:
    def test_mapper_skip_excludes_subchannel(self):
        calls = []
        servers = [_srv(f"n{i}", calls=calls) for i in range(3)]
        try:
            pc = ParallelChannel(call_mapper=SkipIndex(1))
            for s in servers:
                pc.add_channel(brpc.Channel(f"127.0.0.1:{s.port}",
                                            timeout_ms=3000))
            out = pc.call_sync("Node", "Q", {"x": 1}, serializer="json")
            tags = sorted(r["tag"] for r in out if r is not None)
            assert tags == ["n0", "n2"]
            assert "n1" not in calls          # never contacted
        finally:
            for s in servers:
                s.stop()
                s.join()

    def test_custom_merger_folds(self):
        servers = [_srv(f"n{i}") for i in range(3)]
        try:
            pc = ParallelChannel(response_merger=TagFold())
            for s in servers:
                pc.add_channel(brpc.Channel(f"127.0.0.1:{s.port}",
                                            timeout_ms=3000))
            out = pc.call_sync("Node", "Q", {}, serializer="json")
            assert out == {"tags": ["n0", "n1", "n2"]}
        finally:
            for s in servers:
                s.stop()
                s.join()

    def test_fail_limit_exceeded_raises(self):
        servers = [_srv("ok0"), _srv("bad1", fail=True),
                   _srv("bad2", fail=True)]
        try:
            pc = ParallelChannel(fail_limit=1)
            for s in servers:
                pc.add_channel(brpc.Channel(f"127.0.0.1:{s.port}",
                                            timeout_ms=3000))
            with pytest.raises(errors.RpcError):
                pc.call_sync("Node", "Q", {}, serializer="json")
        finally:
            for s in servers:
                s.stop()
                s.join()

    def test_all_subchannels_dead(self):
        pc = ParallelChannel(fail_limit=0)
        for port in (1, 2):
            pc.add_channel(brpc.Channel(f"127.0.0.1:{port}",
                                        timeout_ms=1500))
        with pytest.raises(errors.RpcError):
            pc.call_sync("Node", "Q", {}, serializer="json")


class TestSelectiveFailover:
    def test_failover_skips_failed_subchannel(self):
        calls = []
        bad = _srv("bad", fail=True, calls=calls)
        good = _srv("good", calls=calls)
        try:
            sc = SelectiveChannel(max_retry=2)
            sc.add_channel(brpc.Channel(f"127.0.0.1:{bad.port}",
                                        timeout_ms=3000))
            sc.add_channel(brpc.Channel(f"127.0.0.1:{good.port}",
                                        timeout_ms=3000))
            cntl = brpc.Controller()
            out = sc.call_sync("Node", "Q", {}, serializer="json",
                               cntl=cntl)
            assert out == {"tag": "good"}
            assert cntl.retried_count == 1
            assert cntl.error_code == 0       # reset after the winner
        finally:
            bad.stop(); bad.join()
            good.stop(); good.join()

    def test_each_subchannel_tried_once(self):
        calls = []
        servers = [_srv(f"b{i}", fail=True, calls=calls) for i in range(3)]
        try:
            sc = SelectiveChannel(max_retry=10)   # more than channels
            for s in servers:
                sc.add_channel(brpc.Channel(f"127.0.0.1:{s.port}",
                                            timeout_ms=3000))
            with pytest.raises(errors.RpcError):
                sc.call_sync("Node", "Q", {}, serializer="json")
            assert sorted(calls) == ["b0", "b1", "b2"]  # no double-tries
        finally:
            for s in servers:
                s.stop()
                s.join()

    def test_empty_selective_raises_enodata(self):
        sc = SelectiveChannel()
        with pytest.raises(errors.RpcError) as ei:
            sc.call_sync("Node", "Q", {}, serializer="json")
        assert ei.value.code == errors.ENODATA


class TestParallelConcurrency:
    def test_concurrent_fanouts(self):
        servers = [_srv(f"n{i}") for i in range(3)]
        try:
            pc = ParallelChannel()
            for s in servers:
                pc.add_channel(brpc.Channel(f"127.0.0.1:{s.port}",
                                            timeout_ms=5000))
            results = []
            errs = []

            def worker():
                try:
                    for _ in range(20):
                        out = pc.call_sync("Node", "Q", {},
                                           serializer="json")
                        results.append(len([r for r in out if r]))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=worker) for _ in range(4)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs, errs[:2]
            assert results and all(n == 3 for n in results)
        finally:
            for s in servers:
                s.stop()
                s.join()
