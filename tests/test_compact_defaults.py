"""Compact codec (mcpack2pb slot) + default process variables."""
import pytest

import brpc_tpu as brpc
from brpc_tpu.rpc import compact


def test_compact_roundtrip():
    v = {"s": "héllo", "i": -42, "big": 1 << 62, "f": 2.5, "t": True,
         "f2": False, "n": None, "b": b"\x00\xff", "l": [1, [2, [3]]],
         "d": {"x": {"y": "z"}}}
    assert compact.loads(compact.dumps(v)) == v


def test_compact_smaller_than_json():
    import json
    v = {"values": list(range(100)), "name": "metrics"}
    assert len(compact.dumps(v)) < len(json.dumps(v).encode())


def test_compact_json_bridge():
    v = {"k": [1, "two", b"raw"], "ok": True}
    j = compact.compact_to_json(compact.dumps(v))
    assert compact.loads(compact.json_to_compact(j)) == v


def test_compact_serializer_rpc_roundtrip():
    class S(brpc.Service):
        @brpc.method(request="compact", response="compact")
        def Sum(self, cntl, req):
            return {"total": sum(req["xs"]), "tag": req["tag"]}

    s = brpc.Server()
    s.add_service(S())
    s.start("127.0.0.1", 0)
    try:
        ch = brpc.Channel(f"127.0.0.1:{s.port}")
        out = ch.call_sync("S", "Sum", {"xs": [1, 2, 3], "tag": b"\x01"},
                           serializer="compact")
        assert out == {"total": 6, "tag": b"\x01"}
    finally:
        s.stop()
        s.join()


def test_default_process_variables_on_vars_page():
    from brpc_tpu.bvar.variable import dump_exposed
    s = brpc.Server()
    s.start("127.0.0.1", 0)
    try:
        vars_ = dump_exposed("process_*")
        assert vars_["process_pid"] > 0
        assert vars_["process_memory_resident_bytes"] > 1 << 20
        assert vars_["process_fd_count"] > 0
        assert vars_["process_thread_count"] >= 1
        assert vars_["process_cpu_seconds"] > 0
        # and they render on the console
        from brpc_tpu.rpc.http import HttpChannel
        h = HttpChannel(f"127.0.0.1:{s.port}")
        r = h.request("GET", "/vars")
        assert r.status == 200
        assert b"process_memory_resident_bytes" in r.body
        h.close()
    finally:
        s.stop()
        s.join()
