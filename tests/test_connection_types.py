"""ConnectionType tests — single/pooled/short reuse schemes
(reference socket_map.h:147, protocol.h:161-180)."""
import threading

import brpc_tpu as brpc
from brpc_tpu.butil.endpoint import str2endpoint
from brpc_tpu.policy import health_check
from brpc_tpu.rpc.channel import SocketMap


def _start_echo_server():
    class Echo(brpc.Service):
        @brpc.method(request="json", response="json")
        def Echo(self, cntl, req):
            return req

    srv = brpc.Server()
    srv.add_service(Echo())
    srv.start("127.0.0.1", 0)
    return srv


class TestConnectionTypes:
    def test_single_reuses_one_connection(self):
        srv = _start_echo_server()
        try:
            before = srv.connection_count
            ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=5000,
                              connection_type="single")
            for i in range(10):
                assert ch.call_sync("Echo", "Echo", {"i": i},
                                    serializer="json") == {"i": i}
            # all calls multiplexed one socket
            assert srv.connection_count - before <= 1
        finally:
            srv.stop()
            srv.join()

    def test_pooled_checkout_and_return(self):
        srv = _start_echo_server()
        try:
            ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=5000,
                              connection_type="pooled")
            ep = str2endpoint(f"127.0.0.1:{srv.port}")
            smap = SocketMap.instance()
            # sequential calls reuse the single pooled connection
            for i in range(5):
                assert ch.call_sync("Echo", "Echo", {"i": i},
                                    serializer="json") == {"i": i}
            assert smap.pooled_count(ep) == 1
            # concurrent calls grow the pool beyond one
            n = 8
            barrier = threading.Barrier(n)
            errs = []

            def worker(i):
                try:
                    barrier.wait(5)
                    assert ch.call_sync("Echo", "Echo", {"i": i},
                                        serializer="json") == {"i": i}
                except Exception as e:
                    errs.append(e)

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(n)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs, errs
            assert 1 <= smap.pooled_count(ep) <= n
        finally:
            srv.stop()
            srv.join()

    def test_short_closes_after_call(self):
        srv = _start_echo_server()
        try:
            ch = brpc.Channel(f"127.0.0.1:{srv.port}", timeout_ms=5000,
                              connection_type="short")
            ep = str2endpoint(f"127.0.0.1:{srv.port}")
            for i in range(3):
                assert ch.call_sync("Echo", "Echo", {"i": i},
                                    serializer="json") == {"i": i}
            # deliberate closes must NOT mark the endpoint broken
            assert not health_check.is_broken(ep)
            assert SocketMap.instance().pooled_count(ep) == 0
        finally:
            srv.stop()
            srv.join()

    def test_pooled_recovers_from_server_restart(self):
        srv = _start_echo_server()
        port = srv.port
        ch = brpc.Channel(f"127.0.0.1:{port}", timeout_ms=2000,
                          connection_type="pooled", max_retry=3)
        assert ch.call_sync("Echo", "Echo", {"a": 1},
                            serializer="json") == {"a": 1}
        srv.stop()
        srv.join()
        # old pooled connection is now dead; a new server on the same port
        # must be reachable (dead free-list entries are skipped)
        class Echo(brpc.Service):
            @brpc.method(request="json", response="json")
            def Echo(self, cntl, req):
                return req
        srv2 = brpc.Server()
        srv2.add_service(Echo())
        try:
            srv2.start("127.0.0.1", port)
        except OSError:
            return  # port raced away; skip the tail of this test
        try:
            assert ch.call_sync("Echo", "Echo", {"b": 2},
                                serializer="json") == {"b": 2}
        finally:
            srv2.stop()
            srv2.join()
