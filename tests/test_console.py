"""Builtin HTTP console tests (analog of brpc_builtin_service_unittest)."""
import http.client
import json

import pytest

import brpc_tpu as brpc


class Hello(brpc.Service):
    @brpc.method(request="json", response="json")
    def Say(self, cntl, req):
        return {"hello": (req or {}).get("name", "world")}


@pytest.fixture(scope="module")
def server():
    from brpc_tpu import flags, rpcz
    # rpcz is off by default (FLAGS_enable_rpcz parity); the /rpcz page
    # test needs spans collected
    rpcz.set_enabled(True)
    flags.set_flag("rpcz_enabled", True)
    s = brpc.Server()
    s.add_service(Hello())
    s.start("127.0.0.1", 0)
    # generate some traffic for /status
    ch = brpc.Channel(f"127.0.0.1:{s.port}", timeout_ms=5000)
    ch.call_sync("Hello", "Say", {"name": "x"}, serializer="json")
    yield s
    s.stop()
    s.join()
    rpcz.set_enabled(False)
    flags.set_flag("rpcz_enabled", False)


def _get(server, path):
    c = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
    c.request("GET", path)
    r = c.getresponse()
    body = r.read()
    c.close()
    return r.status, body


def test_index(server):
    status, body = _get(server, "/")
    assert status == 200 and b"/vars" in body


def test_health(server):
    assert _get(server, "/health") == (200, b"OK\n")


def test_status_lists_methods(server):
    status, body = _get(server, "/status")
    assert status == 200
    assert b"Hello.Say" in body
    assert b"count=1" in body


def test_vars(server):
    status, body = _get(server, "/vars")
    assert status == 200
    assert b"rpc_server_Hello_Say" in body


def test_vars_filter(server):
    _, body = _get(server, "/vars?filter=rpc_server_Hello*")
    assert b"rpc_server_Hello_Say" in body
    assert b"rpc_health_check" not in body


def test_flags_list_and_set(server):
    _, body = _get(server, "/flags")
    assert b"rpcz_enabled" in body
    status, body = _get(server, "/flags?setvalue=rpcz_sample_rate&value=0.5")
    assert status == 200 and body == b"ok\n"
    from brpc_tpu.flags import get_flag
    assert get_flag("rpcz_sample_rate") == 0.5
    _get(server, "/flags?setvalue=rpcz_sample_rate&value=1.0")


def test_flags_reject_non_reloadable(server):
    status, _ = _get(server, "/flags?setvalue=max_body_size&value=5")
    assert status == 400


def test_rpcz_shows_spans(server):
    _, body = _get(server, "/rpcz")
    assert b"Hello.Say" in body


def test_prometheus_metrics(server):
    status, body = _get(server, "/brpc_metrics")
    assert status == 200
    assert b"# TYPE" in body
    assert b"rpc_server_Hello_Say_count" in body


def test_services_inventory(server):
    _, body = _get(server, "/services")
    data = json.loads(body)
    assert data["Hello"]["Say"]["request"] == "json"


def test_connections_and_bthreads(server):
    status, body = _get(server, "/connections")
    assert status == 200 and b"socket_id" in body
    status, body = _get(server, "/bthreads")
    assert b"workers:" in body


def test_restful_rpc_bridge(server):
    c = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
    c.request("POST", "/Hello/Say", json.dumps({"name": "rest"}),
              {"Content-Type": "application/json"})
    r = c.getresponse()
    assert r.status == 200
    assert json.loads(r.read()) == {"hello": "rest"}
    c.close()


def test_404(server):
    status, _ = _get(server, "/definitely-not-a-page")
    assert status == 404
